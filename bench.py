"""Driver benchmark: ballots verified+tallied per second per chip.

Measures the BASELINE.md north-star path on the production 4096-bit group:
batch verification of encrypted ballots (subgroup membership + disjunctive
Chaum-Pedersen selection proofs + contest limit proofs + code chain +
homomorphic tally aggregation — Verifier V4-V7) over the device batch plane.

Prints ONE JSON line as the LAST stdout line:
{"metric", "value", "unit", "vs_baseline", "platform", "nballots", ...}.
``vs_baseline`` is value / (1M ballots / 60 s / 8 chips) — the driver target
"verify 1M encrypted ballots in <60 s on a v5e-8" (BASELINE.json); >1.0
means the target rate is met on this chip.

Resilience (the real TPU sits behind the flaky axon tunnel, which has
killed prior runs both at backend init and mid-compile):
  * platform decided by a bounded subprocess probe BEFORE importing jax
    (a wedged relay HANGS ``import jax`` — utils/platform.py);
  * a tiny warm-up pass populates the persistent compile cache first, so
    a flake mid-run costs one small recompile, not the whole program set;
  * every compile-heavy phase retries with backoff on JaxRuntimeError;
  * if the TPU run still dies, the benchmark re-runs itself in a CPU
    subprocess and re-emits its number with an ``error`` field recording
    the TPU failure — the artifact is ALWAYS parseable;
  * an atexit hook and a watchdog thread guarantee the JSON line even on
    unexpected exceptions or a wedged device call.

Knobs: BENCH_NBALLOTS, BENCH_PROBE_TIMEOUT/RETRIES/WAIT, BENCH_ATTEMPTS,
BENCH_RETRY_WAIT, BENCH_WATCHDOG (seconds, 0 disables), BENCH_NO_FALLBACK.
"""

from __future__ import annotations

import atexit
import contextlib
import json
import os
import subprocess
import sys
import threading
import time

TARGET = 1_000_000 / 60.0 / 8  # 1M ballots / 60 s / v5e-8 chips

RESULT: dict = {
    "metric": "ballots_verified_tallied_per_sec_per_chip",
    "value": 0.0,
    "unit": "ballots/s/chip",
    "vs_baseline": 0.0,
    "platform": "unknown",
    "nballots": 0,
    "error": "did not complete",
}
_emitted = False


def emit() -> None:
    """Print the metric JSON as the last stdout line, exactly once."""
    global _emitted
    if _emitted:
        return
    _emitted = True
    if RESULT.get("error") is None:
        RESULT.pop("error", None)
    sys.stderr.flush()
    flush_partial()
    print(json.dumps(RESULT), flush=True)


def _append_progress_row() -> None:
    """Append one compact trajectory row to PROGRESS.jsonl after a
    successful run, so the bench history lives in one machine-readable
    stream instead of loose BENCH_r*.json files (tools/bench_diff.py
    accepts the stream as a baseline).  BENCH_PROGRESS= path override;
    empty string disables."""
    path = os.environ.get(
        "BENCH_PROGRESS",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "PROGRESS.jsonl"))
    if not path:
        return
    try:
        git_rev = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10,
        ).stdout.strip() or None
    except (OSError, subprocess.SubprocessError):
        git_rev = None
    row = {
        "kind": "bench",
        "ts": round(time.time(), 1),
        "ballots_per_s_per_chip": RESULT.get("value"),
        "vs_baseline": RESULT.get("vs_baseline"),
        "powmod_per_s": RESULT.get("powmod_per_s"),
        "tenant_aggregate_ballots_per_s":
            RESULT.get("tenant_aggregate_ballots_per_s"),
        "platform": RESULT.get("platform"),
        "nballots": RESULT.get("nballots"),
        "git": git_rev,
    }
    try:
        with open(path, "a") as f:
            f.write(json.dumps(row, separators=(",", ":")) + "\n")
    except OSError as e:
        note(f"progress row write failed: {e}")


def flush_partial() -> None:
    """Write the CURRENT artifact to disk (atomic replace).  Called after
    every phase, so a driver SIGKILL — which skips atexit AND signal
    handlers — still leaves partial data on disk (VERDICT r6 item 1).
    BENCH_PARTIAL= path override; empty string disables."""
    path = os.environ.get("BENCH_PARTIAL", "BENCH_partial.json")
    if not path:
        return
    try:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(RESULT, f)
        os.replace(tmp, path)
    except OSError as e:
        note(f"partial-artifact flush failed: {e}")


def note(msg: str) -> None:
    print(f"# [{time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
          flush=True)


@contextlib.contextmanager
def _env_flag(name: str, value: str):
    """Set an env knob for a scoped phase, restoring the prior value."""
    old = os.environ.get(name)
    os.environ[name] = value
    try:
        yield
    finally:
        if old is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = old


def retry(tag: str, fn, attempts: int | None = None,
          wait: float | None = None):
    """Run ``fn`` with backoff — survives transient tunnel/compile flakes
    (r3 died on one ``remote_compile: response body closed``)."""
    attempts = attempts or int(os.environ.get("BENCH_ATTEMPTS", "4"))
    wait = wait if wait is not None else \
        float(os.environ.get("BENCH_RETRY_WAIT", "10"))
    last = None
    for a in range(attempts):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — JaxRuntimeError et al.
            last = e
            note(f"{tag}: attempt {a + 1}/{attempts} failed: "
                 f"{type(e).__name__}: {e}")
            if a + 1 < attempts:
                time.sleep(wait * (a + 1))
    raise last


def _install_signal_emitters() -> None:
    """SIGTERM/SIGINT (e.g. a driver timeout kill) must still produce a
    parseable artifact — atexit alone doesn't run on default SIGTERM."""
    import signal

    def handler(signum, frame):
        base = RESULT.get("error")
        RESULT["error"] = (f"{base}; " if base else "") + \
            f"killed by signal {signum}"
        emit()
        os._exit(0)

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, handler)
        except (ValueError, OSError):
            pass


def _start_watchdog() -> None:
    """Force-emit a partial artifact and exit if the run wedges (a hung
    device call can't be interrupted; the driver's kill would lose the
    JSON line entirely)."""
    # default sized for a COLD compile cache: the fused cap-shape
    # programs are the largest this repo compiles, and the r4 run showed
    # ~1100 s of remote compiles for a smaller program set — give the
    # first fused TPU run room before force-emitting a partial artifact
    seconds = float(os.environ.get("BENCH_WATCHDOG", "5400"))
    if seconds <= 0:
        return

    def fire():
        if RESULT.get("error"):  # workload incomplete — record the wedge
            RESULT["error"] += f" [watchdog fired after {seconds:.0f}s]"
        emit()  # metric already landed: emit as-is, drop the diagnostics
        os._exit(0)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()


def _microbench(group) -> None:
    """NTT-vs-CIOS powmod shootout + MFU estimate (VERDICT r3 item 3).

    Rates land in RESULT extra fields AND on stderr; best-effort — a
    failure here never breaks the artifact.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from electionguard_tpu.core.group_jax import JaxGroupOps

    B = 1024
    rng = np.random.default_rng(0)
    exps = [int.from_bytes(rng.bytes(32), "big") % group.q for _ in range(B)]
    bases = [pow(group.g, e | 1, group.p) for e in exps[:64]]
    bases = (bases * (B // 64 + 1))[:B]

    def timed(ops):
        A = jnp.asarray(ops.to_limbs_p(bases))
        E = jnp.asarray(ops.to_limbs_q(exps))
        out = retry(f"microbench-{ops.backend}-compile",
                    lambda: jax.block_until_ready(ops._powmod_j(A, E)))
        t0 = time.perf_counter()
        for _ in range(3):
            out = ops._powmod_j(A, E)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / 3

    lines = []
    rates: dict[str, float] = {}
    for backend in ("cios", "ntt", "pallas"):
        try:
            ops = JaxGroupOps(group, backend=backend)
            if ops.backend != backend:  # ntt silently degraded
                continue
            dt = timed(ops)
            rates[backend] = B / dt
            lines.append(f"{backend}={B / dt:.0f} powmod/s "
                         f"({dt / B * 1e6:.0f} us/el)")
        except Exception as e:  # noqa: BLE001 — diagnostics
            lines.append(f"{backend}=error({type(e).__name__})")
    # MFU estimate: one 4096-bit modexp with a 256-bit exponent is ~320
    # Montgomery mults (256 squarings + 64 window mults); each CIOS mult
    # is ~2*n^2 = 131072 16x16 MACs of useful work.  Denominator: the
    # chip's nominal ~400e12 int8 MAC/s — a rough utilization figure,
    # not a measured roofline.
    best = max(rates.values(), default=0.0)
    if best:
        macs = best * 320 * 2 * 256 * 256
        lines.append(f"mfu~{macs / 400e12 * 100:.2f}% "
                     f"({macs / 1e12:.2f} T useful-mac/s)")
        RESULT["mfu_pct"] = round(macs / 400e12 * 100, 3)
    RESULT["powmod_per_s"] = {k: round(v, 1) for k, v in rates.items()}
    note(f"microbench batch={B}: " + "  ".join(lines))


def _bench_bignum(group) -> None:
    """Per-backend primitive rates through core.bignum_bench.

    On the chip: production batch, full-width ladders, all three
    backends.  On the CPU fallback the pallas rows run in interpret
    mode (~2.5 s per emulated launch), so the batch, reps, and powmod
    ladder width shrink and the pallas row set drops the fixed-table
    ladder; every row records the shape it actually ran.
    """
    import jax

    from electionguard_tpu.core import bignum_bench

    on_tpu = jax.devices()[0].platform == "tpu"
    if not on_tpu:
        # measure the real pallas kernels (emulated) instead of the
        # silent ntt fallback
        os.environ.setdefault("EGTPU_PALLAS_INTERPRET", "1")
    batch = 512 if on_tpu else 16
    reps = 3 if on_tpu else 1
    rows: list = []
    RESULT["bignum"] = rows
    for backend in ("cios", "ntt", "pallas"):
        ops = ("mulmod", "powmod", "fixed")
        bits = None
        if backend == "pallas" and not on_tpu:
            ops = ("mulmod", "powmod")  # fixed = ~8k emulated launches
            bits, batch = 32, 8
        try:
            got = retry(f"bignum-{backend}",
                        lambda: bignum_bench.backend_rows(
                            group, backend, batch=batch, ops=ops,
                            exp_bits=bits, reps=reps))
        except Exception as e:  # noqa: BLE001 — diagnostics
            RESULT.setdefault("bignum_backend_errors", {})[backend] = \
                f"{type(e).__name__}: {e}"
            continue
        rows.extend(got)
        flush_partial()
    note("bignum phase: " + "  ".join(
        f"{r['effective']}:{r['op']}={r['per_s']:.0f}/s" for r in rows))


def _prewarm_fingerprint(g, mesh) -> dict:
    """What the prewarmed program set depends on: group, tile cap,
    bignum backend, sharding.  Same fingerprint + a populated persistent
    cache ⇒ every tile-shaped program is a cache hit and prewarm is a
    skippable no-op (VERDICT r6 item 1: the warm-cache fast path)."""
    from electionguard_tpu.core.group_jax import jax_ops

    ops = jax_ops(g)
    return {"group": g.spec.name, "tile": int(ops.tile),
            "backend": ops.backend, "sharded": mesh is not None}


def _prewarm_stamp_path() -> str:
    from electionguard_tpu.utils import enable_compile_cache
    return os.path.join(enable_compile_cache(), "egtpu_prewarm_stamp.json")


def _cache_is_prewarmed(g, mesh) -> bool:
    """True when a previous run prewarmed THIS program set into the
    persistent compile cache: the stamp fingerprint matches and the
    cache still holds at least as many entries as when it was stamped."""
    if os.environ.get("BENCH_FORCE_PREWARM"):
        return False
    try:
        with open(_prewarm_stamp_path()) as f:
            stamp = json.load(f)
        if stamp.get("fingerprint") != _prewarm_fingerprint(g, mesh):
            return False
        from electionguard_tpu.utils import enable_compile_cache
        entries = len([e for e in os.listdir(enable_compile_cache())
                       if not e.startswith("egtpu_")])
        return entries >= int(stamp.get("cache_entries", 1 << 62))
    except (OSError, ValueError):
        return False


def _stamp_prewarm(g, mesh) -> None:
    try:
        from electionguard_tpu.utils import enable_compile_cache
        entries = len([e for e in os.listdir(enable_compile_cache())
                       if not e.startswith("egtpu_")])
        with open(_prewarm_stamp_path(), "w") as f:
            json.dump({"fingerprint": _prewarm_fingerprint(g, mesh),
                       "cache_entries": entries}, f)
    except OSError as e:
        note(f"prewarm stamp failed: {e}")


def _prewarm_tiles(g, init, mesh=None) -> None:
    """Compile every cap-shaped program the measured pass will hit, one
    cheap retried dummy dispatch per op.  dispatch_bucket collapses all
    large batches onto the one tile shape, so after this the full pass
    is pure execution — a tunnel flake during these compiles costs one
    retry, not the run."""
    import numpy as np

    from electionguard_tpu.core.group_jax import jax_exp_ops, jax_ops
    from electionguard_tpu.core.hash import _encode
    from electionguard_tpu.encrypt.fused import get_fused_encryptor
    from electionguard_tpu.verify.fused import get_fused

    ops = jax_ops(g)
    ee = jax_exp_ops(g)
    fe = get_fused_encryptor(ops, ee, mesh)
    fv = get_fused(ops, mesh)
    cap = ops.tile
    ones = np.zeros((cap, ops.n), np.uint32)
    ones[:, 0] = 1
    zq = np.zeros((cap, ee.ne), np.uint32)
    K = init.joint_public_key.value
    qbar = init.extended_base_hash
    ops.fixed_table(K)      # build both key tables outside the timed
    ops.fixed_table_hat(K)  # steps (plain 8 MiB + NTT hat 64 MiB)
    seed_row = np.zeros(32, np.uint8)
    bids = np.zeros((cap, 32), np.uint8)
    ords = np.zeros(cap, np.uint32)
    votes = np.zeros(cap, np.int64)
    prod_in = np.broadcast_to(ones[:, None, :], (cap, 16, ops.n))
    prod_in_t = np.broadcast_to(ones[None], (16, cap, ops.n))
    steps = [
        ("enc-selections", lambda: fe.encrypt_selections(
            seed_row, bids, ords, votes, K, _encode(qbar))),
        ("enc-contests", lambda: fe.encrypt_contests(
            seed_row, bids, ords, zq, zq, K,
            _encode(qbar) + _encode(1))),
        ("ver-selections", lambda: fv.v4_selections(
            ones, ones, zq, zq, zq, zq, K, _encode(qbar))),
        ("ver-contests", lambda: fv.v5_contests(
            ones, ones, zq, zq, zq, K,
            _encode(qbar) + _encode(1))),
        ("mulmod", lambda: np.asarray(ops.mulmod(ones, ones))),
        ("prod-reduce", lambda: np.asarray(ops.prod_reduce(prod_in))),
        ("prod-reduce-wide", lambda: np.asarray(ops.prod_reduce(prod_in_t))),
    ]
    t_all = time.time()
    for tag, fn in steps:
        t0 = time.time()
        retry(f"prewarm-{tag}", fn)
        note(f"prewarm {tag}: {time.time() - t0:.1f}s")
    # recorded so a warm persistent compile cache is PROVABLE across
    # driver invocations: a second run's prewarm_s collapsing (~minutes
    # -> seconds) is the cache-hit evidence
    RESULT["prewarm_s"] = round(time.time() - t_all, 1)


def run_workload(nballots: int, n_chips: int) -> None:
    """Build a 1-guardian election, encrypt, tally, verify; fills RESULT.
    Each phase is retried so one transient dispatch failure doesn't kill
    the run."""
    from electionguard_tpu.ballot.plaintext import RandomBallotProvider
    from electionguard_tpu.core.group import production_group
    from electionguard_tpu.encrypt.encryptor import BatchEncryptor
    from electionguard_tpu.keyceremony.exchange import key_ceremony_exchange
    from electionguard_tpu.keyceremony.trustee import KeyCeremonyTrustee
    from electionguard_tpu.publish.election_record import (ElectionConfig,
                                                           ElectionRecord)
    from electionguard_tpu.tally.accumulate import accumulate_ballots
    from electionguard_tpu.utils import maybe_profile
    from electionguard_tpu.verify.verifier import Verifier
    from electionguard_tpu.workflow.e2e import sample_manifest

    t_setup = time.time()
    g = production_group()
    mesh = None
    if os.environ.get("BENCH_SHARDED"):
        # route the fused encrypt/verify programs through the dp-sharded
        # plane (1-chip mesh on the real chip; n-chip when a pod exists)
        from electionguard_tpu.parallel.mesh import DP_AXIS, election_mesh
        mesh = election_mesh()
        RESULT["sharded_dp"] = mesh.shape[DP_AXIS]
    manifest = sample_manifest(ncontests=1, nselections=2)
    trustees = [KeyCeremonyTrustee(g, "guardian-0", 1, 1)]
    init = key_ceremony_exchange(trustees, g).make_election_initialized(
        ElectionConfig(manifest, 1, 1), {"created_by": "bench"})
    seed = g.int_to_q(42)

    from electionguard_tpu.obs import trace as obs_trace

    def pipeline(bs, tag):
        # fresh encryptor per record: ballot ids repeat between the warm
        # and full passes, and one encryptor rejects repeated ids (its
        # nonce PRF is keyed by ballot identity)
        def done(phase, **extra):
            # per-phase partials land in RESULT as they complete AND are
            # flushed to disk, so even a SIGKILL mid-later-phase leaves a
            # diagnosable on-disk artifact (VERDICT r6 item 1)
            if tag == "full":
                RESULT["phases_done"] = \
                    RESULT.get("phases_done", "") + f" {phase}"
                RESULT.update(extra)
            flush_partial()

        # per-phase spans (EGTPU_OBS_TRACE): compile time inside a phase
        # is attributed to it by the obs.jaxmon listener, so the span
        # artifact separates host orchestration / device compile /
        # device execute per bench phase
        enc = BatchEncryptor(init, g, mesh=mesh)
        t0 = time.time()
        # encrypt with EGTPU_VERIFY_BATCH on so the proofs carry
        # commitment hints (device cost is zero — the commitments are
        # already computed for the challenge hash; only the transfer is
        # gated) and the batch-verify pass below has something to batch
        with _env_flag("EGTPU_VERIFY_BATCH", "1"), \
                obs_trace.span(f"bench.encrypt.{tag}", {"n": len(bs)}):
            encrypted, invalid = retry(
                f"{tag}-encrypt",
                lambda: enc.encrypt_ballots(bs, seed=seed))
        dt_enc = time.time() - t0
        assert not invalid and len(encrypted) == len(bs)
        done("encrypt", encrypt_per_s=round(len(bs) / dt_enc, 1))
        t0 = time.time()
        with obs_trace.span(f"bench.tally.{tag}"):
            tally_result = retry(
                f"{tag}-tally", lambda: accumulate_ballots(init, encrypted))
        done("tally", tally_s=round(time.time() - t0, 3))
        record = ElectionRecord(election_init=init,
                                encrypted_ballots=encrypted,
                                tally_result=tally_result)
        # warmup pass compiles every kernel at the measured shapes
        with obs_trace.span(f"bench.verify-warm.{tag}"):
            res = retry(f"{tag}-verify-warm",
                        lambda: Verifier(record, g, mesh=mesh).verify())
        assert res.ok, res.summary()
        done("verify_warm")
        t0 = time.time()
        with maybe_profile(f"bench-verify-{tag}"), \
                obs_trace.span(f"bench.verify.{tag}", {"n": len(bs)}):
            res = retry(f"{tag}-verify",
                        lambda: Verifier(record, g, mesh=mesh).verify())
        dt_ver = time.time() - t0
        assert res.ok, res.summary()
        done("verify")
        # RLC batch verify on the same record (EGTPU_VERIFY_BATCH): the
        # hints attached at encryption route V4/V5/V2 through the MSM
        # screen.  Warm pass first (the MSM/hint-hash programs compile
        # at this shape), then the timed pass; the naive rate above
        # stays the headline metric, the ratio is the tracked speedup.
        with _env_flag("EGTPU_VERIFY_BATCH", "1"):
            with obs_trace.span(f"bench.verify-batch-warm.{tag}"):
                res = retry(f"{tag}-verify-batch-warm",
                            lambda: Verifier(record, g, mesh=mesh).verify())
            assert res.ok, res.summary()
            t0 = time.time()
            with obs_trace.span(f"bench.verify-batch.{tag}",
                                {"n": len(bs)}):
                res = retry(f"{tag}-verify-batch",
                            lambda: Verifier(record, g, mesh=mesh).verify())
            dt_batch = time.time() - t0
            assert res.ok, res.summary()
        done("verify_batch")
        return dt_enc, dt_ver, dt_batch, record

    # tiny warm-up: proves the device path end-to-end cheaply and
    # populates the persistent compile cache.  2 ballots keeps every
    # warm dispatch inside the {16, 32} buckets — each distinct shape
    # costs a full remote compile on the tunnel, so fewer is faster.
    warm = list(RandomBallotProvider(manifest, 2, seed=2).ballots())
    note("warm-up pass (2 ballots) ...")
    pipeline(warm, "warm")
    from electionguard_tpu.core.group_jax import jax_ops
    sel_rows = 3 * nballots   # 2 selections + 1 placeholder per ballot
    if sel_rows > jax_ops(g).tile // 8:
        # the full pass will dispatch at the tile-cap shape — compile it
        # now, under retry (pointless for the small CPU fallback, whose
        # batches stay in the small power-of-two buckets) ... unless a
        # previous run already prewarmed this exact program set into the
        # persistent cache: then every dispatch is a cache hit and the
        # measured pass can start immediately (warm-cache fast path)
        if _cache_is_prewarmed(g, mesh):
            note("persistent cache holds the stamped prewarm set; "
                 "skipping tile prewarm")
            RESULT["prewarm_skipped_warm_cache"] = True
        else:
            note(f"warm-up done in {time.time() - t_setup:.1f}s; "
                 f"prewarming tile-shaped programs ...")
            _prewarm_tiles(g, init, mesh)
            _stamp_prewarm(g, mesh)
    t_setup = time.time() - t_setup
    RESULT["setup_s"] = round(t_setup, 1)
    # was the setup warm or cold? hit/miss/write counters of the on-disk
    # table cache (EGTPU_TABLE_CACHE), plus whether it was enabled at all
    from electionguard_tpu.core import table_cache
    RESULT["table_cache"] = dict(table_cache.stats(),
                                 dir=table_cache.cache_dir())
    flush_partial()
    note(f"setup done in {t_setup:.1f}s; full pass ({nballots} ballots)")

    ballots = list(RandomBallotProvider(manifest, nballots, seed=1).ballots())
    t_encrypt, t_verify, t_batch, record = pipeline(ballots, "full")

    rate = nballots / t_verify / n_chips
    RESULT.update(
        value=round(rate, 3),
        vs_baseline=round(rate / TARGET, 5),
        nballots=nballots,
        encrypt_per_s=round(nballots / t_encrypt, 1),
        verify_s=round(t_verify, 3),
        verify_batch_s=round(t_batch, 3),
        verify_batch_per_s=round(nballots / t_batch / n_chips, 3),
        verify_batch_speedup=round(t_verify / t_batch, 3),
        error=None,
    )
    note(f"nballots={nballots} chips={n_chips} "
         f"encrypt={t_encrypt:.2f}s ({nballots / t_encrypt:.1f}/s) "
         f"verify={t_verify:.2f}s batch={t_batch:.2f}s "
         f"({t_verify / t_batch:.2f}x) setup={t_setup:.1f}s")
    flush_partial()

    # ---- mixnet phase: shuffle ballots/s, prove s, verify ballots/s ------
    # best-effort: the headline verify metric is already landed, so a
    # mixnet failure is recorded but never triggers the CPU fallback
    try:
        _bench_mixnet(g, init, record, n_chips)
    except Exception as e:  # noqa: BLE001 — diagnostics
        note(f"mixnet phase failed: {type(e).__name__}: {e}")
        RESULT["mixnet_error"] = f"{type(e).__name__}: {e}"
    flush_partial()

    # ---- mixfed phase: federated stages/s over 2 real server processes ---
    # measures the PLANE (gRPC transport, chunked row streaming,
    # pre-forward verification, publish + checkpoint), not modexp
    # throughput — so it runs on the tiny group and stays best-effort
    try:
        _bench_mixfed()
    except Exception as e:  # noqa: BLE001 — diagnostics
        note(f"mixfed phase failed: {type(e).__name__}: {e}")
        RESULT["mixfed_error"] = f"{type(e).__name__}: {e}"
    flush_partial()

    # ---- obs phase: collector ingest rate + hot-path span overhead ------
    # the telemetry plane's two numbers: spans/s one collector sustains
    # over real gRPC, and the p99 delta the client hooks add to a traced
    # request loop (the <5% serving contract) — best-effort like mixfed
    try:
        _bench_obs()
    except Exception as e:  # noqa: BLE001 — diagnostics
        note(f"obs phase failed: {type(e).__name__}: {e}")
        RESULT["obs_error"] = f"{type(e).__name__}: {e}"
    flush_partial()

    # ---- fabric phase: router overhead + fleet ballots/s at 1/2/4 -------
    # the serving fabric's two numbers: the latency the front door adds
    # over a direct worker hit, and what an in-process fleet sustains as
    # workers are added — the routing plane, not modexp throughput, so
    # it pins the tiny group and stays best-effort like mixfed/obs
    try:
        _bench_fabric()
    except Exception as e:  # noqa: BLE001 — diagnostics
        note(f"fabric phase failed: {type(e).__name__}: {e}")
        RESULT["fabric_error"] = f"{type(e).__name__}: {e}"
    flush_partial()

    # ---- multitenant phase: N elections through ONE worker pool ---------
    # the shared-program fabric's numbers: aggregate ballots/s with 4
    # overlapping elections on one pool vs the same pool single-tenant
    # (the consolidation tax), the per-tenant p99 spread, and the
    # device-compile delta across the multi-tenant leg (0 = the traced
    # election key really is shared).  Tiny group, best-effort like the
    # planes above
    try:
        _bench_multitenant()
    except Exception as e:  # noqa: BLE001 — diagnostics
        note(f"multitenant phase failed: {type(e).__name__}: {e}")
        RESULT["multitenant_error"] = f"{type(e).__name__}: {e}"
    flush_partial()

    # ---- live phase: incremental verifier chunks/s + residual drain -----
    # the live verification plane's numbers: chunks/s the tailer+fold
    # sustains while the stream grows, the audit-lag p99 it holds, and
    # the residual finalize seconds once the election closes — plane
    # overhead, not modexp, so it pins the tiny group like mixfed/obs
    try:
        _bench_live()
    except Exception as e:  # noqa: BLE001 — diagnostics
        note(f"live phase failed: {type(e).__name__}: {e}")
        RESULT["live_error"] = f"{type(e).__name__}: {e}"
    flush_partial()

    # ---- validate phase: RLC screen rate + serve admission overhead -----
    # the ingestion gate's two numbers: production-group elements/s
    # through the batched subgroup screen, and the p99 delta the gate
    # adds to a real serve admission (the <10% ISSUE 17 contract) —
    # best-effort like the planes above
    try:
        _bench_validate()
    except Exception as e:  # noqa: BLE001 — diagnostics
        note(f"validate phase failed: {type(e).__name__}: {e}")
        RESULT["validate_error"] = f"{type(e).__name__}: {e}"
    flush_partial()

    # ---- bignum phase: per-backend primitive rates (cios/ntt/pallas) ----
    # the roofline's raw numbers — mulmod/powmod/fixed rows through the
    # shared core.bignum_bench helper, labeled requested-vs-effective.
    # Best-effort like the planes above; rows flush per backend.
    try:
        _bench_bignum(g)
    except Exception as e:  # noqa: BLE001 — diagnostics
        note(f"bignum phase failed: {type(e).__name__}: {e}")
        RESULT["bignum_error"] = f"{type(e).__name__}: {e}"
    flush_partial()

    # ---- race phase: monitor overhead on one deterministic sim run ------
    # the detector's cost: instrumented vs plain wall time for the same
    # seed (schedules are bit-identical — asserted), plus the monitor's
    # access-event throughput.  Best-effort like the planes above.
    try:
        _bench_race()
    except Exception as e:  # noqa: BLE001 — diagnostics
        note(f"race phase failed: {type(e).__name__}: {e}")
        RESULT["race_error"] = f"{type(e).__name__}: {e}"
    flush_partial()

    # ---- capacity phase: predicted-vs-actual model error ----------------
    # replays the capacity model (obs/capacity) against two measured
    # configurations — the SCALE.json fabric scaling point and a traced
    # tiny e2e election — so model drift gates through bench_diff like
    # any perf regression.  Best-effort like the planes above.
    try:
        _bench_capacity()
    except Exception as e:  # noqa: BLE001 — diagnostics
        note(f"capacity phase failed: {type(e).__name__}: {e}")
        RESULT["capacity_error"] = f"{type(e).__name__}: {e}"
    flush_partial()

    # ---- simscale phase: virtual-election playout rate ------------------
    # a million-ballot virtual election (sim/election) at a reduced
    # event rate: how many SIMULATED ballots the process-model layer
    # plays out per real second.  Guards the sim layer's own speed (a
    # scheduler or devicemodel regression shows up here, not in any
    # crypto metric).  Best-effort like the planes above.
    try:
        _bench_simscale()
    except Exception as e:  # noqa: BLE001 — diagnostics
        note(f"simscale phase failed: {type(e).__name__}: {e}")
        RESULT["simscale_error"] = f"{type(e).__name__}: {e}"
    flush_partial()

    import jax
    if jax.devices()[0].platform != "cpu":
        # the NTT-vs-CIOS shootout only means something on the chip; on
        # the CPU fallback it burns minutes for an irrelevant number
        try:
            _microbench(g)
        except Exception as e:  # noqa: BLE001 — diagnostics
            note(f"microbench skipped: {type(e).__name__}: {e}")


def _bench_capacity() -> None:
    """Capacity-model drift gate: re-validate the analytic pipeline
    model against measured configurations (obs/capacity.validate) and
    record the worst prediction error.  ``capacity_model_err_pct``
    carries a bench_diff band, so a code change that shifts the cost
    structure out from under the model fails the perf gate instead of
    silently rotting CAPACITY.md.  Also re-answers the headline chips
    question per fitted backend from the current artifacts."""
    from electionguard_tpu.obs import capacity
    from electionguard_tpu.utils import knobs

    v = capacity.validate()
    checked = [c for c in v["configs"] if "err_pct" in c]
    RESULT.update(
        capacity_model_err_pct=v["max_err_pct"],
        capacity_validation_pass=v["pass"],
        capacity_configs_checked=len(checked),
    )
    model = capacity.fit()
    ballots = knobs.get_int("EGTPU_CAPACITY_BALLOTS")
    deadline = knobs.get_float("EGTPU_CAPACITY_DEADLINE_S")
    RESULT["capacity_chips_for_deadline"] = {
        backend: capacity.chips_for_deadline(model, ballots, deadline,
                                             backend)["chips"]
        for backend in sorted(model.powmod_per_s)}
    RESULT["phases_done"] = RESULT.get("phases_done", "") + " capacity"
    note(f"capacity model err {v['max_err_pct']}% over {len(checked)} "
         f"measured config(s) ({'PASS' if v['pass'] else 'FAIL'})")


def _bench_simscale() -> None:
    """Virtual-election playout rate: one chaos-enabled 10^6-ballot
    election on the virtual clock at a reduced event rate (4 quarter-
    million micro-batches, 4 representative ballots per shape), timed
    end-to-end in real seconds.  ``sim_ballots_per_s`` carries a
    bench_diff band so a slowdown in the scheduler, procmodel, or
    devicemodel layers gates like any perf regression; the trace hash
    rides along so a rerun's bit-for-bit claim is checkable from
    BENCH.json alone."""
    from electionguard_tpu.sim.election import (ElectionSpec,
                                                run_virtual_election)

    spec = ElectionSpec(ballots=1_000_000, batch=250_000,
                        rep_ballots=4, workers=2, chips=8,
                        chaos_after_batches=2)
    rep = run_virtual_election(seed=3, spec=spec, chaos=True)
    if not rep.ok:
        raise RuntimeError(f"virtual election oracles: {rep.violations}")
    RESULT.update(
        sim_ballots_per_s=round(rep.ballots / max(rep.wall_s, 1e-9), 1),
        sim_virtual_s=round(rep.virtual_s, 1),
        sim_trace_hash=rep.trace_hash,
        sim_events=rep.events,
    )
    RESULT["phases_done"] = RESULT.get("phases_done", "") + " simscale"
    note(f"simscale: {RESULT['sim_ballots_per_s']:.0f} simulated "
         f"ballots/s ({rep.events} events in {rep.wall_s:.1f}s real, "
         f"{rep.virtual_s:.0f}s virtual)")


def _bench_live(nballots: int = 64, chunk: int = 8) -> None:
    """Live verification plane: a 1-guardian tiny election is written
    ballot-by-ballot through the framed stream while a ``LiveVerifier``
    tails it — every write is followed by a poll, so the measured tail
    time is pure plane cost (tailer read, chunk fold, ledger append,
    checkpoint fsync).  Then the terminal artifacts land and the
    residual drain + record-level finalize is timed separately: that is
    the work LEFT at election close, the e2e ``-liveVerify`` <5% gate's
    denominator."""
    import shutil
    import tempfile

    from electionguard_tpu.ballot.plaintext import RandomBallotProvider
    from electionguard_tpu.core.dlog import DLog
    from electionguard_tpu.core.group import tiny_group
    from electionguard_tpu.decrypt.decryption import Decryption
    from electionguard_tpu.decrypt.trustee import DecryptingTrustee
    from electionguard_tpu.encrypt.encryptor import BatchEncryptor
    from electionguard_tpu.keyceremony.exchange import key_ceremony_exchange
    from electionguard_tpu.keyceremony.trustee import KeyCeremonyTrustee
    from electionguard_tpu.publish.election_record import (DecryptionResult,
                                                           ElectionConfig)
    from electionguard_tpu.publish.publisher import Publisher
    from electionguard_tpu.tally.accumulate import accumulate_ballots
    from electionguard_tpu.verify.live import LiveVerifier
    from electionguard_tpu.workflow.e2e import sample_manifest

    g = tiny_group()
    manifest = sample_manifest(1, 2)
    trustees = [KeyCeremonyTrustee(g, "guardian-0", 1, 1)]
    init = key_ceremony_exchange(trustees, g).make_election_initialized(
        ElectionConfig(manifest, 1, 1), {"created_by": "bench"})
    ballots = list(RandomBallotProvider(manifest, nballots,
                                        seed=3).ballots())
    encrypted, invalid = BatchEncryptor(init, g).encrypt_ballots(
        ballots, seed=g.int_to_q(77))
    assert not invalid

    tally_result = accumulate_ballots(init, encrypted)
    dec = Decryption(
        g, init,
        [DecryptingTrustee.from_state(
            g, trustees[0].decrypting_trustee_state())],
        [], DLog(g, max_exponent=max(16, nballots + 2)))
    dr = DecryptionResult(tally_result,
                          dec.decrypt(tally_result.encrypted_tally),
                          tuple(dec.get_available_guardians()))

    out = tempfile.mkdtemp(prefix="bench_live_")
    try:
        pub = Publisher(out)
        pub.write_election_initialized(init)
        live = LiveVerifier(out, g, chunk=chunk)
        lags = []
        t_tail = 0.0
        with pub.open_encrypted_ballots() as stream:
            for eb in encrypted:
                stream.write(eb)
                stream.flush()
                t0 = time.perf_counter()
                live.poll()
                t_tail += time.perf_counter() - t0
                lags.append(live.audit_lag_frames())
        pub.write_tally_result(tally_result)
        pub.write_decryption_result(dr)
        t0 = time.perf_counter()
        res = live.finalize()
        t_resid = time.perf_counter() - t0
        if not res.ok:
            raise RuntimeError(f"live bench record went red: {res.errors}")
        n_chunks = len(live.ledger.chunks)
        lags.sort()
        p99 = lags[min(len(lags) - 1, int(0.99 * len(lags)))]
        RESULT.update(
            live_chunks_per_s=round(n_chunks / max(t_tail, 1e-9), 2),
            live_chunk_s=round(t_tail / max(n_chunks, 1), 4),
            live_audit_lag_p99=p99,
            live_residual_verify_s=round(t_resid, 3),
            live_nballots=nballots, live_chunk_frames=chunk,
        )
        RESULT["phases_done"] = RESULT.get("phases_done", "") + " live"
        note(f"live {nballots} ballots in chunks of {chunk}: "
             f"{n_chunks / max(t_tail, 1e-9):.1f} chunks/s tailing "
             f"(lag p99 {p99} frames), residual finalize {t_resid:.2f}s")
    finally:
        shutil.rmtree(out, ignore_errors=True)


def _bench_validate(n_elems: int = 512, nsingles: int = 32) -> None:
    """Ingestion-gate cost (ISSUE 17): (a) production-group elements/s
    through the RLC subgroup screen — the number the batched-vs-
    per-element argument rests on — and (b) the gate's share of a real
    serve admission round trip, p99 with EGTPU_VALIDATE on vs off over
    the same in-process server (tiny group, like mixfed/fabric: this
    measures the PLANE's <10% admission contract, not modexp)."""
    import shutil
    import tempfile

    from electionguard_tpu.ballot.plaintext import RandomBallotProvider
    from electionguard_tpu.core.group import production_group, tiny_group
    from electionguard_tpu.crypto import validate
    from electionguard_tpu.keyceremony.exchange import key_ceremony_exchange
    from electionguard_tpu.keyceremony.trustee import KeyCeremonyTrustee
    from electionguard_tpu.publish.election_record import ElectionConfig
    from electionguard_tpu.serve.service import (EncryptionClient,
                                                 EncryptionService)
    from electionguard_tpu.workflow.e2e import sample_manifest

    # -- (a) RLC screening rate, production group, one full chunk ------
    g = production_group()
    elems = [(f"el[{i}]", pow(g.g, i + 2, g.p)) for i in range(n_elems)]
    validate.gate_elements(g, elems, "bench")        # warm
    reps = 3
    t0 = time.perf_counter()
    for _ in range(reps):
        validate.gate_elements(g, elems, "bench")
    dt = time.perf_counter() - t0
    rlc_per_s = reps * n_elems / max(dt, 1e-9)

    # -- (b) serve-admission p99, gate on vs off -----------------------
    tg = tiny_group()
    manifest = sample_manifest(1, 2)
    trustees = [KeyCeremonyTrustee(tg, "guardian-0", 1, 1)]
    init = key_ceremony_exchange(trustees, tg).make_election_initialized(
        ElectionConfig(manifest, 1, 1), {"created_by": "bench"})
    ballots = list(RandomBallotProvider(manifest, 2 * nsingles + 2,
                                        seed=53).ballots())

    # ONE server + client for both modes: the per-admission gate sits
    # on the client's response path and reads EGTPU_VALIDATE live, so
    # flipping the knob between loops isolates the gate from server
    # lifecycle noise (compile warm-up would otherwise dominate
    # whichever mode ran first)
    out = tempfile.mkdtemp(prefix="bench_validate_")
    svc = EncryptionService(init, tg, port=0, out_dir=out,
                            max_batch=8, max_wait_ms=5)
    client = EncryptionClient(f"localhost:{svc.port}", tg)

    def p99_singles(bs, mode):
        with _env_flag("EGTPU_VALIDATE", mode):
            lat = []
            for b in bs:
                t0 = time.perf_counter()
                assert client.encrypt(b) is not None
                lat.append((time.perf_counter() - t0) * 1e3)
            lat.sort()
            return lat[min(len(lat) - 1, int(0.99 * len(lat)))]

    try:
        for b in ballots[:2]:                        # warm channel + jit
            client.encrypt(b)
        p99_off = p99_singles(ballots[2:nsingles + 2], "off")
        p99_on = p99_singles(ballots[nsingles + 2:], "on")
    finally:
        client.close()
        svc.shutdown()
        shutil.rmtree(out, ignore_errors=True)
    overhead = (p99_on - p99_off) / max(p99_off, 1e-9) * 100
    RESULT.update(
        validate_rlc_per_s=round(rlc_per_s, 1),
        validate_serve_p99_off_ms=round(p99_off, 2),
        validate_serve_p99_on_ms=round(p99_on, 2),
        validate_serve_overhead_pct=round(overhead, 1),
    )
    RESULT["phases_done"] = RESULT.get("phases_done", "") + " validate"
    note(f"validate: RLC screen {rlc_per_s:.0f} elems/s "
         f"({n_elems}-element production-group chunks); serve admission "
         f"p99 {p99_off:.1f}ms off -> {p99_on:.1f}ms on "
         f"({overhead:+.1f}%)")


def _bench_race() -> None:
    """Race-monitor overhead: one fast-profile sim seed run plain and
    then with the happens-before + lockset monitor attached.  The two
    runs dispatch the bit-identical schedule (asserted via trace hash),
    so the wall-time delta IS the monitor: vector-clock updates plus
    one callback per watched attribute access."""
    from electionguard_tpu.sim.cluster import SimConfig
    from electionguard_tpu.sim.explore import run_sim

    cfg = SimConfig(n_mix_stages=1)
    run_sim(0, config=cfg)                       # warm jit compiles
    t0 = time.perf_counter()
    plain = run_sim(0, config=cfg)
    t_off = time.perf_counter() - t0
    t0 = time.perf_counter()
    raced = run_sim(0, config=cfg, race=True)
    t_on = time.perf_counter() - t0
    if raced.trace_hash != plain.trace_hash:
        raise RuntimeError("race monitor perturbed the schedule")
    overhead = (t_on - t_off) / t_off * 100
    RESULT["race_monitor"] = {
        "events": raced.race_events,
        "events_per_s": round(raced.race_events / t_on, 1),
        "run_off_s": round(t_off, 3),
        "run_on_s": round(t_on, 3),
        "overhead_pct": round(overhead, 1),
    }
    note(f"race monitor: {raced.race_events} events "
         f"({raced.race_events / t_on:.0f}/s), "
         f"{t_off:.2f}s -> {t_on:.2f}s (+{overhead:.0f}%)")


def _bench_mixnet(g, init, record, n_chips: int) -> None:
    """Time one Terelius–Wikström mix stage over the bench record's
    ballots: batched re-encryption shuffle, proof generation, and proof
    verification (one warm stage first so measured numbers are
    execution, not compiles — same warm-then-measure discipline as the
    verify phases)."""
    from electionguard_tpu.mixnet import verify_mix
    from electionguard_tpu.mixnet.proof import prove_shuffle, rows_digest
    from electionguard_tpu.mixnet.shuffle import Shuffler
    from electionguard_tpu.mixnet.stage import MixStage, rows_from_ballots
    from electionguard_tpu.obs import trace as obs_trace
    from electionguard_tpu.verify.verifier import VerificationResult

    pads, datas = rows_from_ballots(record.encrypted_ballots)
    n, w = len(pads), len(pads[0])
    K = init.joint_public_key.value
    qbar = init.extended_base_hash
    shuffler = Shuffler(g, K)
    seed = b"bench-mix"

    def one_stage():
        out_p, out_d, perm, rand = retry(
            "mix-shuffle", lambda: shuffler.shuffle(pads, datas, seed))
        t_sh = time.time()
        out_p, out_d, perm, rand = shuffler.shuffle(pads, datas, seed)
        t_sh = time.time() - t_sh
        ih = rows_digest(g, pads, datas)
        retry("mix-prove",
              lambda: prove_shuffle(g, K, qbar, 0, pads, datas, out_p,
                                    out_d, perm, rand, seed,
                                    input_hash=ih))
        t_pr = time.time()
        proof = prove_shuffle(g, K, qbar, 0, pads, datas, out_p, out_d,
                              perm, rand, seed, input_hash=ih)
        t_pr = time.time() - t_pr
        stage = MixStage(0, n, w, ih, out_p, out_d, proof)
        retry("mix-verify",
              lambda: verify_mix.verify_stages(
                  g, init, [stage], VerificationResult(),
                  lambda: (pads, datas)))
        res = VerificationResult()
        t_ve = time.time()
        ok = verify_mix.verify_stages(g, init, [stage], res,
                                      lambda: (pads, datas))
        t_ve = time.time() - t_ve
        assert ok, res.summary()
        return t_sh, t_pr, t_ve

    with obs_trace.span("bench.mixnet", {"n": n, "w": w}):
        t_sh, t_pr, t_ve = one_stage()
    RESULT.update(
        mix_shuffle_per_s=round(n / max(t_sh, 1e-9) / n_chips, 1),
        mix_prove_s=round(t_pr, 3),
        mix_verify_per_s=round(n / max(t_ve, 1e-9) / n_chips, 1),
        mix_rows=n, mix_width=w,
    )
    RESULT["phases_done"] = RESULT.get("phases_done", "") + " mixnet"
    note(f"mixnet n={n} w={w}: shuffle={t_sh:.2f}s "
         f"({n / max(t_sh, 1e-9):.1f}/s) prove={t_pr:.2f}s "
         f"verify={t_ve:.2f}s ({n / max(t_ve, 1e-9):.1f}/s)")


def _bench_mixfed(n_stages: int = 2, n_rows: int = 64,
                  width: int = 2) -> None:
    """Federated mixing throughput: an in-process coordinator drives
    ``n_stages`` stages over 2 REAL mix-server OS processes (reverse
    registration, chunked row push/pull over gRPC, shuffle + TW proof,
    pre-forward verification, framed publish, checkpoint fsync).  The
    headline number is stages/s — the per-stage overhead ceiling of the
    federated plane itself; modexp throughput is _bench_mixnet's job, so
    this phase pins the tiny group and CPU servers on purpose."""
    import shutil
    import tempfile

    from electionguard_tpu.core.group import tiny_group
    from electionguard_tpu.crypto.elgamal import (ElGamalKeypair,
                                                  elgamal_encrypt)
    from electionguard_tpu.mixfed.coordinator import MixCoordinator
    from electionguard_tpu.obs import trace as obs_trace
    from electionguard_tpu.utils.platform import detach_axon

    g = tiny_group()
    key = ElGamalKeypair.from_secret(g.int_to_q(987654321))
    K, qbar = key.public_key, g.int_to_q(424242)
    pads, datas = [], []
    for i in range(n_rows):
        row_a, row_b = [], []
        for j in range(width):
            ct = elgamal_encrypt(g, (i + j) % 2,
                                 g.int_to_q(5000 + i * width + j), K)
            row_a.append(ct.pad.value)
            row_b.append(ct.data.value)
        pads.append(row_a)
        datas.append(row_b)

    out = tempfile.mkdtemp(prefix="bench_mixfed_")
    env = dict(os.environ)
    detach_axon(env)          # servers never contend for the bench chip
    env["JAX_PLATFORMS"] = "cpu"
    procs: list = []
    shut = False
    coord = MixCoordinator(g, out, port=0)
    try:
        for i in range(2):
            procs.append(subprocess.Popen(
                [sys.executable, "-m",
                 "electionguard_tpu.cli.run_mix_server",
                 "-name", f"bench-mix-{i}",
                 "-serverPort", str(coord.port), "-group", "tiny"],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL))
        if not coord.wait_for_servers(2, timeout=120):
            raise RuntimeError("mix servers failed to register in 120s")
        t0 = time.time()
        with obs_trace.span("bench.mixfed",
                            {"n": n_rows, "w": width, "stages": n_stages}):
            published = coord.run_mix(K.value, qbar, n_stages, pads, datas)
        dt = time.time() - t0
        assert published == n_stages, f"published {published}/{n_stages}"
        coord.shutdown(all_ok=True)
        shut = True
        for p in procs:
            p.wait(timeout=30)
        RESULT.update(
            mixfed_stages_per_s=round(n_stages / max(dt, 1e-9), 2),
            mixfed_stage_s=round(dt / n_stages, 3),
            mixfed_rows=n_rows, mixfed_servers=2,
        )
        RESULT["phases_done"] = RESULT.get("phases_done", "") + " mixfed"
        note(f"mixfed {n_stages} stages x {n_rows} rows over 2 server "
             f"processes: {dt:.2f}s ({n_stages / max(dt, 1e-9):.2f} "
             f"stages/s)")
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        if not shut:
            try:
                coord.shutdown(all_ok=False)
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        shutil.rmtree(out, ignore_errors=True)


def _bench_obs(n_batches: int = 20, batch_spans: int = 1000,
               n_requests: int = 1000) -> None:
    """Telemetry-plane overhead: how many spans/s one collector ingests
    over real gRPC (synthetic pre-serialized batches, the pure ingest
    path), and what p99 latency the client's hot-path hook — a bounded
    buffer append — adds to a traced request loop, collector attached
    vs. not.  The serving plane rides this contract, so the delta is the
    number to watch (the e2e acceptance bound is <5%)."""
    import shutil
    import tempfile

    from electionguard_tpu.obs import collector as obs_collector
    from electionguard_tpu.obs import trace as obs_trace
    from electionguard_tpu.publish import pb
    from electionguard_tpu.remote import rpc_util

    out = tempfile.mkdtemp(prefix="bench_obs_")
    if not obs_trace.enabled():
        # the request loop measures real span export; enable into the
        # temp dir when the run isn't already traced
        obs_trace.enable(os.path.join(out, "trace"), proc="bench-obs")

    import hashlib
    buf = os.urandom(2 << 20)

    def request_loop():
        # one traced "request" of ~1ms GIL-RELEASING work (sha256 over a
        # big buffer) — the per-call shape of a serving request, whose
        # ms-scale crypto runs on the device with the GIL released, so
        # the client's background pusher overlaps it like in production
        # instead of serializing against a pure-Python loop
        lat = []
        for _ in range(n_requests):
            t0 = time.perf_counter()
            with obs_trace.span("bench.obs.request"):
                hashlib.sha256(buf).digest()
            lat.append(time.perf_counter() - t0)
        lat.sort()
        return lat[int(0.99 * len(lat))] * 1e3  # ms

    collector, server, port, _ = obs_collector.serve(0, out,
                                                     http_port=None)
    client = None
    channel = None
    try:
        # -- hot-path p99 first, while the collector is quiet: the same
        # loop with and without the client hooks attached --
        request_loop()  # warm-up (interpreter, span path) — discarded
        p99_off = request_loop()
        client = obs_collector.TelemetryClient(f"localhost:{port}")
        client.start()
        p99_on = request_loop()
        overhead = (p99_on - p99_off) / max(p99_off, 1e-9) * 100
        # the deterministic half of the contract: the per-span cost the
        # export hook adds on the caller's thread (serialize + bounded
        # buffer append) — µs-scale, independent of scheduler noise
        rec = {"trace_id": "ab" * 16, "span_id": "cd" * 8,
               "parent_id": "", "name": "bench.obs.hook",
               "proc": "bench-obs", "pid": 1, "tid": 0, "ts": 1, "dur": 1}
        t0 = time.perf_counter()
        for _ in range(10000):
            client._on_span(rec)
        hook_us = (time.perf_counter() - t0) / 10000 * 1e6

        # -- ingest throughput: pre-built batches straight at the rpc --
        lines = [json.dumps(
            {"trace_id": "ab" * 16, "span_id": f"{i:016x}",
             "parent_id": "", "name": "bench.obs.ingest",
             "proc": "bench-load", "pid": 1, "tid": 0, "ts": i, "dur": 1})
            for i in range(batch_spans)]
        channel = rpc_util.make_plain_channel(f"localhost:{port}")
        stub = rpc_util.Stub(channel, "ObsCollectorService")

        def push(seq):
            stub.call("pushTelemetry", pb.msg("TelemetryBatch")(
                proc="bench-load", pid=1, seq=seq, span_lines=lines,
                heartbeat=pb.msg("ObsHeartbeat")(status="SERVING")))

        push(1)  # warm the channel + descriptor path
        t0 = time.time()
        for k in range(n_batches):
            push(k + 2)
        dt = time.time() - t0
        spans_per_s = n_batches * batch_spans / max(dt, 1e-9)
        RESULT.update(
            obs_spans_per_s=round(spans_per_s, 1),
            obs_p99_off_ms=round(p99_off, 4),
            obs_p99_on_ms=round(p99_on, 4),
            obs_p99_overhead_pct=round(overhead, 2),
            obs_hook_us=round(hook_us, 2),
        )
        RESULT["phases_done"] = RESULT.get("phases_done", "") + " obs"
        note(f"obs ingest {n_batches}x{batch_spans} spans in {dt:.2f}s "
             f"({spans_per_s:.0f} spans/s); request p99 "
             f"{p99_off:.4f}ms -> {p99_on:.4f}ms with client "
             f"({overhead:+.1f}%); hook {hook_us:.1f}us/span")
    finally:
        if client is not None:
            client.close()
        if channel is not None:
            channel.close()
        collector.stop()
        server.stop(grace=0)
        shutil.rmtree(out, ignore_errors=True)


def _bench_fabric(fleets=(1, 2, 4), nsingles: int = 24,
                  per_client: int = 16) -> None:
    """Serving-fabric plane: (a) the p50 latency penalty the router's
    forward hop adds over hitting a worker directly, and (b) fleet
    ballots/s at 1/2/4 in-process workers behind one router (3 closed-
    loop clients per worker, full-bucket batch rpcs).  Everything runs
    in one process on the tiny group — this measures the routing plane
    (forwarding, least-depth selection, health bookkeeping), so on a
    host with few cores the curve is expected to flatten once the
    workers saturate the CPU; tools/scale_run.py --fabric is the
    subprocess drill with a pinned device leg."""
    import shutil
    import statistics
    import tempfile
    import threading
    from dataclasses import replace as dc_replace

    from electionguard_tpu.ballot.plaintext import RandomBallotProvider
    from electionguard_tpu.core.group import tiny_group
    from electionguard_tpu.fabric import manifest as fab_manifest
    from electionguard_tpu.fabric.router import EncryptionRouter
    from electionguard_tpu.keyceremony.exchange import key_ceremony_exchange
    from electionguard_tpu.keyceremony.trustee import KeyCeremonyTrustee
    from electionguard_tpu.publish.election_record import ElectionConfig
    from electionguard_tpu.remote import rpc_util
    from electionguard_tpu.serve.service import (EncryptionClient,
                                                 EncryptionService)
    from electionguard_tpu.workflow.e2e import sample_manifest

    g = tiny_group()
    manifest = sample_manifest(1, 2)
    trustees = [KeyCeremonyTrustee(g, "guardian-0", 1, 1)]
    init = key_ceremony_exchange(trustees, g).make_election_initialized(
        ElectionConfig(manifest, 1, 1), {"created_by": "bench"})
    out = tempfile.mkdtemp(prefix="bench_fabric_")

    def make_worker(router, sid_dir, wid):
        kp = fab_manifest.ManifestKeypair.generate(g)
        port = rpc_util.find_free_port()
        ch = rpc_util.make_channel(router.url)
        try:
            from electionguard_tpu.publish import pb
            resp = rpc_util.Stub(ch, "FabricRegistrationService").call(
                "registerEncryptionWorker",
                pb.RegisterEncryptionWorkerRequest(
                    worker_id=wid, remote_url=f"localhost:{port}",
                    group_fingerprint=g.fingerprint(),
                    registration_nonce=os.urandom(16),
                    manifest_public_key=kp.public.value.to_bytes(
                        (kp.public.value.bit_length() + 7) // 8 or 1,
                        "big")))
        finally:
            ch.close()
        return EncryptionService(
            init, g, port=port, out_dir=os.path.join(out, sid_dir),
            max_batch=8, max_wait_ms=5, shard_id=resp.shard_id,
            worker_id=wid, chain_seed=fab_manifest.shard_chain_seed(
                init.manifest_hash, resp.shard_id),
            manifest_keypair=kp)

    def p50_singles(url, ballots):
        client = EncryptionClient(url, g)
        try:
            client.encrypt(ballots[0])  # warm the channel
            lat = []
            for b in ballots[1:]:
                t0 = time.perf_counter()
                assert client.encrypt(b) is not None
                lat.append((time.perf_counter() - t0) * 1e3)
            return statistics.median(lat)
        finally:
            client.close()

    try:
        # -- (a) router overhead: same worker config, direct vs fronted --
        ballots = list(RandomBallotProvider(manifest, nsingles + 1,
                                            seed=31).ballots())
        direct = EncryptionService(init, g, port=0,
                                   out_dir=os.path.join(out, "direct"),
                                   max_batch=8, max_wait_ms=5)
        p50_direct = p50_singles(f"localhost:{direct.port}", ballots)
        direct.shutdown()
        router = EncryptionRouter(g, health_interval=0.5)
        svc = make_worker(router, "fronted", "wf")
        router.wait_for_workers(1, timeout=60, live=True)
        fronted_ballots = [dc_replace(b, ballot_id="f-" + b.ballot_id)
                           for b in ballots]
        p50_router = p50_singles(router.url, fronted_ballots)
        svc.shutdown()
        router.shutdown()
        RESULT.update(
            fabric_direct_p50_ms=round(p50_direct, 2),
            fabric_router_p50_ms=round(p50_router, 2),
            fabric_router_overhead_ms=round(p50_router - p50_direct, 2),
        )
        note(f"fabric router hop: direct p50 {p50_direct:.2f}ms -> "
             f"fronted {p50_router:.2f}ms "
             f"({p50_router - p50_direct:+.2f}ms)")

        # -- (b) fleet curve: 3 closed-loop clients per worker ------------
        for w in fleets:
            router = EncryptionRouter(g, health_interval=0.5)
            svcs = [make_worker(router, f"x{w}-s{i}", f"x{w}w{i}")
                    for i in range(w)]
            router.wait_for_workers(w, timeout=60, live=True)
            nclients = 3 * w
            protos = list(RandomBallotProvider(
                manifest, per_client, seed=77).ballots())
            done = []

            def one_client(ci):
                client = EncryptionClient(router.url, g)
                try:
                    mine = [dc_replace(b, ballot_id=f"c{ci}-{b.ballot_id}")
                            for b in protos]
                    for k in range(0, len(mine), 8):
                        res = client.encrypt_batch(mine[k:k + 8])
                        assert all(e is not None for e, _ in res)
                    done.append(len(mine))
                finally:
                    client.close()

            threads = [threading.Thread(target=one_client, args=(ci,),
                                        daemon=True)
                       for ci in range(nclients)]
            t0 = time.time()
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=600)
            dt = time.time() - t0
            for s in svcs:
                s.shutdown()
            router.shutdown()
            total = sum(done)
            assert total == nclients * per_client, \
                f"fleet x{w}: {total}/{nclients * per_client}"
            rate = total / max(dt, 1e-9)
            RESULT[f"fabric_{w}w_ballots_per_s"] = round(rate, 1)
            note(f"fabric fleet x{w}: {total} ballots in {dt:.2f}s "
                 f"({rate:.1f}/s)")
        RESULT["phases_done"] = RESULT.get("phases_done", "") + " fabric"
    finally:
        shutil.rmtree(out, ignore_errors=True)


def _bench_multitenant(n_tenants: int = 4, per_tenant: int = 16) -> None:
    """Multi-tenant consolidation tax: ``n_tenants`` elections with
    distinct key ceremonies interleaved through ONE EncryptionService
    vs the same pool serving a single tenant.  Three numbers: aggregate
    ballots/s across the overlapping elections, the per-tenant p99
    spread (max - min), and the device-compile delta across the
    multi-tenant leg — 0 means the election key really is a traced
    argument and tenants share every compiled bucket program.  Tiny
    group: this measures the tenant plane, not modexp throughput."""
    import threading
    from dataclasses import replace as dc_replace

    from electionguard_tpu.ballot.plaintext import RandomBallotProvider
    from electionguard_tpu.core.group import tiny_group
    from electionguard_tpu.keyceremony.exchange import key_ceremony_exchange
    from electionguard_tpu.keyceremony.trustee import KeyCeremonyTrustee
    from electionguard_tpu.obs import tenant
    from electionguard_tpu.publish.election_record import ElectionConfig
    from electionguard_tpu.serve.service import (EncryptionClient,
                                                 EncryptionService)
    from electionguard_tpu.serve.tenants import (ElectionContext,
                                                 TenantRegistry)
    from electionguard_tpu.workflow.e2e import sample_manifest

    g = tiny_group()
    manifest = sample_manifest(1, 2)

    def ceremony(tag):
        trustees = [KeyCeremonyTrustee(g, f"{tag}-g0", 1, 1)]
        return key_ceremony_exchange(trustees, g).make_election_initialized(
            ElectionConfig(manifest, 1, 1), {"created_by": f"bench-{tag}"})

    protos = list(RandomBallotProvider(manifest, per_tenant,
                                       seed=53).ballots())

    def run_pool(tenant_ids):
        registry = TenantRegistry()
        for i, el in enumerate(tenant_ids):
            registry.add(ElectionContext(el, ceremony(el), group=g,
                                         seed=g.int_to_q(301 + i)))
        svc = EncryptionService(ceremony(f"{tenant_ids[0]}-house"), g,
                                max_batch=8, max_wait_ms=5,
                                tenants=registry)
        try:
            url = f"localhost:{svc.port}"
            warm = EncryptionClient(url, g)   # build each lane's key table
            for el in tenant_ids:
                with tenant.tenant_scope(el):
                    warm.encrypt(dc_replace(protos[0],
                                            ballot_id=f"{el}-warm"))
            warm.close()
            compiles0 = svc.metrics.counters()["device_compiles"]
            done = []

            def one_tenant(el):
                client = EncryptionClient(url, g)
                try:
                    with tenant.tenant_scope(el):
                        mine = [dc_replace(b,
                                           ballot_id=f"{el}-{b.ballot_id}")
                                for b in protos]
                        for k in range(0, len(mine), 8):
                            res = client.encrypt_batch(mine[k:k + 8])
                            assert all(e is not None for e, _ in res)
                    done.append(len(mine))
                finally:
                    client.close()

            threads = [threading.Thread(target=one_tenant, args=(el,),
                                        daemon=True)
                       for el in tenant_ids]
            t0 = time.time()
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=600)
            dt = time.time() - t0
            total = sum(done)
            assert total == len(tenant_ids) * per_tenant, \
                f"multitenant: {total}/{len(tenant_ids) * per_tenant}"
            p99s = [svc.metrics.histogram_for("request_latency_ms",
                                              el).quantile(0.99)
                    for el in tenant_ids]
            compiles = svc.metrics.counters()["device_compiles"] - compiles0
            return total / max(dt, 1e-9), p99s, compiles
        finally:
            svc.drain()

    els = [f"mt-{c}" for c in "abcdefgh"][:n_tenants]
    agg_rate, p99s, compiles = run_pool(els)
    solo_rate, _, _ = run_pool(["mt-solo"])
    RESULT.update(
        tenant_aggregate_ballots_per_s=round(agg_rate, 1),
        tenant_single_ballots_per_s=round(solo_rate, 1),
        tenant_p99_spread_ms=round(max(p99s) - min(p99s), 2),
        tenant_compiles_delta=int(compiles),
    )
    note(f"multitenant x{n_tenants}: {agg_rate:.1f} ballots/s aggregate "
         f"(solo {solo_rate:.1f}/s), p99 spread "
         f"{max(p99s) - min(p99s):.2f}ms, {compiles} compiles after "
         f"warmup")
    RESULT["phases_done"] = RESULT.get("phases_done", "") + " multitenant"


def _cpu_fallback(tpu_error: str) -> bool:
    """Re-run this benchmark in a detached-from-tunnel CPU subprocess and
    adopt its JSON line; returns True if a number was recovered."""
    from electionguard_tpu.utils.platform import detach_axon

    env = dict(os.environ)
    detach_axon(env)
    env["BENCH_NBALLOTS"] = "32"   # never inherit a TPU-sized batch
    env["BENCH_NO_FALLBACK"] = "1"
    env["BENCH_WATCHDOG"] = "600"
    note("re-running on CPU after TPU failure ...")
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
            capture_output=True, text=True, timeout=900)
    except subprocess.TimeoutExpired:
        note("CPU fallback timed out")
        return False
    sys.stderr.write(r.stderr[-4000:])
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            child = json.loads(line)
        except ValueError:
            continue
        if child.get("error"):
            # the CPU run failed too — keep both causes, don't present
            # a 0.0 artifact as a valid measurement
            note(f"CPU fallback also failed: {child['error']}")
            RESULT["error"] = (f"tpu run failed ({tpu_error}); "
                               f"cpu fallback failed ({child['error']})")
            return False
        RESULT.update(child)
        RESULT["error"] = f"tpu run failed ({tpu_error}); value is CPU"
        RESULT["platform"] = "cpu"
        return True
    note(f"CPU fallback produced no JSON (rc={r.returncode})")
    return False


def main() -> int:
    atexit.register(emit)
    _install_signal_emitters()
    _start_watchdog()

    from electionguard_tpu.utils.platform import ensure_tpu_or_cpu
    platform = ensure_tpu_or_cpu(
        probe_timeout=float(os.environ.get("BENCH_PROBE_TIMEOUT", "90")),
        retries=int(os.environ.get("BENCH_PROBE_RETRIES", "3")),
        retry_wait=float(os.environ.get("BENCH_PROBE_WAIT", "20")))
    RESULT["platform"] = platform
    # >=4096 selections on TPU (2 selections/ballot); small on CPU fallback
    nballots = int(os.environ.get(
        "BENCH_NBALLOTS", "2048" if platform == "tpu" else "32"))
    RESULT["nballots"] = nballots
    flush_partial()

    from electionguard_tpu.utils import enable_compile_cache
    cache_dir = enable_compile_cache()
    try:  # cache population across runs = the cross-process hit evidence
        RESULT["compile_cache_entries_start"] = len(os.listdir(cache_dir))
    except OSError:
        pass

    # span artifacts per phase when EGTPU_OBS_TRACE is set (plus the
    # Prometheus endpoint / JSONL log mirror on their own env vars)
    from electionguard_tpu import obs
    obs.init_from_env()

    import jax
    n_chips = max(1, len(jax.devices()))
    actual = jax.devices()[0].platform
    if actual != platform:
        note(f"platform mismatch: probed {platform}, jax reports {actual}")
        RESULT["platform"] = platform = \
            "tpu" if actual not in ("cpu",) else "cpu"
        if "BENCH_NBALLOTS" not in os.environ:
            # re-pick the batch for the platform we actually landed on —
            # a TPU-sized batch on a CPU fallback would wedge for hours
            nballots = 2048 if platform == "tpu" else 32
            RESULT["nballots"] = nballots

    try:
        run_workload(nballots, n_chips)
    except Exception as e:  # noqa: BLE001 — emit SOMETHING, always
        err = f"{type(e).__name__}: {e}"
        note(f"workload failed: {err}")
        RESULT["error"] = err
        if (platform == "tpu"
                and not os.environ.get("BENCH_NO_FALLBACK")):
            _cpu_fallback(err)
    try:
        RESULT["compile_cache_entries_end"] = len(os.listdir(cache_dir))
    except OSError:
        pass
    if RESULT.get("error") is None:
        _append_progress_row()
    emit()
    return 0


if __name__ == "__main__":
    sys.exit(main())

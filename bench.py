"""Driver benchmark: ballots verified+tallied per second per chip.

Measures the BASELINE.md north-star path on the production 4096-bit group:
batch verification of encrypted ballots (subgroup membership + disjunctive
Chaum-Pedersen selection proofs + contest limit proofs + code chain +
homomorphic tally aggregation — Verifier V4-V7) over the device batch plane.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` is value / (1M ballots / 60 s / 8 chips) — the driver target
"verify 1M encrypted ballots in <60 s on a v5e-8" (BASELINE.json); >1.0
means the target rate is met on this chip.
"""

from __future__ import annotations

import json
import os
import sys
import time


def main() -> int:
    nballots = int(os.environ.get("BENCH_NBALLOTS", "256"))
    t_setup = time.time()

    from electionguard_tpu.utils import enable_compile_cache, maybe_profile
    enable_compile_cache()

    from electionguard_tpu.ballot.plaintext import RandomBallotProvider
    from electionguard_tpu.core.group import production_group
    from electionguard_tpu.encrypt.encryptor import BatchEncryptor
    from electionguard_tpu.keyceremony.exchange import key_ceremony_exchange
    from electionguard_tpu.keyceremony.trustee import KeyCeremonyTrustee
    from electionguard_tpu.publish.election_record import (ElectionConfig,
                                                           ElectionRecord)
    from electionguard_tpu.tally.accumulate import accumulate_ballots
    from electionguard_tpu.verify.verifier import Verifier
    from electionguard_tpu.workflow.e2e import sample_manifest

    import jax
    n_chips = max(1, len(jax.devices()))

    g = production_group()
    manifest = sample_manifest(ncontests=1, nselections=2)
    trustees = [KeyCeremonyTrustee(g, "guardian-0", 1, 1)]
    init = key_ceremony_exchange(trustees, g).make_election_initialized(
        ElectionConfig(manifest, 1, 1), {"created_by": "bench"})

    ballots = list(RandomBallotProvider(manifest, nballots, seed=1).ballots())
    enc = BatchEncryptor(init, g)
    t0 = time.time()
    encrypted, invalid = enc.encrypt_ballots(ballots, seed=g.int_to_q(42))
    t_encrypt = time.time() - t0
    assert not invalid and len(encrypted) == nballots
    tally_result = accumulate_ballots(init, encrypted)

    record = ElectionRecord(election_init=init, encrypted_ballots=encrypted,
                            tally_result=tally_result)

    t_setup = time.time() - t_setup  # election build + encrypt + tally

    # warmup pass compiles every kernel at the measured shapes
    res = Verifier(record, g).verify()
    assert res.ok, res.summary()
    t0 = time.time()
    with maybe_profile("bench-verify"):
        res = Verifier(record, g).verify()
    t_verify = time.time() - t0
    assert res.ok, res.summary()

    ballots_per_sec_per_chip = nballots / t_verify / n_chips
    target = 1_000_000 / 60.0 / 8  # 1M ballots / 60 s / v5e-8
    print(json.dumps({
        "metric": "ballots_verified_tallied_per_sec_per_chip",
        "value": round(ballots_per_sec_per_chip, 3),
        "unit": "ballots/s/chip",
        "vs_baseline": round(ballots_per_sec_per_chip / target, 5),
    }))
    print(f"# nballots={nballots} chips={n_chips} "
          f"encrypt={t_encrypt:.2f}s ({nballots / t_encrypt:.1f}/s) "
          f"verify={t_verify:.2f}s setup={t_setup:.1f}s "
          f"platform={jax.devices()[0].platform}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Driver benchmark: ballots verified+tallied per second per chip.

Measures the BASELINE.md north-star path on the production 4096-bit group:
batch verification of encrypted ballots (subgroup membership + disjunctive
Chaum-Pedersen selection proofs + contest limit proofs + code chain +
homomorphic tally aggregation — Verifier V4-V7) over the device batch plane.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` is value / (1M ballots / 60 s / 8 chips) — the driver target
"verify 1M encrypted ballots in <60 s on a v5e-8" (BASELINE.json); >1.0
means the target rate is met on this chip.

Platform handling: the real TPU sits behind the flaky axon tunnel (a wedged
relay HANGS ``import jax``), so before any jax import we probe TPU
reachability in a bounded subprocess and fall back to CPU by stripping the
tunnel env — the same escape hatch tests/conftest.py uses.  Knobs:
BENCH_NBALLOTS, BENCH_PROBE_TIMEOUT/RETRIES/WAIT.
"""

from __future__ import annotations

import json
import os
import sys
import time


def _microbench(group, nballots: int) -> None:
    """NTT-vs-CIOS powmod comparison + MFU estimate, to stderr only.

    Best-effort diagnostics: wrapped by the caller so a failure here can
    never break the JSON artifact.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from electionguard_tpu.core.group_jax import JaxGroupOps

    B = min(4096, max(256, 2 * nballots))
    rng = np.random.default_rng(0)
    exps = [int.from_bytes(rng.bytes(32), "big") % group.q
            for _ in range(B)]
    bases = [pow(group.g, e | 1, group.p) for e in exps[:64]]
    bases = (bases * (B // 64 + 1))[:B]

    def timed(ops):
        A = jnp.asarray(ops.to_limbs_p(bases))
        E = jnp.asarray(ops.to_limbs_q(exps))
        out = ops._powmod_j(A, E)            # compile + warmup
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(3):
            out = ops._powmod_j(A, E)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / 3

    lines = []
    rates = {}
    for backend in ("cios", "ntt"):
        try:
            ops = JaxGroupOps(group, backend=backend)
            if ops.backend != backend:       # ntt silently degraded
                continue
            dt = timed(ops)
            rates[backend] = B / dt
            lines.append(f"{backend}={B / dt:.0f} powmod/s "
                         f"({dt / B * 1e6:.0f} us/el)")
        except Exception as e:               # noqa: BLE001 — diagnostics
            lines.append(f"{backend}=error({type(e).__name__})")
    # MFU estimate: one 4096-bit modexp with a 256-bit exponent is ~320
    # Montgomery mults (256 squarings + 64 window mults); each CIOS mult
    # is ~2*n^2 = 131072 16x16 MACs of useful work.  Denominator: the
    # chip's nominal ~400e12 int8 MAC/s (Trillium-class per the env notes)
    # — a rough utilization figure, not a measured roofline.
    best = max(rates.values(), default=0.0)
    if best:
        macs = best * 320 * 2 * 256 * 256
        lines.append(f"mfu~{macs / 400e12 * 100:.2f}% "
                     f"({macs / 1e12:.2f} T useful-mac/s)")
    print(f"# microbench batch={B}: " + "  ".join(lines), file=sys.stderr)


def main() -> int:
    from electionguard_tpu.utils.platform import ensure_tpu_or_cpu
    platform = ensure_tpu_or_cpu(
        probe_timeout=float(os.environ.get("BENCH_PROBE_TIMEOUT", "90")),
        retries=int(os.environ.get("BENCH_PROBE_RETRIES", "2")),
        retry_wait=float(os.environ.get("BENCH_PROBE_WAIT", "20")))
    # >=4096 selections on TPU (2 selections/ballot); small on CPU fallback
    nballots = int(os.environ.get(
        "BENCH_NBALLOTS", "2048" if platform == "tpu" else "32"))
    t_setup = time.time()

    from electionguard_tpu.utils import enable_compile_cache, maybe_profile
    enable_compile_cache()

    from electionguard_tpu.ballot.plaintext import RandomBallotProvider
    from electionguard_tpu.core.group import production_group
    from electionguard_tpu.encrypt.encryptor import BatchEncryptor
    from electionguard_tpu.keyceremony.exchange import key_ceremony_exchange
    from electionguard_tpu.keyceremony.trustee import KeyCeremonyTrustee
    from electionguard_tpu.publish.election_record import (ElectionConfig,
                                                           ElectionRecord)
    from electionguard_tpu.tally.accumulate import accumulate_ballots
    from electionguard_tpu.verify.verifier import Verifier
    from electionguard_tpu.workflow.e2e import sample_manifest

    import jax
    n_chips = max(1, len(jax.devices()))

    g = production_group()
    manifest = sample_manifest(ncontests=1, nselections=2)
    trustees = [KeyCeremonyTrustee(g, "guardian-0", 1, 1)]
    init = key_ceremony_exchange(trustees, g).make_election_initialized(
        ElectionConfig(manifest, 1, 1), {"created_by": "bench"})

    ballots = list(RandomBallotProvider(manifest, nballots, seed=1).ballots())
    enc = BatchEncryptor(init, g)
    t0 = time.time()
    encrypted, invalid = enc.encrypt_ballots(ballots, seed=g.int_to_q(42))
    t_encrypt = time.time() - t0
    assert not invalid and len(encrypted) == nballots
    tally_result = accumulate_ballots(init, encrypted)

    record = ElectionRecord(election_init=init, encrypted_ballots=encrypted,
                            tally_result=tally_result)

    t_setup = time.time() - t_setup  # election build + encrypt + tally

    # warmup pass compiles every kernel at the measured shapes
    res = Verifier(record, g).verify()
    assert res.ok, res.summary()
    t0 = time.time()
    with maybe_profile("bench-verify"):
        res = Verifier(record, g).verify()
    t_verify = time.time() - t0
    assert res.ok, res.summary()

    ballots_per_sec_per_chip = nballots / t_verify / n_chips
    target = 1_000_000 / 60.0 / 8  # 1M ballots / 60 s / v5e-8
    print(json.dumps({
        "metric": "ballots_verified_tallied_per_sec_per_chip",
        "value": round(ballots_per_sec_per_chip, 3),
        "unit": "ballots/s/chip",
        "vs_baseline": round(ballots_per_sec_per_chip / target, 5),
    }))
    print(f"# nballots={nballots} chips={n_chips} "
          f"encrypt={t_encrypt:.2f}s ({nballots / t_encrypt:.1f}/s) "
          f"verify={t_verify:.2f}s setup={t_setup:.1f}s "
          f"platform={jax.devices()[0].platform}", file=sys.stderr)
    try:
        _microbench(g, nballots)
    except Exception as e:                   # noqa: BLE001 — diagnostics
        print(f"# microbench skipped: {type(e).__name__}: {e}",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())

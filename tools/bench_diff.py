"""Noise-aware perf-regression gate over bench.py artifacts.

Compares a fresh bench result against a pinned baseline, metric by
metric, each with a direction (higher- or lower-is-better) and a
relative tolerance band sized to that metric's observed run-to-run
noise.  A run is a REGRESSION only when a metric is *worse* than the
baseline by more than its band — improvements never fail, and metrics
missing from either side are reported but don't gate (bench phases are
individually skippable).

Accepted artifact shapes (both ``--baseline`` and ``--run``):

* a raw ``bench.py`` RESULT json (the last stdout line of a run);
* a ``BENCH_r*.json`` wrapper (``{"parsed": {...}}``);
* the repo ``BASELINE.json`` (its latest ``published`` entry; when
  none has been published yet, the gate seeds itself from the highest
  ``BENCH_r*.json`` sitting next to it);
* a ``PROGRESS.jsonl`` trajectory (the last ``"kind": "bench"`` row).

Usage::

    python bench.py > /tmp/bench.json   # RESULT json is the last line
    python tools/bench_diff.py --baseline BASELINE.json --run /tmp/bench.json
    python tools/bench_diff.py --run /tmp/bench.json --tolerance value=0.25
    python tools/bench_diff.py --run /tmp/bench.json --json verdict.json

Exit codes: 0 pass, 1 regression, 2 artifact load error.  The default
``--baseline`` is the ``EGTPU_BENCH_BASELINE`` knob, falling back to
the repo's ``BASELINE.json``.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: metric -> (higher_is_better, relative tolerance).  Bands reflect the
#: observed run-to-run noise of each bench phase on a warm compile
#: cache; the headline ballots/s gets the tightest band.
METRICS: dict[str, tuple[bool, float]] = {
    "value": (True, 0.10),               # ballots/s/chip (headline)
    "encrypt_per_s": (True, 0.15),
    "tally_s": (False, 0.20),
    "verify_s": (False, 0.20),
    "verify_batch_per_s": (True, 0.20),  # RLC/MSM verify (ballots/s/chip)
    "mixnet_rows_per_s": (True, 0.20),
    "mixfed_stages_per_s": (True, 0.20),
    "live_chunks_per_s": (True, 0.20),   # streaming verifier tail rate
    "validate_rlc_per_s": (True, 0.20),  # ingestion-gate subgroup screen
    "obs_spans_per_s": (True, 0.25),
    "setup_s": (False, 0.50),            # dominated by compile cache
    # capacity-model prediction error vs measured configs: lower is
    # better; the wide band tolerates timing noise in the sub-second
    # calibration elections while still catching a model whose error
    # doubles (drift in the cost structure it was fitted on)
    "capacity_model_err_pct": (False, 1.0),
    # process-model sim layer: simulated ballots played out per real
    # second for the reduced-event-rate million-ballot election; wide
    # band — the run is scheduler-bound and shares the box with jit
    "sim_ballots_per_s": (True, 0.25),
    # aggregate ballots/s with 4 overlapping elections on one worker
    # pool (the multitenant phase's headline): a shrink here means the
    # shared-program fabric started paying a per-tenant tax (recompiles,
    # lane contention) that consolidation was supposed to eliminate
    "tenant_aggregate_ballots_per_s": (True, 0.20),
}
#: per-backend powmod rates live in a dict metric
_POWMOD_TOL = (True, 0.15)
#: fabric_<N>w_ballots_per_s keys are dynamic in worker count
_FABRIC_RE = re.compile(r"^fabric_\d+w_ballots_per_s$")
_FABRIC_TOL = (True, 0.20)


def _metric_spec(key: str) -> tuple[bool, float] | None:
    if key in METRICS:
        return METRICS[key]
    if _FABRIC_RE.match(key):
        return _FABRIC_TOL
    return None


def _seed_from_bench_files(near: str) -> dict | None:
    """Highest-numbered BENCH_r*.json beside ``near``, parsed."""
    rounds = []
    for p in glob.glob(os.path.join(os.path.dirname(near) or ".",
                                    "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        if m:
            rounds.append((int(m.group(1)), p))
    for _, p in sorted(rounds, reverse=True):
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = doc.get("parsed")
        if isinstance(parsed, dict) and "value" in parsed:
            return parsed
    return None


def load_artifact(path: str) -> tuple[dict, str]:
    """Load one artifact into a flat metric dict; returns
    ``(metrics, provenance)``.  Raises ValueError when nothing usable
    is found."""
    if path.endswith(".jsonl"):
        last = None
        with open(path, errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and rec.get("kind") == "bench":
                    last = rec
        if last is None:
            raise ValueError(f"{path}: no bench rows")
        flat = dict(last)
        if "ballots_per_s_per_chip" in flat:
            flat.setdefault("value", flat["ballots_per_s_per_chip"])
        return flat, f"{path} (last bench row)"
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: not a json object")
    if isinstance(doc.get("parsed"), dict):       # BENCH_r*.json wrapper
        return doc["parsed"], f"{path} (parsed)"
    if "published" in doc and "value" not in doc:  # BASELINE.json
        pub = doc["published"]
        entries = list(pub.values()) if isinstance(pub, dict) else \
            list(pub or [])
        entries = [e for e in entries
                   if isinstance(e, dict) and "value" in e]
        if entries:
            return entries[-1], f"{path} (published)"
        seeded = _seed_from_bench_files(path)
        if seeded is not None:
            return seeded, f"{path} (seeded from highest BENCH_r*.json)"
        raise ValueError(f"{path}: nothing published and no "
                         f"BENCH_r*.json to seed from")
    if "value" in doc:                             # raw RESULT json
        return doc, path
    raise ValueError(f"{path}: unrecognized bench artifact shape")


def compare(baseline: dict, run: dict,
            overrides: dict[str, float] | None = None) -> dict:
    """Per-metric comparison; returns the machine-readable verdict."""
    overrides = overrides or {}
    rows: list[dict] = []

    def one(key: str, base_v, run_v, higher: bool, tol: float) -> None:
        tol = overrides.get(key, tol)
        if base_v is None or run_v is None:
            rows.append({"metric": key, "status": "missing",
                         "baseline": base_v, "run": run_v})
            return
        try:
            base_v, run_v = float(base_v), float(run_v)
        except (TypeError, ValueError):
            rows.append({"metric": key, "status": "missing",
                         "baseline": base_v, "run": run_v})
            return
        if base_v == 0:
            rows.append({"metric": key, "status": "skipped",
                         "baseline": base_v, "run": run_v})
            return
        delta = (run_v - base_v) / abs(base_v)
        worse = -delta if higher else delta
        status = "regression" if worse > tol else \
            ("improved" if worse < -tol else "ok")
        rows.append({"metric": key, "status": status,
                     "baseline": base_v, "run": run_v,
                     "delta_rel": round(delta, 4), "tolerance": tol,
                     "higher_is_better": higher})

    keys = set(baseline) | set(run)
    for key in sorted(keys):
        spec = _metric_spec(key)
        if spec is not None:
            one(key, baseline.get(key), run.get(key), *spec)
    bp, rp = baseline.get("powmod_per_s"), run.get("powmod_per_s")
    if isinstance(bp, dict) and isinstance(rp, dict):
        for backend in sorted(set(bp) | set(rp)):
            one(f"powmod_per_s.{backend}", bp.get(backend),
                rp.get(backend), *_POWMOD_TOL)

    regressions = [r for r in rows if r["status"] == "regression"]
    verdict = {
        "pass": not regressions,
        "n_compared": sum(1 for r in rows
                          if r["status"] in ("ok", "improved",
                                             "regression")),
        "regressions": [r["metric"] for r in regressions],
        "platform_match": baseline.get("platform") == run.get("platform"),
        "baseline_platform": baseline.get("platform"),
        "run_platform": run.get("platform"),
        "metrics": rows,
    }
    return verdict


def _parse_tolerances(items: list[str]) -> dict[str, float]:
    out: dict[str, float] = {}
    for item in items:
        if "=" not in item:
            raise ValueError(f"--tolerance wants metric=rel, got {item!r}")
        k, v = item.split("=", 1)
        out[k] = float(v)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("bench_diff")
    ap.add_argument("--baseline", default=None,
                    help="baseline artifact (default: EGTPU_BENCH_"
                         "BASELINE knob, else the repo BASELINE.json)")
    ap.add_argument("--run", required=True,
                    help="fresh bench artifact to gate")
    ap.add_argument("--tolerance", action="append", default=[],
                    metavar="METRIC=REL",
                    help="override one metric's relative band, "
                         "e.g. value=0.25 (repeatable)")
    ap.add_argument("--json", nargs="?", const="-", default=None,
                    metavar="PATH",
                    help="write the machine-readable verdict "
                         "(- or no value = stdout)")
    args = ap.parse_args(argv)

    from electionguard_tpu.utils import knobs

    baseline_path = args.baseline or \
        knobs.get_str("EGTPU_BENCH_BASELINE") or \
        os.path.join(_REPO, "BASELINE.json")
    try:
        overrides = _parse_tolerances(args.tolerance)
        baseline, base_src = load_artifact(baseline_path)
        run, run_src = load_artifact(args.run)
    except (OSError, ValueError) as e:
        print(f"bench_diff: {e}", file=sys.stderr)
        return 2

    verdict = compare(baseline, run, overrides)
    verdict["baseline_source"] = base_src
    verdict["run_source"] = run_src

    if args.json is not None:
        text = json.dumps(verdict, indent=2, sort_keys=True)
        if args.json == "-":
            print(text)
        else:
            with open(args.json, "w") as f:
                f.write(text + "\n")
    if not verdict["platform_match"]:
        print(f"bench_diff: WARNING platform mismatch "
              f"(baseline {verdict['baseline_platform']}, "
              f"run {verdict['run_platform']}): bands assume same "
              f"hardware", file=sys.stderr)
    for r in verdict["metrics"]:
        if r["status"] in ("ok", "improved", "regression"):
            arrow = {"ok": "=", "improved": "+", "regression": "!"}
            print(f"  [{arrow[r['status']]}] {r['metric']}: "
                  f"{r['baseline']} -> {r['run']} "
                  f"({r['delta_rel'] * 100:+.1f}%, "
                  f"band {r['tolerance'] * 100:.0f}%)")
    if verdict["pass"]:
        print(f"bench_diff: PASS ({verdict['n_compared']} metric(s) "
              f"compared, baseline: {base_src})")
        return 0
    print(f"bench_diff: REGRESSION in "
          f"{', '.join(verdict['regressions'])} "
          f"(baseline: {base_src})", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())

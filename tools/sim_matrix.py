#!/usr/bin/env python3
"""sim_matrix — the deterministic-simulation seed sweep runner.

Usage::

    python tools/sim_matrix.py --seeds 20            # quick sweep
    python tools/sim_matrix.py --seeds 1000 --json   # + SIM_RESULTS.json
    python tools/sim_matrix.py --seeds 1000 --procs 8
    python tools/sim_matrix.py --adversaries --json  # Byzantine sweep
    python tools/sim_matrix.py --replay '<schedule json>' --seed 17

Each seed is one full virtual-cluster run (key ceremony → encryption
serving → federated mix → compensated decryption → independent
verification) under a seed-derived fault schedule, checked by every
oracle.  Failing seeds are shrunk to minimal replayable schedules and
recorded — ``--json`` writes the tracked SIM_RESULTS.json artifact with
the seeds run, oracle failures, shrunk repros, and honest throughput.

``--adversaries`` runs the attack × fault matrix instead: every seed
additionally draws 1-2 named in-protocol attacks from the
``sim/adversary.py`` corpus (stream 5, composed with the same crash /
network fault schedules), the soundness oracle requires each fired
attack to be detected in-band or by the verifier, and the artifact
(default SIM_BYZ_RESULTS.json) records the per-attack fired/detected
histogram with the detection classes seen.  A green sweep is the
repo's zero-green-undetected claim.

``--procs N`` shards the seed range over N worker subprocesses.
Workers share the persistent JAX compilation cache, so only the first
sweep on a machine pays the compile warmup; within a worker, seeds
share the process-wide jitted program set and tiny-group tables, and
the host-pad dispatch trim (EGTPU_DISPATCH_HOST_PAD,
core/group_jax.run_tiled) removes the per-call eager padding tax that
used to bound steady-state seeds/s.  The artifact records the honest
split: ``warmup_s`` (first seed, dispatch/deserialize-bound),
``steady_seeds_per_s`` (everything after), and ``dispatch_trim`` — a
same-process calibration of seeds/s with the trim off vs on.

Trace hashes are deterministic per process; to compare them across
processes or machines, pin PYTHONHASHSEED.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
from dataclasses import asdict

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

# the sweep re-jits the same programs every process: the persistent
# compilation cache turns the per-process warmup from ~60s into ~15s
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(tempfile.gettempdir(), "egtpu-jax-cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.1")
# the watchdog bounds REAL time between yields; on an oversubscribed
# sweep box (N workers per core) an honest CPU-starved task can blow
# the 60s default — the virtual-time liveness horizon still catches
# true wedges (race_matrix.py raises it the same way)
os.environ.setdefault("EGTPU_SIM_WATCHDOG_S", "300")


def _config(fast: bool):
    from electionguard_tpu.sim.cluster import SimConfig
    return SimConfig(n_mix_stages=1) if fast else SimConfig()


def _sweep(start: int, count: int, fast: bool,
           shrink_budget: int | None, adversaries: bool = False,
           live: bool = False, param: bool = False) -> dict:
    """Run seeds [start, start+count) in THIS process; shrink failures."""
    from electionguard_tpu.sim import adversary
    from electionguard_tpu.sim.explore import run_sim
    from electionguard_tpu.sim.shrink import shrink

    cfg = _config(fast)
    plant = ("live-verify",) if live else ()
    ok = 0
    failures = []
    attacks: dict[str, dict] = {}
    fired_total = 0
    live_stats = {"runs": 0, "converged": 0, "crashes": 0, "torn": 0,
                  "chunks": 0, "rejected_chunks": 0}
    warmup_s = 0.0
    t_loop = time.time()
    for seed in range(start, start + count):
        r = run_sim(seed, config=cfg, adversaries=adversaries,
                    plant=plant, param_adversaries=param)
        if seed == start:
            # first seed pays the per-process jit dispatch/deserialize
            # warmup; the rest run against warm program + table caches
            warmup_s = time.time() - t_loop
        if r.live:
            live_stats["runs"] += 1
            live_stats["converged"] += bool(r.live["converged"])
            live_stats["crashes"] += r.live["crashes"]
            live_stats["torn"] += r.live["torn"]
            live_stats["chunks"] += len(r.live["live_accepts"])
            live_stats["rejected_chunks"] += sum(
                not a for a in r.live["live_accepts"])
        if adversaries or param:
            # per-attack detection histogram: an instance counts as
            # detected exactly when the soundness oracle raised no
            # violation for it (the oracle also sees abort texts and
            # verifier reds that the reject log alone misses)
            sound = [v for v in r.violations if v.startswith("soundness")]
            seen = {cls for cls, _detail in r.detections}
            for name, _method, _n, _node in r.fired:
                fired_total += 1
                a = attacks.setdefault(
                    name, {"fired": 0, "detected": 0, "via": {}})
                a["fired"] += 1
                if not any(f"attack {name} fired" in v for v in sound):
                    a["detected"] += 1
                for cls in sorted(adversary.expected_for(name) & seen):
                    a["via"][cls] = a["via"].get(cls, 0) + 1
        if r.ok:
            ok += 1
            continue
        entry = {
            "seed": seed,
            "violations": r.violations,
            "schedule": [asdict(e) for e in r.schedule],
            "trace_hash": r.trace_hash,
        }
        if r.schedule:
            res = shrink(seed, r.schedule, config=cfg, plant=plant,
                         budget=shrink_budget)
            entry["shrunk_schedule"] = [asdict(e) for e in res.schedule]
            entry["shrunk_violations"] = res.violations
            entry["shrink_runs"] = res.runs
            entry["shrink_exhausted"] = res.exhausted
        failures.append(entry)
        print(f"FAIL {r.summary()}", file=sys.stderr)
    return {"ok": ok, "failures": failures, "attacks": attacks,
            "fired_total": fired_total, "live": live_stats,
            "warmup_s": round(warmup_s, 3),
            "steady_s": round(time.time() - t_loop - warmup_s, 3),
            "steady_seeds": max(count - 1, 0)}


def _sweep_procs(start: int, count: int, procs: int, fast: bool,
                 shrink_budget: int | None,
                 adversaries: bool = False, live: bool = False,
                 param: bool = False) -> dict:
    """Shard the range over worker subprocesses, merge their chunks."""
    per = (count + procs - 1) // procs
    jobs = []
    tmpdir = tempfile.mkdtemp(prefix="egtpu-sim-matrix-")
    for i in range(procs):
        s = start + i * per
        n = min(per, start + count - s)
        if n <= 0:
            break
        out = os.path.join(tmpdir, f"chunk-{i}.json")
        cmd = [sys.executable, os.path.abspath(__file__),
               "--start", str(s), "--seeds", str(n),
               "--chunk-worker", out]
        if fast:
            cmd.append("--fast")
        if adversaries:
            cmd.append("--adversaries")
        if live:
            cmd.append("--live")
        if param:
            cmd.append("--param-adversaries")
        if shrink_budget is not None:
            cmd += ["--shrink-budget", str(shrink_budget)]
        jobs.append((subprocess.Popen(cmd), out))
    merged = {"ok": 0, "failures": [], "attacks": {}, "fired_total": 0,
              "live": {"runs": 0, "converged": 0, "crashes": 0,
                       "torn": 0, "chunks": 0, "rejected_chunks": 0},
              "warmup_s": 0.0, "steady_s": 0.0, "steady_seeds": 0}
    rc = 0
    for proc, out in jobs:
        rc |= proc.wait()
        if os.path.exists(out):
            chunk = json.load(open(out))
            merged["ok"] += chunk["ok"]
            merged["failures"].extend(chunk["failures"])
            merged["fired_total"] += chunk.get("fired_total", 0)
            # workers run concurrently: warmup/steady wall is the
            # slowest worker's, steady seed count sums across them
            merged["warmup_s"] = max(merged["warmup_s"],
                                     chunk.get("warmup_s", 0.0))
            merged["steady_s"] = max(merged["steady_s"],
                                     chunk.get("steady_s", 0.0))
            merged["steady_seeds"] += chunk.get("steady_seeds", 0)
            for k, n_k in chunk.get("live", {}).items():
                merged["live"][k] += n_k
            for name, a in chunk.get("attacks", {}).items():
                m = merged["attacks"].setdefault(
                    name, {"fired": 0, "detected": 0, "via": {}})
                m["fired"] += a["fired"]
                m["detected"] += a["detected"]
                for cls, n_cls in a["via"].items():
                    m["via"][cls] = m["via"].get(cls, 0) + n_cls
    if rc:
        raise SystemExit(f"a sweep worker failed (exit {rc})")
    merged["failures"].sort(key=lambda f: f["seed"])
    return merged


def _dispatch_calibration(fast: bool, seeds: int = 8) -> dict:
    """Honest before/after of the host-pad dispatch trim: run the same
    seeds in THIS warm process with EGTPU_DISPATCH_HOST_PAD off then on
    (seeds 999_984.., disjoint from any sweep range), so the only
    variable is the eager-padding tax the trim removes."""
    from electionguard_tpu.sim.explore import run_sim

    cfg = _config(fast)
    run_sim(999_983, config=cfg)      # warm programs outside both timings
    out: dict = {"seeds": seeds}
    prev = os.environ.get("EGTPU_DISPATCH_HOST_PAD")
    try:
        for label, flag in (("before", "0"), ("after", "1")):
            os.environ["EGTPU_DISPATCH_HOST_PAD"] = flag
            t0 = time.time()
            for s in range(999_984, 999_984 + seeds):
                run_sim(s, config=cfg)
            dt = time.time() - t0
            out[f"{label}_seeds_per_s"] = round(seeds / dt, 2) if dt else None
    finally:
        if prev is None:
            os.environ.pop("EGTPU_DISPATCH_HOST_PAD", None)
        else:
            os.environ["EGTPU_DISPATCH_HOST_PAD"] = prev
    if out.get("before_seeds_per_s") and out.get("after_seeds_per_s"):
        out["speedup"] = round(
            out["after_seeds_per_s"] / out["before_seeds_per_s"], 2)
    return out


def _replay(seed: int, schedule_json: str, fast: bool) -> int:
    from electionguard_tpu.sim.explore import run_sim
    from electionguard_tpu.sim.schedule import from_json
    r = run_sim(seed, schedule=from_json(schedule_json),
                config=_config(fast))
    print(r.summary())
    print(f"trace_hash={r.trace_hash}")
    return 0 if r.ok else 1


def main(argv=None) -> int:
    from electionguard_tpu.utils import knobs

    ap = argparse.ArgumentParser(
        prog="sim_matrix", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--seeds", type=int, default=None,
                    help="how many seeds to sweep (default "
                         "EGTPU_SIM_SEEDS, or EGTPU_SIM_ADV_SEEDS "
                         "under --adversaries)")
    ap.add_argument("--start", type=int,
                    default=knobs.get_int("EGTPU_SIM_SEED"),
                    help="first seed")
    ap.add_argument("--procs", type=int, default=1,
                    help="worker subprocesses to shard the range over")
    ap.add_argument("--fast", action="store_true",
                    help="1 mix stage instead of 2 (faster, less "
                         "cascade coverage)")
    ap.add_argument("--adversaries", action="store_true",
                    help="Byzantine sweep: compose each seed's fault "
                         "schedule with drawn in-protocol attacks and "
                         "check the soundness oracle")
    ap.add_argument("--param-adversaries", action="store_true",
                    help="parameter-level sweep: every seed draws 1-2 "
                         "forged-group-element attacks (param_* family: "
                         "non-subgroup keys, small-order ciphertexts, "
                         "identity shares, non-canonical wire values) "
                         "from their own seed stream; the soundness "
                         "oracle requires the ingestion gate to reject "
                         "each at its boundary with the named "
                         "[validate.*] class (composes with "
                         "--adversaries and --live)")
    ap.add_argument("--live", action="store_true",
                    help="live-verification sweep: every seed replays "
                         "its finished record through the incremental "
                         "verifier (verify/live) under seed-derived "
                         "torn tails + SIGKILL/checkpoint resumes; the "
                         "live_convergence oracle requires the verdict, "
                         "chunk-accept set, and commitment root to be "
                         "bit-identical to the terminal fold (composes "
                         "with --adversaries)")
    ap.add_argument("--shrink-budget", type=int, default=None,
                    help="probe-run cap per failing-schedule shrink")
    ap.add_argument("--json", nargs="?", const="auto", default=None,
                    metavar="PATH",
                    help="write the sweep artifact (default "
                         "SIM_RESULTS.json at the repo root, "
                         "SIM_BYZ_RESULTS.json under --adversaries)")
    ap.add_argument("--replay", metavar="SCHEDULE_JSON", default=None,
                    help="replay one schedule under --start's seed "
                         "instead of sweeping")
    ap.add_argument("--chunk-worker", metavar="PATH", default=None,
                    help=argparse.SUPPRESS)   # internal: emit one chunk
    args = ap.parse_args(argv)
    if args.seeds is None:
        args.seeds = knobs.get_int(
            "EGTPU_SIM_PARAM_SEEDS" if args.param_adversaries
            else "EGTPU_SIM_ADV_SEEDS" if args.adversaries
            else "EGTPU_SIM_SEEDS")
    if args.json == "auto":
        args.json = os.path.join(
            REPO_ROOT, "SIM_PARAM_RESULTS.json" if args.param_adversaries
            else "SIM_LIVE_RESULTS.json" if args.live
            else "SIM_BYZ_RESULTS.json" if args.adversaries
            else "SIM_RESULTS.json")

    if args.replay is not None:
        return _replay(args.start, args.replay, args.fast)

    t0 = time.time()
    if args.chunk_worker:
        chunk = _sweep(args.start, args.seeds, args.fast,
                       args.shrink_budget, args.adversaries, args.live,
                       args.param_adversaries)
        with open(args.chunk_worker, "w") as f:
            json.dump(chunk, f)
        return 0
    if args.procs > 1:
        merged = _sweep_procs(args.start, args.seeds, args.procs,
                              args.fast, args.shrink_budget,
                              args.adversaries, args.live,
                              args.param_adversaries)
    else:
        merged = _sweep(args.start, args.seeds, args.fast,
                        args.shrink_budget, args.adversaries, args.live,
                        args.param_adversaries)
    wall = time.time() - t0
    trim = _dispatch_calibration(args.fast)

    steady = (round(merged["steady_seeds"] / merged["steady_s"], 2)
              if merged.get("steady_s") else None)
    result = {
        "generated_by": "tools/sim_matrix.py",
        "seed_start": args.start,
        "n_seeds": args.seeds,
        "profile": "fast" if args.fast else "default",
        "procs": args.procs,
        "ok": merged["ok"],
        "failed": len(merged["failures"]),
        "failures": merged["failures"],
        "wall_s": round(wall, 1),
        "schedules_per_s": round(args.seeds / wall, 2) if wall else None,
        "warmup_s": merged.get("warmup_s"),
        "steady_seeds_per_s": steady,
        "dispatch_trim": trim,
    }
    print(f"{merged['ok']}/{args.seeds} seeds green, "
          f"{len(merged['failures'])} failures, {wall:.1f}s "
          f"({result['schedules_per_s']} schedules/s; "
          f"{steady} steady after {merged.get('warmup_s')}s warmup)")
    print(f"  dispatch trim: {trim.get('before_seeds_per_s')} -> "
          f"{trim.get('after_seeds_per_s')} seeds/s "
          f"(x{trim.get('speedup')}, host-pad off vs on, "
          f"{trim['seeds']} calibration seeds)")
    if args.live:
        ls = merged["live"]
        result.update({"mode": ("live+adversaries" if args.adversaries
                                else "live"), "live": ls})
        print(f"  live: {ls['converged']}/{ls['runs']} runs converged "
              f"bit-identically through {ls['crashes']} crash-resumes "
              f"and {ls['torn']} torn tails ({ls['chunks']} chunks, "
              f"{ls['rejected_chunks']} rejected)")
    if args.adversaries or args.param_adversaries:
        undetected = sum(a["fired"] - a["detected"]
                         for a in merged["attacks"].values())
        mode = "+".join(m for m, on in (
            ("live", args.live), ("adversaries", args.adversaries),
            ("param-adversaries", args.param_adversaries)) if on)
        result.update({
            "mode": mode,
            "attacks": merged["attacks"],
            "fired_total": merged["fired_total"],
            "undetected_total": undetected,
            "attacks_per_s": (round(merged["fired_total"] / wall, 2)
                              if wall else None),
        })
        for name in sorted(merged["attacks"]):
            a = merged["attacks"][name]
            via = ", ".join(f"{c}x{n}" for c, n in sorted(a["via"].items()))
            print(f"  {name}: fired {a['fired']}, detected "
                  f"{a['detected']} ({via or 'abort/verifier only'})")
        print(f"  {merged['fired_total']} attacks fired, "
              f"{undetected} green-undetected")
    for f in merged["failures"]:
        shrunk = f.get("shrunk_schedule")
        print(f"  seed {f['seed']}: {f['violations'][0]}"
              + (f"  [shrunk to {len(shrunk)} events]" if shrunk else ""))
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(result, fh, indent=2, sort_keys=True)
            fh.write("\n")
        print(f"wrote {os.path.relpath(args.json)}")
    return 1 if merged["failures"] else 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python3
"""sim_matrix — the deterministic-simulation seed sweep runner.

Usage::

    python tools/sim_matrix.py --seeds 20            # quick sweep
    python tools/sim_matrix.py --seeds 1000 --json   # + SIM_RESULTS.json
    python tools/sim_matrix.py --seeds 1000 --procs 8
    python tools/sim_matrix.py --replay '<schedule json>' --seed 17

Each seed is one full virtual-cluster run (key ceremony → encryption
serving → federated mix → compensated decryption → independent
verification) under a seed-derived fault schedule, checked by every
oracle.  Failing seeds are shrunk to minimal replayable schedules and
recorded — ``--json`` writes the tracked SIM_RESULTS.json artifact with
the seeds run, oracle failures, shrunk repros, and honest throughput.

``--procs N`` shards the seed range over N worker subprocesses (the
per-seed cost is JAX dispatch-bound, so sweep throughput scales with
cores).  Workers share the persistent JAX compilation cache, so only
the first sweep on a machine pays the compile warmup.

Trace hashes are deterministic per process; to compare them across
processes or machines, pin PYTHONHASHSEED.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
from dataclasses import asdict

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

# the sweep re-jits the same programs every process: the persistent
# compilation cache turns the per-process warmup from ~60s into ~15s
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(tempfile.gettempdir(), "egtpu-jax-cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.1")


def _config(fast: bool):
    from electionguard_tpu.sim.cluster import SimConfig
    return SimConfig(n_mix_stages=1) if fast else SimConfig()


def _sweep(start: int, count: int, fast: bool,
           shrink_budget: int | None) -> dict:
    """Run seeds [start, start+count) in THIS process; shrink failures."""
    from electionguard_tpu.sim.explore import run_sim
    from electionguard_tpu.sim.shrink import shrink

    cfg = _config(fast)
    ok = 0
    failures = []
    for seed in range(start, start + count):
        r = run_sim(seed, config=cfg)
        if r.ok:
            ok += 1
            continue
        entry = {
            "seed": seed,
            "violations": r.violations,
            "schedule": [asdict(e) for e in r.schedule],
            "trace_hash": r.trace_hash,
        }
        if r.schedule:
            res = shrink(seed, r.schedule, config=cfg,
                         budget=shrink_budget)
            entry["shrunk_schedule"] = [asdict(e) for e in res.schedule]
            entry["shrunk_violations"] = res.violations
            entry["shrink_runs"] = res.runs
            entry["shrink_exhausted"] = res.exhausted
        failures.append(entry)
        print(f"FAIL {r.summary()}", file=sys.stderr)
    return {"ok": ok, "failures": failures}


def _sweep_procs(start: int, count: int, procs: int, fast: bool,
                 shrink_budget: int | None) -> dict:
    """Shard the range over worker subprocesses, merge their chunks."""
    per = (count + procs - 1) // procs
    jobs = []
    tmpdir = tempfile.mkdtemp(prefix="egtpu-sim-matrix-")
    for i in range(procs):
        s = start + i * per
        n = min(per, start + count - s)
        if n <= 0:
            break
        out = os.path.join(tmpdir, f"chunk-{i}.json")
        cmd = [sys.executable, os.path.abspath(__file__),
               "--start", str(s), "--seeds", str(n),
               "--chunk-worker", out]
        if fast:
            cmd.append("--fast")
        if shrink_budget is not None:
            cmd += ["--shrink-budget", str(shrink_budget)]
        jobs.append((subprocess.Popen(cmd), out))
    merged = {"ok": 0, "failures": []}
    rc = 0
    for proc, out in jobs:
        rc |= proc.wait()
        if os.path.exists(out):
            chunk = json.load(open(out))
            merged["ok"] += chunk["ok"]
            merged["failures"].extend(chunk["failures"])
    if rc:
        raise SystemExit(f"a sweep worker failed (exit {rc})")
    merged["failures"].sort(key=lambda f: f["seed"])
    return merged


def _replay(seed: int, schedule_json: str, fast: bool) -> int:
    from electionguard_tpu.sim.explore import run_sim
    from electionguard_tpu.sim.schedule import from_json
    r = run_sim(seed, schedule=from_json(schedule_json),
                config=_config(fast))
    print(r.summary())
    print(f"trace_hash={r.trace_hash}")
    return 0 if r.ok else 1


def main(argv=None) -> int:
    from electionguard_tpu.utils import knobs

    ap = argparse.ArgumentParser(
        prog="sim_matrix", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--seeds", type=int,
                    default=knobs.get_int("EGTPU_SIM_SEEDS"),
                    help="how many seeds to sweep")
    ap.add_argument("--start", type=int,
                    default=knobs.get_int("EGTPU_SIM_SEED"),
                    help="first seed")
    ap.add_argument("--procs", type=int, default=1,
                    help="worker subprocesses to shard the range over")
    ap.add_argument("--fast", action="store_true",
                    help="1 mix stage instead of 2 (faster, less "
                         "cascade coverage)")
    ap.add_argument("--shrink-budget", type=int, default=None,
                    help="probe-run cap per failing-schedule shrink")
    ap.add_argument("--json", nargs="?", const=os.path.join(
                        REPO_ROOT, "SIM_RESULTS.json"), default=None,
                    metavar="PATH",
                    help="write the sweep artifact (default "
                         "SIM_RESULTS.json at the repo root)")
    ap.add_argument("--replay", metavar="SCHEDULE_JSON", default=None,
                    help="replay one schedule under --start's seed "
                         "instead of sweeping")
    ap.add_argument("--chunk-worker", metavar="PATH", default=None,
                    help=argparse.SUPPRESS)   # internal: emit one chunk
    args = ap.parse_args(argv)

    if args.replay is not None:
        return _replay(args.start, args.replay, args.fast)

    t0 = time.time()
    if args.chunk_worker:
        chunk = _sweep(args.start, args.seeds, args.fast,
                       args.shrink_budget)
        with open(args.chunk_worker, "w") as f:
            json.dump(chunk, f)
        return 0
    if args.procs > 1:
        merged = _sweep_procs(args.start, args.seeds, args.procs,
                              args.fast, args.shrink_budget)
    else:
        merged = _sweep(args.start, args.seeds, args.fast,
                        args.shrink_budget)
    wall = time.time() - t0

    result = {
        "generated_by": "tools/sim_matrix.py",
        "seed_start": args.start,
        "n_seeds": args.seeds,
        "profile": "fast" if args.fast else "default",
        "procs": args.procs,
        "ok": merged["ok"],
        "failed": len(merged["failures"]),
        "failures": merged["failures"],
        "wall_s": round(wall, 1),
        "schedules_per_s": round(args.seeds / wall, 2) if wall else None,
    }
    print(f"{merged['ok']}/{args.seeds} seeds green, "
          f"{len(merged['failures'])} failures, {wall:.1f}s "
          f"({result['schedules_per_s']} schedules/s)")
    for f in merged["failures"]:
        shrunk = f.get("shrunk_schedule")
        print(f"  seed {f['seed']}: {f['violations'][0]}"
              + (f"  [shrunk to {len(shrunk)} events]" if shrunk else ""))
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(result, fh, indent=2, sort_keys=True)
            fh.write("\n")
        print(f"wrote {os.path.relpath(args.json)}")
    return 1 if merged["failures"] else 0


if __name__ == "__main__":
    sys.exit(main())

"""Merge the per-process span files of a traced run into one timeline.

Every process of a run (workflow driver, coordinators, guardians, the
encryption service, loadgen) exports ``spans-<proc>-<pid>.jsonl`` into
the shared ``EGTPU_OBS_TRACE`` dir; this tool merges them into a single
Chrome-trace JSON that Perfetto (https://ui.perfetto.dev) or
``chrome://tracing`` opens directly, and prints a validation report
(span/process counts, trace ids, orphan parents, envelope gaps, rpc
client/server pairing).

In-flight spans — records with ``"open": true`` and no ``dur``, streamed
by the obs collector for work still running (the live process roots,
an unfinished phase) — are tolerated: they are reported under
``open_spans`` instead of failing ``-strict``, so the tool also works
mid-run (and on died runs) against a collector's receive dir::

    python tools/assemble_trace.py -dir /tmp/eg/obs/recv -strict

Usage::

    python tools/assemble_trace.py -dir /tmp/eg/trace [-out trace.json]
    python tools/assemble_trace.py -dir /tmp/eg/trace -strict   # CI gate
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("assemble_trace")
    ap.add_argument("-dir", dest="trace_dir", required=True,
                    help="span dir (the run's EGTPU_OBS_TRACE)")
    ap.add_argument("-out", dest="output", default=None,
                    help="merged Chrome-trace JSON path "
                         "(default <dir>/trace.json)")
    ap.add_argument("-strict", action="store_true",
                    help="exit 1 unless the trace is clean: one trace "
                         "id, no orphans, no envelope gaps (in-flight "
                         "open spans are reported, not failed)")
    args = ap.parse_args(argv)

    from electionguard_tpu.obs import assemble

    out = args.output or os.path.join(args.trace_dir, "trace.json")
    report = assemble.merge_dir(args.trace_dir, out)
    print(json.dumps(report, indent=2, sort_keys=True))
    if args.strict and (len(report["trace_ids"]) != 1
                        or report["orphans"] or report["gaps"]):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

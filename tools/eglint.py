#!/usr/bin/env python3
"""eglint — the repo's project-native static analyzer.

Usage::

    python tools/eglint.py                 # report findings, exit 0
    python tools/eglint.py -strict         # exit 1 on any live finding
    python tools/eglint.py --json          # also write ANALYSIS.json
    python tools/eglint.py --rule secret-taint --rule raw-channel
    python tools/eglint.py --write-knobs   # regenerate ENV_KNOBS.md
    python tools/eglint.py --write-guards  # regenerate ANALYSIS_GUARDS.json

Findings are suppressed either inline (``# eglint: disable=RULE`` on
the offending line) or via ``electionguard_tpu/analysis/baseline.json``
(every entry needs a ``note`` rationale; secret-taint and raw-channel
may never be baselined).  See README "Static analysis".
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from electionguard_tpu.analysis import core  # noqa: E402
from electionguard_tpu.utils import knobs  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="eglint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("-strict", "--strict", action="store_true",
                    help="exit nonzero on any unbaselined finding")
    ap.add_argument("--json", nargs="?", const=os.path.join(
                        REPO_ROOT, "ANALYSIS.json"), default=None,
                    metavar="PATH",
                    help="write the findings artifact (default "
                         "ANALYSIS.json at the repo root)")
    ap.add_argument("--rule", action="append", default=None,
                    metavar="PASS", help="run only this pass "
                    "(repeatable); default: all")
    ap.add_argument("--package", default=None, metavar="DIR",
                    help="package dir to scan (default: the installed "
                         "electionguard_tpu package)")
    ap.add_argument("--write-knobs", action="store_true",
                    help="regenerate ENV_KNOBS.md from utils/knobs.py "
                         "and exit")
    ap.add_argument("--write-guards", action="store_true",
                    help="regenerate ANALYSIS_GUARDS.json (the "
                         "lock-discipline guard sets that seed the "
                         "dynamic race monitor) and exit")
    args = ap.parse_args(argv)

    if args.write_knobs:
        out = os.path.join(REPO_ROOT, "ENV_KNOBS.md")
        with open(out, "w") as f:
            f.write(knobs.render_table())
        print(f"wrote {os.path.relpath(out)}")
        return 0

    if args.write_guards:
        from electionguard_tpu.analysis import lock_discipline
        project = core.Project(package_dir=args.package) if args.package \
            else core.Project()
        out = os.path.join(REPO_ROOT, "ANALYSIS_GUARDS.json")
        with open(out, "w") as f:
            f.write(lock_discipline.render_guards(project))
        print(f"wrote {os.path.relpath(out)}")
        return 0

    project = core.Project(package_dir=args.package) if args.package \
        else core.Project()
    report = core.run_passes(project, passes=args.rule)

    for f in report.findings:
        print(f)
    for f in report.baselined:
        print(f"{f}  [baselined]")
    for e in report.stale_baseline:
        print(f"{e['path']}:{e['line']}: [{e['rule']}] stale baseline "
              f"entry (finding no longer fires) — remove it")
    n_sup = sum(report.suppressed.values())
    print(f"eglint: {len(report.files_scanned)} files, "
          f"{len(report.passes_run)} passes, "
          f"{len(report.findings)} findings, "
          f"{len(report.baselined)} baselined, {n_sup} suppressed "
          f"inline")

    if args.json:
        with open(args.json, "w") as f:
            json.dump(report.to_json(), f, indent=2, sort_keys=False)
            f.write("\n")
        print(f"wrote {os.path.relpath(args.json)}")

    if args.strict and (report.findings or report.stale_baseline):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Scale evidence for BASELINE configs 3-5 (VERDICT r3 item 6).

Two phases, each optional:

  --stream N   : N-ballot (default 100k) fully-streamed run on the tiny
                 group — encrypt chunk-by-chunk to a framed on-disk record,
                 accumulate the tally from the stream, then verify from the
                 stream — with peak-RSS tracking proving O(chunk) host
                 residency end-to-end (the reference's analogue loads the
                 record in memory with an 11-thread pool,
                 RunRemoteWorkflowTest.java:140,180).
  --prod N     : N-ballot production-4096 encrypt+verify wall-clock on the
                 current platform, extrapolated to the 1M/60s north star.

Writes SCALE.json (machine-readable) and appends a row to SCALE.md.

Usage:  python tools/scale_run.py --stream 100000 --prod 2048
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# --mix-sharded needs the virtual 8-device CPU mesh; XLA reads this at
# first jax import, so it must land in the environment before ANY
# electionguard module pulls jax in (they all import lazily, in-function)
if any(a.startswith("--mix-sharded") for a in sys.argv):
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def rss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def stream_phase(nballots: int, chunk: int, workdir: str) -> dict:
    from electionguard_tpu.ballot.ciphertext import BallotState
    from electionguard_tpu.ballot.plaintext import RandomBallotProvider
    from electionguard_tpu.core.group import tiny_group
    from electionguard_tpu.encrypt.encryptor import BatchEncryptor
    from electionguard_tpu.keyceremony.exchange import key_ceremony_exchange
    from electionguard_tpu.keyceremony.trustee import KeyCeremonyTrustee
    from electionguard_tpu.publish.election_record import (ElectionConfig,
                                                           ElectionRecord)
    from electionguard_tpu.publish.publisher import Consumer, Publisher
    from electionguard_tpu.tally.accumulate import accumulate_ballots
    from electionguard_tpu.verify.verifier import Verifier
    from electionguard_tpu.workflow.e2e import sample_manifest

    g = tiny_group()
    manifest = sample_manifest(1, 2)
    trustees = [KeyCeremonyTrustee(g, "g0", 1, 1)]
    init = key_ceremony_exchange(trustees, g).make_election_initialized(
        ElectionConfig(manifest, 1, 1), {"created_by": "scale_run"})
    pub = Publisher(workdir)
    pub.write_election_initialized(init)
    enc = BatchEncryptor(init, g)
    seed = g.int_to_q(42)

    # ---- encrypt: generate, encrypt, write, DROP, one chunk at a time
    t0 = time.time()
    provider = RandomBallotProvider(manifest, nballots, seed=3).ballots()
    code_seed = None
    written = 0
    with pub.open_encrypted_ballots() as stream:
        done = False
        while not done:
            batch = []
            for _ in range(chunk):
                try:
                    batch.append(next(provider))
                except StopIteration:
                    done = True
                    break
            if not batch:
                break
            spoiled = {b.ballot_id for i, b in enumerate(batch)
                       if (written + i + 1) % 10 == 0}
            out, invalid = enc.encrypt_ballots(
                batch, seed=seed, code_seed=code_seed, spoiled_ids=spoiled)
            assert not invalid
            for b in out:
                stream.write(b)
            code_seed = out[-1].code
            written += len(out)
    t_encrypt = time.time() - t0
    rss_after_encrypt = rss_mb()

    consumer = Consumer(workdir, g)

    # ---- tally: streamed accumulation from disk
    t0 = time.time()
    tally_result = accumulate_ballots(
        init, consumer.iterate_encrypted_ballots(), chunk_size=chunk)
    pub.write_tally_result(tally_result)
    t_tally = time.time() - t0
    rss_after_tally = rss_mb()

    # ---- verify: streamed verification from disk (V4-V7)
    t0 = time.time()
    record = ElectionRecord(
        election_init=init,
        encrypted_ballots=consumer.iterate_encrypted_ballots(),
        tally_result=tally_result)
    res = Verifier(record, g, chunk_size=chunk).verify()
    t_verify = time.time() - t0
    assert res.ok, res.summary()

    n_spoiled = sum(1 for b in consumer.iterate_encrypted_ballots()
                    if b.state == BallotState.SPOILED)
    record_bytes = os.path.getsize(os.path.join(workdir,
                                                "encrypted_ballots.pb"))
    return {
        "phase": "stream", "group": "tiny", "nballots": written,
        "n_spoiled": n_spoiled, "chunk_size": chunk,
        "record_mb": round(record_bytes / 1e6, 1),
        "encrypt_s": round(t_encrypt, 1),
        "encrypt_per_s": round(written / t_encrypt, 1),
        "tally_s": round(t_tally, 1),
        "verify_s": round(t_verify, 1),
        "verify_per_s": round(written / t_verify, 1),
        "peak_rss_mb": {"after_encrypt": round(rss_after_encrypt, 1),
                        "after_tally": round(rss_after_tally, 1),
                        "final": round(rss_mb(), 1)},
        "verifier_ok": res.ok,
    }


def prod_phase(nballots: int) -> dict:
    import jax

    from electionguard_tpu.ballot.plaintext import RandomBallotProvider
    from electionguard_tpu.core.group import production_group
    from electionguard_tpu.encrypt.encryptor import BatchEncryptor
    from electionguard_tpu.keyceremony.exchange import key_ceremony_exchange
    from electionguard_tpu.keyceremony.trustee import KeyCeremonyTrustee
    from electionguard_tpu.publish.election_record import (ElectionConfig,
                                                           ElectionRecord)
    from electionguard_tpu.tally.accumulate import accumulate_ballots
    from electionguard_tpu.verify.verifier import Verifier
    from electionguard_tpu.workflow.e2e import sample_manifest

    g = production_group()
    manifest = sample_manifest(1, 2)
    trustees = [KeyCeremonyTrustee(g, "g0", 1, 1)]
    init = key_ceremony_exchange(trustees, g).make_election_initialized(
        ElectionConfig(manifest, 1, 1), {"created_by": "scale_run"})
    ballots = list(RandomBallotProvider(manifest, nballots,
                                        seed=1).ballots())
    enc = BatchEncryptor(init, g)
    t0 = time.time()
    encrypted, invalid = enc.encrypt_ballots(ballots, seed=g.int_to_q(42))
    t_encrypt = time.time() - t0
    assert not invalid
    tally_result = accumulate_ballots(init, encrypted)
    record = ElectionRecord(election_init=init, encrypted_ballots=encrypted,
                            tally_result=tally_result)
    res = Verifier(record, g).verify()        # warmup/compile
    assert res.ok, res.summary()
    t0 = time.time()
    res = Verifier(record, g).verify()
    t_verify = time.time() - t0
    assert res.ok, res.summary()
    rate = nballots / t_verify
    return {
        "phase": "prod", "group": "production-4096",
        "platform": jax.devices()[0].platform, "nballots": nballots,
        "encrypt_s": round(t_encrypt, 1),
        "encrypt_per_s": round(nballots / t_encrypt, 1),
        "verify_s": round(t_verify, 1),
        "verify_per_s_per_chip": round(rate, 1),
        "extrapolated_1m_verify_s_on_8_chips": round(1e6 / rate / 8, 1),
        "peak_rss_mb": round(rss_mb(), 1),
    }


def mix_sharded_phase(n_rows: int, width: int = 2) -> dict:
    """dp-scaling row for the sharded shuffle plane (ISSUE 6 satellite /
    ADVICE item 6): one TW mix stage (shuffle + prove) at dp=1 vs the
    row axis dp-sharded over the virtual 8-device mesh, differential-
    asserted BIT-IDENTICAL (same seed -> same permutation, same
    re-encryption randomness, same transcript).  On virtual CPU devices
    all 8 'chips' share one host, so dp8_stage_s measures the sharded
    plane's dispatch overhead, not real scaling — the row is the
    plumbing evidence a pod run slots into."""
    import jax

    from electionguard_tpu.core.group import tiny_group
    from electionguard_tpu.core.group_jax import jax_ops
    from electionguard_tpu.crypto.elgamal import (ElGamalKeypair,
                                                  elgamal_encrypt)
    from electionguard_tpu.mixnet.shuffle import Shuffler
    from electionguard_tpu.mixnet.stage import run_stage
    from electionguard_tpu.parallel.mesh import election_mesh
    from electionguard_tpu.parallel.sharded import ShardedGroupOps

    n_dev = len(jax.devices())
    assert n_dev >= 8, f"need the virtual 8-device mesh, got {n_dev}"
    g = tiny_group()
    key = ElGamalKeypair.from_secret(g.int_to_q(987654321))
    K, qbar = key.public_key, g.int_to_q(424242)
    pads, datas = [], []
    for i in range(n_rows):
        row_a, row_b = [], []
        for j in range(width):
            ct = elgamal_encrypt(g, (i + j) % 2,
                                 g.int_to_q(9000 + i * width + j), K)
            row_a.append(ct.pad.value)
            row_b.append(ct.data.value)
        pads.append(row_a)
        datas.append(row_b)
    seed = b"scale-mix-sharded"

    def one(ops, tag):
        sh = Shuffler(g, K.value, ops=ops)
        run_stage(g, K.value, qbar, 0, pads, datas, seed=seed,
                  shuffler=sh)                       # warm/compile
        t0 = time.time()
        st = run_stage(g, K.value, qbar, 0, pads, datas, seed=seed,
                       shuffler=sh)
        dt = time.time() - t0
        print(f"  {tag}: {dt:.2f}s ({n_rows / dt:.1f} rows/s)",
              flush=True)
        return st, dt

    st1, t1 = one(None, "dp=1 (single device)")
    sharded = ShardedGroupOps(jax_ops(g), election_mesh(8))
    st8, t8 = one(sharded, "dp=8 (virtual mesh)")
    identical = (st1.pads == st8.pads and st1.datas == st8.datas
                 and st1.proof == st8.proof)
    assert identical, "sharded stage diverged from single-device stage"
    return {
        "phase": "mix_sharded", "group": "tiny",
        "platform": jax.devices()[0].platform, "devices": n_dev,
        "n_rows": n_rows, "width": width,
        "dp1_stage_s": round(t1, 2), "dp8_stage_s": round(t8, 2),
        "dp1_rows_per_s": round(n_rows / t1, 1),
        "dp8_rows_per_s": round(n_rows / t8, 1),
        "bit_identical": identical,
        "peak_rss_mb": round(rss_mb(), 1),
    }


def fabric_phase(nballots: int, workers=(1, 2, 4),
                 workdir: str = "/tmp/egtpu_scale_fabric",
                 emulate_device_ms: float = 500.0) -> dict:
    """Workers × ballots/s curve for the sharded serving fabric: for
    each fleet size, launch a router + N encryption worker subprocesses
    (reverse-dial registration), drive the router with the loadgen
    harness, and record achieved fleet throughput.  Each fleet's shard
    records are merged and counted — the curve is only reported for
    fleets whose merged record is complete.

    ``emulate_device_ms`` pads every worker's device leg to a fixed
    wall-clock duration (EGTPU_FABRIC_EMULATE_DEVICE_MS): on a
    single-host run all workers share the host's cores, so a raw curve
    measures core contention, not the fabric — with per-batch device
    time pinned (the real fleet's one-chip-per-worker regime) the curve
    isolates what this PR adds, the routing plane's ability to keep N
    shards busy concurrently.  This is the serving-plane analogue of
    mix_sharded_phase's virtual 8-device mesh.  0 disables the
    emulation and measures raw contended throughput."""
    import shutil

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from loadgen_encrypt import run_loadgen

    from electionguard_tpu.core.group import tiny_group
    from electionguard_tpu.fabric.merge import merge_shard_records
    from electionguard_tpu.keyceremony.exchange import key_ceremony_exchange
    from electionguard_tpu.keyceremony.trustee import KeyCeremonyTrustee
    from electionguard_tpu.publish.election_record import ElectionConfig
    from electionguard_tpu.publish.publisher import Publisher
    from electionguard_tpu.remote.rpc_util import find_free_port
    from electionguard_tpu.workflow.e2e import _watch_log, sample_manifest
    from electionguard_tpu.workflow.run_command import RunCommand, wait_all

    g = tiny_group()
    manifest = sample_manifest(1, 2)
    trustees = [KeyCeremonyTrustee(g, "g0", 1, 1)]
    init = key_ceremony_exchange(trustees, g).make_election_initialized(
        ElectionConfig(manifest, 1, 1), {"created_by": "scale_run"})
    if os.path.exists(workdir):
        shutil.rmtree(workdir)
    record_dir = os.path.join(workdir, "record")
    Publisher(record_dir).write_election_initialized(init)
    logs = os.path.join(workdir, "logs")

    curve = []
    for w in workers:
        port = find_free_port()
        url = f"localhost:{port}"
        router = RunCommand.python_module(
            f"router-x{w}", "electionguard_tpu.cli.run_router",
            ["-port", str(port), "-group", "tiny"], logs)
        shards_root = os.path.join(workdir, f"shards-x{w}")
        svcs = [RunCommand.python_module(
            f"worker-x{w}-{i}",
            "electionguard_tpu.cli.run_encryption_service",
            ["-in", record_dir, "-out", os.path.join(shards_root, f"w{i}"),
             "-port", "0", "-router", url, "-workerId", f"w{i}",
             "-fixedNonces", "-maxBatch", "8",
             "-maxWaitMs", "10", "-group", "tiny"], logs,
            env={"EGTPU_FABRIC_EMULATE_DEVICE_MS":
                 str(emulate_device_ms)})
            for i in range(w)]
        try:
            # prewarm compiles every bucket at startup, so the measured
            # wave sees steady-state latency, not one-time compiles
            assert _watch_log(router.stdout_path, b" live at ", count=w,
                              timeout=300), f"fleet of {w} never went live"
            # short warmup wave settles channels/threads before timing
            run_loadgen(url, manifest, g, nclients=w, nballots=8,
                        seed=1000 + w, batch=8)
            # saturation load: full-bucket batch rpcs, 3 clients per
            # worker (queue depth ~3 keeps every shard busy across
            # client turnarounds), total offered load ∝ fleet size so
            # each row measures capacity, not a fixed trickle
            nclients = 3 * w
            per_client = max(8, nballots // 3)
            t0 = time.time()
            rep = run_loadgen(url, manifest, g, nclients=nclients,
                              nballots=per_client, seed=w, batch=8)
            wall = time.time() - t0
            sent = nclients * per_client + w * 8
        finally:
            for s in svcs:
                s.process.terminate()
            drained = wait_all(svcs, timeout=180)
            router.process.terminate()
            if router.wait_for(15) is None:
                router.kill()
        mrep = merge_shard_records(
            g, sorted(os.path.join(shards_root, d)
                      for d in os.listdir(shards_root)),
            os.path.join(workdir, f"merged-x{w}"))
        assert drained and rep["errors"] == 0 \
            and mrep.n_ballots == sent, \
            f"fleet of {w}: drained={drained} errors={rep['errors']} " \
            f"merged={mrep.n_ballots}/{sent}"
        row = {"workers": w, "ballots": nclients * per_client,
               "wall_s": round(wall, 1),
               "ballots_per_s": rep["ballots_per_s"],
               "latency_p50_ms": rep["latency_p50_ms"],
               "latency_p99_ms": rep["latency_p99_ms"],
               "merged_ballots": mrep.n_ballots}
        print(f"  fabric x{w}: {rep['ballots_per_s']:.1f} ballots/s "
              f"(p50 {rep['latency_p50_ms']:.0f}ms)", flush=True)
        curve.append(row)

    by_w = {r["workers"]: r["ballots_per_s"] for r in curve}
    out = {"phase": "fabric", "group": "tiny", "nballots": nballots,
           "device_emulation_ms": emulate_device_ms,
           "curve": curve, "peak_rss_mb": round(rss_mb(), 1)}
    if 1 in by_w and 2 in by_w and by_w[1]:
        out["scale_2w_vs_1w"] = round(by_w[2] / by_w[1], 2)
    return out


def main() -> int:
    ap = argparse.ArgumentParser("scale_run")
    ap.add_argument("--stream", type=int, default=0,
                    help="streamed tiny-group ballots (e.g. 100000)")
    ap.add_argument("--prod", type=int, default=0,
                    help="production-group verify wall-clock ballots")
    ap.add_argument("--mix-sharded", type=int, default=0,
                    help="dp-scaling rows for the sharded shuffle on "
                         "the virtual 8-device mesh (N = rows)")
    ap.add_argument("--fabric", type=int, default=0,
                    help="fleet-throughput curve for the sharded "
                         "serving fabric (N = total ballots per fleet "
                         "size; router + 1/2/4 worker subprocesses)")
    ap.add_argument("--fabric-workers", default="1,2,4",
                    help="comma-separated fleet sizes for --fabric")
    ap.add_argument("--fabric-emulate-device-ms", type=float,
                    default=500.0,
                    help="pin per-batch device time for the --fabric "
                         "curve (one-chip-per-worker regime; 0 = raw "
                         "host-contended throughput)")
    ap.add_argument("--chunk", type=int, default=4096)
    ap.add_argument("--workdir", default="/tmp/egtpu_scale")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "SCALE.json"))
    args = ap.parse_args()

    from electionguard_tpu.utils import enable_compile_cache
    enable_compile_cache()

    results = []
    if args.stream:
        os.makedirs(args.workdir, exist_ok=True)
        r = stream_phase(args.stream, args.chunk, args.workdir)
        print(json.dumps(r), flush=True)
        results.append(r)
    if args.prod:
        r = prod_phase(args.prod)
        print(json.dumps(r), flush=True)
        results.append(r)
    if args.mix_sharded:
        r = mix_sharded_phase(args.mix_sharded)
        print(json.dumps(r), flush=True)
        results.append(r)
    if args.fabric:
        fleet = tuple(int(x) for x in args.fabric_workers.split(","))
        r = fabric_phase(args.fabric, workers=fleet,
                         workdir=args.workdir + "_fabric",
                         emulate_device_ms=args.fabric_emulate_device_ms)
        print(json.dumps(r), flush=True)
        results.append(r)

    existing = []
    if os.path.exists(args.out):
        with open(args.out) as f:
            existing = json.load(f)
    with open(args.out, "w") as f:
        json.dump(existing + results, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())

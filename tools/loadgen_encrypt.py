"""Multi-client load generator for the online encryption service.

Drives N client threads (each with its own channel) of random ballots at
a ``BallotEncryptionService``, then reports:

* achieved ballots/s (wall clock over all completed requests),
* client-observed p50/p99 latency,
* mean batch occupancy + queue depth + compile counters from the
  service's own ``getMetrics`` rpc.

RESOURCE_EXHAUSTED responses (explicit backpressure) are counted and
retried with a short backoff — a saturated service sheds load without
losing any ballot the generator is determined to deliver.

Usage::

    python tools/loadgen_encrypt.py -url localhost:17711 -in <record_dir> \
        -clients 8 -nballots 64 [-group tiny]

``run_loadgen`` is importable — the serving smoke test
(tests/test_serve.py) runs a tiny-group pass of exactly this harness.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import threading
import time

import grpc

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _percentile(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i]


def run_loadgen(url: str, manifest, group, nclients: int = 4,
                nballots: int = 32, seed: int = 0,
                retry_backoff_s: float = 0.05,
                max_retries: int = 200,
                latency_out: str = None,
                batch: int = 1) -> dict:
    """Fire ``nclients`` threads × ``nballots`` single-ballot rpcs at
    ``url``; returns the report dict (also printed by main).

    ``url`` may be a single worker OR a fabric router (the surface is
    identical); behind a router every response carries the answering
    shard id, and the report grows a ``per_shard`` latency breakdown.

    ``latency_out``: optional JSONL path — one line per request with the
    client-observed latency, the answering ``shard``, AND the request's
    trace/span ids (when tracing is on, every rpc carries them to the
    service), so client↔shard joins work in the merged trace.

    ``batch``: >1 groups each client's ballots into encryptBallotBatch
    rpcs of this size (amortizes rpc overhead; the router forwards a
    whole batch to one shard).  Per-ballot latency is then its batch
    rpc's latency.
    """
    from electionguard_tpu.ballot.plaintext import RandomBallotProvider
    from electionguard_tpu.obs import trace
    from electionguard_tpu.serve.service import EncryptionClient

    lock = threading.Lock()
    latencies: list[float] = []
    shard_lat: dict[int, list[float]] = {}
    errors: list[str] = []
    rejected = 0
    codes: dict[str, bytes] = {}
    lat_f = open(latency_out, "w") if latency_out else None

    def record(b, ok, err, lat, attempts, shard, enc, sp, ts_us):
        with lock:
            if ok:
                latencies.append(lat)
                shard_lat.setdefault(shard, []).append(lat)
                codes[b.ballot_id] = enc.code
            else:
                errors.append(f"{b.ballot_id}: {err}")
            if lat_f is not None:
                lat_f.write(json.dumps(
                    {"ballot_id": b.ballot_id,
                     "trace_id": sp.trace_id,
                     "span_id": sp.span_id,
                     "ts": ts_us,
                     "shard": shard,
                     "latency_ms": (round(lat * 1e3, 3)
                                    if lat is not None else None),
                     "attempts": attempts, "ok": ok,
                     "error": err},
                    separators=(",", ":")) + "\n")

    def send_one(client, b):
        nonlocal rejected
        ts_us = time.time_ns() // 1000
        ok, err, lat, attempts = False, None, None, 0
        enc = None
        sp = trace.span("loadgen.request",
                        {"ballot_id": b.ballot_id}
                        if trace.enabled() else None)
        with sp:
            for attempt in range(max_retries):
                attempts = attempt + 1
                t0 = time.monotonic()
                try:
                    enc = client.encrypt(b)
                except grpc.RpcError as e:
                    if (e.code()
                            == grpc.StatusCode.RESOURCE_EXHAUSTED
                            and attempt < max_retries - 1):
                        with lock:
                            rejected += 1
                        time.sleep(retry_backoff_s
                                   * (1 + attempt % 5))
                        continue
                    err = str(e.code())
                    break
                except ValueError as e:  # in-band invalid ballot
                    err = str(e)
                    break
                lat = time.monotonic() - t0
                ok = True
                break
        record(b, ok, err, lat, attempts, client.last_shard_id, enc, sp,
               ts_us)

    def send_batch(client, chunk):
        nonlocal rejected
        ts_us = time.time_ns() // 1000
        sp = trace.span("loadgen.batch",
                        {"n": str(len(chunk))}
                        if trace.enabled() else None)
        with sp:
            for attempt in range(max_retries):
                t0 = time.monotonic()
                try:
                    results = client.encrypt_batch(chunk)
                except grpc.RpcError as e:
                    if (e.code()
                            == grpc.StatusCode.RESOURCE_EXHAUSTED
                            and attempt < max_retries - 1):
                        with lock:
                            rejected += 1
                        time.sleep(retry_backoff_s
                                   * (1 + attempt % 5))
                        continue
                    for b in chunk:
                        record(b, False, str(e.code()), None, attempt + 1,
                               client.last_shard_id, None, sp, ts_us)
                    return
                lat = time.monotonic() - t0
                for b, (enc, err) in zip(chunk, results):
                    record(b, err is None, err, lat, attempt + 1,
                           client.last_shard_id, enc, sp, ts_us)
                return

    def one_client(idx: int):
        client = EncryptionClient(url, group)
        ballots = list(RandomBallotProvider(
            manifest, nballots, seed=seed + idx).ballots())
        # distinct ids across clients AND across loadgen waves
        # (ballot ids are unique election-wide)
        ballots = [dataclasses.replace(
            b, ballot_id=f"c{idx}s{seed}-{b.ballot_id}") for b in ballots]
        try:
            if batch > 1:
                for i in range(0, len(ballots), batch):
                    send_batch(client, ballots[i:i + batch])
            else:
                for b in ballots:
                    send_one(client, b)
        finally:
            client.close()

    threads = [threading.Thread(target=one_client, args=(i,), daemon=True)
               for i in range(nclients)]
    t_start = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t_start

    # service-side view: occupancy / queue depth / compiles
    from electionguard_tpu.serve.service import EncryptionClient as _C
    client = _C(url, group)
    try:
        m = client.metrics()
        counters = dict(m.counters)
        hists = {h.name: h for h in m.histograms}
        occ = hists.get("batch_occupancy")
        occupancy_mean = (occ.sum / occ.count) if occ and occ.count else 0.0
    finally:
        client.close()

    if lat_f is not None:
        lat_f.close()

    lat_sorted = sorted(latencies)
    report = {
        "clients": nclients,
        "requested": nclients * nballots,
        "completed": len(latencies),
        "errors": len(errors),
        "rejected_retries": rejected,
        "wall_s": round(wall, 3),
        "ballots_per_s": round(len(latencies) / wall, 2) if wall else 0.0,
        "latency_p50_ms": round(_percentile(lat_sorted, 0.50) * 1e3, 1),
        "latency_p99_ms": round(_percentile(lat_sorted, 0.99) * 1e3, 1),
        "batch_occupancy_mean": round(occupancy_mean, 3),
        "service_counters": counters,
        "error_samples": errors[:5],
    }
    # fabric: behind a router every response names its shard (>= 0); a
    # single worker answers -1 and the breakdown stays out of the report
    if any(s >= 0 for s in shard_lat):
        per_shard = {}
        for s, lats in sorted(shard_lat.items()):
            ls = sorted(lats)
            per_shard[str(s)] = {
                "completed": len(ls),
                "ballots_per_s": (round(len(ls) / wall, 2)
                                  if wall else 0.0),
                "latency_p50_ms": round(_percentile(ls, 0.50) * 1e3, 1),
                "latency_p99_ms": round(_percentile(ls, 0.99) * 1e3, 1),
            }
        report["per_shard"] = per_shard
    report["_codes"] = codes  # for callers that diff against offline
    return report


def main(argv=None) -> int:
    from electionguard_tpu.cli.common import (add_group_flag, resolve_group,
                                              setup_logging)
    from electionguard_tpu.publish.publisher import Consumer

    log = setup_logging("LoadgenEncrypt")
    ap = argparse.ArgumentParser("loadgen_encrypt")
    ap.add_argument("-url", default=None, help="service host:port")
    ap.add_argument("-target", dest="url",
                    help="alias of -url; a fabric router is a valid "
                         "target (same rpc surface) and unlocks the "
                         "per_shard report section")
    ap.add_argument("-in", dest="input", required=True,
                    help="record dir with election_initialized.pb "
                         "(manifest source)")
    ap.add_argument("-clients", type=int, default=4)
    ap.add_argument("-nballots", type=int, default=32,
                    help="ballots per client")
    ap.add_argument("-batch", type=int, default=1,
                    help="group each client's ballots into "
                         "encryptBallotBatch rpcs of this size")
    ap.add_argument("-seed", type=int, default=0)
    ap.add_argument("-json", dest="json_out", default=None,
                    help="also write the report to this path")
    ap.add_argument("-latencyOut", dest="latency_out", default=None,
                    help="per-request latency JSONL (ballot_id, trace/"
                         "span ids, latency_ms, attempts) for post-hoc "
                         "joins against the server span timeline")
    add_group_flag(ap)
    args = ap.parse_args(argv)
    if not args.url:
        ap.error("one of -url / -target is required")

    group = resolve_group(args)
    init = Consumer(args.input, group).read_election_initialized()
    report = run_loadgen(args.url, init.config.manifest, group,
                         nclients=args.clients, nballots=args.nballots,
                         seed=args.seed, latency_out=args.latency_out,
                         batch=args.batch)
    report.pop("_codes", None)
    print(json.dumps(report, indent=2, sort_keys=True))
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
    log.info("%d/%d ballots at %.1f/s (p50 %.0fms p99 %.0fms, "
             "occupancy %.2f)", report["completed"], report["requested"],
             report["ballots_per_s"], report["latency_p50_ms"],
             report["latency_p99_ms"], report["batch_occupancy_mean"])
    return 0 if report["errors"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())

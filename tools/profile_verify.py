"""Per-program on-chip timing at the verify/encrypt real dispatch shapes.

Times each FUSED device program the production pipelines issue — V4
selection check, V5 contest check, selection encryption, contest
encryption, the V5/V7 product-reduce — at the shapes a 2048-ballot
chunk produces, plus the host<->device transfer cost, so optimization
effort follows measured time, not guesses.  Compiles are expected to be
warm (run ``python bench.py`` first).

Usage: python tools/profile_verify.py [nballots]
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timed(tag, fn, reps=3):
    import jax
    out = fn()
    jax.block_until_ready(out)  # compile / first dispatch
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    print(f"{tag:<28s} {dt * 1e3:9.1f} ms")
    return dt


def main() -> int:
    nballots = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
    from electionguard_tpu.utils import enable_compile_cache
    enable_compile_cache()
    import jax
    import jax.numpy as jnp

    from electionguard_tpu.core import bignum_jax as bn
    from electionguard_tpu.core import sha256_jax
    from electionguard_tpu.core.group import production_group
    from electionguard_tpu.core.group_jax import jax_exp_ops, jax_ops
    from electionguard_tpu.core.hash import _encode

    g = production_group()
    eo = jax_ops(g)
    ee = jax_exp_ops(g)
    print(f"platform={jax.default_backend()} backend={eo.backend} "
          f"tile={eo.tile} nballots={nballots}")

    S = 3 * nballots          # selection rows (2 selections + 1 placeholder)
    C = nballots              # contest rows
    rng = np.random.default_rng(0)
    exps = [int.from_bytes(rng.bytes(32), "big") % g.q for _ in range(64)]
    elems = [pow(g.g, e | 1, g.p) for e in exps]

    def rows_p(k):
        return np.asarray((eo.to_limbs_p(elems) * (k // 64 + 1))[:k])

    def rows_q(k):
        return np.asarray((ee.to_limbs(exps) * (k // 64 + 1))[:k])

    K = pow(g.g, 0x1234567890ABCDEF, g.p)
    eo.fixed_table(K)
    qbar = _encode(123456789)

    from electionguard_tpu.encrypt.fused import get_fused_encryptor
    from electionguard_tpu.verify.fused import get_fused
    fe = get_fused_encryptor(eo, ee)
    fv = get_fused(eo)

    # fused encryption at chunk shape (nonces derived in-program);
    # warm-up output doubles as the verification input — every timed
    # lambda closes over prebuilt arrays so host conversion stays out
    # of the measured region
    seed_row = rng.integers(0, 256, 32, dtype=np.uint8)
    bids = rng.integers(0, 256, (S, 32), dtype=np.uint8)
    ords = np.arange(S, dtype=np.uint32)
    votes = (np.arange(S) % 2).astype(np.int64)
    alpha, beta, _, CR, VR, CF, VF = fe.encrypt_selections(
        seed_row, bids, ords, votes, K, qbar)  # warm-up + outputs
    total = 0.0
    t_enc = timed("fused enc-selections S", lambda: fe.encrypt_selections(
        seed_row, bids, ords, votes, K, qbar))
    total += t_enc
    rs_c, vs_c = rows_q(C), rows_q(C)
    total += timed("fused enc-contests C", lambda: fe.encrypt_contests(
        seed_row, bids[:C], ords[:C], rs_c, vs_c, K, qbar + _encode(1)))

    # fused verification of what encryption just produced
    v1m = (votes == 1)[:, None]
    c0 = np.where(v1m, CF, CR)
    v0 = np.where(v1m, VF, VR)
    c1 = np.where(v1m, CR, CF)
    v1_ = np.where(v1m, VR, VF)
    ok = np.asarray(fv.v4_selections(alpha, beta, c0, v0, c1, v1_,
                                     K, qbar))
    assert ok.all(), "fused V4 rejected fused-encrypted rows — " \
        "refusing to profile a broken pipeline"
    t_v4 = timed("fused v4-selections S", lambda: fv.v4_selections(
        alpha, beta, c0, v0, c1, v1_, K, qbar))
    total += t_v4
    ca_c, cb_c = rows_p(C), rows_p(C)
    lq_c, cc_c, cv_c = rows_q(C), rows_q(C), rows_q(C)
    total += timed("fused v5-contests C", lambda: fv.v5_contests(
        ca_c, cb_c, lq_c, cc_c, cv_c, K, qbar + _encode(1)))
    prod_in = np.broadcast_to(rows_p(S)[:, None, :], (S, 2, eo.n))
    total += timed("prod-reduce V7", lambda: eo.prod_reduce(prod_in))
    elem_b = np.zeros((S, g.spec.p_bytes), np.uint8)
    elem_b[:, -1] = 7
    timed("sha challenge S (unfused)",
          lambda: sha256_jax.batch_challenge_p(g, qbar, [elem_b] * 6))

    # host<->device transfer at a chunk-sized limb block
    dev = jnp.asarray(rows_p(2 * S))
    jax.block_until_ready(dev)
    timed("transfer d2h 2S rows", lambda: np.asarray(dev) + 0)

    print(f"{'device total (one chunk)':<28s} {total * 1e3:9.1f} ms  "
          f"({nballots / total:.1f} ballots/s ex-host; "
          f"v4 alone {nballots / t_v4:.1f}/s, "
          f"enc alone {nballots / t_enc:.1f}/s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Per-op on-chip timing at the verify pass's real dispatch shapes.

Times each device op the V4/V5 chunk path issues (residue, powmod,
fixed-base pows, mulmod, device SHA challenges) at the tile shapes a
2048-ballot chunk produces, plus the host<->device transfer cost, so
optimization effort follows measured time, not guesses.  Compiles are
expected to be warm (run ``python bench.py`` first); every dispatch is
still wrapped in a small retry for tunnel flakes.

Usage: python tools/profile_verify.py [nballots]
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timed(tag, fn, reps=3):
    import jax
    out = fn()
    jax.block_until_ready(out)  # compile / first dispatch
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    print(f"{tag:<28s} {dt * 1e3:9.1f} ms")
    return dt


def main() -> int:
    nballots = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
    from electionguard_tpu.utils import enable_compile_cache
    enable_compile_cache()
    import jax
    import jax.numpy as jnp

    from electionguard_tpu.core import bignum_jax as bn
    from electionguard_tpu.core import sha256_jax
    from electionguard_tpu.core.group import production_group
    from electionguard_tpu.core.group_jax import jax_exp_ops, jax_ops
    from electionguard_tpu.core.hash import _encode

    g = production_group()
    eo = jax_ops(g)
    ee = jax_exp_ops(g)
    print(f"platform={jax.default_backend()} backend={eo.backend} "
          f"tile={eo.tile} nballots={nballots}")

    S = 3 * nballots          # selection rows (2 selections + 1 placeholder)
    C = nballots              # contest rows
    rng = np.random.default_rng(0)
    exps = [int.from_bytes(rng.bytes(32), "big") % g.q for _ in range(64)]
    elems = [pow(g.g, e | 1, g.p) for e in exps]

    def rows_p(k):
        return np.asarray((eo.to_limbs_p(elems) * (k // 64 + 1))[:k])

    def rows_q(k):
        return np.asarray((ee.to_limbs(exps) * (k // 64 + 1))[:k])

    A = rows_p(S)
    E = rows_q(S)
    K = pow(g.g, 0x1234567890ABCDEF, g.p)
    eo.fixed_table(K)

    total = 0.0
    total += timed("residue 2S", lambda: eo.is_valid_residue(rows_p(2 * S)))
    total += timed("powmod 4S (var_pows)",
                   lambda: eo.powmod(rows_p(4 * S), rows_q(4 * S)))
    total += timed("g_pow 2S", lambda: eo.g_pow(rows_q(2 * S)))
    total += timed("base_pow K 2S", lambda: eo.base_pow(K, rows_q(2 * S)))
    total += timed("mulmod 5S", lambda: eo.mulmod(rows_p(5 * S),
                                                  rows_p(5 * S)))
    total += timed("powmod 2C (V5)",
                   lambda: eo.powmod(rows_p(2 * C), rows_q(2 * C)))
    total += timed("g_pow+K_pow 2C", lambda: (eo.g_pow(rows_q(C)),
                                              eo.base_pow(K, rows_q(C))))
    elem_b = np.zeros((S, g.spec.p_bytes), np.uint8)
    elem_b[:, -1] = 7
    qbar = _encode(123456789)
    total += timed("sha challenge S (V4)",
                   lambda: sha256_jax.batch_challenge_p(
                       g, qbar, [elem_b] * 6))
    total += timed("zq add S", lambda: ee.add(rows_q(S), rows_q(S)))

    # host<->device transfer at a var_pows-sized result
    dev = jnp.asarray(rows_p(4 * S))
    jax.block_until_ready(dev)
    timed("transfer d2h 4S rows", lambda: np.asarray(dev) + 0)

    print(f"{'device total (one chunk)':<28s} {total * 1e3:9.1f} ms  "
          f"({nballots / total:.1f} ballots/s ex-host)")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Flight report: turn any trace dir into FLIGHT_REPORT.md.

Analyzes a traced run's span dir (the run's ``EGTPU_OBS_TRACE`` dir, or
a collector's receive dir) and writes the post-run evidence bundle:
critical path with per-hop durations, phase x process x category
wall-clock attribution, top-N self-time spans, per-shard balance table
with straggler naming, compile/device-time summary, and SLO verdicts.

A damaged trace (killed worker, truncated span file, clock skew)
degrades to a partial report with warnings — the tool only fails when
the dir holds no spans at all.

Usage::

    python tools/egreport.py /tmp/eg/trace
    python tools/egreport.py /tmp/eg/trace -out FLIGHT_REPORT.md -topN 20
    python tools/egreport.py /tmp/eg/trace -json            # verdict json

``workflow/e2e.py -flightReport`` runs the same generator in-process
after every traced run.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("egreport")
    ap.add_argument("trace_dir",
                    help="span dir (the run's EGTPU_OBS_TRACE or a "
                         "collector recv dir)")
    ap.add_argument("-out", dest="output", default=None,
                    help="report path (default FLIGHT_REPORT.md next to "
                         "the trace dir)")
    ap.add_argument("-topN", dest="top_n", type=int, default=None,
                    help="rows in the top-self-time table "
                         "(default EGTPU_FLIGHT_TOP_N)")
    ap.add_argument("-json", dest="as_json", action="store_true",
                    help="also print the machine-readable analysis json")
    args = ap.parse_args(argv)

    from electionguard_tpu.obs import flight

    out_path, analysis = flight.write_report(
        args.trace_dir, out_path=args.output, top_n=args.top_n)
    if args.as_json:
        print(json.dumps(analysis.to_json(), indent=2, sort_keys=True))
    else:
        print(f"flight report: {out_path}")
        print(f"  spans={len(analysis.spans)} wall={analysis.wall_us / 1e6:.1f}s "
              f"path={analysis.path_total_us / 1e6:.1f}s "
              f"coverage={analysis.coverage * 100:.1f}%")
        for p in analysis.antipatterns:
            print(f"  anti-pattern: {p['kind']} on {p['subject']}")
        for msg in analysis.warnings:
            print(f"  warning: {msg}")
    if not analysis.spans:
        print("no spans found: nothing to report", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

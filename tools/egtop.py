"""Live election-fleet mission control — a ``top`` for an e2e run.

Polls the obs collector's ``getFleetStatus`` rpc (obs/collector.py) and
redraws a terminal status board: fleet health, one row per process
(state, liveness, heartbeat age, queue depth, current phase, serving
p99, spans streamed, client-side drops), and the recent SLO alerts.
When the fleet serves multiple elections, a tenant pane follows: one
row per election with its ballot counts, request p99 against ITS SLO
objective (OK/BURN verdict), and its share of fleet device time.

With ``-trace <dir>`` the board gains a critical-path pane: each frame
re-analyzes the span dir (the collector's receive dir, or the run's
``EGTPU_OBS_TRACE``) with obs/analyze and shows the top hops the run's
wall-clock is actually waiting on — the live version of the flight
report's first table.

With ``-capacity [path]`` the board gains a capacity pane from the
tracked CAPACITY.json (tools/egplan.py): headline chips-for-deadline
per backend plus the last predicted-vs-measured validation verdict.

Usage::

    python tools/egtop.py -collector localhost:17171
    python tools/egtop.py -collector localhost:17171 -once   # one frame
    python tools/egtop.py -collector localhost:17171 -trace /tmp/eg/obs/recv
    python tools/egtop.py -collector localhost:17171 -capacity
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_DEFAULT_CAPACITY = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "CAPACITY.json")

_STATE_GLYPH = {"ALIVE": "✓", "EXITED": "-", "DEAD": "✗"}
_COLORS = {"green": "\x1b[32m", "red": "\x1b[31m"}
_RESET = "\x1b[0m"


def _paint(text: str, color: str, enabled: bool) -> str:
    if not enabled or color not in _COLORS:
        return text
    return f"{_COLORS[color]}{text}{_RESET}"


def parse_shard(phase: str) -> dict | None:
    """Parse the serving-plane heartbeat phase string
    ``serving shard=<id> head=<hex16> admitted=<n>`` (set by
    serve/service.py in fabric mode) into its fields; None when the
    process is not a shard worker."""
    if not phase or "shard=" not in phase:
        return None
    out = {}
    for tok in phase.split():
        if "=" in tok:
            k, v = tok.split("=", 1)
            out[k] = v
    try:
        return {"shard": int(out["shard"]), "head": out.get("head", "-"),
                "admitted": int(out.get("admitted", "0"))}
    except (KeyError, ValueError):
        return None


def render(status, color: bool = True) -> str:
    """One frame of the board from a FleetStatusResponse."""
    lines = []
    alive = sum(1 for p in status.processes if p.state == "ALIVE")
    dead = sum(1 for p in status.processes if p.state == "DEAD")
    lines.append(
        f"fleet {_paint(status.health.upper(), status.health, color)}  "
        f"procs {alive} alive / {dead} dead / "
        f"{len(status.processes)} total   spans {status.spans_total}   "
        f"slo evals {status.slo_evals}")
    lines.append(f"{'':1} {'PROC':<26}{'PID':>7} {'STATE':<7}{'STATUS':<9}"
                 f"{'HB_AGE':>7} {'QUEUE':>6} {'P99MS':>7} {'SPANS':>7} "
                 f"{'DROP':>5}  PHASE")
    for p in status.processes:
        glyph = _STATE_GLYPH.get(p.state, "?")
        row_color = {"DEAD": "red", "ALIVE": "green"}.get(p.state, "")
        lines.append(_paint(
            f"{glyph} {p.proc:<26}{p.pid:>7} {p.state:<7}{p.status:<9}"
            f"{p.heartbeat_age_s:>6.1f}s {p.queue_depth:>6} "
            f"{p.p99_ms:>7.1f} {p.spans:>7} {p.dropped:>5}  "
            f"{p.phase or '-'}", row_color, color))
    # fabric: one row per encryption shard, parsed from the worker
    # heartbeats' phase fields (serve/service.py emits
    # "serving shard=<id> head=<hex16> admitted=<n>")
    shards = []
    for p in status.processes:
        s = parse_shard(p.phase)
        if s is not None:
            shards.append((s, p))
    if shards:
        lines.append(f"{'':1} {'SHARD':<6}{'WORKER':<26}{'STATE':<7}"
                     f"{'QUEUE':>6} {'ADMITTED':>9}  CHAIN_HEAD")
        for s, p in sorted(shards, key=lambda sp: sp[0]["shard"]):
            row_color = {"DEAD": "red", "ALIVE": "green"}.get(p.state, "")
            lines.append(_paint(
                f"  {s['shard']:<6}{p.proc:<26}{p.state:<7}"
                f"{p.queue_depth:>6} {s['admitted']:>9}  {s['head']}",
                row_color, color))
    if status.alerts:
        lines.append("recent alerts:")
        for a in list(status.alerts)[-8:]:
            lines.append(_paint(f"  ! {a}", "red", color))
    return "\n".join(lines)


def render_critical_path(trace_dir: str, rows: int = 5) -> str:
    """Critical-path pane: the top ``rows`` hops by self-on-path time
    over the spans exported so far.  A mid-run or damaged trace degrades
    to a one-line notice, never breaks the board."""
    try:
        from electionguard_tpu.obs import analyze
        a = analyze.analyze(trace_dir)
    except Exception as e:  # noqa: BLE001 — the pane must never kill the board
        return f"critical path unavailable: {e}"
    if not a.path:
        return "critical path unavailable (no closed process-root span yet)"
    lines = [f"critical path  wall {a.wall_us / 1e6:.1f}s  "
             f"{len(a.path)} hop(s)"
             + (f"  [{len(a.warnings)} warning(s)]" if a.warnings else "")]
    top = sorted(a.path, key=lambda r: -r["dur_us"])[:rows]
    for r in top:
        pct = 100.0 * r["dur_us"] / a.wall_us if a.wall_us else 0.0
        lines.append(f"  {r['dur_us'] / 1e6:>7.2f}s {pct:>5.1f}%  "
                     f"{r['name']}  [{r['proc']}]")
    for p in a.antipatterns:
        lines.append(f"  ! {p['kind']}: {p['subject']}")
    return "\n".join(lines)


def render_tenants(stub, timeout: float = 5.0) -> str:
    """Tenant pane: one row per election over the fleet-merged metrics
    (``getMetrics``): ballots encrypted/admitted/rejected, request p99
    vs that tenant's SLO objective (``per_election`` override, else the
    fleet default) with an OK/BURN verdict, and the tenant's share of
    total device time (the noisy-neighbor detector's raw material).
    Degrades to a one-line notice, never breaks the board."""
    try:
        from electionguard_tpu.obs import slo as slo_mod
        from electionguard_tpu.publish import pb
        resp = stub.call("getMetrics", pb.msg("MetricsRequest")(),
                         timeout=timeout)
        cfg = slo_mod.load_config()["serving_p99_ms"]
    except Exception as e:  # noqa: BLE001 — the pane must never kill the board
        return f"tenant pane unavailable: {e}"
    counts: dict[str, dict[str, int]] = {}
    for flat, v in resp.counters.items():
        name, labels = slo_mod.parse_labels(flat)
        el = labels.get("election")
        if el is None:
            continue
        if name in ("ballots_encrypted", "requests_admitted",
                    "requests_rejected_queue_full",
                    "tenant_device_ms_total"):
            per = counts.setdefault(el, {})
            per[name] = per.get(name, 0) + v
    hists: dict[str, list] = {}
    for h in resp.histograms:
        name, labels = slo_mod.parse_labels(h.name)
        el = labels.get("election")
        if name == "request_latency_ms" and el is not None:
            hists.setdefault(el, []).append(h)
    elections = sorted(set(counts) | set(hists))
    if not elections:
        return "tenants: none (no election-labeled series yet)"
    total_ms = sum(per.get("tenant_device_ms_total", 0)
                   for per in counts.values())
    lines = [f"{'':1} {'ELECTION':<22}{'ENCRYPTED':>10}{'ADMITTED':>9}"
             f"{'REJECTED':>9}{'P99MS':>8}{'OBJ':>7} {'SLO':<5}"
             f"{'DEV%':>5}"]
    for el in elections:
        per = counts.get(el, {})
        # merged per-tenant p99 across the fleet's processes
        merged = {"bounds": (), "counts": [], "count": 0}
        for h in hists.get(el, ()):
            if not merged["bounds"]:
                merged["bounds"] = tuple(h.bounds)
                merged["counts"] = [0] * len(h.counts)
            for i, c in enumerate(h.counts):
                merged["counts"][i] += c
            merged["count"] += h.count
        p99 = slo_mod.histogram_quantile(merged, 0.99)
        objective = cfg.get("per_election", {}).get(el, cfg["objective"])
        verdict = "OK" if p99 <= objective else "BURN"
        share = (100.0 * per.get("tenant_device_ms_total", 0) / total_ms
                 if total_ms else 0.0)
        label = el if len(el) <= 21 else el[:18] + "..."
        lines.append(
            f"  {label:<22}{per.get('ballots_encrypted', 0):>10}"
            f"{per.get('requests_admitted', 0):>9}"
            f"{per.get('requests_rejected_queue_full', 0):>9}"
            f"{p99:>8.0f}{objective:>7.0f} {verdict:<5}{share:>4.0f}%")
    return "\n".join(lines)


def render_capacity(capacity_path: str) -> str:
    """Capacity pane: headline chips-for-deadline per backend and the
    last validation verdict from the tracked CAPACITY.json
    (``tools/egplan.py``).  A missing or damaged file degrades to a
    one-line notice, never breaks the board."""
    try:
        with open(capacity_path) as f:
            doc = json.load(f)
        headline = doc["headline"]
    except Exception as e:  # noqa: BLE001 — the pane must never kill the board
        return f"capacity plan unavailable: {e}"
    lines = [f"capacity plan  {doc.get('ballots', 0):,} ballots "
             f"< {doc.get('deadline_s', 0):.0f}s  "
             f"[{doc.get('model', {}).get('platform', '?')}]"]
    for row in headline:
        if row.get("chips") is None:
            lines.append(f"  {row['backend']:<8} unreachable")
            continue
        lo, hi = row.get("chips_hi"), row.get("chips_lo")
        band = f"  [{min(lo, hi):,}–{max(lo, hi):,}]" if lo and hi else ""
        lines.append(f"  {row['backend']:<8}{row['chips']:>10,} chip(s)"
                     f"{band}  bottleneck: {row.get('bottleneck', '-')}")
    val = doc.get("validation")
    if val and val.get("max_err_pct") is not None:
        lines.append(f"  model vs measured: max err "
                     f"{val['max_err_pct']:.1f}% over "
                     f"{val.get('n_checked', 0)} config(s) "
                     f"({'PASS' if val.get('pass') else 'FAIL'})")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("egtop")
    ap.add_argument("-collector", required=True,
                    help="obs collector address (host:port)")
    ap.add_argument("-interval", type=float, default=1.0,
                    help="refresh interval in seconds")
    ap.add_argument("-once", action="store_true",
                    help="print one frame and exit (no screen control)")
    ap.add_argument("-noColor", dest="no_color", action="store_true")
    ap.add_argument("-trace", dest="trace_dir", default=None,
                    help="span dir to analyze per frame (collector recv "
                         "dir or EGTPU_OBS_TRACE): adds a critical-path "
                         "pane under the fleet board")
    ap.add_argument("-capacity", dest="capacity_path", default=None,
                    nargs="?", const=_DEFAULT_CAPACITY,
                    help="CAPACITY.json to render as a capacity pane "
                         "(bare flag = the repo's tracked copy)")
    args = ap.parse_args(argv)

    from electionguard_tpu.publish import pb
    from electionguard_tpu.remote.rpc_util import Stub, make_plain_channel

    stub = Stub(make_plain_channel(args.collector), "ObsCollectorService")
    color = not args.no_color and (args.once or sys.stdout.isatty())
    req = pb.msg("FleetStatusRequest")()
    while True:
        try:
            status = stub.call("getFleetStatus", req, timeout=5.0)
        except Exception as e:  # noqa: BLE001 — show the outage, keep going
            frame = f"egtop: collector {args.collector} unreachable: {e}"
            status = None
        else:
            frame = render(status, color=color)
            frame += "\n" + render_tenants(stub)
        if args.trace_dir:
            frame += "\n" + render_critical_path(args.trace_dir)
        if args.capacity_path:
            frame += "\n" + render_capacity(args.capacity_path)
        if args.once:
            print(frame)
            return 0 if status is not None else 1
        # full-screen redraw: clear + home, like watch(1)
        sys.stdout.write("\x1b[2J\x1b[H")
        sys.stdout.write(time.strftime("%H:%M:%S") + "  egtop  "
                         + args.collector + "\n" + frame + "\n")
        sys.stdout.flush()
        time.sleep(args.interval)


if __name__ == "__main__":
    try:
        sys.exit(main())
    except KeyboardInterrupt:
        sys.exit(0)

"""Microbenchmark for the bignum data plane: montmul / powmod / fixed_pow.

Times the primitive batch kernels at production shapes so kernel work can be
iterated on without a full bench.py run.  Usage:

    python tools/bench_bignum.py [--batch 512] [--ops powmod,fixed,mulmod]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def _timeit(fn, *args, reps=3):
    out = fn(*args)
    jax.block_until_ready(out)          # warmup/compile
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=512)
    ap.add_argument("--ops", default="mulmod,powmod,fixed,residue")
    args = ap.parse_args()
    B = args.batch
    which = set(args.ops.split(","))

    from electionguard_tpu.core import bignum_jax as bn
    from electionguard_tpu.core.group import production_group
    from electionguard_tpu.core.group_jax import jax_ops

    g = production_group()
    ops = jax_ops(g)
    rng = np.random.default_rng(0)

    exps = [int.from_bytes(rng.bytes(32), "big") % g.q for _ in range(B)]
    bases = [pow(g.g, e | 1, g.p) for e in exps[: min(B, 64)]]
    bases = (bases * (B // len(bases) + 1))[:B]
    A = jnp.asarray(ops.to_limbs_p(bases))
    E = jnp.asarray(ops.to_limbs_q(exps))

    print(f"platform={jax.devices()[0].platform} batch={B} "
          f"n={ops.n} limbs x 16b")

    if "mulmod" in which:
        dt = _timeit(ops._mulmod_j, A, A)
        print(f"mulmod : {dt*1e3:8.2f} ms  "
              f"{B/dt:12.0f} el/s  {dt/B*1e9:8.0f} ns/el")
    if "powmod" in which:
        dt = _timeit(ops._powmod_j, A, E)
        print(f"powmod : {dt*1e3:8.2f} ms  "
              f"{B/dt:12.0f} el/s  {dt/B*1e6:8.1f} us/el")
    if "fixed" in which:
        dt = _timeit(ops._fixed_pow_j, ops.g_table, E)
        print(f"g_pow  : {dt*1e3:8.2f} ms  "
              f"{B/dt:12.0f} el/s  {dt/B*1e6:8.1f} us/el")
    if "residue" in which:
        q_exp = jnp.broadcast_to(
            jnp.asarray(bn.int_to_limbs(g.q, ops.ne)), (B, ops.ne))
        dt = _timeit(ops._verify_residue_j, A, q_exp)
        print(f"residue: {dt*1e3:8.2f} ms  "
              f"{B/dt:12.0f} el/s  {dt/B*1e6:8.1f} us/el")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

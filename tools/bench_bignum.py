"""Microbenchmark for the bignum data plane: montmul / powmod / fixed_pow.

Times the primitive batch kernels at production shapes so kernel work can be
iterated on without a full bench.py run.  Usage:

    python tools/bench_bignum.py [--batch 512] [--ops powmod,fixed,mulmod]
    python tools/bench_bignum.py --backend all --json BENCH_BIGNUM.json

Without ``--backend`` the session-default backend is timed through the full
legacy op set (mulmod/powmod/fixed/fixedmulti/residue/fused).  With
``--backend cios|ntt|pallas|all`` the shared ``core.bignum_bench`` helper
times mulmod/powmod/fixed per requested backend and emits labeled rows
(requested vs effective backend, batch, exp_bits, platform); ``--json``
writes them as the tracked roofline artifact.  Off-TPU, pallas rows run the
kernels in interpret mode (slow): batch/reps/exp-bits default down so a
``--backend pallas`` run finishes in about a minute instead of hours.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def _timeit(fn, *args, reps=3):
    out = fn(*args)
    jax.block_until_ready(out)          # warmup/compile
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def _backend_mode(args) -> int:
    """--backend path: labeled per-backend rows via core.bignum_bench."""
    import json

    import jax

    from electionguard_tpu.core import bignum_bench
    from electionguard_tpu.core.group import production_group

    backends = (("cios", "ntt", "pallas") if args.backend == "all"
                else (args.backend,))
    on_tpu = jax.default_backend() == "tpu"
    if "pallas" in backends and not on_tpu:
        # measure the real kernels (emulated) instead of the ntt
        # fallback; interpret launches are ~2.5 s each, so shrink the
        # run unless the caller sized it explicitly
        os.environ.setdefault("EGTPU_PALLAS_INTERPRET", "1")
        if args.batch is None:
            args.batch = 8
        if args.reps is None:
            args.reps = 1
        if args.exp_bits is None:
            args.exp_bits = 32
        print("off-TPU pallas: interpret mode, defaults reduced to "
              f"batch={args.batch} reps={args.reps} "
              f"exp_bits={args.exp_bits}")
    batch = args.batch if args.batch is not None else 512
    reps = args.reps if args.reps is not None else 3
    ops = tuple(o for o in args.ops.split(",")
                if o in bignum_bench.DEFAULT_OPS)
    rows = []
    for backend in backends:
        bops = ops
        if backend == "pallas" and not on_tpu and "fixed" in bops:
            # the fixed-base hat-table build alone is ~8k emulated
            # kernel launches; keep interpret runs tractable
            print("off-TPU pallas: skipping fixed (hat-table build is "
                  "hours in interpret mode)")
            bops = tuple(o for o in bops if o != "fixed")
        if backend == "pallas" and not on_tpu and "msm" in bops:
            # the bucket suffix scan alone is ~2^w emulated montmul
            # launches per window
            print("off-TPU pallas: skipping msm (bucket combine is "
                  "hours in interpret mode)")
            bops = tuple(o for o in bops if o != "msm")
        got = bignum_bench.backend_rows(
            production_group(), backend, batch=batch, ops=bops,
            exp_bits=args.exp_bits, reps=reps)
        rows.extend(got)
        for r in got:
            eff = ("" if r["effective"] == r["backend"]
                   else f" (degraded to {r['effective']})")
            bits = f" exp_bits={r['exp_bits']}" if r["exp_bits"] else ""
            print(f"{r['backend']:>6}:{r['op']:<7} "
                  f"{r['sec_per_call'] * 1e3:10.2f} ms  "
                  f"{r['per_s']:12.1f} el/s{bits}{eff}")
    if args.json:
        blob = {"platform": jax.devices()[0].platform, "rows": rows}
        with open(args.json, "w") as f:
            json.dump(blob, f, indent=1)
        print(f"wrote {len(rows)} rows -> {args.json}")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument(
        "--ops", default="mulmod,powmod,fixed,fixedmulti,residue,msm")
    ap.add_argument("--backend", default=None,
                    choices=["cios", "ntt", "pallas", "all"],
                    help="time these backends via core.bignum_bench "
                         "(labeled rows) instead of the session default")
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="write the per-backend rows as JSON "
                         "(requires --backend)")
    ap.add_argument("--exp-bits", dest="exp_bits", type=int, default=None,
                    help="reduced powmod ladder width (--backend mode)")
    ap.add_argument("--reps", type=int, default=None)
    args = ap.parse_args()
    from electionguard_tpu.utils import enable_compile_cache
    enable_compile_cache()
    if args.backend:
        return _backend_mode(args)
    B = args.batch if args.batch is not None else 512
    which = set(args.ops.split(","))

    from electionguard_tpu.core import bignum_jax as bn
    from electionguard_tpu.core.group import production_group
    from electionguard_tpu.core.group_jax import jax_ops

    g = production_group()
    ops = jax_ops(g)
    rng = np.random.default_rng(0)

    exps = [int.from_bytes(rng.bytes(32), "big") % g.q for _ in range(B)]
    bases = [pow(g.g, e | 1, g.p) for e in exps[: min(B, 64)]]
    bases = (bases * (B // len(bases) + 1))[:B]
    A = jnp.asarray(ops.to_limbs_p(bases))
    E = jnp.asarray(ops.to_limbs_q(exps))

    print(f"platform={jax.devices()[0].platform} batch={B} "
          f"n={ops.n} limbs x 16b")

    if "mulmod" in which:
        dt = _timeit(ops._mulmod_j, A, A)
        print(f"mulmod : {dt*1e3:8.2f} ms  "
              f"{B/dt:12.0f} el/s  {dt/B*1e9:8.0f} ns/el")
    if "powmod" in which:
        dt = _timeit(ops._powmod_j, A, E)
        print(f"powmod : {dt*1e3:8.2f} ms  "
              f"{B/dt:12.0f} el/s  {dt/B*1e6:8.1f} us/el")
    if "fixed" in which:
        dt = _timeit(ops._fixed_pow_j, ops.g_table, E)
        print(f"g_pow  : {dt*1e3:8.2f} ms  "
              f"{B/dt:12.0f} el/s  {dt/B*1e6:8.1f} us/el")
    if "fixedmulti" in which:
        # the mixnet's dual-base commitment ladder g^{e0} h^{e1}
        # (group_jax.fixed_multi_pow) vs the same product through the
        # variable-base shared-base ladder (multi_powmod + mulmod): the
        # fixed-base tables turn ~2x336 montmuls into 2x32 gathers + 63
        # multiplies per element
        E2 = jnp.stack([E, E[::-1]], axis=1)          # (B, 2, ne)
        tabs = jnp.stack([ops.fixed_table(g.g), ops.fixed_table(bases[0])])
        dt = _timeit(ops._fixed_multi_pow_j, tabs, E2)
        print(f"fix2exp: {dt*1e3:8.2f} ms  "
              f"{B/dt:12.0f} el/s  {dt/B*1e6:8.1f} us/el  "
              f"(fixed-base dual ladder)")
        gl = jnp.broadcast_to(jnp.asarray(ops.to_limbs_p([g.g])[0]),
                              (B, ops.n))
        dt_var = _timeit(lambda: ops._mulmod_j(
            ops._powmod_j(gl, E), ops._powmod_j(A, E[::-1])))
        print(f"var2exp: {dt_var*1e3:8.2f} ms  "
              f"{B/dt_var:12.0f} el/s  {dt_var/B*1e6:8.1f} us/el  "
              f"(variable-base ladders; fixed is {dt_var/dt:.1f}x faster)")
    if "residue" in which:
        q_exp = jnp.broadcast_to(
            jnp.asarray(bn.int_to_limbs(g.q, ops.ne)), (B, ops.ne))
        dt = _timeit(ops._verify_residue_j, A, q_exp)
        print(f"residue: {dt*1e3:8.2f} ms  "
              f"{B/dt:12.0f} el/s  {dt/B*1e6:8.1f} us/el")
    if "msm" in which:
        # the RLC verify plane's variable-base accumulation: one
        # Pippenger MSM (host digit prep + device buckets) vs B
        # independent 256-bit ladders folded through a product tree
        An = np.asarray(A)
        dt = _timeit(lambda: ops.msm(An, exps))
        print(f"msm    : {dt*1e3:8.2f} ms  "
              f"{B/dt:12.0f} el/s  {dt/B*1e6:8.1f} us/el")
        dt_var = _timeit(lambda: ops.prod_reduce(
            np.asarray(ops.powmod(A, E))[:, None, :]))
        print(f"ladders: {dt_var*1e3:8.2f} ms  "
              f"{B/dt_var:12.0f} el/s  {dt_var/B*1e6:8.1f} us/el  "
              f"(per-row powmod + product; msm is {dt_var/dt:.1f}x faster)")
    if "fused" in which:
        # the production pipelines: fused selection encryption and fused
        # V4 verification, rows/s at this batch shape (selection rows;
        # /3 for ballots at 2 selections + 1 placeholder)
        from electionguard_tpu.core.group_jax import jax_exp_ops
        from electionguard_tpu.core.hash import _encode
        from electionguard_tpu.encrypt.fused import get_fused_encryptor
        from electionguard_tpu.verify.fused import get_fused

        fe = get_fused_encryptor(ops, jax_exp_ops(g))
        fv = get_fused(ops)
        K = bases[0]
        prefix = _encode(7)
        seed_row = np.zeros(32, np.uint8)
        bids = rng.integers(0, 256, (B, 32), dtype=np.uint8)
        ords = np.arange(B, dtype=np.uint32)
        votes = (np.arange(B) % 2).astype(np.int64)
        alpha, beta, _, CR, VR, CF, VF = fe.encrypt_selections(
            seed_row, bids, ords, votes, K, prefix)  # warm-up + outputs
        dt = _timeit(lambda: fe.encrypt_selections(
            seed_row, bids, ords, votes, K, prefix))
        print(f"enc-sel: {dt*1e3:8.2f} ms  "
              f"{B/dt:12.0f} row/s  {dt/B*1e6:8.1f} us/row")
        v1m = (votes == 1)[:, None]
        c0 = np.where(v1m, CF, CR)
        v0 = np.where(v1m, VF, VR)
        c1 = np.where(v1m, CR, CF)
        v1_ = np.where(v1m, VR, VF)
        ok = np.asarray(fv.v4_selections(
            alpha, beta, c0, v0, c1, v1_, K, prefix))
        assert ok.all(), "fused V4 rejected fused-encrypted rows — " \
            "refusing to time a broken pipeline"
        dt = _timeit(lambda: fv.v4_selections(
            alpha, beta, c0, v0, c1, v1_, K, prefix))
        print(f"ver-v4 : {dt*1e3:8.2f} ms  "
              f"{B/dt:12.0f} row/s  {dt/B*1e6:8.1f} us/row")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

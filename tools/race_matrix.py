#!/usr/bin/env python3
"""race_matrix — the dynamic race-detector seed sweep runner.

Usage::

    python tools/race_matrix.py --seeds 20             # quick sweep
    python tools/race_matrix.py --seeds 200 --json     # + RACE_RESULTS.json
    python tools/race_matrix.py --seeds 200 --procs 8
    python tools/race_matrix.py --adversaries          # attack matrix too

Each seed runs the full virtual-cluster workflow TWICE with the
happens-before + lockset monitor attached (``run_sim(race=True)``):
once under the default uniform-random scheduler and once under PCT
(priority-based probabilistic concurrency testing, own RNG stream), so
rare interleavings get systematically explored.  Every oracle still
runs — a ``race:`` violation is an oracle class like any other — and
failing seeds are ddmin-shrunk (race-aware probes replay with the same
strategy) to minimal replayable schedules; a race that reproduces with
NO faults shrinks to the empty schedule, leaving just the racing task
pair.

The sweep also runs the detector's self-test fixtures (``race-hb``,
``race-lockset``, ``race-handoff`` plants) and asserts: the HB
detector and the lockset heuristic each fire at their exact planted
access pair, the handoff guard stays green, and a same-seed rerun is
bit-for-bit identical (trace hash).  The fixture repros land in the
artifact so tests can replay them.

``--json`` writes the tracked RACE_RESULTS.json artifact.  Trace
hashes are deterministic per process; to compare across processes pin
PYTHONHASHSEED.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
from dataclasses import asdict

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(tempfile.gettempdir(), "egtpu-jax-cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.1")
# instrumented runs + N-way cold jit compiles contend for the CPU; a
# slow first run is not a deadlock (workers inherit this)
os.environ.setdefault("EGTPU_SIM_WATCHDOG_S", "300")

STRATEGIES = ("random", "pct")

#: fixed coordinates of the self-test fixture runs recorded in the
#: artifact (tests replay these bit-for-bit)
SELFTEST_SEED = 3


def _config(fast: bool):
    from electionguard_tpu.sim.cluster import SimConfig
    return SimConfig(n_mix_stages=1) if fast else SimConfig()


def _sweep(start: int, count: int, fast: bool,
           shrink_budget: int | None, adversaries: bool = False) -> dict:
    """Race-sweep seeds [start, start+count) in THIS process."""
    from electionguard_tpu.sim.explore import run_sim
    from electionguard_tpu.sim.shrink import shrink

    cfg = _config(fast)
    ok = 0
    runs = 0
    events_total = 0
    failures = []
    races: dict[str, dict] = {}
    for seed in range(start, start + count):
        for strategy in STRATEGIES:
            r = run_sim(seed, config=cfg, adversaries=adversaries,
                        race=True, strategy=strategy)
            runs += 1
            events_total += r.race_events
            for d in r.races:
                key = (f"{d['kind']} {d['pair']} {d['var']} "
                       f"{d['prior']['site']} vs {d['current']['site']}")
                e = races.setdefault(key, {"n": 0, "first": None,
                                           "report": d})
                e["n"] += 1
                if e["first"] is None:
                    e["first"] = {"seed": seed, "strategy": strategy}
            if r.ok:
                ok += 1
                continue
            entry = {
                "seed": seed,
                "strategy": strategy,
                "violations": r.violations,
                "schedule": [asdict(e) for e in r.schedule],
                "trace_hash": r.trace_hash,
            }
            if r.schedule:
                res = shrink(seed, r.schedule, config=cfg,
                             budget=shrink_budget, race=True,
                             strategy=strategy)
                entry["shrunk_schedule"] = [asdict(e)
                                            for e in res.schedule]
                entry["shrunk_violations"] = res.violations
                entry["shrink_runs"] = res.runs
            failures.append(entry)
            print(f"FAIL {r.summary()}", file=sys.stderr)
    return {"ok": ok, "runs": runs, "failures": failures,
            "events_total": events_total, "races": races}


def _sweep_procs(start: int, count: int, procs: int, fast: bool,
                 shrink_budget: int | None,
                 adversaries: bool = False) -> dict:
    """Shard the seed range over worker subprocesses, merge chunks."""
    per = (count + procs - 1) // procs
    jobs = []
    tmpdir = tempfile.mkdtemp(prefix="egtpu-race-matrix-")
    for i in range(procs):
        s = start + i * per
        n = min(per, start + count - s)
        if n <= 0:
            break
        out = os.path.join(tmpdir, f"chunk-{i}.json")
        cmd = [sys.executable, os.path.abspath(__file__),
               "--start", str(s), "--seeds", str(n),
               "--chunk-worker", out]
        if fast:
            cmd.append("--fast")
        if adversaries:
            cmd.append("--adversaries")
        if shrink_budget is not None:
            cmd += ["--shrink-budget", str(shrink_budget)]
        jobs.append((subprocess.Popen(cmd), out))
    merged = {"ok": 0, "runs": 0, "failures": [], "events_total": 0,
              "races": {}}
    rc = 0
    for proc, out in jobs:
        rc |= proc.wait()
        if os.path.exists(out):
            chunk = json.load(open(out))
            merged["ok"] += chunk["ok"]
            merged["runs"] += chunk["runs"]
            merged["events_total"] += chunk["events_total"]
            merged["failures"].extend(chunk["failures"])
            for key, e in chunk["races"].items():
                m = merged["races"].setdefault(
                    key, {"n": 0, "first": e["first"],
                          "report": e["report"]})
                m["n"] += e["n"]
    if rc:
        raise SystemExit(f"a sweep worker failed (exit {rc})")
    merged["failures"].sort(key=lambda f: (f["seed"], f["strategy"]))
    return merged


def _selftest(fast: bool, shrink_budget: int | None) -> dict:
    """Planted-fixture gate: HB and lockset fire at their exact pairs,
    the handoff guard stays green, repros shrink to minimal schedules,
    and same-seed reruns are bit-for-bit identical."""
    from electionguard_tpu.sim.explore import run_sim
    from electionguard_tpu.sim.shrink import shrink

    cfg = _config(fast)
    out = {}
    expect = {
        "race-hb": ("hb", "RaceProbeBox.shared"),
        "race-lockset": ("lockset", "RaceProbeBox.shared"),
        "race-handoff": None,
    }
    all_ok = True
    for plant, want in expect.items():
        entry = {"plant": plant, "seed": SELFTEST_SEED, "strategy": "pct"}
        r = run_sim(SELFTEST_SEED, plant=(plant,), config=cfg,
                    race=True, strategy="pct")
        r2 = run_sim(SELFTEST_SEED, plant=(plant,), config=cfg,
                     race=True, strategy="pct")
        entry["deterministic"] = r.trace_hash == r2.trace_hash
        if want is None:
            entry["ok"] = entry["deterministic"] and r.ok
            entry["races"] = list(r.races)
        else:
            kind, var = want
            hits = [d for d in r.races
                    if d["kind"] == kind and d["var"] == var]
            entry["detected"] = bool(hits)
            entry["races"] = hits
            res = shrink(SELFTEST_SEED, r.schedule, plant=(plant,),
                         config=cfg, budget=shrink_budget,
                         oracle_classes=frozenset(["race"]),
                         race=True, strategy="pct")
            rr = run_sim(SELFTEST_SEED, schedule=res.schedule,
                         plant=(plant,), config=cfg,
                         race=True, strategy="pct")
            entry["shrunk_schedule"] = [asdict(e) for e in res.schedule]
            entry["shrunk_violations"] = res.violations
            entry["repro_trace_hash"] = rr.trace_hash
            entry["ok"] = (entry["deterministic"] and bool(hits)
                           and bool(res.violations))
        all_ok = all_ok and entry["ok"]
        print(f"  selftest {plant}: "
              f"{'ok' if entry['ok'] else 'FAIL'} "
              f"(deterministic={entry['deterministic']})")
        out[plant] = entry
    out["ok"] = all_ok
    return out


def main(argv=None) -> int:
    from electionguard_tpu.utils import knobs

    ap = argparse.ArgumentParser(
        prog="race_matrix", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--seeds", type=int, default=None,
                    help="how many seeds to sweep (default "
                         "EGTPU_SIM_SEEDS); each seed runs once per "
                         "strategy (random, pct)")
    ap.add_argument("--start", type=int,
                    default=knobs.get_int("EGTPU_SIM_SEED"),
                    help="first seed")
    ap.add_argument("--procs", type=int, default=1,
                    help="worker subprocesses to shard the range over")
    ap.add_argument("--fast", action="store_true",
                    help="1 mix stage instead of 2")
    ap.add_argument("--adversaries", action="store_true",
                    help="compose the in-protocol attack corpus into "
                         "every run (stream 5)")
    ap.add_argument("--shrink-budget", type=int, default=None,
                    help="probe-run cap per failing-schedule shrink")
    ap.add_argument("--no-selftest", action="store_true",
                    help="skip the planted-fixture gate")
    ap.add_argument("--json", nargs="?", const=os.path.join(
                        REPO_ROOT, "RACE_RESULTS.json"), default=None,
                    metavar="PATH",
                    help="write the sweep artifact (default "
                         "RACE_RESULTS.json at the repo root)")
    ap.add_argument("--chunk-worker", metavar="PATH", default=None,
                    help=argparse.SUPPRESS)   # internal: emit one chunk
    args = ap.parse_args(argv)
    if args.seeds is None:
        args.seeds = knobs.get_int("EGTPU_SIM_SEEDS")

    t0 = time.time()
    if args.chunk_worker:
        chunk = _sweep(args.start, args.seeds, args.fast,
                       args.shrink_budget, args.adversaries)
        with open(args.chunk_worker, "w") as f:
            json.dump(chunk, f)
        return 0
    if args.procs > 1:
        merged = _sweep_procs(args.start, args.seeds, args.procs,
                              args.fast, args.shrink_budget,
                              args.adversaries)
    else:
        merged = _sweep(args.start, args.seeds, args.fast,
                        args.shrink_budget, args.adversaries)
    selftest = ({"ok": True, "skipped": True} if args.no_selftest
                else _selftest(args.fast, args.shrink_budget))
    wall = time.time() - t0

    n_runs = merged["runs"]
    result = {
        "generated_by": "tools/race_matrix.py",
        "seed_start": args.start,
        "n_seeds": args.seeds,
        "strategies": list(STRATEGIES),
        "adversaries": args.adversaries,
        "profile": "fast" if args.fast else "default",
        "procs": args.procs,
        "runs": n_runs,
        "ok": merged["ok"],
        "failed": len(merged["failures"]),
        "failures": merged["failures"],
        "races_distinct": len(merged["races"]),
        "races": {k: merged["races"][k]
                  for k in sorted(merged["races"])},
        "monitor_events_total": merged["events_total"],
        "selftest": selftest,
        "waivers": 0,   # the baseline ships empty; the gate keeps it so
        "wall_s": round(wall, 1),
        "runs_per_s": round(n_runs / wall, 2) if wall else None,
    }
    print(f"{merged['ok']}/{n_runs} runs green "
          f"({args.seeds} seeds x {len(STRATEGIES)} strategies), "
          f"{len(merged['races'])} distinct races, "
          f"{merged['events_total']} monitor events, {wall:.1f}s")
    for key, e in sorted(merged["races"].items()):
        print(f"  race x{e['n']}: {key} (first seed "
              f"{e['first']['seed']}/{e['first']['strategy']})")
    for f in merged["failures"]:
        shrunk = f.get("shrunk_schedule")
        print(f"  seed {f['seed']}/{f['strategy']}: "
              f"{f['violations'][0]}"
              + (f"  [shrunk to {len(shrunk)} events]"
                 if shrunk is not None else ""))
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(result, fh, indent=2, sort_keys=True)
            fh.write("\n")
        print(f"wrote {os.path.relpath(args.json)}")
    return 1 if (merged["failures"] or not selftest["ok"]) else 0


if __name__ == "__main__":
    sys.exit(main())

"""cProfile the full Verifier pass (and optionally encrypt) at a small
ballot count to expose host-side hotspots: limb codecs, Python loops,
hash glue, d2h transfers.  Run after bench.py so compiles are warm.

Usage: python tools/profile_host.py [nballots] [encrypt|verify|both]
"""

from __future__ import annotations

import cProfile
import io
import os
import pstats
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    nballots = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    what = sys.argv[2] if len(sys.argv) > 2 else "both"
    from electionguard_tpu.utils import enable_compile_cache
    enable_compile_cache()

    from electionguard_tpu.ballot.plaintext import RandomBallotProvider
    from electionguard_tpu.core.group import production_group
    from electionguard_tpu.encrypt.encryptor import BatchEncryptor
    from electionguard_tpu.keyceremony.exchange import key_ceremony_exchange
    from electionguard_tpu.keyceremony.trustee import KeyCeremonyTrustee
    from electionguard_tpu.publish.election_record import (ElectionConfig,
                                                           ElectionRecord)
    from electionguard_tpu.tally.accumulate import accumulate_ballots
    from electionguard_tpu.verify.verifier import Verifier
    from electionguard_tpu.workflow.e2e import sample_manifest

    g = production_group()
    manifest = sample_manifest(ncontests=1, nselections=2)
    trustees = [KeyCeremonyTrustee(g, "guardian-0", 1, 1)]
    init = key_ceremony_exchange(trustees, g).make_election_initialized(
        ElectionConfig(manifest, 1, 1), {"created_by": "profile"})
    ballots = list(RandomBallotProvider(manifest, nballots, seed=1).ballots())

    def report(tag, pr, dt):
        s = io.StringIO()
        ps = pstats.Stats(pr, stream=s).sort_stats("cumulative")
        ps.print_stats(25)
        print(f"==== {tag}: {dt:.2f}s for {nballots} ballots "
              f"({nballots / dt:.1f}/s) ====")
        print("\n".join(s.getvalue().splitlines()[:40]))

    enc = BatchEncryptor(init, g)
    if what in ("encrypt", "both"):
        pr = cProfile.Profile()
        t0 = time.time()
        pr.enable()
        encrypted, invalid = enc.encrypt_ballots(ballots, seed=g.int_to_q(42))
        pr.disable()
        report("encrypt", pr, time.time() - t0)
    else:
        encrypted, invalid = enc.encrypt_ballots(ballots, seed=g.int_to_q(42))
    assert not invalid

    tally_result = accumulate_ballots(init, encrypted)
    record = ElectionRecord(election_init=init, encrypted_ballots=encrypted,
                            tally_result=tally_result)
    Verifier(record, g).verify()  # warm pass
    if what in ("verify", "both"):
        pr = cProfile.Profile()
        t0 = time.time()
        pr.enable()
        res = Verifier(record, g).verify()
        pr.disable()
        assert res.ok, res.summary()
        report("verify", pr, time.time() - t0)
    return 0


if __name__ == "__main__":
    sys.exit(main())

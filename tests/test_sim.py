"""Deterministic simulation testing: determinism pins, oracle
sensitivity, shrinker minimality, and the pinned seed sweep.

The sweep tier here (seeds 0..19) is the tier-1 guarantee: every pinned
seed's full virtual-cluster run — key ceremony, serving, federated mix,
compensated decryption, independent verification — must stay green with
every oracle passing.  ``tools/sim_matrix.py`` runs the wide sweep and
records it in SIM_RESULTS.json.

Trace hashes are compared across runs INSIDE one process: the sha256
event-trace hash is seed-deterministic, but string hashing of dict keys
makes it sensitive to PYTHONHASHSEED across processes (pin that env var
to compare hashes between machines or CI runs).
"""

import pytest

from electionguard_tpu.sim.explore import explore, run_sim
from electionguard_tpu.sim.schedule import (FaultEvent, from_json,
                                            generate_schedule, to_json)
from electionguard_tpu.sim.shrink import shrink

# the planted exactly-once bug: a dropped encryptBallot response whose
# retry-dedup path "eats" the committed record entry
DROP_ENC = FaultEvent("drop_response", method="encryptBallot", nth=1)

NOISE = [
    FaultEvent("latency", method="pullRows", nth=1, seconds=0.2),
    FaultEvent("unavailable", method="sendPublicKeys", nth=1),
    FaultEvent("latency", method="directDecrypt", nth=1, seconds=0.1),
    FaultEvent("duplicate", seconds=0.02),
    FaultEvent("unavailable", method="shuffleStage", nth=1),
]


def _classes(report):
    return {v.split(":", 1)[0] for v in report.violations}


# ---------------------------------------------------------------- determinism

def test_same_seed_replays_bit_for_bit():
    """One seed fully determines the execution: the event-trace hash,
    event count, and virtual duration replay identically."""
    a = run_sim(7)
    b = run_sim(7)
    assert a.ok and b.ok
    assert a.trace_hash == b.trace_hash
    assert (a.events, a.virtual_s) == (b.events, b.virtual_s)
    assert a.schedule == b.schedule
    c = run_sim(8)
    assert c.trace_hash != a.trace_hash


def test_replay_from_schedule_json_round_trip():
    """A report's schedule JSON replays the exact same execution — the
    repro artifact in SIM_RESULTS.json is sufficient to reproduce."""
    a = run_sim(1)          # seed 1 draws a non-empty fault schedule
    assert a.schedule, "pin a seed whose generated schedule is non-empty"
    b = run_sim(1, schedule=from_json(a.schedule_json()))
    assert b.trace_hash == a.trace_hash


def test_schedule_generation_is_stream_isolated():
    """Schedule JSON round-trips losslessly and generation is a pure
    function of its RNG stream."""
    import random
    s1 = generate_schedule(random.Random(123))
    s2 = generate_schedule(random.Random(123))
    assert s1 == s2
    assert from_json(to_json(s1)) == s1


# ------------------------------------------------------------ oracle coverage
# Each oracle must actually fire: run with a hand-planted known-bad
# behavior and assert the violation class.  A sweep whose oracles can
# never trip is theater.

def test_oracle_catches_lost_ballot():
    r = run_sim(3, schedule=[DROP_ENC], plant=("lost-ballot",))
    assert not r.ok
    assert "no_ballot_lost" in _classes(r)
    assert any("missing from the record" in v for v in r.violations)


def test_oracle_catches_chain_break():
    r = run_sim(3, schedule=[], plant=("chain-break",))
    assert "chain_contiguous" in _classes(r)


def test_oracle_catches_tampered_ballot():
    """Swapped selection ciphertexts pass structural checks but the
    independent Verifier must reject the record."""
    r = run_sim(3, schedule=[], plant=("tamper-ballot",))
    assert "verifier_green" in _classes(r)


def test_oracle_catches_tampered_tally():
    r = run_sim(3, schedule=[], plant=("tamper-tally",))
    assert "quorum_tally" in _classes(r)


def test_oracle_catches_wedged_workflow():
    """A livelocked task trips the virtual-time horizon — in virtual
    time, so the test itself is instant."""
    r = run_sim(3, schedule=[], plant=("wedge",))
    assert _classes(r) == {"liveness"}


# ------------------------------------------------------- live verification

def test_live_verify_plant_converges_green():
    """The live-verify leg replays the finished record as a growing
    stream through torn tails and SIGKILL/checkpoint resumes; on a
    clean run every oracle (including live_convergence) stays green and
    the report carries the agreed commitment root."""
    r = run_sim(3, schedule=[], plant=("live-verify",))
    assert r.ok, r.violations
    assert r.live["converged"] and r.live["live_ok"]
    assert len(r.live["live_root"]) == 64
    assert all(r.live["live_accepts"])
    # seed 3's stream 7 draws actually exercise the torture paths
    assert r.live["crashes"] >= 1 and r.live["torn"] >= 1


def test_live_verify_catches_tamper_at_equal_or_earlier_chunk():
    """A tampered ballot turns the run red through the usual oracles,
    while the live pass REJECTS the tampered chunk mid-stream — and the
    live_convergence oracle holds: same verdict, same accept set, same
    root as the terminal fold, detection no later than batch."""
    r = run_sim(3, schedule=[], plant=("live-verify", "tamper-ballot"))
    assert "verifier_green" in _classes(r)
    assert "live_convergence" not in _classes(r)
    assert r.live["converged"] and not r.live["live_ok"]
    assert False in r.live["live_accepts"]


def test_live_convergence_oracle_fires_on_divergence():
    """The oracle itself must be able to trip: a rigged report with a
    flipped accept bit / different root is a violation (anything less
    and the sweep's bit-identical claim is theater)."""
    from electionguard_tpu.sim import oracle
    from electionguard_tpu.sim.cluster import SimOutcome

    r = run_sim(3, schedule=[], plant=("live-verify",))
    rep = dict(r.live)
    out = SimOutcome(completed=True)
    base = {
        "chunk": rep["chunk"], "crashes": 0, "torn": 0,
        "n_frames": rep["n_frames"],
        "live_ok": True, "batch_ok": True,
        "live_checks": {"V4": True}, "batch_checks": {"V4": True},
        "live_errors": [], "batch_errors": [],
        "live_accepts": [True, True], "batch_accepts": [True, True],
        "live_first_reject": None, "batch_first_reject": None,
        "live_root": rep["live_root"], "batch_root": rep["live_root"],
        "live_head": "00", "batch_head": "00",
    }
    out.live_report = dict(base, live_accepts=[True, False],
                           live_first_reject=1)
    flipped = [v for v in oracle._live_convergence(out)]
    assert any("chunk-accept set diverged" in v for v in flipped)
    out.live_report = dict(base, batch_root="ab" * 32)
    assert any("commitment diverged" in v
               for v in oracle._live_convergence(out))
    out.live_report = dict(base, batch_first_reject=0,
                           live_first_reject=1,
                           batch_accepts=base["live_accepts"])
    assert any("equal-or-earlier" in v
               for v in oracle._live_convergence(out))
    out.live_report = dict(base)
    assert oracle._live_convergence(out) == []


# ------------------------------------------------------------------ shrinking

def test_shrinker_minimizes_planted_lost_ballot():
    """ddmin + greedy strips all five noise events: the minimal repro
    for the planted exactly-once bug is the single dropped
    encryptBallot response."""
    padded = NOISE[:2] + [DROP_ENC] + NOISE[2:]
    res = shrink(3, padded, plant=("lost-ballot",))
    assert res.schedule == [DROP_ENC]
    assert not res.exhausted
    assert any(v.startswith("no_ballot_lost") for v in res.violations)
    # the repro artifact round-trips
    assert from_json(res.repro_json()) == [DROP_ENC]


def test_shrinker_returns_empty_violations_for_green_schedule():
    res = shrink(3, [NOISE[0]], plant=())
    assert res.violations == []
    assert res.runs == 1


def test_shrinker_respects_budget():
    padded = NOISE + [DROP_ENC]
    res = shrink(3, padded, plant=("lost-ballot",), budget=2)
    assert res.runs <= 2
    # budget too small to finish: flagged, never silently "minimal"
    assert res.exhausted or res.schedule == [DROP_ENC]


# ------------------------------------------------------------------ the sweep

def test_pinned_seed_sweep_is_green():
    """Tier-1 sweep: 20 pinned seeds, every oracle green, all
    executions distinct (the schedules actually vary)."""
    reports = explore(range(20))
    bad = [r.summary() for r in reports if not r.ok]
    assert not bad, f"sim sweep failures: {bad}"
    assert len({r.trace_hash for r in reports}) == len(reports)
    # the generator exercised real fault schedules, not 20 quiet runs
    assert sum(len(r.schedule) for r in reports) >= 10


@pytest.mark.slow
def test_wide_seed_sweep_is_green():
    """The wide sweep (seeds 20..119); tools/sim_matrix.py goes wider
    still and records SIM_RESULTS.json."""
    reports = explore(range(20, 120))
    bad = [r.summary() for r in reports if not r.ok]
    assert not bad, f"sim sweep failures: {bad}"


# ------------------------------------------------------- regression pins

def test_pinned_regression_compound_faults_ceremony_survives():
    """Seeds 77, 108, 347 of the first 1000-seed sweep: compound faults
    exhausted a SINGLE rpc's sub-second retry budget mid-key-ceremony
    and the whole election died — seed 108 (shrunk: conn_death +
    drop_response on registerTrustee) killed the trustee process on
    registration failure, wedging the coordinator against a server
    whose trustee never materializes; seeds 77/347 (guardian crash +
    injected UNAVAILABLE + conn_death on receiveSecretKeyShare) made
    the coordinator abort the ceremony on one transport-dead idempotent
    step.  Fixed by protocol-level re-attempts: nonce-idempotent
    registration retry in KeyCeremonyTrusteeServer and transport-death
    step retry in key_ceremony_exchange.  These seeds must stay green."""
    for seed in (77, 108, 347):
        r = run_sim(seed)
        assert r.ok, r.summary()


# ------------------------------------------------------- regression (seed 0+)

def test_fused_reenc_program_is_shared_across_keys(tgroup):
    """Pinned regression for a real bug the sweep surfaced: every sim
    seed runs a fresh key ceremony, and the mix stage's fused
    re-encryption program used to bake the election key table in as a
    closure constant — so EVERY seed recompiled the whole fused pipeline
    (~7s/seed, 34x slower sweeps; first seen as seed 0 vs seed 1 wall
    times).  The key table must be a traced argument: shufflers for
    different keys on one group share ONE jitted program."""
    from electionguard_tpu.mixnet.shuffle import Shuffler
    g = tgroup
    k1 = pow(g.g, 5, g.p)
    k2 = pow(g.g, 9, g.p)
    s1 = Shuffler(g, k1)
    s2 = Shuffler(g, k2)
    assert s1.ops is s2.ops
    assert s1._reenc_j is s2._reenc_j, (
        "fused re-encryption recompiles per election key")

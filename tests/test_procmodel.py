"""Process-model sim layer: SimProcess lifecycle, devicemodel cost
determinism, virtual-time chaos twins, and million-ballot virtual
elections on the virtual clock.

Three tiers of guarantee:

* **procmodel mechanics** — the RunCommand-mirror control surface
  (`python_module`/`kill`/`kill_hard`/`restart`/`wait_for`/`poll`)
  drives whole simulated processes as scheduler events, so every
  spawn/SIGKILL/restart lands in the sha256 trace hash and a same-seed
  rerun replays the chaos story bit-for-bit.
* **virtual-time chaos twins** — the real-time SIGKILL/restart drills
  (`workflow/e2e.py -chaosRestartGuardian`, mixfed kill/requeue) run
  here on the virtual clock with the SAME oracles and real tiny-group
  crypto, but zero real sleeps; the subprocess originals stay under the
  `e2e` marker in test_e2e_subprocess.py as the reality anchor.
* **virtual elections at scale** — `sim/election.py` plays out a
  10^6-ballot election (reduced event rate in tier-1, the full default
  spec `@slow`), gated against the analytic capacity model.

Trace hashes are compared across runs INSIDE one process (see
test_sim.py on PYTHONHASHSEED).
"""

import dataclasses
import hashlib

import numpy as np
import pytest

from electionguard_tpu.obs import capacity
from electionguard_tpu.sim import devicemodel, procmodel
from electionguard_tpu.sim.devicemodel import DeviceModel, DevicePlane
from electionguard_tpu.sim.election import (ElectionSpec, Journal,
                                            run_virtual_election)
from electionguard_tpu.sim.procmodel import (EXIT_KILL, EXIT_TERM, EXITED,
                                             KILLED, RUNNING, SimProcess,
                                             wait_all)
from electionguard_tpu.sim.scheduler import SimClock, SimScheduler
from electionguard_tpu.utils import clock, devicetime


def _run(main, seed=1, horizon=1e6):
    """One procmodel sim: scheduler + clock + ambient install, main on
    the driver node, full teardown; returns the finished scheduler."""
    sched = SimScheduler(seed=seed, horizon=horizon)
    clock.install(SimClock(sched))
    procmodel.install(sched)
    try:
        sched.run(main)
    finally:
        procmodel.uninstall()
        clock.uninstall()
    return sched


def _kinds(sched):
    return [k for _t, k, _d in sched.trace]


def _events(sched, kind):
    return [d for _t, k, d in sched.trace if k == kind]


# ===================================================================
# SimProcess lifecycle mechanics (the RunCommand mirror)
# ===================================================================

def test_lifecycle_events_land_in_trace():
    """SPAWNING -> RUNNING -> EXITED, with every transition a scheduler
    event covered by the trace hash."""
    seen = {}

    def entry(flags, env):
        seen["flags"], seen["env"] = flags, env
        clock.sleep(2.0)
        return 0

    def main():
        p = SimProcess("svc", entry, ["-x", "1"], env={"K": "v"})
        assert p.state in ("SPAWNING", RUNNING)
        assert p.wait_for(100.0) == 0
        assert p.state == EXITED and p.poll() == 0
        seen["proc"] = p

    sched = _run(main)
    assert seen["flags"] == ["-x", "1"]
    assert seen["env"]["K"] == "v"
    assert _events(sched, "proc-spawn") == ["svc gen=0"]
    assert _events(sched, "proc-running") == ["svc"]
    assert _events(sched, "proc-exit") == ["svc rc=0"]
    # the lifecycle log carries virtual timestamps
    assert [w for _t, w in seen["proc"].log] == \
        ["spawn", "running", "exit rc=0"]


def test_python_module_mirrors_runcommand(tmp_path):
    """The registry twin of ``python -m module``: env snapshot gets the
    EGTPU_OBS_PROC identity, unknown modules fail loudly."""
    procmodel.register_entry("egtpu.test.echo",
                             lambda flags, env: int(flags[0]))

    def main():
        p = SimProcess.python_module("echo-1", "egtpu.test.echo", ["7"],
                                     str(tmp_path))
        assert p.env()["EGTPU_OBS_PROC"] == "echo-1"
        assert p.wait_for(10.0) == 7   # nonzero rc propagates

    _run(main)
    with pytest.raises(KeyError, match="no in-sim entry"):
        procmodel.entry_for("egtpu.test.unregistered")


def test_kill_and_kill_hard_signal_codes():
    """kill()/kill_hard() tear the node's tasks down at their next
    yield point and report signal-style exit codes immediately."""
    def spin(flags, env):
        while True:
            clock.sleep(1.0)

    def main():
        a = SimProcess("spin-a", spin, [])
        b = SimProcess("spin-b", spin, [])
        clock.sleep(3.0)
        a.kill()
        b.kill_hard()
        assert (a.state, a.poll()) == (KILLED, EXIT_TERM)
        assert (b.state, b.poll()) == (KILLED, EXIT_KILL)
        a.kill_hard()           # idempotent: already down
        assert a.poll() == EXIT_TERM
        clock.sleep(5.0)        # the unwind produces no exit event

    sched = _run(main)
    assert _events(sched, "proc-kill") == ["spin-a"]
    assert _events(sched, "proc-kill-hard") == ["spin-b"]
    assert _events(sched, "proc-exit") == []


def test_restart_replays_entry_with_env_snapshot():
    """restart() requires the previous incarnation down, bumps the
    generation, and replays the entry with the CURRENT env snapshot."""
    incarnations = []

    def entry(flags, env):
        incarnations.append(dict(env))
        while True:
            clock.sleep(1.0)

    def main():
        p = SimProcess("svc", entry, [], env={"MODE": "first"})
        clock.sleep(1.5)
        with pytest.raises(RuntimeError, match="still running"):
            p.restart()
        p.kill_hard()
        p._env["MODE"] = "second"
        p.restart()
        clock.sleep(1.5)
        assert p.state == RUNNING
        p.kill_hard()

    sched = _run(main)
    assert [e["MODE"] for e in incarnations] == ["first", "second"]
    assert _events(sched, "proc-restart") == ["svc gen=1"]
    assert _events(sched, "proc-spawn") == ["svc gen=0", "svc gen=1"]


def test_restart_on_exit_strips_fault_env_and_waits_downtime():
    """The chaos-watcher twin: first exit triggers one restart with the
    fault knob stripped, after the virtual downtime."""
    runs = []

    def entry(flags, env):
        runs.append((clock.monotonic(), dict(env)))
        if env.get("EGTPU_FAULT"):
            raise SystemExit(3)
        return 0

    def main():
        p = SimProcess("flaky", entry, [], env={"EGTPU_FAULT": "1"})
        p.restart_on_exit(strip_env=("EGTPU_FAULT",), downtime_s=4.0)
        clock.sleep(20.0)
        assert (p.state, p.poll()) == (EXITED, 0)

    sched = _run(main)
    assert len(runs) == 2
    assert "EGTPU_FAULT" not in runs[1][1]
    assert runs[1][0] - runs[0][0] >= 4.0       # virtual downtime held
    assert _events(sched, "proc-exit") == ["flaky rc=3", "flaky rc=0"]


def test_wait_for_timeout_and_wait_all_kills_stragglers():
    def quick(flags, env):
        clock.sleep(1.0)
        return 0

    def forever(flags, env):
        while True:
            clock.sleep(1.0)

    def main():
        p = SimProcess("slowpoke", forever, [])
        assert p.wait_for(5.0) is None          # virtual timeout
        q = SimProcess("quick", quick, [])
        assert not wait_all([q, p], timeout=10.0)
        assert q.poll() == 0
        assert (p.state, p.poll()) == (KILLED, EXIT_TERM)

    _run(main)


def test_kill_restart_schedule_replays_bit_for_bit():
    """The tentpole determinism pin: a whole kill/restart chaos story
    (spawn, mid-flight SIGKILL, downtime, restart, drain) replays to
    the identical trace hash under the same seed, and a different seed
    diverges."""
    def story(seed):
        done = []

        def entry(flags, env):
            for i in range(10):
                clock.sleep(1.0)
                done.append(i)
            return 0

        def main():
            p = SimProcess("svc", entry, [])
            p.restart_on_exit(downtime_s=2.0)
            clock.sleep(3.5)
            p.kill_hard()
            clock.sleep(30.0)
            assert (p.state, p.poll()) == (EXITED, 0)

        return _run(main, seed=seed).trace_hash()

    assert story(11) == story(11)
    assert story(11) != story(12)


# ===================================================================
# devicemodel: fitted per-op cost as virtual clock advance
# ===================================================================

def _toy_model():
    return capacity.CostModel(
        platform="test",
        powmod_per_s={"cios": capacity.Estimate(1000.0)},
        fixed_per_s={"cios": capacity.Estimate(4000.0)},
        rpc_per_ballot_s=capacity.Estimate(0.002),
        occupancy=capacity.Estimate(0.8),
        serial_fraction=capacity.Estimate(0.1))


def test_devicemodel_rate_algebra_mirrors_capacity_predict():
    """seconds() is exactly capacity.predict's device_s term — same
    rows-per-ballot table, same chips x occupancy deflation, encrypt on
    the fixed-base roofline, everything else on powmod."""
    dm = DeviceModel(_toy_model(), backend="cios", chips=4, workers=8)
    occ = 0.8
    rows = capacity.ROWS_PER_BALLOT["encrypt"] * 100
    assert dm.seconds("encrypt", 100) == pytest.approx(
        rows / (4000.0 * 4 * occ))
    rows = capacity.ROWS_PER_BALLOT["mix_stage"] * 100
    assert dm.seconds("mix_stage", 100) == pytest.approx(
        rows / (1000.0 * 4 * occ))
    # host leg: Amdahl-deflated rpc seconds for ONE worker's drain
    eff = capacity.worker_efficiency(8, 0.1)
    assert dm.host_seconds(1000) == pytest.approx(1000 * 0.002 / eff)
    # determinism: same inputs, same virtual cost, every time
    assert dm.seconds("decrypt", 12345) == dm.seconds("decrypt", 12345)
    with pytest.raises(ValueError, match="no powmod roofline"):
        DeviceModel(_toy_model(), backend="pallas").seconds("decrypt", 1)


def test_device_plane_queueing_serializes_concurrent_charges():
    """Two workers charging the shared plane contend like batches on
    one chip: total busy time is the sum, and each charge begins at the
    plane's busy_until, never inside another's window.  Verify-flavored
    ops land on their own plane (the live-verification chips)."""
    dm = DeviceModel(_toy_model(), backend="cios", chips=1)
    ends = {}

    def worker(name):
        def body():
            dm.charge_seconds("device", 5.0)
            ends[name] = clock.monotonic()
        return body

    def main():
        sched = procmodel.current_scheduler()
        sched.spawn("w1", worker("w1"), node="driver")
        sched.spawn("w2", worker("w2"), node="driver")
        sched.poll_until(lambda: len(ends) == 2, None)
        dm.charge("verify_batch", 100)

    sched = _run(main)
    plane = dm.plane("device")
    assert plane.busy_s == pytest.approx(10.0)
    assert sorted(ends.values()) == pytest.approx([5.0, 10.0])
    assert dm.plane("verify").charges == 1
    assert dm.plane("verify").busy_s > 0
    assert sched.now >= 10.0


def test_devicetime_seam_routes_batch_crypto_entry_points(election):
    """The ambient seam: utils.devicetime is a no-op until a charger is
    installed; with one installed, the batch crypto entry points
    (mixnet run_stage here) charge their semantic op + row count."""
    calls = []
    assert not devicetime.active()
    devicetime.charge("encrypt", 5)            # no-op, no charger
    devicetime.set_charger(lambda op, n: calls.append((op, n)))
    try:
        assert devicetime.active()
        from electionguard_tpu.mixnet.stage import (rows_from_ballots,
                                                    run_stage)
        g, init = election["group"], election["init"]
        pads, datas = rows_from_ballots(election["encrypted"])
        run_stage(g, init.joint_public_key.value,
                  init.extended_base_hash, 0, pads, datas,
                  seed=b"seam-test")
    finally:
        devicetime.set_charger(None)
    assert ("mix_stage", float(len(pads))) in calls
    assert not devicetime.active()


def test_devicemodel_install_routes_seam_to_planes():
    """devicemodel.install(dm) wires the seam to the plane queues (and
    uninstall() restores the no-op)."""
    dm = DeviceModel(_toy_model(), backend="cios", chips=8)

    def main():
        devicemodel.install(dm)
        try:
            devicetime.charge("decrypt", 1000)
            devicetime.charge("verify", 1000)
        finally:
            devicemodel.uninstall()

    _run(main)
    assert dm.plane("device").charges == 1
    assert dm.plane("verify").charges == 1
    assert dm.plane("device").busy_s == pytest.approx(
        dm.seconds("decrypt", 1000))
    assert not devicetime.active()


# ===================================================================
# virtual-time chaos twins of the subprocess drills
# ===================================================================

def test_virtual_guardian_chaos_restart_twin(tgroup):
    """-chaosRestartGuardian on the virtual clock: guardian-1's process
    hard-exits right after it commits + checkpoints its FIRST received
    key share, restart_on_exit strips the fault knob and replays the
    entry from the resume checkpoint — and the ceremony completes with
    the committed share intact, the x-coordinate reclaimed, and the
    joint key identical to the guardians' public-key product.  Same
    oracles as the subprocess drill (test_e2e_subprocess /
    test_faults.test_key_ceremony_survives_trustee_crash_restart),
    zero real sleeps, and the whole story in the trace hash."""
    from electionguard_tpu.keyceremony.trustee import KeyCeremonyTrustee

    g = tgroup
    n = 3
    trustees = [KeyCeremonyTrustee(g, f"guardian-{i}", i + 1, 2)
                for i in range(n)]
    # round 1 outside the sim (the coordinator's registration phase):
    # every guardian validates every other's public keys
    for t in trustees:
        for u in trustees:
            if t is not u:
                assert t.receive_public_keys(u.send_public_keys()).ok
    senders = {t.id: t for t in trustees}
    # resume files: the per-guardian mid-ceremony checkpoint store
    store = {t.id: t.ceremony_state() for t in trustees}
    order = [t.id for t in trustees]
    restored_x = {}

    def guardian_entry_for(name):
        def entry(flags, env):
            # a fresh incarnation has ONLY its resume file: restore,
            # like run_remote_trustee -resumeFile
            me = KeyCeremonyTrustee.from_ceremony_state(g, store[name])
            restored_x[name] = me.x_coordinate
            for sender in order:
                if sender == name or sender in me.received_shares:
                    continue            # replayed rpc dedupes
                share = senders[sender].send_secret_key_share(name)
                assert me.receive_secret_key_share(share).ok
                store[name] = me.ceremony_state()   # commit+checkpoint
                clock.sleep(0.5)
                if env.get("EGTPU_FAULT_PLAN") and \
                        len(me.received_shares) == 1:
                    # crash_after receiveSecretKeyShare on_calls=[1]
                    raise SystemExit(1)
            return 0
        return entry

    def main():
        procs = []
        for t in trustees:
            env = {"EGTPU_FAULT_PLAN": "crash_after"} \
                if t.id == "guardian-1" else {}
            procs.append(SimProcess(t.id, guardian_entry_for(t.id), [],
                                    env=env))
        procs[1].restart_on_exit(strip_env=("EGTPU_FAULT_PLAN",),
                                 downtime_s=1.0)
        assert wait_all([procs[0], procs[2]], timeout=600.0)
        procmodel.current_scheduler().poll_until(
            lambda: procs[1].state == EXITED and procs[1].poll() == 0,
            None)

    sched = _run(main, seed=5)

    # the crash + env-stripped restart is in the story
    assert "guardian-1 rc=1" in _events(sched, "proc-exit")
    assert _events(sched, "proc-restart") == ["guardian-1 gen=1"]
    # the restarted incarnation reclaimed its x, didn't re-register
    assert restored_x["guardian-1"] == 2
    # ceremony oracles, from the resume files (what a real restart has)
    final = {name: KeyCeremonyTrustee.from_ceremony_state(g, st)
             for name, st in store.items()}
    assert all(len(t.received_shares) == n - 1 for t in final.values())
    # the checkpointed first share survived the crash (guardian-0 sends
    # first in the pinned order)
    assert "guardian-0" in final["guardian-1"].received_shares
    joint = g.mult_p(*(t.election_public_key for t in trustees))
    assert g.mult_p(*(t.election_public_key
                      for t in final.values())) == joint


def test_virtual_mixfed_kill_requeue_twin(tgroup, election):
    """The mixfed SIGKILL drill on the virtual clock: mix server 0 is
    SIGKILL'd mid-stage (during its device window, after claiming the
    stage job), the coordinator requeues the stage on the spare exactly
    once, and the finished cascade is bit-identical to the undisturbed
    reference — stage seeds pin the shuffle, so WHO runs a stage must
    not matter.  Mirrors `-chaosKillMixServer` (workflow/e2e.py) with
    the same green-record oracle and no real sleeps."""
    from electionguard_tpu.mixnet.stage import rows_from_ballots, run_stage

    g, init = tgroup, election["init"]
    jpk, qbar = init.joint_public_key.value, init.extended_base_hash
    pads0, datas0 = rows_from_ballots(election["encrypted"])
    seeds = [hashlib.sha256(f"mixtwin|{k}".encode()).digest()
             for k in range(2)]

    # the undisturbed reference cascade (also warms the jit programs the
    # in-sim replay hits)
    ref = []
    p, d = pads0, datas0
    for k in range(2):
        st = run_stage(g, jpk, qbar, k, p, d, seed=seeds[k])
        ref.append(st)
        p, d = st.pads, st.datas

    def story(seed):
        committed: dict[int, object] = {}
        jobs = list(range(2))
        claimed: dict[str, int] = {}

        def server_entry(flags, env):
            me = env["EGTPU_OBS_PROC"]
            while True:
                sched = procmodel.current_scheduler()
                sched.poll_until(
                    lambda: (jobs and len(committed) >= jobs[0])
                    or len(committed) == 2, None)
                if len(committed) == 2:
                    return 0
                k = jobs.pop(0)
                claimed[me] = k
                sched.event("mix-claim", f"stage={k} {me}")
                clock.sleep(2.0)        # the device window: killable
                if k in committed:      # exactly-once under requeue
                    continue
                pin, din = (pads0, datas0) if k == 0 else \
                    (committed[k - 1].pads, committed[k - 1].datas)
                st = run_stage(g, jpk, qbar, k, pin, din, seed=seeds[k])
                committed[k] = st
                claimed.pop(me, None)
                sched.event("mix-commit", f"stage={k} {me}")

        def main():
            sched = procmodel.current_scheduler()
            servers = [SimProcess(f"mix-{i}", server_entry, [],
                                  env={"EGTPU_OBS_PROC": f"mix-{i}"})
                       for i in range(2)]

            def saboteur():
                sched.poll_until(lambda: "mix-0" in claimed, None)
                victim = servers[0]
                victim.kill_hard()
                k = claimed.pop("mix-0", None)
                if k is not None and k not in committed:
                    jobs.insert(0, k)
                    sched.event("requeue", f"stage={k} on spare")

            sched.spawn("saboteur", saboteur, node="driver")
            sched.poll_until(lambda: len(committed) == 2, None)
            servers[1].wait_for(600.0)

        sched = _run(main, seed=seed)
        return sched, committed

    sched, committed = story(seed=3)
    assert _events(sched, "proc-kill-hard") == ["mix-0"]
    assert any("on spare" in d for d in _events(sched, "requeue"))
    # exactly-once: each stage committed once, by the spare
    assert sorted(committed) == [0, 1]
    assert all("mix-1" in d for d in _events(sched, "mix-commit"))
    # green record: bit-identical to the undisturbed reference cascade
    for k in range(2):
        assert np.array_equal(np.asarray(committed[k].pads),
                              np.asarray(ref[k].pads))
        assert np.array_equal(np.asarray(committed[k].datas),
                              np.asarray(ref[k].datas))
    # and the whole kill/requeue story replays bit-for-bit
    sched2, _ = story(seed=3)
    assert sched2.trace_hash() == sched.trace_hash()


# ===================================================================
# virtual elections at scale
# ===================================================================

#: tier-1 reduced event rate: the full 10^6 electorate in 4 micro-
#: batches, 4 representative ballots per shape
_SMOKE = ElectionSpec(ballots=1_000_000, batch=250_000, rep_ballots=4,
                      workers=2, chips=8, chaos_after_batches=2)


def test_million_ballot_smoke_replays_bit_for_bit():
    """A 10^6-ballot virtual election at a reduced event rate: every
    phase plays out, every oracle green, and a same-seed rerun —
    THROUGH a mid-election worker SIGKILL/restart with its in-flight
    batch requeued — reproduces the trace hash bit-for-bit."""
    a = run_virtual_election(seed=3, spec=_SMOKE, chaos=True)
    assert a.ok, a.violations
    assert a.ballots == 1_000_000
    assert a.batches == 4
    names = [s.name for s in a.timeline]
    assert names == ["ceremony", "serve-encrypt", "mix×2", "decrypt",
                     "verify-batch-residual"]
    assert a.virtual_s > 0 and a.device_busy_s["device"] > 0
    assert a.live["live_root"] == a.live["batch_root"]

    b = run_virtual_election(seed=3, spec=_SMOKE, chaos=True)
    assert b.trace_hash == a.trace_hash
    assert (b.events, b.virtual_s, b.journal_head) == \
        (a.events, a.virtual_s, a.journal_head)

    c = run_virtual_election(seed=4, spec=_SMOKE, chaos=True)
    assert c.ok and c.trace_hash != a.trace_hash


def test_chaos_kill_restart_is_in_the_election_trace():
    """chaos=True injects the worker SIGKILL + requeue + restart into
    the event trace (so the two modes hash differently), while the
    journal still admits every ballot exactly once."""
    calm = run_virtual_election(seed=3, spec=_SMOKE, chaos=False)
    chaos = run_virtual_election(seed=3, spec=_SMOKE, chaos=True)
    assert calm.ok and chaos.ok
    assert calm.trace_hash != chaos.trace_hash
    assert calm.ballots == chaos.ballots == 1_000_000


def test_election_spec_from_knobs(monkeypatch):
    monkeypatch.setenv("EGTPU_SIM_SCALE_BALLOTS", "500000")
    monkeypatch.setenv("EGTPU_SIM_SCALE_WORKERS", "9")
    spec = ElectionSpec.from_knobs()
    assert (spec.ballots, spec.workers) == (500_000, 9)
    assert spec.plan().ballots == 500_000
    assert dataclasses.replace(spec, ballots=10).ballots == 10


def test_journal_chain_detects_tamper_and_duplicates():
    j = Journal()
    j.append(0, 100)
    j.append(1, 50)
    assert j.total() == 150 and j.chain_ok() and j.has(0)
    with pytest.raises(ValueError, match="duplicate"):
        j.append(0, 100)
    j.entries[0] = (0, 999, j.entries[0][2])    # tamper
    assert not j.chain_ok()


@pytest.mark.slow
def test_full_default_election_meets_capacity_gate():
    """Acceptance: the full default spec (10^6 ballots, 8192-ballot
    micro-batches, 16 workers) plays out end-to-end under chaos in <= 5
    minutes of real wall-clock, and the played-out timeline agrees with
    the analytic capacity prediction within EGTPU_CAPACITY_TOL — the
    same gate `egplan --validate` runs."""
    out = capacity.validate_sim_election()
    assert not out.get("skipped"), out
    assert out["oracles_ok"], out["violations"]
    assert out["pass"], out
    assert out["wall_s"] <= 300.0
    assert out["err_pct"] <= capacity.tolerance() * 100

"""Multi-host integration test: two OS processes, each with 4 virtual CPU
devices, form one 8-device JAX distributed runtime and run the sharded
group ops across the process (DCN) boundary — the CPU stand-in for a
multi-host TPU pod (SURVEY.md §5.8's second communication plane)."""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
for k in list(os.environ):
    if "AXON" in k or "PALLAS" in k or k.startswith("TPU"):
        os.environ.pop(k)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from electionguard_tpu.parallel.distributed import (
    distributed_init, global_batch, local_result, multihost_election_mesh)

# must run before anything creates device constants (bignum_jax does at
# import time), which would initialise the XLA backend prematurely
distributed_init()

from electionguard_tpu.parallel.mesh import DP_AXIS
from electionguard_tpu.core.group import tiny_group
from electionguard_tpu.core.group_jax import JaxGroupOps
from electionguard_tpu.core import bignum_jax as bn
import jax.numpy as jnp
# version-portable shard_map (check_vma on new jax, check_rep on old)
from electionguard_tpu.parallel.sharded import shard_map as _sm
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, len(jax.devices())

mesh = multihost_election_mesh(wp=1)
g = tiny_group()
ops = JaxGroupOps(g, backend="cios")

B = 16
rng = np.random.default_rng(0)
bases = [pow(g.g, int(e), g.p) for e in rng.integers(1, 1 << 30, B)]
exps = [int(e) for e in rng.integers(1, 1 << 30, B)]
A = ops.to_limbs_p(bases)
E = ops.to_limbs_q(exps)

mapped = _sm(
    ops._powmod_impl, mesh=mesh,
    in_specs=(P(DP_AXIS), P(DP_AXIS)), out_specs=P(DP_AXIS))


@jax.jit
def step(a, e):
    out = mapped(a, e)
    # bring the dp-sharded result back replicated so every host can read it
    return jax.lax.with_sharding_constraint(
        out, NamedSharding(mesh, P()))


out = step(global_batch(mesh, A), global_batch(mesh, E))
got = local_result(out)
want = [pow(b, e, g.p) for b, e in zip(bases, exps)]
assert bn.limbs_to_ints(got) == want, "cross-host powmod mismatch"
print(f"proc {jax.process_index()} OK", flush=True)
"""


_VERIFY_WORKER = r"""
import os, sys
for k in list(os.environ):
    if "AXON" in k or "PALLAS" in k or k.startswith("TPU"):
        os.environ.pop(k)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from electionguard_tpu.parallel.distributed import (
    distributed_init, global_batch, local_result, multihost_election_mesh)

distributed_init()

from electionguard_tpu.parallel.mesh import DP_AXIS
from electionguard_tpu.parallel.sharded import ShardedGroupOps
from electionguard_tpu.core.group import tiny_group
from electionguard_tpu.core.group_jax import JaxGroupOps
from electionguard_tpu.core import bignum_jax as bn
import jax.numpy as jnp
assert jax.process_count() == 2 and len(jax.devices()) == 8

# a REAL verify step across the process (DCN) boundary: the Schnorr/CP
# commitment recompute a = g^v x^c (fixed-base PowRadix + variable powmod
# + modmul, dp-sharded) plus the homomorphic tally product contracting dp
mesh = multihost_election_mesh(wp=1)
g = tiny_group()
ops = JaxGroupOps(g, backend="cios")
sops = ShardedGroupOps(ops, mesh)

B = 16
rng = np.random.default_rng(1)
xs = [pow(g.g, int(e), g.p) for e in rng.integers(1, 1 << 30, B)]
cs = [int(e) % g.q for e in rng.integers(1, 1 << 30, B)]
vs = [int(e) % g.q for e in rng.integers(1, 1 << 30, B)]
X = ops.to_limbs_p(xs)
C = ops.to_limbs_q(cs)
V = ops.to_limbs_q(vs)
dig = np.asarray(sops._digits8(jnp.asarray(V)))

Xg = global_batch(mesh, X)
Cg = global_batch(mesh, C)
digg = global_batch(mesh, dig, P(DP_AXIS, None))
table = jax.device_put(ops.g_table, NamedSharding(mesh, P()))

pow_m = sops._powmod_j
fix_m = sops._fixed_pow_j
mul_m = sops._mulmod_j
prod_m = sops._prod_reduce_j


@jax.jit
def step(X, C, dig, table):
    a = mul_m(fix_m(table, dig), pow_m(X, C))
    tally = prod_m(X[:, None, :])
    rep = NamedSharding(mesh, P())
    return (jax.lax.with_sharding_constraint(a, rep),
            jax.lax.with_sharding_constraint(tally, rep))


a, tally = step(Xg, Cg, digg, table)
got_a = bn.limbs_to_ints(local_result(a))
got_t = bn.limbs_to_ints(local_result(tally))
want_a = [pow(g.g, v, g.p) * pow(x, c, g.p) % g.p
          for x, c, v in zip(xs, cs, vs)]
want_t = 1
for x in xs:
    want_t = want_t * x % g.p
assert got_a == want_a, "cross-host verify commitments mismatch"
assert got_t == [want_t], "cross-host tally product mismatch"
print(f"proc {jax.process_index()} OK", flush=True)
"""


def _run_two_workers(worker_src):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env_base = {k: v for k, v in os.environ.items()
                if "AXON" not in k and "PALLAS" not in k
                and not k.startswith("TPU")}
    procs = []
    for pid in range(2):
        env = dict(env_base,
                   EGTPU_COORDINATOR=f"127.0.0.1:{port}",
                   EGTPU_NUM_PROCESSES="2",
                   EGTPU_PROCESS_ID=str(pid),
                   PYTHONPATH=os.path.dirname(os.path.dirname(
                       os.path.abspath(__file__))))
        procs.append(subprocess.Popen(
            [sys.executable, "-c", worker_src], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = [p.communicate(timeout=240)[0] for p in procs]
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-3000:]}"
        assert "OK" in out


def test_two_process_sharded_powmod(tmp_path):
    _run_two_workers(_WORKER)


def test_two_process_sharded_verify_step(tmp_path):
    """SURVEY §5.8 second plane, cross-host: commitment recompute + tally
    product over a 2-process 8-device mesh, byte-identical to host ints
    (VERDICT round-2 item 9)."""
    _run_two_workers(_VERIFY_WORKER)

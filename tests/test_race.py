"""Race-detector tests: monitor invisibility, planted-fixture
detection at exact access pairs, false-positive guards, PCT replay
from the RACE_RESULTS.json repro, the guards drift gate, and the
tier-1 in-process detector sweep over the fault/adversary suites.

Trace hashes are compared INSIDE one process only (see test_sim.py's
module docstring: the event-trace hash is seed-deterministic but
PYTHONHASHSEED-sensitive across processes), so the artifact replay
test re-derives its own baseline hash instead of trusting the one
recorded by another process.
"""

import json
import os
import subprocess
import sys

import pytest

from electionguard_tpu.analysis import race as race_mod
from electionguard_tpu.analysis import race_instrument
from electionguard_tpu.sim.cluster import SimConfig
from electionguard_tpu.sim.explore import run_sim
from electionguard_tpu.sim.schedule import from_json
from electionguard_tpu.sim.shrink import shrink

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FAST = SimConfig(n_mix_stages=1)


def _pairs(report):
    """(kind, var, prior task, current task) for every race found."""
    return [(d["kind"], d["var"], d["prior"]["task"], d["current"]["task"])
            for d in report.races]


# ------------------------------------------------------------ invisibility

def test_monitor_is_schedule_invisible():
    """The central invariant: attaching the monitor changes NOTHING
    about the execution — same trace hash bit-for-bit — because it adds
    no yield points and never touches the honest RNG streams."""
    plain = run_sim(0, config=FAST)
    raced = run_sim(0, config=FAST, race=True)
    assert plain.ok and raced.ok
    assert raced.trace_hash == plain.trace_hash
    assert raced.race_events > 0          # it did actually watch
    assert plain.race_events == 0


def test_race_off_run_reports_no_monitor_state():
    r = run_sim(1, config=FAST)
    assert r.races == [] and r.race_events == 0


# ------------------------------------------------------- planted fixtures

@pytest.mark.race
def test_planted_hb_race_detected_at_exact_pair():
    """race-hb: two sleep-ordered lock-free writers.  Sleeps create no
    HB edge, so the FastTrack detector must fire on RaceProbeBox.shared
    naming both planted tasks at their write site."""
    r = run_sim(3, plant=("race-hb",), config=FAST, race=True,
                strategy="pct")
    hb = [d for d in r.races
          if d["kind"] == "hb" and d["var"] == "RaceProbeBox.shared"]
    assert hb, f"HB detector missed the planted race: {_pairs(r)}"
    tasks = {d["prior"]["task"] for d in hb} | {d["current"]["task"]
                                               for d in hb}
    assert tasks == {"race-hb-1", "race-hb-2"}
    for d in hb:
        assert d["prior"]["site"].endswith(":go")
        assert d["current"]["site"].endswith(":go")
        assert "sim/cluster.py" in d["current"]["site"]
    assert any(v.startswith("race: hb") for v in r.violations)


@pytest.mark.race
def test_planted_lockset_race_is_lockset_only():
    """race-lockset: every access locked and every pair HB-ordered by
    an event handoff, but under DIFFERENT locks — only the lockset
    heuristic can see it (and HB must stay quiet: the handoffs order
    the accesses in this schedule)."""
    r = run_sim(3, plant=("race-lockset",), config=FAST, race=True,
                strategy="pct")
    kinds = {d["kind"] for d in r.races}
    assert kinds == {"lockset"}, f"expected lockset only, got {_pairs(r)}"
    d = next(d for d in r.races if d["var"] == "RaceProbeBox.shared")
    sides = {d["prior"]["site"].rsplit(":", 1)[-1],
             d["current"]["site"].rsplit(":", 1)[-1]}
    assert sides == {"ls_first", "ls_second"}
    locks = set(d["prior"]["locks"]) | set(d["current"]["locks"])
    assert locks == {"RaceProbeBox._lock_a", "RaceProbeBox._lock_b"}


@pytest.mark.race
def test_message_passing_handoff_stays_green():
    """race-handoff: lock-free write, Event set, lock-free read — legal
    publication.  The false-positive guard for both detectors (the
    seam-wait HB edge orders the pair; the Eraser ownership transfer
    keeps the lockset heuristic quiet)."""
    r = run_sim(3, plant=("race-handoff",), config=FAST, race=True,
                strategy="pct")
    assert r.ok, r.violations
    assert r.races == [], f"false positive: {_pairs(r)}"


@pytest.mark.race
def test_planted_race_shrinks_to_empty_schedule():
    """ddmin minimality: the planted race needs no faults at all, so
    the minimized repro is the EMPTY schedule — just the racing pair."""
    r = run_sim(3, plant=("race-hb",), config=FAST, race=True,
                strategy="pct")
    assert not r.ok
    res = shrink(3, r.schedule, plant=("race-hb",), config=FAST,
                 oracle_classes=frozenset(["race"]), race=True,
                 strategy="pct")
    assert res.schedule == []
    assert any("RaceProbeBox.shared" in v for v in res.violations)


# ------------------------------------------------------------ PCT strategy

def test_pct_is_deterministic_and_distinct_from_random():
    """Same seed + pct replays bit-for-bit; the PCT priority schedule
    dispatches differently from the uniform-random strategy."""
    a = run_sim(5, config=FAST, strategy="pct")
    b = run_sim(5, config=FAST, strategy="pct")
    assert a.ok and b.ok
    assert a.trace_hash == b.trace_hash
    c = run_sim(5, config=FAST, strategy="random")
    assert c.trace_hash != a.trace_hash


@pytest.mark.race
def test_pct_replay_from_race_results_repro():
    """The RACE_RESULTS.json selftest repro is sufficient to replay:
    same seed + strategy + shrunk schedule + plant reproduce the same
    race pair, bit-for-bit across two in-process runs."""
    path = os.path.join(REPO_ROOT, "RACE_RESULTS.json")
    assert os.path.exists(path), "run python tools/race_matrix.py --json"
    doc = json.load(open(path))
    entry = doc["selftest"]["race-hb"]
    config = FAST if doc["profile"] == "fast" else SimConfig()
    sched = from_json(json.dumps(entry["shrunk_schedule"]))
    a = run_sim(entry["seed"], schedule=sched, plant=(entry["plant"],),
                config=config, race=True, strategy=entry["strategy"])
    b = run_sim(entry["seed"], schedule=sched, plant=(entry["plant"],),
                config=config, race=True, strategy=entry["strategy"])
    assert a.trace_hash == b.trace_hash          # bit-for-bit replay
    assert [d["var"] for d in a.races] == [d["var"] for d in b.races]
    got = {(d["kind"], d["var"]) for d in a.races}
    assert ("hb", "RaceProbeBox.shared") in got
    # the recorded violations name the same access pair
    assert any("RaceProbeBox.shared" in v
               for v in entry["shrunk_violations"])


def test_race_results_artifact_is_green():
    """The committed sweep artifact: every run green, no failures, the
    waiver baseline empty, the selftest fixtures all detected."""
    doc = json.load(open(os.path.join(REPO_ROOT, "RACE_RESULTS.json")))
    assert doc["failed"] == 0 and doc["failures"] == []
    assert doc["ok"] == doc["runs"]
    assert doc["races_distinct"] == 0
    assert doc["waivers"] == 0
    assert doc["selftest"]["ok"]
    for plant in ("race-hb", "race-lockset", "race-handoff"):
        assert doc["selftest"][plant]["ok"], plant


# -------------------------------------------------------------- regressions

@pytest.mark.race
def test_fixed_races_stay_fixed_seed0():
    """Pinned regressions for the two access pairs the first sweep
    surfaced (both at seed 0 / random):

    * lockset w/r ``DecryptionCoordinator.proxies`` — ``ready()``'s
      lock-held read vs the sim driver's lock-free ``coord.proxies``
      read; fixed by the ``registered()`` snapshot accessor.
    * hb w/r ``Counter._v`` — ``_observe_server``'s counter built
      under ``MetricsRegistry._lock`` vs a remote task's ``inc()``;
      fixed by the server start→dispatch HB edge (real gRPC publishes
      handlers at ``start()``).
    """
    r = run_sim(0, config=FAST, race=True, strategy="random")
    assert r.ok, r.violations
    racy_vars = {d["var"] for d in r.races}
    assert "DecryptionCoordinator.proxies" not in racy_vars
    assert "Counter._v" not in racy_vars
    assert not r.races, f"new race appeared: {_pairs(r)}"


def test_registered_snapshots_under_lock():
    """The proxies fix itself: ``registered()`` returns a copy, not the
    live list registration handlers mutate under ``_lock``."""
    import threading
    from electionguard_tpu.remote.decrypting_remote import (
        DecryptionCoordinator)
    coord = DecryptionCoordinator.__new__(DecryptionCoordinator)
    coord._lock = threading.Lock()
    coord.proxies = [1, 2]
    snap = coord.registered()
    assert snap == [1, 2] and snap is not coord.proxies


# ------------------------------------------------------------------ waivers

def test_waiver_baseline_ships_empty():
    assert race_mod.load_waivers() == []


def test_waivers_require_notes(tmp_path):
    p = tmp_path / "w.json"
    p.write_text(json.dumps(
        {"waivers": [{"var": "X.y", "kind": "hb"}]}))
    with pytest.raises(ValueError, match="no note"):
        race_mod.load_waivers(str(p))
    p.write_text(json.dumps(
        {"waivers": [{"var": "X.y", "note": "known benign"}]}))
    (w,) = race_mod.load_waivers(str(p))
    rep = race_mod.RaceReport(
        kind="hb", var="X.y", pair="w/w",
        prior=race_mod.RaceSide("a", "write", "f:1"),
        current=race_mod.RaceSide("b", "write", "f:2"), vtime=0.0)
    assert race_mod.waived(rep, [w])          # kind defaults to "*"
    rep2 = race_mod.RaceReport(
        kind="hb", var="Other.z", pair="w/w",
        prior=rep.prior, current=rep.current, vtime=0.0)
    assert not race_mod.waived(rep2, [w])


def test_watch_knob_parses_targets():
    got = race_instrument.parse_watch("pkg.mod:Cls=a+b;other.mod:K=x")
    assert got == [
        {"module": "pkg.mod", "class": "Cls", "lock_attrs": [],
         "guarded": ["a", "b"]},
        {"module": "other.mod", "class": "K", "lock_attrs": [],
         "guarded": ["x"]}]
    with pytest.raises(ValueError, match="bad EGTPU_RACE_WATCH"):
        race_instrument.parse_watch("no-equals-sign")


# ----------------------------------------------------------- guards drift

def test_analysis_guards_artifact_in_sync():
    """ANALYSIS_GUARDS.json is generated from the lock-discipline
    pass's inferred guarded-attribute sets; the committed artifact must
    match a fresh inference (same gate pattern as ENV_KNOBS.md)."""
    from electionguard_tpu.analysis import core, lock_discipline
    path = os.path.join(REPO_ROOT, "ANALYSIS_GUARDS.json")
    assert os.path.exists(path), \
        "run python tools/eglint.py --write-guards"
    committed = open(path).read()
    fresh = lock_discipline.render_guards(core.Project())
    assert committed == fresh, (
        "ANALYSIS_GUARDS.json drifted from the lock-discipline "
        "inference: run python tools/eglint.py --write-guards")


# ------------------------------------------------- tier-1 detector sweeps

@pytest.mark.race
@pytest.mark.parametrize("strategy", ["random", "pct"])
def test_detector_sweep_fault_suite(strategy):
    """Tier-1 gate: the detector over the in-process fault suite (the
    generated per-seed fault schedules) finds no unwaived race under
    either exploration strategy."""
    for seed in range(4):
        r = run_sim(seed, config=FAST, race=True, strategy=strategy)
        assert r.ok, f"seed {seed}/{strategy}: {r.violations}"
        assert not r.races, (f"seed {seed}/{strategy} raced: "
                             f"{_pairs(r)}")


@pytest.mark.race
def test_detector_sweep_adversary_suite():
    """Tier-1 gate: same, with the Byzantine adversary corpus composed
    into the runs (stream 5)."""
    for seed in (0, 1, 2):
        r = run_sim(seed, config=FAST, adversaries=True, race=True,
                    strategy="pct")
        assert r.ok, f"adversary seed {seed}: {r.violations}"
        assert not r.races, f"adversary seed {seed}: {_pairs(r)}"


@pytest.mark.race
@pytest.mark.slow
def test_wide_race_sweep_subprocess(tmp_path):
    """The wide sweep via the actual CLI (fresh process, selftest
    included): a RACE_RESULTS-shaped artifact with zero failures."""
    artifact = tmp_path / "race_results.json"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools",
                                      "race_matrix.py"),
         "--seeds", "12", "--fast", "--json", str(artifact)],
        capture_output=True, text=True, timeout=900)
    assert out.returncode == 0, out.stdout + out.stderr
    doc = json.loads(artifact.read_text())
    assert doc["failed"] == 0 and doc["ok"] == doc["runs"] == 24
    assert doc["selftest"]["ok"]

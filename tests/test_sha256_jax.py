"""Differential tests: device-batched SHA-256 / Fiat–Shamir vs hashlib."""

import hashlib

import numpy as np
import pytest

import jax.numpy as jnp

from electionguard_tpu.core import bignum_jax as bn
from electionguard_tpu.core import sha256_jax as sj
from electionguard_tpu.core.hash import _encode, hash_elems
from electionguard_tpu.core.group_jax import limbs_to_bytes_be


@pytest.mark.parametrize("L", [0, 1, 55, 56, 63, 64, 65, 127, 512, 3139])
def test_sha256_rows_matches_hashlib(L):
    rng = np.random.default_rng(L)
    B = 5
    msgs = rng.integers(0, 256, (B, L), dtype=np.uint8)
    got = np.asarray(sj.sha256_rows(jnp.asarray(msgs)))
    for i in range(B):
        want = hashlib.sha256(msgs[i].tobytes()).digest()
        assert bytes(got[i]) == want, f"row {i} len {L}"


def test_digest_to_q_limbs(pgroup):
    rng = np.random.default_rng(3)
    digests = rng.integers(0, 256, (32, 32), dtype=np.uint8)
    # include a digest >= q (q = 2^256 - 189: bytes all 0xFF)
    digests[0] = 0xFF
    got = sj.digest_to_q_limbs(pgroup, jnp.asarray(digests))
    for i in range(digests.shape[0]):
        want = int.from_bytes(bytes(digests[i]), "big") % pgroup.q
        assert bn.limbs_to_int(np.asarray(got[i])) == want


def test_batch_challenge_matches_hash_elems(pgroup):
    g = pgroup
    rng = np.random.default_rng(11)
    B = 7
    qbar = g.int_to_q(int.from_bytes(rng.bytes(32), "big"))
    elems = [[g.int_to_p(pow(g.g, int(rng.integers(1, 1 << 60)), g.p))
              for _ in range(B)] for _ in range(6)]
    elem_bytes = [
        np.stack([np.frombuffer(e.to_bytes(), np.uint8) for e in col])
        for col in elems]
    prefix = _encode(qbar)
    got = np.asarray(sj.batch_challenge_p(g, prefix, elem_bytes))
    for i in range(B):
        want = hash_elems(g, qbar, *[col[i] for col in elems]).value
        assert bn.limbs_to_int(got[i]) == want


def test_batch_challenge_roundtrip_from_limbs(pgroup):
    """The path the verifier uses: device limb arrays -> byte images ->
    batch challenge, vs scalar hash_elems over bytes_to_p elements."""
    g = pgroup
    rng = np.random.default_rng(13)
    B = 4
    vals = [pow(g.g, int(rng.integers(1, 1 << 50)), g.p) for _ in range(B)]
    limbs = bn.ints_to_limbs(vals, 256)
    byte_img = limbs_to_bytes_be(limbs)
    qbar = g.int_to_q(12345)
    got = np.asarray(sj.batch_challenge_p(g, _encode(qbar), [byte_img]))
    for i in range(B):
        want = hash_elems(g, qbar, g.int_to_p(vals[i])).value
        assert bn.limbs_to_int(got[i]) == want

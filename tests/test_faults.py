"""Chaos suite: every injected failure drives a real recovery path and
the election record still verifies (ISSUE 2 acceptance).

Layers under test, all with deterministic fault plans (testing/faults.py
— Nth-call injection, no timers, no randomness):

* the fault-plan machinery itself (client interceptor, server wrapper,
  the drop-response idempotency killer, env-var activation);
* key ceremony: a trustee "process" dies right after committing its
  first received key share and restarts from its resume file — the
  ceremony completes and every trustee file lands;
* decryption: a trustee dies mid-run; while quorum holds it is demoted
  to the missing set and the tally completes with compensated shares;
  below quorum the run fails cleanly with a quorum error;
* serving plane: a crashed encryption service replays its write-ahead
  admission journal on restart — zero lost admitted ballots, the code
  chain contiguous, the record bit-for-bit the offline encryptor's
  output.  Both an in-process crash and a real SIGKILL'd subprocess.

Everything here is tiny-group and deliberately non-slow: failure
semantics are tier-1 machinery, not an overnight suite.
"""

import json
import os
import threading
import time

import grpc
import pytest

from electionguard_tpu.ballot.plaintext import RandomBallotProvider
from electionguard_tpu.core.dlog import DLog
from electionguard_tpu.crypto.elgamal import elgamal_encrypt
from electionguard_tpu.ballot.tally import (EncryptedTally,
                                            EncryptedTallyContest,
                                            EncryptedTallySelection)
from electionguard_tpu.decrypt.decryption import (Decryption,
                                                  DecryptionError)
from electionguard_tpu.decrypt.trustee import DecryptingTrustee
from electionguard_tpu.keyceremony.exchange import key_ceremony_exchange
from electionguard_tpu.keyceremony.interface import Result
from electionguard_tpu.keyceremony.trustee import KeyCeremonyTrustee
from electionguard_tpu.publish.election_record import ElectionConfig
from electionguard_tpu.remote import rpc_util
from electionguard_tpu.remote.decrypting_remote import (
    DecryptingTrusteeServer, DecryptionCoordinator)
from electionguard_tpu.remote.keyceremony_remote import (
    KeyCeremonyCoordinator, KeyCeremonyTrusteeServer, RemoteKeyCeremonyProxy)
from electionguard_tpu.serve import journal as wal
from electionguard_tpu.sim import simulation
from electionguard_tpu.testing import faults
from tests.test_keyceremony import tiny_manifest


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    """A test's fault plan must never leak into the next test."""
    yield
    faults.clear()


@pytest.fixture
def fastrpc(monkeypatch):
    """Fast, deterministic retry posture: 2 attempts, pinned jitter
    (upper bound), sub-second bounded connect windows."""
    monkeypatch.setenv("EGTPU_RPC_RETRIES", "2")
    monkeypatch.setenv("EGTPU_RPC_RETRY_WAIT", "0.2")
    monkeypatch.setenv("EGTPU_RPC_RETRY_CAP", "0.4")
    monkeypatch.setenv("EGTPU_RPC_CONNECT_WINDOW", "0.4")
    monkeypatch.setattr(rpc_util, "_uniform", lambda lo, hi: hi)


# =====================================================================
# fault-plan machinery
# =====================================================================


def test_fault_plan_parsing_and_env_activation(tmp_path, monkeypatch):
    spec = {"rules": [{"method": "x", "kind": "unavailable",
                       "on_calls": [2]},
                      {"method": "*", "kind": "latency",
                       "latency_s": 0.5}]}
    monkeypatch.setenv("EGTPU_FAULT_PLAN", json.dumps(spec))
    plan = faults.FaultPlan.from_env()
    assert plan.hard_exit  # env plans crash for real on crash_after
    assert plan.rules[0].on_calls == (2,)
    assert plan.rules[1].method == "*"
    # @file indirection
    p = tmp_path / "plan.json"
    p.write_text(json.dumps(spec))
    monkeypatch.setenv("EGTPU_FAULT_PLAN", f"@{p}")
    assert faults.FaultPlan.from_env().rules == plan.rules
    monkeypatch.delenv("EGTPU_FAULT_PLAN")
    assert faults.FaultPlan.from_env() is None
    with pytest.raises(ValueError, match="unknown fault kind"):
        faults.FaultRule(method="m", kind="bogus")


def test_fault_rule_matching_and_sides():
    r = faults.FaultRule(method="*", kind="latency")
    assert r.matches("anything", 7)          # wildcard + every call
    assert r.side == "client"                # latency defaults client
    assert faults.FaultRule(method="m", kind="drop_response").side \
        == "server"
    assert faults.FaultRule(method="m", kind="unavailable",
                            where="server").side == "server"
    n = faults.FaultRule(method="m", kind="deadline", on_calls=(2, 4))
    assert not n.matches("m", 1) and n.matches("m", 2)
    assert not n.matches("other", 2)


def test_client_injected_unavailable_is_retried_through(tgroup, fastrpc):
    """An injected UNAVAILABLE on the first attempt is absorbed by the
    retry layer: the caller never sees the fault.  Runs on the virtual
    clock — the retry backoff costs zero real time."""
    plan = faults.install(faults.FaultPlan(rules=[faults.FaultRule(
        method="registerTrustee", kind="unavailable", on_calls=(1,))]))

    def body():
        coord = KeyCeremonyCoordinator(tgroup, 1, 1, port=0)
        try:
            proxy = RemoteKeyCeremonyProxy(f"localhost:{coord.port}")
            resp = proxy.register_trustee("solo", "localhost:9", tgroup,
                                          nonce=b"n1")
            proxy.close()
            assert resp.x_coordinate == 1 and not resp.error
            # the audit log proves the fault actually fired (attempt 1),
            # and the retry (call 2) went through clean
            assert plan.injected == [("client", "registerTrustee", 1,
                                      "unavailable")]
        finally:
            coord.shutdown(all_ok=True)

    with simulation() as sim:
        sim.run(body)


def test_client_injected_deadline_is_fatal_first_attempt(tgroup, fastrpc):
    """DEADLINE_EXCEEDED on a first (full-budget) attempt is a real
    timeout, not a connect hiccup — no retry."""
    plan = faults.install(faults.FaultPlan(rules=[faults.FaultRule(
        method="registerTrustee", kind="deadline", on_calls=(1,))]))

    def body():
        coord = KeyCeremonyCoordinator(tgroup, 1, 1, port=0)
        try:
            proxy = RemoteKeyCeremonyProxy(f"localhost:{coord.port}")
            with pytest.raises(grpc.RpcError) as ei:
                proxy.register_trustee("solo", "localhost:9", tgroup)
            proxy.close()
            assert ei.value.code() == grpc.StatusCode.DEADLINE_EXCEEDED
            assert len(plan.injected) == 1   # exactly one attempt
            assert coord.ready() == 0        # never reached the peer
        finally:
            coord.shutdown(all_ok=True)

    with simulation() as sim:
        sim.run(body)


def test_injected_latency_delays_the_call(tgroup, fastrpc):
    """Server-side latency injection stretches VIRTUAL time, not wall
    time: the call observes the delay, the test doesn't."""
    faults.install(faults.FaultPlan(rules=[faults.FaultRule(
        method="registerTrustee", kind="latency", latency_s=0.25)]))

    with simulation() as sim:
        def body():
            coord = KeyCeremonyCoordinator(tgroup, 1, 1, port=0)
            try:
                proxy = RemoteKeyCeremonyProxy(f"localhost:{coord.port}")
                t0 = sim.now
                resp = proxy.register_trustee("solo", "localhost:9",
                                              tgroup, nonce=b"n1")
                proxy.close()
                assert sim.now - t0 >= 0.25
                assert resp.x_coordinate == 1
            finally:
                coord.shutdown(all_ok=True)

        sim.run(body)


def test_server_drop_response_replays_idempotently(tgroup, fastrpc):
    """The idempotency killer: the impl RUNS (registration committed),
    the response is dropped, the client retries — the replay must hand
    back the original answer, not a duplicate registration."""
    plan = faults.install(faults.FaultPlan(rules=[faults.FaultRule(
        method="registerTrustee", kind="drop_response", on_calls=(1,))]))

    def body():
        coord = KeyCeremonyCoordinator(tgroup, 1, 1, port=0)  # wrapped
        try:
            proxy = RemoteKeyCeremonyProxy(f"localhost:{coord.port}")
            resp = proxy.register_trustee("solo", "localhost:9", tgroup,
                                          nonce=b"n1")
            proxy.close()
            assert resp.x_coordinate == 1 and not resp.error
            assert coord.ready() == 1        # committed exactly once
            assert ("server", "registerTrustee", 1,
                    "drop_response") in plan.injected
        finally:
            coord.shutdown(all_ok=True)

    with simulation() as sim:
        sim.run(body)


# =====================================================================
# key ceremony: trustee dies mid-ceremony, restarts from its resume file
# =====================================================================


def test_key_ceremony_survives_trustee_crash_restart(tgroup, tmp_path,
                                                     monkeypatch):
    """Acceptance (a): guardian-1's process dies right after it commits
    (and checkpoints) its first received key share; a new process pointed
    at the resume file re-binds the same port, re-registers with the same
    nonce, restores the polynomial and received state — and the ceremony,
    bridged by the coordinator's bounded retries, completes."""
    monkeypatch.setenv("EGTPU_RPC_RETRIES", "8")
    monkeypatch.setenv("EGTPU_RPC_RETRY_WAIT", "0.5")
    monkeypatch.setenv("EGTPU_RPC_RETRY_CAP", "1.0")
    monkeypatch.setenv("EGTPU_RPC_CONNECT_WINDOW", "1.0")
    monkeypatch.setattr(rpc_util, "_uniform", lambda lo, hi: hi)

    crashed = threading.Event()
    victim: dict = {}

    def crash(_method):
        # the "process" dies: its server vanishes a beat after the
        # response is dropped (so the client's failure is the clean
        # injected UNAVAILABLE, as for a torn connection)
        threading.Timer(0.1,
                        lambda: victim["server"].server.stop(grace=0)
                        ).start()
        crashed.set()

    # exchange round 3 starts with (sender=guardian-0, receiver=
    # guardian-1): the 1st receiveSecretKeyShare served in this process
    # is guardian-1's — a deterministic protocol point, not a timer
    plan = faults.FaultPlan(rules=[faults.FaultRule(
        method="receiveSecretKeyShare", kind="crash_after",
        on_calls=(1,))])
    plan.crash_cb = crash
    faults.install(plan)

    coord = KeyCeremonyCoordinator(tgroup, 3, 2, port=0)
    resume = str(tmp_path / "guardian-1.resume")
    servers = []
    try:
        for i in range(3):
            servers.append(KeyCeremonyTrusteeServer(
                tgroup, f"guardian-{i}", f"localhost:{coord.port}",
                out_dir=str(tmp_path),
                resume_file=resume if i == 1 else None))
        victim["server"] = servers[1]
        assert coord.wait_for_registrations(timeout=10)

        box: dict = {}
        th = threading.Thread(target=lambda: box.setdefault(
            "res", coord.run_key_ceremony(str(tmp_path))))
        th.start()
        assert crashed.wait(timeout=30), "fault plan never fired"
        assert os.path.exists(resume)
        time.sleep(0.3)   # let the dead server release its port

        # relaunch from the resume file (retry the bind: the old socket
        # may take a beat to fully release)
        deadline = time.monotonic() + 10
        while True:
            try:
                servers[1] = KeyCeremonyTrusteeServer(
                    tgroup, "guardian-1", f"localhost:{coord.port}",
                    out_dir=str(tmp_path), resume_file=resume)
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.2)
        assert servers[1].x_coordinate == 2   # reclaimed, not reassigned
        # the checkpointed share survived the crash
        assert "guardian-0" in servers[1].trustee.received_shares

        th.join(timeout=120)
        assert not th.is_alive(), "ceremony wedged after the restart"
        results = box["res"]
        assert not isinstance(results, Result), \
            f"ceremony failed: {results.error}"
        joint = tgroup.mult_p(*(s.trustee.election_public_key
                                for s in servers))
        assert results.joint_public_key == joint
        for s in servers:
            assert len(s.trustee.received_shares) == 2
        assert ("server", "receiveSecretKeyShare", 1,
                "crash_after") in plan.injected
        for i in range(3):
            assert (tmp_path / f"trustee-guardian-{i}.json").exists()
    finally:
        faults.clear()
        coord.shutdown(all_ok=True)
        for s in servers:
            s.shutdown()


# =====================================================================
# decryption: trustee dies mid-run
# =====================================================================


@pytest.fixture(scope="module")
def dec_election(tgroup):
    """3-guardian/quorum-2 ceremony (in-process) + a small encrypted
    tally: votes [3, 2] over 5 cast ballots."""
    trustees = [KeyCeremonyTrustee(tgroup, f"guardian-{i}", i + 1, 2)
                for i in range(3)]
    results = key_ceremony_exchange(trustees, tgroup)
    init = results.make_election_initialized(
        ElectionConfig(tiny_manifest(), 3, 2))
    votes = [3, 2]
    cts = []
    for v in votes:
        acc = None
        for j in range(5):
            ct = elgamal_encrypt(tgroup, 1 if j < v else 0,
                                 tgroup.rand_q(), init.joint_public_key)
            acc = ct if acc is None else acc.mult(ct)
        cts.append(acc)
    tally = EncryptedTally("t", (EncryptedTallyContest(
        "contest-0", 0, tuple(
            EncryptedTallySelection(f"sel-{i}", i, ct)
            for i, ct in enumerate(cts))),), cast_ballot_count=5)
    return dict(init=init, votes=votes, tally=tally,
                states=[t.decrypting_trustee_state() for t in trustees],
                dlog=DLog(tgroup, max_exponent=10))


def _spin_decryption(tgroup, dec_election):
    coord = DecryptionCoordinator(tgroup, navailable=3, port=0)
    servers = []
    for i in range(3):
        servers.append(DecryptingTrusteeServer(
            tgroup,
            DecryptingTrustee.from_state(tgroup,
                                         dec_election["states"][i]),
            f"localhost:{coord.port}"))
    assert coord.wait_for_registrations(timeout=10)
    coord.mark_started()
    return coord, servers


def test_decryption_demotes_dead_trustee_when_quorum_holds(
        tgroup, dec_election, fastrpc):
    """Acceptance (b) success half: guardian-0 dies mid-decryption (its
    first directDecrypt commits, the response is lost, the process is
    gone); it is demoted to the missing set and the tally completes with
    compensated shares from the two survivors — quorum was all the
    threshold scheme ever needed."""
    victim: dict = {}
    plan = faults.FaultPlan(rules=[faults.FaultRule(
        method="directDecrypt", kind="crash_after", on_calls=(1,))])
    # the "process" dies with the committed call: its server drops
    # synchronously (the injected abort is the torn-connection error
    # the client sees)
    plan.crash_cb = lambda _m: victim["server"].server.stop(grace=0)
    faults.install(plan)

    def body():
        coord, servers = _spin_decryption(tgroup, dec_election)
        victim["server"] = servers[0]
        try:
            d = Decryption(tgroup, dec_election["init"], coord.proxies,
                           [], dec_election["dlog"])
            out = d.decrypt(dec_election["tally"])
            got = [s.tally for s in out.contests[0].selections]
            assert got == dec_election["votes"]
            # guardian-0 was demoted and reconstructed, mid-run
            assert d.missing == ["guardian-0"]
            assert [t.id for t in d.trustees] == ["guardian-1",
                                                  "guardian-2"]
            for s in out.contests[0].selections:
                by_id = {sh.guardian_id: sh for sh in s.shares}
                assert set(by_id) == {"guardian-0", "guardian-1",
                                      "guardian-2"}
                # the reconstructed share carries its compensating parts
                assert by_id["guardian-0"].proof is None
                assert set(by_id["guardian-0"].recovered_parts) == \
                    {"guardian-1", "guardian-2"}
            assert ("server", "directDecrypt", 1,
                    "crash_after") in plan.injected
        finally:
            faults.clear()
            coord.shutdown(all_ok=True)
            for s in servers:
                s.shutdown()

    with simulation() as sim:
        sim.run(body)


def test_decryption_fails_cleanly_below_quorum(tgroup, dec_election,
                                               fastrpc):
    """Acceptance (b) failure half: with two of three guardians dead the
    survivors cannot meet quorum 2 — the run must fail with an explicit
    quorum error after bounded retries, not hang or emit a bad tally."""
    with simulation() as sim:
        def body():
            coord, servers = _spin_decryption(tgroup, dec_election)
            try:
                servers[0].server.stop(grace=0)
                servers[1].server.stop(grace=0)
                d = Decryption(tgroup, dec_election["init"],
                               coord.proxies, [], dec_election["dlog"])
                t0 = sim.now
                with pytest.raises(DecryptionError,
                                   match="no longer meet quorum"):
                    d.decrypt(dec_election["tally"])
                # bounded: two demote rounds of fast retries (virtual
                # seconds), not a hang
                assert sim.now - t0 < 30
            finally:
                coord.shutdown(all_ok=True)
                for s in servers:
                    s.shutdown()

        sim.run(body)


# =====================================================================
# serving plane: write-ahead journal + crash recovery
# =====================================================================


def _ballots(n, seed=3):
    return list(RandomBallotProvider(tiny_manifest(), n,
                                     seed=seed).ballots())


def test_journal_replay_tombstones_and_torn_tail(tmp_path):
    path = str(tmp_path / wal.JOURNAL_NAME)
    j = wal.AdmissionJournal(path)
    ballots = _ballots(3)
    j.append(ballots[0], False)
    j.append(ballots[1], True)
    j.append(ballots[2], False)
    j.append_drop(ballots[1].ballot_id)   # rejected after journaling
    j.close()
    # a SIGKILL can tear the final line mid-append: that admission was
    # never ack'd, so replay must ignore it — and only it
    with open(path, "ab") as f:
        f.write(b'{"id": "torn-ball')
    entries = wal.replay(path)
    assert [(e.ballot.ballot_id, e.spoil) for e in entries] == \
        [(ballots[0].ballot_id, False), (ballots[2].ballot_id, False)]
    # corruption anywhere BUT a torn tail is an error, not a skip
    with open(path, "ab") as f:
        f.write(b'\n{"id": "x", "spoil": false, "ballot": {}}\n')
    with pytest.raises(IOError, match="corrupt journal line"):
        wal.replay(path)
    # reset truncates: an empty journal is the clean-shutdown marker
    j2 = wal.AdmissionJournal(path)
    j2.reset()
    j2.close()
    assert wal.replay(path) == []


def test_repair_frame_stream_truncates_torn_tail(tmp_path):
    from electionguard_tpu.publish.publisher import repair_frame_stream
    import struct
    path = str(tmp_path / "ballots.pb")
    frames = [b"frame-one", b"frame-two-longer"]
    with open(path, "wb") as f:
        for fr in frames:
            f.write(struct.pack(">I", len(fr)) + fr)
        f.write(struct.pack(">I", 100) + b"torn")   # crash mid-frame
    n, last = repair_frame_stream(path)
    assert (n, last) == (2, frames[1])
    assert os.path.getsize(path) == sum(4 + len(fr) for fr in frames)
    n2, last2 = repair_frame_stream(path)           # idempotent
    assert (n2, last2) == (2, frames[1])
    assert repair_frame_stream(str(tmp_path / "absent.pb")) == (0, None)


@pytest.fixture(scope="module")
def chaos_init(tgroup):
    from electionguard_tpu.keyceremony.exchange import \
        key_ceremony_exchange
    trustees = [KeyCeremonyTrustee(tgroup, f"guardian-{i}", i + 1, 2)
                for i in range(3)]
    return key_ceremony_exchange(trustees, tgroup) \
        .make_election_initialized(ElectionConfig(tiny_manifest(), 3, 2),
                                   {"created_by": "chaos-test"})


_TS = 1754_000_000


def test_service_crash_recovery_replays_exact_gap(chaos_init, tgroup,
                                                  tmp_path):
    """In-process crash: the worker wedges after 2 published ballots (the
    EGTPU_CHAOS_HOLD_AFTER_BALLOTS hook), 3 more are admitted (journaled)
    but never encrypted, the service "dies".  A restarted service must
    re-encrypt exactly the 3-ballot gap, chain-contiguous, and the final
    record must be bit-for-bit the offline encryptor's output."""
    from electionguard_tpu.encrypt.encryptor import BatchEncryptor
    from electionguard_tpu.publish.election_record import ElectionRecord
    from electionguard_tpu.publish.publisher import Consumer
    from electionguard_tpu.serve.service import (EncryptionClient,
                                                 EncryptionService)
    from electionguard_tpu.verify.verifier import Verifier

    out = str(tmp_path / "record")
    ballots = _ballots(7)
    svc = EncryptionService(chaos_init, tgroup, port=0, out_dir=out,
                            max_batch=4, max_wait_ms=15, seed=tgroup.int_to_q(42),
                            timestamp=_TS, prewarm=False, hold_after=2)
    client = EncryptionClient(f"localhost:{svc.port}", tgroup)
    first = [client.encrypt(b) for b in ballots[:2]]   # published
    assert [e.ballot_id for e in first] == \
        [b.ballot_id for b in ballots[:2]]
    # worker is now wedged: these are admitted (fsync'd WAL) but will
    # never be encrypted by THIS incarnation
    for b in ballots[2:5]:
        svc._admit(b, False)
    # crash: the server vanishes, no drain, no journal reset
    svc.server.stop(grace=0)
    client.close()
    assert len(wal.replay(os.path.join(out, wal.JOURNAL_NAME))) == 5

    svc2 = EncryptionService(chaos_init, tgroup, port=0, out_dir=out,
                             max_batch=4, max_wait_ms=15,
                             seed=tgroup.int_to_q(42), timestamp=_TS,
                             prewarm=False)
    try:
        assert svc2.recovered_ballots == 3
        client2 = EncryptionClient(f"localhost:{svc2.port}", tgroup)
        h = client2.health()
        assert (h.status, h.ready, h.recovered_ballots) == \
            ("SERVING", True, 3)
        more = [client2.encrypt(b) for b in ballots[5:]]
        assert len(more) == 2
        client2.close()
    finally:
        svc2.drain()
    # clean drain resolved everything: empty journal = clean marker
    assert os.path.getsize(os.path.join(out, wal.JOURNAL_NAME)) == 0

    cons = Consumer(out, tgroup)
    record = ElectionRecord(cons.read_election_initialized())
    record.encrypted_ballots = list(cons.iterate_encrypted_ballots())
    # zero lost admitted ballots, in admission order
    assert [b.ballot_id for b in record.encrypted_ballots] == \
        [b.ballot_id for b in ballots]
    res = Verifier(record, tgroup).verify()
    assert res.ok, res.summary()
    # bit-for-bit: one offline pass over the same ballots reproduces the
    # crash-straddling record exactly — the recovery re-encrypted the
    # gap on the SAME code chain the crashed service left behind
    offline, invalid = BatchEncryptor(chaos_init, tgroup).encrypt_ballots(
        ballots, seed=tgroup.int_to_q(42), timestamp=_TS)
    assert not invalid
    assert offline == record.encrypted_ballots


def test_sigkill_service_restarts_from_journal(chaos_init, tgroup,
                                               tmp_path):
    """Acceptance (c), for real: the service subprocess is SIGKILL'd with
    admitted-but-unpublished ballots in its (journaled) queue; the
    restarted process replays the journal, reports the recovery over the
    health rpc, keeps serving, and a SIGTERM drain publishes a verifiable
    chain-contiguous record with zero lost admitted ballots."""
    from electionguard_tpu.encrypt.encryptor import BatchEncryptor
    from electionguard_tpu.publish.election_record import ElectionRecord
    from electionguard_tpu.publish.publisher import Consumer, Publisher
    from electionguard_tpu.serve.service import EncryptionClient
    from electionguard_tpu.verify.verifier import Verifier
    from electionguard_tpu.workflow.run_command import RunCommand

    indir = str(tmp_path / "init")
    Publisher(indir).write_election_initialized(chaos_init)
    out = str(tmp_path / "record")
    port = rpc_util.find_free_port()
    url = f"localhost:{port}"
    ballots = _ballots(7)

    def wait_serving(recovered, timeout=120):
        deadline = time.monotonic() + timeout
        last = None
        while time.monotonic() < deadline:
            c = EncryptionClient(url, tgroup)
            try:
                h = c.health(timeout=5)
                last = (h.status, h.recovered_ballots)
                if h.status == "SERVING" and \
                        h.recovered_ballots == recovered:
                    return
            except grpc.RpcError:
                pass
            finally:
                c.close()
            time.sleep(0.5)
        raise AssertionError(f"service never SERVING/{recovered}: {last}")

    svc = RunCommand.python_module(
        "encryption-service", "electionguard_tpu.cli.run_encryption_service",
        ["-in", indir, "-out", out, "-port", str(port), "-maxBatch", "4",
         "-maxWaitMs", "15", "-fixedNonces", "-timestamp", str(_TS),
         "-noPrewarm", "-group", "tiny"],
        str(tmp_path / "logs"),
        env={"EGTPU_CHAOS_HOLD_AFTER_BALLOTS": "2"})
    try:
        wait_serving(recovered=0)
        client = EncryptionClient(url, tgroup)
        first = [client.encrypt(b, timeout=60) for b in ballots[:2]]
        assert [e.ballot_id for e in first] == \
            [b.ballot_id for b in ballots[:2]]
        # the worker is wedged; these admissions journal, then hang —
        # their client threads die with the SIGKILL'd connection
        def submit_lost(b):
            try:
                client.encrypt(b, timeout=60)
            except (grpc.RpcError, Exception):  # noqa: BLE001
                pass
        threads = [threading.Thread(target=submit_lost, args=(b,),
                                    daemon=True) for b in ballots[2:5]]
        for t in threads:
            t.start()
        jpath = os.path.join(out, wal.JOURNAL_NAME)
        deadline = time.monotonic() + 60
        while len(wal.replay(jpath)) < 5:
            assert time.monotonic() < deadline, "admissions never journaled"
            time.sleep(0.2)

        svc.kill_hard()          # SIGKILL: no handlers, no drain
        client.close()
        svc._env.pop("EGTPU_CHAOS_HOLD_AFTER_BALLOTS")
        svc.restart()
        wait_serving(recovered=3)

        client2 = EncryptionClient(url, tgroup)
        more = [client2.encrypt(b, timeout=60) for b in ballots[5:]]
        assert len(more) == 2
        client2.close()

        svc.process.terminate()  # SIGTERM: graceful drain + publish
        assert svc.wait_for(60) == 0, "drain did not exit cleanly"
    finally:
        svc.kill()

    assert os.path.getsize(os.path.join(out, wal.JOURNAL_NAME)) == 0
    cons = Consumer(out, tgroup)
    record = ElectionRecord(cons.read_election_initialized())
    record.encrypted_ballots = list(cons.iterate_encrypted_ballots())
    got_ids = [b.ballot_id for b in record.encrypted_ballots]
    # zero lost admitted ballots, the pre-crash prefix in order
    assert sorted(got_ids) == sorted(b.ballot_id for b in ballots)
    assert got_ids[:2] == [b.ballot_id for b in ballots[:2]]
    res = Verifier(record, tgroup).verify()
    assert res.ok, res.summary()
    # bit-for-bit: the offline encryptor over the record's admission
    # order reproduces ciphertexts and codes across BOTH crash boundaries
    by_id = {b.ballot_id: b for b in ballots}
    offline, invalid = BatchEncryptor(chaos_init, tgroup).encrypt_ballots(
        [by_id[i] for i in got_ids], seed=tgroup.int_to_q(42),
        timestamp=_TS)
    assert not invalid
    assert offline == record.encrypted_ballots

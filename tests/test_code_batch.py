"""batch_crypto_hashes / batch_codes must be byte-identical to the
per-ballot hash_digest tree (EncryptedBallot.crypto_hash /
is_valid_code) — including heterogeneous ballots (different id widths,
contest counts) and both the hashlib and device-SHA row paths."""

import dataclasses

import numpy as np

from electionguard_tpu.ballot.code_batch import (batch_codes,
                                                 batch_crypto_hashes)
from electionguard_tpu.ballot.plaintext import RandomBallotProvider
from electionguard_tpu.encrypt.encryptor import BatchEncryptor
from electionguard_tpu.keyceremony.exchange import key_ceremony_exchange
from electionguard_tpu.keyceremony.trustee import KeyCeremonyTrustee
from electionguard_tpu.publish.election_record import ElectionConfig
from electionguard_tpu.workflow.e2e import sample_manifest


def _encrypted(g, nballots, ncontests=2):
    manifest = sample_manifest(ncontests, 2)
    init = key_ceremony_exchange(
        [KeyCeremonyTrustee(g, "g0", 1, 1)], g).make_election_initialized(
        ElectionConfig(manifest, 1, 1), {})
    ballots = list(RandomBallotProvider(manifest, nballots,
                                        seed=6).ballots())
    enc = BatchEncryptor(init, g)
    out, invalid = enc.encrypt_ballots(ballots, seed=g.int_to_q(4))
    assert not invalid
    return out


def test_batch_matches_per_ballot(tgroup):
    encrypted = _encrypted(tgroup, 9)
    # make widths heterogeneous: stretch one ballot's id
    encrypted[3] = dataclasses.replace(
        encrypted[3], ballot_id=encrypted[3].ballot_id + "-stretched-id")
    hashes = batch_crypto_hashes(encrypted)
    codes = batch_codes(encrypted)
    for i, b in enumerate(encrypted):
        assert hashes[i].tobytes() == b.crypto_hash()
        assert codes[i].tobytes() == b.make_code(
            b.code_seed, b.timestamp, b.crypto_hash())


def test_encryptor_codes_still_valid_and_chained(tgroup):
    encrypted = _encrypted(tgroup, 7)
    assert all(b.is_valid_code() for b in encrypted)
    for prev, cur in zip(encrypted, encrypted[1:]):
        assert cur.code_seed == prev.code


def test_device_row_path_matches_hashlib(tgroup, monkeypatch):
    """Force the device SHA path (threshold 1) and compare."""
    encrypted = _encrypted(tgroup, 6, ncontests=1)
    want = batch_codes(encrypted)
    import electionguard_tpu.ballot.code_batch as cb
    monkeypatch.setattr(cb, "_DEVICE_MIN_ROWS", 1)
    got = batch_codes(encrypted)
    np.testing.assert_array_equal(got, want)

"""Test harness config: force an 8-device virtual CPU mesh.

Multi-chip hardware is not available in CI; sharding tests run on
``xla_force_host_platform_device_count=8`` CPU devices, mirroring how the
driver dry-runs the multi-chip path (see __graft_entry__.dryrun_multichip).
Must run before the first ``import jax`` anywhere in the test session.
"""

import os

# Detach from the axon TPU tunnel entirely: tests are CPU-only, and a wedged
# relay otherwise hangs `import jax` (the axon plugin dials the relay at
# backend init regardless of JAX_PLATFORMS).  One scrub rule for the whole
# codebase: utils.platform (pure stdlib, safe to import before jax).
from electionguard_tpu.utils.platform import detach_axon  # noqa: E402

detach_axon()
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def tgroup():
    from electionguard_tpu.core.group import tiny_group
    return tiny_group()


@pytest.fixture(scope="session")
def pgroup():
    from electionguard_tpu.core.group import production_group
    return production_group()

"""Test harness config: force an 8-device virtual CPU mesh.

Multi-chip hardware is not available in CI; sharding tests run on
``xla_force_host_platform_device_count=8`` CPU devices, mirroring how the
driver dry-runs the multi-chip path (see __graft_entry__.dryrun_multichip).
Must run before the first ``import jax`` anywhere in the test session.
"""

import os

# Detach from the axon TPU tunnel entirely: tests are CPU-only, and a wedged
# relay otherwise hangs `import jax` (the axon plugin dials the relay at
# backend init regardless of JAX_PLATFORMS).  One scrub rule for the whole
# codebase: utils.platform (pure stdlib, safe to import before jax).
from electionguard_tpu.utils.platform import detach_axon  # noqa: E402

detach_axon()
# Hermetic setup tables: never read/write an ambient on-disk table cache
# from tests (individual tests opt back in with a tmp_path dir).
os.environ.setdefault("EGTPU_TABLE_CACHE", "")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def tgroup():
    from electionguard_tpu.core.group import tiny_group
    return tiny_group()


@pytest.fixture(scope="session")
def pgroup():
    from electionguard_tpu.core.group import production_group
    return production_group()


@pytest.fixture(scope="session")
def election():
    """Full workflow artifacts on the tiny group, 3 guardians quorum 2
    (shared; tests must not mutate — use dataclasses.replace copies)."""
    from electionguard_tpu.ballot.plaintext import RandomBallotProvider
    from electionguard_tpu.core.dlog import DLog
    from electionguard_tpu.core.group import tiny_group
    from electionguard_tpu.decrypt.decryption import Decryption
    from electionguard_tpu.decrypt.trustee import DecryptingTrustee
    from electionguard_tpu.encrypt.encryptor import BatchEncryptor
    from electionguard_tpu.keyceremony.exchange import key_ceremony_exchange
    from electionguard_tpu.keyceremony.trustee import KeyCeremonyTrustee
    from electionguard_tpu.publish.election_record import (DecryptionResult,
                                                           ElectionConfig)
    from electionguard_tpu.tally.accumulate import accumulate_ballots
    from tests.test_keyceremony import tiny_manifest

    g = tiny_group()
    manifest = tiny_manifest()
    trustees = [KeyCeremonyTrustee(g, f"guardian-{i}", i + 1, 2)
                for i in range(3)]
    results = key_ceremony_exchange(trustees, g)
    init = results.make_election_initialized(
        ElectionConfig(manifest, 3, 2), {"created_by": "test"})

    ballots = list(RandomBallotProvider(manifest, 20, seed=7).ballots())
    enc = BatchEncryptor(init, g)
    encrypted, invalid = enc.encrypt_ballots(ballots, seed=g.int_to_q(99))
    assert not invalid

    tally_result = accumulate_ballots(init, encrypted)

    dec_trustees = [DecryptingTrustee.from_state(
        g, t.decrypting_trustee_state()) for t in trustees]
    decryption = Decryption(g, init, dec_trustees[:2],
                            [dec_trustees[2].id], DLog(g, max_exponent=100))
    decrypted = decryption.decrypt(tally_result.encrypted_tally)
    dr = DecryptionResult(
        tally_result, decrypted,
        tuple(decryption.get_available_guardians()))
    return dict(group=g, manifest=manifest, init=init, ballots=ballots,
                encrypted=encrypted, tally_result=tally_result,
                decryption_result=dr, trustees=trustees)


@pytest.fixture(scope="session")
def pelection(pgroup):
    """Small full-workflow record on the PRODUCTION group (1 guardian,
    quorum 1, 3 ballots, 1 contest x 2 selections), shared by every
    slow-marked production-path test: encryption runs through the fused
    device pipeline, decryption through the direct path."""
    from electionguard_tpu.ballot.plaintext import RandomBallotProvider
    from electionguard_tpu.core.dlog import DLog
    from electionguard_tpu.decrypt.decryption import Decryption
    from electionguard_tpu.decrypt.trustee import DecryptingTrustee
    from electionguard_tpu.encrypt.encryptor import BatchEncryptor
    from electionguard_tpu.keyceremony.exchange import key_ceremony_exchange
    from electionguard_tpu.keyceremony.trustee import KeyCeremonyTrustee
    from electionguard_tpu.publish.election_record import (DecryptionResult,
                                                           ElectionConfig)
    from electionguard_tpu.tally.accumulate import accumulate_ballots
    from electionguard_tpu.workflow.e2e import sample_manifest

    g = pgroup
    manifest = sample_manifest(ncontests=1, nselections=2)
    trustees = [KeyCeremonyTrustee(g, "guardian-0", 1, 1)]
    init = key_ceremony_exchange(trustees, g).make_election_initialized(
        ElectionConfig(manifest, 1, 1), {"created_by": "test"})
    ballots = list(RandomBallotProvider(manifest, 3, seed=5).ballots())
    enc = BatchEncryptor(init, g)
    encrypted, invalid = enc.encrypt_ballots(ballots, seed=g.int_to_q(11))
    assert not invalid
    tally_result = accumulate_ballots(init, encrypted)
    dec = Decryption(
        g, init,
        [DecryptingTrustee.from_state(g, trustees[0]
                                      .decrypting_trustee_state())],
        [], DLog(g, max_exponent=16))
    decrypted = dec.decrypt(tally_result.encrypted_tally)
    dr = DecryptionResult(tally_result, decrypted,
                          tuple(dec.get_available_guardians()))
    return dict(group=g, init=init, ballots=ballots, encrypted=encrypted,
                tally_result=tally_result, decryption_result=dr)

"""Persistent setup-table cache (core/table_cache): fingerprint keying,
atomic/torn-write safety, and the NttCtx / PowRadix integration."""

import glob
import os

import numpy as np
import pytest

import jax.numpy as jnp

from electionguard_tpu.core import ntt_mxu
from electionguard_tpu.core import table_cache as tc
from electionguard_tpu.core.group_jax import JaxGroupOps


@pytest.fixture
def cache_dir(tmp_path, monkeypatch):
    d = tmp_path / "tables"
    monkeypatch.setenv("EGTPU_TABLE_CACHE", str(d))
    tc.reset_stats()
    yield str(d)
    tc.reset_stats()


def test_disabled_by_default(monkeypatch):
    monkeypatch.setenv("EGTPU_TABLE_CACHE", "")
    assert tc.cache_dir() is None
    assert tc.load("kind", "00" * 32) is None
    tc.store("kind", "00" * 32, {"a": np.arange(3)})  # no-op, no error


def test_fingerprint_covers_every_field():
    base = tc.fingerprint("k", p="a", n=4)
    assert base == tc.fingerprint("k", n=4, p="a")      # order-free
    assert base != tc.fingerprint("k", p="a", n=5)
    assert base != tc.fingerprint("other", p="a", n=4)


def test_int_digest_large_ints():
    a, b = (1 << 4095) + 7, (1 << 4095) + 9
    assert tc.int_digest(a) != tc.int_digest(b)
    assert tc.int_digest(a) == tc.int_digest(a)
    assert tc.int_digest(0)  # zero-safe


def test_store_load_round_trip(cache_dir):
    arrays = {"x": np.arange(10, dtype=np.int32),
              "y": np.ones((2, 3), dtype=np.uint32)}
    fp = tc.fingerprint("demo", n=1)
    tc.store("demo", fp, arrays)
    assert tc.stats()["writes"] == 1
    got = tc.load("demo", fp)
    assert got is not None and tc.stats()["hits"] == 1
    assert sorted(got) == ["x", "y"]
    assert np.array_equal(got["x"], arrays["x"])
    assert np.array_equal(got["y"], arrays["y"])
    assert got["y"].dtype == np.uint32
    # no temp files left behind (mkstemp names start with a dot)
    assert not glob.glob(os.path.join(cache_dir, ".*.tmp"))


def test_miss_on_absent_and_foreign_fingerprint(cache_dir):
    fp1 = tc.fingerprint("demo", n=1)
    fp2 = tc.fingerprint("demo", n=2)
    assert tc.load("demo", fp1) is None          # absent
    tc.store("demo", fp1, {"x": np.arange(3)})
    assert tc.load("demo", fp2) is None          # different key
    # same path prefix but embedded fingerprint mismatch -> miss
    src = glob.glob(os.path.join(cache_dir, "demo-*.npz"))[0]
    dst = os.path.join(cache_dir, f"demo-{fp2[:32]}.npz")
    os.replace(src, dst)
    assert tc.load("demo", fp2) is None


def test_torn_write_degrades_to_rebuild(cache_dir):
    fp = tc.fingerprint("demo", n=1)
    tc.store("demo", fp, {"x": np.arange(3)})
    path = glob.glob(os.path.join(cache_dir, "demo-*.npz"))[0]
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[:len(blob) // 2])   # truncate mid-file
    tc.reset_stats()
    assert tc.load("demo", fp) is None
    s = tc.stats()
    assert s["errors"] == 1 and s["misses"] == 1 and s["hits"] == 0


def test_make_ntt_ctx_cache_round_trip(cache_dir, pgroup):
    p = pgroup.p
    ntt_mxu.make_ntt_ctx.cache_clear()
    cold = ntt_mxu.make_ntt_ctx(p)
    assert tc.stats()["writes"] == 1 and tc.stats()["hits"] == 0
    ntt_mxu.make_ntt_ctx.cache_clear()
    warm = ntt_mxu.make_ntt_ctx(p)
    assert tc.stats()["hits"] == 1
    # full NttCtx equality: arrays bit-for-bit, statics exactly
    assert cold.m == warm.m and cold.mprime == warm.mprime
    assert cold.mu26 == warm.mu26 and cold.mu27 == warm.mu27
    assert cold.biasc == warm.biasc and cold.inv12s == warm.inv12s
    for f in ("V0", "V1", "iV0", "iV1", "evoff0", "evoff1", "ivoff0",
              "ivoff1", "toep_m", "f_m", "toep_p", "f_p", "p_pad"):
        a, b = getattr(cold, f), getattr(warm, f)
        assert a.dtype == b.dtype and bool(jnp.all(a == b)), f
    ntt_mxu.make_ntt_ctx.cache_clear()


def test_powradix_tables_cache_round_trip(cache_dir, tgroup):
    ops_cold = JaxGroupOps(tgroup)           # writes powradix entries
    writes = tc.stats()["writes"]
    assert writes >= 1
    ops_warm = JaxGroupOps(tgroup)
    assert tc.stats()["hits"] >= 1
    assert tc.stats()["writes"] == writes    # nothing rebuilt
    assert bool(jnp.all(ops_cold.g_table == ops_warm.g_table))
    assert ops_cold.g_pow_ints([7]) == ops_warm.g_pow_ints([7])

"""Observability plane: metrics registry, Prometheus exposition, trace
spans, cross-process propagation over gRPC, and the client retry metrics
the rpc plane now records.

The subprocess twin — a full traced 5-phase workflow merged into one
Chrome-trace timeline — lives in tests/test_e2e_subprocess.py; here the
same machinery is pinned in-process so the non-slow tier covers it.
"""

import json
import logging
import os
import urllib.request

import grpc
import pytest

from electionguard_tpu.obs import assemble, httpd
from electionguard_tpu.obs import registry as reg
from electionguard_tpu.obs import slog, trace
from electionguard_tpu.publish import pb
from electionguard_tpu.remote import rpc_util
from electionguard_tpu.testing import faults


@pytest.fixture()
def clean_trace():
    """Each trace test starts and ends with tracing OFF (enable() is
    once-per-process in production; tests reset explicitly)."""
    trace._reset_for_tests()
    yield
    trace._reset_for_tests()


# =====================================================================
# registry
# =====================================================================


def test_registry_counter_gauge_histogram():
    r = reg.MetricsRegistry()
    c = r.counter("reqs_total", {"method": "foo"})
    c.inc()
    c.inc(4)
    # same (name, labels) -> same object
    assert r.counter("reqs_total", {"method": "foo"}) is c
    assert r.counter("reqs_total", {"method": "bar"}) is not c
    r.gauge("depth", fn=lambda: 7)
    h = r.histogram("lat_ms", (1.0, 10.0, 100.0))
    for v in (0.5, 5.0, 50.0, 500.0):
        h.observe(v)
    snap = r.snapshot()
    assert snap["counters"]['reqs_total{method="foo"}'] == 5
    assert snap["gauges"]["depth"] == 7
    hs = snap["histograms"]["lat_ms"]
    assert hs["counts"] == [1, 1, 1, 1] and hs["count"] == 4
    assert h.quantile(0.5) == 10.0 and h.mean() == pytest.approx(138.875)


def test_registry_merge_sums_across_processes():
    a = {"counters": {"x": 2, "y": 1}, "gauges": {"d": 3},
         "histograms": {"h": {"name": "h", "bounds": [1.0, 2.0],
                              "counts": [1, 0, 2], "sum": 5.0, "count": 3}}}
    b = {"counters": {"x": 5}, "gauges": {"d": 4},
         "histograms": {"h": {"name": "h", "bounds": [1.0, 2.0],
                              "counts": [0, 1, 1], "sum": 4.0, "count": 2}}}
    m = reg.MetricsRegistry.merge([a, b])
    assert m["counters"] == {"x": 7, "y": 1}
    assert m["gauges"] == {"d": 7}
    assert m["histograms"]["h"]["counts"] == [1, 1, 3]
    assert m["histograms"]["h"]["count"] == 5
    assert m["histograms"]["h"]["sum"] == 9.0


def test_prometheus_text_format():
    r = reg.MetricsRegistry()
    r.counter("reqs_total", {"method": "foo"}).inc(3)
    r.histogram("lat_ms", (1.0, 10.0)).observe(5.0)
    text = r.prometheus_text()
    assert "# TYPE egtpu_reqs_total counter" in text
    assert 'egtpu_reqs_total{method="foo"} 3' in text
    assert "# TYPE egtpu_lat_ms histogram" in text
    assert 'egtpu_lat_ms_bucket{le="10.0"} 1' in text
    assert 'egtpu_lat_ms_bucket{le="+Inf"} 1' in text
    assert "egtpu_lat_ms_count 1" in text


def test_http_endpoint_scrape():
    marker = reg.REGISTRY.counter("obs_test_scrape_total")
    marker.inc(11)
    server, port = httpd.start(0)
    try:
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
        assert "egtpu_obs_test_scrape_total 11" in text
        ok = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10).read()
        assert ok == b"ok\n"
    finally:
        server.shutdown()


def test_metrics_response_proto_roundtrip():
    r = reg.MetricsRegistry()
    r.counter("a_total").inc(2)
    r.gauge("g", fn=lambda: 9)
    r.histogram("h", (1.0,)).observe(0.5)
    resp = r.to_proto()
    assert resp.counters["a_total"] == 2
    assert resp.counters["g"] == 9
    assert resp.histograms[0].name == "h"
    assert list(resp.histograms[0].counts) == [1, 0]


# =====================================================================
# trace spans
# =====================================================================


def test_span_disabled_is_shared_noop(clean_trace):
    s1 = trace.span("anything")
    s2 = trace.span("else")
    assert s1 is s2  # the zero-allocation singleton
    with s1 as s:
        s.set("k", "v")   # must be inert, not raise
    assert trace.current_ids() == ("", "")


def test_span_export_and_parenting(clean_trace, tmp_path):
    trace.enable(str(tmp_path), trace_id_hex="ab" * 16, proc="t1")
    with trace.span("outer", {"k": 1}):
        with trace.span("inner"):
            pass
    trace.shutdown()
    spans = assemble.load_spans(str(tmp_path))
    by_name = {s["name"]: s for s in spans}
    assert set(by_name) == {"process", "outer", "inner"}
    assert all(s["trace_id"] == "ab" * 16 for s in spans)
    assert by_name["inner"]["parent_id"] == by_name["outer"]["span_id"]
    assert by_name["outer"]["parent_id"] == by_name["process"]["span_id"]
    assert by_name["outer"]["attrs"] == {"k": 1}
    report = assemble.validate(spans)
    assert report["orphans"] == [] and report["gaps"] == []
    # chrome trace is well-formed: one X event per span + process name
    ct = assemble.chrome_trace(spans)
    xs = [e for e in ct["traceEvents"] if e["ph"] == "X"]
    assert len(xs) == 3 and all(e["dur"] >= 1 for e in xs)


def test_rpc_trace_propagation_and_default_metrics_rpc(clean_trace,
                                                       tmp_path):
    """Client and server spans of one rpc share the trace id, nest
    client->server across the wire, and a service with no explicit
    getMetrics impl still answers it from the registry."""
    trace.enable(str(tmp_path), proc="rpc-test")

    def impl(request, context):
        return pb.msg("RegisterKeyCeremonyTrusteeResponse")(
            guardian_id=request.guardian_id, x_coordinate=1, quorum=1)

    server, port = rpc_util.make_server(0)
    server.add_generic_rpc_handlers((rpc_util.generic_service(
        "RemoteKeyCeremonyService", {"registerTrustee": impl}),))
    server.start()
    channel = rpc_util.make_channel(f"localhost:{port}")
    stub = rpc_util.Stub(channel, "RemoteKeyCeremonyService")
    try:
        resp = stub.call("registerTrustee",
                         pb.msg("RegisterKeyCeremonyTrusteeRequest")(
                             guardian_id="g"))
        assert resp.x_coordinate == 1
        m = stub.call("getMetrics", pb.msg("MetricsRequest")())
        calls = {k: v for k, v in m.counters.items()
                 if k.startswith("rpc_server_calls_total")}
        assert any("registerTrustee" in k for k in calls)
    finally:
        channel.close()
        server.stop(grace=0)
    trace.shutdown()
    spans = assemble.load_spans(str(tmp_path))
    report = assemble.validate(spans)
    assert len(report["trace_ids"]) == 1
    assert report["orphans"] == [] and report["gaps"] == []
    assert report["rpc_pairs"] == 2 and report["rpc_server_unpaired"] == 0
    client = [s for s in spans
              if s["name"] == "rpc.client.registerTrustee"][0]
    srv = [s for s in spans
           if s["name"] == "rpc.server.registerTrustee"][0]
    assert srv["parent_id"] == client["span_id"]
    # server span nests inside the client span's window
    assert (client["ts"] <= srv["ts"]
            and srv["ts"] + srv["dur"] <= client["ts"] + client["dur"] + 1)


def test_stub_call_records_retry_metrics():
    """Satellite: retries/backoff are visible in the registry even
    without a fault-plan audit log."""
    def d(name, labels):
        return reg.REGISTRY.counter(name, labels).value

    labels = {"method": "registerTrustee", "class": "registration"}
    before = (d("rpc_client_calls_total", labels),
              d("rpc_client_retries_total", labels),
              d("rpc_client_backoff_seconds_total", labels))

    def impl(request, context):
        return pb.msg("RegisterKeyCeremonyTrusteeResponse")(
            guardian_id="g", x_coordinate=1, quorum=1)

    plan = faults.install(faults.FaultPlan(rules=[
        faults.FaultRule(method="registerTrustee", kind="unavailable",
                         on_calls=(1, 2))]))
    server, port = rpc_util.make_server(0)
    server.add_generic_rpc_handlers((rpc_util.generic_service(
        "RemoteKeyCeremonyService", {"registerTrustee": impl}),))
    server.start()
    channel = rpc_util.make_channel(f"localhost:{port}")
    stub = rpc_util.Stub(channel, "RemoteKeyCeremonyService")
    pol = rpc_util.RetryPolicy(attempts=3, base_wait=0.01, max_wait=0.02,
                               connect_window=1.0, budget=10.0)
    try:
        resp = stub.call("registerTrustee",
                         pb.msg("RegisterKeyCeremonyTrusteeRequest")(
                             guardian_id="g"),
                         timeout=30, policy=pol)
        assert resp.x_coordinate == 1
        assert len(plan.injected) == 2
    finally:
        faults.clear()
        channel.close()
        server.stop(grace=0)
    assert d("rpc_client_calls_total", labels) == before[0] + 1
    assert d("rpc_client_retries_total", labels) == before[1] + 2
    assert d("rpc_client_backoff_seconds_total", labels) > before[2]


def test_stub_call_records_failures():
    before = None
    port = rpc_util.find_free_port()
    channel = rpc_util.make_channel(f"localhost:{port}")
    stub = rpc_util.Stub(channel, "RemoteKeyCeremonyService")
    labels = {"method": "registerTrustee", "code": "UNAVAILABLE"}
    before = reg.REGISTRY.counter("rpc_client_failures_total", labels).value
    pol = rpc_util.RetryPolicy(attempts=1, base_wait=0.01, max_wait=0.01,
                               connect_window=0.05, budget=1.0)
    try:
        with pytest.raises(grpc.RpcError):
            stub.call("registerTrustee",
                      pb.msg("RegisterKeyCeremonyTrusteeRequest")(
                          guardian_id="x"), timeout=5, policy=pol)
    finally:
        channel.close()
    after = reg.REGISTRY.counter("rpc_client_failures_total", labels).value
    assert after == before + 1


# =====================================================================
# structured log mirror + serving summary
# =====================================================================


def test_slog_jsonl_carries_trace_context(clean_trace, tmp_path):
    trace.enable(str(tmp_path), trace_id_hex="cd" * 16, proc="slogt")
    handler = slog.JsonlHandler(str(tmp_path / "log.jsonl"))
    logger = logging.getLogger("egtpu.test.slog")
    logger.addHandler(handler)
    logger.setLevel(logging.INFO)
    try:
        with trace.span("op") as sp:
            logger.info("hello %s", "world")
            span_id = sp.span_id
    finally:
        logger.removeHandler(handler)
        handler.close()
    rows = [json.loads(ln) for ln in open(tmp_path / "log.jsonl")]
    assert rows[0]["msg"] == "hello world"
    assert rows[0]["trace_id"] == "cd" * 16
    assert rows[0]["span_id"] == span_id
    assert rows[0]["pid"] == os.getpid()


def test_service_metrics_summary_surfaces_failed_and_recovered():
    """Satellite: requests_failed and ballots_recovered were counted but
    never surfaced in the drain log."""
    from electionguard_tpu.serve.metrics import ServiceMetrics
    m = ServiceMetrics(queue_depth=lambda: 2)
    m.inc("requests_failed", 3)
    m.inc("ballots_recovered", 5)
    s = m.summary()
    assert "failed=3" in s
    assert "recovered=5" in s
    assert "queue_depth=2" in s

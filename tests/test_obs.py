"""Observability plane: metrics registry, Prometheus exposition, trace
spans, cross-process propagation over gRPC, and the client retry metrics
the rpc plane now records.

The subprocess twin — a full traced 5-phase workflow merged into one
Chrome-trace timeline — lives in tests/test_e2e_subprocess.py; here the
same machinery is pinned in-process so the non-slow tier covers it.
"""

import json
import logging
import os
import urllib.request

import grpc
import pytest

from electionguard_tpu.obs import assemble, httpd
from electionguard_tpu.obs import registry as reg
from electionguard_tpu.obs import slog, trace
from electionguard_tpu.publish import pb
from electionguard_tpu.remote import rpc_util
from electionguard_tpu.testing import faults


@pytest.fixture()
def clean_trace():
    """Each trace test starts and ends with tracing OFF (enable() is
    once-per-process in production; tests reset explicitly)."""
    trace._reset_for_tests()
    yield
    trace._reset_for_tests()


# =====================================================================
# registry
# =====================================================================


def test_registry_counter_gauge_histogram():
    r = reg.MetricsRegistry()
    c = r.counter("reqs_total", {"method": "foo"})
    c.inc()
    c.inc(4)
    # same (name, labels) -> same object
    assert r.counter("reqs_total", {"method": "foo"}) is c
    assert r.counter("reqs_total", {"method": "bar"}) is not c
    r.gauge("depth", fn=lambda: 7)
    h = r.histogram("lat_ms", (1.0, 10.0, 100.0))
    for v in (0.5, 5.0, 50.0, 500.0):
        h.observe(v)
    snap = r.snapshot()
    assert snap["counters"]['reqs_total{method="foo"}'] == 5
    assert snap["gauges"]["depth"] == 7
    hs = snap["histograms"]["lat_ms"]
    assert hs["counts"] == [1, 1, 1, 1] and hs["count"] == 4
    assert h.quantile(0.5) == 10.0 and h.mean() == pytest.approx(138.875)


def test_registry_merge_sums_across_processes():
    a = {"counters": {"x": 2, "y": 1}, "gauges": {"d": 3},
         "histograms": {"h": {"name": "h", "bounds": [1.0, 2.0],
                              "counts": [1, 0, 2], "sum": 5.0, "count": 3}}}
    b = {"counters": {"x": 5}, "gauges": {"d": 4},
         "histograms": {"h": {"name": "h", "bounds": [1.0, 2.0],
                              "counts": [0, 1, 1], "sum": 4.0, "count": 2}}}
    m = reg.MetricsRegistry.merge([a, b])
    assert m["counters"] == {"x": 7, "y": 1}
    assert m["gauges"] == {"d": 7}
    assert m["histograms"]["h"]["counts"] == [1, 1, 3]
    assert m["histograms"]["h"]["count"] == 5
    assert m["histograms"]["h"]["sum"] == 9.0


def test_prometheus_text_format():
    r = reg.MetricsRegistry()
    r.counter("reqs_total", {"method": "foo"}).inc(3)
    r.histogram("lat_ms", (1.0, 10.0)).observe(5.0)
    text = r.prometheus_text()
    assert "# TYPE egtpu_reqs_total counter" in text
    assert 'egtpu_reqs_total{method="foo"} 3' in text
    assert "# TYPE egtpu_lat_ms histogram" in text
    assert 'egtpu_lat_ms_bucket{le="10.0"} 1' in text
    assert 'egtpu_lat_ms_bucket{le="+Inf"} 1' in text
    assert "egtpu_lat_ms_count 1" in text


def test_label_value_escaping_round_trips_through_flat_name():
    """Satellite: flat_name escapes backslash/quote/newline per the
    Prometheus text format, and slo.parse_labels inverts it exactly —
    including values containing ``,`` and ``=`` that the old naive
    splitter mangled."""
    from electionguard_tpu.obs import slo as slo_mod
    nasty = 'pre"cinct\\7\n, ward="N"'
    flat = reg.flat_name("ballots_total",
                         {"election": nasty, "shard": "3"})
    assert "\n" not in flat               # exposition stays line-based
    name, labels = slo_mod.parse_labels(flat)
    assert name == "ballots_total"
    assert labels == {"election": nasty, "shard": "3"}
    # and the registry get-or-create keyed on the flat name agrees
    r = reg.MetricsRegistry()
    c = r.counter("ballots_total", {"election": nasty})
    c.inc(2)
    snap = r.snapshot()
    [(k, v)] = snap["counters"].items()
    assert slo_mod.parse_labels(k)[1]["election"] == nasty and v == 2


def test_http_scrape_parse_round_trip_with_hostile_labels():
    """Satellite: a counter whose label value holds quotes, backslashes
    and newlines survives a REAL http scrape — correct versioned
    Content-Type, one line per series, and the line parses back to the
    original value."""
    from electionguard_tpu.obs import slo as slo_mod
    hostile = 'a"b\\c\nd'
    reg.REGISTRY.counter("obs_hostile_total",
                         {"election": hostile}).inc(5)
    server, port = httpd.start(0)
    try:
        resp = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10)
        assert resp.headers["Content-Type"] == \
            "text/plain; version=0.0.4; charset=utf-8"
        text = resp.read().decode()
    finally:
        server.shutdown()
    line = [ln for ln in text.splitlines()
            if ln.startswith("egtpu_obs_hostile_total{")][0]
    series, value = line.rsplit(" ", 1)
    assert int(value) == 5
    _, labels = slo_mod.parse_labels(series[len("egtpu_"):])
    assert labels["election"] == hostile


def test_http_endpoint_scrape():
    marker = reg.REGISTRY.counter("obs_test_scrape_total")
    marker.inc(11)
    server, port = httpd.start(0)
    try:
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
        assert "egtpu_obs_test_scrape_total 11" in text
        ok = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10).read()
        assert ok == b"ok\n"
    finally:
        server.shutdown()


def test_metrics_response_proto_roundtrip():
    r = reg.MetricsRegistry()
    r.counter("a_total").inc(2)
    r.gauge("g", fn=lambda: 9)
    r.histogram("h", (1.0,)).observe(0.5)
    resp = r.to_proto()
    assert resp.counters["a_total"] == 2
    assert resp.counters["g"] == 9
    assert resp.histograms[0].name == "h"
    assert list(resp.histograms[0].counts) == [1, 0]


# =====================================================================
# trace spans
# =====================================================================


def test_span_disabled_is_shared_noop(clean_trace):
    s1 = trace.span("anything")
    s2 = trace.span("else")
    assert s1 is s2  # the zero-allocation singleton
    with s1 as s:
        s.set("k", "v")   # must be inert, not raise
    assert trace.current_ids() == ("", "")


def test_span_export_and_parenting(clean_trace, tmp_path):
    trace.enable(str(tmp_path), trace_id_hex="ab" * 16, proc="t1")
    with trace.span("outer", {"k": 1}):
        with trace.span("inner"):
            pass
    trace.shutdown()
    spans = assemble.load_spans(str(tmp_path))
    by_name = {s["name"]: s for s in spans}
    assert set(by_name) == {"process", "outer", "inner"}
    assert all(s["trace_id"] == "ab" * 16 for s in spans)
    assert by_name["inner"]["parent_id"] == by_name["outer"]["span_id"]
    assert by_name["outer"]["parent_id"] == by_name["process"]["span_id"]
    assert by_name["outer"]["attrs"] == {"k": 1}
    report = assemble.validate(spans)
    assert report["orphans"] == [] and report["gaps"] == []
    # chrome trace is well-formed: one X event per span + process name
    ct = assemble.chrome_trace(spans)
    xs = [e for e in ct["traceEvents"] if e["ph"] == "X"]
    assert len(xs) == 3 and all(e["dur"] >= 1 for e in xs)


def test_rpc_trace_propagation_and_default_metrics_rpc(clean_trace,
                                                       tmp_path):
    """Client and server spans of one rpc share the trace id, nest
    client->server across the wire, and a service with no explicit
    getMetrics impl still answers it from the registry."""
    trace.enable(str(tmp_path), proc="rpc-test")

    def impl(request, context):
        return pb.msg("RegisterKeyCeremonyTrusteeResponse")(
            guardian_id=request.guardian_id, x_coordinate=1, quorum=1)

    server, port = rpc_util.make_server(0)
    server.add_generic_rpc_handlers((rpc_util.generic_service(
        "RemoteKeyCeremonyService", {"registerTrustee": impl}),))
    server.start()
    channel = rpc_util.make_channel(f"localhost:{port}")
    stub = rpc_util.Stub(channel, "RemoteKeyCeremonyService")
    try:
        resp = stub.call("registerTrustee",
                         pb.msg("RegisterKeyCeremonyTrusteeRequest")(
                             guardian_id="g"))
        assert resp.x_coordinate == 1
        m = stub.call("getMetrics", pb.msg("MetricsRequest")())
        calls = {k: v for k, v in m.counters.items()
                 if k.startswith("rpc_server_calls_total")}
        assert any("registerTrustee" in k for k in calls)
    finally:
        channel.close()
        server.stop(grace=0)
    trace.shutdown()
    spans = assemble.load_spans(str(tmp_path))
    report = assemble.validate(spans)
    assert len(report["trace_ids"]) == 1
    assert report["orphans"] == [] and report["gaps"] == []
    assert report["rpc_pairs"] == 2 and report["rpc_server_unpaired"] == 0
    client = [s for s in spans
              if s["name"] == "rpc.client.registerTrustee"][0]
    srv = [s for s in spans
           if s["name"] == "rpc.server.registerTrustee"][0]
    assert srv["parent_id"] == client["span_id"]
    # server span nests inside the client span's window
    assert (client["ts"] <= srv["ts"]
            and srv["ts"] + srv["dur"] <= client["ts"] + client["dur"] + 1)


def test_stub_call_records_retry_metrics():
    """Satellite: retries/backoff are visible in the registry even
    without a fault-plan audit log."""
    def d(name, labels):
        return reg.REGISTRY.counter(name, labels).value

    labels = {"method": "registerTrustee", "class": "registration"}
    before = (d("rpc_client_calls_total", labels),
              d("rpc_client_retries_total", labels),
              d("rpc_client_backoff_seconds_total", labels))

    def impl(request, context):
        return pb.msg("RegisterKeyCeremonyTrusteeResponse")(
            guardian_id="g", x_coordinate=1, quorum=1)

    plan = faults.install(faults.FaultPlan(rules=[
        faults.FaultRule(method="registerTrustee", kind="unavailable",
                         on_calls=(1, 2))]))
    server, port = rpc_util.make_server(0)
    server.add_generic_rpc_handlers((rpc_util.generic_service(
        "RemoteKeyCeremonyService", {"registerTrustee": impl}),))
    server.start()
    channel = rpc_util.make_channel(f"localhost:{port}")
    stub = rpc_util.Stub(channel, "RemoteKeyCeremonyService")
    pol = rpc_util.RetryPolicy(attempts=3, base_wait=0.01, max_wait=0.02,
                               connect_window=1.0, budget=10.0)
    try:
        resp = stub.call("registerTrustee",
                         pb.msg("RegisterKeyCeremonyTrusteeRequest")(
                             guardian_id="g"),
                         timeout=30, policy=pol)
        assert resp.x_coordinate == 1
        assert len(plan.injected) == 2
    finally:
        faults.clear()
        channel.close()
        server.stop(grace=0)
    assert d("rpc_client_calls_total", labels) == before[0] + 1
    assert d("rpc_client_retries_total", labels) == before[1] + 2
    assert d("rpc_client_backoff_seconds_total", labels) > before[2]


def test_stub_call_records_failures():
    before = None
    port = rpc_util.find_free_port()
    channel = rpc_util.make_channel(f"localhost:{port}")
    stub = rpc_util.Stub(channel, "RemoteKeyCeremonyService")
    labels = {"method": "registerTrustee", "code": "UNAVAILABLE"}
    before = reg.REGISTRY.counter("rpc_client_failures_total", labels).value
    pol = rpc_util.RetryPolicy(attempts=1, base_wait=0.01, max_wait=0.01,
                               connect_window=0.05, budget=1.0)
    try:
        with pytest.raises(grpc.RpcError):
            stub.call("registerTrustee",
                      pb.msg("RegisterKeyCeremonyTrusteeRequest")(
                          guardian_id="x"), timeout=5, policy=pol)
    finally:
        channel.close()
    after = reg.REGISTRY.counter("rpc_client_failures_total", labels).value
    assert after == before + 1


# =====================================================================
# structured log mirror + serving summary
# =====================================================================


def test_slog_jsonl_carries_trace_context(clean_trace, tmp_path):
    trace.enable(str(tmp_path), trace_id_hex="cd" * 16, proc="slogt")
    handler = slog.JsonlHandler(str(tmp_path / "log.jsonl"))
    logger = logging.getLogger("egtpu.test.slog")
    logger.addHandler(handler)
    logger.setLevel(logging.INFO)
    try:
        with trace.span("op") as sp:
            logger.info("hello %s", "world")
            span_id = sp.span_id
    finally:
        logger.removeHandler(handler)
        handler.close()
    rows = [json.loads(ln) for ln in open(tmp_path / "log.jsonl")]
    assert rows[0]["msg"] == "hello world"
    assert rows[0]["trace_id"] == "cd" * 16
    assert rows[0]["span_id"] == span_id
    assert rows[0]["pid"] == os.getpid()


def test_service_metrics_summary_surfaces_failed_and_recovered():
    """Satellite: requests_failed and ballots_recovered were counted but
    never surfaced in the drain log."""
    from electionguard_tpu.serve.metrics import ServiceMetrics
    m = ServiceMetrics(queue_depth=lambda: 2)
    m.inc("requests_failed", 3)
    m.inc("ballots_recovered", 5)
    s = m.summary()
    assert "failed=3" in s
    assert "recovered=5" in s
    assert "queue_depth=2" in s


# =====================================================================
# telemetry collector + slo engine
# =====================================================================


def _batch(proc, pid, seq=1, status="SERVING", phase="", metrics=None,
           span_lines=(), log_lines=()):
    """One TelemetryBatch as a pushing process would build it."""
    return pb.msg("TelemetryBatch")(
        proc=proc, pid=pid, seq=seq,
        span_lines=list(span_lines), log_lines=list(log_lines),
        metrics_json=json.dumps(metrics) if metrics else "",
        heartbeat=pb.msg("ObsHeartbeat")(status=status, phase=phase))


def _quiet_slo(**over):
    """An SLO config with every objective but heartbeat liveness pushed
    out of reach, so tests of one check never trip on registry state
    left behind by earlier tests in the same process."""
    from electionguard_tpu.obs import slo as slo_mod
    return slo_mod.load_config(json.dumps({
        "serving_p99_ms": {"objective": 1e12},
        "queue_depth_max": 10**9,
        "stage_lag_s": 1e12,
        "availability": {"fast_burn": 1e12, "slow_burn": 1e12},
        **over}))


def test_fleet_scrape_merges_labeled_histograms_three_processes(tmp_path):
    """Satellite: three simulated processes push labeled histograms with
    overlapping AND disjoint label sets; the one fleet scrape sums
    shared series bucket-exactly and keeps the rest distinct under their
    ``proc=`` label."""
    from electionguard_tpu.obs import collector as coll

    def hist(counts, total):
        return {"name": "request_latency_ms", "bounds": [10.0, 100.0],
                "counts": counts, "sum": total, "count": sum(counts)}

    c = coll.ObsCollector(str(tmp_path), slo_config=_quiet_slo())
    # two shards of the SAME role share the {op="enc"} series; shard b
    # also carries a disjoint {op="dec"} series; a third, different role
    # reports the same base name unlabeled
    c.push_telemetry(_batch("simshard", 101, metrics={
        "histograms": {'request_latency_ms{op="enc"}': hist([1, 2, 3], 50.0)},
        "counters": {'simreq_total{op="enc"}': 4}}))
    c.push_telemetry(_batch("simshard", 102, metrics={
        "histograms": {'request_latency_ms{op="enc"}': hist([4, 0, 1], 7.0),
                       'request_latency_ms{op="dec"}': hist([0, 1, 0], 20.0)},
        "counters": {'simreq_total{op="enc"}': 2}}))
    c.push_telemetry(_batch("simverify", 103, metrics={
        "histograms": {"request_latency_ms": hist([2, 2, 2], 60.0)}}))

    snap = c.fleet_snapshot()
    merged = snap["histograms"][
        'request_latency_ms{op="enc",proc="simshard"}']
    assert merged["counts"] == [5, 2, 4]          # bucket-exact sums
    assert merged["count"] == 11 and merged["sum"] == 57.0
    lone = snap["histograms"][
        'request_latency_ms{op="dec",proc="simshard"}']
    assert lone["counts"] == [0, 1, 0] and lone["sum"] == 20.0
    other = snap["histograms"]['request_latency_ms{proc="simverify"}']
    assert other["counts"] == [2, 2, 2]           # role stays distinct
    assert snap["counters"]['simreq_total{op="enc",proc="simshard"}'] == 6
    # the same series survive into the Prometheus exposition
    text = c.fleet_text()
    assert ('egtpu_request_latency_ms_bucket'
            '{op="enc",proc="simshard",le="10.0"} 5') in text


def test_collector_persists_heartbeat_stream(tmp_path):
    """Every pushed heartbeat lands as one JSONL row in the receive
    dir, where post-run trace analytics reads queue depths and shard
    phases (obs/analyze.load_heartbeats)."""
    from electionguard_tpu.obs import analyze
    from electionguard_tpu.obs import collector as coll

    c = coll.ObsCollector(str(tmp_path), slo_config=_quiet_slo())
    c.push_telemetry(pb.msg("TelemetryBatch")(
        proc="simworker", pid=9, seq=1,
        heartbeat=pb.msg("ObsHeartbeat")(
            status="SERVING", phase="serving shard=3 head=ab admitted=5",
            queue_depth=7, uptime_s=1.5)))
    c.push_telemetry(pb.msg("TelemetryBatch")(
        proc="simworker", pid=9, seq=2,
        heartbeat=pb.msg("ObsHeartbeat")(status="SERVING",
                                         queue_depth=2)))
    path = os.path.join(str(tmp_path), "recv", "heartbeats.jsonl")
    with open(path) as f:
        rows = [json.loads(line) for line in f]
    assert [r["queue_depth"] for r in rows] == [7, 2]
    assert all(r["proc"] == "simworker" and r["pid"] == 9 for r in rows)
    # the analyzer reads them back (and parses the shard id)
    hbs = analyze.load_heartbeats(os.path.join(str(tmp_path), "recv"))
    assert len(hbs) == 2
    assert hbs[0]["phase"].startswith("serving shard=3")


def test_retain_spec_parsing():
    """EGTPU_OBS_RETAIN grammar: SIZE[,AGE] with KB/MB/GB and s/m/h/d
    suffixes; either half may be empty; junk raises."""
    import pytest

    from electionguard_tpu.obs import collector as coll
    assert coll.parse_retain("") == (None, None)
    assert coll.parse_retain("256MB,24h") == (256 * 1024 ** 2, 86400.0)
    assert coll.parse_retain("4kb") == (4096, None)
    assert coll.parse_retain("1000") == (1000, None)
    assert coll.parse_retain(",30m") == (None, 1800.0)
    assert coll.parse_retain("1.5GB,90s") == \
        (int(1.5 * 1024 ** 3), 90.0)
    for bad in ("24h", "1MB,fast", "1MB,2h,3d", "lots"):
        with pytest.raises(ValueError):
            coll.parse_retain(bad)


def test_collector_retention_rotates_oldest_first(tmp_path, monkeypatch):
    """Satellite: with EGTPU_OBS_RETAIN set, the eval-loop retention
    pass deletes receive-dir files past the age cap, then oldest-first
    until under the size cap — counting each in
    obs_rotated_files_total — and an evicted stream reappears on its
    next append."""
    from electionguard_tpu.obs import collector as coll
    from electionguard_tpu.obs import registry

    monkeypatch.setenv("EGTPU_OBS_RETAIN", "150,1h")
    c = coll.ObsCollector(str(tmp_path), slo_config=_quiet_slo())
    assert (c.retain_bytes, c.retain_age_s) == (150, 3600.0)
    span = json.dumps({"name": "s", "t0": 0, "dur": 1})
    for pid in (1, 2, 3):
        c.push_telemetry(_batch("simworker", pid, span_lines=[span] * 2))
    recv = os.path.join(str(tmp_path), "recv")
    now = 1_000_000.0
    # pid 1 far past the age cap, pid 2 inside it but oldest under the
    # size cap, pid 3 fresh; heartbeats.jsonl fresh too
    os.utime(os.path.join(recv, "spans-simworker-1.jsonl"),
             (now - 7200, now - 7200))
    os.utime(os.path.join(recv, "spans-simworker-2.jsonl"),
             (now - 60, now - 60))
    for name in ("spans-simworker-3.jsonl", "heartbeats.jsonl"):
        os.utime(os.path.join(recv, name), (now, now))
    # size the cap so exactly the two fresh files fit under it
    c.retain_bytes = (
        os.path.getsize(os.path.join(recv, "spans-simworker-3.jsonl"))
        + os.path.getsize(os.path.join(recv, "heartbeats.jsonl")))
    before = registry.REGISTRY.counter("obs_rotated_files_total").value

    rotated = c._enforce_retention(now=now)

    assert rotated == 2
    left = sorted(os.listdir(recv))
    assert "spans-simworker-1.jsonl" not in left      # age-capped
    assert "spans-simworker-2.jsonl" not in left      # size cap, oldest
    assert "spans-simworker-3.jsonl" in left
    assert "heartbeats.jsonl" in left
    assert registry.REGISTRY.counter(
        "obs_rotated_files_total").value == before + 2
    # nothing over cap now: a second pass is a no-op
    assert c._enforce_retention(now=now) == 0
    # the evicted stream comes back on the next push
    c.push_telemetry(_batch("simworker", 1, seq=2, span_lines=[span]))
    assert os.path.exists(os.path.join(recv, "spans-simworker-1.jsonl"))


def test_collector_retention_disabled_by_default(tmp_path):
    """No EGTPU_OBS_RETAIN -> retention is a no-op (unbounded)."""
    from electionguard_tpu.obs import collector as coll
    c = coll.ObsCollector(str(tmp_path), slo_config=_quiet_slo())
    assert (c.retain_bytes, c.retain_age_s) == (None, None)
    c.push_telemetry(_batch("simworker", 5, span_lines=[
        json.dumps({"name": "s", "t0": 0, "dur": 1})]))
    assert c._enforce_retention(now=1e12) == 0
    assert os.path.exists(os.path.join(
        str(tmp_path), "recv", "spans-simworker-5.jsonl"))


def test_collector_heartbeat_death_red_window_and_recovery(tmp_path,
                                                           clean_trace):
    """Liveness end to end against the collector, clock injected: a
    SERVING process goes silent, the heartbeat_miss alert fires ONCE
    (edge-triggered) well inside any rpc deadline class, the process is
    flagged DEAD and the fleet goes red — then green again once the
    death ages past dead_red_for_s."""
    from electionguard_tpu.obs import collector as coll
    from electionguard_tpu.utils import clock
    c = coll.ObsCollector(str(tmp_path), slo_config=_quiet_slo())
    t0 = clock.monotonic()
    c.push_telemetry(_batch("victim", 4242, status="SERVING",
                            phase="mix-stage-0"))
    assert c.evaluate_once(now=t0 + 1.0) == []
    assert c._health == "green"

    # 5s of silence > the 3s (= 3 x 1s) window
    fired = c.evaluate_once(now=t0 + 5.0)
    assert [a.kind for a in fired] == ["heartbeat_miss"]
    alert = fired[0]
    assert alert.subject == "victim"
    assert alert.attrs["window_s"] == pytest.approx(3.0)
    assert alert.attrs["window_s"] < alert.attrs["detection_s"] < 600.0
    st = c.get_fleet_status()
    assert st.health == "red"
    assert [(p.proc, p.state) for p in st.processes] == [("victim", "DEAD")]
    assert any("heartbeat_miss" in a for a in st.alerts)

    # edge-triggered: continued silence does not re-fire
    assert c.evaluate_once(now=t0 + 6.0) == []
    assert c._health == "red"
    # past dead_red_for_s (10s) the death is recorded history
    c.evaluate_once(now=t0 + 5.0 + 10.5)
    assert c._health == "green"
    assert c.get_fleet_status().health == "green"


def test_collector_exiting_goodbye_is_not_a_death(tmp_path, clean_trace):
    """The atexit goodbye (status EXITING) followed by silence means a
    clean shutdown: state EXITED, no alert, fleet stays green."""
    from electionguard_tpu.obs import collector as coll
    from electionguard_tpu.utils import clock
    c = coll.ObsCollector(str(tmp_path), slo_config=_quiet_slo())
    t0 = clock.monotonic()
    c.push_telemetry(_batch("worker", 77, status="EXITING"))
    assert c.evaluate_once(now=t0 + 5.0) == []
    st = c.get_fleet_status()
    assert st.health == "green"
    assert [p.state for p in st.processes] == ["EXITED"]


def test_telemetry_client_buffer_drop_oldest():
    """Hot-path contract: a full client buffer drops the OLDEST line and
    counts it in obs_dropped_total — the exporting thread never blocks,
    never grows unbounded."""
    from electionguard_tpu.obs import collector as coll
    client = coll.TelemetryClient("localhost:1", max_buffer=5)
    before = reg.REGISTRY.counter("obs_dropped_total").value
    for i in range(8):
        client._enqueue("span", f'{{"i":{i}}}')
    assert len(client._buf) == 5
    assert [ln for _, ln in client._buf] == [
        f'{{"i":{i}}}' for i in range(3, 8)]
    assert reg.REGISTRY.counter("obs_dropped_total").value == before + 3


def test_collector_live_assembly_survives_dead_process(tmp_path,
                                                       clean_trace):
    """A process pushes an open root marker plus a closed child, then
    dies without ever closing the root.  The collector's live assembly
    — and a PLAIN file assembly of its receive dir — must both be
    strict-valid, with the in-flight root reported as open, not failed
    as an orphan/gap."""
    from electionguard_tpu.obs import collector as coll
    c = coll.ObsCollector(str(tmp_path), slo_config=_quiet_slo())
    tid = "ef" * 16
    root = {"trace_id": tid, "span_id": "a" * 16, "parent_id": "",
            "name": "process", "proc": "victim", "pid": 7, "tid": 0,
            "ts": 0, "open": True}
    child = {"trace_id": tid, "span_id": "b" * 16, "parent_id": "a" * 16,
             "name": "work", "proc": "victim", "pid": 7, "tid": 0,
             "ts": 10, "dur": 5}
    c.push_telemetry(_batch("victim", 7, span_lines=[
        json.dumps(root), json.dumps(child)]))
    c._assemble_live()

    report = c.live_report
    assert report["trace_ids"] == [tid]
    assert report["orphans"] == [] and report["gaps"] == []
    assert report["open_spans"] == ["a" * 16]
    assert os.path.exists(c.live_path)
    # the report is persisted beside the timeline for dead-run consumers
    with open(os.path.join(str(tmp_path), "trace_live_report.json")) as f:
        assert json.load(f)["open_spans"] == ["a" * 16]
    # the open markers are persisted as a spans file, so assembling the
    # receive dir from files ALONE (mid-run, or after the collector
    # died too) resolves the in-flight parent
    spans = assemble.load_spans(c.recv_dir)
    file_report = assemble.validate(spans)
    assert file_report["orphans"] == [] and file_report["gaps"] == []
    assert file_report["open_spans"] == ["a" * 16]


def test_egtop_once_renders_fleet_board(tmp_path, clean_trace):
    """The mission-control tool end to end: egtop -once against a live
    collector server prints one frame with the fleet line and a row per
    process, and exits 0 (1 when the collector is unreachable)."""
    import subprocess
    import sys as _sys

    from electionguard_tpu.obs import collector as coll
    collector, server, port, _ = coll.serve(
        0, str(tmp_path), slo_config=_quiet_slo(), http_port=None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        collector.push_telemetry(_batch("boardproc", 314,
                                        status="SERVING", phase="tally"))
        top = subprocess.run(
            [_sys.executable, os.path.join(repo, "tools", "egtop.py"),
             "-collector", f"localhost:{port}", "-once", "-noColor"],
            capture_output=True, text=True, timeout=60, cwd=repo)
        assert top.returncode == 0, top.stdout + top.stderr
        assert "fleet GREEN" in top.stdout
        assert "boardproc" in top.stdout and "tally" in top.stdout
    finally:
        collector.stop()
        server.stop(grace=0)
    # unreachable collector: frame explains, exit code says so
    from electionguard_tpu.remote import rpc_util
    dead_port = rpc_util.find_free_port()
    top = subprocess.run(
        [_sys.executable, os.path.join(repo, "tools", "egtop.py"),
         "-collector", f"localhost:{dead_port}", "-once"],
        capture_output=True, text=True, timeout=60, cwd=repo)
    assert top.returncode == 1
    assert "unreachable" in top.stdout


def test_slo_availability_burn_fast_and_slow_windows():
    """The availability objective only pages when BOTH burn windows
    exceed their thresholds: a brief blip inside the fast window alone
    must not fire; a sustained burn across both must — once."""
    from electionguard_tpu.obs import slo as slo_mod
    cfg = slo_mod.load_config(json.dumps({
        "availability": {"fast_window_s": 10.0, "slow_window_s": 60.0,
                         "fast_burn": 2.0, "slow_burn": 2.0},
        "serving_p99_ms": {"objective": 1e12},
        "queue_depth_max": 10**9, "stage_lag_s": 1e12}))
    eng = slo_mod.SLOEngine(cfg, method_class=lambda m: "data")

    def snap(calls, fails):
        return {"counters": {
            'rpc_client_calls_total{class="data",method="m"}': calls,
            'rpc_client_failures_total{code="X",method="m"}': fails}}

    # t=0..5: healthy traffic fills the slow window with successes
    for t in (0.0, 5.0):
        assert eng.evaluate(t, snap(calls=100 * (t + 1), fails=0), []) == []
    # t=8: a burst of failures — fast window burns, slow window still
    # diluted below threshold -> no page
    assert eng.evaluate(8.0, snap(calls=620, fails=2), []) == []
    # t=20..30: failures sustained -> both windows above burn -> ONE fire
    fired = eng.evaluate(20.0, snap(calls=700, fails=60), [])
    fired += eng.evaluate(30.0, snap(calls=750, fails=90), [])
    burns = [a for a in fired if a.kind == "availability_burn"]
    assert len(burns) == 1 and burns[0].subject == "data"
    assert burns[0].attrs["fast_burn"] > 2.0
    assert burns[0].attrs["slow_burn"] > 2.0


def test_slo_audit_lag_edge_triggered_and_knob_defaulted():
    """The live-verification audit-lag objective: fires once when the
    ``live_audit_lag_frames`` gauge passes the limit, clears and
    re-arms when the verifier catches back up.  ``objective: null``
    resolves the EGTPU_LIVE_AUDIT_LAG_MAX knob."""
    from electionguard_tpu.obs import slo as slo_mod
    eng = slo_mod.SLOEngine(slo_mod.load_config(
        json.dumps({"audit_lag_frames": {"objective": 100}})))

    def snap(lag):
        return {"gauges": {"live_audit_lag_frames": lag}}

    assert eng.evaluate(0.0, snap(50), []) == []
    fired = eng.evaluate(1.0, snap(500), [])
    assert [a.kind for a in fired] == ["audit_lag"]
    assert fired[0].attrs == {"lag_frames": 500, "limit": 100}
    # still lagging: edge-triggered, no re-fire
    assert eng.evaluate(2.0, snap(600), []) == []
    assert eng.health(2.0)[0] == "red"
    # caught up: clears; a later excursion fires again
    assert eng.evaluate(3.0, snap(0), []) == []
    assert eng.health(3.0)[0] == "green"
    assert len(eng.evaluate(4.0, snap(101), [])) == 1
    # default objective comes from the registered knob
    from electionguard_tpu.utils import knobs
    dflt = slo_mod.SLOEngine(slo_mod.load_config(None))
    lim = knobs.get_int("EGTPU_LIVE_AUDIT_LAG_MAX")
    assert dflt.evaluate(0.0, snap(lim), []) == []
    assert len(dflt.evaluate(1.0, snap(lim + 1), [])) == 1

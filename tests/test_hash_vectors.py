"""Golden vectors pinning the Fiat–Shamir hash construction byte-for-byte.

This framework deliberately does NOT reproduce ElectionGuard spec-1.03's
"|"-joined string hashing (the construction the reference's records feed —
reference: src/main/proto/keyceremony_trustee_rpc.proto:40 "see spec 1.03
eq 17", src/main/proto/common.proto:6-16): that form is not injective
across types, and the reference does not vendor the Kotlin library that
defines it, so byte-compatibility could never be proven here.  Instead
core/hash.py defines a canonical injective tag-length encoding; records
are internally consistent and verified end-to-end by our Verifier, but are
NOT checkable by external spec-1.03 verifiers (documented in README.md
§Interop).

These vectors freeze that construction: any unintended change to the
encoding, digest, mod-q reduction, HMAC, or KDF breaks this file.  They
double as the cross-library comparison points an external implementation
would need.
"""

from electionguard_tpu.core.group import production_group
from electionguard_tpu.core.hash import (_encode, hash_digest, hash_elems,
                                         hmac_digest, kdf)


def test_encode_primitives():
    assert _encode(None).hex() == "0000000000"
    assert _encode(0).hex() == "030000000100"
    assert _encode(255).hex() == "0300000001ff"
    assert _encode(65536).hex() == "0300000003010000"
    assert _encode("abc").hex() == "0400000003616263"
    assert _encode(b"abc").hex() == "0500000003616263"
    # str and bytes with identical payloads MUST encode differently
    assert _encode("abc") != _encode(b"abc")
    # sequences hash their inner encoding (fixed 32-byte digest frame)
    assert _encode(["a", 1]).hex() == (
        "0600000020"
        "acf3ba12785d9b6cb466c0cda666441b1722e104e7978333f755046f1de43a93")


def test_encode_group_elements_fixed_width():
    g = production_group()
    e = g.int_to_p(pow(g.g, 5, g.p))
    q5 = g.int_to_q(5)
    enc_p = _encode(e)
    enc_q = _encode(q5)
    # tag(1) + len(4) + 512/32-byte big-endian images — the same framing
    # sha256_jax._TAG_P_HDR replays on-device
    assert len(enc_p) == 517 and enc_p[:5].hex() == "0100000200"
    assert len(enc_q) == 37 and enc_q[:5].hex() == "0200000020"


def test_hash_digest_vectors():
    # empty input = SHA-256("")
    assert hash_digest().hex() == (
        "e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855")
    assert hash_digest("spec", 42, b"\x00\x01", None).hex() == (
        "f09859d778009f0891b0b9d56e15d6e75d14648aa8001a6b5145a750eaba6131")


def test_hash_elems_mod_q_vectors():
    g = production_group()
    assert hash_elems(g, "x", 123).value == int(
        "96009231549028838145706641538645905516456599800031253640724677890"
        "392363932179")
    e = g.int_to_p(pow(g.g, 5, g.p))
    assert hash_elems(g, e, g.int_to_q(5)).value == int(
        "10986852551582276970926743173588022263901486514513943585690547153"
        "3748118678666")


def test_hmac_and_kdf_vectors():
    assert hmac_digest(b"key", "msg", 7).hex() == (
        "f18c5e7ac18f3f6044a2cf4e06d00bc85a0777c36dd55f1f4f9c6baf82d0b89c")
    assert kdf(b"key", "label", b"ctx", 40).hex() == (
        "0d8f10fc994459c48c1ee8cc0a7f223a64bf3abd7fd75a2b59cc1573331eb4dd"
        "9969860a136b701b")
    # counter-mode prefix property: a longer stream extends a shorter one
    assert kdf(b"key", "label", b"ctx", 64)[:32] != kdf(
        b"key", "label", b"ctx", 32)  # length is bound into the PRF input


def test_injectivity_boundaries():
    # moving bytes across item boundaries must change the digest
    assert hash_digest("ab", "c") != hash_digest("a", "bc")
    assert hash_digest(b"", b"") != hash_digest(b"")
    assert hash_digest(None) != hash_digest(b"")
    assert hash_digest(1, 2) != hash_digest((1, 2))

"""Parameter-level adversary family (ISSUE 17): every forged-element
attack planted alone must be rejected AT ITS INGESTION BOUNDARY with
the right ``[validate.*]`` class, runs replay bit-for-bit with attacks
mounted, and the pinned mixed sweep (faults + Byzantine + param) stays
green under the soundness oracle.

Mirror of test_sim_adversary.py for the forged-parameter dimension;
``tools/sim_matrix.py --param-adversaries`` runs the wide sweep and
records SIM_PARAM_RESULTS.json.
"""

import random

import pytest

from electionguard_tpu.sim import adversary
from electionguard_tpu.sim.explore import explore, run_sim
from electionguard_tpu.sim.schedule import (FaultEvent,
                                            generate_param_schedule)


def _adv(name: str, node: str = "", nth: int = 1) -> FaultEvent:
    return FaultEvent("adversary", method=name, nth=nth, a=node)


def _classes(report):
    return {v.split(":", 1)[0] for v in report.violations}


def _detected(report):
    return {cls for cls, _detail in report.detections}


# ------------------------------------------------------------- registry

def test_param_corpus_invariants():
    """Seven forged-parameter attacks, every one expecting a named
    validate.* class, none leaking into the Byzantine corpus (they
    compose via --param-adversaries, never dilute the PR 16 sweep)."""
    corpus = adversary.param_corpus()
    assert len(corpus) == 7
    byz = {a.name for a in adversary.corpus()}
    for atk in corpus:
        assert atk.name.startswith("param_")
        assert atk.name not in byz
        assert atk.expect
        assert all(c.startswith("validate.") for c in atk.expect), (
            f"{atk.name} expects a non-gate class: {atk.expect}")
        assert adversary.build(atk.name, atk.targets[0], atk.nth_range[0])


def test_param_schedule_generation_is_deterministic():
    s1 = generate_param_schedule(random.Random("param:7"))
    s2 = generate_param_schedule(random.Random("param:7"))
    assert s1 == s2 and s1
    assert all(e.kind == "adversary" for e in s1)
    assert all(e.method.startswith("param_") for e in s1)


def test_param_schedule_never_comounts_one_rpc_call():
    """Two attacks mutating the same (method, node, nth) message mask
    each other — the gate rejects on the first failing check, so the
    second attack would fire green-undetected.  The generator must
    never emit that collision."""
    by_rule = {a.name: a.rules[0][0] for a in adversary.param_corpus()}
    for seed in range(300):
        events = generate_param_schedule(random.Random(f"param:{seed}"))
        calls = [(by_rule[e.method], e.a, e.nth) for e in events]
        assert len(calls) == len(set(calls)), (
            f"seed {seed}: attacks co-mounted on one RPC call: {events}")


# ----------------------------------------------- planted attacks (one each)
# (attack, node, nth, boundary label, expected class): the rejection
# must carry the class AND the boundary tag of the ingestion point the
# forged element entered through — proving it died AT the boundary,
# not downstream in arithmetic or the terminal verifier.

PLANTS = [
    ("param_nonsubgroup_key", "guardian-0", 1,
     "keyceremony", "validate.nonsubgroup"),
    ("param_smuggled_commitment", "guardian-1", 1,
     "keyceremony", "validate.nonsubgroup"),
    ("param_small_order_ciphertext", "serve", 1,
     "serve", "validate.small_order"),
    ("param_identity_share", "dec-0", 1,
     "decrypt", "validate.identity"),
    ("param_wrong_group_trustee", "guardian-2", 1,
     "keyceremony", "validate.group_mismatch"),
    ("param_noncanonical_element", "guardian-1", 1,
     "keyceremony", "validate.range"),
    ("param_out_of_range_response", "guardian-2", 1,
     "keyceremony", "validate.response_range"),
]


def test_plants_cover_the_whole_param_corpus():
    assert ({p[0] for p in PLANTS}
            == {a.name for a in adversary.param_corpus()})


@pytest.mark.parametrize("name,node,nth,boundary,cls", PLANTS,
                         ids=[p[0] for p in PLANTS])
def test_planted_param_attack_rejected_at_its_boundary(
        name, node, nth, boundary, cls):
    r = run_sim(3, schedule=[_adv(name, node, nth)])
    assert r.fired, f"{name} never fired — stale (node, nth) plant"
    assert all(f[0] == name for f in r.fired)
    hits = [d for c, d in r.detections if c == cls]
    assert hits, (f"{name} fired but {cls} not in "
                  f"{sorted(_detected(r))}")
    assert any(d.startswith(f"{boundary}:") for d in hits), (
        f"{name} rejected with {cls} but not at boundary "
        f"'{boundary}': {hits}")
    assert r.ok, r.summary()
    assert "soundness" not in _classes(r)


def test_small_order_ciphertext_second_admission():
    """nth_range=(1, 2): the SECOND encryptBallot admission is also a
    live mount point (regression guard for the nth plumbing)."""
    r = run_sim(3, schedule=[_adv("param_small_order_ciphertext",
                                  "serve", 2)])
    assert r.fired
    assert "validate.small_order" in _detected(r)
    assert r.ok, r.summary()


# ------------------------------------------------------------- replay

def test_param_run_replays_bit_for_bit():
    """The param stream is string-seeded and deterministic: same seed,
    same forged elements, same trace, same rejections."""
    a = run_sim(5, param_adversaries=True)
    b = run_sim(5, param_adversaries=True)
    assert a.trace_hash == b.trace_hash
    assert a.fired == b.fired
    assert a.schedule == b.schedule
    assert a.detections == b.detections


def test_param_stream_does_not_perturb_honest_streams():
    """Mounting param attacks must not change which faults (stream 1)
    or Byzantine attacks (stream 5) the same seed draws."""
    byz = run_sim(9, adversaries=True)
    both = run_sim(9, adversaries=True, param_adversaries=True)
    non_param = [e for e in both.schedule
                 if not (e.kind == "adversary"
                         and e.method.startswith("param_"))]
    assert non_param == byz.schedule


# ------------------------------------------------------------- the sweep

def test_pinned_mixed_param_sweep_is_green():
    """Tier-1 param sweep: 20 pinned seeds, each composing the honest
    fault schedule with Byzantine (stream 5) AND param (string stream)
    attacks.  Zero soundness violations — every forged element either
    rejected in-band or sound-aborts the run."""
    reports = explore(range(20), adversaries=True, param_adversaries=True)
    bad = [r.summary() for r in reports if not r.ok]
    assert not bad, f"param sweep failures: {bad}"
    assert all("soundness" not in _classes(r) for r in reports)
    names = {f[0] for r in reports for f in r.fired
             if f[0].startswith("param_")}
    assert len(names) >= 4, f"sweep only exercised {sorted(names)}"


@pytest.mark.slow
def test_wide_param_sweep_is_green():
    """The wide param sweep (seeds 20..219); sim_matrix
    --param-adversaries goes wider and records SIM_PARAM_RESULTS.json."""
    reports = explore(range(20, 220), adversaries=True,
                      param_adversaries=True)
    bad = [r.summary() for r in reports if not r.ok]
    assert not bad, f"param sweep failures: {bad}"
    assert all("soundness" not in _classes(r) for r in reports)

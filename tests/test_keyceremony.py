"""Key ceremony + threshold decryption tests (tiny group, in-process).

Covers the full trust protocol the reference drives over gRPC
(SURVEY.md §3.1/§3.2), including the compensated-decryption quorum path and
the challenge path the reference never wired.
"""

import json

import pytest

from electionguard_tpu.ballot.manifest import (BallotStyle, Candidate,
                                               ContestDescription,
                                               GeopoliticalUnit, Manifest,
                                               Party, SelectionDescription)
from electionguard_tpu.ballot.tally import (EncryptedTally,
                                            EncryptedTallyContest,
                                            EncryptedTallySelection)
from electionguard_tpu.core.dlog import DLog
from electionguard_tpu.crypto.elgamal import elgamal_accumulate, elgamal_encrypt
from electionguard_tpu.decrypt.decryption import (Decryption, DecryptionError,
                                                  lagrange_coefficient)
from electionguard_tpu.decrypt.trustee import DecryptingTrustee, read_trustee
from electionguard_tpu.keyceremony.exchange import key_ceremony_exchange
from electionguard_tpu.keyceremony.interface import Result, SecretKeyShare
from electionguard_tpu.keyceremony.trustee import (KeyCeremonyTrustee,
                                                   commitment_product,
                                                   compute_polynomial)
from electionguard_tpu.publish.election_record import ElectionConfig


def tiny_manifest() -> Manifest:
    sels = tuple(SelectionDescription(f"sel-{i}", i, f"cand-{i}")
                 for i in range(2))
    contest = ContestDescription("contest-0", 0, "gp-0", "one_of_m", 1,
                                 "The Contest", sels)
    return Manifest(
        election_scope_id="test-election", spec_version="tpu-1.0",
        start_date="2026-07-01", end_date="2026-07-29",
        geopolitical_units=(GeopoliticalUnit("gp-0", "District 0"),),
        parties=(Party("party-0", "Party"),),
        candidates=tuple(Candidate(f"cand-{i}", f"Candidate {i}")
                         for i in range(2)),
        contests=(contest,),
        ballot_styles=(BallotStyle("style-0", ("gp-0",)),),
    )


def run_ceremony(group, n=5, k=3):
    trustees = [KeyCeremonyTrustee(group, f"guardian-{i}", i + 1, k)
                for i in range(n)]
    results = key_ceremony_exchange(trustees, group)
    assert not isinstance(results, Result), results
    return trustees, results


def test_polynomial_and_commitments(tgroup):
    g = tgroup
    t = KeyCeremonyTrustee(g, "g1", 1, 3)
    for x in (1, 2, 7):
        px = compute_polynomial(g, t._coefficients, x)
        assert g.g_pow_p(px) == commitment_product(
            g, t.coefficient_commitments, x)


def test_ceremony_joint_key(tgroup):
    g = tgroup
    trustees, results = run_ceremony(g)
    # K = g^{Σ a_i0}
    secret_sum = g.add_q(*(t._coefficients[0] for t in trustees))
    assert results.joint_public_key == g.g_pow_p(secret_sum)
    # every trustee received n-1 verified shares
    for t in trustees:
        assert len(t.received_shares) == 4


def test_election_initialized(tgroup):
    g = tgroup
    _, results = run_ceremony(g, 3, 2)
    config = ElectionConfig(tiny_manifest(), 3, 2)
    init = results.make_election_initialized(config, {"by": "test"})
    assert init.joint_public_key == results.joint_public_key
    assert len(init.guardians) == 3
    assert [gr.x_coordinate for gr in init.guardians] == [1, 2, 3]
    assert init.crypto_base_hash != init.extended_base_hash
    assert init.guardian("guardian-1") is not None
    assert init.guardian("nope") is None


def test_duplicate_ids_rejected(tgroup):
    g = tgroup
    t1 = KeyCeremonyTrustee(g, "same", 1, 2)
    t2 = KeyCeremonyTrustee(g, "same", 2, 2)
    res = key_ceremony_exchange([t1, t2], g)
    assert isinstance(res, Result) and not res.ok


def test_corrupt_share_challenge_path(tgroup):
    """A share corrupted in transport triggers the challenge path; the
    honest sender's revealed coordinate passes the commitment check and the
    ceremony completes."""
    g = tgroup

    class FlakyTrustee(KeyCeremonyTrustee):
        def send_secret_key_share(self, other_id):
            share = super().send_secret_key_share(other_id)
            if other_id == "guardian-1":  # corrupt one edge
                bad = bytes(b ^ 0xFF for b in share.encrypted_coordinate.c1)
                from electionguard_tpu.crypto.hashed_elgamal import \
                    HashedElGamalCiphertext
                share = SecretKeyShare(
                    share.generating_guardian_id,
                    share.designated_guardian_id,
                    share.designated_guardian_x,
                    HashedElGamalCiphertext(
                        share.encrypted_coordinate.c0, bad,
                        share.encrypted_coordinate.c2,
                        share.encrypted_coordinate.num_bytes))
            return share

    trustees = [FlakyTrustee(g, "guardian-0", 1, 2),
                KeyCeremonyTrustee(g, "guardian-1", 2, 2),
                KeyCeremonyTrustee(g, "guardian-2", 3, 2)]
    results = key_ceremony_exchange(trustees, g)
    assert not isinstance(results, Result), results
    assert len(trustees[1].received_shares) == 2


def test_lying_trustee_detected(tgroup):
    """A trustee whose polynomial doesn't match its commitments is caught
    at challenge verification."""
    g = tgroup

    class LyingTrustee(KeyCeremonyTrustee):
        def send_secret_key_share(self, other_id):
            share = super().send_secret_key_share(other_id)
            if other_id == "guardian-1":
                keys = self.other_public_keys[other_id]
                from electionguard_tpu.crypto.hashed_elgamal import \
                    hashed_elgamal_encrypt
                wrong = self.group.int_to_q(12345)
                enc = hashed_elgamal_encrypt(
                    self.group, wrong.to_bytes(), self.group.rand_q(),
                    keys.election_public_key,
                    f"{self.id}->{other_id}".encode())
                share = SecretKeyShare(self.id, other_id,
                                       keys.x_coordinate, enc)
            return share

        def challenge_share(self, challenger_id):
            # keeps lying under challenge
            from electionguard_tpu.keyceremony.interface import \
                KeyShareChallengeResponse
            return KeyShareChallengeResponse(
                self.id, challenger_id, self.group.int_to_q(12345))

    trustees = [LyingTrustee(g, "guardian-0", 1, 2),
                KeyCeremonyTrustee(g, "guardian-1", 2, 2),
                KeyCeremonyTrustee(g, "guardian-2", 3, 2)]
    res = key_ceremony_exchange(trustees, g)
    assert isinstance(res, Result) and not res.ok
    assert "challenge verification failed" in res.error


# ---------------------------------------------------------------------------
# threshold decryption
# ---------------------------------------------------------------------------

def make_tally(group, public_key, votes):
    """Encrypt per-selection vote counts as a 1-contest tally."""
    cts = []
    for i, v in enumerate(votes):
        parts = [elgamal_encrypt(group, 1 if j < v else 0, group.rand_q(),
                                 public_key) for j in range(max(votes))]
        cts.append(elgamal_accumulate(parts) if parts else None)
    sels = tuple(
        EncryptedTallySelection(f"sel-{i}", i, ct)
        for i, ct in enumerate(cts))
    return EncryptedTally(
        "tally-0", (EncryptedTallyContest("contest-0", 0, sels),),
        cast_ballot_count=sum(votes))


def setup_election(tgroup, n=5, k=3):
    trustees, results = run_ceremony(tgroup, n, k)
    config = ElectionConfig(tiny_manifest(), n, k)
    init = results.make_election_initialized(config)
    dec_trustees = [
        DecryptingTrustee.from_state(
            tgroup, t.decrypting_trustee_state())
        for t in trustees]
    return trustees, dec_trustees, init


def test_direct_decryption_all_available(tgroup):
    g = tgroup
    _, dec, init = setup_election(g)
    tally = make_tally(g, init.joint_public_key, [7, 3])
    d = Decryption(g, init, dec, [], DLog(g, max_exponent=100))
    out = d.decrypt(tally)
    got = [s.tally for s in out.contests[0].selections]
    assert got == [7, 3]
    assert all(len(s.shares) == 5 for s in out.contests[0].selections)


@pytest.mark.parametrize("missing_idx", [[0], [0, 4], [1, 3]])
def test_compensated_decryption(tgroup, missing_idx):
    g = tgroup
    _, dec, init = setup_election(g, 5, 3)
    tally = make_tally(g, init.joint_public_key, [4, 9])
    missing = [dec[i].id for i in missing_idx]
    avail = [t for i, t in enumerate(dec) if i not in missing_idx]
    d = Decryption(g, init, avail, missing, DLog(g, max_exponent=100))
    out = d.decrypt(tally)
    got = [s.tally for s in out.contests[0].selections]
    assert got == [4, 9]
    # missing guardians appear as reconstructed shares
    for s in out.contests[0].selections:
        ids = {sh.guardian_id for sh in s.shares}
        assert set(missing) <= ids


def test_quorum_enforced(tgroup):
    g = tgroup
    _, dec, init = setup_election(g, 5, 3)
    with pytest.raises(DecryptionError, match="quorum"):
        Decryption(g, init, dec[:2], [t.id for t in dec[2:]])


def test_lagrange_interpolation(tgroup):
    """Σ w_ℓ P(x_ℓ) == P(0) for any polynomial of degree < #points."""
    g = tgroup
    coeffs = [g.rand_q() for _ in range(3)]
    xs = [1, 3, 7]
    total = 0
    for x in xs:
        w = lagrange_coefficient(g, xs, x)
        px = compute_polynomial(g, coeffs, x)
        total = (total + w.value * px.value) % g.q
    assert total == coeffs[0].value


def test_trustee_file_roundtrip(tgroup, tmp_path):
    g = tgroup
    trustees, _ = run_ceremony(g, 3, 2)
    res = trustees[0].save_state(str(tmp_path))
    assert res.ok
    loaded = read_trustee(g, str(tmp_path / "trustee-guardian-0.json"))
    assert loaded.id == "guardian-0"
    assert loaded.x_coordinate == 1
    assert loaded.election_public_key == trustees[0].election_public_key
    assert set(loaded._received_shares) == set(trustees[0].received_shares)


def test_available_guardians_record(tgroup):
    g = tgroup
    _, dec, init = setup_election(g, 4, 2)
    d = Decryption(g, init, dec[:2], [t.id for t in dec[2:]],
                   DLog(g, max_exponent=10))
    ags = d.get_available_guardians()
    assert len(ags) == 2
    xs = [t.x_coordinate for t in dec[:2]]
    for ag in ags:
        assert ag.lagrange_coefficient == lagrange_coefficient(
            g, xs, ag.x_coordinate)

"""Lint: no bare ``print()`` in electionguard_tpu/ library code.

The rule itself now lives in the analysis framework
(``electionguard_tpu/analysis/no_bare_print.py``, rule
``no-bare-print``); this test is the seed lint's thin wrapper over that
pass.  It preserves the original pins: the recursive package walk must
still cover the newer subpackages AND the telemetry-plane modules (so a
future layout change can't silently drop them from the lint), and the
``cli/`` exemption must stay exactly ``("cli",)`` — entry-point stdout
IS the user interface, everything else goes through ``logging``.
"""

import ast

from electionguard_tpu.analysis import core, no_bare_print


def _project() -> core.Project:
    return core.Project()


def test_walk_covers_new_packages_and_obs_modules():
    project = _project()
    tops = set()
    rels = set()
    for f in project.files():
        parts = project.package_rel_parts(f)
        if len(parts) > 1:
            tops.add(parts[0])
        rels.add("/".join(parts))
    assert {"mixnet", "mixfed", "obs", "serve", "fabric", "sim"} <= tops
    assert {"obs/collector.py", "obs/slo.py", "obs/assemble.py"} <= rels
    # the Pallas kernel package (its bodies feed the jit-hygiene pass)
    assert {"core/pallas/__init__.py", "core/pallas/engine.py"} <= rels
    # the Byzantine adversary plane (the corpus and the named-error
    # registry its soundness oracle matches on)
    assert {"sim/adversary.py", "utils/errors.py"} <= rels
    # the live verification plane (streaming verifier + bulletin board)
    # and the shared frame codec it tails
    assert {"verify/live/__init__.py", "verify/live/verifier.py",
            "verify/live/commitment.py", "verify/live/board.py",
            "publish/framing.py"} <= rels
    # the capacity-planning plane (cost models + predicted-vs-actual)
    assert "obs/capacity.py" in rels
    # the process-model sim layer (virtual processes + device costs +
    # the million-ballot election driver) and its ambient charge seam
    assert {"sim/procmodel.py", "sim/devicemodel.py", "sim/election.py",
            "utils/devicetime.py"} <= rels


def test_no_bare_print_in_library_code():
    report = core.run_passes(_project(), passes=["no-bare-print"],
                             baseline=[])
    assert not report.findings, (
        "bare print() in library code (use logging — obs.slog mirrors "
        "it as structured JSONL with trace context):\n  "
        + "\n  ".join(str(f) for f in report.findings))


def test_cli_exemption_is_pinned_and_load_bearing():
    # the exemption list must not silently widen...
    assert no_bare_print.EXEMPT_DIRS == ("cli",)
    # ...and must actually be load-bearing: cli/ really does print to
    # stdout (if this ever becomes false, drop the exemption too)
    project = _project()
    cli_prints = 0
    for f in project.files():
        if project.package_rel_parts(f)[0] != "cli":
            continue
        for node in ast.walk(f.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"
                    and not any(kw.arg == "file"
                                for kw in node.keywords)):
                cli_prints += 1
    assert cli_prints > 0

"""Lint: no bare ``print()`` in electionguard_tpu/ library code.

Library telemetry goes through ``logging`` (mirrored as structured JSONL
with trace context by ``obs.slog``) — a bare ``print()`` is invisible to
the observability plane and unattributable to a trace.  CLI entry points
(``electionguard_tpu/cli/``) are exempt: their stdout IS their user
interface.  A ``print(..., file=...)`` writing to an explicitly chosen
stream (e.g. ``RunCommand.show(stream=...)`` dumping captured subprocess
output) is display plumbing, not telemetry, and stays allowed.

AST-based, so ``print`` inside string literals (subprocess ``-c``
snippets in utils/platform.py) never false-positives.
"""

import ast
import os

import electionguard_tpu

PKG_DIR = os.path.dirname(os.path.abspath(electionguard_tpu.__file__))
EXEMPT_DIRS = ("cli",)   # entry points: stdout is the interface


def _bare_prints(path: str) -> list[int]:
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    lines = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
                and not any(kw.arg == "file" for kw in node.keywords)):
            lines.append(node.lineno)
    return lines


def test_no_bare_print_in_library_code():
    offenders = []
    scanned_pkgs = set()
    scanned_files = set()
    for root, dirs, files in os.walk(PKG_DIR):
        rel = os.path.relpath(root, PKG_DIR)
        top = rel.split(os.sep)[0]
        if top in EXEMPT_DIRS or "__pycache__" in root:
            continue
        scanned_pkgs.add(top)
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            scanned_files.add(os.path.relpath(path, PKG_DIR))
            for lineno in _bare_prints(path):
                offenders.append(
                    f"{os.path.relpath(path, PKG_DIR)}:{lineno}")
    # the walk is recursive by construction; pin the newer packages AND
    # the telemetry-plane modules themselves so a future layout change
    # can't silently drop them from the lint
    assert {"mixnet", "mixfed", "obs", "serve"} <= scanned_pkgs
    assert {os.path.join("obs", "collector.py"),
            os.path.join("obs", "slo.py"),
            os.path.join("obs", "assemble.py")} <= scanned_files
    assert not offenders, (
        "bare print() in library code (use logging — obs.slog mirrors "
        "it as structured JSONL with trace context):\n  "
        + "\n  ".join(offenders))

"""Registration-time group negotiation: a trustee running a different group
gets a clean in-band rejection at the handshake — instead of the opaque
byte-width error mid-protocol the reference would produce (its registration
response defined a ``constants`` field for this but never populated it:
reference src/main/proto/decrypting_rpc.proto:20,
RunRemoteDecryptor.java:356-360)."""

import pytest

from electionguard_tpu.remote.decrypting_remote import (DecryptionCoordinator,
                                                        RemoteDecryptorProxy)
from electionguard_tpu.remote.keyceremony_remote import (
    KeyCeremonyCoordinator, KeyCeremonyTrusteeServer)


def test_keyceremony_group_mismatch_rejected(tgroup, pgroup):
    coord = KeyCeremonyCoordinator(tgroup, 1, 1, port=0)
    try:
        with pytest.raises(RuntimeError, match="group constants mismatch"):
            KeyCeremonyTrusteeServer(pgroup, "g0",
                                     f"localhost:{coord.port}")
        assert coord.ready() == 0
    finally:
        coord.server.stop(grace=0)


def test_keyceremony_group_match_accepted(tgroup):
    coord = KeyCeremonyCoordinator(tgroup, 1, 1, port=0)
    try:
        ts = KeyCeremonyTrusteeServer(tgroup, "g0",
                                      f"localhost:{coord.port}")
        assert coord.ready() == 1
        ts.server.stop(grace=0)
    finally:
        coord.server.stop(grace=0)


def test_decrypting_group_mismatch_rejected(tgroup, pgroup):
    coord = DecryptionCoordinator(tgroup, 1, port=0)
    try:
        proxy = RemoteDecryptorProxy(f"localhost:{coord.port}")
        try:
            resp = proxy.register_trustee(
                "g0", "localhost:1", 1,
                pgroup.int_to_p(pow(pgroup.g, 3, pgroup.p)), pgroup)
        finally:
            proxy.close()
        assert "group constants mismatch" in resp.error
        # the response tells the trustee which group the coordinator runs
        assert resp.constants.name == tgroup.spec.name
        assert coord.ready() == 0
    finally:
        coord.server.stop(grace=0)

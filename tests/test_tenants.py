"""Multi-tenant serving tests: N overlapping elections over ONE worker
pool, per-tenant observability, and the starved-tenant chaos drill.

The heavyweight invariants pinned here:

* **shared programs** — 4 elections with 4 distinct key ceremonies run
  through one EncryptionService; ``device_compiles`` stays flat across
  the interleaved load (the election key is a traced argument, so tenant
  lanes reuse the prewarmed bucket programs), every tenant's published
  record is chain-contiguous and verifier-green, and its codes are
  bit-for-bit the offline BatchEncryptor's for the same ballots in the
  same order;
* **per-tenant quotas** — a flooding election exhausts ITS OWN
  admission quota (RESOURCE_EXHAUSTED naming it) while the victim's
  requests keep flowing and its p99 stays inside the fleet SLO;
* **noisy-neighbor attribution** — the SLO engine joins per-election
  device time against per-election SLO burn and names the OFFENDER,
  not the victim that paged;
* **hostile tenant ids** — ids containing ``,``, ``=``, ``"`` and
  newlines round-trip losslessly through the metrics registry, the
  Prometheus exposition, ``slo.parse_labels``, span attrs and the
  trace analyzer's tenant buckets; the per-process cardinality guard
  bounds the distinct-id set with a named error;
* **group-keyed table cache** — PowRadix entries are fingerprinted by
  (group digest, base digest) with NO election component, so a second
  worker joining the fleet reuses every tenant's tables (cross-tenant
  hit rate > 0).
"""

import json
import os
import threading

import grpc
import pytest

from electionguard_tpu.ballot.plaintext import (PlaintextBallot,
                                                PlaintextBallotContest,
                                                PlaintextBallotSelection)
from electionguard_tpu.obs import analyze as analyze_mod
from electionguard_tpu.obs import registry as registry_mod
from electionguard_tpu.obs import slo as slo_mod
from electionguard_tpu.obs import tenant
from electionguard_tpu.publish.election_record import ElectionConfig
from electionguard_tpu.serve import tenants as tenants_mod
from electionguard_tpu.serve.tenants import (ElectionContext, TenantQuota,
                                             TenantQuotaError,
                                             TenantRegistry)
from tests.test_keyceremony import tiny_manifest

TS = 1754_000_000

#: election ids chosen to break naive label quoting, CSV-ish parsers,
#: and line-oriented formats — every surface must carry them losslessly
HOSTILE_IDS = ('acme,fall-2026', 'general="2026"', 'line1\nline2',
               'eq=and\\slash')


def _ceremony(tgroup, tag: str, n: int = 1, quorum: int = 1):
    """One election's ElectionInitialized: its own trustees, its own
    joint key — tenants share manifest SHAPES, never key material."""
    from electionguard_tpu.keyceremony.exchange import key_ceremony_exchange
    from electionguard_tpu.keyceremony.trustee import KeyCeremonyTrustee
    trustees = [KeyCeremonyTrustee(tgroup, f"{tag}-guardian-{i}", i + 1,
                                   quorum) for i in range(n)]
    return key_ceremony_exchange(trustees, tgroup).make_election_initialized(
        ElectionConfig(tiny_manifest(), n, quorum),
        {"created_by": f"tenant-test-{tag}"})


def _ballot(election: str, i: int) -> PlaintextBallot:
    return PlaintextBallot(
        f"{election}-ballot-{i:04d}", "style-0",
        (PlaintextBallotContest(
            "contest-0", (PlaintextBallotSelection("sel-0", i % 2),
                          PlaintextBallotSelection("sel-1", 0))),))


class _RegistryStub:
    """egtop-facing stand-in for the obs collector: answers getMetrics
    with ``proto_of`` over a live registry snapshot — the same
    flat-named wire shape the collector's fleet merge serves."""

    def __init__(self, registry):
        self._registry = registry

    def call(self, method, request, timeout=None):
        assert method == "getMetrics"
        return registry_mod.proto_of(self._registry.snapshot())


# =====================================================================
# the N-tenant drill: 4 overlapping elections, one worker pool
# =====================================================================


def test_n_tenant_drill_one_pool_four_elections(tgroup, tmp_path,
                                                monkeypatch):
    """Acceptance drill: 4 virtual elections with distinct key
    ceremonies through ONE service; compiles flat, per-tenant records
    chain-contiguous + verifier-green, table cache cross-tenant."""
    from electionguard_tpu.core import group_jax, table_cache
    from electionguard_tpu.encrypt.encryptor import BatchEncryptor
    from electionguard_tpu.publish.election_record import ElectionRecord
    from electionguard_tpu.publish.publisher import Consumer
    from electionguard_tpu.serve.service import (EncryptionClient,
                                                 EncryptionService)
    from electionguard_tpu.verify.verifier import Verifier
    import tools.egtop as egtop

    monkeypatch.setenv("EGTPU_TABLE_CACHE", str(tmp_path / "tables"))
    elections = [f"city-{c}" for c in "abcd"]
    inits = {el: _ceremony(tgroup, el) for el in elections}
    seeds = {el: tgroup.int_to_q(101 + i)
             for i, el in enumerate(elections)}
    registry = TenantRegistry()
    for el in elections:
        registry.add(ElectionContext(
            el, inits[el], group=tgroup,
            out_dir=tenants_mod.tenant_record_dir(str(tmp_path), el),
            seed=seeds[el]))
    house = _ceremony(tgroup, "house")
    svc = EncryptionService(house, tgroup, max_batch=8, max_wait_ms=15,
                            seed=tgroup.int_to_q(42), timestamp=TS,
                            tenants=registry)
    submitted = {el: [_ballot(el, i) for i in range(6)]
                 for el in elections}
    try:
        # warmup: one ballot per tenant builds each election's host-side
        # key table; the device bucket programs were all compiled by the
        # prewarm (the key is a traced argument, shared across lanes)
        warm = EncryptionClient(f"localhost:{svc.port}", tgroup)
        results = {el: {} for el in elections}
        for el in elections:
            with tenant.tenant_scope(el):
                enc = warm.encrypt(submitted[el][0])
            results[el][enc.ballot_id] = enc
        warm.close()
        compiles0 = svc.metrics.counters()["device_compiles"]

        errs: list = []

        def run_tenant(el):
            client = EncryptionClient(f"localhost:{svc.port}", tgroup)
            try:
                with tenant.tenant_scope(el):
                    for b in submitted[el][1:]:
                        results[el][b.ballot_id] = client.encrypt(b)
            except BaseException as e:  # noqa: BLE001
                errs.append((el, e))
            finally:
                client.close()

        threads = [threading.Thread(target=run_tenant, args=(el,))
                   for el in elections]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errs, errs

        # the tentpole: N tenants' overlapping traffic compiled NOTHING
        # after warmup — device_compiles is flat across the drill
        compiles1 = svc.metrics.counters()["device_compiles"]
        assert compiles1 == compiles0, (
            f"cross-tenant traffic recompiled: {compiles0} -> {compiles1}")

        # per-tenant series split the shared fleet's metrics
        snap = svc.metrics.registry.snapshot()
        for el in elections:
            flat = registry_mod.flat_name("ballots_encrypted",
                                          {"election": el})
            assert snap["counters"][flat] == 6
            dflat = registry_mod.flat_name("tenant_device_ms_total",
                                           {"election": el})
            assert snap["counters"][dflat] > 0

        # egtop's tenant pane renders one row per election with an SLO
        # verdict off the same flat-named wire shape the collector serves
        pane = egtop.render_tenants(_RegistryStub(svc.metrics.registry))
        for el in elections:
            assert el in pane
        assert "OK" in pane and "ELECTION" in pane
    finally:
        svc.drain()

    # every tenant's record: complete, tenant-pure, verifier-green, and
    # bit-for-bit the offline encryptor's chain for the same ballots
    for el in elections:
        cons = Consumer(registry.get(el).record_dir, tgroup)
        record = ElectionRecord(cons.read_election_initialized())
        record.encrypted_ballots = list(cons.iterate_encrypted_ballots())
        ids = [b.ballot_id for b in record.encrypted_ballots]
        assert len(ids) == 6
        assert all(i.startswith(el) for i in ids), ids  # no bleed
        res = Verifier(record, tgroup).verify()
        assert res.ok, f"{el}: {res.summary()}"
        by_id = {b.ballot_id: b for b in submitted[el]}
        offline, invalid = BatchEncryptor(inits[el], tgroup).encrypt_ballots(
            [by_id[i] for i in ids], seed=seeds[el], timestamp=TS)
        assert not invalid
        assert offline == record.encrypted_ballots
        for off in offline:
            assert results[el][off.ballot_id].code == off.code

    # table-cache: entries are (group, base)-keyed — election-blind —
    # so a SECOND worker joining the fleet rebuilds nothing: it reads
    # every tenant's key table from the cache the first worker wrote
    table_cache.reset_stats()
    joiner = group_jax.JaxGroupOps(tgroup)
    for el in elections:
        joiner.fixed_table(inits[el].joint_public_key.value)
    stats = table_cache.stats()
    assert stats["hits"] >= len(elections), stats


def test_table_cache_fingerprint_is_election_blind(tgroup, tmp_path,
                                                   monkeypatch):
    """The cross-tenant reuse above is structural: the cache fingerprint
    has a group component and a base component, and NO tenant one."""
    from electionguard_tpu.core import group_jax
    monkeypatch.setenv("EGTPU_TABLE_CACHE", str(tmp_path / "tables"))
    ops = group_jax.JaxGroupOps(tgroup)
    with tenant.tenant_scope("fp-tenant-a"):
        fp_a = ops._table_fingerprint("powradix", tgroup.g)
    with tenant.tenant_scope("fp-tenant-b"):
        fp_b = ops._table_fingerprint("powradix", tgroup.g)
    assert fp_a == fp_b
    assert fp_a != ops._table_fingerprint("powradix", tgroup.g + 1)


# =====================================================================
# starved-tenant chaos drill: quotas + noisy-neighbor attribution
# =====================================================================


def test_starved_tenant_quota_names_flooder(tgroup, monkeypatch):
    """Chaos drill: a flooding election is shed by ITS quota (the
    rejection names it), the victim's requests flow and its p99 stays
    inside the fleet SLO, and the SLO engine's noisy-neighbor join over
    the drill's REAL metrics names the flooder as offender."""
    from electionguard_tpu.serve.service import (EncryptionClient,
                                                 EncryptionService)
    import tools.egtop as egtop

    monkeypatch.setenv("EGTPU_TENANT_QUOTA", "2")
    hold = threading.Event()
    registry = TenantRegistry()
    registry.add(ElectionContext("victim", _ceremony(tgroup, "victim"),
                                 group=tgroup, seed=tgroup.int_to_q(7)))
    registry.add(ElectionContext("flooder", _ceremony(tgroup, "flooder"),
                                 group=tgroup, seed=tgroup.int_to_q(8)))
    svc = EncryptionService(_ceremony(tgroup, "chaos-house"), tgroup,
                            max_batch=8, max_wait_ms=15,
                            seed=tgroup.int_to_q(42), timestamp=TS,
                            hold=hold, tenants=registry)
    try:
        url = f"localhost:{svc.port}"
        rejected: list = []
        flood_ok: list = []
        vic_ok: list = []
        vic_errs: list = []

        def flood(i):
            client = EncryptionClient(url, tgroup)
            try:
                with tenant.tenant_scope("flooder"):
                    flood_ok.append(client.encrypt(_ballot("flooder", i)))
            except grpc.RpcError as e:
                rejected.append(e)
            finally:
                client.close()

        def victim(i):
            client = EncryptionClient(url, tgroup)
            try:
                with tenant.tenant_scope("victim"):
                    vic_ok.append(client.encrypt(_ballot("victim", i)))
            except BaseException as e:  # noqa: BLE001
                vic_errs.append(e)
            finally:
                client.close()

        # phase 1 — worker held: the flooder bursts 6 concurrent
        # requests against a quota of 2; exactly 4 shed immediately
        flood_threads = [threading.Thread(target=flood, args=(i,))
                         for i in range(6)]
        for t in flood_threads:
            t.start()
        from electionguard_tpu.utils import clock
        deadline = clock.monotonic() + 30
        while len(rejected) < 4 and clock.monotonic() < deadline:
            clock.sleep(0.005)
        assert len(rejected) == 4, \
            f"expected 4 quota rejections, saw {len(rejected)}"
        for e in rejected:
            assert e.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
            assert "[tenant.quota]" in e.details()
            assert "'flooder'" in e.details()   # the rejection NAMES it

        # ... while the victim's admissions keep flowing under the
        # flooder's pressure (quota accounting is per-election)
        vic_threads = [threading.Thread(target=victim, args=(i,))
                       for i in range(2)]
        for t in vic_threads:
            t.start()
        deadline = clock.monotonic() + 30
        while svc._tenant_quota.inflight("victim") < 2 \
                and clock.monotonic() < deadline:
            clock.sleep(0.005)
        assert svc._tenant_quota.inflight("victim") == 2

        # release the device owner: every admitted request completes
        hold.set()
        for t in flood_threads + vic_threads:
            t.join(timeout=120)
        assert not vic_errs, vic_errs
        assert len(vic_ok) == 2 and len(flood_ok) == 2

        # phase 2 — the flooder hogs the device INSIDE its quota: a
        # sustained sequential pump dominates per-tenant device time
        client = EncryptionClient(url, tgroup)
        with tenant.tenant_scope("flooder"):
            for i in range(6, 30):
                client.encrypt(_ballot("flooder", i))
        client.close()

        # victim p99 stays inside the FLEET objective under quota
        vic_p99 = svc.metrics.histogram_for(
            "request_latency_ms", "victim").quantile(0.99)
        fleet_obj = slo_mod.DEFAULT_SLO["serving_p99_ms"]["objective"]
        assert 0 < vic_p99 <= fleet_obj

        # the SLO engine over the drill's REAL metrics: the victim's
        # tenant-scoped objective burns, the noisy-neighbor join names
        # the flooder (the tenant to throttle), never the victim
        engine = slo_mod.SLOEngine(config=slo_mod.load_config(json.dumps({
            "serving_p99_ms": {"per_election": {"victim": 0.5}},
            "noisy_neighbor": {"share": 0.5, "window_s": 60.0},
        })))
        zero = {"counters": {
            registry_mod.flat_name("tenant_device_ms_total",
                                   {"election": el}): 0.0
            for el in ("victim", "flooder")}, "histograms": {},
            "gauges": {}}
        assert engine.evaluate(0.0, zero, []) == []
        fired = engine.evaluate(5.0, svc.metrics.registry.snapshot(), [])
        noisy = [a for a in fired if a.kind == "noisy_neighbor"]
        assert len(noisy) == 1
        assert noisy[0].subject == "flooder"
        assert noisy[0].attrs["offender"] == "flooder"
        assert noisy[0].attrs["victim"] == "victim"
        assert noisy[0].attrs["share"] >= 0.5
        burns = [a for a in fired if a.kind == "serving_p99"]
        assert burns and all(a.attrs["election"] == "victim"
                             for a in burns)

        # egtop -once tenant pane: per-election rows with SLO verdicts,
        # the flooder's shed requests visible in its own row
        pane = egtop.render_tenants(_RegistryStub(svc.metrics.registry))
        assert "victim" in pane and "flooder" in pane
        vic_row = next(ln for ln in pane.splitlines()
                       if ln.strip().startswith("victim"))
        assert "OK" in vic_row
        flood_row = next(ln for ln in pane.splitlines()
                         if ln.strip().startswith("flooder"))
        assert " 4" in flood_row   # the 4 quota rejections
    finally:
        hold.set()
        svc.drain()


def test_noisy_neighbor_detector_edge_triggers(tgroup):
    """Detector unit: synthetic two-tick history — offender named once
    (edge-triggered), low-share tenants never blamed."""
    engine = slo_mod.SLOEngine(config=slo_mod.load_config(json.dumps({
        "serving_p99_ms": {"objective": 100.0},
        "noisy_neighbor": {"share": 0.5, "window_s": 30.0},
    })))

    def dev(el):
        return registry_mod.flat_name("tenant_device_ms_total",
                                      {"election": el})

    lat = registry_mod.flat_name("request_latency_ms",
                                 {"election": "quiet"})
    m0 = {"counters": {dev("flood"): 0.0, dev("quiet"): 0.0},
          "histograms": {}, "gauges": {}}
    assert engine.evaluate(0.0, m0, []) == []
    m1 = {"counters": {dev("flood"): 9000.0, dev("quiet"): 500.0},
          "histograms": {lat: {"bounds": [1000.0], "counts": [0, 5],
                               "sum": 9000.0, "count": 5}},
          "gauges": {}}
    fired = engine.evaluate(10.0, m1, [])
    noisy = [a for a in fired if a.kind == "noisy_neighbor"]
    assert [a.subject for a in noisy] == ["flood"]
    assert noisy[0].attrs["victims"] == ["quiet"]
    assert noisy[0].attrs["share"] > 0.9
    assert "'flood'" in noisy[0].detail
    # edge trigger: the same condition one tick later re-fires nothing
    again = engine.evaluate(11.0, m1, [])
    assert [a for a in again if a.kind == "noisy_neighbor"] == []


# =====================================================================
# tenant plumbing units: quota, scope, cardinality, record dirs
# =====================================================================


def test_tenant_quota_accounting_and_idempotent_release():
    q = TenantQuota(quota=2)
    r1 = q.acquire("el-x")
    q.acquire("el-x")
    with pytest.raises(TenantQuotaError, match=r"\[tenant\.quota\].*el-x"):
        q.acquire("el-x")
    # per-election isolation: another tenant is not starved
    assert q.acquire("el-y") is not None
    r1()
    r1()   # double release must not undercount
    assert q.inflight("el-x") == 1
    q.acquire("el-x")
    with pytest.raises(TenantQuotaError):
        q.acquire("el-x")
    # quota 0 disables accounting entirely
    assert TenantQuota(quota=0).acquire("anyone") is None


def test_tenant_scope_sets_ambient_election():
    assert tenant.current_election() == "default"   # the knob fallback
    with tenant.tenant_scope("scoped-el"):
        assert tenant.current_election() == "scoped-el"
        assert registry_mod.election_labels() == {"election": "scoped-el"}
        with tenant.tenant_scope("inner-el"):
            assert tenant.current_election() == "inner-el"
        assert tenant.current_election() == "scoped-el"
    assert tenant.current_election() == "default"


def test_tenant_cardinality_guard_named_error(monkeypatch):
    monkeypatch.setenv("EGTPU_TENANT_MAX", "2")
    tenant._reset_for_tests()
    try:
        with tenant.tenant_scope("card-1"):
            pass
        with tenant.tenant_scope("card-2"):
            pass
        with tenant.tenant_scope("card-1"):   # re-admission is free
            pass
        with pytest.raises(tenant.TenantCardinalityError,
                           match=r"\[tenant\.cardinality\].*card-3"):
            with tenant.tenant_scope("card-3"):
                pass
        assert tenant.seen_elections() == frozenset({"card-1", "card-2"})
    finally:
        tenant._reset_for_tests()


def test_tenant_registry_rejects_duplicate_election(tgroup):
    init = _ceremony(tgroup, "dup")
    registry = TenantRegistry()
    registry.add(ElectionContext("dup-el", init, group=tgroup,
                                 seed=tgroup.int_to_q(3)))
    with pytest.raises(ValueError, match=r"\[tenant\.duplicate\]"):
        registry.add(ElectionContext("dup-el", init, group=tgroup,
                                     seed=tgroup.int_to_q(4)))


def test_tenant_record_dir_contains_hostile_ids(tmp_path):
    base = str(tmp_path)
    dirs = set()
    for hid in HOSTILE_IDS + ("../../etc/passwd", "", "plain-election"):
        d = tenants_mod.tenant_record_dir(base, hid)
        # never a traversal, never a raw hostile byte in the path
        assert os.path.dirname(d) == base
        assert ".." not in os.path.basename(d)
        assert "\n" not in d and '"' not in d
        assert d == tenants_mod.tenant_record_dir(base, hid)  # stable
        dirs.add(d)
    assert len(dirs) == len(HOSTILE_IDS) + 3   # digest keeps ids distinct


# =====================================================================
# hostile tenant ids through every observability surface
# =====================================================================


def test_hostile_ids_roundtrip_registry_and_parse_labels():
    reg = registry_mod.MetricsRegistry("hostile")
    for hid in HOSTILE_IDS:
        with tenant.tenant_scope(hid):
            reg.counter("ballots_encrypted",
                        registry_mod.election_labels()).inc()
            reg.histogram("request_latency_ms",
                          (1.0, 10.0),
                          registry_mod.election_labels()).observe(2.0)
    snap = reg.snapshot()
    seen = {slo_mod.parse_labels(flat)[1]["election"]
            for flat in snap["counters"]}
    assert seen == set(HOSTILE_IDS)
    seen_h = {slo_mod.parse_labels(flat)[1]["election"]
              for flat in snap["histograms"]}
    assert seen_h == set(HOSTILE_IDS)
    # the Prometheus exposition stays line-oriented: embedded newlines
    # are escaped, one series per line
    text = reg.prometheus_text()
    series = [ln for ln in text.splitlines()
              if ln.startswith("egtpu_ballots_encrypted{")]
    assert len(series) == len(HOSTILE_IDS)
    assert any(r'line1\nline2' in ln for ln in series)
    assert any(r'general=\"2026\"' in ln for ln in series)


def test_hostile_ids_in_span_attrs_and_analyzer_buckets(tmp_path):
    spans = [{"trace_id": "t1", "span_id": "root", "parent_id": "",
              "name": "process", "ts": 0, "dur": 10_000,
              "proc": "serve-0"}]
    for i, hid in enumerate(HOSTILE_IDS):
        spans.append({"trace_id": "t1", "span_id": f"b{i}",
                      "parent_id": "root", "name": "worker.batch",
                      "ts": 100 + i * 200, "dur": 100, "proc": "serve-0",
                      "attrs": {"election": hid, "bucket": 1,
                                "n_real": 1}})
    (tmp_path / "spans-serve-0-1.jsonl").write_text(
        "".join(json.dumps(s) + "\n" for s in spans))
    a = analyze_mod.analyze(str(tmp_path))
    assert set(a.tenants) == set(HOSTILE_IDS)
    for stats in a.tenants.values():
        assert stats["n_batches"] == 1 and stats["device_us"] == 100
    assert abs(sum(s["share"] for s in a.tenants.values()) - 1.0) < 1e-6
    # the analysis artifact serializes the hostile ids losslessly
    doc = json.loads(json.dumps(a.to_json()))
    assert {row["election"] for row in doc["tenants"]} == set(HOSTILE_IDS)


def test_hostile_ids_respected_by_per_election_objectives():
    """A per_election SLO override keyed by a hostile id matches the
    series parsed back out of the flat snapshot name."""
    hid = HOSTILE_IDS[0]
    engine = slo_mod.SLOEngine(config=slo_mod.load_config(json.dumps({
        "serving_p99_ms": {"objective": 10_000.0,
                           "per_election": {hid: 0.5}},
    })))
    lat = registry_mod.flat_name("request_latency_ms", {"election": hid})
    fired = engine.evaluate(
        0.0, {"counters": {}, "gauges": {},
              "histograms": {lat: {"bounds": [1000.0], "counts": [0, 3],
                                   "sum": 4000.0, "count": 3}}}, [])
    burns = [a for a in fired if a.kind == "serving_p99"]
    assert len(burns) == 1 and burns[0].attrs["election"] == hid
    assert burns[0].attrs["objective_ms"] == 0.5

"""Byzantine adversary corpus: planted-attack detection, soundness
oracle sensitivity, shrinker minimality over adversary events, and the
pinned mixed attack+fault sweep.

Mirror of test_sim.py's structure for the malice dimension: every named
in-protocol attack in ``sim/adversary.py`` is planted individually and
must be detected in-band with one of its expected named error classes
(``utils/errors.py``) or by the verifier; the planted ``adv_noop``
attack (fires, changes nothing, detectable by nothing) proves the
soundness oracle itself is live.  ``tools/sim_matrix.py --adversaries``
runs the wide sweep and records it in SIM_BYZ_RESULTS.json.
"""

import pytest

from electionguard_tpu.sim import adversary
from electionguard_tpu.sim.explore import explore, run_sim
from electionguard_tpu.sim.schedule import (FaultEvent, from_json,
                                            generate_adversary_schedule,
                                            to_adversary_plan, to_json)
from electionguard_tpu.sim.shrink import shrink


def _adv(name: str, node: str = "", nth: int = 1) -> FaultEvent:
    return FaultEvent("adversary", method=name, nth=nth, a=node)


def _classes(report):
    return {v.split(":", 1)[0] for v in report.violations}


def _detected(report):
    return {cls for cls, _detail in report.detections}


# ------------------------------------------------------------- registry

def test_registry_invariants():
    """Every corpus attack is detectable by construction: a non-empty
    expect set, concrete targets, and rules that instantiate."""
    corpus = adversary.corpus()
    assert len(corpus) >= 8, "ISSUE floor: at least 8 named attacks"
    sides = set()
    for atk in corpus:
        assert atk.expect, f"{atk.name} has no expected detection class"
        assert atk.targets, f"{atk.name} has no targets"
        lo, hi = atk.nth_range
        assert 1 <= lo <= hi
        rules = adversary.build(atk.name, atk.targets[0], lo)
        assert rules
        sides |= {r.side for r in rules}
    # the corpus spans both mount sides: server (trustees, mixers) and
    # client (voters, registrations)
    assert sides == {"client", "server"}
    # adv_noop is the planted oracle probe, never drawn into sweeps
    assert "adv_noop" not in {a.name for a in corpus}
    assert "adv_noop" in adversary.REGISTRY


def test_plan_from_events_dedupes_involutive_mounts():
    """Mounting the same (attack, node, nth) twice must not cancel the
    involutive mutators — duplicates are dropped."""
    plan = adversary.plan_from_events(
        [("kc_bad_schnorr", "guardian-0", 1),
         ("kc_bad_schnorr", "guardian-0", 1),
         ("not_a_real_attack", "guardian-0", 1)])
    assert len(plan.rules) == 1


def test_adversary_schedule_generation_is_stream_isolated():
    import random
    s1 = generate_adversary_schedule(random.Random(9))
    s2 = generate_adversary_schedule(random.Random(9))
    assert s1 == s2 and s1
    assert all(e.kind == "adversary" for e in s1)
    assert from_json(to_json(s1)) == s1
    plan = to_adversary_plan(s1)
    assert plan.rules


def test_mix_tamper_env_alias_mounts_registry_attack(monkeypatch):
    """EGTPU_MIX_TAMPER is a thin alias over the registry: the env knob
    mounts mix_tamper_output (any server for '1', one server for an
    id), through the same lazy plan the sim installs explicitly."""
    monkeypatch.setenv("EGTPU_MIX_TAMPER", "mix-1")
    monkeypatch.setattr(adversary, "_loaded_env", False)
    monkeypatch.setattr(adversary, "_active", None)
    try:
        plan = adversary.active_plan()
        assert plan is not None
        (rule,) = plan.rules
        assert rule.attack == "mix_tamper_output"
        assert rule.node == "mix-1"
        assert not adversary.mix_tamper_fires("mix-0")
        assert adversary.mix_tamper_fires("mix-1")
        assert plan.fired and plan.fired[0][0] == "mix_tamper_output"
    finally:
        adversary.clear()


# ----------------------------------------------- planted attacks (one each)
# Each corpus attack planted alone at a known-firing (node, nth): it
# must actually fire AND be detected with one of its expected classes,
# with the run either completing green or sound-aborting — never a
# soundness violation, never an unexplained failure.

PLANTS = [
    ("kc_bad_schnorr", "guardian-0", 1),
    ("kc_equivocate", "guardian-1", 1),
    ("kc_bad_share_mac", "guardian-0", 1),
    ("kc_bad_challenge", "guardian-2", 1),
    ("mix_tamper_output", "mix-0", 1),
    ("mix_swap_commitments", "mix-0", 1),
    ("mix_replay_transcript", "", 2),
    ("client_malformed_ballot", "voter-0", 1),
    ("client_duplicate_ballot", "voter-0", 1),
    ("client_stale_nonce", "guardian-0", 1),
]


def test_plants_cover_the_whole_corpus():
    assert {p[0] for p in PLANTS} == {a.name for a in adversary.corpus()}


@pytest.mark.parametrize("name,node,nth", PLANTS,
                         ids=[p[0] for p in PLANTS])
def test_planted_attack_is_detected(name, node, nth):
    r = run_sim(3, schedule=[_adv(name, node, nth)])
    assert r.fired, f"{name} never fired — stale (node, nth) plant"
    assert all(f[0] == name for f in r.fired)
    assert adversary.expected_for(name) & _detected(r), (
        f"{name} fired but no expected class in {sorted(_detected(r))}")
    assert r.ok, r.summary()


def test_honest_run_records_no_attacks():
    """adversaries=False is byte-identical honest behavior: nothing
    fires, and the adversary plumbing adds no detections of its own."""
    r = run_sim(0)
    assert r.ok
    assert r.fired == []


# ------------------------------------------------------ soundness oracle

def test_soundness_oracle_fires_on_undetected_attack():
    """adv_noop fires (audit log) but mutates nothing, so no defense
    can see it: the exact green-undetected record the soundness oracle
    exists to catch."""
    r = run_sim(3, schedule=[_adv("adv_noop")])
    assert r.fired and r.fired[0][0] == "adv_noop"
    assert not r.ok
    assert _classes(r) == {"soundness"}
    assert any("attack adv_noop fired" in v and "never detected" in v
               for v in r.violations)


def test_detected_attack_raises_no_soundness_violation():
    """The converse: a detected attack contributes no violation even
    though it fired (detection set intersects the expect set)."""
    r = run_sim(3, schedule=[_adv("client_malformed_ballot", "voter-0")])
    assert r.fired
    assert "soundness" not in _classes(r)


# ------------------------------------------------------------- shrinking

ADV_NOOP = _adv("adv_noop")

NOISE = [
    FaultEvent("latency", method="pullRows", nth=1, seconds=0.2),
    FaultEvent("unavailable", method="sendPublicKeys", nth=1),
    FaultEvent("duplicate", seconds=0.02),
    FaultEvent("adversary", method="client_malformed_ballot", nth=1,
               a="voter-0"),   # detected attack: removable noise
]


def test_shrinker_minimizes_adversary_events():
    """ddmin + greedy strips the fault noise AND the detected attack:
    the minimal repro for the planted soundness violation is the single
    undetectable adversary event."""
    padded = NOISE[:2] + [ADV_NOOP] + NOISE[2:]
    res = shrink(3, padded)
    assert res.schedule == [ADV_NOOP]
    assert not res.exhausted
    assert any(v.startswith("soundness") for v in res.violations)
    assert from_json(res.repro_json()) == [ADV_NOOP]


# ------------------------------------------------------------- the sweep

def test_pinned_mixed_sweep_is_green():
    """Tier-1 Byzantine sweep: 20 pinned seeds, each composing a
    crash/network fault schedule (stream 1) with 1-2 drawn attacks
    (stream 5).  Every run must be green — detected attacks, sound
    aborts — with zero soundness violations, and the corpus must
    actually exercise several distinct attacks."""
    reports = explore(range(20), adversaries=True)
    bad = [r.summary() for r in reports if not r.ok]
    assert not bad, f"adversary sweep failures: {bad}"
    assert all("soundness" not in _classes(r) for r in reports)
    names = {f[0] for r in reports for f in r.fired}
    assert len(names) >= 5, f"sweep only exercised {sorted(names)}"
    assert sum(len(r.fired) for r in reports) >= 10


def test_adversary_run_replays_bit_for_bit():
    """Stream 5 is deterministic: same seed, same attacks, same trace."""
    a = run_sim(5, adversaries=True)
    b = run_sim(5, adversaries=True)
    assert a.trace_hash == b.trace_hash
    assert a.fired == b.fired
    assert a.schedule == b.schedule


def test_adversary_stream_does_not_perturb_honest_streams():
    """Adding adversaries must not change which FAULTS a seed draws:
    the fault slice of the schedule is identical with and without."""
    honest = run_sim(9)
    byz = run_sim(9, adversaries=True)
    faults_only = [e for e in byz.schedule if e.kind != "adversary"]
    assert faults_only == honest.schedule


@pytest.mark.slow
def test_wide_mixed_sweep_is_green():
    """The wide Byzantine sweep (seeds 20..219); sim_matrix
    --adversaries goes wider still and records SIM_BYZ_RESULTS.json."""
    reports = explore(range(20, 220), adversaries=True)
    bad = [r.summary() for r in reports if not r.ok]
    assert not bad, f"adversary sweep failures: {bad}"
    assert all("soundness" not in _classes(r) for r in reports)


# ------------------------------------------------------- regression pins

def test_pinned_regression_attack_exhausts_spares_soundly():
    """Seeds 30 and 62 of the first Byzantine sweep: attack + fault
    compositions burned every mix server (tamper/collusion evictions on
    top of a crash or a double-target draw) and the cascade exhaustion
    surfaced as a bare 'no registered mix server left' — a liveness red
    even though every attack WAS detected and the tampered record was
    never published.  Fixed by carrying the named eviction causes into
    the exhaustion error, which makes the abort attributable to the
    attack (a sound abort).  These seeds must stay green."""
    for seed in (30, 62):
        r = run_sim(seed, adversaries=True)
        assert r.ok, r.summary()
        assert r.fired


def test_pinned_regression_inflight_death_is_not_fired():
    """Seeds 115 and 175 of the 200-seed sweep, both false soundness
    reds from audit-log fidelity bugs: on 115 a partition killed the
    mutated sendPublicKeys response in flight — no defense ever saw the
    bad proof, the honest retry superseded it, yet it was recorded as
    fired; on 175 two kc attacks mounted the SAME involutive share-flip
    mutator on one call and cancelled to a byte-identical honest share.
    Fixed by delivery-scoped fired recording in the sim transport and
    by deduping rule mounts (composition now yields the stronger
    attack).  These seeds must stay green."""
    r115 = run_sim(115, adversaries=True)
    assert r115.ok, r115.summary()
    # the attack's only firing chance died in flight: NOT fired
    assert r115.fired == []
    r175 = run_sim(175, adversaries=True)
    assert r175.ok, r175.summary()
    assert r175.fired
    assert adversary.expected_for("kc_bad_challenge") & _detected(r175)


def test_pinned_regression_replay_of_poisoned_transcript_detected():
    """Seeds 112 and 125 of the 200-seed sweep: a replayed transcript
    that ANOTHER mix attack had poisoned was caught by
    verify-before-forward as mix.binding — detected and never
    published, but outside the replay attack's expect list, so the
    soundness oracle raised a false red.  The expect list now spans the
    whole stage-verification family.  These seeds must stay green."""
    for seed in (112, 125):
        r = run_sim(seed, adversaries=True)
        assert r.ok, r.summary()
        names = {f[0] for f in r.fired}
        assert "mix_replay_transcript" in names

"""In-process 5-phase workflow E2E on the tiny group: ceremony → batch
encrypt → accumulate → threshold decrypt → full verify.

This is the de-facto ``train()`` of the framework (SURVEY.md §3.4) minus the
process boundaries, on fast parameters.  The batch (device) encryption
pipeline must produce proofs that the *scalar* verifiers accept, and the
full Verifier must pass end-to-end — the hash-seam compatibility test.
"""

from electionguard_tpu.ballot.ciphertext import BallotState
from electionguard_tpu.core.dlog import DLog
from electionguard_tpu.decrypt.decryption import Decryption
from electionguard_tpu.decrypt.trustee import DecryptingTrustee
from electionguard_tpu.encrypt.encryptor import BatchEncryptor
from electionguard_tpu.publish.election_record import ElectionRecord
from electionguard_tpu.verify.verifier import Verifier


# the `election` fixture (tiny group, 3 guardians quorum 2) is session-
# scoped in tests/conftest.py, shared with the feeder-verify tests


def test_encryption_shapes(election):
    encrypted = election["encrypted"]
    assert len(encrypted) == 20
    for b in encrypted:
        assert len(b.contests) == 1
        c = b.contests[0]
        # 2 real + 1 placeholder (votes_allowed=1)
        assert len(c.selections) == 3
        assert sum(s.is_placeholder for s in c.selections) == 1


def test_scalar_proof_compat(election):
    """Device-generated proofs verify with the scalar is_valid path."""
    g, init = election["group"], election["init"]
    qbar = init.extended_base_hash
    K = init.joint_public_key
    b = election["encrypted"][0]
    c = b.contests[0]
    for s in c.selections:
        assert s.proof.is_valid(s.ciphertext, K, qbar), s.selection_id
    assert c.proof.is_valid(c.accumulation(), K, qbar)


def test_ballot_codes_chain(election):
    encrypted = election["encrypted"]
    assert all(b.is_valid_code() for b in encrypted)
    for prev, cur in zip(encrypted, encrypted[1:]):
        assert cur.code_seed == prev.code


def test_tally_matches_plaintext(election):
    """Decrypted tally equals the plaintext vote sums."""
    want = {}
    for pb in election["ballots"]:
        for c in pb.contests:
            for s in c.selections:
                want[(c.contest_id, s.selection_id)] = \
                    want.get((c.contest_id, s.selection_id), 0) + s.vote
    decrypted = election["decryption_result"].decrypted_tally
    got = {(c.contest_id, s.selection_id): s.tally
           for c in decrypted.contests for s in c.selections}
    assert got == want


def test_full_verifier_passes(election):
    record = ElectionRecord(
        election_init=election["init"],
        encrypted_ballots=election["encrypted"],
        tally_result=election["tally_result"],
        decryption_result=election["decryption_result"])
    res = Verifier(record, election["group"]).verify()
    assert res.ok, res.summary()
    assert len(res.checks) >= 12


def test_verifier_catches_tampered_ballot(election):
    import dataclasses
    record = ElectionRecord(
        election_init=election["init"],
        encrypted_ballots=list(election["encrypted"]),
        tally_result=election["tally_result"],
        decryption_result=election["decryption_result"])
    # swap two selections' ciphertexts inside a ballot (proofs now mismatch)
    b = record.encrypted_ballots[3]
    c = b.contests[0]
    s0, s1 = c.selections[0], c.selections[1]
    tampered_sels = (
        dataclasses.replace(s0, ciphertext=s1.ciphertext),
        dataclasses.replace(s1, ciphertext=s0.ciphertext),
        c.selections[2])
    tampered = dataclasses.replace(
        b, contests=(dataclasses.replace(c, selections=tampered_sels),))
    record.encrypted_ballots[3] = tampered
    res = Verifier(record, election["group"]).verify()
    assert not res.ok
    assert not res.checks["V4.selection_proofs"]


def test_verifier_catches_tally_tamper(election):
    import dataclasses
    tr = election["tally_result"]
    g = election["group"]
    t = tr.encrypted_tally
    c0 = t.contests[0]
    s0 = c0.selections[0]
    from electionguard_tpu.crypto.elgamal import ElGamalCiphertext
    bad_ct = ElGamalCiphertext(s0.ciphertext.pad,
                               g.mult_p(s0.ciphertext.data, g.G_MOD_P))
    bad_tally = dataclasses.replace(
        t, contests=(dataclasses.replace(
            c0, selections=(dataclasses.replace(s0, ciphertext=bad_ct),)
            + c0.selections[1:]),))
    record = ElectionRecord(
        election_init=election["init"],
        encrypted_ballots=election["encrypted"],
        tally_result=dataclasses.replace(tr, encrypted_tally=bad_tally))
    res = Verifier(record, election["group"]).verify()
    assert not res.ok
    assert not res.checks["V7.aggregation"]


def test_verifier_catches_placeholder_flip(election):
    """Flipping is_placeholder on a real vote-1 selection must fail
    verification (it would silently delete the vote from the tally)."""
    import dataclasses
    record = ElectionRecord(
        election_init=election["init"],
        encrypted_ballots=list(election["encrypted"]),
        tally_result=election["tally_result"],
        decryption_result=election["decryption_result"])
    b = record.encrypted_ballots[0]
    c = b.contests[0]
    real = next(s for s in c.selections if not s.is_placeholder)
    flipped_sels = tuple(
        dataclasses.replace(s, is_placeholder=True) if s is real else s
        for s in c.selections)
    tampered = dataclasses.replace(
        b, contests=(dataclasses.replace(c, selections=flipped_sels),))
    record.encrypted_ballots[0] = tampered
    res = Verifier(record, election["group"]).verify()
    assert not res.ok
    # caught by the id/flag consistency check and/or the broken ballot code
    assert (not res.checks["V4.selection_proofs"]
            or not res.checks["V6.ballot_chaining"])


def test_verifier_catches_duplicated_selection(election):
    """A contest carrying the same selection twice (double vote) must fail
    the exact-match structural check."""
    import dataclasses
    record = ElectionRecord(
        election_init=election["init"],
        encrypted_ballots=list(election["encrypted"]),
        tally_result=election["tally_result"],
        decryption_result=election["decryption_result"])
    b = record.encrypted_ballots[1]
    c = b.contests[0]
    real = next(s for s in c.selections if not s.is_placeholder)
    doubled = dataclasses.replace(
        b, contests=(dataclasses.replace(
            c, selections=c.selections + (real,)),))
    record.encrypted_ballots[1] = doubled
    res = Verifier(record, election["group"]).verify()
    assert not res.ok
    assert not res.checks["V4.selection_proofs"]


def test_encryptor_rejects_duplicate_selection(election):
    from electionguard_tpu.ballot.plaintext import (PlaintextBallot,
                                                    PlaintextBallotContest,
                                                    PlaintextBallotSelection)
    g = election["group"]
    enc = BatchEncryptor(election["init"], g)
    dup = PlaintextBallot("dup", "style-0", (PlaintextBallotContest(
        "contest-0", (PlaintextBallotSelection("sel-0", 1),
                      PlaintextBallotSelection("sel-0", 0))),))
    out, invalid = enc.encrypt_ballots([dup], seed=g.int_to_q(8))
    assert not out and len(invalid) == 1
    assert "duplicate selection" in invalid[0][1]


def test_decrypt_ballots_batches_rpc_legs(election):
    """decrypt_ballots must make ONE direct + ONE compensated call per
    trustee for a whole chunk (VERDICT r3 item 5) and agree with the
    per-ballot path."""
    g, init = election["group"], election["init"]
    dec_trustees = [DecryptingTrustee.from_state(
        g, t.decrypting_trustee_state()) for t in election["trustees"]]

    class CountingTrustee:
        def __init__(self, inner):
            self.inner, self.calls = inner, 0

        id = property(lambda self: self.inner.id)
        x_coordinate = property(lambda self: self.inner.x_coordinate)
        election_public_key = property(
            lambda self: self.inner.election_public_key)

        def direct_decrypt(self, texts, h):
            self.calls += 1
            return self.inner.direct_decrypt(texts, h)

        def compensated_decrypt(self, m, texts, h):
            self.calls += 1
            return self.inner.compensated_decrypt(m, texts, h)

    counting = [CountingTrustee(t) for t in dec_trustees[:2]]
    missing = [dec_trustees[2].id]
    decryption = Decryption(g, init, counting, missing,
                            DLog(g, max_exponent=100))
    chunk = list(election["encrypted"][:3])
    batch = decryption.decrypt_ballots(chunk)
    assert [t.calls for t in counting] == [2, 2]

    per_ballot = Decryption(g, init, dec_trustees[:2], missing,
                            DLog(g, max_exponent=100))
    for bt, b in zip(batch, chunk):
        st = per_ballot.decrypt_ballot(b)
        assert bt.tally_id == st.tally_id == b.ballot_id
        got = {(c.contest_id, s.selection_id): s.tally
               for c in bt.contests for s in c.selections}
        want = {(c.contest_id, s.selection_id): s.tally
                for c in st.contests for s in c.selections}
        assert got == want


def test_verifier_v12_contest_bounds(election):
    """A decoded tally exceeding cast-count bounds must fail V12 even
    when the claimed value is self-consistent (g^t == value)."""
    import dataclasses
    g = election["group"]
    dr = election["decryption_result"]
    dt = dr.decrypted_tally
    c0 = dt.contests[0]
    s0 = c0.selections[0]
    cast = dr.tally_result.encrypted_tally.cast_ballot_count
    bad_t = cast + 5
    bad = dataclasses.replace(s0, tally=bad_t,
                              value=g.g_pow_p(g.int_to_q(bad_t)))
    bad_dt = dataclasses.replace(
        dt, contests=(dataclasses.replace(
            c0, selections=(bad,) + c0.selections[1:]),) + dt.contests[1:])
    record = ElectionRecord(
        election_init=election["init"],
        encrypted_ballots=election["encrypted"],
        tally_result=election["tally_result"],
        decryption_result=dataclasses.replace(dr, decrypted_tally=bad_dt))
    res = Verifier(record, g).verify()
    assert not res.checks["V12.tally_decode"]


def test_verifier_catches_dropped_selection_from_decryption(election):
    """Publishing a decryption that omits one encrypted-tally selection
    must fail V12's coverage check — even when the attacker also drops
    the selection from the DecryptionResult's OWN embedded tally copy
    (the check must anchor to the independently verified record tally)."""
    import dataclasses
    dr = election["decryption_result"]
    dt = dr.decrypted_tally
    c0 = dt.contests[0]
    slim = dataclasses.replace(
        dt, contests=(dataclasses.replace(
            c0, selections=c0.selections[1:]),) + dt.contests[1:])
    et = dr.tally_result.encrypted_tally
    ec0 = et.contests[0]
    slim_et = dataclasses.replace(
        et, contests=(dataclasses.replace(
            ec0, selections=ec0.selections[1:]),) + et.contests[1:])
    slim_tr = dataclasses.replace(dr.tally_result, encrypted_tally=slim_et)
    record = ElectionRecord(
        election_init=election["init"],
        encrypted_ballots=election["encrypted"],
        tally_result=election["tally_result"],
        decryption_result=dataclasses.replace(
            dr, decrypted_tally=slim, tally_result=slim_tr))
    res = Verifier(record, election["group"]).verify()
    assert not res.checks["V12.tally_decode"]


def test_verifier_catches_dropped_share(election):
    """Dropping one available guardian's direct share from a selection
    must fail V8's coverage check (not just the combine equation)."""
    import dataclasses
    dr = election["decryption_result"]
    dt = dr.decrypted_tally
    c0 = dt.contests[0]
    s0 = c0.selections[0]
    kept = tuple(sh for sh in s0.shares if sh.proof is None) + \
        tuple(sh for sh in s0.shares if sh.proof is not None)[1:]
    bad = dataclasses.replace(s0, shares=kept)
    bad_dt = dataclasses.replace(
        dt, contests=(dataclasses.replace(
            c0, selections=(bad,) + c0.selections[1:]),) + dt.contests[1:])
    record = ElectionRecord(
        election_init=election["init"],
        encrypted_ballots=election["encrypted"],
        tally_result=election["tally_result"],
        decryption_result=dataclasses.replace(dr, decrypted_tally=bad_dt))
    res = Verifier(record, election["group"]).verify()
    assert not res.checks["V8.direct_proofs"]


def test_stream_spoiled_tallies_chunks(election):
    """stream_spoiled_tallies must filter SPOILED ballots, decrypt in
    chunk-sized batches (ceil(n/chunk) rpc legs per trustee per
    protocol), and yield one tally per spoiled ballot in order."""
    import dataclasses

    from electionguard_tpu.decrypt.decryption import stream_spoiled_tallies
    g, init = election["group"], election["init"]
    dec_trustees = [DecryptingTrustee.from_state(
        g, t.decrypting_trustee_state()) for t in election["trustees"]]

    calls = {"n": 0}

    class CountingTrustee:
        def __init__(self, inner):
            self.inner = inner

        id = property(lambda self: self.inner.id)
        x_coordinate = property(lambda self: self.inner.x_coordinate)
        election_public_key = property(
            lambda self: self.inner.election_public_key)

        def direct_decrypt(self, texts, h):
            calls["n"] += 1
            return self.inner.direct_decrypt(texts, h)

        def compensated_decrypt(self, m, texts, h):
            calls["n"] += 1
            return self.inner.compensated_decrypt(m, texts, h)

    decryption = Decryption(
        g, init, [CountingTrustee(t) for t in dec_trustees[:2]],
        [dec_trustees[2].id], DLog(g, max_exponent=100))
    ballots = [dataclasses.replace(b, state=BallotState.SPOILED)
               if i % 2 == 0 else b
               for i, b in enumerate(election["encrypted"][:10])]
    tallies = list(stream_spoiled_tallies(iter(ballots), decryption,
                                          chunk_size=2))
    spoiled_ids = [b.ballot_id for b in ballots
                   if b.state == BallotState.SPOILED]
    assert [t.tally_id for t in tallies] == spoiled_ids  # 5, in order
    # 5 spoiled / chunk 2 = 3 chunks x 2 trustees x (direct + comp)
    assert calls["n"] == 3 * 2 * 2


def test_spoiled_tally_forgery_detected(election):
    """A fabricated spoiled-ballot decryption must fail V13."""
    import dataclasses
    from electionguard_tpu.ballot.ciphertext import BallotState
    spoiled = dataclasses.replace(election["encrypted"][0],
                                  state=BallotState.SPOILED)
    ballots = [spoiled] + list(election["encrypted"][1:])
    # forge a tally claiming arbitrary values with garbage shares
    from electionguard_tpu.ballot.tally import (PlaintextTally,
                                                PlaintextTallyContest,
                                                PlaintextTallySelection,
                                                PartialDecryption)
    g = election["group"]
    c0 = spoiled.contests[0]
    forged = PlaintextTally(spoiled.ballot_id, (PlaintextTallyContest(
        c0.contest_id, tuple(
            PlaintextTallySelection(
                s.selection_id, 1, g.G_MOD_P, s.ciphertext,
                (PartialDecryption("guardian-0", g.G_MOD_P, None, {}),))
            for s in c0.selections)),))
    record = ElectionRecord(
        election_init=election["init"],
        encrypted_ballots=ballots,
        tally_result=election["tally_result"],
        decryption_result=election["decryption_result"],
        spoiled_ballot_tallies=[forged])
    res = Verifier(record, g).verify()
    assert not res.ok
    assert not res.checks["V13.spoiled"]


def test_verifier_catches_bad_guardian_proof(election):
    """A tampered guardian Schnorr response must fail V2 through the
    batched verification path."""
    import dataclasses
    g = election["group"]
    init = election["init"]
    gr = init.guardians[0]
    pr = gr.coefficient_proofs[0]
    bad_pr = dataclasses.replace(
        pr, response=g.add_q(pr.response, g.ONE_MOD_Q))
    bad_gr = dataclasses.replace(
        gr, coefficient_proofs=(bad_pr,) + gr.coefficient_proofs[1:])
    bad_init = dataclasses.replace(
        init, guardians=(bad_gr,) + init.guardians[1:])
    record = ElectionRecord(
        election_init=bad_init,
        encrypted_ballots=election["encrypted"],
        tally_result=election["tally_result"])
    res = Verifier(record, g).verify()
    assert not res.checks["V2.guardian_keys"]

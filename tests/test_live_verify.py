"""Live verification plane: tailing edge cases + live/batch convergence.

Pins the contracts of ``verify/live``:

* a torn stream tail is "not yet", never an error — the tailer retries
  and converges once the writer completes the frame;
* a journaled-but-unpublished admission (fsync'd WAL entry whose ballot
  has not reached the record stream) is audit LAG, never red;
* SIGKILL anywhere — after a checkpoint, or between "chunk verified"
  and "checkpoint written" — resumes to the SAME final verdict, error
  list, chunk-accept set, and commitment root as an uncrashed run;
* live and terminal batch verification agree bit-for-bit, on green
  records and on tampered ones (both red, same offender);
* the commitment ledger's inclusion proofs verify against the root the
  bulletin board serves, over real gRPC.
"""

import json
import os
import shutil
import struct

import pytest

from electionguard_tpu.publish import serialize
from electionguard_tpu.publish.election_record import ElectionRecord
from electionguard_tpu.publish.publisher import Consumer, Publisher
from electionguard_tpu.utils import errors
from electionguard_tpu.verify.live import (BulletinBoard,
                                           BulletinBoardClient,
                                           CommitmentLedger, LiveVerifier)
from electionguard_tpu.verify.verifier import Verifier

CHUNK = 4   # 20 ballots -> 5 chunks: boundaries exercise the ledger


def _frames(election):
    return [serialize.publish_encrypted_ballot(b).SerializeToString()
            for b in election["encrypted"]]


def _init_dir(election, tmp_path, name="record"):
    out = str(tmp_path / name)
    Publisher(out).write_election_initialized(election["init"])
    return out


def _append_frames(record_dir, frames, torn=b""):
    """Append complete frames (+ optionally torn trailing bytes) to the
    ballot stream, like the serving plane's incremental flushes."""
    path = os.path.join(record_dir, "encrypted_ballots.pb")
    with open(path, "ab") as f:
        for fr in frames:
            f.write(struct.pack(">I", len(fr)) + fr)
        if torn:
            f.write(torn)
        f.flush()
        os.fsync(f.fileno())


def _write_terminal(election, record_dir):
    pub = Publisher(record_dir)
    pub.write_tally_result(election["tally_result"])
    pub.write_decryption_result(election["decryption_result"])


def _batch_verify(election, record_dir):
    """The terminal batch pass at the live chunk size (identical chunk
    boundaries make even the error ORDER comparable)."""
    g = election["group"]
    consumer = Consumer(record_dir, g)
    record = ElectionRecord(consumer.read_election_initialized())
    record.tally_result = consumer.read_tally_result()
    record.decryption_result = consumer.read_decryption_result()
    record.encrypted_ballots = consumer.iterate_encrypted_ballots()
    return Verifier(record, g, chunk_size=CHUNK).verify()


def _oneshot_live(election, record_dir, tmp_path, name):
    """A fresh LiveVerifier over the finished record (the batch-side
    ledger rebuild the convergence oracle compares roots against)."""
    live = LiveVerifier(record_dir, election["group"], chunk=CHUNK,
                        checkpoint_path=str(tmp_path / name))
    res = live.finalize()
    return live, res


def test_torn_tail_then_completion(election, tmp_path):
    record_dir = _init_dir(election, tmp_path)
    frames = _frames(election)
    live = LiveVerifier(record_dir, election["group"], chunk=CHUNK)

    # first flush lands 6 complete frames plus a torn half-frame
    torn = struct.pack(">I", len(frames[6])) + frames[6][:5]
    _append_frames(record_dir, frames[:6], torn=torn)
    live.poll()
    assert live.verified_frames == 4          # one full chunk committed
    assert live.frames_published() == 6       # torn frame NOT counted
    assert live.audit_state()["verdict_ok"]

    # the writer completes the torn frame and the rest of the stream
    path = os.path.join(record_dir, "encrypted_ballots.pb")
    with open(path, "ab") as f:
        f.write(frames[6][5:])
    _append_frames(record_dir, frames[7:])
    live.poll()
    assert live.verified_frames == 20
    _write_terminal(election, record_dir)
    res = live.finalize()
    assert res.ok, res.summary()
    assert len(live.ledger.chunks) == 5
    assert all(c.accepted for c in live.ledger.chunks)

    # bit-identical to the terminal batch pass and its ledger rebuild
    batch = _batch_verify(election, record_dir)
    assert (res.checks, res.errors) == (batch.checks, batch.errors)
    ref, ref_res = _oneshot_live(election, record_dir, tmp_path, "ref.json")
    assert ref_res.ok
    assert live.ledger.root() == ref.ledger.root()
    assert live.ledger.head == ref.ledger.head


def test_journal_gap_is_lag_not_error(election, tmp_path):
    """Admissions fsync'd into the WAL but not yet published (the crash
    window the serving plane replays) must show as audit lag only."""
    from electionguard_tpu.serve import journal as wal
    record_dir = _init_dir(election, tmp_path)
    frames = _frames(election)
    _append_frames(record_dir, frames[:4])

    j = wal.AdmissionJournal(os.path.join(record_dir, wal.JOURNAL_NAME))
    for b in election["ballots"][:6]:
        j.append(b, False)
    j.append_drop(election["ballots"][5].ballot_id)
    # torn trailing WAL line: mid-append crash, never ack'd
    with open(j.path, "ab") as f:
        f.write(b'{"id": "torn')
    j.close()

    live = LiveVerifier(record_dir, election["group"], chunk=CHUNK)
    live.poll()
    s = live.audit_state()
    assert s["ballots_admitted"] == 5         # 6 admitted - 1 dropped
    assert s["frames_verified"] == 4
    assert s["verdict_ok"] and not s["errors"]
    assert s["status"] == "TAILING"


def test_sigkill_resume_converges(election, tmp_path):
    """Kill the live verifier at a checkpoint AND in the window between
    'chunk verified' and 'checkpoint written': both resumes end
    bit-identical to an uncrashed run."""
    record_dir = _init_dir(election, tmp_path)
    frames = _frames(election)
    ckpt = os.path.join(record_dir, "live_checkpoint.json")

    live = LiveVerifier(record_dir, election["group"], chunk=CHUNK)
    _append_frames(record_dir, frames[:9])
    live.poll()                               # commits chunks 0, 1
    assert live.verified_frames == 8
    ckpt_after_2 = ckpt + ".saved"
    shutil.copy(ckpt, ckpt_after_2)

    _append_frames(record_dir, frames[9:])
    live.poll()                               # commits chunks 2, 3, 4
    del live                                  # SIGKILL incarnation 1

    # crash case A: died right after a checkpoint — resume from it
    _write_terminal(election, record_dir)
    live2 = LiveVerifier(record_dir, election["group"], chunk=CHUNK)
    assert live2.verified_frames == 20        # restored, not re-verified
    res2 = live2.finalize()
    assert res2.ok, res2.summary()

    # crash case B: the checkpoint for chunks 2-4 was never written —
    # the stale checkpoint resumes at frame 8 and re-verifies from disk
    shutil.copy(ckpt_after_2, ckpt)
    live3 = LiveVerifier(record_dir, election["group"], chunk=CHUNK)
    assert live3.verified_frames == 8
    res3 = live3.finalize()
    assert res3.ok
    assert (res3.checks, res3.errors) == (res2.checks, res2.errors)
    assert live3.ledger.root() == live2.ledger.root()
    assert live3.ledger.head == live2.ledger.head
    assert [c.accepted for c in live3.ledger.chunks] == \
        [c.accepted for c in live2.ledger.chunks]

    # and both equal the terminal batch pass
    batch = _batch_verify(election, record_dir)
    assert (res2.checks, res2.errors) == (batch.checks, batch.errors)


def test_tampered_record_live_equals_batch(election, tmp_path):
    """Swap two mid-stream frames (breaks the V6 code chain): live and
    batch must BOTH go red, naming the same offender ballots, and the
    live pass must flag it at the chunk containing the tamper."""
    record_dir = _init_dir(election, tmp_path)
    frames = _frames(election)
    frames[10], frames[11] = frames[11], frames[10]
    _append_frames(record_dir, frames)
    _write_terminal(election, record_dir)

    live, res = _oneshot_live(election, record_dir, tmp_path, "live.json")
    batch = _batch_verify(election, record_dir)
    assert not res.ok and not batch.ok
    assert (res.checks, res.errors) == (batch.checks, batch.errors)
    assert any("V6" in e for e in res.errors)

    # the accept-set localizes the tamper: chunk 2 (frames 8-11) red,
    # chunk 3 (frames 12-15) red (its first seed points at the swap),
    # everything else green
    accepted = [c.accepted for c in live.ledger.chunks]
    assert accepted == [True, True, False, False, True]


def test_bulletin_board_roundtrip(election, tmp_path):
    record_dir = _init_dir(election, tmp_path)
    _append_frames(record_dir, _frames(election))
    _write_terminal(election, record_dir)
    live, res = _oneshot_live(election, record_dir, tmp_path, "live.json")
    assert res.ok

    board = BulletinBoard(live, port=0)
    try:
        client = BulletinBoardClient(f"localhost:{board.port}")
        root = client.root()
        assert root.root == live.ledger.root()
        assert root.chain_head == live.ledger.head
        assert root.n_chunks == 5 and root.n_frames == 20
        for idx in range(root.n_chunks):
            proof = client.inclusion_proof(idx)
            assert CommitmentLedger.verify_proof(
                proof.leaf, list(proof.path), list(proof.right),
                proof.root)
            assert proof.accepted
        with pytest.raises(ValueError, match="no chunk 99"):
            client.inclusion_proof(99)
        s = client.audit_state()
        assert s.status == "DONE" and s.verdict_ok
        assert s.frames_verified == 20 and s.audit_lag_frames == 0
        m = client.metrics()
        # the live verifier's series is election-labeled now (the
        # ambient "default" tenant)
        from electionguard_tpu.obs.registry import (election_labels,
                                                    flat_name)
        chunks_key = flat_name("live_chunks_verified_total",
                               election_labels())
        assert m.counters[chunks_key] >= 5
        client.close()
    finally:
        board.shutdown()


def test_checkpoint_is_json_and_survives_reload(election, tmp_path):
    """The checkpoint must round-trip every aggregate the finalize pass
    needs (V7 products, chain tail, spoiled/dup bookkeeping)."""
    record_dir = _init_dir(election, tmp_path)
    _append_frames(record_dir, _frames(election))
    live = LiveVerifier(record_dir, election["group"], chunk=CHUNK)
    live.poll()
    with open(live.checkpoint_path) as f:
        state = json.load(f)
    assert state["verified_frames"] == 20
    assert state["agg"]["prev_code"]
    assert state["agg"]["prods"]

    live2 = LiveVerifier(record_dir, election["group"], chunk=CHUNK)
    assert live2.agg.prods == live.agg.prods
    assert live2.agg.prev_code == live.agg.prev_code
    assert live2.ledger.head == live.ledger.head


def test_corrupt_frame_is_red_not_retry(election, tmp_path):
    """A header over the sanity bound is a corrupt stream: the tailer
    raises the NAMED error immediately instead of waiting forever."""
    from electionguard_tpu.publish import framing
    record_dir = _init_dir(election, tmp_path)
    frames = _frames(election)
    _append_frames(record_dir, frames[:4])
    path = os.path.join(record_dir, "encrypted_ballots.pb")
    with open(path, "ab") as f:   # insane length header + some bytes
        f.write(struct.pack(">I", 1 << 30) + b"garbage")

    live = LiveVerifier(record_dir, election["group"], chunk=CHUNK)
    with pytest.raises(framing.CorruptFrameError) as ei:
        live.poll()
        live.poll()
    assert "publish.corrupt_frame" in errors.classes_in(str(ei.value))


def test_consumer_named_frame_errors(election, tmp_path):
    """Satellite: Consumer's frame readers fail with the named classes
    (oracle-attributable), not bare struct/ValueError."""
    from electionguard_tpu.publish import framing
    record_dir = _init_dir(election, tmp_path)
    frames = _frames(election)
    _append_frames(record_dir, frames[:2],
                   torn=struct.pack(">I", 999) + b"short")
    consumer = Consumer(record_dir, election["group"])
    with pytest.raises(framing.TruncatedFrameError) as ei:
        list(consumer.iterate_encrypted_ballots())
    assert "publish.truncated_frame" in errors.classes_in(str(ei.value))
    # TruncatedFrameError still IS an IOError: legacy recovery paths
    # (and run_verifier's unreadable-record exit) keep working
    assert isinstance(ei.value, IOError)


def test_mix_stage_row_mismatch_named(election, tmp_path):
    from electionguard_tpu.mixnet.stage import rows_from_ballots, run_stage
    record_dir = _init_dir(election, tmp_path)
    g = election["group"]
    init = election["init"]
    pads, datas = rows_from_ballots(election["encrypted"])
    stage = run_stage(g, init.joint_public_key.value,
                      init.extended_base_hash, 0, pads, datas, seed=b"t")
    pub = Publisher(record_dir)
    path = pub.write_mix_stage(g, stage)
    # drop the final row frame: header n_rows now disagrees
    from electionguard_tpu.publish.framing import read_frames
    all_frames = list(read_frames(path))
    with open(path, "wb") as f:
        for fr in all_frames[:-1]:
            f.write(struct.pack(">I", len(fr)) + fr)
    with pytest.raises(IOError) as ei:
        Consumer(record_dir, g).read_mix_stage(0)
    assert "publish.mix_row_mismatch" in errors.classes_in(str(ei.value))

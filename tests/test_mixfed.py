"""Federated mix plane tests (tiny group, non-slow, in-process).

The acceptance surface of the mixfed subsystem:

* a 3-stage federated run (real gRPC between in-process servers)
  publishes a record that the standard ``verify_stages`` path passes
  with every V15 mix check green;
* the trust boundary is STRUCTURAL: a server refuses a second stage
  in-band, so no process ever holds two stages' permutations or
  randomness (asserted by inspecting server state);
* a tampering server is caught by the coordinator's pre-forward
  verification as a ``mix_binding`` failure — requeued onto a spare
  when one exists, a hard ``MixFedError`` naming the check when not,
  and in both cases NOTHING tainted reaches the published record;
* a server killed mid-stage (fault-plan ``crash_after``) costs one
  requeue onto a spare and the final record still verifies with zero
  dropped or duplicated rows;
* a restarted coordinator resumes at the first unpublished stage
  instead of re-mixing verified work.
"""

import threading

import pytest

from electionguard_tpu.core.group import tiny_group
from electionguard_tpu.crypto.elgamal import ElGamalKeypair, elgamal_encrypt
from electionguard_tpu.mixfed import (MixCoordinator, MixFedError,
                                      MixServerServer)
from electionguard_tpu.mixnet.verify_mix import verify_stages
from electionguard_tpu.obs import REGISTRY, election_labels
from electionguard_tpu.publish import pb, serialize
from electionguard_tpu.publish.publisher import Consumer
from electionguard_tpu.remote import rpc_util
from electionguard_tpu.testing import faults


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    yield
    faults.clear()


@pytest.fixture
def fastrpc(monkeypatch):
    """Fast deterministic retries so dead-server detection is sub-second."""
    monkeypatch.setenv("EGTPU_RPC_RETRIES", "2")
    monkeypatch.setenv("EGTPU_RPC_RETRY_WAIT", "0.2")
    monkeypatch.setenv("EGTPU_RPC_RETRY_CAP", "0.4")
    monkeypatch.setenv("EGTPU_RPC_CONNECT_WINDOW", "0.4")
    monkeypatch.setattr(rpc_util, "_uniform", lambda lo, hi: hi)


@pytest.fixture(scope="module")
def mixkey():
    g = tiny_group()
    return ElGamalKeypair.from_secret(g.int_to_q(987654321))


def _encrypt_rows(g, K, n, w, seed=1000):
    pads, datas = [], []
    for i in range(n):
        row_a, row_b = [], []
        for j in range(w):
            ct = elgamal_encrypt(g, (i + j) % 2,
                                 g.int_to_q(seed + i * w + j), K)
            row_a.append(ct.pad.value)
            row_b.append(ct.data.value)
        pads.append(row_a)
        datas.append(row_b)
    return pads, datas


class _Init:
    def __init__(self, K, qbar):
        self.joint_public_key = K
        self.extended_base_hash = qbar


class _Res:
    def __init__(self):
        self.failures = []

    def record(self, name, ok, msg=""):
        if not ok:
            self.failures.append((name, msg))


def _verify_record(g, K, qbar, out_dir, in_pads, in_datas, n_stages):
    stages = Consumer(out_dir, g).read_mix_stages()
    assert len(stages) == n_stages
    res = _Res()
    ok = verify_stages(g, _Init(K, qbar), stages, res,
                       lambda: (in_pads, in_datas))
    assert ok, f"record failed verification: {res.failures}"
    return stages


def _shutdown(coord, servers, all_ok=True):
    coord.shutdown(all_ok=all_ok)
    for s in servers:
        s.server.stop(grace=0)


# ---------------------------------------------------------------------------
# happy path + trust boundary
# ---------------------------------------------------------------------------

def test_three_stage_federated_record_verifies(tmp_path, mixkey):
    """Three stages over four servers (one spare): the published record
    passes every V15 mix check, each stage ran on a DIFFERENT server,
    and the spare held nothing."""
    g = tiny_group()
    K, qbar = mixkey.public_key, g.int_to_q(424242)
    pads, datas = _encrypt_rows(g, K, 9, 2)
    coord = MixCoordinator(g, str(tmp_path), port=0)
    servers = [MixServerServer(g, coord.url, f"mix{i}") for i in range(4)]
    try:
        assert coord.wait_for_servers(3, timeout=30)
        assert coord.run_mix(K.value, qbar, 3, pads, datas) == 3
        stages = _verify_record(g, K, qbar, str(tmp_path),
                                pads, datas, 3)
        assert [s.stage_index for s in stages] == [0, 1, 2]
        # ---- trust boundary: one stage per process, ever -------------
        held = sorted(s.held_stage for s in servers
                      if s.held_stage is not None)
        assert held == [0, 1, 2]          # three distinct stages...
        assert sum(s.held_stage is None for s in servers) == 1  # ...one idle
        for s in servers:
            # a server's entire mixing state concerns ITS stage only:
            # the permutation/randomness seed never leaves run_stage,
            # and the buffered rows/result are the held stage's alone
            if s.held_stage is None:
                assert not s._chunks and s._result is None
            else:
                assert int(s._result.header.stage_index) == s.held_stage
    finally:
        _shutdown(coord, servers)


def test_server_refuses_second_stage(tmp_path, mixkey):
    """The one-stage-per-process invariant is enforced by the SERVER,
    not by coordinator bookkeeping: a second registerStage for a
    different stage is refused in-band."""
    g = tiny_group()
    coord = MixCoordinator(g, str(tmp_path), port=0)
    server = MixServerServer(g, coord.url, "mix0")
    try:
        channel = rpc_util.make_channel(server.url)
        stub = rpc_util.Stub(channel, "MixServerService")

        def assign(k):
            return stub.call("registerStage", pb.MixStageRequest(
                stage_index=k,
                joint_public_key=serialize._pub_p_int(g, mixkey.public_key.value),
                extended_base_hash=serialize.publish_q(g.int_to_q(1)),
                n_rows=2, width=1, group_fingerprint=g.fingerprint()))

        assert assign(0).error == ""
        assert assign(0).error == ""          # same stage: idempotent
        err = assign(1).error
        assert "already holds stage 0" in err
        assert server.held_stage == 0
        channel.close()
    finally:
        _shutdown(coord, [server])


# ---------------------------------------------------------------------------
# adversarial: tampering server
# ---------------------------------------------------------------------------

def test_tampering_server_requeued_on_spare(tmp_path, mixkey):
    """A server that corrupts an output ciphertext after proving is
    caught by the coordinator's pre-forward verification (the
    Fiat–Shamir challenge no longer re-derives → mix_binding), its
    stage is requeued on an honest spare, and the published record is
    clean."""
    g = tiny_group()
    K, qbar = mixkey.public_key, g.int_to_q(424242)
    pads, datas = _encrypt_rows(g, K, 6, 1)
    coord = MixCoordinator(g, str(tmp_path), port=0)
    bad_counter = REGISTRY.counter("mixfed_bad_proofs_total",
                                   election_labels())
    before = bad_counter.value
    # the tamperer registers FIRST, so stage 0 is assigned to it
    cheat = MixServerServer(g, coord.url, "cheat", tamper=True)
    honest = [MixServerServer(g, coord.url, f"mix{i}") for i in range(2)]
    try:
        assert coord.wait_for_servers(3, timeout=30)
        assert coord.run_mix(K.value, qbar, 2, pads, datas) == 2
        _verify_record(g, K, qbar, str(tmp_path), pads, datas, 2)
        assert bad_counter.value == before + 1
        assert next(s for s in coord.servers if s.id == "cheat").failed
    finally:
        _shutdown(coord, [cheat] + honest)


def test_tamper_aborts_before_forwarding_without_spare(tmp_path, mixkey):
    """With no spare left the coordinator ABORTS, naming the failing
    check class — and the tainted stage never reaches the record."""
    g = tiny_group()
    K, qbar = mixkey.public_key, g.int_to_q(424242)
    pads, datas = _encrypt_rows(g, K, 4, 1)
    coord = MixCoordinator(g, str(tmp_path), port=0)
    cheat = MixServerServer(g, coord.url, "cheat", tamper=True)
    try:
        assert coord.wait_for_servers(1, timeout=30)
        with pytest.raises(MixFedError) as ei:
            coord.run_mix(K.value, qbar, 1, pads, datas)
        assert ei.value.check == "mix_binding"
        # abort happened BEFORE forwarding: nothing was published
        assert Consumer(str(tmp_path), g).mix_stage_count() == 0
    finally:
        _shutdown(coord, [cheat], all_ok=False)


# ---------------------------------------------------------------------------
# chaos: server killed mid-stage
# ---------------------------------------------------------------------------

def test_crash_mid_stage_requeues_on_spare(tmp_path, mixkey, fastrpc):
    """The victim dies right after its first shuffleStage commits (the
    response is lost, the process is gone).  The coordinator's bounded
    retries surface UNAVAILABLE, the stage is requeued on the spare,
    and the final record verifies with zero dropped or duplicated
    rows."""
    g = tiny_group()
    K, qbar = mixkey.public_key, g.int_to_q(424242)
    pads, datas = _encrypt_rows(g, K, 6, 1)
    victim: dict = {}
    plan = faults.FaultPlan(rules=[faults.FaultRule(
        method="shuffleStage", kind="crash_after", on_calls=(1,))])
    plan.crash_cb = lambda _m: threading.Timer(
        0.05, lambda: victim["server"].server.stop(grace=0)).start()
    faults.install(plan)
    requeue = REGISTRY.counter("mixfed_stage_requeues_total",
                               election_labels())
    before = requeue.value
    coord = MixCoordinator(g, str(tmp_path), port=0)
    servers = [MixServerServer(g, coord.url, f"mix{i}") for i in range(3)]
    victim["server"] = servers[0]
    try:
        assert coord.wait_for_servers(3, timeout=30)
        assert coord.run_mix(K.value, qbar, 2, pads, datas) == 2
        assert requeue.value == before + 1
        assert plan.injected, "the crash plan never fired"
        stages = _verify_record(g, K, qbar, str(tmp_path),
                                pads, datas, 2)
        # zero dropped/duplicated rows, by construction and by check:
        # verification green implies each stage is a permutation of its
        # input; row counts pin the cardinality
        assert all(s.n_rows == 6 for s in stages)
    finally:
        _shutdown(coord, servers)


# ---------------------------------------------------------------------------
# checkpoint / resume
# ---------------------------------------------------------------------------

def test_coordinator_restart_resumes_at_unpublished_stage(tmp_path, mixkey):
    """A coordinator that dies between stages is relaunched against the
    same output dir + checkpoint file: verified stages are NOT re-mixed,
    the cascade continues from the published chain head, and the full
    record verifies."""
    g = tiny_group()
    K, qbar = mixkey.public_key, g.int_to_q(424242)
    pads, datas = _encrypt_rows(g, K, 5, 1)
    cp = str(tmp_path / "mix_checkpoint.json")
    out = str(tmp_path / "record")

    coord1 = MixCoordinator(g, out, port=0, checkpoint_file=cp)
    first = [MixServerServer(g, coord1.url, f"a{i}") for i in range(2)]
    try:
        assert coord1.wait_for_servers(2, timeout=30)
        assert coord1.run_mix(K.value, qbar, 2, pads, datas) == 2
    finally:
        _shutdown(coord1, first)

    # "restart": a fresh coordinator + fresh servers, same out/checkpoint
    coord2 = MixCoordinator(g, out, port=0, checkpoint_file=cp)
    second = [MixServerServer(g, coord2.url, "b0")]
    try:
        assert coord2.wait_for_servers(1, timeout=30)
        # only the one unpublished stage runs — one server suffices
        assert coord2.run_mix(K.value, qbar, 3, pads, datas) == 1
        assert second[0].held_stage == 2
        _verify_record(g, K, qbar, out, pads, datas, 3)
    finally:
        _shutdown(coord2, second)

"""Unit tests for protocol primitives (ElGamal, Schnorr, Chaum-Pedersen,
HashedElGamal) on the fast test group, with a production-group smoke test."""

import pytest

from electionguard_tpu.core.dlog import DLog
from electionguard_tpu.core.hash import hash_digest, hash_elems
from electionguard_tpu.core.nonces import Nonces
from electionguard_tpu.crypto.chaum_pedersen import (
    ConstantChaumPedersenProof, make_constant_cp_proof,
    make_disjunctive_cp_proof, make_generic_cp_proof)
from electionguard_tpu.crypto.elgamal import (ElGamalKeypair,
                                              elgamal_accumulate,
                                              elgamal_encrypt)
from electionguard_tpu.crypto.hashed_elgamal import hashed_elgamal_encrypt
from electionguard_tpu.crypto.schnorr import make_schnorr_proof


def test_hash_deterministic_and_injective(tgroup):
    a = hash_elems(tgroup, "x", 1, tgroup.int_to_q(2))
    b = hash_elems(tgroup, "x", 1, tgroup.int_to_q(2))
    assert a == b
    # type-tagged encoding distinguishes str "1" from int 1
    assert hash_digest("1") != hash_digest(1)
    assert hash_digest("a", "bc") != hash_digest("ab", "c")


def test_nonces_deterministic(tgroup):
    seed = tgroup.int_to_q(42)
    n1, n2 = Nonces(seed, "hdr"), Nonces(seed, "hdr")
    assert n1[0] == n2[0] and n1[5] == n2[5]
    assert n1[0] != n1[1]
    assert Nonces(seed, "other")[0] != n1[0]


def test_elgamal_roundtrip(tgroup):
    kp = ElGamalKeypair.generate(tgroup)
    dlog = DLog(tgroup, max_exponent=1000)
    for v in (0, 1, 5, 100):
        ct = elgamal_encrypt(tgroup, v, tgroup.rand_q(), kp.public_key)
        assert ct.decrypt(kp.secret_key, dlog) == v


def test_elgamal_homomorphic(tgroup):
    kp = ElGamalKeypair.generate(tgroup)
    dlog = DLog(tgroup, max_exponent=1000)
    cts = [elgamal_encrypt(tgroup, v, tgroup.rand_q(), kp.public_key)
           for v in (1, 0, 1, 1, 7)]
    acc = elgamal_accumulate(cts)
    assert acc.decrypt(kp.secret_key, dlog) == 10


def test_dlog_bsgs(tgroup):
    dlog = DLog(tgroup, max_exponent=100000)
    for t in (0, 1, 999, 65537, 100000):
        assert dlog.dlog(tgroup.g_pow_p(tgroup.int_to_q(t))) == t


def test_schnorr(tgroup):
    kp = ElGamalKeypair.generate(tgroup)
    proof = make_schnorr_proof(tgroup, kp.secret_key, kp.public_key,
                               tgroup.rand_q())
    assert proof.is_valid()
    # tampered public key fails
    bad = ElGamalKeypair.generate(tgroup)
    from electionguard_tpu.crypto.schnorr import SchnorrProof
    assert not SchnorrProof(bad.public_key, proof.challenge,
                            proof.response).is_valid()


def test_generic_cp(tgroup):
    g = tgroup
    s, u = g.rand_q(), g.rand_q()
    g1 = g.G_MOD_P
    g2 = g.g_pow_p(g.int_to_q(12345))
    ctx = g.int_to_q(777)
    proof = make_generic_cp_proof(g, s, g1, g2, u, ctx)
    x, y = g.pow_p(g1, s), g.pow_p(g2, s)
    assert proof.is_valid(g1, x, g2, y, ctx)
    assert not proof.is_valid(g1, x, g2, g.mult_p(y, g.G_MOD_P), ctx)
    assert not proof.is_valid(g1, x, g2, y, g.int_to_q(778))


@pytest.mark.parametrize("vote", [0, 1])
def test_disjunctive_cp(tgroup, vote):
    g = tgroup
    kp = ElGamalKeypair.generate(g)
    nonce, ctx = g.rand_q(), g.int_to_q(99)
    ct = elgamal_encrypt(g, vote, nonce, kp.public_key)
    proof = make_disjunctive_cp_proof(g, ct, nonce, kp.public_key, ctx, vote,
                                      g.rand_q())
    assert proof.is_valid(ct, kp.public_key, ctx)
    # wrong context fails
    assert not proof.is_valid(ct, kp.public_key, g.int_to_q(100))


def test_disjunctive_cp_rejects_two(tgroup):
    """A vote of 2 cannot be proven in {0,1}; generation refuses, and a
    0-proof on an encryption of 2 must not verify."""
    g = tgroup
    kp = ElGamalKeypair.generate(g)
    nonce, ctx = g.rand_q(), g.int_to_q(99)
    ct2 = elgamal_encrypt(g, 2, nonce, kp.public_key)
    with pytest.raises(ValueError):
        make_disjunctive_cp_proof(g, ct2, nonce, kp.public_key, ctx, 2,
                                  g.rand_q())
    forged = make_disjunctive_cp_proof(g, ct2, nonce, kp.public_key, ctx, 1,
                                       g.rand_q())
    assert not forged.is_valid(ct2, kp.public_key, ctx)


def test_constant_cp(tgroup):
    g = tgroup
    kp = ElGamalKeypair.generate(g)
    ctx = g.int_to_q(55)
    nonces = [g.rand_q() for _ in range(3)]
    cts = [elgamal_encrypt(g, v, n, kp.public_key)
           for v, n in zip((1, 1, 0), nonces)]
    acc = elgamal_accumulate(cts)
    agg_nonce = g.add_q(*nonces)
    proof = make_constant_cp_proof(g, acc, agg_nonce, kp.public_key, ctx, 2,
                                   g.rand_q())
    assert proof.is_valid(acc, kp.public_key, ctx)
    # claiming the wrong constant fails
    bad = ConstantChaumPedersenProof(proof.challenge, proof.response, 3)
    assert not bad.is_valid(acc, kp.public_key, ctx)


def test_hashed_elgamal_roundtrip(tgroup):
    g = tgroup
    kp = ElGamalKeypair.generate(g)
    data = b"the quick brown fox jumps over 32+ byte payloads" * 3
    ct = hashed_elgamal_encrypt(g, data, g.rand_q(), kp.public_key, b"ctx")
    assert ct.decrypt(kp.secret_key, b"ctx") == data
    # wrong context -> MAC failure -> None
    assert ct.decrypt(kp.secret_key, b"other") is None
    # wrong key -> None
    assert ct.decrypt(ElGamalKeypair.generate(g).secret_key, b"ctx") is None


@pytest.mark.slow
def test_production_group_smoke(pgroup):
    """End-to-end primitive check at 4096-bit production size."""
    g = pgroup
    kp = ElGamalKeypair.generate(g)
    nonce, ctx = g.rand_q(), g.int_to_q(7)
    ct = elgamal_encrypt(g, 1, nonce, kp.public_key)
    assert ct.decrypt(kp.secret_key, DLog(g, max_exponent=10)) == 1
    proof = make_disjunctive_cp_proof(g, ct, nonce, kp.public_key, ctx, 1,
                                      g.rand_q())
    assert proof.is_valid(ct, kp.public_key, ctx)
    sp = make_schnorr_proof(g, kp.secret_key, kp.public_key, g.rand_q())
    assert sp.is_valid()

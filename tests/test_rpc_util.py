"""Stub.call retry-machinery edge cases, exercised directly (they were
previously covered only implicitly through the e2e suites): full-jitter
exponential backoff, deadline exhaustion mid-backoff, the bounded
connect-window DEADLINE reclassification, the retry budget, and the
EGTPU_RPC_RETRIES=1 reference posture.

The dial-a-dead-port cases run inside the deterministic simulator:
wait_for_ready connect windows and retry pacing elapse in VIRTUAL time,
so the suite spends no real seconds blocking on sockets that will never
answer — and the elapsed-time assertions are exact, not flake-prone
wall-clock bounds.
"""

import grpc
import pytest

from electionguard_tpu.publish import pb
from electionguard_tpu.remote import rpc_util
from electionguard_tpu.sim import simulation


def _dead_stub():
    """A stub dialing a port nothing listens on (fails UNAVAILABLE)."""
    port = rpc_util.find_free_port()
    channel = rpc_util.make_channel(f"localhost:{port}",
                                    rpc_util.MAX_REGISTRATION_MESSAGE)
    return rpc_util.Stub(channel, "RemoteKeyCeremonyService"), channel


def _req():
    return pb.msg("RegisterKeyCeremonyTrusteeRequest")(
        guardian_id="x", remote_url="localhost:1")


@pytest.fixture()
def sleeps(monkeypatch):
    """Record backoff sleeps instead of sleeping; pin jitter to its
    upper bound so waits are deterministic."""
    rec = {"sleeps": [], "uniform": []}

    def fake_sleep(s):
        rec["sleeps"].append(round(s, 6))

    def fake_uniform(lo, hi):
        rec["uniform"].append((lo, round(hi, 6)))
        return hi

    monkeypatch.setattr(rpc_util, "_sleep", fake_sleep)
    monkeypatch.setattr(rpc_util, "_uniform", fake_uniform)
    return rec


def _call_dead(pol=None, timeout=30.0):
    """One Stub.call against a dead peer inside a fresh simulation;
    returns the virtual seconds the call consumed."""
    with simulation() as sim:
        box = {}

        def body():
            stub, channel = _dead_stub()
            t0 = sim.now
            try:
                with pytest.raises(grpc.RpcError):
                    stub.call("registerTrustee", _req(), timeout=timeout,
                              policy=pol)
            finally:
                channel.close()
            box["virtual_s"] = sim.now - t0

        sim.run(body)
        return box["virtual_s"]


def test_full_jitter_exponential_backoff(sleeps):
    """Waits double from base to cap, drawn from U(0, bound) — not the
    old synchronized-herd linear ladder."""
    pol = rpc_util.RetryPolicy(attempts=4, base_wait=0.1, max_wait=0.3,
                               connect_window=0.05, budget=100.0)
    _call_dead(pol)
    # 4 attempts -> 3 backoffs; bounds 0.1, 0.2, then capped at 0.3
    assert sleeps["sleeps"] == [0.1, 0.2, 0.3]
    # every draw was full-jitter: U(0, bound)
    assert [u for u in sleeps["uniform"]] == [(0.0, 0.1), (0.0, 0.2),
                                              (0.0, 0.3)]


def test_deadline_exhaustion_mid_backoff(sleeps):
    """A backoff wait that would overshoot the caller's total deadline is
    not slept: the call raises immediately with the real error."""
    pol = rpc_util.RetryPolicy(attempts=10, base_wait=5.0, max_wait=60.0,
                               connect_window=0.05, budget=1000.0)
    virtual_s = _call_dead(pol, timeout=1.5)
    assert sleeps["sleeps"] == []          # never slept into the deadline
    assert virtual_s < 1.4                 # and never blocked out to it


def test_retry_budget_bounds_total_backoff(sleeps):
    """Once the Stub's cumulative backoff reaches the budget, the next
    transient failure is raised instead of retried."""
    pol = rpc_util.RetryPolicy(attempts=10, base_wait=0.1, max_wait=10.0,
                               connect_window=0.05, budget=0.15)
    _call_dead(pol)
    # first backoff (0.1) fits the 0.15 budget; the second (0.2) does not
    assert sleeps["sleeps"] == [0.1]


def test_connect_window_deadline_is_transient():
    """DEADLINE_EXCEEDED expiring a BOUNDED wait_for_ready window means
    "peer still unreachable" (transient); expiring the caller's own full
    budget means a real timeout (fatal)."""
    D = grpc.StatusCode.DEADLINE_EXCEEDED
    assert rpc_util._is_transient(grpc.StatusCode.UNAVAILABLE,
                                  wfr=False, per_try=5, remaining=60)
    assert rpc_util._is_transient(D, wfr=True, per_try=5, remaining=60)
    assert not rpc_util._is_transient(D, wfr=True, per_try=60,
                                      remaining=60)  # full-budget wait
    assert not rpc_util._is_transient(D, wfr=False, per_try=60,
                                      remaining=60)  # first attempt


def test_connect_window_bounds_each_retry(sleeps):
    """wait_for_ready retries block at most connect_window each, so a
    permanently-dead peer exhausts attempts in seconds — well inside a
    long caller deadline."""
    pol = rpc_util.RetryPolicy(attempts=3, base_wait=0.01, max_wait=0.01,
                               connect_window=0.3, budget=100.0)
    virtual_s = _call_dead(pol, timeout=60)
    # 2 bounded wfr waits (~0.3 virtual s each) + fail-fast first
    # attempt: the 60 s deadline was never consumed
    assert virtual_s < 5.0
    assert len(sleeps["sleeps"]) == 2


def test_retries_1_restores_reference_posture(sleeps, monkeypatch):
    """EGTPU_RPC_RETRIES=1 = the reference's no-retry behavior: one
    attempt, no backoff, immediate failure."""
    monkeypatch.setenv("EGTPU_RPC_RETRIES", "1")
    assert rpc_util.retry_policy().attempts == 1
    virtual_s = _call_dead(timeout=20)
    assert sleeps["sleeps"] == []
    assert virtual_s < 2.0


def test_deadline_classes_env_tunable(monkeypatch):
    """Registration/control rpcs default short, data plane long; every
    class is an env knob."""
    assert rpc_util.deadline_for("registerTrustee") == 30.0
    assert rpc_util.deadline_for("finish") == 30.0
    assert rpc_util.deadline_for("sendPublicKeys") == 120.0
    assert rpc_util.deadline_for("directDecrypt") == 600.0
    assert rpc_util.deadline_for("encryptBallotBatch") == 600.0
    monkeypatch.setenv("EGTPU_RPC_TIMEOUT_DATA", "42.5")
    assert rpc_util.deadline_for("directDecrypt") == 42.5


def test_env_policy_parsing(monkeypatch):
    monkeypatch.setenv("EGTPU_RPC_RETRIES", "7")
    monkeypatch.setenv("EGTPU_RPC_RETRY_WAIT", "0.25")
    monkeypatch.setenv("EGTPU_RPC_RETRY_CAP", "4")
    monkeypatch.setenv("EGTPU_RPC_CONNECT_WINDOW", "2")
    monkeypatch.setenv("EGTPU_RPC_RETRY_BUDGET", "33")
    pol = rpc_util.retry_policy()
    assert (pol.attempts, pol.base_wait, pol.max_wait,
            pol.connect_window, pol.budget) == (7, 0.25, 4.0, 2.0, 33.0)
    # malformed values degrade to defaults instead of crashing a trustee
    monkeypatch.setenv("EGTPU_RPC_RETRIES", "not-a-number")
    assert rpc_util.retry_policy().attempts == 3

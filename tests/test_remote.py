"""Remote-plane tests: real gRPC servers/clients on localhost.

Exercises the reference's full network topology (SURVEY.md §3.1/§3.2/§3.5)
— reverse-connection registration, coordinator-assigned x-coordinates,
pairwise exchange over proxies, batch decryption rpcs — with every node in
this process (threads) instead of subprocesses; the subprocess version lives
in the workflow E2E harness.
"""

import threading

import pytest

from electionguard_tpu.ballot.tally import (EncryptedTally,
                                            EncryptedTallyContest,
                                            EncryptedTallySelection)
from electionguard_tpu.core.dlog import DLog
from electionguard_tpu.crypto.elgamal import elgamal_encrypt
from electionguard_tpu.decrypt.decryption import Decryption
from electionguard_tpu.decrypt.trustee import read_trustee
from electionguard_tpu.keyceremony.interface import Result
from electionguard_tpu.publish.election_record import ElectionConfig
from electionguard_tpu.remote.decrypting_remote import (
    DecryptingTrusteeServer, DecryptionCoordinator)
from electionguard_tpu.remote.keyceremony_remote import (
    KeyCeremonyCoordinator, KeyCeremonyTrusteeServer)
from tests.test_keyceremony import tiny_manifest


@pytest.fixture()
def remote_ceremony(tgroup, tmp_path):
    """3 trustee servers + coordinator over real localhost gRPC."""
    coord = KeyCeremonyCoordinator(tgroup, 3, 2, port=0)
    servers = []
    try:
        for i in range(3):
            servers.append(KeyCeremonyTrusteeServer(
                tgroup, f"guardian-{i}", f"localhost:{coord.port}",
                out_dir=str(tmp_path)))
        assert coord.wait_for_registrations(timeout=10)
        results = coord.run_key_ceremony(str(tmp_path))
        assert not isinstance(results, Result), results
        yield dict(coord=coord, servers=servers, results=results,
                   tmp=tmp_path)
    finally:
        coord.shutdown(all_ok=True)
        for s in servers:
            s.shutdown()


def test_remote_key_ceremony(remote_ceremony, tgroup):
    results = remote_ceremony["results"]
    servers = remote_ceremony["servers"]
    # coordinator assigned sequential x coordinates
    assert sorted(s.x_coordinate for s in servers) == [1, 2, 3]
    # joint key matches the product of local trustee keys
    joint = tgroup.mult_p(*(s.trustee.election_public_key for s in servers))
    assert results.joint_public_key == joint
    # every trustee holds verified shares from the other two
    for s in servers:
        assert len(s.trustee.received_shares) == 2
    # trustee files were saved remotely
    for i in range(3):
        assert (remote_ceremony["tmp"] / f"trustee-guardian-{i}.json").exists()


def test_duplicate_registration_rejected(remote_ceremony, tgroup):
    # a DIFFERENT server claiming an existing guardian id (its own fresh
    # port, so not an idempotent same-(id,url) replay) must be rejected
    coord = remote_ceremony["coord"]
    with pytest.raises(RuntimeError, match="duplicate guardian id"):
        KeyCeremonyTrusteeServer(tgroup, "guardian-0",
                                 f"localhost:{coord.port}")


def test_remote_decryption_with_missing_guardian(remote_ceremony, tgroup):
    g = tgroup
    results = remote_ceremony["results"]
    tmp = remote_ceremony["tmp"]
    init = results.make_election_initialized(
        ElectionConfig(tiny_manifest(), 3, 2))

    # encrypt a small tally under the joint key
    K = init.joint_public_key
    votes = [5, 2]
    cts = []
    for v in votes:
        acc = None
        for j in range(5):
            ct = elgamal_encrypt(g, 1 if j < v else 0, g.rand_q(), K)
            acc = ct if acc is None else acc.mult(ct)
        cts.append(acc)
    tally = EncryptedTally("t", (EncryptedTallyContest(
        "contest-0", 0, tuple(
            EncryptedTallySelection(f"sel-{i}", i, ct)
            for i, ct in enumerate(cts))),), cast_ballot_count=5)

    # guardian-1 is missing; 0 and 2 serve over gRPC
    coord = DecryptionCoordinator(g, navailable=2, port=0)
    servers = []
    try:
        for i in (0, 2):
            trustee = read_trustee(g, str(tmp / f"trustee-guardian-{i}.json"))
            servers.append(DecryptingTrusteeServer(
                g, trustee, f"localhost:{coord.port}"))
        assert coord.wait_for_registrations(timeout=10)
        coord.mark_started()
        d = Decryption(g, init, coord.proxies, ["guardian-1"],
                       DLog(g, max_exponent=10))
        out = d.decrypt(tally)
        got = [s.tally for s in out.contests[0].selections]
        assert got == votes
        # missing guardian share was reconstructed over the wire
        for s in out.contests[0].selections:
            ids = {sh.guardian_id for sh in s.shares}
            assert "guardian-1" in ids
    finally:
        coord.shutdown(all_ok=True)
        for s in servers:
            s.shutdown()


def test_finish_releases_trustee(tgroup, tmp_path):
    coord = KeyCeremonyCoordinator(tgroup, 1, 1, port=0)
    server = KeyCeremonyTrusteeServer(
        tgroup, "solo", f"localhost:{coord.port}", out_dir=str(tmp_path))
    assert coord.wait_for_registrations(timeout=10)
    results = coord.run_key_ceremony(str(tmp_path))
    assert not isinstance(results, Result)

    waiter = {}
    th = threading.Thread(
        target=lambda: waiter.setdefault(
            "ok", server.wait_until_finished(timeout=15)))
    th.start()
    coord.shutdown(all_ok=True)
    th.join(timeout=20)
    assert waiter.get("ok") is True


def test_first_rpc_waits_for_slow_trustee_construction(tgroup, monkeypatch,
                                                       tmp_path):
    """The coordinator's first sendPublicKeys can land before the trustee
    finishes building its KeyCeremonyTrustee delegate (registration
    response -> slow production-group polynomial build).  The rpc must
    block on the readiness gate instead of dying on a None delegate —
    the race the first production-group workflow run exposed."""
    import time

    import electionguard_tpu.remote.keyceremony_remote as kr

    real_ctor = kr.KeyCeremonyTrustee

    def slow_ctor(*args, **kwargs):
        time.sleep(1.5)
        return real_ctor(*args, **kwargs)

    monkeypatch.setattr(kr, "KeyCeremonyTrustee", slow_ctor)
    coord = KeyCeremonyCoordinator(tgroup, 1, 1, port=0)
    server_box = {}

    def build():
        server_box["s"] = KeyCeremonyTrusteeServer(
            tgroup, "slow-guardian", f"localhost:{coord.port}",
            out_dir=str(tmp_path))

    t = threading.Thread(target=build)
    t.start()
    try:
        # fire the first rpc the moment registration lands, mid-sleep
        assert coord.wait_for_registrations(timeout=10)
        keys = coord.proxies[0].send_public_keys()
        assert not isinstance(keys, Result), keys
        assert keys.guardian_id == "slow-guardian"
    finally:
        t.join(timeout=10)
        coord.shutdown(all_ok=True)
        if "s" in server_box:
            server_box["s"].shutdown()


def test_rpc_retries_transient_unavailable(tgroup):
    """The rpc plane retries UNAVAILABLE (peer not up yet) with backoff —
    beyond the reference's no-retry posture (SURVEY.md §5.3): a
    coordinator that comes up between attempts is reached on retry."""
    import time

    import grpc

    from electionguard_tpu.publish import pb
    from electionguard_tpu.remote import rpc_util

    port = rpc_util.find_free_port()
    channel = rpc_util.make_channel(f"localhost:{port}",
                                    rpc_util.MAX_REGISTRATION_MESSAGE)
    stub = rpc_util.Stub(channel, "RemoteKeyCeremonyService")
    req = pb.msg("RegisterKeyCeremonyTrusteeRequest")(
        guardian_id="late", remote_url="localhost:1")

    # nothing listening: attempts exhaust within the TOTAL deadline
    t0 = time.time()
    with pytest.raises(grpc.RpcError):
        stub.call("registerTrustee", req, timeout=4)
    elapsed = time.time() - t0
    assert 0.5 <= elapsed <= 10  # backoff happened; total deadline held

    # coordinator appears mid-retry: the SAME call now succeeds (the
    # wait_for_ready retry re-dials instead of failing fast)
    box = {}
    timer = threading.Timer(
        0.7, lambda: box.update(
            c=KeyCeremonyCoordinator(tgroup, 1, 1, port=port)))
    timer.start()
    try:
        resp = stub.call("registerTrustee", req, timeout=8)
        assert resp.x_coordinate == 1 and not resp.error
        # a retried registration whose response was lost is idempotent:
        # same (id, url) re-registration returns the SAME coordinate
        again = stub.call("registerTrustee", req, timeout=8)
        assert again.x_coordinate == 1 and not again.error
        # ... but a different trustee claiming the same id is rejected
        imposter = pb.msg("RegisterKeyCeremonyTrusteeRequest")(
            guardian_id="late", remote_url="localhost:2")
        rej = stub.call("registerTrustee", imposter, timeout=8)
        assert "duplicate guardian id" in rej.error
        # ... and so is a RELAUNCHED process (same id+url, new nonce —
        # it holds a fresh secret polynomial, not the registered one)
        relaunch = pb.msg("RegisterKeyCeremonyTrusteeRequest")(
            guardian_id="late", remote_url="localhost:1",
            registration_nonce=b"fresh-process")
        rej2 = stub.call("registerTrustee", relaunch, timeout=8)
        assert "duplicate guardian id" in rej2.error
        # the lost response of the LAST registration races the ceremony
        # start: the idempotent replay must be honored even after start
        with box["c"]._lock:
            box["c"]._started_ceremony = True
        late_replay = stub.call("registerTrustee", req, timeout=8)
        assert late_replay.x_coordinate == 1 and not late_replay.error
        fresh = pb.msg("RegisterKeyCeremonyTrusteeRequest")(
            guardian_id="too-late", remote_url="localhost:3")
        closed = stub.call("registerTrustee", fresh, timeout=8)
        assert "already started" in closed.error
    finally:
        timer.join()
        if "c" in box:
            box["c"].shutdown(all_ok=True)
        channel.close()

"""Flag-flip multi-chip path: fused encrypt/verify sharded over the
8-device virtual CPU mesh must be BIT-IDENTICAL to the single-device
programs (VERDICT r4 item 7 — the sharded plane must back a real
workload, not just dry-run).

The fused programs are elementwise over rows, so dp sharding adds zero
collectives; what these tests pin is that the shard_map wrapping, the
dp padding, and the bucket policy compose without changing a single
limb.  Scaling device being replaced: the reference's 11-thread pool
(src/test/java/electionguard/workflow/RunRemoteWorkflowTest.java:140,180).
"""

import numpy as np
import pytest

from electionguard_tpu.encrypt.encryptor import BatchEncryptor
from electionguard_tpu.parallel.mesh import election_mesh
from electionguard_tpu.publish.election_record import ElectionRecord
from electionguard_tpu.verify.verifier import Verifier

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def mesh():
    return election_mesh()  # all 8 virtual CPU devices, dp=8


def test_sharded_encryption_bit_identical(pelection, mesh):
    g, init = pelection["group"], pelection["init"]
    enc = BatchEncryptor(init, g, mesh=mesh)
    sharded, invalid = enc.encrypt_ballots(pelection["ballots"],
                                           seed=g.int_to_q(11))
    assert not invalid
    for a, b in zip(pelection["encrypted"], sharded):
        for ca, cb in zip(a.contests, b.contests):
            assert ca.proof == cb.proof
            for sa, sb in zip(ca.selections, cb.selections):
                assert sa.ciphertext == sb.ciphertext
                assert sa.proof == sb.proof


def test_sharded_verify_agrees(pelection, mesh):
    record = ElectionRecord(
        election_init=pelection["init"],
        encrypted_ballots=list(pelection["encrypted"]),
        tally_result=pelection["tally_result"],
        decryption_result=pelection["decryption_result"])
    plain = Verifier(record, pelection["group"]).verify()
    sharded = Verifier(record, pelection["group"], mesh=mesh).verify()
    assert sharded.ok and plain.ok
    assert sharded.checks == plain.checks


def test_sharded_verify_rejects_tamper(pelection, mesh):
    import dataclasses
    record = ElectionRecord(
        election_init=pelection["init"],
        encrypted_ballots=list(pelection["encrypted"]),
        tally_result=pelection["tally_result"],
        decryption_result=pelection["decryption_result"])
    b = record.encrypted_ballots[0]
    c = b.contests[0]
    s0, s1 = c.selections[0], c.selections[1]
    record.encrypted_ballots[0] = dataclasses.replace(
        b, contests=(dataclasses.replace(c, selections=(
            dataclasses.replace(s0, ciphertext=s1.ciphertext),
            dataclasses.replace(s1, ciphertext=s0.ciphertext),
            c.selections[2])),))
    res = Verifier(record, pelection["group"], mesh=mesh).verify()
    assert not res.checks["V4.selection_proofs"]

"""Differential tests for the MXU NTT Montgomery engine (core/ntt_mxu.py)
against the VPU CIOS kernel (core/bignum_jax.py) and Python ints.

Runs on the CPU backend (int8 dot_general is exact there too); batches are
kept tiny because CPU matmul throughput is the bottleneck, and full-width
exponent ladders use reduced exp_bits.  The Barrett constants are
re-validated exhaustively over their full input domains.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from electionguard_tpu.core import bignum_jax as bn
from electionguard_tpu.core import ntt_mxu as nt
from electionguard_tpu.core.group import production_group
from electionguard_tpu.core.group_jax import JaxGroupOps


@pytest.fixture(scope="module")
def nctx(pgroup):
    return nt.make_ntt_ctx(pgroup.p)


def _rand_elems(g, k, seed=0):
    rng = np.random.default_rng(seed)
    out = [pow(g.g, int.from_bytes(rng.bytes(32), "big") % g.q, g.p)
           for _ in range(k - 4)]
    R = 1 << 4096
    return out + [0, 1, g.p - 1, (R - 1) % g.p]


def test_barrett_constants_exhaustive():
    """Re-derive the hardcoded Barrett deficits over the full domains."""
    for m in nt.PRIMES:
        for (a, xbits, nsub) in [(13, 26, 2), (14, 28, 3)]:
            mu = (1 << (a + 13)) // m
            worst = 0
            for lo in range(0, 1 << xbits, 1 << 24):
                x = np.arange(lo, min(lo + (1 << 24), 1 << xbits),
                              dtype=np.uint64)
                q = ((x >> a) * mu) >> 13
                r = x - q * m
                worst = max(worst, int(r.max() // m))
            assert worst <= nsub, (m, a, worst)


def test_ntt_roots():
    for m in nt.PRIMES:
        w = nt.OMEGA[m]
        assert pow(w, 1024, m) == 1 and pow(w, 512, m) != 1
        assert (m - 1) % 1024 == 0
    m1, m2 = nt.PRIMES
    assert m1 * m2 > 512 * 255 * 255  # CRT range covers conv coefficients


def test_montmul_matches_cios_and_ints(pgroup, nctx):
    g = pgroup
    xs = _rand_elems(g, 8, seed=1)
    ys = _rand_elems(g, 8, seed=2)
    A = jnp.asarray(bn.ints_to_limbs(xs, nt.NL))
    B = jnp.asarray(bn.ints_to_limbs(ys, nt.NL))
    got = np.asarray(nt.montmul(nctx, A, B))
    ref = np.asarray(bn.montmul(nctx.mctx, A, B))
    np.testing.assert_array_equal(got, ref)
    Rinv = pow(1 << 4096, -1, g.p)
    want = [x * y * Rinv % g.p for x, y in zip(xs, ys)]
    assert bn.limbs_to_ints(got) == want


def test_montsqr_matches(pgroup, nctx):
    g = pgroup
    xs = _rand_elems(g, 8, seed=3)
    A = jnp.asarray(bn.ints_to_limbs(xs, nt.NL))
    got = bn.limbs_to_ints(np.asarray(nt.montsqr(nctx, A)))
    Rinv = pow(1 << 4096, -1, g.p)
    assert got == [x * x * Rinv % g.p for x in xs]


def test_montmul_broadcast_constant(pgroup, nctx):
    g = pgroup
    xs = _rand_elems(g, 6, seed=4)
    A = jnp.asarray(bn.ints_to_limbs(xs, nt.NL))
    got = bn.limbs_to_ints(np.asarray(nt.montmul(nctx, A, nctx.mctx.r2_mod_p)))
    R = 1 << 4096
    assert got == [x * R % g.p for x in xs]  # to_mont


def test_mont_pow_small_exponents(pgroup, nctx):
    """Full ladder logic with reduced exp_bits (CPU-affordable)."""
    g = pgroup
    rng = np.random.default_rng(5)
    xs = _rand_elems(g, 6, seed=6)
    es = [int(rng.integers(0, 1 << 32)) for _ in range(6)]
    A = jnp.asarray(bn.ints_to_limbs(xs, nt.NL))
    E = jnp.asarray(bn.ints_to_limbs(es, 2))
    got = bn.limbs_to_ints(np.asarray(nt.powmod(nctx, A, E, 32)))
    assert got == [pow(x, e, g.p) for x, e in zip(xs, es)]


def test_group_ops_ntt_backend_mulmod_prod(pgroup):
    ops = JaxGroupOps(pgroup, backend="ntt")
    assert ops.backend == "ntt"
    g = pgroup
    xs = _rand_elems(g, 6, seed=7)
    ys = _rand_elems(g, 6, seed=8)
    got = ops.mulmod_ints(xs, ys)
    assert got == [x * y % g.p for x, y in zip(xs, ys)]
    rows = [xs, ys]
    got = ops.prod_ints(rows)
    assert got == [x * y % g.p for x, y in zip(xs, ys)]


def test_group_ops_ntt_fixed_pow(pgroup):
    ops = JaxGroupOps(pgroup, backend="ntt")
    rng = np.random.default_rng(9)
    es = [int.from_bytes(rng.bytes(32), "big") % pgroup.q for _ in range(3)]
    got = ops.g_pow_ints(es)
    assert got == [pow(pgroup.g, e, pgroup.p) for e in es]


def test_noncanonical_input_canonicalized(pgroup, nctx):
    """Operands >= p (any 4096-bit pattern) are safe: the first montmul in
    a chain reduces them mod p (matches the CIOS kernel's behavior)."""
    g = pgroup
    R = 1 << 4096
    xs = [g.p, g.p + 12345, R - 1]
    A = jnp.asarray(bn.ints_to_limbs(xs, nt.NL))
    got = bn.limbs_to_ints(
        np.asarray(nt.montmul(nctx, A, nctx.mctx.r2_mod_p)))
    assert got == [x * R % g.p for x in xs]


def test_montmul_shared_matches_montmul(pgroup, nctx):
    """The shared-base bucket multiply (one forward NTT for the base,
    evaluations broadcast across k) must equal k independent montmuls."""
    g = pgroup
    elems = _rand_elems(g, 8, seed=3)
    sel = jnp.asarray(bn.ints_to_limbs(elems[:6], nt.NL)).reshape(2, 3,
                                                                  nt.NL)
    base = jnp.asarray(bn.ints_to_limbs(elems[6:], nt.NL))
    got = np.asarray(nt.montmul_shared(nctx, sel, base))
    for b in range(2):
        for j in range(3):
            want = np.asarray(nt.montmul(nctx, sel[b, j][None],
                                         base[b][None]))[0]
            np.testing.assert_array_equal(got[b, j], want)


def test_multi_powmod_shared_ntt_backend(pgroup):
    """multi_powmod_shared through the NTT backend (with the shared-base
    NTT hook) must match host pow; reduced exp width keeps CPU time sane."""
    g = pgroup
    ops = JaxGroupOps(g, backend="ntt")
    rng = np.random.default_rng(5)
    bases = [pow(g.g, int.from_bytes(rng.bytes(32), "big") % g.q, g.p)
             for _ in range(2)]
    exps = [[int.from_bytes(rng.bytes(2), "big") for _ in range(3)]
            for _ in range(2)]
    B = jnp.asarray(ops.to_limbs_p(bases))
    E = jnp.asarray(np.stack([ops.to_limbs_q(row) for row in exps]))
    got = bn.multi_powmod_shared(ops.ctx, B, E, 16, montmul_fn=ops._mm,
                                 montsqr_fn=ops._ms,
                                 montmul_shared_fn=ops._mm_shared)
    got_ints = np.asarray(got).reshape(6, ops.n)
    for i, want in enumerate(pow(b, e, g.p) for b, row in zip(bases, exps)
                             for e in row):
        assert bn.limbs_to_int(got_ints[i]) == want

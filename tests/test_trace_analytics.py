"""Trace analytics over synthetic span forests (tier-1, no subprocesses).

Covers the analyzer's contracts on hand-built trace dirs: the critical
path sums EXACTLY to the root wall-clock, attribution lands in the
right phase x process x category buckets, anti-patterns (stragglers,
mid-run recompiles, queue saturation) fire, and every damage mode a
SIGKILL'd fleet can produce — truncated JSONL tails, orphaned spans,
open roots, clock-skewed processes — degrades to a partial report with
warnings, never a crash.  The flow-event emission of obs/assemble and
both CLI gates (egreport, bench_diff) are smoked here too; the
subprocess e2e tests exercise the same paths on real runs.
"""

import importlib.util
import json
import os

import pytest

from electionguard_tpu.obs import analyze, assemble, flight


def _tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(os.path.dirname(__file__), os.pardir,
                           "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _span(sid, name, ts, dur, parent="", proc="workflow-driver", pid=1,
          **attrs):
    rec = {"trace_id": "t1", "span_id": sid, "parent_id": parent,
           "name": name, "ts": ts, "dur": dur, "pid": pid, "tid": 0,
           "proc": proc}
    if attrs:
        rec["attrs"] = attrs
    return rec


def _write(trace_dir, spans):
    os.makedirs(trace_dir, exist_ok=True)
    by_file = {}
    for s in spans:
        by_file.setdefault((s["proc"], s["pid"]), []).append(s)
    for (proc, pid), recs in by_file.items():
        with open(os.path.join(trace_dir,
                               f"spans-{proc}-{pid}.jsonl"), "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")


def _workflow_spans():
    """A miniature 2-process run: driver root -> phases -> rpc pair ->
    worker batch, with idle self-time gaps at every level."""
    return [
        _span("root", "process", 0, 100),
        _span("ph-e", "phase.encrypt", 10, 60, parent="root"),
        _span("rc", "rpc.client.encrypt", 20, 30, parent="ph-e"),
        _span("rs", "rpc.server.encrypt", 22, 20, parent="rc",
              proc="worker", pid=2),
        _span("wb", "worker.batch", 24, 10, parent="rs",
              proc="worker", pid=2),
        _span("wroot", "process", 0, 100, proc="worker", pid=2),
        _span("ph-t", "phase.tally", 75, 20, parent="root"),
    ]


# ---------------------------------------------------------------------------
# critical path + attribution
# ---------------------------------------------------------------------------

def test_critical_path_sums_exactly_to_wall(tmp_path):
    d = str(tmp_path / "trace")
    _write(d, _workflow_spans())
    a = analyze.analyze(d)
    assert a.root["span_id"] == "root"   # driver preferred over worker
    assert a.wall_us == 100
    assert a.path_total_us == 100        # exact, by construction
    assert a.coverage == 1.0
    # the path descends through the rpc pair into the worker batch
    names = [r["name"] for r in a.path]
    assert "worker.batch" in names and "rpc.server.encrypt" in names
    # hop [24,34) is the worker batch, full 10us of it
    wb = [r for r in a.path if r["name"] == "worker.batch"]
    assert [(r["t0"], r["dur_us"]) for r in wb] == [(24, 10)]


def test_category_and_bucket_attribution(tmp_path):
    assert analyze.category_of("device.compile") == "recompile"
    assert analyze.category_of("worker.batch") == "device"
    assert analyze.category_of("rpc.client.encrypt") == "rpc"
    assert analyze.category_of("record.publish") == "serialization"
    assert analyze.category_of("router.queue") == "queue-wait"
    assert analyze.category_of("keyceremony.exchange") == "host"

    d = str(tmp_path / "trace")
    _write(d, _workflow_spans())
    a = analyze.analyze(d)
    # worker batch self time lands in its cross-process phase ancestor
    assert a.buckets[("phase.encrypt", "worker", "device")] == 10
    # rpc server self time = 20 - 10 (child batch)
    assert a.buckets[("phase.encrypt", "worker", "rpc")] == 10
    # every span's self time is accounted once: the driver's tree sums
    # to its root dur, and the worker's root — whose rpc.server span
    # parents CROSS-process into the client span, not into it — idles
    # its full 100us as host self time
    total = sum(a.buckets.values())
    assert total == 100 + 100


def test_top_self_time_and_knob(tmp_path, monkeypatch):
    d = str(tmp_path / "trace")
    _write(d, _workflow_spans())
    monkeypatch.setenv("EGTPU_FLIGHT_TOP_N", "3")
    a = analyze.analyze(d)
    assert len(a.top_self) == 3
    # the worker root is pure idle (its rpc.server span nests under the
    # driver-side client span): the biggest self time in the run
    assert a.top_self[0][0]["name"] == "process"
    assert a.top_self[0][0]["proc"] == "worker"
    assert a.top_self[0][1] == 100


# ---------------------------------------------------------------------------
# degradation: damaged traces produce partial reports, never crashes
# ---------------------------------------------------------------------------

def test_truncated_jsonl_tail_degrades_with_warning(tmp_path):
    d = str(tmp_path / "trace")
    _write(d, _workflow_spans())
    with open(os.path.join(d, "spans-worker-2.jsonl"), "a") as f:
        f.write('{"trace_id": "t1", "span_id": "torn", "na')   # SIGKILL
    a = analyze.analyze(d)
    assert any("malformed" in w for w in a.warnings)
    assert a.coverage == 1.0             # the rest still analyzes fully


def test_orphaned_spans_partial_report(tmp_path):
    d = str(tmp_path / "trace")
    spans = _workflow_spans() + [
        _span("lost", "encrypt.batch", 40, 5, parent="never-exported",
              proc="worker", pid=2)]
    _write(d, spans)
    a = analyze.analyze(d)
    assert any("orphaned" in w for w in a.warnings)
    assert a.path                        # critical path still computed


def test_open_root_no_critical_path_but_no_crash(tmp_path):
    d = str(tmp_path / "trace")
    root = _span("root", "process", 0, 0)
    del root["dur"]
    root["open"] = True                  # killed driver: root never closed
    _write(d, [root, _span("ph", "phase.encrypt", 10, 20, parent="root")])
    a = analyze.analyze(d)
    assert a.path == []
    assert any("open" in w for w in a.warnings)
    assert any("critical path unavailable" in w for w in a.warnings)
    report = flight.render(a)
    assert "Critical path unavailable" in report


def test_empty_trace_dir_degrades_with_warning(tmp_path):
    """A dir with no span files at all (a run killed before its first
    export, or a wrong -trace path) analyzes to an empty partial
    report with a warning — and still renders as a flight report."""
    d = str(tmp_path / "empty")
    os.makedirs(d)
    a = analyze.analyze(d)
    assert a.spans == [] and a.path == [] and a.buckets == {}
    assert any("no spans" in w for w in a.warnings)
    report = flight.render(a)
    assert "partial report" in report
    assert "Critical path unavailable" in report


def test_heartbeatless_trace_dir_degrades(tmp_path):
    """Spans but no heartbeats.jsonl (a file-export run that never went
    through a collector): analytics that need heartbeats degrade —
    queue stats empty, SLO verdict says so — without warnings-spam or
    a crash."""
    d = str(tmp_path / "trace")
    _write(d, _workflow_spans())
    assert not os.path.exists(os.path.join(d, "heartbeats.jsonl"))
    a = analyze.analyze(d)
    assert a.queue_max == {}
    assert a.path                        # span analytics fully intact
    report = flight.render(a)
    assert "queue depth: no heartbeat data" in report


def test_clock_skewed_child_is_clipped_not_fatal(tmp_path):
    d = str(tmp_path / "trace")
    spans = [
        _span("root", "process", 0, 100),
        # worker clock runs 30us ahead: child extends past parent end
        _span("late", "rpc.server.encrypt", 90, 25, parent="root",
              proc="worker", pid=2),
    ]
    _write(d, spans)
    a = analyze.analyze(d)
    assert a.path_total_us == a.wall_us == 100   # clipped at the root
    assert sum(us for k, us in a.buckets.items()
               if k[1] == "workflow-driver") == 90


# ---------------------------------------------------------------------------
# anti-patterns
# ---------------------------------------------------------------------------

def _fleet_spans(slow_mean_ms=60, fast_mean_ms=10):
    spans = [_span("root", "process", 0, 1_000_000)]
    for w, mean_ms in (("encryption-worker-0", slow_mean_ms),
                       ("encryption-worker-1", fast_mean_ms),
                       ("encryption-worker-2", fast_mean_ms)):
        pid = 10 + int(w[-1])
        for i in range(3):
            spans.append(_span(
                f"{w}-b{i}", "worker.batch", 1000 + i * 100_000,
                mean_ms * 1000, parent="root", proc=w, pid=pid))
    return spans


def test_straggler_named_and_reported(tmp_path):
    d = str(tmp_path / "trace")
    _write(d, _fleet_spans())
    a = analyze.analyze(d)
    assert [s["proc"] for s in a.stragglers] == ["encryption-worker-0"]
    assert any(p["kind"] == "straggler-shard"
               and p["subject"] == "encryption-worker-0"
               for p in a.antipatterns)
    rpt = flight.render(a)
    assert "### Stragglers" in rpt
    assert "**encryption-worker-0**" in rpt


def test_straggler_ratio_knob(tmp_path, monkeypatch):
    d = str(tmp_path / "trace")
    _write(d, _fleet_spans(slow_mean_ms=60, fast_mean_ms=45))
    assert analyze.analyze(d).stragglers == []     # 1.33x < default 1.5
    monkeypatch.setenv("EGTPU_FLIGHT_STRAGGLER_RATIO", "1.2")
    assert [s["proc"] for s in analyze.analyze(d).stragglers] \
        == ["encryption-worker-0"]


def test_midrun_recompile_flagged_prewarm_is_not(tmp_path):
    d = str(tmp_path / "trace")
    spans = [
        _span("root", "process", 0, 1000),
        # prewarm: compile BEFORE the first device batch — fine
        _span("c0", "device.compile", 10, 50, parent="root"),
        _span("b0", "encrypt.batch", 100, 50, parent="root"),
        # a new shape mid-run: compile AFTER the first batch — flagged
        _span("c1", "device.compile", 300, 50, parent="root"),
    ]
    _write(d, spans)
    a = analyze.analyze(d)
    assert a.recompiles_total == 2
    assert [m["ts"] for m in a.midrun_recompiles] == [300]
    assert any(p["kind"] == "midrun-recompile" for p in a.antipatterns)
    rpt = flight.render(a)
    assert "mid-run recompiles: 1" in rpt
    assert "recompile discipline: **FAIL**" in rpt


def test_queue_saturation_from_heartbeats(tmp_path):
    d = str(tmp_path / "trace")
    _write(d, _workflow_spans())
    with open(os.path.join(d, "heartbeats.jsonl"), "w") as f:
        for depth, proc in ((3, "worker"), (300, "worker"),
                            (1, "workflow-driver")):
            f.write(json.dumps({
                "t_us": 50, "proc": proc, "pid": 2, "status": "SERVING",
                "phase": "serving shard=1 head=ab admitted=4",
                "queue_depth": depth}) + "\n")
        f.write("{torn")                           # tolerant here too
    a = analyze.analyze(d)
    assert a.queue_max["worker"] == 300
    assert any(p["kind"] == "queue-saturation" and p["subject"] == "worker"
               for p in a.antipatterns)
    # the heartbeat's shard id annotates the balance table
    assert [s.shard for s in a.shards] == [1]
    rpt = flight.render(a)
    assert "queue depth: **FAIL**" in rpt


# ---------------------------------------------------------------------------
# assembler flow events (Perfetto arrows)
# ---------------------------------------------------------------------------

def test_chrome_trace_emits_flow_pairs_for_cross_process_links():
    spans = _workflow_spans()
    events = assemble.chrome_trace(spans)["traceEvents"]
    assert len([e for e in events if e["ph"] == "X"]) == len(spans)
    starts = [e for e in events if e["ph"] == "s"]
    finishes = [e for e in events if e["ph"] == "f"]
    # exactly one cross-pid link in the fixture: rpc.client -> rpc.server
    assert [e["id"] for e in starts] == ["rs"]
    assert [e["id"] for e in finishes] == ["rs"]
    s, f = starts[0], finishes[0]
    assert f["bp"] == "e"
    assert s["name"] == f["name"] == "egtpu-link"
    assert s["cat"] == f["cat"] == "egtpu"
    # the start binds inside the PARENT's slice on the parent's track
    assert s["pid"] == 1 and f["pid"] == 2
    assert 20 <= s["ts"] < 50


def test_flow_start_clamped_into_short_parent():
    spans = [
        _span("p", "rpc.client.x", 10, 5),
        _span("c", "rpc.server.x", 40, 5, parent="p", proc="w", pid=2),
    ]
    ev = assemble.chrome_trace(spans)["traceEvents"]
    s = [e for e in ev if e["ph"] == "s"][0]
    assert 10 <= s["ts"] <= 14           # inside [10, 15), not at 40


# ---------------------------------------------------------------------------
# the CLIs: egreport + bench_diff
# ---------------------------------------------------------------------------

def test_egreport_cli(tmp_path, capsys):
    d = str(tmp_path / "trace")
    _write(d, _workflow_spans())
    egreport = _tool("egreport")
    out = str(tmp_path / "FLIGHT_REPORT.md")
    assert egreport.main([d, "-out", out]) == 0
    with open(out) as f:
        rpt = f.read()
    assert "# Flight report" in rpt and "## Critical path" in rpt
    assert "coverage=100.0%" in capsys.readouterr().out
    # an empty dir is the one hard failure
    empty = str(tmp_path / "empty")
    os.makedirs(empty)
    assert egreport.main([empty]) == 1


def _bench(tmp_path, name, **overrides):
    base = {"metric": "ballots_verified_tallied_per_sec_per_chip",
            "value": 2.5, "unit": "ballots/s/chip", "platform": "cpu",
            "nballots": 32, "encrypt_per_s": 10.0, "tally_s": 2.0,
            "verify_s": 12.0,
            "powmod_per_s": {"cios": 1000.0, "ntt": 800.0}}
    base.update(overrides)
    p = str(tmp_path / name)
    with open(p, "w") as f:
        json.dump(base, f)
    return p


def test_bench_diff_same_run_passes(tmp_path):
    bd = _tool("bench_diff")
    base = _bench(tmp_path, "base.json")
    assert bd.main(["--baseline", base, "--run", base]) == 0


def test_bench_diff_regression_fails_improvement_passes(tmp_path):
    bd = _tool("bench_diff")
    base = _bench(tmp_path, "base.json")
    # 20% ballots/s drop: outside the 10% band -> non-zero exit
    slow = _bench(tmp_path, "slow.json", value=2.0)
    assert bd.main(["--baseline", base, "--run", slow]) == 1
    # 20% improvement never fails, in either metric direction
    fast = _bench(tmp_path, "fast.json", value=3.0, verify_s=8.0)
    assert bd.main(["--baseline", base, "--run", fast]) == 0
    # lower-is-better direction: verify_s +30% is a regression
    slow_v = _bench(tmp_path, "slow_v.json", verify_s=16.0)
    assert bd.main(["--baseline", base, "--run", slow_v]) == 1
    # per-backend powmod rates gate too
    slow_p = _bench(tmp_path, "slow_p.json",
                    powmod_per_s={"cios": 700.0, "ntt": 800.0})
    assert bd.main(["--baseline", base, "--run", slow_p]) == 1


def test_bench_diff_tolerance_override_and_verdict_json(tmp_path):
    bd = _tool("bench_diff")
    base = _bench(tmp_path, "base.json")
    slow = _bench(tmp_path, "slow.json", value=2.0)
    verdict_path = str(tmp_path / "verdict.json")
    # widening the band waves the same run through
    assert bd.main(["--baseline", base, "--run", slow,
                    "--tolerance", "value=0.25",
                    "--json", verdict_path]) == 0
    with open(verdict_path) as f:
        v = json.load(f)
    assert v["pass"] is True and v["regressions"] == []
    row = [m for m in v["metrics"] if m["metric"] == "value"][0]
    assert row["tolerance"] == 0.25 and row["status"] == "ok"


def test_bench_diff_seeds_from_baseline_json_shape(tmp_path):
    """A BASELINE.json with nothing published yet falls back to the
    highest BENCH_r*.json beside it (how the repo baseline bootstraps)."""
    bd = _tool("bench_diff")
    baseline = str(tmp_path / "BASELINE.json")
    with open(baseline, "w") as f:
        json.dump({"metric": "...", "north_star": 2083.0,
                   "published": {}}, f)
    with open(str(tmp_path / "BENCH_r03.json"), "w") as f:
        json.dump({"n": 3, "parsed": {"value": 2.5, "platform": "cpu"}}, f)
    with open(str(tmp_path / "BENCH_r05.json"), "w") as f:
        json.dump({"n": 5, "parsed": {"value": 2.6, "platform": "cpu"}}, f)
    metrics, src = bd.load_artifact(baseline)
    assert metrics["value"] == 2.6 and "BENCH_r" in src
    # and a PROGRESS.jsonl trajectory works as either side
    prog = str(tmp_path / "PROGRESS.jsonl")
    with open(prog, "w") as f:
        f.write(json.dumps({"ts": 1, "round": 1}) + "\n")        # driver row
        f.write(json.dumps({"kind": "bench", "platform": "cpu",
                            "ballots_per_s_per_chip": 2.55}) + "\n")
    run = _bench(tmp_path, "run.json", value=2.5)
    assert bd.main(["--baseline", prog, "--run", run]) == 0
    # unusable artifacts are a load error, not a crash
    assert bd.main(["--baseline", str(tmp_path / "nope.json"),
                    "--run", run]) == 2


def test_bench_diff_knob_default(tmp_path, monkeypatch):
    bd = _tool("bench_diff")
    base = _bench(tmp_path, "base.json")
    run = _bench(tmp_path, "run.json")
    monkeypatch.setenv("EGTPU_BENCH_BASELINE", base)
    assert bd.main(["--run", run]) == 0


# ---------------------------------------------------------------------------
# egtop pane
# ---------------------------------------------------------------------------

def test_egtop_critical_path_pane(tmp_path):
    egtop = _tool("egtop")
    d = str(tmp_path / "trace")
    _write(d, _workflow_spans())
    pane = egtop.render_critical_path(d)
    assert "critical path" in pane and "worker.batch" in pane
    # a trace with no closed root degrades to a notice, never a crash
    assert "unavailable" in egtop.render_critical_path(
        str(tmp_path / "missing"))


def test_egtop_capacity_pane(tmp_path):
    egtop = _tool("egtop")
    doc = {"ballots": 1_000_000, "deadline_s": 60.0,
           "model": {"platform": "cpu"},
           "headline": [
               {"backend": "cios", "chips": 9781, "chips_lo": 8192,
                "chips_hi": 11369, "bottleneck": "verify-batch"},
               {"backend": "bad", "chips": None, "chips_lo": None,
                "chips_hi": None, "bottleneck": None}],
           "validation": {"max_err_pct": 14.4, "n_checked": 2,
                          "pass": True}}
    p = str(tmp_path / "CAPACITY.json")
    with open(p, "w") as f:
        json.dump(doc, f)
    pane = egtop.render_capacity(p)
    assert "1,000,000 ballots < 60s" in pane
    assert "9,781" in pane and "verify-batch" in pane
    assert "unreachable" in pane          # no-roofline backend row
    assert "max err 14.4% over 2 config(s) (PASS)" in pane
    # a missing file degrades to a notice, never a crash
    assert "unavailable" in egtop.render_capacity(
        str(tmp_path / "nope.json"))

"""Guardian-side batch plane (VERDICT r3 item 4): the trustee's
direct/compensated decryption must run on the device batch plane on the
production group — and its (challenge, response) proofs must verify with
the scalar-plane ``GenericChaumPedersenProof.is_valid``, pinning the
device Fiat–Shamir byte framing against the host construction."""

from electionguard_tpu.core.group import production_group
from electionguard_tpu.crypto.elgamal import elgamal_encrypt
from electionguard_tpu.decrypt.trustee import DecryptingTrustee
from electionguard_tpu.keyceremony.exchange import key_ceremony_exchange
from electionguard_tpu.keyceremony.trustee import KeyCeremonyTrustee


def _trustees_from_ceremony(g, n, quorum):
    kts = [KeyCeremonyTrustee(g, f"g{i}", i + 1, quorum) for i in range(n)]
    key_ceremony_exchange(kts, g)
    return [DecryptingTrustee.from_state(g, kt.decrypting_trustee_state())
            for kt in kts], kts


def test_direct_decrypt_batch_production():
    g = production_group()
    [dt], [kt] = _trustees_from_ceremony(g, 1, 1)
    K = dt.election_public_key
    qbar = g.rand_q()
    texts = [elgamal_encrypt(g, v, g.rand_q(), K) for v in (0, 1, 1, 0, 1)]
    res = dt.direct_decrypt(texts, qbar)
    assert len(res) == len(texts)
    secret = g.int_to_q(kt.decrypting_trustee_state()["secret_key"])
    for ct, d in zip(texts, res):
        # share really is A^s (checked against the host plane)
        assert d.partial_decryption == g.pow_p(ct.pad, secret)
        # device-hashed proof verifies on the scalar plane
        assert d.proof.is_valid(g.G_MOD_P, K, ct.pad,
                                d.partial_decryption, qbar)


def test_compensated_decrypt_batch_production():
    g = production_group()
    dts, _ = _trustees_from_ceremony(g, 3, 2)
    present, missing = dts[0], dts[2]
    K = present.election_public_key
    qbar = g.rand_q()
    texts = [elgamal_encrypt(g, v, g.rand_q(), K) for v in (1, 0)]
    res = present.compensated_decrypt(missing.id, texts, qbar)
    assert len(res) == len(texts)
    for ct, c in zip(texts, res):
        assert c.proof.is_valid(
            g.G_MOD_P, c.recovered_public_key_share, ct.pad,
            c.partial_decryption, qbar)

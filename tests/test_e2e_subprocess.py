"""Subprocess E2E: the full 5-phase workflow as separate OS processes.

The pytest wrapper around electionguard_tpu.workflow.e2e — the reference's
RunRemoteWorkflowTest equivalent, with a real pass/fail discipline (the
reference's own harness had a literal "LOOK how do we know if it worked?"
comment — SURVEY.md §4; here the verifier exit code is the answer).
"""

import os
import subprocess
import sys

import pytest


def _cpu_env():
    env = {k: v for k, v in os.environ.items()
           if "AXON" not in k and "PALLAS" not in k
           and not k.startswith("TPU")}
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _run_workflow(tmp_path, group: str, nballots: int, timeout: int):
    proc = subprocess.run(
        [sys.executable, "-m", "electionguard_tpu.workflow.e2e",
         "-out", str(tmp_path), "-nballots", str(nballots),
         "-nguardians", "3", "-quorum", "2", "-navailable", "2",
         "-group", group],
        capture_output=True, text=True, timeout=timeout, env=_cpu_env(),
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "WORKFLOW PASS" in proc.stdout + proc.stderr


@pytest.mark.slow
def test_five_phase_workflow(tmp_path):
    _run_workflow(tmp_path, "tiny", nballots=8, timeout=600)


@pytest.mark.slow
def test_five_phase_workflow_production(tmp_path):
    """The reference's full scenario on the REAL group over real gRPC:
    3 guardians, quorum 2, 2 available -> compensated decryption, spoiled
    ballots, full verification (RunRemoteWorkflowTest.java:83-194).
    Promoted from the hand-run WORKFLOW_PRODUCTION.log into CI (VERDICT
    r4 item 6) so the production compensated path can never regress
    green again."""
    _run_workflow(tmp_path, "production", nballots=4, timeout=1500)

"""Subprocess E2E: the full 5-phase workflow as separate OS processes.

The pytest wrapper around electionguard_tpu.workflow.e2e — the reference's
RunRemoteWorkflowTest equivalent, with a real pass/fail discipline (the
reference's own harness had a literal "LOOK how do we know if it worked?"
comment — SURVEY.md §4; here the verifier exit code is the answer).
"""

import os
import subprocess
import sys

import pytest


def _cpu_env():
    env = {k: v for k, v in os.environ.items()
           if "AXON" not in k and "PALLAS" not in k
           and not k.startswith("TPU")}
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _run_workflow(tmp_path, group: str, nballots: int, timeout: int,
                  extra_flags: list = ()):
    proc = subprocess.run(
        [sys.executable, "-m", "electionguard_tpu.workflow.e2e",
         "-out", str(tmp_path), "-nballots", str(nballots),
         "-nguardians", "3", "-quorum", "2", "-navailable", "2",
         "-group", group, *extra_flags],
        capture_output=True, text=True, timeout=timeout, env=_cpu_env(),
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "WORKFLOW PASS" in proc.stdout + proc.stderr
    return proc


# `e2e` rides on `slow`: `-m "slow and not e2e"` / `-m e2e` split the
# slow tier into two parallelizable halves (VERDICT r6 item 7) without
# changing what `-m "not slow"` selects.
pytestmark = [pytest.mark.slow, pytest.mark.e2e]


def test_five_phase_workflow(tmp_path):
    _run_workflow(tmp_path, "tiny", nballots=8, timeout=600)


def test_five_phase_workflow_chaos_guardian_restart(tmp_path):
    """The subprocess twin of the in-process chaos ceremony test
    (tests/test_faults.py): guardian-1 hard-exits (EGTPU_FAULT_PLAN
    crash_after, os._exit — no handlers, no drain) right after it
    commits its first received key share, is relaunched against its
    resume file, and the 5-phase workflow still lands a fully verified
    record."""
    proc = _run_workflow(tmp_path, "tiny", nballots=6, timeout=600,
                         extra_flags=["-chaosRestartGuardian", "1"])
    out = proc.stdout + proc.stderr
    assert "survived the guardian-1 chaos restart" in out
    g1_log = os.path.join(str(tmp_path), "logs", "guardian-1.stdout")
    with open(g1_log) as f:
        log = f.read()
    assert "injected crash after receiveSecretKeyShare" in log
    assert "RESUMED mid-ceremony" in log


def test_five_phase_workflow_production(tmp_path):
    """The reference's full scenario on the REAL group over real gRPC:
    3 guardians, quorum 2, 2 available -> compensated decryption, spoiled
    ballots, full verification (RunRemoteWorkflowTest.java:83-194).
    Promoted from the hand-run WORKFLOW_PRODUCTION.log into CI (VERDICT
    r4 item 6) so the production compensated path can never regress
    green again."""
    _run_workflow(tmp_path, "production", nballots=4, timeout=1500)

"""Subprocess E2E: the full 5-phase workflow as separate OS processes.

The pytest wrapper around electionguard_tpu.workflow.e2e — the reference's
RunRemoteWorkflowTest equivalent, with a real pass/fail discipline (the
reference's own harness had a literal "LOOK how do we know if it worked?"
comment — SURVEY.md §4; here the verifier exit code is the answer).
"""

import os
import subprocess
import sys

import pytest


def _cpu_env():
    env = {k: v for k, v in os.environ.items()
           if "AXON" not in k and "PALLAS" not in k
           and not k.startswith("TPU")}
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _run_workflow(tmp_path, group: str, nballots: int, timeout: int,
                  extra_flags: list = (), env_extra: dict = None):
    env = _cpu_env()
    env.update(env_extra or {})
    proc = subprocess.run(
        [sys.executable, "-m", "electionguard_tpu.workflow.e2e",
         "-out", str(tmp_path), "-nballots", str(nballots),
         "-nguardians", "3", "-quorum", "2", "-navailable", "2",
         "-group", group, *extra_flags],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "WORKFLOW PASS" in proc.stdout + proc.stderr
    return proc


# `e2e` rides on `slow`: `-m "slow and not e2e"` / `-m e2e` split the
# slow tier into two parallelizable halves (VERDICT r6 item 7) without
# changing what `-m "not slow"` selects.
pytestmark = [pytest.mark.slow, pytest.mark.e2e]


def test_five_phase_workflow(tmp_path):
    _run_workflow(tmp_path, "tiny", nballots=8, timeout=600)


def test_five_phase_workflow_chaos_guardian_restart(tmp_path):
    """The subprocess twin of the in-process chaos ceremony test
    (tests/test_faults.py): guardian-1 hard-exits (EGTPU_FAULT_PLAN
    crash_after, os._exit — no handlers, no drain) right after it
    commits its first received key share, is relaunched against its
    resume file, and the 5-phase workflow still lands a fully verified
    record."""
    proc = _run_workflow(tmp_path, "tiny", nballots=6, timeout=600,
                         extra_flags=["-chaosRestartGuardian", "1"])
    out = proc.stdout + proc.stderr
    assert "survived the guardian-1 chaos restart" in out
    g1_log = os.path.join(str(tmp_path), "logs", "guardian-1.stdout")
    with open(g1_log) as f:
        log = f.read()
    assert "injected crash after receiveSecretKeyShare" in log
    assert "RESUMED mid-ceremony" in log


def test_five_phase_workflow_mixed(tmp_path):
    """The workflow with the optional mixnet phase: 2 re-encryption mix
    stages run between tally accumulation and decryption, the published
    cascade rides in the record dir, and phase-5 verification checks the
    V15 family as part of the same run."""
    proc = _run_workflow(tmp_path, "tiny", nballots=8, timeout=600,
                         extra_flags=["-mix", "2"])
    out = proc.stdout + proc.stderr
    assert "2 mix stages took" in out
    # the verifier's summary (dumped by ver.show()) is green for the
    # whole V15 family
    for check in ("mix_structure", "mix_chain", "mix_membership",
                  "mix_binding", "mix_permutation", "mix_reencryption"):
        assert f"PASS V15.{check}" in out, out
    assert os.path.exists(os.path.join(
        str(tmp_path), "record", "mix_stage_001.pb"))


def test_five_phase_workflow_federated_mix(tmp_path):
    """The federated twin of ``-mix K``: 2 mix stages as 2 separate
    mix-server OS processes plus a coordinator process, traced.  The
    published cascade must be chain-contiguous, carry the SAME verdict
    as the single-process path (every V15 check green through the same
    phase-5 verifier), and the whole topology must join the run's single
    trace id."""
    proc = _run_workflow(tmp_path, "tiny", nballots=8, timeout=600,
                         extra_flags=["-mixServers", "2", "-trace"])
    out = proc.stdout + proc.stderr
    assert "2 federated mix stages over 2 server processes" in out
    # identical verdict to the -mix path (test_five_phase_workflow_mixed):
    # the full V15 family is green through the SAME verifier binary
    for check in ("mix_structure", "mix_chain", "mix_membership",
                  "mix_binding", "mix_permutation", "mix_reencryption"):
        assert f"PASS V15.{check}" in out, out
    # chain-contiguous published stages: densely numbered, nothing extra
    record = os.path.join(str(tmp_path), "record")
    assert os.path.exists(os.path.join(record, "mix_stage_000.pb"))
    assert os.path.exists(os.path.join(record, "mix_stage_001.pb"))
    assert not os.path.exists(os.path.join(record, "mix_stage_002.pb"))

    # one trace id across the driver, the coordinator, and both servers
    from electionguard_tpu.obs import assemble
    spans = assemble.load_spans(os.path.join(str(tmp_path), "trace"))
    report = assemble.validate(spans)
    assert len(report["trace_ids"]) == 1
    procs = {p.split(":")[0] for p in report["processes"]}
    assert {"mix-coordinator", "mix-server-0", "mix-server-1"} <= procs
    names = {s["name"] for s in spans}
    assert {"phase.mixfed", "mixfed.stage", "mixfed.forward"} <= names
    # each server span tree carries exactly its own stage
    stage_of = {s["attrs"]["server"]: s["attrs"]["stage"]
                for s in spans if s["name"] == "mixfed.stage"}
    assert stage_of == {"mix-0": 0, "mix-1": 1}


def test_five_phase_workflow_live_verify(tmp_path):
    """-liveVerify: the live verifier (verify/live) launches right after
    the key ceremony, tails the framed ballot stream while phases 2-4
    write it, serves a BulletinBoardService the driver probes
    mid-election, then drains and finalizes when the decryption result
    lands.  Acceptance: the audit artifact is green with <5% of the
    stream unverified at close, and the live verdict matches the batch
    phase-5 verifier that runs in the same workflow."""
    import json

    proc = _run_workflow(tmp_path, "tiny", nballots=8, timeout=600,
                         extra_flags=["-liveVerify"])
    out = proc.stdout + proc.stderr
    assert "live verifier tailing" in out
    assert "live audit mid-election" in out
    assert "[5.5] live verification converged" in out
    with open(os.path.join(str(tmp_path), "live_audit.json")) as f:
        audit = json.load(f)
    assert audit["verdict_ok"] and audit["status"] == "DONE"
    assert audit["residual_fraction"] < 0.05
    assert audit["frames_verified"] == audit["frames_published"] == 8
    assert audit["chunks_rejected"] == 0 and audit["n_chunks"] >= 8
    assert len(audit["root"]) == 64   # hex sha256 commitment root
    # both verifiers (live + batch phase 5) dumped a green summary
    assert out.count("PASS V6.ballot_chaining") == 2


def test_five_phase_workflow_federated_mix_chaos_kill(tmp_path):
    """Subprocess SIGKILL drill: mix-server-0 hard-exits (os._exit, no
    drain) right after its first shuffle commits.  The coordinator's
    bounded retries surface the death, the stage requeues on the spare
    the chaos flag launches, and the final record still verifies green —
    zero dropped or duplicated rows."""
    proc = _run_workflow(tmp_path, "tiny", nballots=6, timeout=600,
                         extra_flags=["-mixServers", "2",
                                      "-chaosKillMixServer"])
    out = proc.stdout + proc.stderr
    assert "2 federated mix stages over 3 server processes" in out
    for check in ("mix_structure", "mix_chain", "mix_membership",
                  "mix_binding", "mix_permutation", "mix_reencryption"):
        assert f"PASS V15.{check}" in out, out
    with open(os.path.join(str(tmp_path), "logs",
                           "mix-server-0.stdout")) as f:
        victim_log = f.read()
    assert "injected crash after shuffleStage" in victim_log
    with open(os.path.join(str(tmp_path), "logs",
                           "mix-coordinator.stdout")) as f:
        coord_log = f.read()
    assert "requeueing on a spare" in coord_log


@pytest.mark.slowest
def test_five_phase_workflow_chaos_kill_under_obs_collector(tmp_path):
    """The SIGKILL drill under live observability: mix-server-0 dies via
    os._exit mid-mix (no goodbye, no flush) while the run's obs
    collector is watching.  The collector must detect the death from
    missed heartbeats — far inside the victim's ``data`` rpc deadline
    class (600s), i.e. long before any in-flight rpc against it would
    time out — fire the ``heartbeat_miss`` alert as a first-class span
    in the run timeline, take the fleet red, and return to green once
    the stage requeues on the spare and the death ages out.  The run
    itself still lands a fully verified record, so the end-of-run
    fleet-green gate passes."""
    import glob
    import json
    import re

    proc = _run_workflow(
        tmp_path, "tiny", nballots=6, timeout=600,
        extra_flags=["-mixServers", "2", "-chaosKillMixServer",
                     "-obsCollector", "-trace"],
        # shrink the post-death red window so the decrypt+verify tail is
        # guaranteed to outlast it (the green gate is part of the PASS)
        env_extra={"EGTPU_OBS_SLO":
                   '{"heartbeat": {"dead_red_for_s": 4.0}}'})
    out = proc.stdout + proc.stderr

    # the chaos story itself is unchanged: crash, requeue, green record
    with open(os.path.join(str(tmp_path), "logs",
                           "mix-server-0.stdout")) as f:
        assert "injected crash after shuffleStage" in f.read()
    with open(os.path.join(str(tmp_path), "logs",
                           "mix-coordinator.stdout")) as f:
        assert "requeueing on a spare" in f.read()
    assert "[obs] fleet green" in out

    # the collector saw the whole arc: miss -> alert -> dead -> red ->
    # (requeue elsewhere) -> green
    with open(os.path.join(str(tmp_path), "logs",
                           "obs-collector.stdout")) as f:
        coll_log = f.read()
    assert "slo alert [heartbeat_miss] mix-server-0" in coll_log
    assert "declared dead" in coll_log
    assert "fleet: health green -> red" in coll_log
    assert "fleet: health red -> green" in coll_log

    # the alert is a first-class span in the collector's receive dir,
    # with the detection latency attribute inside the data class
    alerts = []
    for path in glob.glob(os.path.join(str(tmp_path), "obs", "recv",
                                       "spans-*.jsonl")):
        with open(path) as f:
            for line in f:
                rec = json.loads(line)
                if rec["name"] == "slo.alert":
                    alerts.append(rec)
    miss = [a for a in alerts
            if a["attrs"]["kind"] == "heartbeat_miss"
            and a["attrs"]["subject"] == "mix-server-0"]
    assert miss, f"no heartbeat_miss alert span in {alerts}"
    assert 0.0 < miss[0]["attrs"]["detection_s"] < 600.0

    # the dead process is still on the final fleet board, state DEAD,
    # next to the spare that replaced it
    assert "mix-server-2" in out
    assert re.search(r"mix-server-0:\d+\s+DEAD", out), out

    # the live timeline the collector assembled survives the death
    # strict-valid: the victim's in-flight spans are open markers, not
    # orphans or envelope gaps
    with open(os.path.join(str(tmp_path), "obs",
                           "trace_live_report.json")) as f:
        rep = json.load(f)
    assert len(rep["trace_ids"]) == 1
    assert rep["orphans"] == [] and rep["gaps"] == []
    # at least driver + coordinator + collector + both mix servers
    assert len(rep["processes"]) >= 5
    # ...and the standalone tool agrees on the receive dir (-strict)
    tool = subprocess.run(
        [sys.executable, "tools/assemble_trace.py", "-dir",
         os.path.join(str(tmp_path), "obs", "recv"), "-out",
         os.path.join(str(tmp_path), "obs", "trace_tool.json"), "-strict"],
        capture_output=True, text=True, timeout=120, env=_cpu_env(),
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert tool.returncode == 0, tool.stdout + tool.stderr


def test_five_phase_workflow_traced(tmp_path):
    """Observability acceptance: one traced e2e run yields a merged
    Chrome-trace timeline with spans from every spawned process under a
    single trace_id, rpc client/server pairs nested across process
    boundaries, device compile spans attributed to their batches, and a
    gap-free (every span inside its process envelope) structure that
    assemble_trace -strict signs off on."""
    import json
    import subprocess as sp

    proc = _run_workflow(tmp_path, "tiny", nballots=6, timeout=600,
                         extra_flags=["-trace"])
    assert "TRACE:" in proc.stdout + proc.stderr

    from electionguard_tpu.obs import assemble
    trace_dir = os.path.join(str(tmp_path), "trace")
    spans = assemble.load_spans(trace_dir)
    report = assemble.validate(spans)
    # one trace id across every process of the run
    assert len(report["trace_ids"]) == 1
    assert len(report["processes"]) >= 3
    # well-formed and gap-free: all parents resolve, every span inside
    # its process root envelope, every rpc.server span paired with its
    # cross-process rpc.client parent
    assert report["orphans"] == [] and report["gaps"] == []
    assert report["rpc_pairs"] >= 10 and report["rpc_server_unpaired"] == 0
    names = {s["name"] for s in spans}
    assert {"process", "phase.key-ceremony", "phase.encrypt",
            "phase.decrypt", "encrypt.batch", "decrypt.batch",
            "keyceremony.exchange", "device.compile"} <= names
    # compile spans are attributed: parented into a real span tree
    ids = {s["span_id"] for s in spans}
    assert all(s["parent_id"] in ids
               for s in spans if s["name"] == "device.compile")

    # the driver already merged; the standalone tool agrees (-strict)
    merged = os.path.join(str(tmp_path), "trace.json")
    assert os.path.exists(merged)
    with open(merged) as f:
        events = json.load(f)["traceEvents"]
    assert len([e for e in events if e["ph"] == "X"]) == len(spans)
    tool = sp.run(
        [sys.executable, "tools/assemble_trace.py", "-dir", trace_dir,
         "-out", os.path.join(str(tmp_path), "trace_tool.json"),
         "-strict"],
        capture_output=True, text=True, timeout=120, env=_cpu_env(),
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert tool.returncode == 0, tool.stdout + tool.stderr


def test_five_phase_workflow_fabric(tmp_path):
    """Phase 2 through the sharded serving fabric: a router process
    fronting 2 encryption-worker processes, each publishing its own
    shard record under a signed manifest; the driver merges the shards
    into the one record phases 3-5 consume.  The phase-5 verifier must
    be green INCLUDING the V.shard_manifest family, and the traced run
    must show the router and both workers on the single run timeline.

    Runs with the straggler drill (worker 0 alone padded by
    -fabricSkewMs) and -flightReport, so the same run also proves the
    flight-report acceptance criteria: the critical-path durations sum
    to the run's measured wall-clock and the seeded straggler is named
    in the straggler section."""
    import re

    proc = _run_workflow(tmp_path, "tiny", nballots=8, timeout=600,
                         extra_flags=["-fabricWorkers", "2",
                                      "-flightReport",
                                      "-fabricSkewMs", "200"])
    out = proc.stdout + proc.stderr
    assert "fabric up: router" in out
    assert "fabric load done: 8/8 ballots admitted, zero lost" in out
    assert "merged 2 shard records" in out
    for check in ("signature", "seed", "chain", "overlap", "complete"):
        assert f"PASS V.shard_manifest.{check}" in out, out
    # both shards published + the merged record carries both manifests
    import json
    with open(os.path.join(str(tmp_path), "record",
                           "shard_manifests.json")) as f:
        manifests = json.load(f)
    assert [m["shard_id"] for m in manifests] == [0, 1]
    assert sum(m["admitted_count"] for m in manifests) == 8
    for i in range(2):
        assert os.path.exists(os.path.join(
            str(tmp_path), "shards", f"shard-w{i}", "shard_manifest.json"))
    # the whole fabric joins the run's single trace
    from electionguard_tpu.obs import assemble
    spans = assemble.load_spans(os.path.join(str(tmp_path), "trace"))
    report = assemble.validate(spans)
    assert len(report["trace_ids"]) == 1
    procs = {p.split(":")[0] for p in report["processes"]}
    assert {"fabric-router", "encryption-worker-0",
            "encryption-worker-1"} <= procs
    assert "worker.batch" in {s["name"] for s in spans}

    # flight report: critical path covers the run's wall-clock...
    from electionguard_tpu.obs import analyze
    a = analyze.analyze(os.path.join(str(tmp_path), "trace"))
    assert a.wall_us > 0
    # ...exactly, by construction of the decomposition...
    assert abs(a.coverage - 1.0) < 1e-3, a.coverage
    # ...and within 5% of the independently measured end-to-end time
    # the driver logs (the acceptance criterion)
    m = re.search(r"WORKFLOW PASS: 5 phases, 8 ballots, "
                  r"([0-9.]+)s total", out)
    t_meas = float(m.group(1))
    assert abs(a.path_total_us / 1e6 - t_meas) / t_meas < 0.05, \
        (a.path_total_us / 1e6, t_meas)
    # the seeded straggler (worker 0 under 200ms/batch device skew) is
    # named, and the report on disk says so too
    assert [s["proc"] for s in a.stragglers] == ["encryption-worker-0"]
    report_path = os.path.join(str(tmp_path), "FLIGHT_REPORT.md")
    assert os.path.exists(report_path)
    with open(report_path) as f:
        rpt = f.read()
    assert "### Stragglers" in rpt
    assert "**encryption-worker-0**" in rpt
    assert "## Critical path" in rpt
    assert "## Wall-clock attribution" in rpt


@pytest.mark.slowest
def test_five_phase_workflow_fabric_chaos_kill(tmp_path):
    """The fleet SIGKILL drill: worker 0 wedges after 2 ballots (chaos
    knob), is SIGKILL'd mid-load with admitted-but-unpublished ballots
    in its journal, the router requeues them onto the survivor, and the
    relaunched worker reclaims its shard — tombstoning the requeued ids
    instead of double-publishing.  Zero lost admitted ballots, and the
    merged record still verifies green through V.shard_manifest.

    Also runs -flightReport: the SIGKILL'd worker's trace is damaged by
    construction (its root span never closes), so the drill doubles as
    the flight generator's degradation test on a REAL broken trace."""
    proc = _run_workflow(
        tmp_path, "tiny", nballots=8, timeout=900,
        extra_flags=["-fabricWorkers", "2",
                     "-chaosKillEncryptionWorker", "-flightReport"])
    out = proc.stdout + proc.stderr
    assert "CHAOS: worker 0 SIGKILL'd" in out
    assert "fabric load done: 8/8 ballots admitted, zero lost" in out
    for check in ("signature", "seed", "chain", "overlap", "complete"):
        assert f"PASS V.shard_manifest.{check}" in out, out
    with open(os.path.join(str(tmp_path), "logs",
                           "fabric-router.stdout")) as f:
        router_log = f.read()
    assert "requeued" in router_log
    assert "re-registered" in router_log
    with open(os.path.join(str(tmp_path), "logs",
                           "encryption-worker-0.stdout")) as f:
        w0_log = f.read()
    assert "worker wedged" in w0_log
    # the relaunch registered against the router and tombstoned the
    # journaled admissions the router had requeued onto the survivor
    # (replaying them would double-publish)
    assert "requeued ids to skip" in w0_log
    assert "journaled admissions requeued to other shards" in w0_log

    # the flight report must still materialize over the damaged trace
    # (never a crash — degradation to partial-with-warnings is the
    # contract), and the run timeline is complete enough for a path
    assert "FLIGHT REPORT:" in out
    report_path = os.path.join(str(tmp_path), "FLIGHT_REPORT.md")
    assert os.path.exists(report_path)
    with open(report_path) as f:
        rpt = f.read()
    assert "# Flight report" in rpt
    assert "## Critical path" in rpt


@pytest.fixture(scope="session")
def production_run(tmp_path_factory):
    """ONE production-group subprocess workflow shared by every test
    that only inspects its artifacts (VERDICT #7: the multi-minute
    production run used to be re-run per test)."""
    out = tmp_path_factory.mktemp("production_e2e")
    proc = _run_workflow(out, "production", nballots=4, timeout=1500,
                         extra_flags=["-flightReport"])
    return str(out), proc


@pytest.mark.slowest
def test_five_phase_workflow_production(production_run):
    """The reference's full scenario on the REAL group over real gRPC:
    3 guardians, quorum 2, 2 available -> compensated decryption, spoiled
    ballots, full verification (RunRemoteWorkflowTest.java:83-194).
    Promoted from the hand-run WORKFLOW_PRODUCTION.log into CI (VERDICT
    r4 item 6) so the production compensated path can never regress
    green again."""
    out_dir, proc = production_run
    assert "WORKFLOW PASS" in proc.stdout + proc.stderr
    assert os.path.exists(os.path.join(out_dir, "record"))


@pytest.mark.slowest
def test_production_run_flight_report(production_run):
    """The SAME production run's flight report (shared session fixture,
    no second multi-minute workflow): full critical-path coverage on the
    real group, and the standalone egreport CLI reproduces it from the
    trace dir alone."""
    out_dir, _ = production_run
    report_path = os.path.join(out_dir, "FLIGHT_REPORT.md")
    assert os.path.exists(report_path)
    from electionguard_tpu.obs import analyze
    a = analyze.analyze(os.path.join(out_dir, "trace"))
    assert a.wall_us > 0 and abs(a.coverage - 1.0) < 1e-3
    # standalone CLI over the same dir
    tool = subprocess.run(
        [sys.executable, "tools/egreport.py",
         os.path.join(out_dir, "trace"),
         "-out", os.path.join(out_dir, "FLIGHT_REPORT_cli.md")],
        capture_output=True, text=True, timeout=300, env=_cpu_env(),
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert tool.returncode == 0, tool.stdout + tool.stderr
    with open(os.path.join(out_dir, "FLIGHT_REPORT_cli.md")) as f:
        assert "## Critical path" in f.read()

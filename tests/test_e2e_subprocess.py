"""Subprocess E2E: the full 5-phase workflow as separate OS processes.

The pytest wrapper around electionguard_tpu.workflow.e2e — the reference's
RunRemoteWorkflowTest equivalent, with a real pass/fail discipline (the
reference's own harness had a literal "LOOK how do we know if it worked?"
comment — SURVEY.md §4; here the verifier exit code is the answer).
"""

import os
import subprocess
import sys

import pytest


@pytest.mark.slow
def test_five_phase_workflow(tmp_path):
    env = {k: v for k, v in os.environ.items()
           if "AXON" not in k and "PALLAS" not in k
           and not k.startswith("TPU")}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "electionguard_tpu.workflow.e2e",
         "-out", str(tmp_path), "-nballots", "8", "-nguardians", "3",
         "-quorum", "2", "-navailable", "2", "-group", "tiny"],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "WORKFLOW PASS" in proc.stdout + proc.stderr

"""Unit tests for the scalar group plane (the missing test pyramid the
reference delegates upstream — SURVEY.md §4)."""

import pytest

from electionguard_tpu.core.group import (ElementModP, ElementModQ,
                                          production_group, tiny_group)


@pytest.mark.parametrize("grp", ["tgroup", "pgroup"])
def test_group_structure(grp, request):
    g = request.getfixturevalue(grp)
    assert (g.p - 1) % g.q == 0
    assert g.r == (g.p - 1) // g.q
    assert pow(g.g, g.q, g.p) == 1
    assert g.g != 1


def test_production_sizes(pgroup):
    assert pgroup.p.bit_length() == 4096
    assert pgroup.q == (1 << 256) - 189  # spec 1.03 q
    assert pgroup.spec.p_bytes == 512 and pgroup.spec.q_bytes == 32


def test_q_arithmetic(tgroup):
    g = tgroup
    a, b = g.int_to_q(1234567), g.int_to_q(7654321)
    assert g.add_q(a, b).value == (a.value + b.value) % g.q
    assert g.sub_q(a, b).value == (a.value - b.value) % g.q
    assert g.mult_q(a, b).value == a.value * b.value % g.q
    assert g.mult_q(a, g.inv_q(a)).value == 1
    assert g.add_q(a, g.neg_q(a)).value == 0
    assert g.a_plus_bc_q(a, b, b).value == (a.value + b.value * b.value) % g.q


def test_p_arithmetic(tgroup):
    g = tgroup
    e = g.int_to_q(987654321)
    x = g.g_pow_p(e)
    assert x.value == pow(g.g, e.value, g.p)
    assert g.mult_p(x, g.inv_p(x)).value == 1
    assert g.pow_p(x, g.int_to_q(3)).value == pow(x.value, 3, g.p)
    assert g.div_p(x, x).value == 1


def test_subgroup_membership(tgroup):
    g = tgroup
    assert g.g_pow_p(g.rand_q()).is_valid_residue()
    # an element outside the order-q subgroup fails the residue check
    bad = ElementModP(2, g)  # 2 generates a larger group w.h.p.
    if pow(2, g.q, g.p) != 1:
        assert not bad.is_valid_residue()


def test_pow_identity(tgroup):
    g = tgroup
    a, b = g.rand_q(), g.rand_q()
    # g^a * g^b == g^(a+b)
    assert g.mult_p(g.g_pow_p(a), g.g_pow_p(b)) == g.g_pow_p(g.add_q(a, b))


def test_bytes_roundtrip(tgroup):
    g = tgroup
    q = g.rand_q()
    assert g.bytes_to_q(q.to_bytes()) == q
    p = g.g_pow_p(q)
    assert g.bytes_to_p(p.to_bytes()) == p
    assert len(p.to_bytes()) == g.spec.p_bytes


def test_range_validation(tgroup):
    with pytest.raises(ValueError):
        ElementModQ(tgroup.q, tgroup)
    with pytest.raises(ValueError):
        ElementModP(tgroup.p, tgroup)


def test_immutability(tgroup):
    q = tgroup.int_to_q(5)
    with pytest.raises(AttributeError):
        q.value = 6

"""NTT-backend fused pipeline differential (the TPU-default engine).

The CI suite runs the fused encrypt/verify programs on the CIOS backend
(CPU default); the real chip runs them on the MXU NTT engine with
hat-table fixed-base walks (ntt_mxu.montmul_hat) and the shared-base
multi-exp (ntt_mxu.montmul_shared).  These tests pin the NTT-backed
fused programs bit-identical to the CIOS-backed ones, so the engine the
bench measures is the engine CI verified.
"""

import numpy as np
import pytest

from electionguard_tpu.core.group_jax import JaxGroupOps, jax_exp_ops
from electionguard_tpu.core.hash import _encode
from electionguard_tpu.encrypt.fused import FusedEncryptor
from electionguard_tpu.verify.fused import FusedVerifier

pytestmark = pytest.mark.slow


def test_ntt_fused_encrypt_verify_matches_cios(pgroup):
    g = pgroup
    ee = jax_exp_ops(g)
    ops_ntt = JaxGroupOps(g, backend="ntt")
    ops_cios = JaxGroupOps(g, backend="cios")
    assert ops_ntt.backend == "ntt" and ops_ntt._mm_hat is not None
    fe_n = FusedEncryptor(ops_ntt, ee)
    fe_c = FusedEncryptor(ops_cios, ee)
    rng = np.random.default_rng(9)
    S = 4
    seed_row = rng.integers(0, 256, 32, dtype=np.uint8)
    bids = rng.integers(0, 256, (S, 32), dtype=np.uint8)
    ords = np.arange(S, dtype=np.uint32)
    votes = np.array([0, 1, 0, 1], dtype=np.int64)
    K = pow(g.g, 12345, g.p)
    prefix = _encode(7)  # stands in for enc(qbar), same on both engines

    out_n = fe_n.encrypt_selections(seed_row, bids, ords, votes, K, prefix)
    out_c = fe_c.encrypt_selections(seed_row, bids, ords, votes, K, prefix)
    for a, b in zip(out_n, out_c):
        np.testing.assert_array_equal(a, b)

    # the NTT-backed fused verifier (hat tables + shared-base multi-exp)
    # must accept what the NTT-backed fused encryptor produced
    alpha, beta, _, CR, VR, CF, VF = out_n
    v1m = (votes == 1)[:, None]
    ok = FusedVerifier(ops_ntt).v4_selections(
        alpha, beta,
        np.where(v1m, CF, CR), np.where(v1m, VF, VR),
        np.where(v1m, CR, CF), np.where(v1m, VR, VF), K, prefix)
    assert np.asarray(ok).all()

    con_n = fe_n.encrypt_contests(seed_row, bids[:1], ords[:1],
                                  ee.to_limbs([5]), ee.to_limbs([1]),
                                  K, prefix)
    con_c = fe_c.encrypt_contests(seed_row, bids[:1], ords[:1],
                                  ee.to_limbs([5]), ee.to_limbs([1]),
                                  K, prefix)
    for a, b in zip(con_n, con_c):
        np.testing.assert_array_equal(a, b)

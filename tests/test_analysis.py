"""Tier-1 gate + known-bad fixtures for the analysis framework.

Two jobs:

* **the gate**: run every registered pass over the real package and
  require a clean strict report (no live findings, no stale baseline,
  empty baseline for secret-taint/raw-channel, ENV_KNOBS.md in sync,
  ANALYSIS.json artifact present and clean);
* **prove each pass fires**: one deliberately-bad fixture per pass,
  written into a temp dir with the package-relative layout the
  path-scoped passes key on — the real package walk never sees them —
  asserting the finding lands on the exact line, that an inline
  ``# eglint: disable=RULE`` suppresses exactly one finding, and that
  the baseline round-trips.
"""

import json
import textwrap

import pytest

from electionguard_tpu.analysis import core
from electionguard_tpu.utils import knobs as knobs_mod

ALL_PASSES = {"env-knob-registry", "ingestion-validation", "jit-hygiene",
              "lock-discipline", "no-bare-print", "rpc-contract",
              "secret-taint", "tenant-label", "trace-coverage",
              "wall-clock-discipline"}


# ---------------------------------------------------------------------------
# fixture plumbing
# ---------------------------------------------------------------------------

def _project(tmp_path, files: dict[str, str]) -> core.Project:
    """A throwaway project: ``files`` maps package-relative paths to
    source text, rooted at ``tmp_path/pkg``."""
    pkg = tmp_path / "pkg"
    for rel, text in files.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    return core.Project(package_dir=pkg, root=tmp_path)


def _run(project, passes, baseline=()):
    return core.run_passes(project, passes=passes,
                           baseline=list(baseline))


def _lines(report, rule):
    return [f.line for f in report.findings if f.rule == rule]


# ---------------------------------------------------------------------------
# the whole-package gate
# ---------------------------------------------------------------------------

def test_registry_has_all_passes():
    core.load_default_passes()
    assert set(core.PASSES) == ALL_PASSES


def test_package_strict_gate():
    report = core.run_passes()
    assert set(report.passes_run) == ALL_PASSES
    assert len(report.files_scanned) > 80
    assert not report.findings, "\n".join(str(f) for f in report.findings)
    assert not report.stale_baseline


def test_secret_rules_ship_with_empty_baseline():
    baseline = core.load_baseline()
    assert core.NO_BASELINE_RULES == ("secret-taint", "raw-channel")
    assert not [e for e in baseline
                if e["rule"] in core.NO_BASELINE_RULES]
    # and every entry that IS baselined carries a tracking rationale
    assert all(str(e["note"]).strip() for e in baseline)


def test_env_knobs_table_in_sync():
    table = core.REPO_ROOT / "ENV_KNOBS.md"
    assert table.exists(), "run `python tools/eglint.py --write-knobs`"
    assert table.read_text() == knobs_mod.render_table(), (
        "ENV_KNOBS.md drifted from utils/knobs.py: run "
        "`python tools/eglint.py --write-knobs`")


def test_analysis_json_artifact():
    path = core.REPO_ROOT / "ANALYSIS.json"
    assert path.exists(), "run `python tools/eglint.py --json`"
    data = json.loads(path.read_text())
    assert data["version"] == 1
    assert set(data["passes"]) == ALL_PASSES
    assert data["findings"] == []
    assert data["stale_baseline"] == []


# ---------------------------------------------------------------------------
# known-bad fixtures: each pass fires on the exact line
# ---------------------------------------------------------------------------

def test_secret_taint_fires_on_logged_secret(tmp_path):
    project = _project(tmp_path, {"keyceremony/trustee.py": """\
        import logging

        log = logging.getLogger("t")


        def leak(group):
            seed = group.rand_q()
            log.info("seed=%s", seed)
    """})
    report = _run(project, ["secret-taint"])
    assert _lines(report, "secret-taint") == [8]


def test_secret_taint_declassifier_stops_taint(tmp_path):
    project = _project(tmp_path, {"keyceremony/trustee.py": """\
        import logging

        log = logging.getLogger("t")


        def ok(group):
            seed = group.rand_q()
            pub = group.g_pow_p(seed)
            log.info("pub=%s", pub)
    """})
    assert not _run(project, ["secret-taint"]).findings


def test_raw_channel_fires_outside_rpc_util(tmp_path):
    project = _project(tmp_path, {"client.py": """\
        import grpc

        chan = grpc.insecure_channel("localhost:1")
    """})
    report = _run(project, ["rpc-contract"])
    assert _lines(report, "raw-channel") == [3]


_FIXTURE_PROTO = """\
syntax = "proto3";
package egtpu;

message Ping { uint64 chunk_start = 1; }
message Pong { bool ok = 1; }
message Empty { bool x = 1; }

service DemoService {
  rpc pushRows (Ping) returns (Pong);
  rpc health (Empty) returns (Pong);
}
"""


def test_rpc_contract_deadline_and_idempotency(tmp_path):
    project = _project(tmp_path, {
        "publish/proto/remote_rpc.proto": _FIXTURE_PROTO,
        "remote/rpc_util.py": """\
            _DEADLINE_CLASS_OF = {
                "pushRows": "data",
            }


            def generic_service(name, impls):
                return name, impls
        """,
        "remote/server.py": """\
            def _push(request, context):
                return request


            def _health(request, context):
                return context


            SVC = generic_service("DemoService", {"pushRows": _push,
                                                  "health": _health})
        """,
    })
    report = _run(project, ["rpc-contract"])
    msgs = {f.message.split(" — ")[0].split(" (")[0]: f
            for f in report.findings}
    # health has no deadline class, flagged at its proto line
    health_line = 1 + _FIXTURE_PROTO.splitlines().index(
        "  rpc health (Empty) returns (Pong);")
    deadline = [f for f in report.findings if "deadline class" in f.message]
    assert [(f.path.endswith(".proto"), f.line) for f in deadline] \
        == [(True, health_line)]
    # pushRows is chunked but its impl never reads chunk_start
    idem = [f for f in report.findings if "chunk_start" in f.message]
    assert len(idem) == 1 and idem[0].path.endswith("remote/server.py")
    assert idem[0].line == 9        # the generic_service registration
    assert len(report.findings) == 2, msgs


def test_jit_hygiene_fires(tmp_path):
    project = _project(tmp_path, {"kernels.py": """\
        import jax
        import jax.numpy as jnp


        @jax.jit
        def bad_sync(x):
            return x.max().item()


        @jax.jit
        def bad_cast(x):
            return int(x.sum())


        @jax.jit
        def bad_shape(n):
            return jnp.arange(n)


        def caller(x):
            return jax.jit(bad_cast)(x)
    """})
    report = _run(project, ["jit-hygiene"])
    assert sorted(_lines(report, "jit-hygiene")) == [7, 12, 17, 21]


def test_jit_hygiene_walks_pallas_kernel_bodies(tmp_path):
    # a kernel handed straight to pl.pallas_call is jitted code: the
    # host sync inside it must fire at its exact line
    project = _project(tmp_path, {"kern.py": """\
        import jax
        from jax.experimental import pallas as pl


        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...] * x_ref[...].max().item()


        def launch(x):
            return pl.pallas_call(kernel, out_shape=x)(x)
    """})
    report = _run(project, ["jit-hygiene"])
    assert _lines(report, "jit-hygiene") == [6]


def test_jit_hygiene_resolves_pallas_factory_indirection(tmp_path):
    # the factory idiom the core/pallas engine uses: the kernel def is
    # nested inside a maker, bound to an attribute at ctx-build time,
    # and only the attribute reaches pallas_call.  The walk must still
    # reach the nested body.
    project = _project(tmp_path, {"eng.py": """\
        from jax.experimental import pallas as pl


        def make_kernel(m):
            def kernel(x_ref, o_ref):
                o_ref[...] = int(x_ref[...].sum()) % m
            return kernel


        class Ctx:
            def __init__(self, m):
                self._kernel = make_kernel(m)


        def launch(ctx, x):
            return pl.pallas_call(ctx._kernel, out_shape=x)(x)
    """})
    report = _run(project, ["jit-hygiene"])
    assert _lines(report, "jit-hygiene") == [6]


def test_jit_hygiene_construction_time_jit_is_clean(tmp_path):
    # the sharded-plane idiom: jit bound once at __init__ time
    project = _project(tmp_path, {"plane.py": """\
        import jax


        def kernel(x):
            return x + 1


        class Plane:
            def __init__(self):
                self._f = jax.jit(kernel)

            def apply(self, x):
                return self._f(x)
    """})
    assert not _run(project, ["jit-hygiene"]).findings


def test_lock_discipline_fires(tmp_path):
    project = _project(tmp_path, {"state.py": """\
        import threading


        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def put(self, x):
                with self._lock:
                    self._items.append(x)

            def size(self):
                return len(self._items)
    """})
    report = _run(project, ["lock-discipline"])
    assert _lines(report, "lock-discipline") == [14]
    assert "read lock-free in size()" in report.findings[0].message


def test_env_knob_registry_fires(tmp_path):
    project = _project(tmp_path, {
        "utils/knobs.py": """\
            class Knob:
                def __init__(self, name, type, default, doc):
                    pass


            KNOBS = (
                Knob("EGTPU_DEMO", "int", "1", "Demo knob."),
            )
        """,
        "app.py": """\
            import os

            ok = os.environ.get("EGTPU_DEMO", "1")
            bad = os.environ.get("EGTPU_SECRET_TUNING", "")
            drift = os.environ.get("EGTPU_DEMO", "2")
        """,
    })
    # keep the docs-drift check quiet: commit the rendered table
    decls = [knobs_mod.Knob("EGTPU_DEMO", "int", "1", "Demo knob.")]
    (tmp_path / "ENV_KNOBS.md").write_text(knobs_mod.render_table(decls))
    report = _run(project, ["env-knob-registry"])
    assert sorted(_lines(report, "env-knob-registry")) == [4, 5]
    msgs = sorted(f.message for f in report.findings)
    assert "not declared" in msgs[1] and "declares '1'" in msgs[0]


def test_env_knob_registry_flags_missing_table(tmp_path):
    project = _project(tmp_path, {
        "utils/knobs.py": """\
            class Knob:
                def __init__(self, name, type, default, doc):
                    pass


            KNOBS = (
                Knob("EGTPU_DEMO", "int", "1", "Demo knob."),
            )
        """,
    })
    report = _run(project, ["env-knob-registry"])
    assert len(report.findings) == 1
    assert "ENV_KNOBS.md missing" in report.findings[0].message


def test_wall_clock_discipline_fires_at_exact_lines(tmp_path):
    project = _project(tmp_path, {
        "serve/poller.py": """\
            import time
            from time import sleep as zzz

            def wait():
                t0 = time.monotonic()
                zzz(0.5)
                return time.time() - t0
            """,
        # exempt homes: the seam itself, cli/, bench harnesses
        "utils/clock.py": "import time\nNOW = time.time()\n",
        "cli/tool.py": "import time\ntime.sleep(1)\n",
        "core/foo_bench.py": "import time\nt = time.perf_counter()\n",
        # no time import at all -> never scanned for calls
        "tally/add.py": "def add(a, b):\n    return a + b\n",
    })
    report = _run(project, ["wall-clock-discipline"])
    assert [(f.path, f.line) for f in report.findings] \
        == [("pkg/serve/poller.py", 5),
            ("pkg/serve/poller.py", 6),
            ("pkg/serve/poller.py", 7)]
    assert all("utils/clock" in f.message for f in report.findings)


def test_no_bare_print_fires_and_cli_is_exempt(tmp_path):
    project = _project(tmp_path, {
        "mod.py": 'print("hi")\n',
        "cli/tool.py": 'print("hi")\n',
    })
    report = _run(project, ["no-bare-print"])
    assert [(f.path, f.line) for f in report.findings] \
        == [("pkg/mod.py", 1)]


def test_trace_coverage_fires_on_unwrapped_handler(tmp_path):
    project = _project(tmp_path, {"serve/rogue.py": """\
        import grpc


        def service(impls):
            handlers = {}
            for name, fn in impls.items():
                handlers[name] = grpc.unary_unary_rpc_method_handler(fn)
            return grpc.method_handlers_generic_handler("Svc", handlers)
    """})
    report = _run(project, ["trace-coverage"])
    assert _lines(report, "trace-coverage") == [7, 8]


def test_trace_coverage_accepts_wrapped_registration(tmp_path):
    project = _project(tmp_path, {"serve/good.py": """\
        import grpc

        from electionguard_tpu.obs import trace as obs_trace


        def service(impls):
            handlers = {}
            for name, fn in impls.items():
                wrapped = obs_trace.wrap_server_method("Svc", name, fn)
                handlers[name] = grpc.unary_unary_rpc_method_handler(
                    wrapped)
            return handlers


        def register(server, reg, front, collector):
            server.add_generic_rpc_handlers(
                (generic_service(reg), collector.service()))


        def generic_service(svc):
            return svc
    """})
    report = _run(project, ["trace-coverage"])
    assert report.findings == []


def test_trace_coverage_fires_on_rogue_generic_registration(tmp_path):
    project = _project(tmp_path, {"serve/sneaky.py": """\
        def register(server, impls):
            handler = make_untraced_handler(impls)
            server.add_generic_rpc_handlers((handler,))
    """})
    report = _run(project, ["trace-coverage"])
    assert _lines(report, "trace-coverage") == [3]


# ---------------------------------------------------------------------------
# suppression layers
# ---------------------------------------------------------------------------

def test_inline_disable_suppresses_exactly_one(tmp_path):
    project = _project(tmp_path, {"keyceremony/trustee.py": """\
        import logging

        log = logging.getLogger("t")


        def leak(group):
            seed = group.rand_q()
            log.info("a=%s", seed)  # eglint: disable=secret-taint
            log.info("b=%s", seed)
    """})
    report = _run(project, ["secret-taint"])
    assert report.suppressed == {"secret-taint": 1}
    assert _lines(report, "secret-taint") == [9]


def test_baseline_round_trip(tmp_path):
    files = {"mod.py": 'print("hi")\n'}
    project = _project(tmp_path, files)
    first = _run(project, ["no-bare-print"])
    assert len(first.findings) == 1

    path = tmp_path / "baseline.json"
    core.write_baseline(path, first.findings,
                        note="fixture: parked for the round-trip test")
    baseline = core.load_baseline(path)
    second = _run(project, ["no-bare-print"], baseline=baseline)
    assert not second.findings
    assert [f.key for f in second.baselined] \
        == [f.key for f in first.findings]
    assert not second.stale_baseline

    # fix the finding without removing the entry -> stale, never silent
    third = _run(_project(tmp_path / "fixed", {"mod.py": "x = 1\n"}),
                 ["no-bare-print"], baseline=baseline)
    assert third.stale_baseline == baseline


def test_baseline_rejects_noteless_and_no_baseline_rules(tmp_path):
    p = tmp_path / "baseline.json"
    p.write_text(json.dumps(
        [{"rule": "lock-discipline", "path": "x.py", "line": 1}]))
    with pytest.raises(ValueError, match="no note"):
        core.load_baseline(p)
    p.write_text(json.dumps(
        [{"rule": "secret-taint", "path": "x.py", "line": 1,
          "note": "tempting, but no"}]))
    with pytest.raises(ValueError, match="may not be baselined"):
        core.load_baseline(p)


# ---------------------------------------------------------------------------
# ingestion-validation
# ---------------------------------------------------------------------------

def test_ingestion_validation_fires_outside_boundary(tmp_path):
    # a brand-new conversion site in a non-exempt, non-boundary file
    project = _project(tmp_path, {"decrypt/new_path.py": """\
        from electionguard_tpu.publish import serialize

        def receive(group, msg):
            share = serialize.import_p(group, msg.partial_decryption)
            return share
    """})
    report = _run(project, ["ingestion-validation"])
    assert _lines(report, "ingestion-validation") == [4]
    assert "outside a registered ingestion boundary" \
        in report.findings[0].message


def test_ingestion_validation_boundary_lost_its_gate(tmp_path):
    # a registered boundary file whose gate call was deleted
    project = _project(tmp_path, {"mixfed/server.py": """\
        from electionguard_tpu.publish import serialize

        def push(group, request):
            return [serialize.import_mix_row(group, r)
                    for r in request.rows]
    """})
    report = _run(project, ["ingestion-validation"])
    assert _lines(report, "ingestion-validation") == [4]
    assert "has no crypto/validate.gate_" in report.findings[0].message


def test_ingestion_validation_gated_and_exempt_paths_clean(tmp_path):
    project = _project(tmp_path, {
        # registered boundary WITH its gate: clean
        "mixfed/server.py": """\
            from electionguard_tpu.crypto import validate
            from electionguard_tpu.publish import serialize

            def push(group, request):
                validate.gate_wire_p(group, [], "mixfed")
                return [serialize.import_mix_row(group, r)
                        for r in request.rows]
        """,
        # the terminal verifier re-proves membership itself: exempt
        "verify/verifier.py": """\
            from electionguard_tpu.publish import serialize

            def check(group, m):
                return serialize.import_encrypted_ballot(group, m)
        """,
        # the publisher round-trips its own artifacts: exempt
        "publish/publisher.py": """\
            from electionguard_tpu.publish import serialize

            def read_back(group, m):
                return serialize.import_p(group, m)
        """,
    })
    report = _run(project, ["ingestion-validation"])
    assert _lines(report, "ingestion-validation") == []


def test_tenant_label_fires_on_unlabeled_series(tmp_path):
    project = _project(tmp_path, {
        "serve/mod.py": """\
            from electionguard_tpu.obs.registry import election_labels


            def good_direct(registry):
                registry.counter("ballots_encrypted", election_labels())
                registry.histogram("request_latency_ms", (1.0,),
                                   election_labels({"election": "x"}))


            def good_indirect(registry):
                labels = election_labels()
                registry.counter("requests_admitted", labels)


            def bad(registry):
                registry.counter("ballots_encrypted")
                registry.histogram("request_latency_ms", (1.0,))
                registry.gauge("queue_depth")
        """,
        "core/other.py": """\
            def outside_tenant_dirs(registry):
                registry.counter("ballots_encrypted")
        """,
    })
    report = _run(project, ["tenant-label"])
    # only the unlabeled counter/histogram in a tenant dir fire; gauges
    # (process-scoped) and non-tenant dirs are exempt
    assert [(f.path, f.line) for f in report.findings] \
        == [("pkg/serve/mod.py", 16), ("pkg/serve/mod.py", 17)]

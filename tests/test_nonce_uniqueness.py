"""Nonce-reuse must be structurally impossible (VERDICT r2/r3 medium
finding): device-derived nonces were once keyed by batch position, so a
caller encrypting multiple chunks under one seed WITHOUT threading
``ballot_index_base`` silently reused R across chunks — identical ElGamal
pads leaking vote equality.  Nonces are now keyed by ballot identity
(SHA-256 of ballot_id); these tests pin that on the PRODUCTION group (the
device SHA-256 path the hazard lived in) by replaying the old footgun
call pattern and asserting every pad is distinct."""

from electionguard_tpu.ballot.plaintext import RandomBallotProvider
from electionguard_tpu.core.group import production_group
from electionguard_tpu.encrypt.encryptor import BatchEncryptor
from electionguard_tpu.keyceremony.exchange import key_ceremony_exchange
from electionguard_tpu.keyceremony.trustee import KeyCeremonyTrustee
from electionguard_tpu.publish.election_record import ElectionConfig
from electionguard_tpu.workflow.e2e import sample_manifest


def _production_election(nballots):
    g = production_group()
    manifest = sample_manifest(1, 2)
    trustees = [KeyCeremonyTrustee(g, "g0", 1, 1)]
    init = key_ceremony_exchange(trustees, g).make_election_initialized(
        ElectionConfig(manifest, 1, 1), {})
    ballots = list(RandomBallotProvider(manifest, nballots,
                                        seed=7).ballots())
    return g, init, ballots


def _all_pads(encrypted):
    return [s.ciphertext.pad.value
            for b in encrypted for c in b.contests for s in c.selections]


def test_chunked_seed_reuse_yields_distinct_pads():
    # the exact footgun: two chunks, one seed, NO ballot_index_base
    g, init, ballots = _production_election(4)
    enc = BatchEncryptor(init, g)
    seed = g.int_to_q(1234)
    e1, inv1 = enc.encrypt_ballots(ballots[:2], seed=seed)
    e2, inv2 = enc.encrypt_ballots(ballots[2:], seed=seed,
                                   code_seed=e1[-1].code)
    assert not inv1 and not inv2
    pads = _all_pads(e1) + _all_pads(e2)
    assert len(pads) == len(set(pads)), "ElGamal pad reused across chunks"


def test_duplicate_ballot_id_rejected():
    g, init, ballots = _production_election(2)
    enc = BatchEncryptor(init, g)
    dup = ballots[0]
    out, invalid = enc.encrypt_ballots([dup, ballots[1], dup],
                                       seed=g.int_to_q(5))
    assert len(out) == 2
    assert len(invalid) == 1 and "duplicate ballot id" in invalid[0][1]
    # ... and ACROSS chunks on the same encryptor: a repeated id in a
    # later encrypt_ballots call would replay the same nonce rows
    out2, invalid2 = enc.encrypt_ballots([dup], seed=g.int_to_q(5),
                                         code_seed=out[-1].code)
    assert not out2
    assert len(invalid2) == 1 and "duplicate ballot id" in invalid2[0][1]

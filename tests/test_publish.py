"""Serialization + election-record store roundtrip tests."""

import pytest

from electionguard_tpu.publish import pb, serialize
from electionguard_tpu.publish.publisher import (Consumer, Publisher,
                                                 election_record_from_consumer)
# the `election` fixture is session-scoped in tests/conftest.py


def test_primitive_roundtrips(tgroup):
    g = tgroup
    q = g.rand_q()
    assert serialize.import_q(g, serialize.publish_q(q)) == q
    p = g.g_pow_p(q)
    assert serialize.import_p(g, serialize.publish_p(p)) == p
    # wire widths enforced
    with pytest.raises(ValueError):
        serialize.import_p(g, pb.ElementModP(value=b"\x00"))
    with pytest.raises(ValueError):
        serialize.import_u256(pb.UInt256(value=b"short"))


def test_proof_roundtrips(tgroup):
    from electionguard_tpu.crypto.elgamal import ElGamalKeypair, elgamal_encrypt
    from electionguard_tpu.crypto.chaum_pedersen import \
        make_disjunctive_cp_proof
    from electionguard_tpu.crypto.schnorr import make_schnorr_proof
    from electionguard_tpu.crypto.hashed_elgamal import hashed_elgamal_encrypt
    g = tgroup
    kp = ElGamalKeypair.generate(g)
    sp = make_schnorr_proof(g, kp.secret_key, kp.public_key, g.rand_q())
    sp2 = serialize.import_schnorr(g, serialize.publish_schnorr(sp),
                                   sp.public_key)
    assert sp2 == sp and sp2.is_valid()
    n, ctx = g.rand_q(), g.int_to_q(5)
    ct = elgamal_encrypt(g, 1, n, kp.public_key)
    ct2 = serialize.import_ciphertext(g, serialize.publish_ciphertext(ct))
    assert ct2 == ct
    dp = make_disjunctive_cp_proof(g, ct, n, kp.public_key, ctx, 1, g.rand_q())
    dp2 = serialize.import_disjunctive_proof(
        g, serialize.publish_disjunctive_proof(dp))
    assert dp2 == dp and dp2.is_valid(ct2, kp.public_key, ctx)
    h = hashed_elgamal_encrypt(g, b"data bytes", g.rand_q(), kp.public_key)
    h2 = serialize.import_hashed_ciphertext(
        g, serialize.publish_hashed_ciphertext(h))
    assert h2 == h


def test_schnorr_reference_byte_layout(tgroup):
    """The wire-compat contract, byte-level: a reference-layout
    SchnorrProof (reserved 1-2, challenge=3, response=4, each an
    ElementModQ submessage) parses into this schema, and our encoder
    never emits the reserved field numbers (VERDICT r5 "What's missing"
    #2)."""
    from electionguard_tpu.crypto.schnorr import make_schnorr_proof
    from electionguard_tpu.crypto.elgamal import ElGamalKeypair
    g = tgroup
    kp = ElGamalKeypair.generate(g)
    sp = make_schnorr_proof(g, kp.secret_key, kp.public_key, g.rand_q())

    def q_submsg(e):  # ElementModQ { bytes value = 1; }
        payload = bytes([0x0A, len(e.to_bytes())]) + e.to_bytes()
        return payload

    # hand-assembled reference bytes: field 3 (tag 0x1A) challenge,
    # field 4 (tag 0x22) response, length-delimited submessages
    c, r = q_submsg(sp.challenge), q_submsg(sp.response)
    ref_bytes = (bytes([0x1A, len(c)]) + c + bytes([0x22, len(r)]) + r)
    parsed = serialize.pb.SchnorrProof.FromString(ref_bytes)
    sp2 = serialize.import_schnorr(g, parsed, sp.public_key)
    assert sp2 == sp and sp2.is_valid()
    # symmetric: our encoding IS the reference layout
    assert serialize.publish_schnorr(sp).SerializeToString() == ref_bytes
    # HashedElGamalCiphertext.c2 travels as width-checked UInt256
    c2_field = serialize.pb.HashedElGamalCiphertext.DESCRIPTOR \
        .fields_by_name["c2"]
    assert c2_field.message_type.name == "UInt256"
    with pytest.raises(ValueError):
        serialize.import_hashed_ciphertext(
            g, serialize.pb.HashedElGamalCiphertext(
                c0=serialize.publish_p(kp.public_key),
                c1=b"x", c2=serialize.pb.UInt256(value=b"short"),
                num_bytes=1))


def test_record_roundtrip_through_disk(election, tmp_path):  # noqa: F811
    g = election["group"]
    pub = Publisher(str(tmp_path / "record"))
    pub.write_election_initialized(election["init"])
    n = pub.write_encrypted_ballots(election["encrypted"])
    assert n == len(election["encrypted"])
    pub.write_tally_result(election["tally_result"])
    pub.write_decryption_result(election["decryption_result"])

    cons = Consumer(str(tmp_path / "record"), g)
    record = election_record_from_consumer(cons)
    assert record.election_init == election["init"]
    assert record.encrypted_ballots == election["encrypted"]
    assert record.tally_result == election["tally_result"]
    assert record.decryption_result == election["decryption_result"]


def test_roundtripped_record_verifies(election, tmp_path):  # noqa: F811
    from electionguard_tpu.verify.verifier import Verifier
    g = election["group"]
    pub = Publisher(str(tmp_path / "record"))
    pub.write_election_initialized(election["init"])
    pub.write_encrypted_ballots(election["encrypted"])
    pub.write_tally_result(election["tally_result"])
    pub.write_decryption_result(election["decryption_result"])
    record = election_record_from_consumer(
        Consumer(str(tmp_path / "record"), g))
    res = Verifier(record, g).verify()
    assert res.ok, res.summary()


def test_publisher_fail_fast(tmp_path):
    d = tmp_path / "out"
    d.mkdir()
    (d / "junk").write_text("x")
    with pytest.raises(FileExistsError):
        Publisher(str(d), create_new=True)
    Publisher(str(d), create_new=False)  # append mode fine


def test_plaintext_ballot_staging(election, tmp_path):  # noqa: F811
    pub = Publisher(str(tmp_path / "record"))
    for b in election["ballots"][:3]:
        pub.write_plaintext_ballot("plaintext_ballots", b)
    cons = Consumer(str(tmp_path / "record"), election["group"])
    back = list(cons.iterate_plaintext_ballots("plaintext_ballots"))
    assert back == sorted(election["ballots"][:3], key=lambda b: b.ballot_id)

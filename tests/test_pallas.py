"""Differential tests for the fused Pallas backend (core/pallas).

Everything runs the kernels under ``pallas_call(..., interpret=True)``
on the CPU backend — slow but bit-exact emulation of the kernel bodies
— so equality against the VPU CIOS kernel (``bignum_jax``) and the
unfused MXU engine (``ntt_mxu``) is asserted limb-for-limb, never
approximately.  Batches stay tiny and exponent ladders use reduced
exp_bits (the ``test_ntt_mxu`` sizing); the backend fallback chain and
the compile-once dispatch guarantee are pinned alongside the math.
"""

import warnings

import numpy as np
import pytest

import jax.numpy as jnp

from electionguard_tpu.core import bignum_jax as bn
from electionguard_tpu.core import ntt_mxu as nt
from electionguard_tpu.core.group_jax import JaxGroupOps, _default_backend
from electionguard_tpu.core.pallas import engine as pe


@pytest.fixture(scope="module")
def pctx(pgroup):
    return pe.make_pallas_ctx(pgroup.p)


def _rand_elems(g, k, seed=0):
    rng = np.random.default_rng(seed)
    out = [pow(g.g, int.from_bytes(rng.bytes(32), "big") % g.q, g.p)
           for _ in range(k - 4)]
    R = 1 << 4096
    return out + [0, 1, g.p - 1, (R - 1) % g.p]


def _limbs(xs):
    return jnp.asarray(bn.ints_to_limbs(xs, nt.NL))


# ---------------------------------------------------------------------------
# kernel-level differentials (production group, interpret mode)
# ---------------------------------------------------------------------------

def test_montmul_montsqr_bit_identical(pgroup, pctx):
    g = pgroup
    A = _limbs(_rand_elems(g, 6, seed=1))
    B = _limbs(_rand_elems(g, 6, seed=2))
    assert pctx.interpret  # CPU backend -> interpret-mode launches
    assert bool(jnp.all(pe.montmul(pctx, A, B)
                        == bn.montmul(pctx.mctx, A, B)))
    assert bool(jnp.all(pe.montsqr(pctx, A)
                        == bn.montmul(pctx.mctx, A, A)))


def test_montmul_matches_ntt_engine(pgroup, pctx):
    nctx = nt.make_ntt_ctx(pgroup.p)
    A = _limbs(_rand_elems(pgroup, 6, seed=3))
    B = _limbs(_rand_elems(pgroup, 6, seed=4))
    assert bool(jnp.all(pe.montmul(pctx, A, B)
                        == nt.montmul(nctx, A, B)))


def test_montmul_shared_matches_montmul(pgroup, pctx):
    A = _limbs(_rand_elems(pgroup, 4, seed=5))
    B = _limbs(_rand_elems(pgroup, 4, seed=6))
    C = _limbs(_rand_elems(pgroup, 4, seed=7))
    sel = jnp.stack([A, B, C], axis=1)              # (4, 3, NL)
    out = pe.montmul_shared(pctx, sel, B)
    for j in range(3):
        assert bool(jnp.all(out[:, j] == pe.montmul(pctx, sel[:, j], B)))


def test_nttfwd_and_hat_paths(pgroup, pctx):
    nctx = nt.make_ntt_ctx(pgroup.p)
    A = _limbs(_rand_elems(pgroup, 6, seed=8))
    B = _limbs(_rand_elems(pgroup, 6, seed=9))
    bh = pe.nttfwd(pctx, B)
    # forward evaluations are bit-identical to the unfused engine, so
    # hat tables are interchangeable between the ntt and pallas backends
    assert bool(jnp.all(bh == nt.nttfwd(nctx, B)))
    assert bool(jnp.all(pe.montmul_hat(pctx, A, bh)
                        == bn.montmul(pctx.mctx, A, B)))


def test_mont_pow_reduced_bits(pgroup, pctx):
    g = pgroup
    B = _limbs(_rand_elems(g, 6, seed=10))
    rng = np.random.default_rng(11)
    exps = [int(e) for e in rng.integers(0, 1 << 32, size=6)]
    E = jnp.asarray(bn.ints_to_limbs(exps, 2))
    got = pe.powmod(pctx, B, E, 32)
    want = bn.powmod(pctx.mctx, B, E, 32)
    assert bool(jnp.all(got == want))


def test_grid_blocking_and_odd_batches(pgroup):
    # a fresh ctx (not the lru-shared one) so mutating block is safe:
    # 17 rows with 8-row blocks = a 3-step grid with a padded tail
    ctx = pe.PallasCtx(pgroup.p)
    ctx.block = 8
    A = _limbs(_rand_elems(pgroup, 17, seed=12))
    B = _limbs(_rand_elems(pgroup, 17, seed=13))
    assert bool(jnp.all(pe.montmul(ctx, A, B)
                        == bn.montmul(ctx.mctx, A, B)))
    # odd batch below one block pads to the pow2 bucket
    assert bool(jnp.all(pe.montmul(ctx, A[:5], B[:5])
                        == bn.montmul(ctx.mctx, A[:5], B[:5])))


# ---------------------------------------------------------------------------
# backend selection / fallback chain
# ---------------------------------------------------------------------------

def test_default_backend_accepts_pallas(monkeypatch):
    monkeypatch.setenv("EGTPU_BIGNUM", "pallas")
    assert _default_backend() == "pallas"
    monkeypatch.setenv("EGTPU_BIGNUM", "bogus")
    with pytest.raises(ValueError, match="pallas"):
        _default_backend()


def test_fallback_tiny_group_to_cios(tgroup):
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        ops = JaxGroupOps(tgroup, backend="pallas")
    assert ops.backend == "cios"
    assert any("falling back to cios" in str(x.message) for x in w)
    # and the degraded backend still computes correctly
    assert ops.mulmod_ints([3, 5], [7, 11]) \
        == [21 % tgroup.p, 55 % tgroup.p]


def test_fallback_no_tpu_no_interpret_to_ntt(pgroup, monkeypatch):
    monkeypatch.delenv("EGTPU_PALLAS_INTERPRET", raising=False)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        ops = JaxGroupOps(pgroup, backend="pallas")
    assert ops.backend == "ntt"
    assert any("EGTPU_PALLAS_INTERPRET" in str(x.message) for x in w)


def test_unknown_backend_raises(tgroup):
    with pytest.raises(ValueError, match="unknown bignum backend"):
        JaxGroupOps(tgroup, backend="cuda")


# ---------------------------------------------------------------------------
# JaxGroupOps-level: zero call-site changes, tables, compile-once
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def pops(pgroup):
    """Production-group ops on the pallas backend (interpret mode)."""
    import os
    old = os.environ.get("EGTPU_PALLAS_INTERPRET")
    os.environ["EGTPU_PALLAS_INTERPRET"] = "1"
    try:
        yield JaxGroupOps(pgroup, backend="pallas")
    finally:
        if old is None:
            os.environ.pop("EGTPU_PALLAS_INTERPRET", None)
        else:
            os.environ["EGTPU_PALLAS_INTERPRET"] = old


def test_ops_pallas_backend_selected(pops):
    assert pops.backend == "pallas"
    assert pops._ms is not None and pops._mm_shared is not None
    assert pops._mm_hat is not None and pops._nttfwd is not None


def test_ops_mulmod_ints(pgroup, pops):
    xs = _rand_elems(pgroup, 5, seed=20)
    ys = _rand_elems(pgroup, 5, seed=21)
    assert pops.mulmod_ints(xs, ys) \
        == [x * y % pgroup.p for x, y in zip(xs, ys)]


def test_ops_hat_tables_built_by_pallas_nttfwd(pgroup, pops):
    # the PowRadix hat table is built through pallas nttfwd with zero
    # call-site changes, and matches the independent ntt-engine
    # transform row-for-row (cross-engine, not circular).  The full
    # jitted g_pow ladder is exercised on-chip by bench_bignum --ops
    # fixed; compiling its 32 inlined interpret kernels here costs
    # minutes of XLA time for no extra coverage.
    hat = pops.fixed_table_hat(pgroup.g)
    assert hat is not None
    assert hat.shape == (pops.nwin8, 256, 2, nt.NC)
    nctx = nt.make_ntt_ctx(pgroup.p)
    rows = pops.g_table.reshape(-1, nt.NL)[1:9]
    assert bool(jnp.all(hat.reshape(-1, 2, nt.NC)[1:9]
                        == nt.nttfwd(nctx, rows)))
    # one hat-row ladder step == the plain montmul against that row
    a = _limbs(_rand_elems(pgroup, 8, seed=24))
    assert bool(jnp.all(pops._mm_hat(a, hat.reshape(-1, 2, nt.NC)[1:9])
                        == bn.montmul(pops.ctx, a, rows)))


def test_ops_multi_pow_shared_reduced_bits(pgroup, pops):
    B = _limbs(_rand_elems(pgroup, 4, seed=22))
    rng = np.random.default_rng(23)
    exps = rng.integers(0, 1 << 16, size=(4, 3))
    E = jnp.asarray(np.stack(
        [bn.ints_to_limbs([int(e) for e in row], 1) for row in exps]))
    out = bn.multi_powmod_shared(pops.ctx, B, E, 16,
                                 montmul_fn=pops._mm,
                                 montsqr_fn=pops._ms,
                                 montmul_shared_fn=pops._mm_shared)
    ints = bn.limbs_to_ints(np.asarray(out).reshape(-1, nt.NL))
    bi = _rand_elems(pgroup, 4, seed=22)
    want = [pow(bi[i], int(exps[i, j]), pgroup.p)
            for i in range(4) for j in range(3)]
    assert ints == want


def test_second_dispatch_compiles_nothing(pgroup, pops):
    from electionguard_tpu.obs import jaxmon
    jaxmon.install()
    a = _limbs(_rand_elems(pgroup, 4, seed=30))
    b = _limbs(_rand_elems(pgroup, 4, seed=31))
    np.asarray(pops.mulmod(a, b))            # warm the (op, bucket) pair
    before = jaxmon.compile_count()
    np.asarray(pops.mulmod(b, a))            # same bucket, new data
    assert jaxmon.compile_count() == before

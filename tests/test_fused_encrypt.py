"""Fused device encryption (encrypt/fused.py) differential coverage.

The fused pipeline derives nonces, does all group math, and computes the
Fiat–Shamir challenges in ONE device program per tile; these tests pin
it against fully independent host paths on the production group:

* every proof it emits verifies with the SCALAR ``is_valid`` (host
  hash_elems + Python-int pow — shares no code with the device path),
* the ElGamal pads equal g^R for R recomputed through the host nonce
  twin (``_nonce_rows`` + ``_derive_nonce_ints``), pinning the on-device
  PRF byte layout,
* encryption is deterministic in (seed, ballot identity),
* the decrypted tally equals the plaintext vote sums (fixture decrypts
  through the direct path).

Reference analogue: ``batchEncryption(...)`` feeding ``Verifier`` —
src/test/java/electionguard/workflow/RunRemoteWorkflowTest.java:140,179.
"""

import hashlib

import numpy as np
import pytest

from electionguard_tpu.core.group_jax import jax_exp_ops
from electionguard_tpu.encrypt.encryptor import (BatchEncryptor,
                                                 _derive_nonce_ints,
                                                 _nonce_rows)

pytestmark = pytest.mark.slow


def test_scalar_proof_compat_production(pelection):
    """Device-generated proofs must satisfy the scalar verifiers."""
    g, init = pelection["group"], pelection["init"]
    qbar = init.extended_base_hash
    K = init.joint_public_key
    for b in pelection["encrypted"]:
        assert b.is_valid_code()
        for c in b.contests:
            assert c.proof.is_valid(c.accumulation(), K, qbar)
            for s in c.selections:
                assert s.proof.is_valid(s.ciphertext, K, qbar), \
                    s.selection_id


def test_pads_match_host_nonce_twin(pelection):
    """α = g^R with R recomputed via the host nonce-row twin: pins the
    fused program's on-device PRF (seed/tag/bid/ordinal layout) exactly."""
    g = pelection["group"]
    ee = jax_exp_ops(g)
    seed = g.int_to_q(11)  # the fixture's encryption seed
    for b in pelection["encrypted"]:
        bid = hashlib.sha256(b.ballot_id.encode()).digest()
        sels = [s for c in b.contests for s in c.selections]
        msgs = _nonce_rows(seed, np.zeros(len(sels), np.uint8),
                           np.frombuffer(bid * len(sels),
                                         np.uint8).reshape(-1, 32),
                           np.arange(len(sels), dtype=np.uint32))
        R_host = _derive_nonce_ints(g, ee, msgs)
        for s, r in zip(sels, R_host):
            assert s.ciphertext.pad.value == pow(g.g, r, g.p)


def test_encryption_deterministic(pelection):
    g, init = pelection["group"], pelection["init"]
    enc2 = BatchEncryptor(init, g)
    again, invalid = enc2.encrypt_ballots(pelection["ballots"],
                                          seed=g.int_to_q(11))
    assert not invalid
    for a, b in zip(pelection["encrypted"], again):
        # (codes differ: they hash the encryption timestamp; everything
        # seed-derived must be identical)
        for ca, cb in zip(a.contests, b.contests):
            assert ca.proof == cb.proof
            for sa, sb in zip(ca.selections, cb.selections):
                assert sa.ciphertext == sb.ciphertext
                assert sa.proof == sb.proof


def test_tally_matches_plaintext_production(pelection):
    want = {}
    for pb in pelection["ballots"]:
        for c in pb.contests:
            for s in c.selections:
                want[(c.contest_id, s.selection_id)] = \
                    want.get((c.contest_id, s.selection_id), 0) + s.vote
    decrypted = pelection["decryption_result"].decrypted_tally
    got = {(c.contest_id, s.selection_id): s.tally
           for c in decrypted.contests for s in c.selections}
    assert got == want

"""Dispatch tiling (EGTPU_TILE) must be transparent: batches above the
cap run as a loop of cap-shaped tiles, and results must be identical to
the single-dispatch path.  The cap exists so an arbitrary-size election
compiles a BOUNDED set of batch shapes instead of one multi-minute XLA
compile per power-of-two (the r4 TPU bench died in exactly those
compiles)."""

import numpy as np
import pytest

from electionguard_tpu.core.group_jax import JaxGroupOps
from electionguard_tpu.core import sha256_jax
from electionguard_tpu.core.group import production_group


@pytest.fixture
def tiny_tile(monkeypatch):
    monkeypatch.setenv("EGTPU_TILE", "16")


def test_group_ops_tiled_match_host(tgroup, tiny_tile):
    g = tgroup
    ops = JaxGroupOps(g)
    assert ops.tile == 16
    rng = np.random.default_rng(4)
    n = 45  # 2 full tiles + remainder
    bases = [1 + int.from_bytes(rng.bytes(16), "big") % (g.p - 1)
             for _ in range(n)]
    exps = [int.from_bytes(rng.bytes(16), "big") % g.q for _ in range(n)]
    assert ops.powmod_ints(bases, exps) == \
        [pow(b, e, g.p) for b, e in zip(bases, exps)]
    assert ops.g_pow_ints(exps) == [pow(g.g, e, g.p) for e in exps]
    assert ops.mulmod_ints(bases, bases) == \
        [b * b % g.p for b in bases]
    ok = np.asarray(ops.is_valid_residue(ops.to_limbs_p(
        [pow(g.g, e, g.p) for e in exps])))
    assert ok.all()


def test_sha_challenge_tiled_matches_untiled(monkeypatch):
    g = production_group()
    rng = np.random.default_rng(5)
    n = 37
    elem = rng.integers(0, 256, size=(n, g.spec.p_bytes), dtype=np.uint8)
    monkeypatch.setenv("EGTPU_TILE", "4096")
    want = np.asarray(sha256_jax.batch_challenge_p(g, b"ctx", [elem]))
    monkeypatch.setenv("EGTPU_TILE", "16")
    got = np.asarray(sha256_jax.batch_challenge_p(g, b"ctx", [elem]))
    np.testing.assert_array_equal(got, want)

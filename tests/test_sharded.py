"""Sharded-plane pins: the jax-0.4.37 concatenate repro + the sharded
shuffle/proof differential.

Two invariants the mixfed servers' ``-shards`` plane rests on:

* ``parallel/sharded._pad_rows`` must NEVER route a partially-replicated
  operand (dp-sharded on a wp>1 mesh) through device ``jnp.concatenate``
  — jax 0.4.37's CPU backend lowers that with a wrong row stride and
  silently corrupts the data.  The fix is a host detour; this file pins
  both the detour's correctness and (on affected jax builds) the raw
  corruption that makes it necessary.  ``__graft_entry__``'s multichip
  dryrun composes concatenate-free for the same reason.
* a ``ShardedGroupOps``-mounted shuffle stage must be BIT-IDENTICAL to
  the single-device stage — same permutation, same re-encryption
  randomness, same TW proof transcript — so a federated record never
  reveals which topology mixed it.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from electionguard_tpu.core.group import tiny_group
from electionguard_tpu.core.group_jax import jax_ops
from electionguard_tpu.mixnet.proof import rows_digest
from electionguard_tpu.mixnet.shuffle import Shuffler
from electionguard_tpu.mixnet.stage import run_stage
from electionguard_tpu.mixnet.verify_mix import verify_stage
from electionguard_tpu.parallel.mesh import DP_AXIS, WP_AXIS, election_mesh
from electionguard_tpu.parallel.sharded import (ShardedGroupOps,
                                                _pad_rows,
                                                _partially_replicated)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the virtual 8-device mesh")


def _dp_sharded_on_wp2_mesh(rows: int = 8, cols: int = 6):
    """An array committed dp-sharded on a (dp=4, wp=2) mesh — the
    partially-replicated layout whose concatenate lowering 0.4.37
    corrupts."""
    mesh = election_mesh(8, wp=2)
    x = np.arange(rows * cols, dtype=np.uint32).reshape(rows, cols)
    return mesh, x, jax.device_put(x, NamedSharding(mesh, P(DP_AXIS)))


def test_partially_replicated_detector():
    mesh, _, committed = _dp_sharded_on_wp2_mesh()
    # dp-sharded but wp-replicated: the wp axis (size 2) is unused
    assert _partially_replicated(committed)
    # plain numpy / uncommitted arrays: no sharding to misread
    assert not _partially_replicated(np.zeros((4, 4), np.uint32))
    # fully-specified placement (both axes used) is safe to concatenate
    both = jax.device_put(np.zeros((4, 8), np.uint32),
                          NamedSharding(mesh, P(DP_AXIS, WP_AXIS)))
    assert not _partially_replicated(both)


def test_pad_rows_detours_partially_replicated_operands():
    """The fix: padding a dp-sharded-on-wp2 array up to a row multiple
    must produce exactly the numpy reference, whatever the backend's
    concatenate lowering does."""
    _, x, committed = _dp_sharded_on_wp2_mesh(rows=12, cols=6)
    fill = np.full((6,), 9, np.uint32)
    want = np.concatenate([x, np.broadcast_to(fill, (4, 6))], axis=0)
    got = np.asarray(_pad_rows(committed, 8, fill))
    np.testing.assert_array_equal(got, want)
    # no-op padding keeps the committed array untouched
    even = np.asarray(_pad_rows(committed, 4, fill))
    np.testing.assert_array_equal(even, x)


def test_concatenate_corruption_repro_is_flagged():
    """The repro pin: on jax builds where device concatenate over the
    partially-replicated layout corrupts (0.4.37 CPU does), the operand
    MUST be one ``_partially_replicated`` flags — i.e. the detour
    engages exactly where the bug lives.  On fixed builds the raw path
    matching the reference is equally green; the invariant is that no
    corrupted layout ever goes unflagged."""
    _, x, committed = _dp_sharded_on_wp2_mesh(rows=8, cols=6)
    pad = jnp.zeros((2, 6), jnp.uint32)
    raw = np.asarray(jnp.concatenate([committed, pad], axis=0))
    want = np.concatenate([x, np.zeros((2, 6), np.uint32)], axis=0)
    if not np.array_equal(raw, want):
        # the 0.4.37 stride bug, live on this build
        assert _partially_replicated(committed), \
            "corrupting layout not flagged — _pad_rows would ship it"


def test_sharded_stage_bit_identical_and_verifies():
    """One TW mix stage through ``ShardedGroupOps`` on the full (dp=4,
    wp=2) virtual mesh vs the single-device plane, same seed: identical
    outputs, identical proof transcript, and the stage verifies green
    through BOTH planes."""
    g = tiny_group()
    ops = jax_ops(g)
    sops = ShardedGroupOps(ops, election_mesh(8, wp=2))
    K = pow(g.g, 12345, g.p)
    n, w = 7, 2
    pads = [[pow(g.g, i * w + j + 1, g.p) for j in range(w)]
            for i in range(n)]
    datas = [[pow(K, i * w + j + 1, g.p) for j in range(w)]
             for i in range(n)]
    qbar, seed = g.int_to_q(424242), b"sharded-differential"

    st1 = run_stage(g, K, qbar, 0, pads, datas, seed=seed,
                    shuffler=Shuffler(g, K))
    st2 = run_stage(g, K, qbar, 0, pads, datas, seed=seed,
                    shuffler=Shuffler(g, K, ops=sops))
    assert st1.pads == st2.pads and st1.datas == st2.datas
    assert st1.proof == st2.proof

    class _Res:
        def __init__(self):
            self.failures = []

        def record(self, name, ok, msg=""):
            if not ok:
                self.failures.append((name, msg))

    ih = rows_digest(g, pads, datas)
    for plane in (sops, None):
        res = _Res()
        assert verify_stage(g, K, qbar, st2, pads, datas, ih, res,
                            ops=plane), res.failures

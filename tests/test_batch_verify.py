"""Differential pinning of RLC batch verification (EGTPU_VERIFY_BATCH).

The batch verifier is an accept SCREEN, never a new accept path: a
record it accepts satisfies the per-row hash binding of every
commitment hint AND the random-linear-combination equation (two MSMs),
and anything it rejects is re-judged by the naive per-proof verifier,
which owns error attribution.  These tests pin, on the tiny group:

* accept-set equality — the per-check verdicts with the flag on equal
  the flag-off verdicts on an honest record;
* every existing tamper class stays red under batch: V4 ciphertext
  swap, V4 response tamper, V5 challenge tamper, V2 Schnorr response
  tamper, and the three mixnet classes (binding, re-encryption, chain);
* Schnorr RLC bisection names exactly the corrupted proof;
* the membership RLC deterministically rejects an order-2 element.

Soundness budget (verify/rlc.py module docstring): a false equation
survives the RLC with probability <= 2^-127 over the verifier's odd
128-bit randomizers; hints are unserialized and hash-bound, so stale
hints after ``dataclasses.replace`` tampering go hash-red and drop to
the naive path deterministically — which is exactly what these tamper
tests exercise.
"""

import dataclasses
import os
from unittest import mock

import pytest

from electionguard_tpu.core.group import tiny_group
from electionguard_tpu.crypto.schnorr import (batch_schnorr_verify,
                                              make_schnorr_proof)
from electionguard_tpu.mixnet import verify_mix
from electionguard_tpu.obs import REGISTRY
from electionguard_tpu.obs.registry import election_labels
from electionguard_tpu.publish.election_record import ElectionRecord
from electionguard_tpu.verify import rlc
from electionguard_tpu.verify.verifier import VerificationResult, Verifier

_ON = {"EGTPU_VERIFY_BATCH": "1"}


@pytest.fixture(scope="module")
def batch_election(election):
    """The session election re-encrypted with the flag on: the seed is
    identical, so ciphertexts and proofs are byte-identical to the
    fixture's (tally/decryption results stay reusable) — the only
    difference is that every proof now carries commitment hints."""
    from electionguard_tpu.encrypt.encryptor import BatchEncryptor

    g = election["group"]
    with mock.patch.dict(os.environ, _ON):
        enc = BatchEncryptor(election["init"], g)
        encrypted, invalid = enc.encrypt_ballots(
            election["ballots"], seed=g.int_to_q(99))
    assert not invalid
    s_new = encrypted[0].contests[0].selections[0]
    s_old = election["encrypted"][0].contests[0].selections[0]
    assert s_new.ciphertext == s_old.ciphertext  # determinism holds
    assert s_new.proof.commitment_hints is not None
    assert encrypted[0].contests[0].proof.commitment_hints is not None
    return {**election, "encrypted": encrypted}


def _record(e, **overrides):
    kw = dict(election_init=e["init"],
              encrypted_ballots=list(e["encrypted"]),
              tally_result=e["tally_result"],
              decryption_result=e["decryption_result"])
    kw.update(overrides)
    return ElectionRecord(**kw)


def _verify_on(record, g):
    with mock.patch.dict(os.environ, _ON):
        return Verifier(record, g).verify()


def test_batch_accept_set_equals_naive(batch_election):
    g = batch_election["group"]
    naive = Verifier(_record(batch_election), g).verify()
    batches0 = REGISTRY.counter("verify_rlc_batches_total").value
    batch = _verify_on(_record(batch_election), g)
    assert batch.ok, batch.summary()
    assert batch.checks == naive.checks
    # the batch path actually ran (V4 + V5 + the two V2 ceremony calls)
    assert REGISTRY.counter("verify_rlc_batches_total").value > batches0


def test_batch_rejects_v4_ciphertext_swap(batch_election):
    """Swapped ciphertexts leave the hints stale: the hash binding goes
    red, the chunk falls back, and the naive path attributes the row."""
    g = batch_election["group"]
    record = _record(batch_election)
    b = record.encrypted_ballots[1]
    c = b.contests[0]
    s0, s1 = c.selections[0], c.selections[1]
    record.encrypted_ballots[1] = dataclasses.replace(
        b, contests=(dataclasses.replace(c, selections=(
            dataclasses.replace(s0, ciphertext=s1.ciphertext),
            dataclasses.replace(s1, ciphertext=s0.ciphertext))
            + tuple(c.selections[2:])),) + tuple(b.contests[1:]))
    falls = REGISTRY.counter("verify_rlc_fallbacks_total",
                             election_labels())
    falls0 = falls.value
    res = _verify_on(record, g)
    assert not res.checks["V4.selection_proofs"]
    assert falls.value > falls0


def test_batch_rejects_v4_response_tamper(batch_election):
    """A tampered response keeps the hash binding green (the hint and
    challenge are untouched) but fails the RLC equation itself."""
    g = batch_election["group"]
    record = _record(batch_election)
    b = record.encrypted_ballots[2]
    c = b.contests[0]
    s0 = c.selections[0]
    bad = dataclasses.replace(
        s0, proof=dataclasses.replace(
            s0.proof, proof_zero_response=g.add_q(
                s0.proof.proof_zero_response, g.ONE_MOD_Q)))
    record.encrypted_ballots[2] = dataclasses.replace(
        b, contests=(dataclasses.replace(
            c, selections=(bad,) + tuple(c.selections[1:])),)
        + tuple(b.contests[1:]))
    res = _verify_on(record, g)
    assert not res.checks["V4.selection_proofs"]
    assert any("disjunctive proof fails" in e for e in res.errors)


def test_batch_rejects_v5_challenge_tamper(batch_election):
    g = batch_election["group"]
    record = _record(batch_election)
    b = record.encrypted_ballots[0]
    c = b.contests[0]
    bad_proof = dataclasses.replace(
        c.proof, challenge=g.add_q(c.proof.challenge, g.ONE_MOD_Q))
    record.encrypted_ballots[0] = dataclasses.replace(
        b, contests=(dataclasses.replace(c, proof=bad_proof),)
        + tuple(b.contests[1:]))
    res = _verify_on(record, g)
    assert not res.checks["V5.contest_limits"]
    assert res.checks["V4.selection_proofs"]  # selections untouched


def test_batch_rejects_v2_schnorr_tamper(batch_election):
    g = batch_election["group"]
    init = batch_election["init"]
    gr = init.guardians[0]
    pr = gr.coefficient_proofs[0]
    bad_pr = dataclasses.replace(
        pr, response=g.add_q(pr.response, g.ONE_MOD_Q))
    bad_gr = dataclasses.replace(
        gr, coefficient_proofs=(bad_pr,) + gr.coefficient_proofs[1:])
    bad_init = dataclasses.replace(
        init, guardians=(bad_gr,) + init.guardians[1:])
    res = _verify_on(_record(batch_election, election_init=bad_init), g)
    assert not res.checks["V2.guardian_keys"]


def test_schnorr_bisection_names_offender(tgroup):
    """One tampered response among 8 proofs: every hint still
    hash-binds, the batch RLC rejects, and the bisection isolates
    exactly the corrupted index (leaf oracle = per-proof is_valid)."""
    g = tgroup
    proofs = []
    for i in range(8):
        s = g.int_to_q(1000 + i)
        proofs.append(make_schnorr_proof(
            g, s, g.g_pow_p(s), g.int_to_q(7000 + i)))
    bad = proofs[5]
    proofs[5] = dataclasses.replace(
        bad, response=g.add_q(bad.response, g.ONE_MOD_Q))
    assert proofs[5].commitment_hint == bad.commitment_hint  # stale, binds
    falls0 = REGISTRY.counter("verify_rlc_fallbacks_total").value
    with mock.patch.dict(os.environ, _ON):
        ok, sub_ok = batch_schnorr_verify(g, proofs, check_subgroup=True)
    assert list(ok) == [i != 5 for i in range(8)]
    assert sub_ok.all()
    assert REGISTRY.counter("verify_rlc_fallbacks_total").value > falls0


def test_schnorr_batch_matches_naive_flag_off(tgroup):
    g = tgroup
    proofs = [make_schnorr_proof(g, g.int_to_q(300 + i),
                                 g.g_pow_p(g.int_to_q(300 + i)),
                                 g.int_to_q(900 + i)) for i in range(5)]
    naive = batch_schnorr_verify(g, proofs)
    with mock.patch.dict(os.environ, _ON):
        batch = batch_schnorr_verify(g, proofs)
    assert list(naive) == list(batch) == [True] * 5


def test_membership_rlc_rejects_order_two_element(tgroup):
    """p-1 has order 2 in Z_p^*: the ODD randomizers expose it
    deterministically, not just with probability 1/2."""
    from electionguard_tpu.core.group_jax import jax_ops

    g = tgroup
    ops = jax_ops(g)
    good = [pow(g.g, e, g.p) for e in (3, 5, 9)]
    assert rlc.membership_rlc(ops, good)
    assert not rlc.membership_rlc(ops, good + [g.p - 1])
    assert not rlc.membership_rlc(ops, [0])      # out of range
    assert rlc.membership_rlc(ops, [])


@pytest.mark.slow
def test_batch_production_fused_path(pelection):
    """Production group: the batch path's hash binding runs the fused
    device SHA programs (v4_hint_hash/v5_hint_hash).  Accept set equals
    naive, and a tampered response still goes red under batch."""
    g = pelection["group"]
    from electionguard_tpu.encrypt.encryptor import BatchEncryptor

    with mock.patch.dict(os.environ, _ON):
        enc = BatchEncryptor(pelection["init"], g)
        encrypted, invalid = enc.encrypt_ballots(
            pelection["ballots"], seed=g.int_to_q(11))
    assert not invalid
    e = {**pelection, "encrypted": encrypted}
    s_new = encrypted[0].contests[0].selections[0]
    assert s_new.proof.commitment_hints is not None
    naive = Verifier(_record(e), g).verify()
    batch = _verify_on(_record(e), g)
    assert batch.ok, batch.summary()
    assert batch.checks == naive.checks

    record = _record(e)
    b = record.encrypted_ballots[0]
    c = b.contests[0]
    s0 = c.selections[0]
    bad = dataclasses.replace(
        s0, proof=dataclasses.replace(
            s0.proof, proof_zero_response=g.add_q(
                s0.proof.proof_zero_response, g.ONE_MOD_Q)))
    record.encrypted_ballots[0] = dataclasses.replace(
        b, contests=(dataclasses.replace(
            c, selections=(bad,) + tuple(c.selections[1:])),))
    res = _verify_on(record, g)
    assert not res.checks["V4.selection_proofs"]


# ---------------------------------------------------------------------------
# mixnet (V15): the three tamper classes stay red under batch
# ---------------------------------------------------------------------------

def test_mix_batch_honest_and_tampered():
    """Honest cascade green under batch; tampered-output (binding),
    wrong-permutation (re-encryption) and replayed-transcript (chain)
    classes each stay red with the same layer attribution as naive."""
    import copy

    from tests.test_mixnet import (_encrypt_rows, _qbar,
                                   _two_stage_cascade, _Init)
    from electionguard_tpu.crypto.elgamal import ElGamalKeypair
    from electionguard_tpu.mixnet.proof import prove_shuffle, rows_digest
    from electionguard_tpu.mixnet.shuffle import Shuffler
    from electionguard_tpu.mixnet.stage import MixStage, run_stage

    g = tiny_group()
    kp = ElGamalKeypair.from_secret(g.int_to_q(987654321))
    K, qbar = kp.public_key, _qbar(g)
    pads, datas, stages = _two_stage_cascade(g, K, qbar)
    init = _Init(K, qbar)

    with mock.patch.dict(os.environ, _ON):
        batches0 = REGISTRY.counter("verify_rlc_batches_total").value
        res = VerificationResult()
        assert verify_mix.verify_stages(g, init, stages, res,
                                        lambda: (pads, datas))
        assert res.ok, res.summary()
        assert REGISTRY.counter(
            "verify_rlc_batches_total").value > batches0

        # binding: output ciphertext modified after proving
        bad = copy.deepcopy(stages[1])
        bad.pads[0][0] = bad.pads[0][0] * g.g % g.p
        res = VerificationResult()
        assert not verify_mix.verify_stages(
            g, init, [stages[0], bad], res, lambda: (pads, datas))
        assert not res.checks["V15.mix_binding"]

        # re-encryption: outputs don't follow the committed permutation
        pads2, datas2 = _encrypt_rows(g, K, 16, 2)
        sh = Shuffler(g, K.value)
        out_p, out_d, perm, rand = sh.shuffle(pads2, datas2, b"cheat")
        out_p[0], out_p[1] = out_p[1], out_p[0]
        out_d[0], out_d[1] = out_d[1], out_d[0]
        ih = rows_digest(g, pads2, datas2)
        proof = prove_shuffle(g, K.value, qbar, 0, pads2, datas2,
                              out_p, out_d, perm, rand, b"cheat",
                              input_hash=ih)
        cheat = MixStage(0, 16, 2, ih, out_p, out_d, proof)
        res = VerificationResult()
        assert not verify_mix.verify_stages(
            g, init, [cheat], res, lambda: (pads2, datas2))
        assert not res.checks["V15.mix_reencryption"]
        assert res.checks["V15.mix_binding"]  # transcript DID bind

        # chain: transcript replayed from a different input
        other_p, other_d = _encrypt_rows(g, K, 16, 2, seed=9999)
        replay = run_stage(g, K.value, qbar, 1, other_p, other_d,
                           seed=b"replay")
        res = VerificationResult()
        assert not verify_mix.verify_stages(
            g, init, [stages[0], replay], res, lambda: (pads, datas))
        assert not res.checks["V15.mix_chain"]

"""Sharded serving fabric: router registration and routing, signed
shard manifests, the verifiable merge, and the ``V.shard_manifest``
verifier family.

One module-scoped fleet (router + 2 in-process shard services on the
tiny group) drives 8 ballots through the front door, drains, and merges
— the assertion tests then pick the run apart.  The three adversarial
manifest-tampering cases the acceptance criteria name (overlapping
shard ranges, gapped chain, forged manifest signature) each pin their
own named ``V.shard_manifest.*`` check going red.
"""

import dataclasses
import json
import os
import shutil

import pytest

from electionguard_tpu.ballot.plaintext import RandomBallotProvider
from electionguard_tpu.fabric import manifest as fab_manifest
from electionguard_tpu.fabric.merge import (MergeError, merge_shard_records,
                                            merge_sub_tallies)
from electionguard_tpu.fabric.router import EncryptionRouter
from electionguard_tpu.keyceremony.exchange import key_ceremony_exchange
from electionguard_tpu.keyceremony.trustee import KeyCeremonyTrustee
from electionguard_tpu.publish import pb
from electionguard_tpu.publish.election_record import ElectionConfig
from electionguard_tpu.publish.publisher import (Consumer,
                                                 election_record_from_consumer)
from electionguard_tpu.remote import rpc_util
from electionguard_tpu.serve.service import (EncryptionClient,
                                             EncryptionService)
from electionguard_tpu.tally.accumulate import accumulate_ballots
from electionguard_tpu.verify.verifier import Verifier
from tests.test_keyceremony import tiny_manifest

NBALLOTS = 8


@pytest.fixture(scope="module")
def fab_init(tgroup):
    trustees = [KeyCeremonyTrustee(tgroup, f"guardian-{i}", i + 1, 2)
                for i in range(3)]
    return key_ceremony_exchange(trustees, tgroup).make_election_initialized(
        ElectionConfig(tiny_manifest(), 3, 2), {"created_by": "test_fabric"})


def _register(router_url, group, worker_id, url, public_key, nonce):
    ch = rpc_util.make_channel(router_url)
    try:
        return rpc_util.Stub(ch, "FabricRegistrationService").call(
            "registerEncryptionWorker",
            pb.RegisterEncryptionWorkerRequest(
                worker_id=worker_id, remote_url=url,
                group_fingerprint=group.fingerprint(),
                registration_nonce=nonce,
                manifest_public_key=public_key))
    finally:
        ch.close()


@pytest.fixture(scope="module")
def fleet(tgroup, fab_init, tmp_path_factory):
    """Router + 2 shard services, NBALLOTS routed through the front
    door, graceful drain, verifiable merge — the artifacts every test
    below asserts on."""
    g = tgroup
    tmp = tmp_path_factory.mktemp("fabric")
    router = EncryptionRouter(g, health_interval=0.2, health_timeout=2.0)
    services = []
    try:
        for i in range(2):
            wid = f"w{i}"
            kp = fab_manifest.ManifestKeypair.generate(g)
            svc_port = rpc_util.find_free_port()
            pk = kp.public.value.to_bytes(
                (kp.public.value.bit_length() + 7) // 8 or 1, "big")
            resp = _register(router.url, g, wid, f"localhost:{svc_port}",
                             pk, os.urandom(16))
            assert not resp.error, resp.error
            sid = resp.shard_id
            svc = EncryptionService(
                fab_init, g, port=svc_port,
                out_dir=str(tmp / f"shard{sid}"),
                max_batch=8, max_wait_ms=10, seed=g.int_to_q(42),
                timestamp=1754_000_000, shard_id=sid, worker_id=wid,
                chain_seed=fab_manifest.shard_chain_seed(
                    fab_init.manifest_hash, sid),
                skip_ballot_ids=list(resp.requeued_ballot_ids),
                manifest_keypair=kp)
            services.append(svc)
        assert router.wait_for_workers(2, timeout=60, live=True), \
            router.snapshot()

        client = EncryptionClient(router.url, g)
        ballots = list(RandomBallotProvider(
            tiny_manifest(), NBALLOTS, seed=7).ballots())
        seen_shards = set()
        encrypted = []
        for b in ballots[:4]:
            enc = client.encrypt(b)
            assert enc is not None
            encrypted.append(enc)
            seen_shards.add(client.last_shard_id)
        res = client.encrypt_batch(ballots[4:])
        assert all(e is not None for e, _ in res), res
        encrypted.extend(e for e, _ in res)
        seen_shards.add(client.last_shard_id)
        health = client.health()
        client.close()

        manifests = {}
        for svc in services:
            svc.drain()
            m = fab_manifest.read_shard_manifest(svc.publisher.dir)
            manifests[m.shard_id] = m
        shard_dirs = [svc.publisher.dir for svc in services]
        merged = str(tmp / "merged")
        report = merge_shard_records(g, shard_dirs, merged)

        yield {
            "g": g, "init": fab_init, "router": router,
            "seen_shards": seen_shards, "health": health,
            "encrypted": encrypted, "manifests": manifests,
            "shard_dirs": shard_dirs, "merged": merged, "report": report,
            "tmp": tmp,
        }
    finally:
        for svc in services:
            svc.shutdown()
        router.shutdown()


# =====================================================================
# routing plane
# =====================================================================


def test_routing_spreads_across_both_shards(fleet):
    # least-queue-depth with round-robin tiebreak: 8 sequential/batch
    # requests against two idle shards must not pin to one
    assert fleet["seen_shards"] == {0, 1}
    snap = {s["shard_id"]: s for s in fleet["router"].snapshot()}
    assert set(snap) == {0, 1}
    assert all(s["forwarded"] > 0 for s in snap.values())


def test_router_health_is_fleet_aggregate(fleet):
    # the front door answers health for the FLEET: shard_id=-1 marks
    # the routing plane (a worker answers with its own shard id)
    assert fleet["health"].status == "SERVING"
    assert fleet["health"].shard_id == -1


def test_registration_nonce_is_idempotent(tgroup):
    router = EncryptionRouter(tgroup, health_interval=30.0)
    # registration gates the manifest key, so the placeholders must be
    # genuine subgroup elements (g^2, g^3)
    k1 = pow(tgroup.g, 2, tgroup.p).to_bytes(tgroup.spec.p_bytes, "big")
    k2 = pow(tgroup.g, 3, tgroup.p).to_bytes(tgroup.spec.p_bytes, "big")
    try:
        nonce = os.urandom(16)
        r1 = _register(router.url, tgroup, "wx", "localhost:1", k1,
                       nonce)
        # lost-response retry: same (worker, nonce, url) replays the
        # SAME shard assignment instead of minting a second shard
        r2 = _register(router.url, tgroup, "wx", "localhost:1", k1,
                       nonce)
        assert not r1.error and not r2.error
        assert r1.shard_id == r2.shard_id
        # same id, same nonce, DIFFERENT url: refused (two live workers
        # can't share an identity)
        r3 = _register(router.url, tgroup, "wx", "localhost:2", k1,
                       nonce)
        assert "already registered" in r3.error
        # fresh nonce: a relaunched worker reclaims its shard
        r4 = _register(router.url, tgroup, "wx", "localhost:2", k1,
                       os.urandom(16))
        assert not r4.error and r4.shard_id == r1.shard_id
        # a different worker gets the next shard
        r5 = _register(router.url, tgroup, "wy", "localhost:3", k2,
                       os.urandom(16))
        assert r5.shard_id == r1.shard_id + 1
    finally:
        router.shutdown()


# =====================================================================
# signed shard manifests + merge
# =====================================================================


def test_shard_manifests_signed_and_seeded(fleet):
    g, init = fleet["g"], fleet["init"]
    assert set(fleet["manifests"]) == {0, 1}
    total = 0
    for sid, m in fleet["manifests"].items():
        assert fab_manifest.verify_manifest_signature(g, m)
        assert m.chain_seed == fab_manifest.shard_chain_seed(
            init.manifest_hash, sid)
        assert m.admitted_count > 0
        total += m.admitted_count
    assert total == NBALLOTS


def test_merge_produces_complete_record(fleet):
    assert fleet["report"].n_shards == 2
    assert fleet["report"].n_ballots == NBALLOTS
    rec = election_record_from_consumer(Consumer(fleet["merged"],
                                                 fleet["g"]))
    assert len(rec.encrypted_ballots) == NBALLOTS
    assert [m.shard_id for m in rec.shard_manifests] == [0, 1]
    # every ballot routed through the front door is in the merged record
    merged_ids = {b.ballot_id for b in rec.encrypted_ballots}
    assert merged_ids == {b.ballot_id for b in fleet["encrypted"]}


def test_sub_tally_merge_is_homomorphic(fleet):
    # per-shard sub-tallies added component-wise == one accumulate over
    # the merged stream (the whole point of merging ciphertexts)
    g, init = fleet["g"], fleet["init"]
    subs = [accumulate_ballots(init,
                               Consumer(d, g).iterate_encrypted_ballots())
            for d in fleet["shard_dirs"]]
    merged_tally = merge_sub_tallies(g, subs)
    direct = accumulate_ballots(
        init, Consumer(fleet["merged"], g).iterate_encrypted_ballots())
    assert merged_tally.encrypted_tally == direct.encrypted_tally


def test_merge_refuses_tampered_admitted_count(fleet):
    # tamper on a COPY so the shared fixture dirs stay pristine
    g = fleet["g"]
    tdir = str(fleet["tmp"] / "tampered-shard0")
    shutil.copytree(fleet["shard_dirs"][0], tdir)
    mpath = os.path.join(tdir, "shard_manifest.json")
    with open(mpath) as f:
        md = json.load(f)
    md["admitted_count"] += 1
    with open(mpath, "w") as f:
        json.dump(md, f)
    with pytest.raises(MergeError):
        merge_shard_records(g, [tdir, fleet["shard_dirs"][1]],
                            str(fleet["tmp"] / "merged-tampered"))


# =====================================================================
# V.shard_manifest verifier family
# =====================================================================


def _verify_with(fleet, manifests=None, ballots=None):
    rec = election_record_from_consumer(Consumer(fleet["merged"],
                                                 fleet["g"]))
    if manifests is not None:
        rec.shard_manifests = manifests
    if ballots is not None:
        rec.encrypted_ballots = ballots
    return Verifier(rec, fleet["g"]).verify()


def test_merged_record_verifies_green(fleet):
    res = _verify_with(fleet)
    assert res.ok, res.summary()
    for check in ("signature", "seed", "chain", "overlap", "complete"):
        assert res.checks.get(f"V.shard_manifest.{check}") is True, \
            res.summary()


def test_forged_manifest_signature_goes_red(fleet):
    # adversarial case 1: forged manifest (claims one more admission
    # than the trustee-signed statement covers)
    ms = list(election_record_from_consumer(
        Consumer(fleet["merged"], fleet["g"])).shard_manifests)
    forged = [dataclasses.replace(
        ms[0], admitted_count=ms[0].admitted_count + 1)] + ms[1:]
    res = _verify_with(fleet, manifests=forged)
    assert res.checks["V.shard_manifest.signature"] is False
    assert not res.ok


def test_gapped_chain_goes_red(fleet):
    # adversarial case 2: a mid-chain ballot quietly dropped from the
    # published stream — its shard's chain is no longer contiguous
    balls = list(Consumer(fleet["merged"],
                          fleet["g"]).iterate_encrypted_ballots())
    gapped = balls[:4] + balls[5:]
    res = _verify_with(fleet, ballots=gapped)
    assert res.checks["V.shard_manifest.chain"] is False
    assert not res.ok


def test_overlapping_shard_ranges_go_red(fleet):
    # adversarial case 3: the same ballot published under two chains
    # (double-counted admission)
    balls = list(Consumer(fleet["merged"],
                          fleet["g"]).iterate_encrypted_ballots())
    res = _verify_with(fleet, ballots=balls + [balls[0]])
    assert res.checks["V.shard_manifest.overlap"] is False
    assert not res.ok


def test_wrong_chain_seed_goes_red(fleet):
    ms = election_record_from_consumer(
        Consumer(fleet["merged"], fleet["g"])).shard_manifests
    bad = [dataclasses.replace(ms[0], chain_seed=b"\x00" * 32)] + ms[1:]
    res = _verify_with(fleet, manifests=bad)
    assert res.checks["V.shard_manifest.seed"] is False
    assert not res.ok


def test_feeder_partial_verify_stays_green(fleet):
    # the streaming-verify path (cli/run_verifier feeder) must carry
    # the shard machinery: partials merged + finalized == one-shot green
    from electionguard_tpu.verify.verifier import (VerificationResult,
                                                   _BallotAggregates)
    g = fleet["g"]
    rec = election_record_from_consumer(Consumer(fleet["merged"], g))
    balls = rec.encrypted_ballots
    v = Verifier(rec, g)
    parts = []
    for lo, hi, prev in ((0, 3, None), (3, NBALLOTS, balls[2].code)):
        pr, pa = VerificationResult(), _BallotAggregates()
        v.verify_ballots_partial(list(balls[lo:hi]), pr, pa,
                                 prev_code=prev)
        parts.append((pr, pa))
    mres, magg = Verifier.merge_partials(parts)
    mres = v.finalize(mres, magg)
    assert mres.ok, mres.summary()


# =====================================================================
# manifest primitives + egtop shard rows
# =====================================================================


def test_manifest_sign_verify_tamper(tgroup):
    kp = fab_manifest.ManifestKeypair.generate(tgroup)
    m = fab_manifest.sign_manifest(tgroup, kp, fab_manifest.ShardManifest(
        shard_id=3, worker_id="w3", chain_seed=b"\x11" * 32,
        head_hash=b"\x22" * 32, admitted_count=5,
        public_key=kp.public.value))
    assert fab_manifest.verify_manifest_signature(tgroup, m)
    # an unsigned manifest never verifies
    assert not fab_manifest.verify_manifest_signature(
        tgroup, dataclasses.replace(m, signature=None))
    for field, value in (("admitted_count", 6), ("worker_id", "w4"),
                         ("head_hash", b"\x23" * 32), ("shard_id", 4)):
        assert not fab_manifest.verify_manifest_signature(
            tgroup, dataclasses.replace(m, **{field: value}))
    # dict round-trip preserves the signature
    again = fab_manifest.ShardManifest.from_dict(m.to_dict())
    assert fab_manifest.verify_manifest_signature(tgroup, again)


def test_egtop_parses_shard_heartbeat_phase():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "egtop", os.path.join(os.path.dirname(__file__), os.pardir,
                              "tools", "egtop.py"))
    egtop = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(egtop)
    s = egtop.parse_shard("serving shard=2 head=00ddc0ffee123456 "
                          "admitted=41")
    assert s == {"shard": 2, "head": "00ddc0ffee123456", "admitted": 41}
    assert egtop.parse_shard("mixing round 3") is None
    assert egtop.parse_shard("") is None
    assert egtop.parse_shard("serving shard=x head=y admitted=z") is None

"""Sharded plane vs single-chip plane: identical results on an 8-device
virtual CPU mesh (conftest forces xla_force_host_platform_device_count=8)."""

import numpy as np
import pytest

import jax

from electionguard_tpu.core.group_jax import jax_ops
from electionguard_tpu.parallel import (ShardedGroupOps, election_mesh,
                                        single_device_mesh)


@pytest.fixture(scope="module")
def meshes():
    n = len(jax.devices())
    assert n == 8, f"conftest should provide 8 virtual devices, got {n}"
    return {
        "dp8": election_mesh(8, wp=1),
        "dp4wp2": election_mesh(8, wp=2),
        "single": single_device_mesh(),
    }


@pytest.fixture(scope="module")
def tops(tgroup):
    return jax_ops(tgroup)


def _rand_elems(group, rng, k):
    # random subgroup members g^e (valid residues)
    return [pow(group.g, int(e), group.p)
            for e in rng.integers(1, group.q, size=k)]


@pytest.mark.parametrize("mesh_name", ["dp8", "dp4wp2", "single"])
@pytest.mark.parametrize("batch", [8, 16, 5])  # 5 exercises padding
def test_sharded_powmod_matches(tgroup, tops, meshes, mesh_name, batch):
    rng = np.random.default_rng(42)
    sops = ShardedGroupOps(tops, meshes[mesh_name])
    bases = _rand_elems(tgroup, rng, batch)
    exps = [int(e) for e in rng.integers(0, tgroup.q, size=batch)]
    want = [pow(b, e, tgroup.p) for b, e in zip(bases, exps)]
    got = sops.powmod_ints(bases, exps)
    assert got == want


@pytest.mark.parametrize("mesh_name", ["dp8", "dp4wp2"])
def test_sharded_g_pow_matches(tgroup, tops, meshes, mesh_name):
    rng = np.random.default_rng(7)
    sops = ShardedGroupOps(tops, meshes[mesh_name])
    exps = [int(e) for e in rng.integers(0, tgroup.q, size=11)]
    want = [pow(tgroup.g, e, tgroup.p) for e in exps]
    assert sops.g_pow_ints(exps) == want


@pytest.mark.parametrize("mesh_name", ["dp8", "dp4wp2"])
def test_sharded_base_pow_matches(tgroup, tops, meshes, mesh_name):
    rng = np.random.default_rng(3)
    sops = ShardedGroupOps(tops, meshes[mesh_name])
    K = pow(tgroup.g, 12345 % tgroup.q, tgroup.p)
    exps = [int(e) for e in rng.integers(0, tgroup.q, size=9)]
    want = [pow(K, e, tgroup.p) for e in exps]
    got = sops.from_limbs(sops.base_pow(K, sops.to_limbs_q(exps)))
    assert got == want


@pytest.mark.parametrize("mesh_name", ["dp8", "dp4wp2", "single"])
@pytest.mark.parametrize("m", [8, 16, 13])  # 13 exercises dp padding
def test_sharded_prod_reduce_matches(tgroup, tops, meshes, mesh_name, m):
    rng = np.random.default_rng(5)
    sops = ShardedGroupOps(tops, meshes[mesh_name])
    cols = 3
    rows = [_rand_elems(tgroup, rng, cols) for _ in range(m)]
    want = [1] * cols
    for row in rows:
        want = [w * x % tgroup.p for w, x in zip(want, row)]
    assert sops.prod_ints(rows) == want


def test_sharded_mulmod_and_residue(tgroup, tops, meshes):
    rng = np.random.default_rng(11)
    sops = ShardedGroupOps(tops, meshes["dp8"])
    a = _rand_elems(tgroup, rng, 10)
    b = _rand_elems(tgroup, rng, 10)
    want = [x * y % tgroup.p for x, y in zip(a, b)]
    assert sops.mulmod_ints(a, b) == want
    # residues: subgroup members and 1 valid; p-1 (order 2) and 0 invalid
    xs = a + [tgroup.p - 1, 1, 0]
    ok = np.asarray(sops.is_valid_residue(sops.to_limbs_p(xs)))
    assert ok[:10].all() and ok[11]
    assert not ok[10] and not ok[12]


def test_output_sharding_is_distributed(tgroup, tops, meshes):
    """The dp-sharded powmod output must actually live sharded on the mesh
    (not gathered to one device) so downstream stages stay distributed."""
    rng = np.random.default_rng(13)
    sops = ShardedGroupOps(tops, meshes["dp8"])
    bases = _rand_elems(tgroup, rng, 16)
    exps = [int(e) for e in rng.integers(0, tgroup.q, size=16)]
    out = sops.powmod(sops.to_limbs_p(bases), sops.to_limbs_q(exps))
    assert len(out.sharding.device_set) == 8

"""Differential tests: JAX limb plane vs Python-int scalar plane.

This is the bit-identical cross-check SURVEY.md §4 calls for ("crypto unit
tests against spec test vectors, bit-identical cross-checks vs a CPU bignum
path") — every batch op must agree with CPython pow/mult exactly.
"""

import random

import numpy as np
import pytest

from electionguard_tpu.core import bignum_jax as bn
from electionguard_tpu.core.group import tiny_group
from electionguard_tpu.core.group_jax import JaxGroupOps, jax_ops

rng = random.Random(20260729)


def test_limb_codec_roundtrip():
    for bits, n in ((64, 4), (4096, 256)):
        xs = [rng.getrandbits(bits) for _ in range(8)] + [0, 1, (1 << bits) - 1]
        arr = bn.ints_to_limbs(xs, n)
        assert bn.limbs_to_ints(arr) == xs
        assert arr.dtype == np.uint32


def test_montmul_tiny_random():
    g = tiny_group()
    ops = jax_ops(g)
    B = 64
    a = [rng.randrange(g.p) for _ in range(B)]
    b = [rng.randrange(g.p) for _ in range(B)]
    got = ops.mulmod_ints(a, b)
    assert got == [x * y % g.p for x, y in zip(a, b)]


def test_montmul_tiny_edges():
    g = tiny_group()
    ops = jax_ops(g)
    edges = [0, 1, 2, g.p - 1, g.p - 2, (1 << 63), g.p // 2]
    a, b = [], []
    for x in edges:
        for y in edges:
            a.append(x)
            b.append(y)
    assert ops.mulmod_ints(a, b) == [x * y % g.p for x, y in zip(a, b)]


def test_powmod_tiny_random():
    g = tiny_group()
    ops = jax_ops(g)
    B = 32
    bases = [rng.randrange(1, g.p) for _ in range(B)]
    exps = [rng.randrange(g.q) for _ in range(B)]
    got = ops.powmod_ints(bases, exps)
    assert got == [pow(b, e, g.p) for b, e in zip(bases, exps)]


def test_powmod_tiny_edges():
    g = tiny_group()
    ops = jax_ops(g)
    bases = [1, g.p - 1, 2, g.g, g.g, 1, g.p - 1]
    exps = [0, 0, 1, g.q - 1, 0, g.q - 1, 1]
    assert ops.powmod_ints(bases, exps) == \
        [pow(b, e, g.p) for b, e in zip(bases, exps)]


def test_g_pow_tiny():
    g = tiny_group()
    ops = jax_ops(g)
    exps = [0, 1, 2, g.q - 1] + [rng.randrange(g.q) for _ in range(28)]
    assert ops.g_pow_ints(exps) == [pow(g.g, e, g.p) for e in exps]


def test_base_pow_tiny():
    g = tiny_group()
    ops = jax_ops(g)
    k = pow(g.g, 12345, g.p)
    exps = [rng.randrange(g.q) for _ in range(16)]
    got = ops.from_limbs(ops.base_pow(k, ops.to_limbs_q(exps)))
    assert got == [pow(k, e, g.p) for e in exps]


@pytest.mark.parametrize("m", [1, 2, 3, 7, 8, 33])
def test_prod_reduce_tiny(m):
    g = tiny_group()
    ops = jax_ops(g)
    B = 5
    rows = [[rng.randrange(1, g.p) for _ in range(B)] for _ in range(m)]
    got = ops.prod_ints(rows)
    want = []
    for col in range(B):
        acc = 1
        for row in rows:
            acc = acc * row[col] % g.p
        want.append(acc)
    assert got == want


def test_residue_check_tiny():
    g = tiny_group()
    ops = jax_ops(g)
    good = [pow(g.g, rng.randrange(g.q), g.p) for _ in range(4)]
    bad = [2, 3]  # 2 generates beyond the order-q subgroup in the tiny group
    arr = ops.to_limbs_p(good + bad)
    res = np.asarray(ops.is_valid_residue(arr))
    assert res.tolist() == [True] * 4 + [False, False]


# ---------------------------------------------------------------------------
# production-size (4096-bit) — the sizes the TPU actually runs
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_production_mulmod_powmod(pgroup):
    g = pgroup
    ops = jax_ops(g)
    B = 4
    a = [rng.randrange(g.p) for _ in range(B)]
    b = [rng.randrange(g.p) for _ in range(B)]
    assert ops.mulmod_ints(a, b) == [x * y % g.p for x, y in zip(a, b)]
    bases = [rng.randrange(1, g.p) for _ in range(B)]
    exps = [rng.randrange(g.q) for _ in range(B)]
    assert ops.powmod_ints(bases, exps) == \
        [pow(x, e, g.p) for x, e in zip(bases, exps)]


@pytest.mark.slow
def test_production_g_pow_and_prod(pgroup):
    g = pgroup
    ops = jax_ops(g)
    exps = [0, 1, g.q - 1, rng.randrange(g.q)]
    assert ops.g_pow_ints(exps) == [pow(g.g, e, g.p) for e in exps]
    rows = [[rng.randrange(1, g.p) for _ in range(2)] for _ in range(5)]
    want = [1, 1]
    for row in rows:
        want = [w * r % g.p for w, r in zip(want, row)]
    assert ops.prod_ints(rows) == want


def test_multi_powmod_tiny():
    """Shared-base bucket multi-exp == k independent host pows, incl.
    edge exponents (0, 1, q-1) and base 1 / p-1."""
    g = tiny_group()
    ops = jax_ops(g)
    B, k = 6, 3
    bases = [1, g.p - 1, g.g] + [rng.randrange(1, g.p) for _ in range(B - 3)]
    exps = [[0, 1, g.q - 1]] + \
        [[rng.randrange(g.q) for _ in range(k)] for _ in range(B - 1)]
    base_l = ops.to_limbs_p(bases)
    exps_l = np.stack([ops.to_limbs_q(e) for e in exps])
    out = np.asarray(ops.multi_powmod(base_l, exps_l))
    got = [ops.from_limbs(out[i]) for i in range(B)]
    want = [[pow(bases[i], e, g.p) for e in exps[i]] for i in range(B)]
    assert got == want


def test_multi_powmod_production(pgroup):
    g = pgroup
    ops = jax_ops(g)
    B, k = 3, 3
    bases = [rng.randrange(1, g.p) for _ in range(B)]
    exps = [[rng.randrange(g.q) for _ in range(k)] for _ in range(B)]
    base_l = ops.to_limbs_p(bases)
    exps_l = np.stack([ops.to_limbs_q(e) for e in exps])
    out = np.asarray(ops.multi_powmod(base_l, exps_l))
    got = [ops.from_limbs(out[i]) for i in range(B)]
    assert got == [[pow(bases[i], e, g.p) for e in exps[i]]
                   for i in range(B)]

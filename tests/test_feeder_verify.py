"""Multi-feeder record verification: N processes over file-offset
slices of the framed ballot stream (README §Scaling model — the
process-parallel replacement for the reference's 11-thread
``Verifier(record, nthreads)``, RunRemoteWorkflowTest.java:180).

Pins: header-only shard scanning, slice iteration, partial/merge/
finalize equivalence with the single-pass verifier, V6 chain continuity
across a shard boundary (seeded by the boundary ballot's code), and the
``run_verifier -feeders N`` CLI end-to-end including tamper rejection.
"""

import os
import struct
import subprocess
import sys

import pytest

from electionguard_tpu.publish.election_record import ElectionRecord
from electionguard_tpu.publish.publisher import (Consumer, Publisher,
                                                 scan_frame_shards)
from electionguard_tpu.verify.verifier import (Verifier,
                                               VerificationResult,
                                               _BallotAggregates)


@pytest.fixture()
def record_dir(election, tmp_path):
    out = str(tmp_path / "record")
    pub = Publisher(out)
    pub.write_election_initialized(election["init"])
    pub.write_encrypted_ballots(election["encrypted"])
    pub.write_tally_result(election["tally_result"])
    pub.write_decryption_result(election["decryption_result"])
    return out


def test_shard_scan_covers_stream(record_dir, election):
    g = election["group"]
    consumer = Consumer(record_dir, g)
    shards = consumer.ballot_shards(3)
    assert sum(cnt for _, cnt, _ in shards) == 20
    seen = []
    for off, cnt, last_off in shards:
        blk = list(consumer.iterate_encrypted_ballots_slice(off, cnt))
        assert len(blk) == cnt
        # last_frame_offset decodes exactly the slice's final ballot
        tail = next(consumer.iterate_encrypted_ballots_slice(last_off, 1))
        assert tail.ballot_id == blk[-1].ballot_id
        seen.extend(b.ballot_id for b in blk)
    assert seen == [b.ballot_id for b in election["encrypted"]]


def test_feeder_partials_match_single_pass(record_dir, election):
    g = election["group"]
    consumer = Consumer(record_dir, g)
    record = ElectionRecord(
        election_init=election["init"],
        encrypted_ballots=election["encrypted"],
        tally_result=election["tally_result"],
        decryption_result=election["decryption_result"])
    single = Verifier(record, g).verify()

    shards = consumer.ballot_shards(3)
    prev_codes = [None]
    for _, _, last_off in shards[:-1]:
        prev_codes.append(next(
            consumer.iterate_encrypted_ballots_slice(last_off, 1)).code)
    parts = []
    for (off, cnt, _), pc in zip(shards, prev_codes):
        res, agg = VerificationResult(), _BallotAggregates()
        Verifier(record, g).verify_ballots_partial(
            consumer.iterate_encrypted_ballots_slice(off, cnt),
            res, agg, prev_code=pc)
        parts.append((res, agg))
    res, agg = Verifier.merge_partials(parts)
    merged = Verifier(record, g).finalize(res, agg)
    assert merged.ok, merged.summary()
    assert merged.checks == single.checks


def test_feeder_boundary_chain_break_detected(record_dir, election):
    """A broken chain exactly AT a shard boundary must fail V6: the
    second feeder's first ballot is checked against the handed-over
    boundary code, not blindly accepted."""
    g = election["group"]
    consumer = Consumer(record_dir, g)
    record = ElectionRecord(
        election_init=election["init"],
        encrypted_ballots=election["encrypted"],
        tally_result=election["tally_result"])
    shards = consumer.ballot_shards(2)
    assert len(shards) == 2
    (off0, cnt0, _), (off1, cnt1, _) = shards
    wrong_code = b"\x00" * 32
    res, agg = VerificationResult(), _BallotAggregates()
    Verifier(record, g).verify_ballots_partial(
        consumer.iterate_encrypted_ballots_slice(off1, cnt1),
        res, agg, prev_code=wrong_code)
    assert not res.checks["V6.ballot_chaining"]


def _run_cli(record_dir, feeders):
    env = {k: v for k, v in os.environ.items()
           if "AXON" not in k and "PALLAS" not in k
           and not k.startswith("TPU")}
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(
        [sys.executable, "-m", "electionguard_tpu.cli.run_verifier",
         "-in", record_dir, "-group", "tiny", "-feeders", str(feeders)],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.slow
@pytest.mark.e2e
def test_cli_feeders_pass_and_reject_tamper(record_dir, election):
    proc = _run_cli(record_dir, 2)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "merged 2 feeder partials" in proc.stdout + proc.stderr

    # swap the two ballots straddling the shard boundary in the FILE:
    # both feeders' slices still verify internally ballot-by-ballot, but
    # the chain across the boundary breaks
    path = os.path.join(record_dir, "encrypted_ballots.pb")
    frames = []
    with open(path, "rb") as f:
        while True:
            hdr = f.read(4)
            if not hdr:
                break
            (n,) = struct.unpack(">I", hdr)
            frames.append(f.read(n))
    frames[9], frames[10] = frames[10], frames[9]
    with open(path, "wb") as f:
        for fr in frames:
            f.write(struct.pack(">I", len(fr)))
            f.write(fr)
    proc = _run_cli(record_dir, 2)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "V6" in proc.stdout


def test_feeder_platform_pinned_in_parent_env_before_spawn():
    """The platform pin must live in the PARENT env while the spawn Pool
    is created (children inherit it at interpreter startup), honoring
    EGTPU_FEEDER_PLATFORM, scrubbing tunnel vars for the CPU default,
    and restoring everything afterwards (ADVICE r5 medium)."""
    from electionguard_tpu.utils.platform import pinned_child_platform

    os.environ["TPU_FAKE_TUNNEL"] = "1"
    os.environ["AXON_FAKE"] = "relay"
    prev_jax = os.environ.get("JAX_PLATFORMS")
    try:
        with pinned_child_platform("cpu"):
            # inside: children would inherit CPU pinning, no tunnel vars
            assert os.environ["JAX_PLATFORMS"] == "cpu"
            assert "TPU_FAKE_TUNNEL" not in os.environ
            assert "AXON_FAKE" not in os.environ
        # restored exactly
        assert os.environ["TPU_FAKE_TUNNEL"] == "1"
        assert os.environ["AXON_FAKE"] == "relay"
        assert os.environ.get("JAX_PLATFORMS") == prev_jax

        # an explicit non-CPU override keeps the tunnel env intact
        with pinned_child_platform("tpu"):
            assert os.environ["JAX_PLATFORMS"] == "tpu"
            assert os.environ["TPU_FAKE_TUNNEL"] == "1"
        assert os.environ.get("JAX_PLATFORMS") == prev_jax
    finally:
        os.environ.pop("TPU_FAKE_TUNNEL", None)
        os.environ.pop("AXON_FAKE", None)


def test_feeder_worker_has_no_late_platform_assignment():
    """The in-worker JAX_PLATFORMS assignment (too late: jax is already
    imported in the child when the worker body runs) must stay gone —
    the pin happens around the Pool in _verify_with_feeders."""
    import inspect

    from electionguard_tpu.cli import run_verifier
    worker_src = inspect.getsource(run_verifier._feeder_worker)
    assert "JAX_PLATFORMS" not in worker_src
    fan_src = inspect.getsource(run_verifier._verify_with_feeders)
    assert "pinned_child_platform" in fan_src

"""Mixnet plane tests (tiny group, non-slow).

Covers the full vertical slice: the batched re-encryption shuffle
preserves plaintexts, the Terelius–Wikström proof round-trips through
the published record format, an honest multi-stage cascade re-verifies
green through the real ``run_verifier`` binary path, the three
adversarial cases (tampered output ciphertext, wrong permutation,
replayed transcript) each fail with a DISTINCT error class, and the
bucketed dispatch discipline holds (a second same-shape stage compiles
nothing new — the ``device_compiles`` acceptance assertion).
"""

import copy

import numpy as np
import pytest

from electionguard_tpu.core.group import tiny_group
from electionguard_tpu.crypto.elgamal import ElGamalKeypair, elgamal_encrypt
from electionguard_tpu.mixnet import verify_mix
from electionguard_tpu.mixnet.generators import derive_generators, \
    generator_seed
from electionguard_tpu.mixnet.proof import prove_shuffle, rows_digest
from electionguard_tpu.mixnet.shuffle import Shuffler, prf_permutation
from electionguard_tpu.mixnet.stage import MixStage, rows_from_ballots, \
    run_stage
from electionguard_tpu.verify.verifier import VerificationResult


@pytest.fixture(scope="module")
def mixkey():
    g = tiny_group()
    return ElGamalKeypair.from_secret(g.int_to_q(987654321))


def _encrypt_rows(g, K, n, w, seed=1000):
    pads, datas = [], []
    for i in range(n):
        row_a, row_b = [], []
        for j in range(w):
            ct = elgamal_encrypt(g, (i + j) % 2,
                                 g.int_to_q(seed + i * w + j), K)
            row_a.append(ct.pad.value)
            row_b.append(ct.data.value)
        pads.append(row_a)
        datas.append(row_b)
    return pads, datas


class _Init:
    """The two ElectionInitialized fields the mix plane reads."""

    def __init__(self, K, qbar):
        self.joint_public_key = K
        self.extended_base_hash = qbar


def _qbar(g):
    return g.int_to_q(424242)


# ---------------------------------------------------------------------------
# shuffle data plane
# ---------------------------------------------------------------------------

def test_shuffle_preserves_plaintexts(mixkey):
    g = tiny_group()
    K, s = mixkey.public_key, mixkey.secret_key
    pads, datas = _encrypt_rows(g, K, 12, 2)
    sh = Shuffler(g, K.value)
    out_p, out_d, perm, rand = sh.shuffle(pads, datas, b"seed")
    assert sorted(perm) == list(range(12))

    def decrypt_row(row_a, row_b):
        from electionguard_tpu.crypto.elgamal import ElGamalCiphertext
        from electionguard_tpu.core.group import ElementModP
        return tuple(
            ElGamalCiphertext(ElementModP(a, g),
                              ElementModP(b, g)).decrypt(s)
            for a, b in zip(row_a, row_b))

    before = sorted(decrypt_row(a, b) for a, b in zip(pads, datas))
    after = sorted(decrypt_row(a, b) for a, b in zip(out_p, out_d))
    assert before == after
    # every ciphertext actually re-encrypted (fresh randomness)
    assert all(out_p[i][j] != pads[perm[i]][j]
               for i in range(12) for j in range(2))
    # output row i re-encrypts input row perm[i] with the returned rand
    i = 3
    assert out_p[i][0] == pads[perm[i]][0] * pow(g.g, rand[i][0],
                                                 g.p) % g.p


def test_shuffle_rejects_ragged_rows(mixkey):
    g = tiny_group()
    pads, datas = _encrypt_rows(g, mixkey.public_key, 4, 2)
    pads[2] = pads[2][:1]
    with pytest.raises(ValueError, match="uniform width"):
        Shuffler(g, mixkey.public_key.value).shuffle(pads, datas, b"s")


def test_prf_permutation_deterministic():
    assert list(prf_permutation(b"x", 50)) == list(prf_permutation(b"x", 50))
    assert list(prf_permutation(b"x", 50)) != list(prf_permutation(b"y", 50))


# ---------------------------------------------------------------------------
# generators + core multi-exp
# ---------------------------------------------------------------------------

def test_generators_in_subgroup_and_cached():
    g = tiny_group()
    seed = generator_seed(_qbar(g))
    hs = derive_generators(g, seed, 8)
    assert len(hs) == 9
    assert len(set(hs)) == 9
    for h in hs:
        assert h != 1 and pow(h, g.q, g.p) == 1
    assert derive_generators(g, seed, 8) is hs  # cache hit


def test_fixed_multi_pow_matches_host(mixkey):
    g = tiny_group()
    from electionguard_tpu.core.group_jax import jax_ops
    ops = jax_ops(g)
    K = mixkey.public_key.value
    es = [(i * 7919 + 13, i * 104729 + 5) for i in range(9)]
    exps = np.stack([ops.to_limbs_q([a for a, _ in es]),
                     ops.to_limbs_q([b for _, b in es])], axis=1)
    got = ops.from_limbs(np.asarray(ops.fixed_multi_pow([g.g, K], exps)))
    want = [pow(g.g, a, g.p) * pow(K, b, g.p) % g.p for a, b in es]
    assert got == want


# ---------------------------------------------------------------------------
# proof: honest cascade + the three distinct adversarial rejections
# ---------------------------------------------------------------------------

def _two_stage_cascade(g, K, qbar, n=16, w=2):
    pads, datas = _encrypt_rows(g, K, n, w)
    s0 = run_stage(g, K.value, qbar, 0, pads, datas, seed=b"stage0")
    s1 = run_stage(g, K.value, qbar, 1, s0.pads, s0.datas, seed=b"stage1")
    return pads, datas, [s0, s1]


def test_honest_cascade_verifies(mixkey):
    g = tiny_group()
    K, qbar = mixkey.public_key, _qbar(g)
    pads, datas, stages = _two_stage_cascade(g, K, qbar)
    res = VerificationResult()
    ok = verify_mix.verify_stages(g, _Init(K, qbar), stages, res,
                                  lambda: (pads, datas))
    assert ok and res.ok, res.summary()
    for name in verify_mix.CHECKS:
        assert res.checks[f"V15.{name}"]


def test_tampered_output_ciphertext_rejected(mixkey):
    """An output ciphertext modified after proving fails the BINDING
    layer (the Fiat–Shamir challenge no longer re-derives) — and only
    that layer is reported."""
    g = tiny_group()
    K, qbar = mixkey.public_key, _qbar(g)
    pads, datas, stages = _two_stage_cascade(g, K, qbar)
    bad = copy.deepcopy(stages[1])
    bad.pads[0][0] = bad.pads[0][0] * g.g % g.p  # stays in the subgroup
    res = VerificationResult()
    ok = verify_mix.verify_stages(g, _Init(K, qbar), [stages[0], bad],
                                  res, lambda: (pads, datas))
    assert not ok and not res.ok
    assert not res.checks["V15.mix_binding"]
    assert all("mix_binding" in e for e in res.errors), res.errors


def test_wrong_permutation_rejected(mixkey):
    """A cheating mixer whose outputs do not follow its committed
    permutation (rows swapped relative to the proof's secrets) produces
    a transcript that BINDS (it hashed what it published) but fails the
    RE-ENCRYPTION consistency equations — a distinct error class."""
    g = tiny_group()
    K, qbar = mixkey.public_key, _qbar(g)
    pads, datas = _encrypt_rows(g, K, 16, 2)
    sh = Shuffler(g, K.value)
    out_p, out_d, perm, rand = sh.shuffle(pads, datas, b"cheat")
    out_p[0], out_p[1] = out_p[1], out_p[0]
    out_d[0], out_d[1] = out_d[1], out_d[0]
    ih = rows_digest(g, pads, datas)
    proof = prove_shuffle(g, K.value, qbar, 0, pads, datas, out_p, out_d,
                          perm, rand, b"cheat", input_hash=ih)
    cheat = MixStage(0, 16, 2, ih, out_p, out_d, proof)
    res = VerificationResult()
    ok = verify_mix.verify_stages(g, _Init(K, qbar), [cheat], res,
                                  lambda: (pads, datas))
    assert not ok and not res.ok
    assert not res.checks["V15.mix_reencryption"]
    assert all("mix_reencryption" in e for e in res.errors), res.errors
    assert res.checks["V15.mix_binding"]  # transcript DID bind


def test_replayed_transcript_rejected(mixkey):
    """A proof transcript replayed from a different input fails the
    CHAIN layer (stage input hash does not match its predecessor's
    output) before any crypto runs — the third distinct error class."""
    g = tiny_group()
    K, qbar = mixkey.public_key, _qbar(g)
    pads, datas, stages = _two_stage_cascade(g, K, qbar)
    other_pads, other_datas = _encrypt_rows(g, K, 16, 2, seed=9999)
    replay = run_stage(g, K.value, qbar, 1, other_pads, other_datas,
                       seed=b"replay")
    res = VerificationResult()
    ok = verify_mix.verify_stages(g, _Init(K, qbar),
                                  [stages[0], replay], res,
                                  lambda: (pads, datas))
    assert not ok and not res.ok
    assert not res.checks["V15.mix_chain"]
    assert all("mix_chain" in e for e in res.errors), res.errors


def test_stage_index_mismatch_rejected(mixkey):
    g = tiny_group()
    K, qbar = mixkey.public_key, _qbar(g)
    pads, datas, stages = _two_stage_cascade(g, K, qbar)
    res = VerificationResult()
    ok = verify_mix.verify_stages(g, _Init(K, qbar),
                                  [stages[1], stages[0]], res,
                                  lambda: (pads, datas))
    assert not ok and not res.checks["V15.mix_structure"]


# ---------------------------------------------------------------------------
# bucketed dispatch: one compile per bucket shape
# ---------------------------------------------------------------------------

def test_second_stage_compiles_nothing(mixkey):
    """The acceptance assertion: after stage 0 has warmed every bucket
    shape (shuffle, prove, verify), a second same-shape stage — shuffle,
    prove, AND verify — adds ZERO backend compiles (the
    ``device_compiles`` counter stays flat, like the serving plane under
    load)."""
    from electionguard_tpu.obs import jaxmon
    jaxmon.install()
    g = tiny_group()
    K, qbar = mixkey.public_key, _qbar(g)
    pads, datas = _encrypt_rows(g, K, 16, 2, seed=5000)
    s0 = run_stage(g, K.value, qbar, 0, pads, datas, seed=b"warm")
    res = VerificationResult()
    assert verify_mix.verify_stages(g, _Init(K, qbar), [s0], res,
                                    lambda: (pads, datas))
    before = jaxmon.compile_count()
    s1 = run_stage(g, K.value, qbar, 1, s0.pads, s0.datas, seed=b"hot")
    res2 = VerificationResult()
    assert verify_mix.verify_stages(
        g, _Init(K, qbar), [s0, s1], res2, lambda: (pads, datas))
    assert jaxmon.compile_count() == before, \
        "a same-shape mix stage must not recompile any device program"


# ---------------------------------------------------------------------------
# published record: serialization + the real verifier binary path
# ---------------------------------------------------------------------------

def test_stage_serialization_roundtrip(tmp_path, mixkey):
    g = tiny_group()
    K, qbar = mixkey.public_key, _qbar(g)
    pads, datas = _encrypt_rows(g, K, 8, 2)
    stage = run_stage(g, K.value, qbar, 0, pads, datas, seed=b"ser")
    from electionguard_tpu.publish.publisher import Consumer, Publisher
    Publisher(str(tmp_path)).write_mix_stage(g, stage)
    consumer = Consumer(str(tmp_path), g)
    assert consumer.mix_stage_count() == 1
    back = consumer.read_mix_stage(0)
    assert back.proof == stage.proof
    assert (back.pads, back.datas) == (stage.pads, stage.datas)
    assert back.input_hash == stage.input_hash
    assert (back.n_rows, back.width) == (8, 2)


def test_mixnet_record_e2e(tmp_path, election):
    """The acceptance e2e, tiny group: 256 ballots encrypted, shuffled
    through 2 mix stages via the real ``run_mixnet`` binary, and the
    published record re-verified green by the real ``run_verifier``
    binary (V15 family included)."""
    from electionguard_tpu.ballot.plaintext import RandomBallotProvider
    from electionguard_tpu.cli import run_mixnet, run_verifier
    from electionguard_tpu.encrypt.encryptor import BatchEncryptor
    from electionguard_tpu.publish.publisher import Publisher

    g = election["group"]
    init = election["init"]
    ballots = list(RandomBallotProvider(
        election["manifest"], 256, seed=21).ballots())
    enc = BatchEncryptor(init, g)
    encrypted, invalid = enc.encrypt_ballots(ballots, seed=g.int_to_q(77))
    assert not invalid and len(encrypted) == 256
    pub = Publisher(str(tmp_path))
    pub.write_election_initialized(init)
    pub.write_encrypted_ballots(encrypted)
    rc = run_mixnet.main(["-in", str(tmp_path), "-out", str(tmp_path),
                          "-stages", "2", "-group", "tiny",
                          "-seed", "e2e"])
    assert rc == 0
    rc = run_verifier.main(["-in", str(tmp_path), "-group", "tiny"])
    assert rc == 0

"""Serving-plane tests: dynamic batcher semantics, the gRPC encryption
service end to end (real localhost channels, N concurrent clients), and
the loadgen smoke run the acceptance criteria require.

The heavyweight invariants pinned here:

* the record a draining service publishes passes the full verifier, and
  its confirmation codes are BIT-FOR-BIT what the offline BatchEncryptor
  produces for the same ballots in the same order (same seed/timestamp)
  — serving adds batching, not a second crypto path;
* bucket-shaped padding keeps the compiled-program count flat after
  warmup (one compile per shape bucket, never again under load);
* backpressure is explicit (RESOURCE_EXHAUSTED) and graceful drain
  delivers every admitted request exactly once.
"""

import threading
import time

import grpc
import pytest

from electionguard_tpu.ballot.plaintext import RandomBallotProvider
from electionguard_tpu.publish.election_record import ElectionConfig
from electionguard_tpu.serve.batcher import (DrainingError, DynamicBatcher,
                                             QueueFullError)
from tests.test_keyceremony import tiny_manifest


def _ballot(i: int):
    from electionguard_tpu.ballot.plaintext import (PlaintextBallot,
                                                    PlaintextBallotContest,
                                                    PlaintextBallotSelection)
    return PlaintextBallot(
        f"ballot-{i:05d}", "style-0",
        (PlaintextBallotContest(
            "contest-0", (PlaintextBallotSelection("sel-0", i % 2),
                          PlaintextBallotSelection("sel-1", 0))),))


# =====================================================================
# batcher unit tests
# =====================================================================


def test_batcher_flush_on_full():
    b = DynamicBatcher(max_batch=4, max_wait_ms=10_000, max_queue=16)
    for i in range(4):
        b.submit(_ballot(i))
    t0 = time.monotonic()
    batch = b.next_batch()
    # full batch flushes immediately — nowhere near the 10 s age flush
    assert len(batch) == 4 and time.monotonic() - t0 < 1.0
    assert [p.ballot.ballot_id for p in batch] == \
        [f"ballot-{i:05d}" for i in range(4)]  # FIFO


def test_batcher_flush_on_timeout():
    b = DynamicBatcher(max_batch=64, max_wait_ms=60, max_queue=16)
    t0 = time.monotonic()
    b.submit(_ballot(0))
    batch = b.next_batch()
    waited = time.monotonic() - t0
    assert len(batch) == 1
    assert waited >= 0.05, f"flushed before max_wait ({waited:.3f}s)"


def test_batcher_backpressure_queue_full():
    b = DynamicBatcher(max_batch=4, max_wait_ms=200, max_queue=3)
    for i in range(3):
        b.submit(_ballot(i))
    with pytest.raises(QueueFullError):
        b.submit(_ballot(99))
    # popping a batch (age flush: 3 < max_batch) frees capacity again
    assert len(b.next_batch()) == 3
    b.submit(_ballot(100))


def test_batcher_bucket_shapes():
    b = DynamicBatcher(max_batch=64, max_queue=64)
    assert b.buckets == (1, 2, 4, 8, 16, 32, 64)
    assert b.bucket_for(1) == 1
    assert b.bucket_for(3) == 4
    assert b.bucket_for(33) == 64
    # power-of-two buckets bound padding: occupancy structurally > 50%
    for n in range(1, 65):
        assert n / b.bucket_for(n) > 0.5
    b2 = DynamicBatcher(max_batch=6, max_queue=8)
    assert b2.buckets == (1, 2, 4, 6)
    with pytest.raises(ValueError):
        DynamicBatcher(max_batch=8, max_queue=8, buckets=[1, 2, 4])


def test_batcher_drain_delivers_every_admitted_exactly_once():
    b = DynamicBatcher(max_batch=4, max_wait_ms=10_000, max_queue=64)
    futures = [b.submit(_ballot(i)) for i in range(10)]
    b.close()
    with pytest.raises(DrainingError):
        b.submit(_ballot(999))
    seen = []
    while True:
        batch = b.next_batch()
        if batch is None:
            break
        seen.extend(p.ballot.ballot_id for p in batch)
        for p in batch:  # the worker would resolve these
            p.future.set_result(p.ballot.ballot_id)
    assert seen == [f"ballot-{i:05d}" for i in range(10)]
    assert len(seen) == len(set(seen)) == 10  # exactly once
    assert [f.result(timeout=1) for f in futures] == seen
    assert b.next_batch() is None  # stays drained


def test_batcher_close_flushes_partial_immediately():
    b = DynamicBatcher(max_batch=64, max_wait_ms=60_000, max_queue=8)
    b.submit(_ballot(0))
    box: dict[str, object] = {}

    def worker():
        box["batch"] = b.next_batch()

    t = threading.Thread(target=worker)
    t.start()
    time.sleep(0.1)  # worker is now waiting out max_wait
    b.close()        # drain must cut the wait short
    t.join(timeout=5)
    assert not t.is_alive() and len(box["batch"]) == 1


# =====================================================================
# service fixtures
# =====================================================================


@pytest.fixture(scope="module")
def serve_init(tgroup):
    """ElectionInitialized for the serving tests (module-scoped: the key
    ceremony is the slow part)."""
    from electionguard_tpu.keyceremony.exchange import key_ceremony_exchange
    from electionguard_tpu.keyceremony.trustee import KeyCeremonyTrustee
    trustees = [KeyCeremonyTrustee(tgroup, f"guardian-{i}", i + 1, 2)
                for i in range(3)]
    return key_ceremony_exchange(trustees, tgroup).make_election_initialized(
        ElectionConfig(tiny_manifest(), 3, 2), {"created_by": "serve-test"})


def _make_service(init, group, tmp_path=None, **kw):
    from electionguard_tpu.serve.service import EncryptionService
    kw.setdefault("seed", group.int_to_q(42))
    kw.setdefault("timestamp", 1754_000_000)
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_wait_ms", 15)
    return EncryptionService(
        init, group,
        out_dir=str(tmp_path / "record") if tmp_path is not None else None,
        **kw)


# =====================================================================
# service end-to-end
# =====================================================================


def test_service_e2e_concurrent_clients_verify_and_bitmatch(
        serve_init, tgroup, tmp_path):
    """Acceptance: N≥4 concurrent gRPC clients; the published record
    passes every verifier check; codes match the offline BatchEncryptor
    bit-for-bit."""
    from electionguard_tpu.encrypt.encryptor import BatchEncryptor
    from electionguard_tpu.publish.election_record import ElectionRecord
    from electionguard_tpu.publish.publisher import Consumer
    from electionguard_tpu.serve.service import EncryptionClient
    from electionguard_tpu.verify.verifier import Verifier

    # 8 ballots: the offline re-encryption below runs as ONE batch of 8,
    # the same dispatch shape the bucket-8 prewarm already compiled — the
    # test adds no fresh device-program compiles to the tier-1 budget
    svc = _make_service(serve_init, tgroup, tmp_path)
    ballots = list(RandomBallotProvider(tiny_manifest(), 8,
                                        seed=11).ballots())
    results: dict[str, object] = {}
    errors: list[BaseException] = []

    def client_run(idx):
        client = EncryptionClient(f"localhost:{svc.port}", tgroup)
        try:
            for b in ballots[idx::4]:
                enc = client.encrypt(b)
                results[b.ballot_id] = enc
        except BaseException as e:  # noqa: BLE001
            errors.append(e)
        finally:
            client.close()

    threads = [threading.Thread(target=client_run, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    assert len(results) == 8
    svc.drain()

    # the published record passes the verifier
    cons = Consumer(str(tmp_path / "record"), tgroup)
    record = ElectionRecord(cons.read_election_initialized())
    record.encrypted_ballots = list(cons.iterate_encrypted_ballots())
    assert len(record.encrypted_ballots) == 8
    res = Verifier(record, tgroup).verify()
    assert res.ok, res.summary()
    # no filler ballot ever reaches the published record
    assert not any(b.ballot_id.startswith("__pad-")
                   for b in record.encrypted_ballots)

    # bit-for-bit: offline BatchEncryptor over the same ballots in the
    # service's processing order reproduces ciphertexts AND codes
    by_id = {b.ballot_id: b for b in ballots}
    order = [b.ballot_id for b in record.encrypted_ballots]
    offline_enc = BatchEncryptor(serve_init, tgroup)
    offline, invalid = offline_enc.encrypt_ballots(
        [by_id[i] for i in order], seed=tgroup.int_to_q(42),
        timestamp=1754_000_000)
    assert not invalid
    assert offline == record.encrypted_ballots
    # ... and the codes the clients saw are the offline codes
    for off in offline:
        assert results[off.ballot_id].code == off.code


def test_service_invalid_ballot_in_band_error(serve_init, tgroup):
    import dataclasses

    from electionguard_tpu.serve.service import EncryptionClient
    svc = _make_service(serve_init, tgroup)
    try:
        client = EncryptionClient(f"localhost:{svc.port}", tgroup)
        good = _ballot(1)
        bad_contest = dataclasses.replace(
            good, ballot_id="bad-1",
            contests=(dataclasses.replace(
                good.contests[0], contest_id="no-such-contest"),))
        with pytest.raises(ValueError, match="unknown contest"):
            client.encrypt(bad_contest)
        with pytest.raises(ValueError, match="reserved"):
            client.encrypt(dataclasses.replace(good,
                                               ballot_id="__pad-000000001"))
        # a good ballot still flows after the failures
        enc = client.encrypt(good)
        assert enc.ballot_id == good.ballot_id
        client.close()
    finally:
        svc.drain()


def test_service_backpressure_resource_exhausted(serve_init, tgroup):
    """Queue full -> RESOURCE_EXHAUSTED on the wire; after the worker is
    released every admitted request completes."""
    from electionguard_tpu.serve.service import EncryptionClient
    hold = threading.Event()  # worker blocked until set
    svc = _make_service(serve_init, tgroup, max_batch=2, max_queue=2,
                        max_wait_ms=5, hold=hold)
    try:
        client = EncryptionClient(f"localhost:{svc.port}", tgroup)
        results, codes = [], []

        def submit(i):
            try:
                results.append(client.encrypt(_ballot(i), timeout=60))
            except grpc.RpcError as e:
                codes.append(e.code())

        threads = [threading.Thread(target=submit, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
            time.sleep(0.15)  # deterministic queue buildup order
        # 2 admitted (queued, worker held), 2 rejected with explicit
        # backpressure; releasing the worker completes the admitted ones
        hold.set()
        for t in threads:
            t.join(timeout=60)
        assert codes.count(grpc.StatusCode.RESOURCE_EXHAUSTED) == 2
        assert len(results) == 2  # every admitted request completed
        client.close()
    finally:
        hold.set()
        svc.drain()


def test_service_drain_rejects_new_completes_inflight(serve_init, tgroup):
    from electionguard_tpu.serve.batcher import DrainingError
    svc = _make_service(serve_init, tgroup, max_batch=4, max_wait_ms=200)
    futures = [svc.batcher.submit(_ballot(i)) for i in range(3)]
    svc.drain()
    # every admitted request completed exactly once, despite the drain
    # cutting the 200 ms age flush short
    encs = [f.result(timeout=1) for f in futures]
    assert [e.ballot_id for e in encs] == \
        [f"ballot-{i:05d}" for i in range(3)]
    with pytest.raises(DrainingError):
        svc.batcher.submit(_ballot(99))
    svc.drain()  # idempotent


def test_service_spoiled_ballot(serve_init, tgroup):
    from electionguard_tpu.ballot.ciphertext import BallotState
    from electionguard_tpu.serve.service import EncryptionClient
    svc = _make_service(serve_init, tgroup)
    try:
        client = EncryptionClient(f"localhost:{svc.port}", tgroup)
        enc = client.encrypt(_ballot(7), spoil=True)
        assert enc.state == BallotState.SPOILED
        client.close()
    finally:
        svc.drain()


# =====================================================================
# compile stability + loadgen smoke
# =====================================================================


def test_bucket_shape_stability_no_recompile(serve_init, tgroup):
    """Second batch of an already-seen bucket triggers ZERO new device
    compiles — the prewarmed bucket set is the whole compiled-shape
    universe."""
    from electionguard_tpu.serve.metrics import device_compile_count
    from electionguard_tpu.serve.worker import EncryptionWorker
    from electionguard_tpu.encrypt.encryptor import BatchEncryptor
    from electionguard_tpu.serve.batcher import DynamicBatcher
    from electionguard_tpu.serve.metrics import ServiceMetrics

    batcher = DynamicBatcher(max_batch=4, max_wait_ms=5, max_queue=16)
    metrics = ServiceMetrics(queue_depth=batcher.depth)
    worker = EncryptionWorker(batcher, BatchEncryptor(serve_init, tgroup),
                              metrics, seed=tgroup.int_to_q(9))
    worker.prewarm()  # compiles every (program, bucket) pair

    def run_batch(ids):
        futs = [batcher.submit(_ballot(i)) for i in ids]
        batch = batcher.next_batch()
        worker._process(batch, time.monotonic)
        return [f.result(timeout=1) for f in futs]

    run_batch([100, 101, 102])       # bucket 4 (padded from 3)
    warm = device_compile_count()
    run_batch([110, 111, 112, 113])  # bucket 4 again, different fill
    run_batch([120])                 # bucket 1 (prewarmed too)
    assert device_compile_count() == warm, \
        "recompile on an already-warm bucket shape"
    assert metrics.get("padded_slots") == 1  # only the 3->4 pad
    # prewarm batches are not traffic: occupancy saw the 3 real flushes
    occ = metrics.batch_occupancy.snapshot()
    assert occ["count"] == 3


def test_loadgen_smoke_occupancy_and_compile_stability(
        serve_init, tgroup, tmp_path):
    """Acceptance: under the loadgen smoke run, compile count is stable
    after warmup, mean batch occupancy ≥ 50% at saturation, the metrics
    rpc reports queue depth/occupancy/latency histograms, the Prometheus
    endpoint scrapes live counters, and the per-request latency JSONL is
    well-formed."""
    import json
    import sys
    import urllib.request
    sys.path.insert(0, "tools")
    from loadgen_encrypt import run_loadgen
    from electionguard_tpu.serve.metrics import device_compile_count

    svc = _make_service(serve_init, tgroup, tmp_path, max_batch=8,
                        max_wait_ms=30, max_queue=32,
                        metrics_http_port=0)
    try:
        url = f"localhost:{svc.port}"
        lat_path = str(tmp_path / "latency.jsonl")
        report = run_loadgen(url, tiny_manifest(), tgroup, nclients=4,
                             nballots=4, seed=1, latency_out=lat_path)
        assert report["errors"] == 0
        assert report["completed"] == 16
        assert report["ballots_per_s"] > 0
        # occupancy ≥ 50% at saturation: structural with power-of-two
        # buckets, and the metrics rpc must agree
        assert report["batch_occupancy_mean"] >= 0.5
        # the per-request latency JSONL joins client-observed latency
        # to the request ids the server side saw
        rows = [json.loads(ln) for ln in open(lat_path)]
        assert len(rows) == 16 and all(r["ok"] for r in rows)
        assert all(r["latency_ms"] > 0 for r in rows)
        # curl-style scrape of the live Prometheus endpoint shows the
        # service counters that just moved
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{svc.metrics_http_port}/metrics",
            timeout=10).read().decode()
        assert "# TYPE egtpu_ballots_encrypted counter" in text
        # ballot-flow counters carry the per-tenant election label
        enc_line = [ln for ln in text.splitlines()
                    if ln.startswith(
                        'egtpu_ballots_encrypted{election="default"} ')][0]
        assert int(enc_line.split()[-1]) >= 16
        assert "egtpu_rpc_server_calls_total" in text
        assert "egtpu_request_latency_ms_bucket" in text
        # warmup done: a second identical wave adds ZERO compiles
        warm = device_compile_count()
        report2 = run_loadgen(url, tiny_manifest(), tgroup, nclients=4,
                              nballots=4, seed=2)
        assert report2["errors"] == 0
        assert device_compile_count() == warm, \
            "compile-cache entries grew after warmup"
        # the metrics rpc carries the full observability surface
        c = report2["service_counters"]
        for key in ("queue_depth", "ballots_encrypted", "batches_flushed",
                    "device_compiles", "padded_slots"):
            assert key in c, f"missing counter {key}"
        from electionguard_tpu.serve.service import EncryptionClient
        client = EncryptionClient(url, tgroup)
        hists = {h.name for h in client.metrics().histograms}
        client.close()
        assert {"request_latency_ms", "batch_occupancy",
                "queue_depth_at_flush"} <= hists
    finally:
        svc.drain()

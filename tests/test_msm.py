"""Differential tests for ``JaxGroupOps.msm`` — the Pippenger bucketed
multi-scalar accumulation behind RLC batch verification.

The MSM must agree bit-exactly with the existing per-row primitives
(``multi_powmod`` / host ``pow``) on every backend, including the edge
bases {1, p-1} and edge exponents {0, 1, q-1}, and must support
exponents wider than q (the batch verifier's exact ~384-bit combined
exponents) and every declared window width.
"""

import os
import random
from unittest import mock

import numpy as np
import pytest

from electionguard_tpu.core.group import tiny_group
from electionguard_tpu.core.group_jax import JaxGroupOps, jax_ops

rng = random.Random(20260805)


def _host_msm(bases, exps, p):
    acc = 1
    for b, e in zip(bases, exps):
        acc = acc * pow(b, e, p) % p
    return acc


def test_msm_tiny_random_vs_multi_powmod():
    """msm == ∏ multi_powmod rows == ∏ host pows on the tiny group."""
    g = tiny_group()
    ops = jax_ops(g)
    B = 37
    bases = [rng.randrange(1, g.p) for _ in range(B)]
    exps = [rng.randrange(g.q) for _ in range(B)]
    want = _host_msm(bases, exps, g.p)
    # cross-check the oracle itself against the existing batch primitive
    per_row = ops.powmod_ints(bases, exps)
    acc = 1
    for v in per_row:
        acc = acc * v % g.p
    assert acc == want
    assert ops.msm_ints(bases, exps) == want


def test_msm_tiny_edges():
    """Edge bases {1, p-1} x edge exponents {0, 1, q-1}, plus an
    all-zero exponent batch (empty buckets everywhere -> identity)."""
    g = tiny_group()
    ops = jax_ops(g)
    bases = [1, g.p - 1, g.g, 1, g.p - 1, rng.randrange(1, g.p)]
    exps = [0, 1, g.q - 1, g.q - 1, 0, 1]
    assert ops.msm_ints(bases, exps) == _host_msm(bases, exps, g.p)
    assert ops.msm_ints(bases, [0] * len(bases)) == 1
    assert ops.msm_ints([], []) == 1


def test_msm_wide_exponents():
    """Exponents wider than q — the RLC verifier's exact (unreduced)
    combined exponents are ~s·c products of ~384 bits."""
    g = tiny_group()
    ops = jax_ops(g)
    bases = [rng.randrange(1, g.p) for _ in range(9)]
    exps = [rng.getrandbits(384) for _ in range(8)] + [0]
    assert ops.msm_ints(bases, exps) == _host_msm(bases, exps, g.p)


@pytest.mark.parametrize("window", ["4", "8", "16"])
def test_msm_window_widths(window):
    g = tiny_group()
    ops = jax_ops(g)
    bases = [1, g.p - 1] + [rng.randrange(1, g.p) for _ in range(14)]
    exps = [0, g.q - 1] + [rng.randrange(g.q) for _ in range(14)]
    with mock.patch.dict(os.environ, {"EGTPU_MSM_WINDOW": window}):
        assert ops.msm_ints(bases, exps) == _host_msm(bases, exps, g.p)


def test_msm_chunked_beyond_tile():
    """N > EGTPU_TILE splits into sub-MSMs combined via prod_reduce."""
    g = tiny_group()
    with mock.patch.dict(os.environ, {"EGTPU_TILE": "16"}):
        ops = JaxGroupOps(g, backend="cios")
        bases = [rng.randrange(1, g.p) for _ in range(53)]
        exps = [rng.randrange(g.q) for _ in range(53)]
        assert ops.msm_ints(bases, exps) == _host_msm(bases, exps, g.p)


def test_msm_rejects_bad_input():
    g = tiny_group()
    ops = jax_ops(g)
    with pytest.raises(ValueError):
        ops.msm_ints([g.g], [-1])
    with pytest.raises(ValueError):
        ops.msm_ints([g.g, g.g], [1])
    with mock.patch.dict(os.environ, {"EGTPU_MSM_WINDOW": "5"}):
        with pytest.raises(ValueError):
            ops.msm_ints([g.g], [1])


@pytest.mark.slow
def test_msm_production_backends(pgroup):
    """ntt (and pallas under interpret mode) agree with the host oracle
    on the 4096-bit production group."""
    g = pgroup
    B = 6
    bases = [1, g.p - 1] + [rng.randrange(1, g.p) for _ in range(B - 2)]
    exps = [0, g.q - 1] + [rng.randrange(g.q) for _ in range(B - 2)]
    want = _host_msm(bases, exps, g.p)
    assert jax_ops(g).msm_ints(bases, exps) == want
    ntt = JaxGroupOps(g, backend="ntt")
    assert ntt.msm_ints(bases, exps) == want


@pytest.mark.slow
def test_msm_pallas_interpret(pgroup):
    with mock.patch.dict(os.environ, {"EGTPU_PALLAS_INTERPRET": "1"}):
        ops = JaxGroupOps(pgroup, backend="pallas")
        bases = [rng.randrange(1, pgroup.p) for _ in range(2)]
        exps = [rng.randrange(pgroup.q) for _ in range(2)]
        assert ops.msm_ints(bases, exps, exp_bits=32 * 8) == \
            _host_msm(bases, exps, pgroup.p)

"""Edge-case suite for the universal ingestion gate (crypto/validate).

Covers the exact boundary values the gate's named classes exist for
(x = 0, 1, p−1, p, 2p−1, the all-ones Montgomery word), RLC batching
across the chunk cap, bisection attribution naming exactly the planted
offenders, the Jacobi quadratic-character screen (even numbers of
order-2 twists must NOT cancel), mode switching, and host-vs-device
path agreement on the tiny group plus a production-group RLC run.
"""

import pytest

from electionguard_tpu.core.group import production_group, tiny_group
from electionguard_tpu.crypto import validate
from electionguard_tpu.crypto.validate import GateError


@pytest.fixture(scope="module")
def tg():
    return tiny_group()


def _sub(g, k):
    """A genuine order-q subgroup member g^k."""
    return pow(g.g, k, g.p)


def _cofactor_qr(g, h):
    """h^(2q): order divides r/2 (odd), a square — passes the Jacobi
    screen, fails subgroup membership.  The element the RLC + bisection
    path exists for."""
    w = pow(h, 2 * g.q, g.p)
    assert w != 1 and pow(w, g.q, g.p) != 1
    assert validate._jacobi(w, g.p) == 1
    return w


def _cls(excinfo):
    return excinfo.value.cls


# ---------------------------------------------------------------------------
# the named per-element classes, one boundary value each
# ---------------------------------------------------------------------------

def test_zero_rejected_as_range(tg):
    with pytest.raises(GateError) as e:
        validate.gate_elements(tg, [("x", 0)], "test")
    assert _cls(e) == "validate.range"
    assert "[validate.range] test:" in str(e.value)


def test_identity_rejected_and_allowed(tg):
    with pytest.raises(GateError) as e:
        validate.gate_elements(tg, [("x", 1)], "test")
    assert _cls(e) == "validate.identity"
    # mix padding rows are legitimate (1, 1) ciphertexts
    validate.gate_elements(tg, [("pad", 1)], "test", allow_identity=True)


def test_order_two_element_rejected_as_small_order(tg):
    with pytest.raises(GateError) as e:
        validate.gate_elements(tg, [("x", tg.p - 1)], "test")
    assert _cls(e) == "validate.small_order"


def test_p_rejected_as_range(tg):
    with pytest.raises(GateError) as e:
        validate.gate_elements(tg, [("x", tg.p)], "test")
    assert _cls(e) == "validate.range"


def test_noncanonical_2p_minus_1_on_the_wire(tg):
    # 2p−1 ≡ p−1 mod p but is NOT the canonical encoding: the wire gate
    # must kill it as a range defect, never silently reduce it
    wide = (2 * tg.p - 1).to_bytes(tg.spec.p_bytes, "big")
    with pytest.raises(GateError) as e:
        validate.gate_wire_p(tg, [("x", wide)], "test")
    assert _cls(e) == "validate.range"


def test_all_ones_montgomery_word_rejected(tg):
    # the R−1 edge: an all-ones wire word (R−1 for the Montgomery radix
    # R = 2^(8·p_bytes)) is ≥ p and must die in the range check — a
    # reduction-happy import path would wrap it into a live element
    with pytest.raises(GateError) as e:
        validate.gate_wire_p(tg, [("x", b"\xff" * tg.spec.p_bytes)], "test")
    assert _cls(e) == "validate.range"


def test_genuine_nonresidue_rejected(tg):
    # p−v for subgroup v: (−v)^q = −1, and with p ≡ 3 (mod 4) the
    # Jacobi screen sees it deterministically
    with pytest.raises(GateError) as e:
        validate.gate_elements(tg, [("x", tg.p - _sub(tg, 7))], "test")
    assert _cls(e) == "validate.nonsubgroup"


def test_even_number_of_order_two_twists_does_not_cancel(tg):
    # TWO twisted elements cancel inside the RLC accumulator
    # ((−1)^(odd+odd) = 1) — the per-element Jacobi screen must reject
    # each one anyway (the seed-5 param-adversary regression)
    items = [("a", tg.p - _sub(tg, 3)), ("b", tg.p - _sub(tg, 5))]
    with pytest.raises(GateError) as e:
        validate.gate_elements(tg, items, "test")
    assert _cls(e) == "validate.nonsubgroup"
    assert "a " in str(e.value)         # first offender named first


def test_wire_q_range(tg):
    validate.gate_wire_q(tg, [("r", (tg.q - 1).to_bytes(
        tg.spec.q_bytes, "big")), ("z", b"\x00")], "test")
    with pytest.raises(GateError) as e:
        validate.gate_wire_q(tg, [("r", tg.q.to_bytes(
            tg.spec.q_bytes, "big"))], "test")
    assert _cls(e) == "validate.response_range"


def test_fingerprint_mismatch_named(tg):
    assert validate.gate_fingerprint(tg, tg.fingerprint(), "test") == ""
    assert validate.gate_fingerprint(tg, b"", "test") == ""
    err = validate.gate_fingerprint(tg, b"\x00" * 32, "test")
    assert "[validate.group_mismatch]" in err
    assert "group constants mismatch" in err


# ---------------------------------------------------------------------------
# batching + bisection attribution
# ---------------------------------------------------------------------------

def test_batch_of_one(tg):
    validate.gate_elements(tg, [("ok", _sub(tg, 11))], "test")
    w = _cofactor_qr(tg, 3)
    with pytest.raises(GateError) as e:
        validate.gate_elements(tg, [("bad", w)], "test")
    assert _cls(e) == "validate.nonsubgroup"
    assert "bad" in str(e.value)


def test_bisection_names_exactly_the_planted_offenders(tg):
    items = [(f"el[{i}]", _sub(tg, i + 2)) for i in range(64)]
    items[7] = ("el[7]", _cofactor_qr(tg, 3))
    items[42] = ("el[42]", _cofactor_qr(tg, 5))
    with pytest.raises(GateError) as e:
        validate.gate_elements(tg, items, "test")
    msg = str(e.value)
    assert _cls(e) == "validate.nonsubgroup"
    assert "el[7]" in msg and "el[42]" in msg
    # vouched-for neighbours are NOT named
    assert "el[6]" not in msg and "el[8]" not in msg and "el[41]" not in msg


def test_batch_over_chunk_cap(tg):
    # > CHUNK elements: the offender lands in the SECOND chunk and the
    # first chunk's screen must stay green
    n = validate.CHUNK + 8
    items = [(f"el[{i}]", _sub(tg, i + 2)) for i in range(n)]
    bad = validate.CHUNK + 3
    items[bad] = (f"el[{bad}]", _cofactor_qr(tg, 7))
    with pytest.raises(GateError) as e:
        validate.gate_elements(tg, items, "test")
    assert f"el[{bad}]" in str(e.value)
    # all-good batch of the same size passes
    validate.gate_elements(
        tg, [(f"el[{i}]", _sub(tg, i + 2)) for i in range(n)], "test")


# ---------------------------------------------------------------------------
# modes
# ---------------------------------------------------------------------------

def test_strict_mode_exact_per_element(tg, monkeypatch):
    monkeypatch.setenv("EGTPU_VALIDATE", "strict")
    w = _cofactor_qr(tg, 3)
    with pytest.raises(GateError) as e:
        validate.gate_elements(
            tg, [("good", _sub(tg, 4)), ("bad", w)], "test")
    assert _cls(e) == "validate.nonsubgroup"
    assert "bad" in str(e.value)
    validate.gate_elements(tg, [("good", _sub(tg, 4))], "test")


def test_off_mode_reverts_to_importer_posture(tg, monkeypatch):
    monkeypatch.setenv("EGTPU_VALIDATE", "off")
    # forged elements sail through the gate...
    validate.gate_elements(tg, [("bad", tg.p - _sub(tg, 3))], "test")
    assert validate.gate_fingerprint(tg, b"\x00" * 32, "test") == ""
    # ...but a non-canonical wire value still dies in the constructor
    # (the pre-gate posture), just without the named class
    with pytest.raises(ValueError):
        validate.gate_wire_p(
            tg, [("x", tg.p.to_bytes(tg.spec.p_bytes, "big"))], "test")
    monkeypatch.setenv("EGTPU_VALIDATE", "bogus")
    assert validate.mode() == "on"      # unknown values fail closed


# ---------------------------------------------------------------------------
# host path vs device RLC path, tiny + production
# ---------------------------------------------------------------------------

def test_tiny_host_and_device_paths_agree(tg):
    from electionguard_tpu.core.group_jax import JaxGroupOps
    ops = JaxGroupOps(tg, backend="cios")
    items = [(f"el[{i}]", _sub(tg, i + 2)) for i in range(16)]
    validate.gate_elements(tg, items, "test")                  # host
    validate.gate_elements(tg, items, "test", ops=ops)         # device
    items[5] = ("el[5]", _cofactor_qr(tg, 3))
    for use_ops in (None, ops):
        with pytest.raises(GateError) as e:
            validate.gate_elements(tg, items, "test", ops=use_ops)
        assert _cls(e) == "validate.nonsubgroup"
        assert "el[5]" in str(e.value)


def test_production_group_rlc_path():
    g = production_group()
    items = [(f"el[{i}]", _sub(g, i + 2)) for i in range(6)]
    validate.gate_elements(g, items, "test")
    items[3] = ("el[3]", _cofactor_qr(g, 3))
    with pytest.raises(GateError) as e:
        validate.gate_elements(g, items, "test")
    assert _cls(e) == "validate.nonsubgroup"
    assert "el[3]" in str(e.value)
    assert "el[2]" not in str(e.value)


# ---------------------------------------------------------------------------
# error-object contract + observability
# ---------------------------------------------------------------------------

def test_gate_error_carries_class_and_boundary(tg):
    with pytest.raises(GateError) as e:
        validate.gate_elements(tg, [("x", 0)], "serve")
    assert e.value.cls == "validate.range"
    assert e.value.boundary == "serve"
    assert isinstance(e.value, ValueError)      # in-band ValueError paths


def test_rejections_bump_counter_and_reject_log(tg):
    from electionguard_tpu import obs
    from electionguard_tpu.utils import errors
    seen = []
    cb = lambda cls, detail: seen.append(cls)  # noqa: E731
    errors.listen(cb)
    try:
        before = obs.REGISTRY.counter("validate_rejects_total").value
        with pytest.raises(GateError):
            validate.gate_elements(tg, [("x", 0)], "test")
        assert obs.REGISTRY.counter(
            "validate_rejects_total").value == before + 1
        assert "validate.range" in seen
    finally:
        errors.unlisten(cb)

"""Streaming-scale paths: the verifier and tally accumulator must accept
lazy ballot iterables, process them in bounded chunks, and produce results
identical to the materialized-list path (BASELINE.md configs 3-4; VERDICT
round-1 'nothing streams at 1M-ballot scale')."""

import dataclasses

from electionguard_tpu.ballot.ciphertext import BallotState
from electionguard_tpu.ballot.plaintext import RandomBallotProvider
from electionguard_tpu.encrypt.encryptor import BatchEncryptor
from electionguard_tpu.keyceremony.exchange import key_ceremony_exchange
from electionguard_tpu.keyceremony.trustee import KeyCeremonyTrustee
from electionguard_tpu.publish.election_record import (ElectionConfig,
                                                       ElectionRecord)
from electionguard_tpu.tally.accumulate import accumulate_ballots
from electionguard_tpu.verify.verifier import Verifier
from electionguard_tpu.workflow.e2e import sample_manifest


def _make_election(g, nballots=40, spoil_every=5):
    manifest = sample_manifest(2, 3)
    trustees = [KeyCeremonyTrustee(g, "g0", 1, 1)]
    init = key_ceremony_exchange(trustees, g).make_election_initialized(
        ElectionConfig(manifest, 1, 1), {})
    ballots = list(RandomBallotProvider(manifest, nballots,
                                        seed=5).ballots())
    spoiled = {b.ballot_id for i, b in enumerate(ballots)
               if spoil_every and (i + 1) % spoil_every == 0}
    enc = BatchEncryptor(init, g)
    # two chunks under one seed exercises cross-chunk nonces + code chain
    half = nballots // 2
    e1, _ = enc.encrypt_ballots(ballots[:half], seed=g.int_to_q(9),
                                spoiled_ids=spoiled)
    e2, _ = enc.encrypt_ballots(ballots[half:], seed=g.int_to_q(9),
                                code_seed=e1[-1].code,
                                ballot_index_base=half,
                                spoiled_ids=spoiled)
    return init, e1 + e2, spoiled


def test_streaming_tally_matches_list(tgroup):
    init, encrypted, spoiled = _make_election(tgroup)
    t_list = accumulate_ballots(init, encrypted)
    t_stream = accumulate_ballots(init, iter(encrypted), chunk_size=7)
    assert t_stream.encrypted_tally == t_list.encrypted_tally
    assert (t_stream.encrypted_tally.cast_ballot_count
            == len(encrypted) - len(spoiled))


def test_streaming_verifier_generator_input(tgroup):
    init, encrypted, spoiled = _make_election(tgroup)
    tally = accumulate_ballots(init, encrypted)
    record = ElectionRecord(election_init=init,
                            encrypted_ballots=iter(encrypted),
                            tally_result=tally)
    res = Verifier(record, tgroup, chunk_size=8).verify()
    assert res.ok, res.summary()


def test_streaming_verifier_chain_break_across_chunks(tgroup):
    init, encrypted, _ = _make_election(tgroup, spoil_every=0)
    tally = accumulate_ballots(init, encrypted)
    # break the chain exactly at a chunk boundary (ballot index 8)
    bad = dataclasses.replace(encrypted[8], code_seed=b"\x00" * 32)
    tampered = encrypted[:8] + [bad] + encrypted[9:]
    record = ElectionRecord(election_init=init,
                            encrypted_ballots=iter(tampered),
                            tally_result=tally)
    res = Verifier(record, tgroup, chunk_size=8).verify()
    assert not res.checks["V6.ballot_chaining"]


def test_streaming_verifier_truncate_front(tgroup):
    """Removing LEADING ballots must break V6: the first surviving
    ballot's code_seed no longer equals the manifest-anchored chain-start
    value (VERDICT r3 weak item 5 — previously invisible to V6)."""
    init, encrypted, _ = _make_election(tgroup, spoil_every=0)
    tally = accumulate_ballots(init, encrypted)
    record = ElectionRecord(election_init=init,
                            encrypted_ballots=iter(encrypted[1:]),
                            tally_result=tally)
    res = Verifier(record, tgroup, chunk_size=8).verify()
    assert not res.checks["V6.ballot_chaining"]


def test_streaming_verifier_detects_cast_count_mismatch(tgroup):
    init, encrypted, _ = _make_election(tgroup, spoil_every=0)
    tally = accumulate_ballots(init, encrypted)
    # drop one cast ballot from the stream: V7 must notice the count and
    # the product both disagree with the published tally
    record = ElectionRecord(election_init=init,
                            encrypted_ballots=iter(encrypted[:-1]),
                            tally_result=tally)
    res = Verifier(record, tgroup, chunk_size=8).verify()
    assert not res.checks["V7.aggregation"]

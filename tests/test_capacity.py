"""Capacity-planning plane (obs/capacity.py): the cost-model fitters,
the analytic pipeline model, and the predicted-vs-actual validation
gate — all on synthetic artifacts and a deterministic fake runner, so
the tier covers every contract without timing a real election.  The
real measured runs live in ``tools/egplan.py --validate`` (which the
bench capacity phase replays per bench round).
"""

import json
import os

import pytest

from electionguard_tpu.obs import capacity
from electionguard_tpu.obs.capacity import (CostModel, Estimate, Plan,
                                            ROWS_PER_BALLOT)


# ---------------------------------------------------------------------------
# estimates
# ---------------------------------------------------------------------------

def test_estimate_from_samples_band():
    # one sample -> the prior band rides along
    one = Estimate.from_samples([10.0], prior=0.2)
    assert (one.mean, one.rel_band, one.n) == (10.0, 0.2, 1)
    # repeated samples -> relative sample std
    est = Estimate.from_samples([9.0, 10.0, 11.0])
    assert est.mean == 10.0 and est.n == 3
    assert est.rel_band == pytest.approx(0.1)
    assert est.lo == pytest.approx(9.0) and est.hi == pytest.approx(11.0)
    with pytest.raises(ValueError):
        Estimate.from_samples([])
    # json round trip preserves the band
    assert Estimate.from_json(est.to_json()).rel_band == \
        pytest.approx(est.rel_band, abs=1e-4)


# ---------------------------------------------------------------------------
# fitters
# ---------------------------------------------------------------------------

def test_fit_bignum_normalizes_ladder_and_keeps_best():
    model = CostModel()
    capacity.fit_bignum({"platform": "cpu", "rows": [
        # variable-base at a short exponent: rows/s scales by bits/256
        {"backend": "cios", "op": "powmod", "batch": 8, "exp_bits": 32,
         "per_s": 80.0},
        {"backend": "cios", "op": "powmod", "batch": 8, "exp_bits": 32,
         "per_s": 88.0},
        # a slower config of the same backend must NOT win
        {"backend": "cios", "op": "powmod", "batch": 1, "exp_bits": 32,
         "per_s": 8.0},
        # fixed-base rows are already at 256 bits
        {"backend": "cios", "op": "fixed", "batch": 8, "exp_bits": 256,
         "per_s": 145.0},
        {"backend": "ntt", "op": "powmod", "batch": 8, "exp_bits": 256,
         "per_s": 0.8},
        {"backend": "cios", "op": "other", "per_s": 999.0},   # ignored
    ]}, model)
    assert model.platform == "cpu"
    assert model.powmod_per_s["cios"].mean == pytest.approx(84.0 * 32 / 256)
    assert model.powmod_per_s["cios"].n == 2
    assert model.fixed_per_s["cios"].mean == pytest.approx(145.0)
    assert model.powmod_per_s["ntt"].mean == pytest.approx(0.8)
    assert "other" not in model.powmod_per_s


def _amdahl_curve(r1, sigma, workers):
    return [{"workers": w,
             "ballots_per_s": w * r1 / (1.0 + sigma * (w - 1))}
            for w in workers]


def test_fit_scale_stream_fabric_and_prod_anchor():
    model = CostModel()
    capacity.fit_scale([
        {"phase": "stream", "nballots": 1000, "encrypt_s": 4.0,
         "verify_s": 3.0},
        {"phase": "stream", "nballots": 2000, "encrypt_s": 8.4},
        {"phase": "prod", "verify_per_s_per_chip": 0.6},
        {"phase": "fabric", "curve": _amdahl_curve(15.0, 0.125,
                                                   (1, 2, 4, 8))},
    ], model)
    # per-ballot host costs: two encrypt samples -> mean + sample band
    enc = model.stream_per_ballot_s["encrypt"]
    assert enc.mean == pytest.approx((0.004 + 0.0042) / 2) and enc.n == 2
    assert model.stream_per_ballot_s["verify"].mean == pytest.approx(0.003)
    assert model.prod_verify_per_s_per_chip.mean == pytest.approx(0.6)
    # an exact Amdahl curve fits back to its own σ and service time
    assert model.serial_fraction.mean == pytest.approx(0.125)
    assert model.serial_fraction.rel_band == pytest.approx(0.0, abs=1e-9)
    assert model.rpc_per_ballot_s.mean == pytest.approx(1 / 15.0)


def test_fit_degrades_with_warnings_on_missing_artifacts(tmp_path):
    model = capacity.fit(repo_root=str(tmp_path))
    assert model.powmod_per_s == {}
    assert any("bignum" in w for w in model.warnings)
    assert any("scale" in w for w in model.warnings)


def test_fit_collector_occupancy_from_histogram():
    model = CostModel()
    capacity.fit_collector({"histograms": {
        'batch_occupancy{proc="serve"}': {"sum": 8.0, "count": 10},
        "unrelated": {"sum": 99.0, "count": 1},
    }}, model)
    assert model.occupancy.mean == pytest.approx(0.8)
    assert model.occupancy.n == 10


# ---------------------------------------------------------------------------
# the analytic pipeline model
# ---------------------------------------------------------------------------

def _model(powmod=100.0, fixed=400.0, sigma=0.125, rpc_s=0.001):
    m = CostModel(platform="test")
    m.powmod_per_s["cios"] = Estimate(powmod, 0.1, 3)
    m.fixed_per_s["cios"] = Estimate(fixed, 0.1, 3)
    m.serial_fraction = Estimate(sigma, 0.05, 2)
    m.rpc_per_ballot_s = Estimate(rpc_s)
    m.occupancy = Estimate(1.0, 0.0, 1)
    return m


def test_predict_composes_phases_and_names_bottleneck():
    m = _model()
    p = capacity.predict(m, Plan(ballots=1000, chips=1, mix_stages=2,
                                 backend="cios"))
    by_name = {ph.name: ph for ph in p.phases}
    assert set(by_name) == {"serve-encrypt", "mix×2", "decrypt",
                            "verify-batch"}
    assert by_name["serve-encrypt"].seconds.mean == pytest.approx(
        1000 * ROWS_PER_BALLOT["encrypt"] / 400.0)
    assert by_name["mix×2"].seconds.mean == pytest.approx(
        1000 * ROWS_PER_BALLOT["mix_stage"] * 2 / 100.0)
    assert p.bottleneck == "mix×2"
    assert p.total.mean == pytest.approx(
        sum(ph.seconds.mean for ph in p.phases))
    # knee: efficiency crosses 50% at 1 + 1/σ workers
    assert p.knee_workers == 9
    # doubling chips halves every device phase
    p2 = capacity.predict(m, Plan(ballots=1000, chips=2, mix_stages=2,
                                  backend="cios"))
    assert p2.total.mean == pytest.approx(p.total.mean / 2)


def test_predict_serving_floor_binds_with_few_workers():
    # 1 worker at 1ms/ballot = 10s for 10k ballots >> device encrypt
    m = _model()
    p = capacity.predict(m, Plan(ballots=10_000, workers=1, chips=64,
                                 backend="cios"))
    enc = p.phases[0]
    assert enc.limiter == "rpc"
    assert enc.seconds.mean == pytest.approx(10.0)
    # unlimited workers (workers=0): the device side binds again
    p = capacity.predict(m, Plan(ballots=10_000, workers=0, chips=64,
                                 backend="cios"))
    assert p.phases[0].limiter == "device"


def test_predict_verify_modes_and_live_residual():
    m = _model()
    naive = capacity.predict(m, Plan(ballots=1000, batch_verify=False))
    batch = capacity.predict(m, Plan(ballots=1000))
    live = capacity.predict(m, Plan(ballots=1000, live_verify=True))
    ratio = ROWS_PER_BALLOT["verify"] / ROWS_PER_BALLOT["verify_batch"]
    assert naive.phases[-1].seconds.mean == pytest.approx(
        batch.phases[-1].seconds.mean * ratio)
    assert live.phases[-1].name == "verify-batch-residual"
    assert live.phases[-1].seconds.mean == pytest.approx(
        batch.phases[-1].seconds.mean * capacity.LIVE_RESIDUAL_FRACTION)
    with pytest.raises(ValueError):
        capacity.predict(m, Plan(backend="missing"))


def test_chips_for_deadline_inverts_predict():
    m = _model()
    row = capacity.chips_for_deadline(m, ballots=1_000_000,
                                      deadline_s=60.0, backend="cios")
    chips = row["chips"]
    assert chips and chips > 1
    # minimality: meets the deadline at chips, misses at chips-1
    assert capacity.predict(
        m, Plan(ballots=1_000_000, chips=chips)).total.mean <= 60.0
    assert capacity.predict(
        m, Plan(ballots=1_000_000, chips=chips - 1)).total.mean > 60.0
    # bands order: optimistic needs fewer chips, pessimistic more
    assert row["chips_lo"] <= chips <= row["chips_hi"]
    assert row["bottleneck"] and row["total_s"]["mean"] <= 60.0
    # an already-met deadline answers 1 chip
    easy = capacity.chips_for_deadline(m, ballots=10, deadline_s=60.0,
                                       backend="cios")
    assert easy["chips"] == 1


# ---------------------------------------------------------------------------
# the validation gate
# ---------------------------------------------------------------------------

def test_validate_fabric_holdout_on_exact_curve(tmp_path):
    path = str(tmp_path / "SCALE.json")
    with open(path, "w") as f:
        json.dump([{"phase": "fabric",
                    "curve": _amdahl_curve(15.0, 0.125, (1, 2, 4, 8))}],
                  f)
    out = capacity.validate_fabric(scale_path=path, tol=0.25)
    assert out["workers"] == 8            # the held-out point
    assert out["err_pct"] == pytest.approx(0.0, abs=0.1)
    assert out["pass"]
    # no usable curve -> skipped, not failed
    with open(path, "w") as f:
        json.dump([{"phase": "fabric", "curve": _amdahl_curve(
            15.0, 0.125, (1, 2))}], f)
    assert "skipped" in capacity.validate_fabric(scale_path=path, tol=0.25)


class _FakeRunner:
    """Deterministic election stand-in: linear per-phase cost plus a
    one-off jitter spike on each first timed repetition — exactly the
    noise shape the min-of-3 estimator must reject."""

    def __init__(self):
        self.calls = []

    def __call__(self, n, tag):
        self.calls.append((n, tag))
        phases = {"encrypt": 0.2 + 0.004 * n,
                  "tally": 0.01 + 0.0001 * n,
                  "verify": 0.5 + 0.008 * n}
        if tag.endswith("-0"):            # first timed rep of each set
            phases = {k: v + 1.7 for k, v in phases.items()}  # jitter
        return {"nballots": n, "phases": phases,
                "wall_s": sum(phases.values())}


def test_validate_e2e_fake_runner_interpolates_exactly():
    runner = _FakeRunner()
    out = capacity.validate_e2e(runner=runner, sizes=(128, 512, 384),
                                tol=0.25)
    # warm passes ran at EVERY measured size before any timing
    warm = [c for c in runner.calls if c[1] == "warm"]
    assert [n for n, _ in warm] == [128, 384, 512]
    assert runner.calls[0][1] == "warm"
    # a linear cost interpolates with zero error despite the jitter
    # spikes (min-of-3 discards them)
    assert out["err_pct"] == pytest.approx(0.0, abs=0.01)
    assert out["pass"] and out["sizes"] == [128, 512, 384]
    assert out["fitted"]["verify"]["per_ballot_s"] == pytest.approx(0.008)
    assert out["fitted"]["verify"]["fixed_s"] == pytest.approx(0.5)


def test_validate_e2e_rejects_equal_calibration_sizes():
    with pytest.raises(ValueError):
        capacity.validate_e2e(runner=_FakeRunner(), sizes=(128, 128, 64))


def test_validate_aggregates_both_configs(tmp_path):
    path = str(tmp_path / "SCALE.json")
    with open(path, "w") as f:
        json.dump([{"phase": "fabric",
                    "curve": _amdahl_curve(15.0, 0.125, (1, 2, 4, 8))}],
                  f)
    out = capacity.validate(runner=_FakeRunner(), scale_path=path,
                            tol=0.25)
    assert out["n_checked"] == 2 and out["pass"]
    assert out["max_err_pct"] is not None
    assert {c["name"] for c in out["configs"]} == \
        {"scale-fabric-holdout", "e2e-traced-election"}
    # a measured point drifting off the law flips the verdict (without
    # raising): the held-out 8-worker rate comes in 10% low
    curve = _amdahl_curve(15.0, 0.125, (1, 2, 4, 8))
    curve[-1]["ballots_per_s"] *= 0.9
    with open(path, "w") as f:
        json.dump([{"phase": "fabric", "curve": curve}], f)
    drifted = capacity.validate(runner=_FakeRunner(), scale_path=path,
                                tol=0.05)
    assert not drifted["pass"]
    assert drifted["max_err_pct"] > 5.0


# ---------------------------------------------------------------------------
# flight-report integration
# ---------------------------------------------------------------------------

class _FakeAnalysis:
    def __init__(self, buckets):
        self.buckets = buckets


def test_phase_comparison_against_tracked_prediction(tmp_path):
    m = _model()
    pred = capacity.predict(m, Plan(ballots=1000, mix_stages=1))
    doc = {"predictions": [pred.to_json()],
           "validation": {"max_err_pct": 3.0, "n_checked": 2,
                          "tolerance_pct": 25.0, "pass": True}}
    path = str(tmp_path / "CAPACITY.json")
    with open(path, "w") as f:
        json.dump(doc, f)
    a = _FakeAnalysis({("phase.encrypt", "drv", "device"): 600,
                       ("phase.mix", "drv", "device"): 200,
                       ("phase.verify", "drv", "device"): 200})
    cmp_rows = capacity.phase_comparison(a, capacity_path=path)
    rows = {r["phase"]: r for r in cmp_rows["rows"]}
    assert rows["serve-encrypt"]["actual_share"] == pytest.approx(0.6)
    assert set(rows) == {"serve-encrypt", "mix×1", "decrypt",
                         "verify-batch"}
    for r in rows.values():
        assert r["delta_pp"] == pytest.approx(
            (r["actual_share"] - r["predicted_share"]) * 100, abs=0.1)
    assert cmp_rows["validation"]["pass"]
    # either side missing -> None, never a crash
    assert capacity.phase_comparison(
        a, capacity_path=str(tmp_path / "nope.json")) is None
    assert capacity.phase_comparison(
        _FakeAnalysis({}), capacity_path=path) is None


def test_egplan_renders_capacity_markdown(tmp_path):
    """The egplan renderer turns a fitted-doc into the tracked
    CAPACITY.md shape: headline band table, fitted terms, what-if grid,
    validation verdict."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "egplan", os.path.join(os.path.dirname(__file__), os.pardir,
                               "tools", "egplan.py"))
    egplan = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(egplan)

    m = _model()
    headline = [capacity.chips_for_deadline(m, 1_000_000, 60.0, "cios")]
    pred = capacity.predict(m, Plan(ballots=1_000_000, chips=8))
    doc = {"ballots": 1_000_000, "deadline_s": 60.0,
           "model": m.to_json(), "headline": headline,
           "predictions": [pred.to_json()],
           "validation": {"tolerance_pct": 25.0, "pass": True,
                          "max_err_pct": 2.6, "n_checked": 1,
                          "configs": [{
                              "name": "scale-fabric-holdout",
                              "workers": 4,
                              "predicted_ballots_per_s": 42.0,
                              "measured_ballots_per_s": 41.0,
                              "err_pct": 2.6, "pass": True}]}}
    md = egplan.render_markdown(doc)
    assert "# Capacity plan" in md
    assert "chips for a 10^6-ballot election under 60 s" in md
    assert f"{headline[0]['chips']:,}" in md
    assert "## Validation (predicted vs measured)" in md
    assert "**PASS**" in md and "2.6%" in md

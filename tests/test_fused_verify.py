"""Production-group verification through the FUSED device path.

Round 4 shipped a green 155-test suite while `Verifier.verify()` raised
AttributeError on the production group, because every verifier test
pinned the tiny group and so never reached the `sha256_jax.supports()`
branch.  These tests run the real 4096-bit group end-to-end (reference
always does: src/main/java/electionguard/util/KUtils.java:10-13), so the
fused V4/V5 programs (verify/fused.py) are exercised by CI:

* a full workflow record verifies (the reference's ground truth,
  src/test/java/electionguard/workflow/RunRemoteWorkflowTest.java:179-182),
* tampered selection/contest proofs are REJECTED through the fused
  challenge compare (not vacuously accepted),
* the fused and unfused paths agree check-for-check.

Marked slow: production-size crypto on the CPU test backend.
"""

import dataclasses

import pytest

from electionguard_tpu.core import sha256_jax
from electionguard_tpu.publish.election_record import ElectionRecord
from electionguard_tpu.verify.verifier import Verifier

pytestmark = pytest.mark.slow


def _record(e, **overrides):
    kw = dict(election_init=e["init"],
              encrypted_ballots=list(e["encrypted"]),
              tally_result=e["tally_result"],
              decryption_result=e["decryption_result"])
    kw.update(overrides)
    return ElectionRecord(**kw)


def test_production_record_verifies_fused(pelection):
    assert sha256_jax.supports(pelection["group"])
    res = Verifier(_record(pelection), pelection["group"]).verify()
    assert res.ok, res.summary()
    assert res.checks["V4.selection_proofs"]
    assert res.checks["V5.contest_limits"]


def test_fused_rejects_tampered_selection_proof(pelection):
    """Swapping two ciphertexts invalidates the selection proofs; the
    fused device challenge compare must reject (V4), proving the fused
    path is not vacuously true."""
    record = _record(pelection)
    b = record.encrypted_ballots[1]
    c = b.contests[0]
    s0, s1 = c.selections[0], c.selections[1]
    tampered = dataclasses.replace(
        b, contests=(dataclasses.replace(c, selections=(
            dataclasses.replace(s0, ciphertext=s1.ciphertext),
            dataclasses.replace(s1, ciphertext=s0.ciphertext),
            c.selections[2])),))
    record.encrypted_ballots[1] = tampered
    res = Verifier(record, pelection["group"]).verify()
    assert not res.checks["V4.selection_proofs"]


def test_fused_rejects_tampered_contest_proof(pelection):
    """A corrupted contest-limit challenge must fail fused V5."""
    g = pelection["group"]
    record = _record(pelection)
    b = record.encrypted_ballots[0]
    c = b.contests[0]
    bad_proof = dataclasses.replace(
        c.proof, challenge=g.add_q(c.proof.challenge, g.ONE_MOD_Q))
    record.encrypted_ballots[0] = dataclasses.replace(
        b, contests=(dataclasses.replace(c, proof=bad_proof),))
    res = Verifier(record, g).verify()
    assert not res.checks["V5.contest_limits"]
    assert res.checks["V4.selection_proofs"]  # selections untouched


def test_fused_matches_unfused(pelection, monkeypatch):
    """Same record, fused vs host-hash path: identical per-check verdicts
    — on the clean record and on a tampered one."""
    g = pelection["group"]

    def both(record):
        fused = Verifier(record, g).verify()
        monkeypatch.setattr(sha256_jax, "supports", lambda _g: False)
        try:
            unfused = Verifier(record, g).verify()
        finally:
            monkeypatch.undo()
        return fused, unfused

    f, u = both(_record(pelection))
    assert f.checks == u.checks and f.ok and u.ok

    record = _record(pelection)
    b = record.encrypted_ballots[2]
    c = b.contests[0]
    s0 = c.selections[0]
    bad = dataclasses.replace(
        s0, proof=dataclasses.replace(
            s0.proof, proof_zero_response=g.add_q(
                s0.proof.proof_zero_response, g.ONE_MOD_Q)))
    record.encrypted_ballots[2] = dataclasses.replace(
        b, contests=(dataclasses.replace(
            c, selections=(bad,) + c.selections[1:]),))
    f, u = both(record)
    assert f.checks == u.checks
    assert not f.checks["V4.selection_proofs"]


def test_batched_schnorr_rejects_tamper_production(pelection):
    """V2's batched Schnorr verification (device Fiat-Shamir on the
    production group) must reject a tampered challenge."""
    g = pelection["group"]
    init = pelection["init"]
    gr = init.guardians[0]
    pr = gr.coefficient_proofs[0]
    bad_pr = dataclasses.replace(
        pr, challenge=g.add_q(pr.challenge, g.ONE_MOD_Q))
    bad_gr = dataclasses.replace(
        gr, coefficient_proofs=(bad_pr,) + gr.coefficient_proofs[1:])
    bad_init = dataclasses.replace(
        init, guardians=(bad_gr,) + init.guardians[1:])
    res = Verifier(_record(pelection, election_init=bad_init), g).verify()
    assert not res.checks["V2.guardian_keys"]

"""Device-mesh construction for the multi-chip batch plane.

The reference's only scale-out devices are an 11-thread CPU pool and batched
rpcs (reference: src/test/java/electionguard/workflow/RunRemoteWorkflowTest.java:140,180
and SURVEY.md §2.10); it has no collectives.  Our scale axis is the same —
ballots × contests × selections — mapped onto a JAX ``Mesh``:

* ``dp`` (data parallel): the flattened selection/ballot batch axis.  Every
  hot op (modexp, residue check, proof-commitment recompute) is elementwise
  over this axis, so it shards with zero communication.
* ``wp`` (window parallel): the 8-bit windows of fixed-base (PowRadix)
  exponentiation.  Each chip holds a slice of the precomputed table, computes
  the Montgomery product of its windows, and the partial products are
  combined with a log-depth all-gather product over ICI — the tensor-parallel
  analogue for exponentiation.

The homomorphic tally product-reduce contracts the ``dp`` axis with the same
all-gather + local-tree combine (SURVEY.md §5.7: "one log-depth reduction").
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DP_AXIS = "dp"
WP_AXIS = "wp"


def election_mesh(n_devices: Optional[int] = None,
                  wp: int = 1,
                  devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a ``(dp, wp)`` mesh over ``n_devices`` (default: all devices).

    ``wp`` devices cooperate on each fixed-base exponentiation window set;
    the remaining factor shards the batch.  ``wp=1`` is pure data parallel —
    the right default for this workload (SURVEY.md §5.7).
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    if n_devices > len(devices):
        raise ValueError(
            f"asked for {n_devices} devices, have {len(devices)}")
    if n_devices % wp != 0:
        raise ValueError(f"wp={wp} must divide n_devices={n_devices}")
    dev = np.asarray(devices[:n_devices]).reshape(n_devices // wp, wp)
    return Mesh(dev, (DP_AXIS, WP_AXIS))


def single_device_mesh() -> Mesh:
    """1×1 mesh: lets the sharded code path run unchanged on one chip."""
    return election_mesh(1, 1)

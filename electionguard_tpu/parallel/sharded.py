"""Sharded batch kernels: ``shard_map`` versions of the group hot ops.

This is the second communication plane SURVEY.md §5.8 calls for — XLA
collectives over ICI inside the coordinator's pod — layered on the same limb
kernels as the single-chip path (electionguard_tpu.core.bignum_jax).  The
gRPC plane (electionguard_tpu.remote) stays the trust boundary; nothing here
ever touches guardian secrets, only ciphertexts, shares, and proofs
(reference boundary: src/main/proto/decrypting_trustee_rpc.proto:15-45).

Sharding layout
---------------
* batch ops (``powmod``, ``mulmod``, ``fixed_pow``, ``is_valid_residue``):
  batch axis sharded over ``dp`` — elementwise, zero communication.
* ``fixed_pow`` additionally splits the PowRadix windows over ``wp``: each
  device multiplies together the table rows for its window slice, then the
  per-device partials are combined with an all-gather + log-tree Montgomery
  product (`lax.all_gather` over ``wp`` rides ICI).
* ``prod_reduce`` (homomorphic tally): the ballot axis is sharded over
  ``dp``; each device reduces its shard with a local log-depth Montgomery
  tree, then combines partials across ``dp`` the same way.  This is the
  multiplicative analogue of ``psum`` (SURVEY.md §5.7).

All entry points pad the batch to a multiple of the mesh and slice the
padding back off, so callers never see the mesh shape.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

# check_vma=False: kernel bodies create fresh zero-carries inside lax.scan
# (bignum_jax.montmul), which the varying-manual-axes checker would reject
# even though every output is honestly dp-varying.  Older jax releases
# (< 0.6) ship shard_map under jax.experimental with the same checker
# spelled check_rep — accept either so the sharded plane runs on both.
try:
    from jax import shard_map as _shard_map
    shard_map = functools.partial(_shard_map, check_vma=False)
except ImportError:
    from jax.experimental.shard_map import shard_map as _shard_map
    shard_map = functools.partial(_shard_map, check_rep=False)

from electionguard_tpu.core import bignum_jax as bn
from electionguard_tpu.parallel.mesh import DP_AXIS, WP_AXIS


def _partially_replicated(x) -> bool:
    """True iff ``x`` is committed to a sharding that leaves a >1-sized
    mesh axis unused (dp-sharded but wp-replicated, say).  jax 0.4.37's
    CPU backend lowers ``jnp.concatenate`` over such operands with a
    wrong row stride — silent data corruption (tests/test_sharded.py
    pins the repro) — so padding/concatenation must detour via host."""
    s = getattr(x, "sharding", None)
    mesh = getattr(s, "mesh", None)
    spec = getattr(s, "spec", None)
    if mesh is None or spec is None:
        return False
    used: set = set()
    for part in spec:
        if part is None:
            continue
        used.update((part,) if isinstance(part, str) else tuple(part))
    return any(size > 1 and name not in used
               for name, size in dict(mesh.shape).items())


def _pad_rows(x: np.ndarray | jax.Array, mult: int, fill_row) -> jax.Array:
    """Pad axis 0 of ``x`` up to a multiple of ``mult`` with ``fill_row``."""
    b = x.shape[0]
    rem = (-b) % mult
    if rem == 0:
        return jnp.asarray(x)
    if _partially_replicated(x):
        x = np.asarray(x)   # see _partially_replicated: concat would corrupt
    pad = jnp.broadcast_to(jnp.asarray(fill_row), (rem,) + x.shape[1:])
    return jnp.concatenate([jnp.asarray(x), pad], axis=0)


class ShardedGroupOps:
    """Mesh-parallel twin of ``JaxGroupOps`` — same public array API, so the
    verifier/tally/encrypt paths swap it in without code changes.

    Wraps a ``JaxGroupOps`` (whose Montgomery context and PowRadix tables it
    reuses) plus a ``Mesh`` from ``electionguard_tpu.parallel.mesh``.
    """

    def __init__(self, ops, mesh: Mesh):
        self.ops = ops
        self.group = ops.group
        self.mesh = mesh
        self.ndp = mesh.shape[DP_AXIS]
        self.nwp = mesh.shape[WP_AXIS]
        if ops.nwin8 % self.nwp != 0:
            raise ValueError(
                f"wp={self.nwp} must divide nwin8={ops.nwin8}")
        self.ctx = ops.ctx
        self.n = ops.n     # limb counts: callers reshape dispatch outputs
        self.ne = ops.ne   # (mixnet proof/verify) exactly like JaxGroupOps
        self._one_p = np.zeros(ops.n, np.uint32)
        self._one_p[0] = 1
        self._zero_q = np.zeros(ops.ne, np.uint32)
        # every kernel routes Montgomery products through ops._mm/_ms so
        # the sharded plane follows the same backend (cios/ntt) as ops
        self._powmod_j = self._build_elementwise(ops._powmod_impl)
        self._mulmod_j = self._build_elementwise(ops._mulmod_impl)
        self._residue_j = self._build_elementwise(ops._verify_residue_impl)
        self._fixed_pow_j = self._build_fixed_pow()
        self._fixed_multi_pow_j = self._build_fixed_multi_pow()
        self._prod_reduce_j = self._build_prod_reduce()

    # -- codecs delegate to the single-chip plane ----------------------
    def to_limbs_p(self, xs):
        return self.ops.to_limbs_p(xs)

    def to_limbs_q(self, xs):
        return self.ops.to_limbs_q(xs)

    def from_limbs(self, arr):
        return self.ops.from_limbs(arr)

    def fixed_table(self, base: int):
        return self.ops.fixed_table(base)

    @property
    def g_table(self):
        return self.ops.g_table

    # ------------------------------------------------------------------
    def _build_elementwise(self, fn):
        """shard_map an elementwise batch kernel over dp (wp replicated)."""
        mapped = shard_map(
            fn, mesh=self.mesh,
            in_specs=(P(DP_AXIS), P(DP_AXIS)),
            out_specs=P(DP_AXIS))
        return jax.jit(mapped)

    def _build_fixed_pow(self):
        ops = self.ops
        ctx = ops.ctx
        local_wins = ops.nwin8 // self.nwp

        def local_partial(table, digits):
            # table: (local_wins, 256, n); digits: (b_loc, local_wins)
            acc = None
            for i in range(local_wins):
                sel = table[i][digits[:, i]]            # (b_loc, n)
                acc = sel if acc is None else ops._mm(acc, sel)
            return acc

        def kernel(table, digits):
            partial = local_partial(table, digits)      # mont domain
            # combine window partials across wp: all-gather + local tree
            parts = lax.all_gather(partial, WP_AXIS)    # (nwp, b_loc, n)
            return bn.from_mont_via(
                ops._mm, bn.mont_prod_tree(ctx, parts, montmul_fn=ops._mm))

        mapped = shard_map(
            kernel, mesh=self.mesh,
            in_specs=(P(WP_AXIS), P(DP_AXIS, WP_AXIS)),
            out_specs=P(DP_AXIS))
        return jax.jit(mapped)

    def _build_fixed_multi_pow(self):
        """∏_j tables[j]^{exps[:,j]} for k host-known bases — the k-base
        PowRadix ladder behind the mixnet's bridging-chain and t̂ sigma
        commitments (group_jax._fixed_multi_pow_impl), with the window
        axis of every base's table sharded over wp and the gathers'
        batch axis over dp.  The k·local_wins per-device partials merge
        into one Montgomery product, then the wp partials combine with
        the same all-gather + log-tree as ``fixed_pow``."""
        ops = self.ops
        ctx = ops.ctx
        local_wins = ops.nwin8 // self.nwp

        def kernel(tables, digits):
            # tables: (k, local_wins, 256, n); digits: (b_loc, k, local_wins)
            k = tables.shape[0]
            acc = None
            for j in range(k):
                for i in range(local_wins):
                    sel = tables[j, i][digits[:, j, i]]    # (b_loc, n)
                    acc = sel if acc is None else ops._mm(acc, sel)
            parts = lax.all_gather(acc, WP_AXIS)           # (nwp, b_loc, n)
            return bn.from_mont_via(
                ops._mm, bn.mont_prod_tree(ctx, parts, montmul_fn=ops._mm))

        mapped = shard_map(
            kernel, mesh=self.mesh,
            in_specs=(P(None, WP_AXIS), P(DP_AXIS, None, WP_AXIS)),
            out_specs=P(DP_AXIS))
        return jax.jit(mapped)

    def _build_prod_reduce(self):
        ops = self.ops
        ctx = ops.ctx

        def kernel(x):                                  # (m_loc, B, n)
            r2 = jnp.broadcast_to(ctx.r2_mod_p, x.shape)
            partial = bn.mont_prod_tree(ctx, ops._mm(x, r2),
                                        montmul_fn=ops._mm)
            parts = lax.all_gather(partial, DP_AXIS)    # (ndp, B, n)
            return bn.from_mont_via(
                ops._mm, bn.mont_prod_tree(ctx, parts, montmul_fn=ops._mm))

        mapped = shard_map(
            kernel, mesh=self.mesh,
            in_specs=(P(DP_AXIS),),
            out_specs=P())
        return jax.jit(mapped)

    # ------------------------------------------------------------------
    # public array API (mirrors JaxGroupOps)
    # ------------------------------------------------------------------
    def powmod(self, base, exp):
        b = base.shape[0]
        base_p = _pad_rows(base, self.ndp, self._one_p)
        exp_p = _pad_rows(exp, self.ndp, self._zero_q)
        return self._powmod_j(base_p, exp_p)[:b]

    def mulmod(self, a, b_arr):
        b = a.shape[0]
        a_p = _pad_rows(a, self.ndp, self._one_p)
        b_p = _pad_rows(b_arr, self.ndp, self._one_p)
        return self._mulmod_j(a_p, b_p)[:b]

    def is_valid_residue(self, x):
        x = jnp.asarray(x)
        b = x.shape[0]
        x_p = _pad_rows(x, self.ndp, self._one_p)
        q_p = jnp.broadcast_to(
            jnp.asarray(bn.int_to_limbs(self.group.q, self.ops.ne)),
            (x_p.shape[0], self.ops.ne))
        return self._residue_j(x_p, q_p)[:b]

    def _digits8(self, exp: jax.Array) -> jax.Array:
        """(B, ne) 16-bit limbs -> (B, nwin8) 8-bit window digit indices."""
        lo = (exp & jnp.uint32(0xFF)).astype(jnp.int32)
        hi = (exp >> 8).astype(jnp.int32)
        digits = jnp.stack([lo, hi], axis=-1).reshape(exp.shape[0], -1)
        return digits[:, :self.ops.nwin8]  # 2*ne may exceed nwin8

    def _fixed_pow(self, table, exp):
        b = exp.shape[0]
        digits = self._digits8(jnp.asarray(exp))
        digits = _pad_rows(digits, self.ndp,
                           np.zeros(self.ops.nwin8, np.int32))
        return self._fixed_pow_j(table, digits)[:b]

    def g_pow(self, exp):
        return self._fixed_pow(self.ops.g_table, exp)

    def base_pow(self, base: int, exp):
        return self._fixed_pow(self.ops.fixed_table(base), exp)

    def fixed_multi_pow(self, bases, exps):
        """∏_j bases[j]^{exps[:, j]} for k host-known bases via cached
        tables: exps (B, k, ne) -> (B, n), dp-sharded batch, wp-sharded
        windows (mirrors JaxGroupOps.fixed_multi_pow; zero-exponent
        padding rows evaluate to 1)."""
        tables = jnp.stack([self.ops.fixed_table(b) for b in bases])
        exps = jnp.asarray(exps)
        b, k = exps.shape[0], exps.shape[1]
        digits = self._digits8(exps.reshape(b * k, -1)).reshape(
            b, k, self.ops.nwin8)
        digits = _pad_rows(digits, self.ndp,
                           np.zeros((k, self.ops.nwin8), np.int32))
        return self._fixed_multi_pow_j(tables, digits)[:b]

    def prod_reduce(self, x):
        """Product over axis 0: (M, B, n) -> (B, n), dp-sharded over M."""
        x = jnp.asarray(x)
        x_p = _pad_rows(x, self.ndp,
                        jnp.broadcast_to(jnp.asarray(self._one_p),
                                         x.shape[1:]))
        return self._prod_reduce_j(x_p)

    # -- int-facing convenience (parity with JaxGroupOps) --------------
    def powmod_ints(self, bases, exps):
        return self.from_limbs(
            self.powmod(self.to_limbs_p(bases), self.to_limbs_q(exps)))

    def mulmod_ints(self, a, b):
        return self.from_limbs(
            self.mulmod(self.to_limbs_p(a), self.to_limbs_p(b)))

    def g_pow_ints(self, exps):
        return self.from_limbs(self.g_pow(self.to_limbs_q(exps)))

    def prod_ints(self, xs):
        arr = np.stack([self.to_limbs_p(row) for row in xs])
        return self.from_limbs(self.prod_reduce(arr))


def sharded_ops(group, mesh: Optional[Mesh] = None) -> ShardedGroupOps:
    """Sharded batch plane for ``group`` over ``mesh`` (default: all
    devices, pure data parallel)."""
    from electionguard_tpu.core.group_jax import jax_ops
    from electionguard_tpu.parallel.mesh import election_mesh
    if mesh is None:
        mesh = election_mesh()
    return ShardedGroupOps(jax_ops(group), mesh)

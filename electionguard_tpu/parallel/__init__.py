"""Multi-chip plane: device meshes + shard_map'd batch kernels.

SURVEY.md §5.7/§5.8: the workload is embarrassingly parallel over ballots
with one log-depth multiplicative reduction, so the mesh story is a ``dp``
batch axis plus an optional ``wp`` window axis for fixed-base
exponentiation; cross-chip combines ride ICI via ``lax.all_gather``.
"""

from electionguard_tpu.parallel.mesh import (DP_AXIS, WP_AXIS, election_mesh,
                                             single_device_mesh)
from electionguard_tpu.parallel.sharded import ShardedGroupOps, sharded_ops

__all__ = [
    "DP_AXIS", "WP_AXIS", "election_mesh", "single_device_mesh",
    "ShardedGroupOps", "sharded_ops",
]

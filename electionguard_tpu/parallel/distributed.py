"""Multi-host distributed plane: XLA collectives over ICI + DCN.

The reference scales across trust domains with gRPC only (SURVEY.md §5.8 —
no NCCL/MPI, no collectives); its math plane is one JVM.  Our math plane
must span hosts the way the reference's gRPC plane spans guardians: this
module initializes JAX's distributed runtime (one process per host, GCE-or-
coordinator discovery exactly like jax on TPU pods) and lays the election
mesh out so that

* ``wp`` (PowRadix window parallelism, all-gather heavy) stays inside one
  host's ICI domain, and
* ``dp`` (the ballot/selection batch axis, zero-communication elementwise
  work + one log-depth tally product) spans hosts over DCN,

which keeps every latency-sensitive collective on ICI and sends only the
embarrassingly-parallel axis across the data-center network.

Hosts feed their full host-local batch through ``global_batch`` /
``local_result``; array construction uses ``make_array_from_callback`` so
each process materializes only its addressable shards.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from electionguard_tpu.parallel.mesh import DP_AXIS, WP_AXIS


def _is_initialized() -> bool:
    """jax.distributed.is_initialized where it exists (>= 0.5); older
    releases expose only the internal global_state client handle."""
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    state = getattr(jax.distributed, "global_state", None)
    return state is not None and getattr(state, "client", None) is not None


def distributed_init(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Initialize the JAX distributed runtime (idempotent).

    Arguments default to the EGTPU_COORDINATOR / EGTPU_NUM_PROCESSES /
    EGTPU_PROCESS_ID environment variables; on TPU pods all three may be
    None and jax discovers the topology itself.
    """
    if _is_initialized():  # idempotent
        return
    coordinator_address = coordinator_address or os.environ.get(
        "EGTPU_COORDINATOR")
    if num_processes is None and "EGTPU_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["EGTPU_NUM_PROCESSES"])
    if process_id is None and "EGTPU_PROCESS_ID" in os.environ:
        process_id = int(os.environ["EGTPU_PROCESS_ID"])
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)


def multihost_election_mesh(wp: int = 1,
                            devices: Optional[Sequence[jax.Device]] = None
                            ) -> Mesh:
    """(dp, wp) mesh over ALL processes' devices, ordered so each wp group
    is process-local (wp collectives ride ICI; dp spans DCN)."""
    if devices is None:
        devices = jax.devices()
    by_proc: dict[int, list[jax.Device]] = {}
    for d in devices:
        by_proc.setdefault(d.process_index, []).append(d)
    ordered: list[jax.Device] = []
    for pid in sorted(by_proc):
        local = by_proc[pid]
        if len(local) % wp != 0:
            raise ValueError(
                f"wp={wp} must divide each host's device count "
                f"({len(local)} on process {pid})")
        ordered.extend(local)
    n = len(ordered)
    dev = np.asarray(ordered).reshape(n // wp, wp)
    return Mesh(dev, (DP_AXIS, WP_AXIS))


def global_batch(mesh: Mesh, arr: np.ndarray,
                 spec: Optional[P] = None) -> jax.Array:
    """Host-local full array -> global dp-sharded device array.

    Every process passes the SAME full batch (the coordinator broadcasts
    work host-side, mirroring the reference's batched rpcs); each process
    materializes only its addressable shards.
    """
    spec = spec if spec is not None else P(DP_AXIS)
    sharding = NamedSharding(mesh, spec)
    arr = np.asarray(arr)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx])


def local_result(x: jax.Array) -> np.ndarray:
    """Replicated-output device array -> host numpy (first local replica).

    The input must be fully replicated (e.g. via a ``P()`` sharding
    constraint); a dp-sharded array would silently yield one shard.
    """
    if not x.sharding.is_fully_replicated:
        raise ValueError(
            "local_result requires a fully replicated array; got sharding "
            f"{x.sharding}. Add a with_sharding_constraint(..., P()) or "
            "all-gather before reading the result host-side.")
    shards = x.addressable_shards
    return np.asarray(shards[0].data)

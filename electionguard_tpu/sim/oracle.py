"""Safety and liveness oracles checked after every simulated run.

Each oracle states an invariant the election planes must hold under ANY
survivable fault schedule; a violation message names the oracle first
(``no_ballot_lost: ...``) so sweeps and shrink predicates can match on
the class.

* ``no_ballot_lost``   — every ballot a voter was acked for appears in
  the published record exactly once (exactly-once admission: neither a
  lost response nor a retry may lose or double-record a ballot);
* ``chain_contiguous`` — the recorded ballot stream forms one unbroken
  confirmation-code chain (each code seeds the next, every code valid);
* ``verifier_green``   — the full independent Verifier accepts the
  record, including V15 over the published mix cascade;
* ``quorum_tally``     — the threshold-decrypted tally equals the
  plaintext vote sums of the acked cast ballots, produced by exactly
  ``navailable`` guardians with the rest compensated;
* ``liveness``         — the workflow ran to completion inside the
  virtual-time horizon with no deadlock and no task crash (reported by
  the run framework via ``liveness_error`` / ``workflow_error``);
* ``race``             — no unwaived data race reported by the dynamic
  happens-before/lockset monitor when the run had it attached
  (``run_sim(race=True)``); waivers live in
  ``analysis/race_waivers.json`` (ships empty, every entry needs a
  note);
* ``live_convergence`` — when the run carried the ``live-verify``
  plant (cluster ``_live_verify_leg``): the live verifier's final
  verdict, error list, chunk-accept set, and commitment root/chain
  head — reached while the record grew, through torn tails and
  SIGKILL/checkpoint-resume — are bit-identical to a terminal
  single-pass fold over the finished record, agree with the
  independent full verifier's verdict, and nothing the batch pass
  rejects is first rejected live at a LATER chunk;
* ``soundness``        — every in-protocol attack that actually fired
  (``outcome.fired``, the adversary plan's audit log) was DETECTED: an
  in-band rejection carrying one of the attack's expected named error
  classes (``utils.errors``), an abort whose error text carries one, or
  a red verifier check in the attack's family.  A run that stays green
  with an undetected attack — tampering yielded a clean record — is the
  violation this oracle exists for.

An abort is a *sound* outcome under attack: when the run ended early
and some error text names an expected class of a fired attack, the
abort IS the in-band rejection, so the liveness violations are
suppressed for that run (the soundness oracle still checks every other
fired attack was detected too).
"""

from __future__ import annotations

from electionguard_tpu.sim import adversary
from electionguard_tpu.utils import errors


def check(outcome) -> list[str]:
    """All oracle violations for one run's :class:`~electionguard_tpu.
    sim.cluster.SimOutcome` (empty = the run is green)."""
    v: list[str] = []
    detections = _detections(outcome)
    expected = set()
    for attack, _method, _n, _node in getattr(outcome, "fired", ()):
        expected |= adversary.expected_for(attack)
    sound_abort = (not outcome.completed
                   and bool(_error_classes(outcome) & expected))
    if not sound_abort:
        if outcome.liveness_error:
            v.append(f"liveness: {outcome.liveness_error}")
        if outcome.workflow_error:
            v.append(f"liveness: workflow failed: "
                     f"{outcome.workflow_error}")
        for name, err in outcome.task_errors:
            v.append(f"liveness: task {name} crashed: {err!r}")
    if not outcome.completed:
        if not v and not sound_abort:
            v.append("liveness: run ended before the workflow completed")
        v.extend(_soundness(outcome, detections))
        v.extend(_races(outcome))
        return v  # downstream oracles need the full artifacts
    v.extend(_no_ballot_lost(outcome))
    v.extend(_chain_contiguous(outcome))
    v.extend(_verifier_green(outcome))
    v.extend(_quorum_tally(outcome))
    v.extend(_live_convergence(outcome))
    v.extend(_soundness(outcome, detections))
    v.extend(_races(outcome))
    return v


def _live_convergence(o) -> list[str]:
    rep = getattr(o, "live_report", None)
    if rep is None:
        return []
    v = []
    if (rep["live_checks"] != rep["batch_checks"]
            or rep["live_errors"] != rep["batch_errors"]):
        v.append("live_convergence: live verdict diverged from the "
                 "terminal fold at the same chunk size "
                 f"(chunk={rep['chunk']} crashes={rep['crashes']} "
                 f"torn={rep['torn']}): live "
                 f"{sorted(k for k, ok in rep['live_checks'].items() if not ok)}"
                 f"/{rep['live_errors']} vs batch "
                 f"{sorted(k for k, ok in rep['batch_checks'].items() if not ok)}"
                 f"/{rep['batch_errors']}")
    if rep["live_accepts"] != rep["batch_accepts"]:
        v.append(f"live_convergence: chunk-accept set diverged: live "
                 f"{rep['live_accepts']} vs batch {rep['batch_accepts']}")
    if (rep["live_root"] != rep["batch_root"]
            or rep["live_head"] != rep["batch_head"]):
        v.append("live_convergence: commitment diverged across "
                 f"{rep['crashes']} crash-resume(s): root "
                 f"{rep['live_root'][:16]} vs {rep['batch_root'][:16]}, "
                 f"head {rep['live_head'][:16]} vs "
                 f"{rep['batch_head'][:16]}")
    vr = o.verify_result
    if vr is not None and rep["live_ok"] != vr.ok:
        v.append(f"live_convergence: live ok={rep['live_ok']} but the "
                 f"independent verifier says ok={vr.ok}")
    b_first, l_first = rep["batch_first_reject"], rep["live_first_reject"]
    if b_first is not None and (l_first is None or l_first > b_first):
        v.append(f"live_convergence: batch fold rejects chunk {b_first} "
                 f"but live first rejected at {l_first} — detection "
                 f"must be equal-or-earlier")
    return v


def _races(o) -> list[str]:
    reports = getattr(o, "races", ())
    if not reports:
        return []
    from electionguard_tpu.analysis import race as race_mod
    waivers = race_mod.load_waivers()
    return [f"race: {r.summary()}" for r in reports
            if not race_mod.waived(r, waivers)]


def _error_classes(o) -> set[str]:
    texts = [o.liveness_error, o.workflow_error]
    texts += [str(err) for _name, err in o.task_errors]
    return errors.classes_over(texts)


def _detections(o) -> set[str]:
    """Every detection class visible for a run: the in-band rejection
    log, class tokens embedded in abort/task error texts, and red
    verifier checks (``V15.mix_binding`` contributes both
    ``verify.mix_binding`` and the in-band form ``mix.binding``)."""
    seen = {cls for cls, _detail in getattr(o, "detections", ())}
    seen |= _error_classes(o)
    vr = o.verify_result
    if vr is not None:
        for name, ok in vr.checks.items():
            if ok:
                continue
            last = name.split(".")[-1]
            seen.add(f"verify.{last}")
            if last.startswith("mix_"):
                seen.add("mix." + last[4:])
    return seen


def _soundness(o, detections: set[str]) -> list[str]:
    fired = list(getattr(o, "fired", ()))
    # calls whose message was rejected because of a CO-MOUNTED attack:
    # when two attacks land on the same (method, call, node) message,
    # the defense that fires first — in practice the ingestion gate,
    # which screens before Schnorr/nonce/share checks run — kills the
    # whole message, so the other attack's expected class can never
    # appear.  A detected co-mount IS containment of that message; the
    # masked attack is moot, not undetected.
    killed = {(m, n, node) for a, m, n, node in fired
              if adversary.expected_for(a) & detections}
    v = []
    for attack, method, n, node in fired:
        expect = adversary.expected_for(attack)
        if expect & detections:
            continue
        if (method, n, node) in killed:
            continue
        where = f" on {node}" if node else ""
        v.append(f"soundness: attack {attack} fired{where} "
                 f"({method} call {n}) and was never detected — "
                 f"expected one of {sorted(expect) or ['<nothing>']}, "
                 f"saw {sorted(detections)}")
    return v


def _no_ballot_lost(o) -> list[str]:
    counts: dict[str, int] = {}
    for b in o.recorded:
        counts[b.ballot_id] = counts.get(b.ballot_id, 0) + 1
    v = []
    for bid in sorted(o.acked):
        n = counts.get(bid, 0)
        if n == 0:
            v.append(f"no_ballot_lost: acked ballot {bid} missing from "
                     f"the record")
        elif n > 1:
            v.append(f"no_ballot_lost: acked ballot {bid} recorded "
                     f"{n} times")
    return v


def _chain_contiguous(o) -> list[str]:
    v = []
    for b in o.recorded:
        if not b.is_valid_code():
            v.append(f"chain_contiguous: ballot {b.ballot_id} has an "
                     f"invalid confirmation code")
    for prev, cur in zip(o.recorded, o.recorded[1:]):
        if cur.code_seed != prev.code:
            v.append(f"chain_contiguous: {cur.ballot_id} does not chain "
                     f"from {prev.ballot_id}")
            break
    return v


def _verifier_green(o) -> list[str]:
    if o.verify_result is None:
        return ["verifier_green: verifier never ran"]
    if not o.verify_result.ok:
        failed = sorted(k for k, ok in o.verify_result.checks.items()
                        if not ok)
        return [f"verifier_green: checks failed: {', '.join(failed)}"]
    return []


def _quorum_tally(o) -> list[str]:
    v = []
    dr = o.decryption_result
    if dr is None:
        return ["quorum_tally: no decryption result"]
    if len(dr.decrypting_guardians) != o.navailable:
        v.append(f"quorum_tally: decrypted with "
                 f"{len(dr.decrypting_guardians)} guardians, expected "
                 f"navailable={o.navailable}")
    want: dict[tuple[str, str], int] = {}
    acked_cast = [b for b in o.ballots if b.ballot_id in o.acked]
    for b in acked_cast:
        for c in b.contests:
            for s in c.selections:
                key = (c.contest_id, s.selection_id)
                want[key] = want.get(key, 0) + s.vote
    got = {(c.contest_id, s.selection_id): s.tally
           for c in dr.decrypted_tally.contests for s in c.selections}
    for key in sorted(want):
        if got.get(key, 0) != want[key]:
            v.append(f"quorum_tally: {key[0]}/{key[1]} decrypted to "
                     f"{got.get(key, 0)}, plaintext sum is {want[key]}")
    return v

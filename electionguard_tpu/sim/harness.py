"""Light-weight simulation harness for tests.

``simulation()`` installs the virtual clock and the in-memory transport
for the duration of a ``with`` block, so a test can run ANY library code
that speaks gRPC / sleeps / polls — coordinators, proxies, retry loops,
fault injection — in virtual time with zero real sleeping:

    with simulation(seed=3) as sim:
        def body():
            coord = KeyCeremonyCoordinator(group, 1, 1, port=0)
            ...
        sim.run(body)

Unlike :func:`electionguard_tpu.sim.explore.run_sim` (the full-workflow
explorer), the harness imposes no workflow, no fault schedule, and no
oracles — the test IS the driver.
"""

from __future__ import annotations

import random
from typing import Optional

from electionguard_tpu.remote import rpc_util
from electionguard_tpu.sim.scheduler import SimClock, SimScheduler
from electionguard_tpu.sim.transport import NetModel, SimTransport
from electionguard_tpu.utils import clock as clock_mod


class Simulation:
    """One installed virtual world; create via :func:`simulation`."""

    def __init__(self, seed: int, horizon: float,
                 net: Optional[NetModel] = None):
        self.sched = SimScheduler(seed=seed, horizon=horizon)
        self.net = net if net is not None else NetModel(
            rng=random.Random(seed + 1))
        self.transport = SimTransport(self.sched, self.net)

    @property
    def now(self) -> float:
        return self.sched.now

    def run(self, fn) -> None:
        """Drive ``fn`` as the main task until it returns (its
        exceptions propagate)."""
        self.sched.run(fn)

    def __enter__(self) -> "Simulation":
        clock_mod.install(SimClock(self.sched))
        rpc_util.set_transport(self.transport)
        return self

    def __exit__(self, *exc) -> None:
        rpc_util.set_transport(None)
        clock_mod.uninstall()


def simulation(seed: int = 0, horizon: float = 600.0,
               net: Optional[NetModel] = None) -> Simulation:
    return Simulation(seed, horizon, net)

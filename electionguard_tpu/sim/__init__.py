"""Deterministic simulation testing (DST) for the distributed planes.

FoundationDB/TigerBeetle-style: the full multi-node workflow — key
ceremony, encryption serving, federated mix cascade, compensated
decryption — runs in ONE process under a cooperative scheduler on a
virtual clock, with an in-memory transport standing in for gRPC.  One
RNG seed fully determines the task interleaving, the per-message
network behavior, and an auto-generated fault schedule; safety and
liveness oracles check every run, and a failing seed's schedule shrinks
to a minimal replayable repro.

Entry points:

* :func:`electionguard_tpu.sim.explore.run_sim` — one seed, one report;
* :func:`electionguard_tpu.sim.explore.explore` — a seed sweep;
* :func:`electionguard_tpu.sim.shrink.shrink` — minimize a failure;
* :func:`electionguard_tpu.sim.harness.simulation` — test harness: the
  clock + transport installed, no imposed workflow;
* ``tools/sim_matrix.py`` — the CLI sweep runner (SIM_RESULTS.json).
"""

from electionguard_tpu.sim.explore import SimReport, explore, run_sim
from electionguard_tpu.sim.harness import simulation
from electionguard_tpu.sim.schedule import FaultEvent, generate_schedule
from electionguard_tpu.sim.shrink import shrink

__all__ = ["SimReport", "run_sim", "explore", "FaultEvent",
           "generate_schedule", "shrink", "simulation"]

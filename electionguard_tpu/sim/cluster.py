"""The virtual cluster: the full multi-node workflow as sim tasks.

One simulated run drives the same four distributed phases the real
deployment runs across processes — with the SAME library classes, only
the clock and the transport virtualized:

1. **key ceremony** — a coordinator task plus one task per guardian
   (``KeyCeremonyTrusteeServer`` with a resume file, so a scheduled
   crash_after restarts the guardian mid-ceremony from its WAL);
2. **encryption serving** — an ``EncryptionService`` task and voter
   tasks submitting ballots through ``EncryptionClient`` (a retried
   admission whose first copy committed is acked via the encryptor's
   duplicate-id rejection — the ballot IS in the record);
3. **federated mix** — a ``MixCoordinator`` task and stage servers plus
   one hot spare, so a scheduled mix-server crash requeues its stage;
4. **compensated decryption** — a ``DecryptionCoordinator`` and
   ``navailable < n`` trustee tasks; the rest are compensated.

The driver then assembles the election record and runs the full
independent Verifier.  ``plant=...`` hooks inject known-bad behavior
(a lost ballot on retry, a chain break, tampered ciphertexts/tallies, a
wedge) so the test suite can prove each oracle actually fires.
"""

from __future__ import annotations

import dataclasses
import os
import random
import shutil
import threading
from dataclasses import dataclass, field

import grpc

from electionguard_tpu.ballot.manifest import (BallotStyle, Candidate,
                                               ContestDescription,
                                               GeopoliticalUnit, Manifest,
                                               Party, SelectionDescription)
from electionguard_tpu.ballot.plaintext import RandomBallotProvider
from electionguard_tpu.core.dlog import DLog
from electionguard_tpu.core.group import tiny_group
from electionguard_tpu.decrypt.decryption import Decryption
from electionguard_tpu.decrypt.trustee import read_trustee
from electionguard_tpu.keyceremony.interface import Result
from electionguard_tpu.mixfed.coordinator import MixCoordinator
from electionguard_tpu.mixfed.server import MixServerServer
from electionguard_tpu.mixnet.stage import rows_from_ballots
from electionguard_tpu.publish.election_record import (DecryptionResult,
                                                       ElectionConfig,
                                                       ElectionRecord)
from electionguard_tpu.publish import framing, serialize
from electionguard_tpu.publish.publisher import _BALLOTS, Consumer, Publisher
from electionguard_tpu.remote.decrypting_remote import (
    DecryptionCoordinator, DecryptingTrusteeServer)
from electionguard_tpu.remote.keyceremony_remote import (
    KeyCeremonyCoordinator, KeyCeremonyTrusteeServer)
from electionguard_tpu.serve.service import (EncryptionClient,
                                             EncryptionService)
from electionguard_tpu.sim import schedule as schedule_mod
from electionguard_tpu.tally.accumulate import accumulate_ballots
from electionguard_tpu.utils import clock, knobs
from electionguard_tpu.verify.verifier import Verifier

KC_PORT = 17111
SERVE_PORT = 17211
MIX_PORT = 17141
DEC_PORT = 17711


@dataclass
class SimConfig:
    """Virtual-cluster shape; defaults sized so a run takes ~100 ms of
    real time (tiny group, few ballots) while still exercising every
    protocol leg including compensation and the hot spare."""
    n_guardians: int = 3
    quorum: int = 2
    navailable: int = 2
    n_ballots: int = 4
    n_voters: int = 2
    n_mix_stages: int = 2
    n_mix_servers: int = 3      # stages + 1 hot spare
    horizon: float = field(
        default_factory=lambda: knobs.get_float("EGTPU_SIM_HORIZON"))


@dataclass
class SimOutcome:
    """Everything the oracles need from one run."""
    navailable: int = 2
    ballots: list = field(default_factory=list)      # submitted plaintext
    acked: dict = field(default_factory=dict)        # ballot_id -> code|None
    recorded: list = field(default_factory=list)     # published stream
    tally_result: object = None
    decryption_result: object = None
    verify_result: object = None
    completed: bool = False
    liveness_error: str = ""
    workflow_error: str = ""
    task_errors: list = field(default_factory=list)
    # adversary audit (soundness oracle): attacks that reached the wire
    # and the named in-band rejections the defenses recorded
    fired: list = field(default_factory=list)
    detections: list = field(default_factory=list)
    # race detector reports (analysis/race.RaceReport) when the run had
    # the monitor attached; the race oracle turns unwaived ones red
    races: list = field(default_factory=list)
    # live-verification convergence report (the "live-verify" plant):
    # live-vs-batch verdict/accept-set/commitment comparison data the
    # live_convergence oracle checks; None when the leg didn't run
    live_report: object = None


class RaceProbeBox:
    """Planted-race target for the detector's self-tests.  ``shared``
    is watched whenever the monitor is on (``run_sim(race=True)``
    passes it as an explicit extra target — it is not part of
    ANALYSIS_GUARDS.json because no production code path touches it)."""

    def __init__(self):
        self._lock_a = threading.Lock()
        self._lock_b = threading.Lock()
        self.shared = 0


def _spawn_race_probes(sched, plant: frozenset) -> None:
    """Detector self-test fixtures: each plant spawns a tiny task pair
    against a fresh :class:`RaceProbeBox`, concurrent with the real
    workflow but touching nothing else.

    * ``race-hb``      — lock-free read+write pair ordered only by
      sleeps (sleeps create no HB edge): the happens-before detector
      must fire on ``RaceProbeBox.shared``;
    * ``race-lockset`` — three accesses, every one locked and every
      pair HB-ordered by an event handoff, but the locks DIFFER: only
      the lockset heuristic can flag that no common lock protects the
      variable;
    * ``race-handoff`` — lock-free write, event set, lock-free read: a
      legal message-passing publication that must stay green (the
      false-positive guard for both detectors).
    """
    if "race-hb" in plant:
        box = RaceProbeBox()

        def hb_writer(k):
            def go():
                clock.sleep(0.001 * k)
                box.shared = box.shared + k
            return go

        sched.spawn("race-hb-1", hb_writer(1), node="driver")
        sched.spawn("race-hb-2", hb_writer(2), node="driver")
    if "race-lockset" in plant:
        box = RaceProbeBox()
        ev1, ev2 = threading.Event(), threading.Event()

        def ls_first():
            with box._lock_a:
                box.shared = 1
            ev1.set()
            clock.wait_event(ev2, 30.0)
            with box._lock_a:
                box.shared = 3

        def ls_second():
            clock.wait_event(ev1, 30.0)
            with box._lock_b:
                box.shared = 2
            ev2.set()

        sched.spawn("race-ls-1", ls_first, node="driver")
        sched.spawn("race-ls-2", ls_second, node="driver")
    if "race-handoff" in plant:
        box = RaceProbeBox()
        ev = threading.Event()

        def ho_writer():
            box.shared = 41
            ev.set()

        def ho_reader():
            clock.wait_event(ev, 30.0)
            assert box.shared == 41

        sched.spawn("race-ho-1", ho_writer, node="driver")
        sched.spawn("race-ho-2", ho_reader, node="driver")


class _MemStream:
    """In-memory stand-in for the ``EncryptedBallotStream`` — the
    authoritative published-ballot sequence for the oracles (the sim
    serves with ``out_dir=None``: no journal fsync on the hot path)."""

    def __init__(self):
        self.ballots = []

    def write(self, ballot) -> None:
        self.ballots.append(ballot)

    def flush(self) -> None:
        pass


def _live_verify_leg(group, init, out: "SimOutcome", mix_dir: str,
                     workdir: str, seed: int, sched) -> dict:
    """Replay the finished election as a GROWING record directory and
    audit it with the live verification plane (verify/live) under a
    seed-derived torture schedule: torn tails land mid-frame, polls
    interleave arbitrarily with the writer, and the verifier is
    SIGKILL'd (the incarnation dropped on the floor, no drain) and
    resumed from its on-disk checkpoint mid-stream.  Returns the
    comparison data the ``live_convergence`` oracle checks against a
    terminal single-pass fold over the same finished record: verdict,
    error list, chunk-accept set, and commitment root/chain head must
    all be bit-identical, and anything the batch pass rejects must be
    rejected live at an equal-or-earlier chunk."""
    from electionguard_tpu.verify.live import LiveVerifier

    # stream 7 of the seed: draws here perturb no honest stream
    rng = random.Random(seed * 8 + 7)
    rec_dir = os.path.join(workdir, "live_record")
    pub = Publisher(rec_dir)
    pub.write_election_initialized(init)
    for name in sorted(os.listdir(mix_dir)):
        if name.startswith("mix_stage_"):
            shutil.copy(os.path.join(mix_dir, name),
                        os.path.join(rec_dir, name))

    chunk = rng.choice((1, 2, 3))
    live = LiveVerifier(rec_dir, group, chunk=chunk)
    crashes = torn = 0
    frames = [serialize.publish_encrypted_ballot(b).SerializeToString()
              for b in out.recorded]
    with open(os.path.join(rec_dir, _BALLOTS), "ab") as f:
        def land(blob: bytes) -> None:
            f.write(blob)
            f.flush()

        for fr in frames:
            blob = len(fr).to_bytes(framing.HEADER_LEN, "big") + fr
            if rng.random() < 0.3:
                # torn tail: a partial frame lands and the tailer polls
                # it — must classify "retry", never "corrupt" — then the
                # remainder completes the frame
                cut = rng.randrange(1, len(blob))
                land(blob[:cut])
                live.poll()
                torn += 1
                land(blob[cut:])
            else:
                land(blob)
            if rng.random() < 0.6:
                live.poll()
            if rng.random() < 0.25:
                crashes += 1
                live = LiveVerifier(rec_dir, group, chunk=chunk)
    pub.write_tally_result(out.tally_result)
    pub.write_decryption_result(out.decryption_result)
    if rng.random() < 0.5:   # one more kill after the stream closed
        crashes += 1
        live = LiveVerifier(rec_dir, group, chunk=chunk)
    live_res = live.finalize()

    # the terminal comparator: a fresh single-pass fold over the
    # finished record at the SAME chunk size (chunk boundaries are a
    # pure function of frame index, so this IS the batch pass)
    batch = LiveVerifier(rec_dir, group, chunk=chunk,
                         checkpoint_path=os.path.join(
                             workdir, "live_batch_checkpoint.json"))
    batch_res = batch.finalize()
    live_accepts = [c.accepted for c in live.ledger.chunks]
    batch_accepts = [c.accepted for c in batch.ledger.chunks]

    def first_reject(accepts):
        return next((i for i, a in enumerate(accepts) if not a), None)

    sched.event("live-verify",
                f"chunk={chunk} crashes={crashes} torn={torn} "
                f"ok={live_res.ok} chunks={len(live_accepts)}")
    return {
        "chunk": chunk, "crashes": crashes, "torn": torn,
        "n_frames": len(frames),
        "live_ok": live_res.ok,
        "live_checks": dict(live_res.checks),
        "live_errors": list(live_res.errors),
        "batch_ok": batch_res.ok,
        "batch_checks": dict(batch_res.checks),
        "batch_errors": list(batch_res.errors),
        "live_accepts": live_accepts,
        "batch_accepts": batch_accepts,
        "live_first_reject": first_reject(live_accepts),
        "batch_first_reject": first_reject(batch_accepts),
        "live_root": live.ledger.root().hex(),
        "batch_root": batch.ledger.root().hex(),
        "live_head": live.ledger.head.hex(),
        "batch_head": batch.ledger.head.hex(),
    }


def sim_manifest() -> Manifest:
    """One contest, two selections — the smallest record the full
    Verifier accepts (mirrors the test suite's tiny manifest)."""
    sels = tuple(SelectionDescription(f"sel-{i}", i, f"cand-{i}")
                 for i in range(2))
    contest = ContestDescription("contest-0", 0, "gp-0", "one_of_m", 1,
                                 "The Contest", sels)
    return Manifest(
        election_scope_id="sim-election", spec_version="tpu-1.0",
        start_date="2026-07-01", end_date="2026-07-29",
        geopolitical_units=(GeopoliticalUnit("gp-0", "District 0"),),
        parties=(Party("party-0", "Party"),),
        candidates=tuple(Candidate(f"cand-{i}", f"Candidate {i}")
                         for i in range(2)),
        contests=(contest,),
        ballot_styles=(BallotStyle("style-0", ("gp-0",)),),
    )


def drive(cfg: SimConfig, sched, transport, plan, schedule, seed: int,
          plant: frozenset, workdir: str, out: SimOutcome) -> None:
    """The main task: spawn each phase's nodes, sequence via a shared
    board, assemble + verify the record into ``out``."""
    group = tiny_group()
    manifest = sim_manifest()
    out.navailable = cfg.navailable
    board: dict = {}

    def wait(pred, timeout: float, what: str) -> None:
        if not sched.poll_until(pred, timeout):
            raise RuntimeError(f"timed out waiting for {what} "
                               f"(t={sched.now:.1f}s)")

    # ---- crash/restart hook ------------------------------------------
    def on_crash(srv, method: str) -> None:
        node = srv.node
        sched.kill_node(node)
        if node.startswith("guardian-"):
            downtime = schedule_mod.guardian_downtime(schedule)
            resume = os.path.join(workdir, f"{node}.resume")

            def restart(node=node, resume=resume, downtime=downtime):
                clock.sleep(downtime)
                s = KeyCeremonyTrusteeServer(
                    group, node, f"localhost:{KC_PORT}",
                    resume_file=resume)
                s.wait_until_finished(timeout=150.0)

            sched.spawn(f"{node}-restart", restart, node=node)
        # a crashed mix server is NOT restarted: the hot spare takes
        # its stage (coordinator requeue path)

    transport.on_crash = on_crash
    _spawn_race_probes(sched, plant)

    # ---- phase 1: key ceremony ---------------------------------------
    def kc_task():
        coord = KeyCeremonyCoordinator(group, cfg.n_guardians, cfg.quorum,
                                       port=KC_PORT)
        try:
            if not coord.wait_for_registrations(timeout=90.0, poll=0.25):
                raise RuntimeError("key ceremony registrations timed out")
            results = coord.run_key_ceremony(workdir)
            if isinstance(results, Result):
                raise RuntimeError(f"key ceremony failed: {results.error}")
            board["init"] = results.make_election_initialized(
                ElectionConfig(manifest, cfg.n_guardians, cfg.quorum),
                {"created_by": "sim"})
        finally:
            coord.shutdown("init" in board)

    sched.spawn("kc", kc_task, node="kc")
    for i in range(cfg.n_guardians):
        gid = f"guardian-{i}"

        def g_task(gid=gid):
            s = KeyCeremonyTrusteeServer(
                group, gid, f"localhost:{KC_PORT}",
                resume_file=os.path.join(workdir, f"{gid}.resume"))
            s.wait_until_finished(timeout=150.0)

        sched.spawn(gid, g_task, node=gid)
    wait(lambda: "init" in board, 150.0, "key ceremony")
    init = board["init"]

    # ---- phase 2: encryption serving ---------------------------------
    ballots = list(RandomBallotProvider(
        manifest, cfg.n_ballots, seed=seed % 100003 + 11).ballots())
    out.ballots = ballots
    stream = _MemStream()

    def serve_task():
        svc = EncryptionService(
            init, group, port=SERVE_PORT, out_dir=None, max_batch=4,
            max_wait_ms=4.0, prewarm=False,
            seed=group.int_to_q(seed % (group.q - 2) + 1))
        # the record stream the oracles audit (no out_dir => no file)
        svc.worker.stream = stream
        board["serve_up"] = True
        wait(lambda: len(board.get("voters_done", ())) == cfg.n_voters,
             150.0, "voters")
        svc.drain(grace=0.25)
        board["served"] = True

    sched.spawn("serve", serve_task, node="serve")

    def voter_task(vi: int, mine) -> None:
        wait(lambda: board.get("serve_up"), 60.0, "serving plane")
        client = EncryptionClient(f"localhost:{SERVE_PORT}", group)
        try:
            for b in mine:
                for attempt in range(4):
                    try:
                        eb = client.encrypt(b, timeout=30.0)
                        out.acked[b.ballot_id] = eb.code
                        break
                    except ValueError as e:
                        if "duplicate" in str(e):
                            # the retried copy of an admission whose
                            # response was dropped: the first copy is
                            # committed and recorded — that IS the ack
                            out.acked[b.ballot_id] = None
                            break
                        if ("[serve.invalid_ballot]" in str(e)
                                or "[validate." in str(e)):
                            # an adversary mangled this submission (or
                            # forged the returned ciphertext, which the
                            # client's ingestion gate refused); the
                            # honest voter resubmits the real ballot —
                            # a committed first admission answers the
                            # retry with the duplicate path above
                            continue
                        raise
                    except grpc.RpcError:
                        if attempt == 3:
                            raise
                        clock.sleep(0.5 * (attempt + 1))
        finally:
            client.close()
            board.setdefault("voters_done", set()).add(vi)

    for vi in range(cfg.n_voters):
        mine = ballots[vi::cfg.n_voters]
        sched.spawn(f"voter-{vi}", lambda vi=vi, mine=mine:
                    voter_task(vi, mine), node=f"voter-{vi}")
    wait(lambda: board.get("served"), 200.0, "serving drained")

    recorded = stream.ballots
    if "lost-ballot" in plant and recorded and any(
            m == "encryptBallot" and k == "drop_response"
            for (_w, m, _n, k) in plan.injected):
        # planted bug: the retry-dedup path "eats" the committed record
        # entry — the classic exactly-once violation the oracle exists
        # to catch
        lost = recorded.pop()
        sched.event("plant", f"lost-ballot {lost.ballot_id}")
    if "chain-break" in plant and len(recorded) >= 2:
        recorded[0], recorded[1] = recorded[1], recorded[0]
        sched.event("plant", "chain-break")
    if "tamper-ballot" in plant and recorded:
        b = recorded[0]
        c = b.contests[0]
        s0, s1 = c.selections[0], c.selections[1]
        tampered = (dataclasses.replace(s0, ciphertext=s1.ciphertext),
                    dataclasses.replace(s1, ciphertext=s0.ciphertext),
                    *c.selections[2:])
        recorded[0] = dataclasses.replace(
            b, contests=(dataclasses.replace(c, selections=tampered),))
        sched.event("plant", "tamper-ballot")
    out.recorded = list(recorded)

    # ---- phase 3: tally + federated mix ------------------------------
    tally_result = accumulate_ballots(init, out.recorded)
    out.tally_result = tally_result
    pads, datas = rows_from_ballots(out.recorded)
    mix_dir = os.path.join(workdir, "mix")
    os.makedirs(mix_dir, exist_ok=True)

    def mix_task():
        coord = MixCoordinator(group, mix_dir, port=MIX_PORT)
        try:
            if not coord.wait_for_servers(cfg.n_mix_servers, timeout=90.0):
                raise RuntimeError("mix server registrations timed out")
            coord.run_mix(init.joint_public_key.value,
                          init.extended_base_hash, cfg.n_mix_stages,
                          pads, datas)
            board["mixed"] = True
        finally:
            coord.shutdown(board.get("mixed", False))

    sched.spawn("mix", mix_task, node="mix")
    for i in range(cfg.n_mix_servers):
        def m_task(i=i):
            s = MixServerServer(group, f"localhost:{MIX_PORT}",
                                f"mix-{i}", shards=0)
            s.wait_until_finished(timeout=200.0)

        sched.spawn(f"mix-{i}", m_task, node=f"mix-{i}")
    wait(lambda: board.get("mixed"), 250.0, "mix cascade")

    # ---- phase 4: compensated decryption -----------------------------
    guardian_ids = [g.guardian_id for g in init.guardians]
    available = guardian_ids[:cfg.navailable]   # the rest are compensated
    dlog = DLog(group, max_exponent=max(16, cfg.n_ballots + 2))

    def dec_task():
        coord = DecryptionCoordinator(group, cfg.navailable, port=DEC_PORT)
        ok = False
        try:
            if not coord.wait_for_registrations(timeout=90.0):
                raise RuntimeError("decryption registrations timed out")
            coord.mark_started()
            proxies = coord.registered()
            registered = {p.id for p in proxies}
            missing = [g for g in guardian_ids if g not in registered]
            decryption = Decryption(group, init, proxies, missing,
                                    dlog)
            decrypted = decryption.decrypt(tally_result.encrypted_tally)
            out.decryption_result = DecryptionResult(
                tally_result, decrypted,
                tuple(decryption.get_available_guardians()))
            ok = True
            board["decrypted"] = True
        finally:
            coord.shutdown(ok)

    sched.spawn("decrypt", dec_task, node="decrypt")
    for idx, gid in enumerate(available):
        def d_task(idx=idx, gid=gid):
            trustee = read_trustee(
                group, os.path.join(workdir, f"trustee-{gid}.json"))
            s = DecryptingTrusteeServer(group, trustee,
                                        f"localhost:{DEC_PORT}")
            s.wait_until_finished(timeout=200.0)

        sched.spawn(f"dec-{idx}", d_task, node=f"dec-{idx}")
    wait(lambda: board.get("decrypted"), 250.0, "threshold decryption")

    if "tamper-tally" in plant and out.decryption_result is not None:
        dt = out.decryption_result.decrypted_tally
        c0 = dt.contests[0]
        s0 = c0.selections[0]
        new_c0 = dataclasses.replace(
            c0, selections=(dataclasses.replace(s0, tally=s0.tally + 1),
                            *c0.selections[1:]))
        out.decryption_result = dataclasses.replace(
            out.decryption_result,
            decrypted_tally=dataclasses.replace(
                dt, contests=(new_c0, *dt.contests[1:])))
        sched.event("plant", "tamper-tally")

    if "wedge" in plant:
        clock.sleep(cfg.horizon * 2)   # livelock: the horizon must trip

    # ---- phase 5: record assembly + independent verification ---------
    record = ElectionRecord(
        election_init=init,
        encrypted_ballots=list(out.recorded),
        tally_result=tally_result,
        decryption_result=out.decryption_result,
        mix_stages=Consumer(mix_dir, group).read_mix_stages())
    out.verify_result = Verifier(
        record, group, mix_input_fn=lambda: (pads, datas)).verify()

    # ---- phase 5.5 (optional): live-verification convergence ---------
    if "live-verify" in plant:
        out.live_report = _live_verify_leg(group, init, out, mix_dir,
                                           workdir, seed, sched)
    out.completed = True
    sched.event("workflow-complete", f"{len(out.recorded)} ballots")

"""In-sim process model: whole OS processes as scheduler events.

The deterministic sim (sim/scheduler) virtualizes *threads* — every
clock-seam call is a yield point — but the chaos planes still model
whole processes with real subprocesses (``workflow/run_command.py``):
SIGKILL drills burn real wall-clock and sit outside the trace hash.
:class:`SimProcess` closes that gap: a simulated process is a task
group keyed by the scheduler's node tag, with the RunCommand lifecycle
(SPAWNING → RUNNING → {EXITED, KILLED}) driven entirely by scheduler
events on the virtual clock.

* **spawn** — ``SimProcess(...)`` / ``SimProcess.python_module(...)``
  mirrors ``RunCommand.python_module``: the "module" names an entry
  point registered via :func:`register_entry` (the in-sim stand-in for
  ``python -m module``), the env dict is snapshotted per incarnation,
  and a ``proc-spawn`` event lands in the trace.
* **kill / kill_hard** — tears down every task of the process's node
  via the scheduler's existing ``kill_node`` (tasks unwind with
  ``TaskKilled`` at their next yield point) and records ``proc-kill``;
  ``poll()`` flips to the signal-style exit code immediately, like a
  SIGKILLed subprocess.
* **restart / restart_on_exit** — replays the SAME entry point with
  the (possibly env-stripped) resume env on the SAME node tag, after a
  virtual downtime; ``proc-restart`` lands in the trace, so a replayed
  seed reproduces the whole crash/recovery story bit-for-bit.

Every lifecycle transition is a ``sched.event(...)`` — the sha256
event-trace hash therefore covers process chaos exactly as it covers
dispatch decisions, which is what makes kill/restart schedules
replayable artifacts instead of wall-clock races.

Install the current scheduler with :func:`install` for the duration of
a run (the election driver and the test harness do this) so
``SimProcess.python_module`` can mirror ``RunCommand.python_module``'s
signature without threading the scheduler through every call site.
"""

from __future__ import annotations

import sys
from typing import Callable, Optional

from electionguard_tpu.sim.scheduler import SimScheduler, TaskKilled
from electionguard_tpu.utils import clock, knobs

#: lifecycle states (string-valued so they read well in traces/logs)
SPAWNING = "SPAWNING"
RUNNING = "RUNNING"
EXITED = "EXITED"
KILLED = "KILLED"

#: signal-style exit codes reported after kill()/kill_hard(), mirroring
#: what a real subprocess.poll() returns after SIGTERM / SIGKILL
EXIT_TERM = -15
EXIT_KILL = -9

#: registered in-sim entry points: module name -> fn(flags, env) -> rc.
#: The in-sim twin of ``python -m module flags...``; entries run inside
#: the process's task, may block only through the clock seam, and their
#: return value (None = 0) is the process exit code.
_ENTRIES: dict[str, Callable] = {}

_SCHED: Optional[SimScheduler] = None


def register_entry(module: str, fn: Callable) -> None:
    """Register ``fn(flags: list[str], env: dict) -> int|None`` as the
    in-sim entry point for ``python -m module``."""
    _ENTRIES[module] = fn


def entry_for(module: str) -> Callable:
    fn = _ENTRIES.get(module)
    if fn is None:
        raise KeyError(
            f"no in-sim entry registered for module {module!r}; "
            f"register_entry() one of {sorted(_ENTRIES) or '(none yet)'}")
    return fn


def install(sched: SimScheduler) -> None:
    """Make ``sched`` the ambient scheduler for ``python_module`` (one
    sim at a time, like ``utils.clock.install``)."""
    global _SCHED
    _SCHED = sched


def uninstall() -> None:
    global _SCHED
    _SCHED = None


def current_scheduler() -> SimScheduler:
    if _SCHED is None:
        raise RuntimeError("no sim scheduler installed "
                           "(procmodel.install(sched) first)")
    return _SCHED


class SimProcess:
    """One simulated process: a task group on its own node tag, with
    the ``RunCommand`` control surface (`wait_for`/`poll`/`kill`/
    `kill_hard`/`restart`/`restart_on_exit`/`show`)."""

    def __init__(self, name: str, entry: Callable, flags: list[str],
                 env: Optional[dict] = None,
                 sched: Optional[SimScheduler] = None,
                 node: Optional[str] = None):
        self.name = name
        self.entry = entry
        self.flags = list(flags)
        self._env = dict(env or {})
        self.sched = sched or current_scheduler()
        #: the scheduler node tag that owns every task this process
        #: spawns — kill() is exactly ``kill_node(self.node)``
        self.node = node or f"proc:{name}"
        self.state = SPAWNING
        self.exit_code: Optional[int] = None
        self.error: Optional[BaseException] = None
        #: (virtual_t, transition) lifecycle log for show()
        self.log: list[tuple[float, str]] = []
        self._gen = 0
        self._spawn()

    # ---- construction mirror -----------------------------------------
    @staticmethod
    def python_module(name: str, module: str, flags: list[str],
                      output_dir: str, env: Optional[dict] = None
                      ) -> "SimProcess":
        """Signature twin of ``RunCommand.python_module`` — launch the
        registered in-sim entry for ``module`` instead of a subprocess.
        ``output_dir`` is accepted for interface parity (a sim process
        captures its story in the trace, not in stdout files)."""
        env = dict(env or {})
        env.setdefault("EGTPU_OBS_PROC", name)
        return SimProcess(name, entry_for(module), flags, env)

    # ---- lifecycle ---------------------------------------------------
    def _mark(self, transition: str) -> None:
        self.log.append((self.sched.now, transition))

    def _spawn(self) -> None:
        gen = self._gen
        self.state = SPAWNING
        self.exit_code = None
        self._mark("spawn")
        self.sched.event("proc-spawn", f"{self.name} gen={gen}")
        env = dict(self._env)

        def body():
            if self._gen != gen:
                return                      # superseded by a restart
            self.state = RUNNING
            self._mark("running")
            self.sched.event("proc-running", self.name)
            rc: Optional[int] = 0
            try:
                rc = self.entry(list(self.flags), env)
            except TaskKilled:
                # kill()/kill_hard() already recorded the transition
                return
            except SystemExit as e:         # an entry's sys.exit(rc)
                rc = e.code if isinstance(e.code, int) else 1
            except BaseException as e:      # noqa: BLE001 - nonzero exit
                if self.state == KILLED or self._gen != gen:
                    return
                self.error = e
                rc = 1
            if self.state == KILLED or self._gen != gen:
                return
            self.state = EXITED
            self.exit_code = int(rc or 0)
            self._mark(f"exit rc={self.exit_code}")
            self.sched.event("proc-exit",
                             f"{self.name} rc={self.exit_code}")

        self.sched.spawn(f"proc:{self.name}#g{gen}", body, node=self.node)

    def _kill(self, transition: str, code: int) -> None:
        if self.exit_code is not None:
            return                          # already down
        self.state = KILLED
        self.exit_code = code
        self._mark(transition)
        self.sched.event(f"proc-{transition}", self.name)
        # unwind every task of this process at its next yield point
        self.sched.kill_node(self.node)

    def kill(self) -> None:
        """Simulated SIGTERM→SIGKILL: in the sim both are the same
        instantaneous teardown (there are no signal handlers to drain),
        reported with the SIGTERM-style code for API parity."""
        self._kill("kill", EXIT_TERM)

    def kill_hard(self) -> None:
        """Simulated SIGKILL — no handlers, no atexit, no drain: the
        node's tasks unwind with ``TaskKilled`` wherever they are."""
        self._kill("kill-hard", EXIT_KILL)

    def restart(self) -> None:
        """Replay the SAME entry point (same flags, current env
        snapshot — e.g. after ``restart_on_exit`` stripped a fault
        knob) on the same node.  The previous incarnation must be
        down, mirroring ``RunCommand.restart``."""
        if self.exit_code is None:
            raise RuntimeError(f"{self.name} still running; kill first")
        self._gen += 1
        self._mark("restart")
        self.sched.event("proc-restart", f"{self.name} gen={self._gen}")
        self._spawn()

    def restart_on_exit(self, strip_env: tuple[str, ...] = (),
                        downtime_s: Optional[float] = None) -> None:
        """Arm a watcher task (on the driver node, so it survives the
        process's own kill) that waits for this process's FIRST exit,
        strips ``strip_env`` keys from the resume env, sleeps the
        virtual ``downtime_s`` (default ``EGTPU_SIM_PROC_DOWNTIME_S``),
        and restarts it once — the virtual twin of
        ``RunCommand.restart_on_exit``."""
        down = (knobs.get_float("EGTPU_SIM_PROC_DOWNTIME_S")
                if downtime_s is None else downtime_s)

        def fire():
            self.sched.poll_until(lambda: self.exit_code is not None,
                                  None)
            for k in strip_env:
                self._env.pop(k, None)
            clock.sleep(down)
            self.restart()

        self.sched.spawn(f"chaos-{self.name}", fire, node="driver")

    # ---- observation mirror ------------------------------------------
    def wait_for(self, timeout: float) -> Optional[int]:
        """Virtual-time wait (call from inside a sim task); returns the
        exit code, or None on timeout."""
        self.sched.poll_until(lambda: self.exit_code is not None, timeout)
        return self.exit_code

    def poll(self) -> Optional[int]:
        return self.exit_code

    def env(self) -> dict:
        """The env snapshot the NEXT incarnation would receive."""
        return dict(self._env)

    def show(self, stream=sys.stdout) -> None:
        print(f"----- {self.name} " + "-" * 40, file=stream)
        print(f"  flags: {' '.join(self.flags)}", file=stream)
        print(f"  state: {self.state}  exit: {self.exit_code}",
              file=stream)
        for t, what in self.log:
            print(f"  t={t:10.3f}s  {what}", file=stream)
        if self.error is not None:
            print(f"  error: {self.error!r}", file=stream)


def wait_all(procs: list[SimProcess], timeout: float) -> bool:
    """Virtual-time twin of ``run_command.wait_all``: wait for every
    process, kill stragglers at the deadline."""
    deadline = clock.monotonic() + timeout
    ok = True
    for p in procs:
        remaining = max(0.0, deadline - clock.monotonic())
        code = p.wait_for(remaining)
        if code is None:
            p.kill()
            ok = False
        elif code != 0:
            ok = False
    return ok

"""Delta-debugging shrinker for failing fault schedules.

Given a seed whose schedule violates an oracle, ``shrink`` searches for
a minimal sub-schedule that still reproduces a violation of the same
oracle class, using ddmin (Zeller's delta debugging) followed by a
greedy one-by-one removal pass.  Every probe is a full deterministic
re-run — same seed, candidate schedule — so the result is a replayable
repro artifact: ``(seed, minimal schedule)`` fails identically on any
checkout.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

from electionguard_tpu.sim import cluster
from electionguard_tpu.sim import schedule as schedule_mod


def _oracle_class(violation: str) -> str:
    return violation.split(":", 1)[0]


@dataclass
class ShrinkResult:
    """Minimal failing schedule + the evidence trail."""
    schedule: list[schedule_mod.FaultEvent]
    violations: list[str]
    runs: int
    exhausted: bool = False            # budget ran out before a fixpoint
    history: list[tuple[int, int]] = field(default_factory=list)

    def repro_json(self) -> str:
        return schedule_mod.to_json(self.schedule)


def shrink(seed: int,
           schedule: list[schedule_mod.FaultEvent],
           plant: Sequence[str] = (),
           config: Optional[cluster.SimConfig] = None,
           oracle_classes: Optional[frozenset[str]] = None,
           budget: Optional[int] = None,
           race: bool = False,
           strategy: Optional[str] = None) -> ShrinkResult:
    """Minimize ``schedule`` while a violation of the same oracle class
    persists under ``run_sim(seed, candidate)``.

    ``oracle_classes`` defaults to the classes the full schedule
    violates (so the shrinker cannot wander onto an unrelated failure);
    ``budget`` caps the number of probe runs
    (``EGTPU_SIM_SHRINK_BUDGET``).  ``race``/``strategy`` replay with
    the race monitor attached under the same scheduler strategy, so a
    ``race:`` violation reproduces during probes (its oracle class is
    ``race`` like any other).
    """
    from electionguard_tpu.sim.explore import run_sim   # avoid cycle
    from electionguard_tpu.utils import knobs

    if budget is None:
        budget = knobs.get_int("EGTPU_SIM_SHRINK_BUDGET")
    runs = 0

    def failing(candidate: list[schedule_mod.FaultEvent]) -> list[str]:
        nonlocal runs
        runs += 1
        report = run_sim(seed, schedule=candidate, plant=plant,
                         config=config, race=race, strategy=strategy)
        hits = [v for v in report.violations
                if oracle_classes is None
                or _oracle_class(v) in oracle_classes]
        return hits

    base = failing(list(schedule))
    if not base:
        return ShrinkResult(schedule=list(schedule), violations=[],
                            runs=runs)
    if oracle_classes is None:
        oracle_classes = frozenset(_oracle_class(v) for v in base)
        base = [v for v in base if _oracle_class(v) in oracle_classes]

    # trivial minimum first: a violation that reproduces with NO faults
    # (typical for races — the interleaving is the bug) short-circuits
    # the whole ddmin descent with the truly minimal repro
    if schedule:
        hits = failing([])
        if hits:
            return ShrinkResult(schedule=[], violations=hits, runs=runs,
                                history=[(runs, 0)])

    current = list(schedule)
    violations = base
    history = [(runs, len(current))]
    exhausted = False

    # ddmin: try dropping chunks of shrinking granularity
    n = 2
    while len(current) >= 2:
        if runs >= budget:
            exhausted = True
            break
        chunk = max(1, len(current) // n)
        reduced = False
        for start in range(0, len(current), chunk):
            candidate = current[:start] + current[start + chunk:]
            if not candidate or runs >= budget:
                continue
            hits = failing(candidate)
            if hits:
                current, violations = candidate, hits
                history.append((runs, len(current)))
                n = max(2, n - 1)
                reduced = True
                break
        if not reduced:
            if chunk == 1:
                break
            n = min(len(current), n * 2)

    # greedy tail: one-by-one removal until a fixpoint
    changed = True
    while changed and len(current) > 1 and runs < budget:
        changed = False
        for i in range(len(current)):
            candidate = current[:i] + current[i + 1:]
            if runs >= budget:
                exhausted = True
                break
            hits = failing(candidate)
            if hits:
                current, violations = candidate, hits
                history.append((runs, len(current)))
                changed = True
                break

    return ShrinkResult(schedule=current, violations=violations,
                        runs=runs, exhausted=exhausted, history=history)

"""Seed-driven fault schedules: generation, FaultPlan assembly, JSON.

A schedule is a flat list of :class:`FaultEvent` — the unit the
shrinker removes.  ``generate_schedule(rng)`` draws a schedule that is
*survivable by construction*: every event class it can emit is one the
planes are built to ride out (bounded partitions inside the retry
windows, crashes only of restartable guardians / spared mix servers,
drops only on idempotent rpcs), so the liveness oracle ("the workflow
completes before the horizon") is a real invariant, not a coin flip.

Events map onto two carriers:

* protocol faults (latency, drop_response, unavailable, crash) become
  a ``testing.faults.FaultPlan`` — the SAME deterministic Nth-call
  injection machinery the real chaos suite uses, firing at exact
  protocol points;
* link faults (partition, duplicate delivery, connection death) become
  the transport's :class:`~electionguard_tpu.sim.transport.NetModel`.

``to_json`` / ``from_json`` round-trip a schedule so a shrunk failing
schedule is a replayable artifact (SIM_RESULTS.json, bug reports).
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass

from electionguard_tpu.sim import adversary
from electionguard_tpu.sim.transport import NetModel, Partition
from electionguard_tpu.testing.faults import FaultPlan, FaultRule
from electionguard_tpu.utils import knobs

# rpcs whose response can be dropped after the state change commits:
# each has an explicit idempotent-replay path (registration nonces,
# chunk overwrite, cross-batch ballot dedup, pure recompute)
DROPPABLE = ("registerTrustee", "registerMixServer", "encryptBallot",
             "receivePublicKeys", "receiveSecretKeyShare", "pushRows",
             "shuffleStage")

# transient client-side failures: every Stub retries UNAVAILABLE
FLAKEABLE = DROPPABLE + ("sendPublicKeys", "sendSecretKeyShare",
                         "pullRows", "directDecrypt",
                         "compensatedDecrypt")

# trustee-server rpcs whose handler checkpoints (WAL) before the
# response, so a crash immediately after is restart-recoverable
GUARDIAN_CRASH_POINTS = ("receivePublicKeys", "receiveSecretKeyShare",
                         "receiveChallengedShare")

# mix-server rpcs; a crashed mix server is replaced by the hot spare
MIX_CRASH_POINTS = ("pushRows", "shuffleStage")

# node pairs partitions may sever (every window is bounded well inside
# the retry budget: 3 attempts x 5s connect windows + backoff)
PARTITION_LINKS = (("kc", "guardian-0"), ("kc", "guardian-1"),
                   ("kc", "guardian-2"), ("voter-0", "serve"),
                   ("voter-1", "serve"), ("mix", "mix-0"),
                   ("mix", "mix-1"), ("decrypt", "dec-0"))

MAX_PARTITION_S = 4.0
MAX_GUARDIAN_DOWNTIME_S = 3.0


@dataclass(frozen=True)
class FaultEvent:
    """One schedulable fault.  ``kind`` selects which fields matter:

    * ``latency``        — method, nth, seconds
    * ``drop_response``  — method, nth
    * ``unavailable``    — method, nth (client side)
    * ``crash_guardian`` — method, nth, seconds (downtime before restart)
    * ``crash_mix``      — method, nth
    * ``partition``      — a, b, t0, seconds (duration)
    * ``duplicate``      — seconds (delivery-duplication probability)
    * ``conn_death``     — nth (global message index that dies in flight)
    * ``adversary``      — method (= attack name from sim/adversary.py),
      a (target node, '' = any), nth (firing call index)
    """
    kind: str
    method: str = ""
    nth: int = 0
    a: str = ""
    b: str = ""
    t0: float = 0.0
    seconds: float = 0.0


def generate_schedule(rng) -> list[FaultEvent]:
    """Draw 0–4 survivable fault events from ``rng`` (random.Random)."""
    events: list[FaultEvent] = []
    kinds = (["latency"] * 3 + ["drop_response"] * 3 + ["unavailable"] * 2
             + ["partition"] * 2 + ["crash_guardian", "crash_mix",
                                    "duplicate", "conn_death"])
    crashed_guardian = crashed_mix = False
    for _ in range(rng.randint(0, 4)):
        kind = rng.choice(kinds)
        if kind == "latency":
            events.append(FaultEvent(
                "latency", method=rng.choice(FLAKEABLE),
                nth=rng.randint(1, 4),
                seconds=round(rng.uniform(0.05, 0.8), 3)))
        elif kind == "drop_response":
            events.append(FaultEvent(
                "drop_response", method=rng.choice(DROPPABLE),
                nth=rng.randint(1, 3)))
        elif kind == "unavailable":
            events.append(FaultEvent(
                "unavailable", method=rng.choice(FLAKEABLE),
                nth=rng.randint(1, 3)))
        elif kind == "partition":
            a, b = rng.choice(PARTITION_LINKS)
            events.append(FaultEvent(
                "partition", a=a, b=b,
                t0=round(rng.uniform(0.0, 30.0), 3),
                seconds=round(rng.uniform(0.5, MAX_PARTITION_S), 3)))
        elif kind == "crash_guardian" and not crashed_guardian:
            crashed_guardian = True
            events.append(FaultEvent(
                "crash_guardian",
                method=rng.choice(GUARDIAN_CRASH_POINTS),
                nth=rng.randint(1, 4),
                seconds=round(rng.uniform(0.5, MAX_GUARDIAN_DOWNTIME_S),
                              3)))
        elif kind == "crash_mix" and not crashed_mix:
            crashed_mix = True
            events.append(FaultEvent(
                "crash_mix", method=rng.choice(MIX_CRASH_POINTS),
                nth=rng.randint(1, 2)))
        elif kind == "duplicate":
            events.append(FaultEvent(
                "duplicate", seconds=round(rng.uniform(0.01, 0.08), 3)))
        elif kind == "conn_death":
            events.append(FaultEvent(
                "conn_death", nth=rng.randint(5, 80)))
    return events


def generate_adversary_schedule(rng) -> list[FaultEvent]:
    """Draw 1–EGTPU_SIM_ADV_MAX in-protocol attacks from ``rng`` (its
    own isolated stream, so adding adversaries never perturbs the fault
    or scheduler draws of the same seed).  Unlike faults, a schedule
    always carries at least one attack — an adversary sweep where some
    seeds are honest would dilute the soundness claim."""
    try:
        cap = max(1, knobs.get_int("EGTPU_SIM_ADV_MAX"))
    except ValueError:
        cap = 2
    corpus = adversary.corpus()
    events: list[FaultEvent] = []
    seen = set()
    for _ in range(rng.randint(1, cap)):
        atk = corpus[rng.randrange(len(corpus))]
        node = atk.targets[rng.randrange(len(atk.targets))]
        nth = rng.randint(*atk.nth_range)
        key = (atk.name, node, nth)
        if key in seen:
            continue
        seen.add(key)
        events.append(FaultEvent("adversary", method=atk.name, nth=nth,
                                 a=node))
    return events


def generate_param_schedule(rng) -> list[FaultEvent]:
    """Draw 1–2 parameter-level attacks (forged group elements, ISSUE
    17) from ``rng``.  Same event shape as the Byzantine schedule —
    kind="adversary" — so :func:`to_adversary_plan`, the shrinker and
    the JSON round-trip all work unchanged; only the corpus differs."""
    corpus = adversary.param_corpus()
    events: list[FaultEvent] = []
    seen = set()
    for _ in range(rng.randint(1, 2)):
        atk = corpus[rng.randrange(len(corpus))]
        node = atk.targets[rng.randrange(len(atk.targets))]
        nth = rng.randint(*atk.nth_range)
        # dedup on the RPC CALL, not the attack name: two attacks
        # mutating the same (method, node, nth) message would mask each
        # other — the gate rejects on the first failing check, so the
        # second attack fires without its expected class ever appearing
        key = (atk.rules[0][0], node, nth)
        if key in seen:
            continue
        seen.add(key)
        events.append(FaultEvent("adversary", method=atk.name, nth=nth,
                                 a=node))
    return events


def to_adversary_plan(events: list[FaultEvent]):
    """The adversary slice of a schedule as an
    :class:`~electionguard_tpu.sim.adversary.AdversaryPlan` (empty plan
    when the schedule carries no attacks, so the caller can install it
    unconditionally)."""
    return adversary.plan_from_events(
        [(e.method, e.a, e.nth) for e in events if e.kind == "adversary"])


def to_fault_plan(events: list[FaultEvent]) -> FaultPlan:
    """The protocol-fault slice of a schedule as a FaultPlan (the
    caller wires ``plan.crash_cb`` to the transport)."""
    rules = []
    for e in events:
        if e.kind == "latency":
            rules.append(FaultRule(method=e.method, kind="latency",
                                   on_calls=(e.nth,), latency_s=e.seconds,
                                   where="server"))
        elif e.kind == "drop_response":
            rules.append(FaultRule(method=e.method, kind="drop_response",
                                   on_calls=(e.nth,)))
        elif e.kind == "unavailable":
            rules.append(FaultRule(method=e.method, kind="unavailable",
                                   on_calls=(e.nth,), where="client"))
        elif e.kind in ("crash_guardian", "crash_mix"):
            rules.append(FaultRule(method=e.method, kind="crash_after",
                                   on_calls=(e.nth,)))
    return FaultPlan(rules=rules)


def net_model(events: list[FaultEvent], rng) -> NetModel:
    """The link-fault slice of a schedule as the transport's NetModel."""
    dup = 0.0
    partitions = []
    kills = set()
    for e in events:
        if e.kind == "duplicate":
            dup = max(dup, e.seconds)
        elif e.kind == "partition":
            partitions.append(Partition(e.a, e.b, e.t0, e.seconds))
        elif e.kind == "conn_death":
            kills.add(e.nth)
    return NetModel(rng=rng, dup_prob=dup, partitions=tuple(partitions),
                    kill_msgs=frozenset(kills))


def guardian_downtime(events: list[FaultEvent]) -> float:
    """Restart delay for a scheduled guardian crash (default when the
    schedule carries none — hand-built schedules in tests)."""
    for e in events:
        if e.kind == "crash_guardian" and e.seconds > 0:
            return e.seconds
    return 1.0


def to_json(events: list[FaultEvent]) -> str:
    return json.dumps([asdict(e) for e in events], sort_keys=True)


def from_json(text: str) -> list[FaultEvent]:
    return [FaultEvent(**d) for d in json.loads(text)]

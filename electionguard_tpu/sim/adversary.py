"""Seeded in-protocol adversaries for the deterministic sim.

Where ``testing/faults.py`` models an honest-but-unlucky world (crashes,
drops, partitions), this registry models *malice*: named attacks in
which a protocol participant itself misbehaves — a trustee serving a bad
Schnorr proof or a share that fails the polynomial check, a guardian
equivocating about its identity, a mix server tampering with its output
after proving or replaying a previous stage's transcript, a client
submitting malformed/duplicate ballots or replaying a stale
registration nonce.

Attacks mount at the SAME hook points the fault plans use, so the
honest path has zero call-site changes:

* server side — :func:`wrap_server_impl` is consulted by
  ``rpc_util.generic_service`` through the late-binding
  ``rpc_util._adversary_wrap`` seam (set when this module imports, so
  real honest processes never pay for it);
* client side — the sim transport asks the active plan to mutate or
  forge-duplicate outbound requests (``AdversaryPlan.apply_client``);
* behavior — a misbehaving server consults the plan directly
  (:func:`mix_tamper_fires`), which is also where the old
  ``EGTPU_MIX_TAMPER`` drill now lands: the knob is a thin env alias
  that mounts the ``mix_tamper_output`` adversary.

Every attack is deterministic: rules fire on exact per-(side, method,
node) call indices derived from the schedule's seed, mutators are pure
functions of the message, and ``fired`` is an audit log the soundness
oracle checks against the run's detections — an attack that fired and
was never rejected in-band nor caught by the verifier is an oracle
violation.

This module stays a leaf of the sim package (stdlib + ``rpc_util``,
which honest processes import anyway) so the mixfed server's gated
import and the rpc_util seam cannot drag the heavy sim package into
honest processes.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Callable, Optional

from electionguard_tpu.remote import rpc_util

# pseudo-method key for behavior rules: the mixfed server consults the
# plan at its tamper decision point; no rpc by this name exists
MIX_TAMPER_METHOD = "__mix_tamper__"

_CLIENT_KINDS = ("mutate_request", "forge_dup")
_SERVER_KINDS = ("mutate_response", "replay_response", "behavior")


def _copy(msg):
    c = type(msg)()
    c.CopyFrom(msg)
    return c


def _flip(b: bytes) -> bytes:
    """Same-width corruption (serialize importers width-check, so the
    mutated field must still parse — wrong value, right shape)."""
    v = bytearray(b or b"\x00")
    v[-1] ^= 0x01
    return bytes(v)


# ---------------------------------------------------------------- rules


@dataclass(frozen=True)
class AdvRule:
    """One mounted misbehavior.  ``node`` narrows the rule to a single
    sim node ('' = any); ``on_calls`` are 1-based per-(side, method,
    node) call indices ('' rules count globally); ``mutate`` edits a
    message in place and returns True iff it really changed it (so
    ``fired`` never records a no-op)."""

    attack: str
    method: str
    kind: str
    on_calls: tuple[int, ...] = ()
    node: str = ""
    mutate: Optional[Callable] = None

    @property
    def side(self) -> str:
        return "client" if self.kind in _CLIENT_KINDS else "server"


class AdversaryPlan:
    """Active set of adversary rules plus the audit state the soundness
    oracle reads: ``fired`` (attack, method, call, node) records every
    misbehavior that actually reached the wire."""

    def __init__(self, rules=()):
        self.rules: tuple[AdvRule, ...] = tuple(rules)
        # sim wires this to transport.current_node; None = real process
        self.node_fn: Optional[Callable[[], str]] = None
        self._lock = threading.Lock()
        self._counts: dict = {}
        self._captures: dict = {}
        self._tls = threading.local()
        self.fired: list[tuple[str, str, int, str]] = []

    def current_node(self) -> str:
        fn = self.node_fn
        return fn() if fn is not None else ""

    def has_rules(self, side: str, method: str) -> bool:
        return any(r.side == side and r.method == method
                   for r in self.rules)

    def firing(self, side: str, method: str, node: str):
        """Advance the call counters and return the [(rule, n)] that
        fire on this call.  Node-scoped rules match the per-node count,
        ''-rules the global one."""
        with self._lock:
            kg = (side, method, "")
            ng = self._counts[kg] = self._counts.get(kg, 0) + 1
            nn = ng
            if node:
                kn = (side, method, node)
                nn = self._counts[kn] = self._counts.get(kn, 0) + 1
        hits = []
        for r in self.rules:
            if r.side != side or r.method != method:
                continue
            if r.node and r.node != node:
                continue
            n = nn if r.node else ng
            if not r.on_calls or n in r.on_calls:
                hits.append((r, n))
        return hits

    def record_fired(self, rule: AdvRule, n: int, node: str) -> None:
        """Durable audit entry: the misbehavior changed state some
        defense can still see (a request that reached its handler, a
        tampered server-side artifact)."""
        with self._lock:
            self.fired.append((rule.attack, rule.method, n, node))

    def record_fired_response(self, rule: AdvRule, n: int,
                              node: str) -> None:
        """Audit entry for a RESPONSE-only misbehavior: staged while a
        delivery scope is open (sim transport), because a mutated or
        replayed response that dies in flight is never seen by any
        defense — the honest retry supersedes it, and counting it as
        fired would be a false soundness violation."""
        staged = getattr(self._tls, "staged", None)
        if staged is not None:
            staged.append((rule.attack, rule.method, n, node))
        else:
            self.record_fired(rule, n, node)

    # delivery scopes (sim transport): recordings made between begin
    # and end land in ``fired`` only if the response was delivered
    # (commit=True) — or, nested, in the enclosing scope's staging
    def begin_delivery(self):
        prev = getattr(self._tls, "staged", None)
        self._tls.staged = []
        return prev

    def end_delivery(self, token, commit: bool) -> None:
        staged = getattr(self._tls, "staged", None) or []
        self._tls.staged = token
        if not commit or not staged:
            return
        if token is not None:
            token.extend(staged)
        else:
            with self._lock:
                self.fired.extend(staged)

    # replay support: the first response seen for a method is cached;
    # a firing replay rule substitutes it for the live answer
    def wants_capture(self, method: str) -> bool:
        return any(r.kind == "replay_response" and r.method == method
                   for r in self.rules)

    def capture(self, method: str, resp) -> None:
        with self._lock:
            self._captures.setdefault(method, _copy(resp))

    def captured(self, method: str):
        with self._lock:
            resp = self._captures.get(method)
        return _copy(resp) if resp is not None else None

    def apply_client(self, method: str, node: str, request):
        """Client-side hook (sim transport): returns
        ``(request_to_send, pending, forged)`` where ``pending`` is the
        [(rule, n)] to record as fired once the real dispatch succeeds
        and ``forged`` is [(rule, n, message)] extra requests to
        dispatch after it (duplicate/replayed submissions)."""
        hits = self.firing("client", method, node)
        req_out, pending, forged = request, [], []
        for rule, n in hits:
            if rule.kind == "mutate_request" and rule.mutate is not None:
                cand = _copy(req_out)
                if rule.mutate(cand):
                    req_out = cand
                    pending.append((rule, n))
            elif rule.kind == "forge_dup":
                cand = _copy(request)
                if rule.mutate is None or rule.mutate(cand):
                    forged.append((rule, n, cand))
        return req_out, pending, forged


# ------------------------------------------------------- install/clear

_install_lock = threading.Lock()
_active: Optional[AdversaryPlan] = None
_loaded_env = False


def install(plan: Optional[AdversaryPlan]) -> Optional[AdversaryPlan]:
    global _active, _loaded_env
    with _install_lock:
        _active = plan
        _loaded_env = True
    return plan


def clear() -> None:
    install(None)


def active_plan() -> Optional[AdversaryPlan]:
    global _active, _loaded_env
    with _install_lock:
        if not _loaded_env:
            _loaded_env = True
            _active = _plan_from_env()
        return _active


def _plan_from_env() -> Optional[AdversaryPlan]:
    # EGTPU_MIX_TAMPER is a thin alias for the mix_tamper_output
    # adversary: "1" tampers on any server's first stage, any other
    # value names the one server that tampers.
    val = os.environ.get("EGTPU_MIX_TAMPER")
    if not val:
        return None
    node = "" if val == "1" else val
    return AdversaryPlan(build("mix_tamper_output", node, 1))


# ------------------------------------------------------------ mutators


def _mut_bad_schnorr(resp) -> bool:
    """Corrupt the first coefficient proof's challenge: the key set no
    longer validates (kc.bad_proof at the coordinator)."""
    if resp.error or not resp.coefficient_proofs:
        return False
    ch = resp.coefficient_proofs[0].challenge
    ch.value = _flip(ch.value)
    return True


def _mut_equivocate(resp) -> bool:
    """Claim another identity for an otherwise-valid key set: the
    coordinator's identity binding (kc.equivocation) must refuse it."""
    if resp.error or not resp.guardian_id:
        return False
    resp.guardian_id = resp.guardian_id + "-evil"
    return True


def _mut_bad_share(resp) -> bool:
    """Corrupt the encrypted coordinate's body: the designated guardian's
    MAC check fails (polynomial share unusable), forcing the challenge
    path (kc.bad_share)."""
    if resp.error or not resp.HasField("encrypted_coordinate"):
        return False
    enc = resp.encrypted_coordinate
    enc.c1 = _flip(enc.c1)
    return True


def _mut_bad_challenge(resp) -> bool:
    """Answer a share challenge with a wrong coordinate: the public
    commitment-product check must fail (kc.challenge_failed)."""
    if resp.error or not resp.HasField("coordinate"):
        return False
    resp.coordinate.value = _flip(resp.coordinate.value)
    return True


def _mut_swap_commitments(resp) -> bool:
    """Collude on the permutation transcript: reorder two permutation
    commitments (each still a valid group element) so the proof no
    longer matches the shuffle it claims."""
    if resp.error or not resp.HasField("header"):
        return False
    pc = resp.header.proof.permutation_commitments
    if len(pc) < 2:
        return False
    tmp = _copy(pc[0])
    pc[0].CopyFrom(pc[1])
    pc[1].CopyFrom(tmp)
    return True


def _mut_malformed_ballot(req) -> bool:
    """Submit a ballot naming a selection the manifest doesn't have:
    admission must reject it in-band (serve.invalid_ballot)."""
    if not req.ballot.contests or not req.ballot.contests[0].selections:
        return False
    req.ballot.contests[0].selections[0].selection_id = "evil-write-in"
    return True


def _mut_stale_nonce(req) -> bool:
    """Replay a registration under the same guardian id with a stale
    nonce — a relaunched/forged trustee must be refused, not silently
    merged (rpc.stale_registration)."""
    if not req.registration_nonce:
        return False
    req.registration_nonce = _flip(req.registration_nonce)
    return True


# ---------------------------------------------- parameter-level mutators
#
# These forge *group elements themselves* rather than protocol state:
# wrong-subgroup keys, small-order ciphertexts, identity shares,
# non-canonical wire values, out-of-range proof responses.  Every one
# is a deterministic function of the honest message and the sim's group
# constants, and every one must die at the ingestion gate
# (crypto/validate.py) with its named [validate.*] class — the terminal
# verifier never gets to see the poisoned value.

_SIM_GROUP = None


def _sim_group():
    """The sim cluster's group (tiny_group), imported lazily so this
    module keeps its leaf-import contract for processes that only
    mount the rpc_util seam."""
    global _SIM_GROUP
    if _SIM_GROUP is None:
        from electionguard_tpu.core.group import tiny_group
        _SIM_GROUP = tiny_group()
    return _SIM_GROUP


def _negate_commitment(resp, idx: int) -> bool:
    """Replace coefficient commitment ``idx`` with its negation p−v:
    still canonical and non-identity, but (−v)^q = −1 for odd q, so it
    is provably outside the order-q subgroup."""
    if resp.error or not resp.coefficient_commitments:
        return False
    g = _sim_group()
    cm = resp.coefficient_commitments[idx]
    v = int.from_bytes(cm.value, "big")
    if not 1 < v < g.p - 1:
        return False
    cm.value = (g.p - v).to_bytes(g.spec.p_bytes, "big")
    return True


def _mut_param_nonsubgroup_key(resp) -> bool:
    """Trustee answers sendPublicKeys with a first commitment outside
    the subgroup: the keyceremony gate's RLC screen goes red
    (validate.nonsubgroup)."""
    return _negate_commitment(resp, 0)


def _mut_param_smuggled_commitment(resp) -> bool:
    """Same forgery buried in the LAST commitment of an otherwise-valid
    key set: the red batch's bisection must name exactly this element
    (validate.nonsubgroup)."""
    if resp.error or len(resp.coefficient_commitments) < 2:
        return False
    return _negate_commitment(resp, len(resp.coefficient_commitments) - 1)


def _mut_param_small_order_ct(resp) -> bool:
    """Serving plane returns a ballot whose first pad is p−1: canonical
    and non-identity but of order 2 — only the small-order check at the
    client's ingestion gate sees it (validate.small_order)."""
    if resp.error or not resp.HasField("encrypted_ballot"):
        return False
    eb = resp.encrypted_ballot
    if not eb.contests or not eb.contests[0].selections:
        return False
    g = _sim_group()
    ct = eb.contests[0].selections[0].ciphertext
    ct.pad.value = (g.p - 1).to_bytes(g.spec.p_bytes, "big")
    return True


def _mut_param_identity_share(resp) -> bool:
    """Decrypting trustee returns the identity as a partial-decryption
    share — a do-nothing share that would silently corrupt the tally if
    combined (validate.identity at the decrypt gate)."""
    if resp.error or not resp.results:
        return False
    g = _sim_group()
    resp.results[0].partial_decryption.value = (1).to_bytes(
        g.spec.p_bytes, "big")
    return True


def _mut_param_wrong_group(req) -> bool:
    """Trustee registers under different group constants: the
    fingerprint comparison at registration must refuse it
    (validate.group_mismatch)."""
    if not req.group_fingerprint:
        return False
    req.group_fingerprint = _flip(req.group_fingerprint)
    return True


def _mut_param_noncanonical(resp) -> bool:
    """First commitment set to x = p: parses at wire width but is not a
    canonical residue — dies in the range check before any arithmetic
    (validate.range)."""
    if resp.error or not resp.coefficient_commitments:
        return False
    g = _sim_group()
    resp.coefficient_commitments[0].value = g.p.to_bytes(
        g.spec.p_bytes, "big")
    return True


def _mut_param_oor_response(resp) -> bool:
    """First coefficient proof's response set to q — a Z_q field
    smuggled out of range (validate.response_range)."""
    if resp.error or not resp.coefficient_proofs:
        return False
    g = _sim_group()
    resp.coefficient_proofs[0].response.value = g.q.to_bytes(
        g.spec.q_bytes, "big")
    return True


def _mut_noop(resp) -> bool:
    """Planted no-op 'attack' (test-only, not in the corpus): fires but
    changes nothing, so NO defense can detect it — the guaranteed
    soundness-oracle violation the planted tests and the shrinker
    demonstration need."""
    return True


# ------------------------------------------------------------ registry


@dataclass(frozen=True)
class Attack:
    """One named in-protocol attack.  ``rules`` are templates
    ``(method, kind, mutate, every)`` instantiated by :func:`build`;
    ``expect`` are the named error classes / detection classes ANY ONE
    of which counts as the defense firing; ``targets`` and
    ``nth_range`` bound the seed-derived draws in
    ``schedule.generate_adversary_schedule``."""

    name: str
    doc: str
    expect: tuple[str, ...]
    targets: tuple[str, ...]
    rules: tuple
    nth_range: tuple[int, int] = (1, 1)
    in_corpus: bool = True


_GUARDIANS = ("guardian-0", "guardian-1", "guardian-2")
_MIXERS = ("mix-0", "mix-1")
_VOTERS = ("voter-0", "voter-1")

ATTACKS: tuple[Attack, ...] = (
    Attack(
        "kc_bad_schnorr",
        "trustee serves a public key set whose Schnorr proof is wrong",
        expect=("kc.bad_proof",),
        targets=_GUARDIANS,
        rules=(("sendPublicKeys", "mutate_response",
                _mut_bad_schnorr, False),),
    ),
    Attack(
        "kc_equivocate",
        "trustee claims a different identity to the coordinator than "
        "it registered under",
        expect=("kc.equivocation",),
        targets=_GUARDIANS,
        rules=(("sendPublicKeys", "mutate_response",
                _mut_equivocate, False),),
    ),
    Attack(
        "kc_bad_share_mac",
        "trustee serves an encrypted key share that fails the MAC / "
        "polynomial check at its designated guardian",
        expect=("kc.bad_share", "kc.challenge_failed"),
        targets=_GUARDIANS,
        rules=(("sendSecretKeyShare", "mutate_response",
                _mut_bad_share, False),),
        nth_range=(1, 2),
    ),
    Attack(
        "kc_bad_challenge",
        "trustee serves a bad share AND answers the resulting challenge "
        "with a wrong coordinate",
        expect=("kc.challenge_failed",),
        targets=_GUARDIANS,
        rules=(("sendSecretKeyShare", "mutate_response",
                _mut_bad_share, False),
               ("challengeShare", "mutate_response",
                _mut_bad_challenge, True)),
        nth_range=(1, 2),
    ),
    Attack(
        "mix_tamper_output",
        "mix server corrupts its shuffled rows AFTER proving "
        "(the EGTPU_MIX_TAMPER drill, registry form)",
        expect=("mix.binding", "mix.reencryption", "mix.permutation"),
        targets=_MIXERS,
        rules=((MIX_TAMPER_METHOD, "behavior", None, False),),
    ),
    Attack(
        "mix_swap_commitments",
        "mix server reorders its permutation commitments — a colluded "
        "transcript over a different permutation than it shuffled",
        expect=("mix.binding", "mix.permutation", "mix.reencryption",
                "mix.chain", "mix.membership", "mix.structure"),
        targets=_MIXERS,
        rules=(("shuffleStage", "mutate_response",
                _mut_swap_commitments, False),),
    ),
    Attack(
        "mix_replay_transcript",
        "mix server answers a stage request with a previous stage's "
        "full transcript (result AND rows)",
        # the stage-binding checks (replay/transfer/input_mismatch)
        # catch a replay against the wrong stage; a replay of a
        # transcript another attack poisoned instead fails stage
        # verification, so the whole verify family counts as detection
        expect=("mix.replay", "mix.transfer", "mix.input_mismatch",
                "mix.binding", "mix.reencryption", "mix.permutation",
                "mix.chain", "mix.structure"),
        targets=("",),
        rules=(("shuffleStage", "replay_response", None, False),
               ("pullRows", "replay_response", None, False)),
        nth_range=(2, 2),
    ),
    Attack(
        "client_malformed_ballot",
        "client submits a ballot naming a selection outside the "
        "manifest",
        expect=("serve.invalid_ballot",),
        targets=_VOTERS,
        rules=(("encryptBallot", "mutate_request",
                _mut_malformed_ballot, False),),
        nth_range=(1, 2),
    ),
    Attack(
        "client_duplicate_ballot",
        "client submits the same ballot twice (forged duplicate "
        "delivery of an honest submission)",
        expect=("serve.duplicate_ballot",),
        targets=_VOTERS,
        rules=(("encryptBallot", "forge_dup", None, False),),
        nth_range=(1, 2),
    ),
    Attack(
        "client_stale_nonce",
        "stale/forged re-registration under an existing guardian id "
        "with a different nonce",
        expect=("rpc.stale_registration",),
        targets=_GUARDIANS,
        rules=(("registerTrustee", "forge_dup",
                _mut_stale_nonce, False),),
    ),
    # ---- parameter-level family (ISSUE 17): forged group elements.
    # Not in the Byzantine corpus — drawn by
    # schedule.generate_param_schedule via param_corpus(), so the
    # existing adversary sweeps keep their seed-for-seed schedules.
    Attack(
        "param_nonsubgroup_key",
        "trustee's first coefficient commitment replaced by p-v — a "
        "canonical non-subgroup key",
        expect=("validate.nonsubgroup",),
        targets=_GUARDIANS,
        rules=(("sendPublicKeys", "mutate_response",
                _mut_param_nonsubgroup_key, False),),
        in_corpus=False,
    ),
    Attack(
        "param_smuggled_commitment",
        "non-subgroup element buried in the LAST commitment of an "
        "otherwise-valid key set (bisection attribution drill)",
        expect=("validate.nonsubgroup",),
        targets=_GUARDIANS,
        rules=(("sendPublicKeys", "mutate_response",
                _mut_param_smuggled_commitment, False),),
        in_corpus=False,
    ),
    Attack(
        "param_small_order_ciphertext",
        "serving plane answers with a ballot whose pad is the order-2 "
        "element p-1",
        expect=("validate.small_order",),
        targets=("serve",),
        rules=(("encryptBallot", "mutate_response",
                _mut_param_small_order_ct, False),),
        nth_range=(1, 2),
        in_corpus=False,
    ),
    Attack(
        "param_identity_share",
        "decrypting trustee returns the identity element as its "
        "partial-decryption share",
        expect=("validate.identity",),
        targets=("dec-0", "dec-1"),
        rules=(("directDecrypt", "mutate_response",
                _mut_param_identity_share, False),),
        in_corpus=False,
    ),
    Attack(
        "param_wrong_group_trustee",
        "trustee registers with a different group-constants "
        "fingerprint than the coordinator's",
        expect=("validate.group_mismatch",),
        targets=_GUARDIANS,
        rules=(("registerTrustee", "mutate_request",
                _mut_param_wrong_group, False),),
        in_corpus=False,
    ),
    Attack(
        "param_noncanonical_element",
        "trustee's first commitment set to x = p — right wire width, "
        "non-canonical value",
        expect=("validate.range",),
        targets=_GUARDIANS,
        rules=(("sendPublicKeys", "mutate_response",
                _mut_param_noncanonical, False),),
        in_corpus=False,
    ),
    Attack(
        "param_out_of_range_response",
        "trustee's first coefficient proof carries a response >= q",
        expect=("validate.response_range",),
        targets=_GUARDIANS,
        rules=(("sendPublicKeys", "mutate_response",
                _mut_param_oor_response, False),),
        in_corpus=False,
    ),
    Attack(
        "adv_noop",
        "planted undetectable no-op (test-only): proves the soundness "
        "oracle fires",
        expect=(),
        targets=("",),
        # mounted on sendPublicKeys, not finish: a sim node stops
        # serving once it handles finish, so finish's first response
        # always dies in flight and a response-side firing there would
        # be (correctly) discarded by the delivery scope
        rules=(("sendPublicKeys", "mutate_response", _mut_noop, False),),
        in_corpus=False,
    ),
)

REGISTRY: dict[str, Attack] = {a.name: a for a in ATTACKS}


def corpus() -> tuple[Attack, ...]:
    return tuple(a for a in ATTACKS if a.in_corpus)


def param_corpus() -> tuple[Attack, ...]:
    """The parameter-level family (forged group elements), drawn by
    ``schedule.generate_param_schedule``.  Kept out of :func:`corpus`
    so the Byzantine sweeps' seed-for-seed schedules are unchanged."""
    return tuple(a for a in ATTACKS if a.name.startswith("param_"))


def expected_for(attack_name: str) -> set[str]:
    a = REGISTRY.get(attack_name)
    return set(a.expect) if a is not None else set()


def build(attack_name: str, node: str, nth: int) -> tuple[AdvRule, ...]:
    """Instantiate one attack's rules against ``node`` at call ``nth``
    (rules templated ``every=True`` fire on all of the node's calls)."""
    a = REGISTRY[attack_name]
    return tuple(
        AdvRule(a.name, method, kind,
                on_calls=() if every else (nth,),
                node=node, mutate=mutate)
        for method, kind, mutate, every in a.rules)


def plan_from_events(items) -> AdversaryPlan:
    """An :class:`AdversaryPlan` from ``(attack, node, nth)`` triples
    (schedule events).  Duplicate MOUNTS are dropped, not just
    duplicate events: several attacks share involutive mutators (e.g.
    kc_bad_challenge embeds kc_bad_share_mac's share flip), so two
    attacks mounting the same (method, kind, node, calls, mutator)
    would cancel each other — composing them must yield the stronger
    attack instead."""
    rules: list[AdvRule] = []
    seen = set()
    for name, node, nth in items:
        if name not in REGISTRY:
            continue
        for rule in build(name, node, nth):
            key = (rule.method, rule.kind, rule.node, rule.on_calls,
                   rule.mutate)
            if key in seen:
                continue
            seen.add(key)
            rules.append(rule)
    return AdversaryPlan(rules)


# ------------------------------------------------------------ mounting


def wrap_server_impl(method: str, fn):
    """Server-side mount point (rpc_util.generic_service, via the
    ``_adversary_wrap`` seam).  Consulted at server-construction time;
    returns ``fn`` unchanged unless the active plan targets it."""
    plan = active_plan()
    if plan is None or not plan.has_rules("server", method):
        return fn

    def adversarial(request, context):
        node = plan.current_node()
        hits = plan.firing("server", method, node)
        replay = next((h for h in hits
                       if h[0].kind == "replay_response"), None)
        if replay is not None:
            cached = plan.captured(method)
            if cached is not None:
                rule, n = replay
                plan.record_fired_response(rule, n, node)
                return cached
        resp = fn(request, context)
        if plan.wants_capture(method):
            plan.capture(method, resp)
        for rule, n in hits:
            if rule.kind == "mutate_response" and rule.mutate is not None:
                if rule.mutate(resp):
                    plan.record_fired_response(rule, n, node)
        return resp

    return adversarial


def mix_tamper_fires(server_id: str) -> bool:
    """Behavior mount point: the mixfed server asks, once per shuffled
    stage, whether THIS server tampers with THIS stage's output."""
    plan = active_plan()
    if plan is None or not plan.has_rules("server", MIX_TAMPER_METHOD):
        return False
    fired = False
    for rule, n in plan.firing("server", MIX_TAMPER_METHOD, server_id):
        if rule.kind == "behavior":
            plan.record_fired(rule, n, server_id)
            fired = True
    return fired


# late-binding seam: honest processes that never import this module
# never consult it; any process that CAN host an adversary (the sim, or
# a mixfed server with EGTPU_MIX_TAMPER set) imports it and thereby
# mounts the server-side hook
rpc_util._adversary_wrap = wrap_server_impl

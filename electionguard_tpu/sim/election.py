"""Virtual elections at scale: 10^6 ballots on the virtual clock.

The capacity plane (PR 18) *predicts* a million-ballot election from
the ``BENCH_BIGNUM.json`` rooflines; this driver *plays one out*.  The
control plane runs at full fidelity — admission → micro-batching →
hash-chained journal → mix cascade → compensated decrypt →
live-verifier chunking, with the serve workers as :class:`SimProcess`
incarnations that can be SIGKILL'd and restarted mid-election — while
the crypto plane runs ONCE per distinct batch shape on the tiny group
and the device time for the full batch comes from the fitted
:class:`~electionguard_tpu.sim.devicemodel.DeviceModel`.  Full
protocol fidelity, scaled device time (the SZKP-style roofline
projection, arXiv 2408.05890).

The representative crypto (ceremony, per-shape batch encrypt, mix
stages, compensated decrypt, terminal verify) executes in a *prelude*
before the scheduler starts: jit compilation is real wall-clock the
watchdog must not mistake for a stuck task, and the representatives
depend only on the seed, never on the interleaving — so hoisting them
changes no observable event.  Inside the sim, workers replay the memo
cache and the clock advances by fitted device cost.

What makes the run a *measurement* rather than a demo:

* every lifecycle/journal/phase transition is a scheduler event, so a
  same-seed rerun reproduces the trace hash bit-for-bit — including
  through a mid-election worker kill/restart with its in-flight batch
  requeued (exactly-once journaling);
* the played-out phase timeline uses ``capacity.predict``'s phase
  names, and ``egplan --validate`` gates simulated-vs-predicted
  wall-clock within ``EGTPU_CAPACITY_TOL`` — the prediction and the
  sim share per-op rates, so the gate checks the *composition*
  (queueing on a shared device, micro-batch rounding, Amdahl'd worker
  drain, residual verification) against the closed form;
* the oracles are the real ones: no ballot lost, journal chain
  contiguous, real Verifier green over the representative record,
  compensated quorum tally exact, live/batch verifier convergence
  bit-identical.
"""

from __future__ import annotations

import hashlib
import os
import random
import shutil
import tempfile
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from electionguard_tpu.obs import capacity
from electionguard_tpu.sim import procmodel
from electionguard_tpu.sim.devicemodel import DeviceModel
from electionguard_tpu.sim.scheduler import SimClock, SimScheduler
from electionguard_tpu.utils import clock as clock_mod
from electionguard_tpu.utils import knobs

#: real (host) clock for wall-time reporting while the sim clock is
#: installed at the seam
_REAL = clock_mod.Clock()

#: the in-sim module names the election's processes launch under
#: (procmodel mirrors of ``RunCommand.python_module``'s module arg)
WORKER_MODULE = "electionguard_tpu.sim.election.serve_worker"
LIVE_MODULE = "electionguard_tpu.sim.election.live_verifier"


@dataclass(frozen=True)
class ElectionSpec:
    """One virtual-election configuration.  ``ballots`` is the virtual
    electorate; ``rep_ballots`` caps how many are actually encrypted
    per distinct batch shape (the crypto-plane representatives)."""

    ballots: int = 1_000_000
    batch: int = 8192              # admission micro-batch (journal unit)
    rep_ballots: int = 64          # real-arithmetic cap per batch shape
    workers: int = 16
    chips: int = 8
    backend: str = "cios"
    mix_stages: int = 2
    n_guardians: int = 3
    quorum: int = 2
    navailable: int = 2            # rest decrypt by compensation
    chaos_after_batches: int = 3   # chaos: kill a worker after N batches
    horizon: float = 5e6           # virtual-seconds cap

    @staticmethod
    def from_knobs() -> "ElectionSpec":
        return ElectionSpec(
            ballots=knobs.get_int("EGTPU_SIM_SCALE_BALLOTS"),
            batch=knobs.get_int("EGTPU_SIM_SCALE_BATCH"),
            rep_ballots=knobs.get_int("EGTPU_SIM_SCALE_REP"),
            workers=knobs.get_int("EGTPU_SIM_SCALE_WORKERS"),
            chips=knobs.get_int("EGTPU_SIM_SCALE_CHIPS"))

    def plan(self) -> capacity.Plan:
        """The analytic twin ``egplan --validate`` compares against."""
        return capacity.Plan(
            ballots=self.ballots, workers=self.workers,
            chips=self.chips, mix_stages=self.mix_stages,
            backend=self.backend, batch_verify=True, live_verify=True)


@dataclass
class PhaseSpan:
    """One played-out phase, named to match ``capacity.predict``."""

    name: str
    t0: float
    t1: float

    @property
    def seconds(self) -> float:
        return self.t1 - self.t0

    def to_json(self) -> dict:
        return {"name": self.name, "t0": round(self.t0, 6),
                "t1": round(self.t1, 6),
                "seconds": round(self.seconds, 6)}


class Journal:
    """The admission journal: hash-chained (batch_id, count) entries.
    The chain head lands in the trace, so the journal's exact content
    and order are covered by bit-for-bit replay."""

    def __init__(self):
        self.entries: list[tuple[int, int, bytes]] = []
        self.head = hashlib.sha256(b"egtpu-journal").digest()
        self._ids: set[int] = set()

    def append(self, batch_id: int, count: int) -> None:
        if batch_id in self._ids:
            raise ValueError(f"duplicate journal batch {batch_id}")
        self.head = hashlib.sha256(
            self.head + f"{batch_id}|{count}".encode()).digest()
        self.entries.append((batch_id, count, self.head))
        self._ids.add(batch_id)

    def has(self, batch_id: int) -> bool:
        return batch_id in self._ids

    def total(self) -> int:
        return sum(n for _, n, _ in self.entries)

    def chain_ok(self) -> bool:
        head = hashlib.sha256(b"egtpu-journal").digest()
        for bid, n, h in self.entries:
            head = hashlib.sha256(head + f"{bid}|{n}".encode()).digest()
            if head != h:
                return False
        return head == self.head


@dataclass
class ElectionReport:
    """What one virtual election measured."""

    seed: int
    ok: bool
    violations: list
    trace_hash: str
    events: int
    virtual_s: float
    wall_s: float
    ballots: int
    batches: int
    timeline: list                      # list[PhaseSpan]
    journal_head: str
    device_busy_s: dict = field(default_factory=dict)
    live: dict = field(default_factory=dict)
    chaos: bool = False

    def phase_seconds(self) -> dict:
        return {s.name: s.seconds for s in self.timeline}

    def modeled_total_s(self) -> float:
        """The gate's total: every phase ``capacity.predict`` also
        prices (i.e. excluding the ceremony prologue)."""
        return sum(s.seconds for s in self.timeline
                   if s.name != "ceremony")

    def to_json(self) -> dict:
        return {"seed": self.seed, "ok": self.ok,
                "violations": list(self.violations),
                "trace_hash": self.trace_hash, "events": self.events,
                "virtual_s": round(self.virtual_s, 3),
                "wall_s": round(self.wall_s, 3),
                "ballots": self.ballots, "batches": self.batches,
                "timeline": [s.to_json() for s in self.timeline],
                "journal_head": self.journal_head,
                "device_busy_s": {k: round(v, 3) for k, v
                                  in self.device_busy_s.items()},
                "chaos": self.chaos,
                "live": dict(self.live)}


def _batches(spec: ElectionSpec) -> list[tuple[int, int]]:
    out, left, bid = [], spec.ballots, 0
    while left > 0:
        n = min(spec.batch, left)
        out.append((bid, n))
        left -= n
        bid += 1
    return out


class _Prelude:
    """The real (representative) crypto, computed seed-deterministically
    before the scheduler starts."""

    def __init__(self, spec: ElectionSpec, seed: int):
        from electionguard_tpu.ballot.plaintext import RandomBallotProvider
        from electionguard_tpu.core.dlog import DLog
        from electionguard_tpu.core.group import tiny_group
        from electionguard_tpu.decrypt.decryption import Decryption
        from electionguard_tpu.decrypt.trustee import DecryptingTrustee
        from electionguard_tpu.encrypt.encryptor import BatchEncryptor
        from electionguard_tpu.keyceremony.exchange import \
            key_ceremony_exchange
        from electionguard_tpu.keyceremony.trustee import KeyCeremonyTrustee
        from electionguard_tpu.mixnet.stage import (rows_from_ballots,
                                                    run_stage)
        from electionguard_tpu.publish.election_record import (
            DecryptionResult, ElectionConfig, ElectionRecord)
        from electionguard_tpu.sim.cluster import sim_manifest
        from electionguard_tpu.tally.accumulate import accumulate_ballots
        from electionguard_tpu.verify.verifier import Verifier

        self.spec, self.seed = spec, seed
        g = self.group = tiny_group()
        manifest = self.manifest = sim_manifest()

        # ceremony (3 guardians, quorum 2 by default)
        trustees = [KeyCeremonyTrustee(g, f"guardian-{i}", i + 1,
                                       spec.quorum)
                    for i in range(spec.n_guardians)]
        init = self.init = key_ceremony_exchange(
            trustees, g).make_election_initialized(
                ElectionConfig(manifest, spec.n_guardians, spec.quorum),
                {"created_by": "sim-election"})

        # per-shape representative encryption (memo the workers replay)
        enc = BatchEncryptor(init, g)
        nonce = g.int_to_q(seed % (g.q - 2) + 1)
        self.rep_cache: dict[int, tuple[list, list]] = {}
        for _, size in _batches(spec):
            n = min(size, spec.rep_ballots)
            if n not in self.rep_cache:
                plain = list(RandomBallotProvider(
                    manifest, n, seed=seed % 100003 + 11).ballots())
                encrypted, invalid = enc.encrypt_ballots(
                    plain, seed=nonce, timestamp=int(SimClock.EPOCH))
                if invalid:
                    raise RuntimeError(f"rep encrypt invalid: {invalid}")
                self.rep_cache[n] = (plain, encrypted)

        # the headline representative record: the full-batch shape
        plain, encrypted = self.rep_cache[
            min(spec.batch, spec.ballots, spec.rep_ballots)]
        self.plain, self.encrypted = plain, encrypted

        # mix cascade over the representative rows, seed-pinned
        self.stages = []
        pads, datas = rows_from_ballots(encrypted)
        self.pads0, self.datas0 = pads, datas
        for k in range(spec.mix_stages):
            st = run_stage(
                g, init.joint_public_key.value, init.extended_base_hash,
                k, pads, datas,
                seed=hashlib.sha256(f"mix|{seed}|{k}".encode()).digest())
            self.stages.append(st)
            pads, datas = st.pads, st.datas

        # compensated decrypt (navailable of n, rest by Lagrange)
        tally_result = self.tally_result = accumulate_ballots(init,
                                                              encrypted)
        dec_trustees = [DecryptingTrustee.from_state(
            g, t.decrypting_trustee_state()) for t in trustees]
        missing = [t.id for t in dec_trustees[spec.navailable:]]
        decryption = Decryption(
            g, init, dec_trustees[:spec.navailable], missing,
            DLog(g, max_exponent=len(encrypted) + 16))
        self.decrypted = decryption.decrypt(tally_result.encrypted_tally)
        self.dr = DecryptionResult(
            tally_result, self.decrypted,
            tuple(decryption.get_available_guardians()))

        # terminal batch verify of the representative record
        record = ElectionRecord(init, encrypted_ballots=list(encrypted),
                                tally_result=tally_result,
                                decryption_result=self.dr,
                                mix_stages=self.stages)
        self.vres = Verifier(
            record, g,
            mix_input_fn=lambda: (self.pads0, self.datas0)).verify()

    def quorum_tally_violations(self) -> list:
        """Compensated decrypt totals must equal the plaintext truth."""
        truth: dict[tuple, int] = {}
        for b in self.plain:
            for c in b.contests:
                for s in c.selections:
                    key = (c.contest_id, s.selection_id)
                    truth[key] = truth.get(key, 0) + s.vote
        out = []
        for c in self.decrypted.contests:
            for s in c.selections:
                if s.tally != truth.get((c.contest_id, s.selection_id),
                                        0):
                    out.append(f"quorum tally mismatch {c.contest_id}/"
                               f"{s.selection_id}: {s.tally}")
        return out


def run_virtual_election(seed: int = 0,
                         spec: Optional[ElectionSpec] = None,
                         model: Optional[capacity.CostModel] = None,
                         chaos: bool = False,
                         workdir: Optional[str] = None) -> ElectionReport:
    """Play out one virtual election; see the module docstring."""
    spec = spec or ElectionSpec.from_knobs()
    model = model or capacity.fit()
    wall0 = _REAL.monotonic()
    own_workdir = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="egtpu-sim-election-")

    pre = _Prelude(spec, seed)
    sched = SimScheduler(seed=seed * 8 + 2, horizon=spec.horizon)
    dm = DeviceModel(model, backend=spec.backend, chips=spec.chips,
                     workers=spec.workers)
    batches = _batches(spec)
    pending: deque = deque(batches)
    journal = Journal()
    inflight: dict[str, tuple[int, int]] = {}
    state = {"serve_done": False, "record_done": False, "verified": 0}
    spans: list[PhaseSpan] = []
    violations: list[str] = []
    result: dict = {}

    def span(name: str, t0: float) -> None:
        sched.event("phase", name)
        spans.append(PhaseSpan(name, t0, sched.now))

    def worker_entry(flags, env):
        wid = env["EGTPU_OBS_PROC"]
        while True:
            sched.poll_until(lambda: pending or state["serve_done"],
                             None)
            if not pending:
                return 0
            bid, size = pending.popleft()
            inflight[wid] = (bid, size)
            # host admission+journal leg: one worker's Amdahl'd rpc
            # cost for the batch (W of these drain in parallel)
            clock_mod.sleep(dm.host_seconds(size))
            # device leg: queued on the shared accelerator plane
            dm.charge("encrypt", size)
            # the representative arithmetic (warm memo; real compute
            # ran once per shape in the prelude)
            pre.rep_cache[min(size, spec.rep_ballots)]
            journal.append(bid, size)
            sched.event("journal-append", f"b{bid} n={size} {wid}")
            inflight.pop(wid, None)

    def live_entry(flags, env):
        """Tail the journal, verifying chunks through the verify plane
        as they land (the live-verification chips)."""
        done = 0
        while True:
            sched.poll_until(
                lambda: len(journal.entries) > done
                or (state["record_done"]
                    and done >= len(journal.entries)), None)
            while done < len(journal.entries):
                _, n, _ = journal.entries[done]
                dm.charge("verify_batch", n)
                state["verified"] += n
                done += 1
            if state["record_done"] and done >= len(journal.entries):
                return 0

    def main() -> None:
        # ---- ceremony (prelude artifact; priced as rooflined rows) ---
        t0 = sched.now
        ngr = spec.n_guardians
        dm.charge_seconds("device", dm.seconds_rows(
            ngr * (spec.quorum + 2 * (ngr - 1))))
        span("ceremony", t0)

        # ---- serve: workers as SimProcesses over the batch queue -----
        t0 = sched.now
        procmodel.register_entry(WORKER_MODULE, worker_entry)
        procmodel.register_entry(LIVE_MODULE, live_entry)
        procs = [procmodel.SimProcess.python_module(
            f"serve-w{w}", WORKER_MODULE, [f"-worker={w}"], workdir)
            for w in range(spec.workers)]
        live_proc = procmodel.SimProcess.python_module(
            "live-verify", LIVE_MODULE, [], workdir)

        if chaos:
            victim = procs[0]
            victim.restart_on_exit(strip_env=("EGTPU_SIM_CHAOS_ONCE",))

            def saboteur():
                sched.poll_until(
                    lambda: len(journal.entries)
                    >= spec.chaos_after_batches, None)
                victim.kill_hard()
                # exactly-once: requeue the victim's in-flight batch
                # unless it already reached the journal
                cur = inflight.pop(victim.name, None)
                if cur is not None and not journal.has(cur[0]):
                    pending.append(cur)
                    sched.event("requeue", f"batch={cur[0]}")

            sched.spawn("saboteur", saboteur, node="driver")

        sched.poll_until(lambda: journal.total() >= spec.ballots, None)
        state["serve_done"] = True
        if not procmodel.wait_all(procs, 3600.0):
            violations.append("serve workers did not drain cleanly")
        sched.event("journal", f"n={len(journal.entries)} "
                               f"head={journal.head.hex()[:16]}")
        span("serve-encrypt", t0)

        # ---- mix cascade (device-charged per micro-batch chunk) ------
        t0 = sched.now
        for _k in range(spec.mix_stages):
            for _, size in batches:
                dm.charge("mix_stage", size)
        if spec.mix_stages:
            span(f"mix×{spec.mix_stages}", t0)

        # ---- compensated decrypt -------------------------------------
        t0 = sched.now
        dm.charge("decrypt", spec.ballots)
        span("decrypt", t0)
        violations.extend(pre.quorum_tally_violations())

        # ---- verify residual: drain the live plane -------------------
        t0 = sched.now
        state["record_done"] = True
        if live_proc.wait_for(3600.0) != 0:
            violations.append("live verifier did not drain")
        if state["verified"] != spec.ballots:
            violations.append(f"live plane verified "
                              f"{state['verified']} of {spec.ballots}")
        span("verify-batch-residual", t0)

        # ---- real oracles over the representative record -------------
        if not pre.vres.ok:
            violations.append(f"verifier red: {pre.vres.errors[:3]}")
        result["live"] = _live_convergence_leg(
            pre, workdir, seed, sched, violations)

        if journal.total() != spec.ballots:
            violations.append(f"ballots lost: journal "
                              f"{journal.total()} != {spec.ballots}")
        if not journal.chain_ok():
            violations.append("journal hash chain broken")

    clock_mod.install(SimClock(sched))
    procmodel.install(sched)
    try:
        sched.run(main)
    finally:
        procmodel.uninstall()
        clock_mod.uninstall()
        if own_workdir:
            shutil.rmtree(workdir, ignore_errors=True)
    for name, err in sched.task_errors():
        violations.append(f"task {name} died: {err!r}")

    return ElectionReport(
        seed=seed, ok=not violations, violations=violations,
        trace_hash=sched.trace_hash(), events=len(sched.trace),
        virtual_s=sched.now, wall_s=_REAL.monotonic() - wall0,
        ballots=journal.total(), batches=len(journal.entries),
        timeline=spans, journal_head=journal.head.hex(),
        device_busy_s={p.name: p.busy_s for p in dm.planes.values()},
        live=result.get("live", {}), chaos=chaos)


def _live_convergence_leg(pre: _Prelude, workdir: str, seed: int, sched,
                          violations: list) -> dict:
    """The REAL live-verification convergence oracle over the
    representative record: publish it as a growing directory (torn
    tails, crash/resume from checkpoint, seed-stream-7 torture like
    ``sim/cluster``) and require the incremental verdict to converge
    to a terminal single-pass fold bit-for-bit."""
    from electionguard_tpu.publish import framing, serialize
    from electionguard_tpu.publish.publisher import _BALLOTS, Publisher
    from electionguard_tpu.verify.live import LiveVerifier

    g, init = pre.group, pre.init
    rng = random.Random(seed * 8 + 7)
    rec_dir = os.path.join(workdir, "live_record")
    pub = Publisher(rec_dir)
    pub.write_election_initialized(init)
    for st in pre.stages:
        pub.write_mix_stage(g, st)
    chunk = rng.choice((1, 2, 3))
    live = LiveVerifier(rec_dir, g, chunk=chunk)
    crashes = torn = 0
    frames = [serialize.publish_encrypted_ballot(b).SerializeToString()
              for b in pre.encrypted]
    with open(os.path.join(rec_dir, _BALLOTS), "ab") as f:
        def land(blob: bytes) -> None:
            f.write(blob)
            f.flush()

        for fr in frames:
            blob = len(fr).to_bytes(framing.HEADER_LEN, "big") + fr
            if rng.random() < 0.3:
                # torn tail: partial frame lands, the tailer polls it
                # (must classify "retry"), then the remainder completes
                cut = rng.randrange(1, len(blob))
                land(blob[:cut])
                live.poll()
                torn += 1
                land(blob[cut:])
            else:
                land(blob)
            if rng.random() < 0.6:
                live.poll()
            if rng.random() < 0.25:
                # SIGKILL the verifier incarnation; resume from its
                # on-disk checkpoint
                crashes += 1
                live = LiveVerifier(rec_dir, g, chunk=chunk)
    pub.write_tally_result(pre.tally_result)
    pub.write_decryption_result(pre.dr)
    live_res = live.finalize()
    batch = LiveVerifier(rec_dir, g, chunk=chunk,
                         checkpoint_path=os.path.join(
                             workdir, "live_batch_checkpoint.json"))
    batch_res = batch.finalize()
    out = {
        "chunk": chunk, "crashes": crashes, "torn": torn,
        "live_ok": live_res.ok, "batch_ok": batch_res.ok,
        "live_root": live.ledger.root().hex(),
        "batch_root": batch.ledger.root().hex(),
        "live_head": live.ledger.head.hex(),
        "batch_head": batch.ledger.head.hex(),
    }
    sched.event("live-verify",
                f"chunk={chunk} crashes={crashes} torn={torn} "
                f"ok={live_res.ok}")
    if not (live_res.ok and batch_res.ok):
        violations.append(
            f"live/batch verifier red: {live_res.errors[:2]} "
            f"{batch_res.errors[:2]}")
    if (out["live_root"] != out["batch_root"]
            or out["live_head"] != out["batch_head"]):
        violations.append("live/batch commitment divergence")
    return out

"""In-memory transport mounted at the ``rpc_util`` factory seams.

``rpc_util.set_transport(SimTransport(...))`` makes every
``make_channel`` / ``make_server`` / ``find_free_port`` call route here:
servers are dictionaries of generic handlers keyed by virtual port,
channels are direct dispatchers, and an rpc is one in-task function
call bracketed by seeded virtual-time link delays.  The full middleware
stack still applies — ``generic_service`` wraps impls with fault
injection, rpc metrics, and tracing before they ever reach a server, so
the sim exercises the same code the real gRPC planes run, minus the
sockets.

The :class:`NetModel` owns the adversarial link behavior, all drawn
from its own seeded RNG so the scheduler's interleaving choices and the
network's misbehavior are independent deterministic streams:

* per-message latency (which also REORDERS concurrent rpcs: two
  in-flight calls from different tasks resume in delay order);
* duplicate delivery (the handler runs twice; the extra response is
  discarded — at-least-once, the retry-idempotency killer);
* partitions (directed windows of virtual time per node pair);
* connection death (the Nth message in the run dies in flight after
  the handler may already have committed).

Requests serialize through the real protobuf wire format both ways, so
a message that would not survive gRPC does not survive the sim either.
"""

from __future__ import annotations

import threading
from collections import namedtuple
from dataclasses import dataclass, field
from typing import Optional

import grpc

from electionguard_tpu.sim.scheduler import SimScheduler
from electionguard_tpu.sim import adversary
from electionguard_tpu.testing import faults

_HCD = namedtuple("_HCD", ("method", "invocation_metadata"))


class SimRpcError(grpc.RpcError):
    """Transport-level failure surfaced to clients, quacking like the
    real thing (``e.code()`` / ``e.details()``)."""

    def __init__(self, code: grpc.StatusCode, details: str):
        super().__init__()
        self._code = code
        self._details = details

    def code(self) -> grpc.StatusCode:
        return self._code

    def details(self) -> str:
        return self._details

    def __str__(self) -> str:
        return f"SimRpcError({self._code}, {self._details!r})"


class _Abort(BaseException):
    """Server-side ``context.abort`` control flow (BaseException so impl
    ``except Exception`` blocks cannot eat it, matching real gRPC)."""

    def __init__(self, code, details):
        self.code = code
        self.details = details


class SimContext:
    """Duck-typed ``grpc.ServicerContext`` for in-memory dispatch."""

    def __init__(self, peer: str):
        self._peer = peer
        self.code = None
        self.details = None

    def invocation_metadata(self):
        return ()

    def peer(self) -> str:
        return f"sim:{self._peer}"

    def is_active(self) -> bool:
        return True

    def time_remaining(self) -> Optional[float]:
        return None

    def set_code(self, code) -> None:
        self.code = code

    def set_details(self, details) -> None:
        self.details = details

    def abort(self, code, details=""):
        raise _Abort(code, details)


@dataclass(frozen=True)
class Partition:
    """Both directions of the (a, b) link are severed for virtual time
    ``[t0, t0 + duration)``."""
    a: str
    b: str
    t0: float
    duration: float

    def severs(self, x: str, y: str, now: float) -> bool:
        return ({x, y} == {self.a, self.b}
                and self.t0 <= now < self.t0 + self.duration)


@dataclass
class NetModel:
    """Seeded adversarial link behavior (see module docstring)."""
    rng: object                       # random.Random
    min_delay: float = 0.0002
    max_delay: float = 0.003
    dup_prob: float = 0.0
    partitions: tuple[Partition, ...] = ()
    kill_msgs: frozenset[int] = frozenset()
    _msgs: int = field(default=0, init=False)

    def delay(self) -> float:
        return self.rng.uniform(self.min_delay, self.max_delay)

    def duplicate(self) -> bool:
        return self.dup_prob > 0 and self.rng.random() < self.dup_prob

    def partitioned(self, a: str, b: str, now: float) -> bool:
        return any(p.severs(a, b, now) for p in self.partitions)

    def next_msg_dies(self) -> bool:
        self._msgs += 1
        return self._msgs in self.kill_msgs


class SimServer:
    """Stands in for ``grpc.Server``: handlers + an up/down bit."""

    def __init__(self, transport: "SimTransport", port: int, node: str):
        self.transport = transport
        self.port = port
        self.node = node
        self.up = False
        self._handlers: list = []

    def add_generic_rpc_handlers(self, handlers) -> None:
        self._handlers.extend(handlers)

    def start(self) -> None:
        self.up = True
        self.transport.sched.event("server-up", f"{self.node}:{self.port}")
        # race-monitor HB edge: starting a server publishes its handlers
        # (and everything they captured — metrics counters, impl state)
        # to every future dispatcher, same as ``grpc.Server.start()``
        mon = self.transport.sched.monitor
        if mon is not None:
            mon.on_publish(("server", self.port))

    def stop(self, grace=None) -> threading.Event:
        if self.up:
            self.transport.sched.event("server-down",
                                       f"{self.node}:{self.port}")
        self.up = False
        ev = threading.Event()
        ev.set()
        return ev

    def dispatch(self, path: str, request_bytes: bytes, peer: str) -> bytes:
        details = _HCD(path, ())
        for gh in self._handlers:
            mh = gh.service(details)
            if mh is not None:
                ctx = SimContext(peer)
                resp = mh.unary_unary(
                    mh.request_deserializer(request_bytes), ctx)
                return mh.response_serializer(resp)
        raise _Abort(grpc.StatusCode.UNIMPLEMENTED, f"no handler for {path}")


class SimTransport:
    """The process-wide virtual network: port registry + dispatch."""

    def __init__(self, sched: SimScheduler, net: NetModel, on_crash=None):
        self.sched = sched
        self.net = net
        #: cluster hook: called (server, method) after a crash_after
        #: fault downs a server, to kill its node's tasks and schedule a
        #: restart where the protocol supports one
        self.on_crash = on_crash
        self.servers: dict[int, SimServer] = {}
        self._next_port = 18000
        self._local = threading.local()

    # ---- rpc_util factory seam ---------------------------------------
    def free_port(self) -> int:
        p = self._next_port
        self._next_port += 1
        return p

    def server(self, port: int, max_message: int = 0):
        if port == 0:
            port = self.free_port()
        existing = self.servers.get(port)
        if existing is not None and existing.up:
            raise OSError(f"sim port {port} already bound by "
                          f"{existing.node}")
        srv = SimServer(self, port, self.sched.current_node())
        self.servers[port] = srv
        return srv, port

    def channel(self, url: str, max_message: int = 0, plain: bool = False):
        return SimChannel(self, url, plain)

    # ---- dispatch ----------------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def current_node(self) -> str:
        """The node 'speaking' right now: the innermost server when a
        handler is running, else the current task's node — so a handler
        making an onward rpc originates from ITS node, not the caller's."""
        st = self._stack()
        return st[-1].node if st else self.sched.current_node()

    def crash_current_server(self, method: str) -> None:
        """``FaultPlan.crash_cb`` target: down the server whose handler
        is executing, then let the cluster kill/restart its node."""
        st = self._stack()
        if not st:
            return
        srv = st[-1]
        srv.up = False
        self.sched.event("crash", f"{srv.node}:{srv.port} after {method}")
        if self.on_crash is not None:
            self.on_crash(srv, method)

    def reachable(self, src: str, port: int) -> bool:
        srv = self.servers.get(port)
        return (srv is not None and srv.up
                and not self.net.partitioned(src, srv.node, self.sched.now))

    def dispatch(self, port: int, path: str, request_bytes: bytes,
                 method: str, src: str) -> bytes:
        srv = self.servers.get(port)
        if srv is None or not srv.up:
            raise SimRpcError(grpc.StatusCode.UNAVAILABLE,
                              f"sim port {port} not serving")
        self.sched.event("rpc", f"{src}->{srv.node}:{port} {method}")
        self._stack().append(srv)
        # race-monitor context: the handler runs inline on the sender's
        # task (the send→receive HB edge is program order by
        # construction); tagging the span lets race reports name the
        # rpc a racy access ran under
        mon = self.sched.monitor
        if mon is not None:
            mon.on_subscribe(("server", port))
            mon.rpc_begin(f"{srv.node}:{method}")
        try:
            return srv.dispatch(path, request_bytes, src)
        except _Abort as a:
            raise SimRpcError(a.code, a.details) from None
        finally:
            if mon is not None:
                mon.rpc_end()
            self._stack().pop()


class _SimMulticallable:
    def __init__(self, channel: "SimChannel", path: str, serializer,
                 deserializer):
        self.channel = channel
        self.path = path
        self.ser = serializer
        self.deser = deserializer

    def __call__(self, request, timeout: Optional[float] = None,
                 wait_for_ready: Optional[bool] = None, metadata=None):
        tr = self.channel.transport
        sched, net = tr.sched, tr.net
        method = self.path.rsplit("/", 1)[-1]
        src = tr.current_node()
        port = int(self.channel.url.rsplit(":", 1)[-1])
        adv = None
        adv_pending, adv_forged = [], []
        if not self.channel.plain:
            plan = faults.active_plan()
            if plan is not None:
                # the real channel applies client rules via interceptor;
                # the sim channel has no grpc.Channel to intercept
                faults.apply_client_rules(plan, method)
        budget = timeout if timeout is not None else 600.0
        deadline = sched.now + budget

        def reach() -> bool:
            return tr.reachable(src, port)

        if not reach():
            if wait_for_ready:
                # real gRPC semantics: wait_for_ready blocks the attempt
                # until the peer connects or the per-try deadline expires
                if not sched.poll_until(reach, budget):
                    raise SimRpcError(
                        grpc.StatusCode.DEADLINE_EXCEEDED,
                        f"connect timeout to {self.channel.url}")
            else:
                sched.sleep(net.delay())
                raise SimRpcError(grpc.StatusCode.UNAVAILABLE,
                                  f"{self.channel.url} unreachable")
        sched.sleep(net.delay())                     # request in flight
        if net.next_msg_dies() or not reach():
            sched.event("conn-death", f"{src}->{port} {method}")
            raise SimRpcError(grpc.StatusCode.UNAVAILABLE,
                              f"connection to {self.channel.url} died "
                              f"in flight")
        if not self.channel.plain:
            adv = adversary.active_plan()
        if adv is not None and adv.has_rules("client", method):
            # client-side adversaries, applied only once the
            # connection checks passed so rule call-counters index
            # requests that actually reach the wire (an attempt that
            # died unreachable must not consume the firing index).
            # Mutations edit a COPY (Stub retries reuse the same
            # request object — poisoning it would corrupt the honest
            # retry); forged duplicates queue for dispatch after the
            # real one.
            request, adv_pending, adv_forged = adv.apply_client(
                method, src, request)
        request_bytes = self.ser(request)
        # delivery scope: response-side misbehaviors (mutated/replayed
        # responses) count as fired only if this response actually
        # reaches the client — one that dies in flight was never seen
        # by any defense, and the honest retry supersedes it
        tok = adv.begin_delivery() if adv is not None else None
        delivered = False
        try:
            response_bytes = tr.dispatch(port, self.path, request_bytes,
                                         method, src)
            for rule, n in adv_pending:
                # durable: the mutated request reached its handler
                adv.record_fired(rule, n, src)
            for rule, n, forged in adv_forged:
                # forged duplicate/replayed submission: its response is
                # discarded by the attacker (nested scope, never
                # committed), but the REQUEST reaching the handler is a
                # durable firing
                sched.event("adversary", f"{src}->{port} forged {method}")
                ftok = adv.begin_delivery()
                try:
                    tr.dispatch(port, self.path, self.ser(forged),
                                method, src)
                    adv.record_fired(rule, n, src)
                except SimRpcError:
                    pass
                finally:
                    adv.end_delivery(ftok, False)
            if net.duplicate():
                # at-least-once delivery: the peer processes the message
                # again; the duplicate's response is discarded
                sched.event("dup-delivery", f"{src}->{port} {method}")
                dtok = (adv.begin_delivery() if adv is not None
                        else None)
                try:
                    tr.dispatch(port, self.path, request_bytes, method,
                                src)
                except SimRpcError:
                    pass
                finally:
                    if adv is not None:
                        adv.end_delivery(dtok, False)
            sched.sleep(net.delay())                 # response in flight
            if sched.now > deadline:
                raise SimRpcError(grpc.StatusCode.DEADLINE_EXCEEDED,
                                  f"{method} deadline exceeded in "
                                  f"transit")
            if not reach():
                raise SimRpcError(grpc.StatusCode.UNAVAILABLE,
                                  f"connection to {self.channel.url} "
                                  f"lost before response")
            delivered = True
        finally:
            if adv is not None:
                adv.end_delivery(tok, delivered)
        return self.deser(response_bytes)


class SimChannel:
    """Stands in for ``grpc.Channel`` (the unary-unary slice the repo
    uses).  ``plain`` channels skip client-side fault rules, mirroring
    ``make_plain_channel``."""

    def __init__(self, transport: SimTransport, url: str, plain: bool):
        self.transport = transport
        self.url = url
        self.plain = plain

    def unary_unary(self, path: str, request_serializer=None,
                    response_deserializer=None, **_kw):
        return _SimMulticallable(self, path, request_serializer,
                                 response_deserializer)

    def close(self) -> None:
        pass

"""Device-time model: fitted per-op cost as virtual clock advance.

The analytic capacity plane (PR 18, ``obs/capacity``) predicts a
10^6-ballot election from the ``BENCH_BIGNUM.json`` rooflines; this
module lets the sim *play one out* with the same numbers.  A
:class:`DeviceModel` wraps a fitted ``capacity.CostModel`` and converts
semantic batch ops ("encrypt N ballots", "mix one stage of N") into
virtual seconds using exactly the rate algebra ``capacity.predict``
uses — rows-per-ballot × ballots / (rate × chips × occupancy) for the
device leg, Amdahl-deflated rpc cost for the host leg — so the
played-out timeline and the analytic prediction disagree only where
*composition* (queueing, micro-batch rounding, phase overlap) differs
from the closed form.  That difference is what ``egplan --validate``
gates.

The actual arithmetic still runs, once per distinct batch shape, on
the tiny group (see ``sim/election.py``): full protocol fidelity,
scaled device time — the SZKP-style roofline treatment (arXiv
2408.05890) of projecting chip-scale throughput without fabricating
the chip.

Charges are serialized through named :class:`DevicePlane` queues (a
shared accelerator is a resource, not a rate): a charge begins at
``max(now, plane.busy_until)``, extends the plane, and sleeps the
caller until the work's end — concurrent workers therefore contend
for device time exactly like batches queueing on one chip, while the
live verifier charges a separate ``verify`` plane (its own chips in
the capacity model's accounting).

Two ways in:

* explicit — the election driver holds a ``DeviceModel`` and calls
  :meth:`DeviceModel.charge` at each pipeline stage;
* ambient — :func:`install` routes the ``utils.devicetime.charge``
  no-op seam in the batch crypto entry points here, so existing sims
  gain device time without touching their call sites.  (The election
  driver runs its real representative legs with the seam OFF to avoid
  double-charging.)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from electionguard_tpu.obs import capacity
from electionguard_tpu.utils import clock, devicetime

#: semantic ops charged to the shared accelerator plane; everything
#: verify-flavored goes to the separate live-verification plane
_VERIFY_OPS = ("verify", "verify_batch")


@dataclass
class DevicePlane:
    """One serialized device resource (chips set): charges queue."""

    name: str
    busy_until: float = 0.0
    busy_s: float = 0.0
    charges: int = 0


@dataclass
class DeviceModel:
    """Fitted per-op virtual device cost for one plan configuration."""

    model: capacity.CostModel
    backend: str = "cios"
    chips: int = 1
    workers: int = 1
    planes: dict = field(default_factory=dict)

    def plane(self, name: str) -> DevicePlane:
        p = self.planes.get(name)
        if p is None:
            p = self.planes[name] = DevicePlane(name)
        return p

    # ---- rate algebra (mirrors capacity.predict) ---------------------
    def _rate(self, op: str) -> float:
        pow_est = self.model.powmod_per_s.get(self.backend)
        if pow_est is None or pow_est.mean <= 0:
            raise ValueError(f"no powmod roofline for backend "
                             f"{self.backend!r}; fit BENCH_BIGNUM.json")
        if op == "encrypt":
            fixed = self.model.fixed_per_s.get(self.backend)
            return (fixed or pow_est).mean
        return pow_est.mean

    def seconds_rows(self, rows: float, op: str = "decrypt") -> float:
        """Virtual device seconds for ``rows`` full-ladder rows (at
        ``op``'s rate) — ``capacity.predict``'s ``device_s``."""
        occ = max(min(self.model.occupancy.mean, 1.0), 1e-3)
        return rows / (self._rate(op) * max(self.chips, 1) * occ)

    def seconds(self, op: str, ballots: float) -> float:
        """Virtual device seconds for ``ballots`` of ``op``."""
        return self.seconds_rows(capacity.ROWS_PER_BALLOT[op] * ballots,
                                 op)

    def host_seconds(self, ballots: float) -> float:
        """Virtual host-leg seconds ONE worker spends admitting +
        journaling ``ballots``: rpc cost Amdahl-inflated by the fitted
        serial fraction, so W workers draining in parallel play out to
        ``ballots·rpc/(W·eff)`` — ``capacity.predict``'s serving
        floor."""
        rpc = self.model.rpc_per_ballot_s
        if rpc is None:
            return 0.0
        eff = capacity.worker_efficiency(self.workers,
                                         self.model.serial_fraction.mean)
        return ballots * rpc.mean / eff

    # ---- the charging seam -------------------------------------------
    def charge_seconds(self, plane_name: str, sec: float) -> None:
        """Queue ``sec`` of work on a plane and sleep (virtual) until
        it completes.  Read-modify-write then sleep: the scheduler is
        cooperative and only the clock call yields, so two workers can
        never claim the same device window."""
        p = self.plane(plane_name)
        now = clock.monotonic()
        start = max(now, p.busy_until)
        p.busy_until = start + sec
        p.busy_s += sec
        p.charges += 1
        clock.sleep(p.busy_until - now)

    def charge(self, op: str, ballots: float) -> None:
        plane = "verify" if op in _VERIFY_OPS else "device"
        self.charge_seconds(plane, self.seconds(op, ballots))


def install(dm: DeviceModel) -> None:
    """Route the ``utils.devicetime`` crypto-entry-point seam to
    ``dm`` (one sim at a time)."""
    devicetime.set_charger(dm.charge)


def uninstall() -> None:
    devicetime.set_charger(None)


def fit_default(repo_root: Optional[str] = None) -> DeviceModel:
    """A DeviceModel over the repo's measured artifacts."""
    return DeviceModel(capacity.fit(repo_root=repo_root))

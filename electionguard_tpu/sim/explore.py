"""Seed → simulated run → report: the DST entry points.

``run_sim(seed)`` derives everything from the seed — the fault schedule
(stream 1), the scheduler's interleaving choices (stream 2), the
network's per-message delays (stream 3), the retry-backoff jitter
(stream 4), and with ``adversaries=True`` the in-protocol attack draws
(stream 5, isolated so an adversary run perturbs none of the honest
streams) — installs the virtual clock, the in-memory transport, the
fault plan, and the adversary plan, drives the full workflow, and
checks every oracle including soundness.  The same seed replays the
same execution bit-for-bit, attested by the sha256 event-trace hash in
the report; ``schedule=`` overrides the generated schedule (replay of a
shrunk repro — adversary events ride in the same list).

``explore(seeds)`` sweeps; the CLI wrapper is ``tools/sim_matrix.py``.
"""

from __future__ import annotations

import random
import shutil
import tempfile
from dataclasses import dataclass, field
from typing import Optional, Sequence

from electionguard_tpu.remote import rpc_util
from electionguard_tpu.sim import adversary, cluster, oracle
from electionguard_tpu.sim import schedule as schedule_mod
from electionguard_tpu.sim.scheduler import (SimClock, SimDeadlock,
                                             SimHorizon, SimScheduler)
from electionguard_tpu.sim.transport import SimTransport
from electionguard_tpu.testing import faults
from electionguard_tpu.utils import clock as clock_mod
from electionguard_tpu.utils import errors, knobs


@dataclass
class SimReport:
    """One run's verdict + its replay coordinates."""
    seed: int
    ok: bool
    violations: list[str]
    trace_hash: str
    events: int
    virtual_s: float
    schedule: list[schedule_mod.FaultEvent]
    injected: list[tuple] = field(default_factory=list)
    #: adversary audit: every attack that actually reached the wire
    #: (attack, method, call_n, node) and every in-band detection
    #: (class, detail) the defenses recorded for the run
    fired: list[tuple] = field(default_factory=list)
    detections: list[tuple] = field(default_factory=list)
    #: race detector output (run_sim(race=True)): one dict per distinct
    #: report (kind, var, access pair, sites, locksets), plus the
    #: scheduler strategy that produced this schedule and the raw
    #: instrumentation event count (the bench overhead denominator)
    races: list[dict] = field(default_factory=list)
    strategy: str = "random"
    race_events: int = 0
    #: live-verification convergence summary (the "live-verify" plant):
    #: chunk size drawn, crash/torn-tail counts the leg injected, and
    #: the commitment root both passes must agree on
    live: dict = field(default_factory=dict)

    def schedule_json(self) -> str:
        return schedule_mod.to_json(self.schedule)

    def summary(self) -> str:
        state = "ok" if self.ok else "FAIL"
        return (f"seed={self.seed} {state} events={self.events} "
                f"t={self.virtual_s:.1f}s faults={len(self.schedule)} "
                f"attacks={len(self.fired)}"
                + (f" races={len(self.races)}" if self.races else "")
                + ("" if self.ok else f" violations={self.violations}"))


def _stream(seed: int, k: int) -> random.Random:
    """Independent deterministic RNG stream k of a seed."""
    return random.Random(seed * 8 + k)


def run_sim(seed: int,
            schedule: Optional[list[schedule_mod.FaultEvent]] = None,
            plant: Sequence[str] = (),
            config: Optional[cluster.SimConfig] = None,
            adversaries: bool = False,
            race: bool = False,
            strategy: Optional[str] = None,
            param_adversaries: bool = False) -> SimReport:
    """One deterministic run of the full virtual-cluster workflow."""
    cfg = config or cluster.SimConfig()
    if schedule is None:
        schedule = schedule_mod.generate_schedule(_stream(seed, 1))
        if adversaries:
            schedule = schedule + schedule_mod.generate_adversary_schedule(
                _stream(seed, 5))
        if param_adversaries:
            # string-seeded stream: independent of the numbered honest
            # streams, so composing param attacks never perturbs the
            # fault / Byzantine / scheduler draws of the same seed
            schedule = schedule + schedule_mod.generate_param_schedule(
                random.Random(f"param:{seed}"))
    race = race or knobs.get_flag("EGTPU_RACE")
    strategy = strategy or knobs.get_str("EGTPU_SIM_STRATEGY")
    # PCT draws (priorities + change points) live on their own stream
    # (6) so strategy choice perturbs no honest stream
    sched = SimScheduler(seed=seed * 8 + 2, horizon=cfg.horizon,
                         strategy=strategy,
                         pct_depth=knobs.get_int("EGTPU_SIM_PCT_DEPTH"),
                         pct_rng=_stream(seed, 6))
    net = schedule_mod.net_model(schedule, _stream(seed, 3))
    transport = SimTransport(sched, net)
    plan = schedule_mod.to_fault_plan(schedule)
    plan.crash_cb = transport.crash_current_server
    adv_plan = schedule_mod.to_adversary_plan(schedule)
    adv_plan.node_fn = transport.current_node
    backoff = _stream(seed, 4)
    out = cluster.SimOutcome()
    workdir = tempfile.mkdtemp(prefix="egtpu-sim-")

    def _on_reject(cls: str, detail: str) -> None:
        out.detections.append((cls, detail))

    monitor = None
    inst = None
    if race:
        from electionguard_tpu.analysis import race as race_mod
        from electionguard_tpu.analysis import race_instrument
        monitor = race_mod.RaceMonitor(sched)
        # the planted-race probe rides along whenever the monitor is on
        # (idle unless a race-* plant spawns its tasks)
        inst = race_instrument.install(
            monitor,
            extra=[(cluster.RaceProbeBox, ("shared",),
                    ("_lock_a", "_lock_b"))])

    prev_uniform = rpc_util._uniform
    clock_mod.install(SimClock(sched))
    rpc_util.set_transport(transport)
    faults.install(plan)
    adversary.install(adv_plan)
    errors.listen(_on_reject)
    rpc_util._uniform = backoff.uniform   # backoff jitter must replay too
    try:
        sched.run(lambda: cluster.drive(cfg, sched, transport, plan,
                                        schedule, seed, frozenset(plant),
                                        workdir, out))
    except (SimDeadlock, SimHorizon) as e:
        out.liveness_error = str(e)
    except Exception as e:                # noqa: BLE001 - becomes a verdict
        out.workflow_error = repr(e)
    finally:
        if inst is not None:
            inst.uninstall()
        rpc_util._uniform = prev_uniform
        errors.unlisten(_on_reject)
        adversary.clear()
        faults.clear()
        rpc_util.set_transport(None)
        clock_mod.uninstall()
        shutil.rmtree(workdir, ignore_errors=True)
    out.task_errors = sched.task_errors()
    out.fired = list(adv_plan.fired)
    if monitor is not None:
        out.races = list(monitor.races)
    violations = oracle.check(out)
    live = {}
    if out.live_report is not None:
        live = {k: out.live_report[k]
                for k in ("chunk", "crashes", "torn", "n_frames",
                          "live_ok", "live_root", "live_accepts")}
        live["converged"] = not any(
            v.startswith("live_convergence") for v in violations)
    return SimReport(seed=seed, ok=not violations, violations=violations,
                     trace_hash=sched.trace_hash(),
                     events=len(sched.trace), virtual_s=sched.now,
                     schedule=list(schedule),
                     injected=list(plan.injected),
                     fired=list(out.fired),
                     detections=list(out.detections),
                     races=[r.to_dict() for r in out.races],
                     strategy=strategy,
                     race_events=monitor.events if monitor else 0,
                     live=live)


def explore(seeds: Sequence[int],
            config: Optional[cluster.SimConfig] = None,
            plant: Sequence[str] = (),
            adversaries: bool = False,
            race: bool = False,
            strategy: Optional[str] = None,
            param_adversaries: bool = False) -> list[SimReport]:
    """Run every seed; returns all reports (callers filter failures)."""
    return [run_sim(s, config=config, plant=plant,
                    adversaries=adversaries, race=race,
                    strategy=strategy, param_adversaries=param_adversaries)
            for s in seeds]


def default_seeds() -> list[int]:
    """The knob-configured seed range (EGTPU_SIM_SEED..+EGTPU_SIM_SEEDS)."""
    start = knobs.get_int("EGTPU_SIM_SEED")
    count = knobs.get_int("EGTPU_SIM_SEEDS")
    return list(range(start, start + count))

"""The deterministic cooperative scheduler and its virtual clock.

Library code already blocks only through the ``utils/clock.py`` seam
(sleep, event/condition waits, future results, thread start/join — the
``wall-clock-discipline`` eglint pass enforces it), so this scheduler
gets control at every point a task could block.  Tasks run on real OS
threads but hold a single run token: exactly one task executes at a
time, and it runs *atomically* until its next clock-seam call.  At that
point it parks, the scheduler picks the next runnable task with its
seeded RNG, and virtual time jumps straight to the earliest wake
deadline when nothing is runnable — sleeps are free.

Determinism argument: with one logical thread of control, the only
scheduling freedom is WHICH parked task resumes next, and that choice
is ``rng.choice`` over a list sorted by spawn order.  Everything else a
run does (rpc payloads, fault firing, virtual delays) is a pure
function of task execution plus the seeded net/fault RNG streams, so
one seed reproduces one execution — attested by the sha256 event-trace
hash, which covers every dispatch decision with its virtual timestamp.

Liveness failures are first-class: a run whose tasks all park with no
future wake is a deadlock, and a run whose virtual time would pass the
horizon is a stuck protocol; both unwind every task (via
:class:`TaskKilled`) and surface as oracle violations, never hangs.  A
real-time watchdog catches the one thing cooperative scheduling cannot
see — a task blocked in a primitive that bypassed the seam.
"""

from __future__ import annotations

import hashlib
import random
import threading
from typing import Callable, Optional

from electionguard_tpu.utils import clock as clock_mod
from electionguard_tpu.utils import knobs

#: virtual seconds a condition-variable wait parks before rechecking its
#: predicate (Condition has no pollable state, so the sim quantizes it)
CV_QUANTUM = 0.005

#: PCT draws its priority change points from [1, PCT_STEPS); runs longer
#: than this many dispatches keep the last assigned priorities (the PCT
#: guarantee is over the first k steps — this is the k estimate)
PCT_STEPS = 4096

_NEW, _READY, _RUNNING, _PARKED, _DONE = range(5)


class TaskKilled(BaseException):
    """Unwinds a killed task at its next (or current) yield point.
    BaseException so ``except Exception`` recovery paths in library
    code cannot swallow a simulated crash."""


class SimDeadlock(Exception):
    """Every task parked, none with a future wake: genuine deadlock."""


class SimHorizon(Exception):
    """Virtual time would pass the horizon: the run is stuck/livelocked."""


class SimStuck(Exception):
    """A task failed to yield within the real-time watchdog — it blocked
    outside the clock seam (a discipline bug, not a protocol bug)."""


class _Task:
    __slots__ = ("name", "node", "seq", "fn", "thread", "go", "state",
                 "pred", "wake_at", "wait_ok", "killed", "error", "adopted")

    def __init__(self, name: str, node: str, seq: int,
                 fn: Optional[Callable] = None):
        self.name = name
        self.node = node
        self.seq = seq
        self.fn = fn
        self.thread: Optional[threading.Thread] = None
        self.go = threading.Event()
        self.state = _NEW
        self.pred: Optional[Callable[[], bool]] = None
        self.wake_at: Optional[float] = None
        self.wait_ok = True     # set by the scheduler before re-dispatch
        self.killed = False
        self.error: Optional[BaseException] = None
        self.adopted = False


class SimScheduler:
    """One simulated run: spawn tasks, ``run(main)``, read the trace."""

    def __init__(self, seed: int, horizon: float = 600.0,
                 strategy: str = "random", pct_depth: int = 3,
                 pct_rng: Optional[random.Random] = None):
        if strategy not in ("random", "pct"):
            raise ValueError(f"unknown sim strategy {strategy!r}")
        self.rng = random.Random(seed)
        self.horizon = horizon
        #: real seconds the running task may go without yielding before
        #: the liveness watchdog declares it stuck outside the clock
        #: seam; sweep drivers raise it so cold jit compiles under CPU
        #: contention are not misdiagnosed as deadlocks
        self.watchdog_s = knobs.get_float("EGTPU_SIM_WATCHDOG_S")
        self.now = 0.0
        self.trace: list[tuple[int, str, str]] = []
        self.strategy = strategy
        #: the race monitor's hook sink (``analysis/race.py``); None when
        #: race detection is off — hooks then cost one attribute load
        self.monitor = None
        self._tasks: list[_Task] = []
        self._by_ident: dict[int, _Task] = {}
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._seq = 0
        self._running: Optional[_Task] = None
        self._finishing = False
        # PCT (probabilistic concurrency testing): random per-task
        # priorities + depth-1 priority change points at random steps;
        # dispatch always picks the highest-priority runnable task.  Own
        # RNG stream so fault/net streams stay strategy-independent.
        self._pct_rng = pct_rng or random.Random(seed ^ 0x9E3779B9)
        self._prio: dict[int, float] = {}
        self._change_points = sorted(
            self._pct_rng.randrange(1, PCT_STEPS)
            for _ in range(max(1, pct_depth) - 1))
        self._demotions = 0
        self._step = 0

    # ---- trace -------------------------------------------------------
    def event(self, kind: str, detail: str = "") -> None:
        self.trace.append((int(self.now * 1e6), kind, detail))

    def trace_hash(self) -> str:
        h = hashlib.sha256()
        for t_us, kind, detail in self.trace:
            h.update(f"{t_us}|{kind}|{detail}\n".encode())
        return h.hexdigest()

    # ---- task management ---------------------------------------------
    def spawn(self, name: str, fn: Callable[[], None],
              node: Optional[str] = None) -> None:
        """Create a task; it becomes runnable at the next dispatch."""
        parent = self._current()
        with self._lock:
            task = _Task(name, node or name, self._seq, fn)
            self._seq += 1
            self._tasks.append(task)
        self._prio[task.seq] = self._pct_rng.random()
        if self.monitor is not None:
            self.monitor.on_spawn(parent, task)
        task.thread = threading.Thread(
            target=self._task_body, args=(task,), name=f"sim:{name}",
            daemon=True)
        task.thread.start()

    def adopt_thread(self, thread: threading.Thread) -> None:
        """Take over a library-created thread (``clock.start_thread``):
        its run() joins the cooperative pool under the spawner's node, so
        ``thread.is_alive()`` / ``join`` keep their real semantics."""
        parent = self._current()
        with self._lock:
            task = _Task(thread.name, parent.node if parent else "driver",
                         self._seq)
            self._seq += 1
            task.adopted = True
            self._tasks.append(task)
        self._prio[task.seq] = self._pct_rng.random()
        if self.monitor is not None:
            self.monitor.on_spawn(parent, task)
        orig_run = thread.run

        def run():
            with self._lock:
                self._by_ident[threading.get_ident()] = task
            task.thread = threading.current_thread()
            task.go.wait()
            try:
                if not task.killed:
                    orig_run()
            except TaskKilled:
                pass
            except BaseException as e:       # noqa: BLE001 - surfaced below
                if not task.killed:
                    task.error = e
            finally:
                mon = self.monitor
                if mon is not None:
                    mon.on_finish(task)
                task.state = _DONE
                self._wake.set()

        thread.run = run
        thread.start()

    def _task_body(self, task: _Task) -> None:
        with self._lock:
            self._by_ident[threading.get_ident()] = task
        task.go.wait()
        try:
            if not task.killed:
                task.fn()
        except TaskKilled:
            pass
        except BaseException as e:           # noqa: BLE001 - surfaced below
            if not task.killed:
                task.error = e
        finally:
            mon = self.monitor
            if mon is not None:
                mon.on_finish(task)
            task.state = _DONE
            self._wake.set()

    def _current(self) -> Optional[_Task]:
        with self._lock:
            return self._by_ident.get(threading.get_ident())

    def current_task(self) -> Optional[_Task]:
        """The sim task running on the calling thread, or None on a
        foreign thread (the race monitor uses this to drop events that
        do not belong to any task, e.g. scheduler-thread pred evals)."""
        return self._current()

    def current_node(self) -> str:
        t = self._current()
        return t.node if t is not None else "driver"

    def kill_node(self, node: str) -> None:
        """Simulated crash: every task of ``node`` unwinds with
        :class:`TaskKilled` at its current/next yield point."""
        with self._lock:
            for t in self._tasks:
                if t.node == node and t.state != _DONE:
                    t.killed = True
        self.event("kill", node)

    def task_errors(self) -> list[tuple[str, BaseException]]:
        with self._lock:
            return [(t.name, t.error) for t in self._tasks
                    if t.error is not None]

    # ---- yield points (called from inside tasks) ---------------------
    def _yield(self, pred: Optional[Callable[[], bool]],
               wake_at: Optional[float]) -> bool:
        task = self._current()
        if task is None:
            raise RuntimeError("clock-seam call from outside the sim "
                               "(scheduler thread or foreign thread)")
        if task.killed:
            raise TaskKilled()
        mon = self.monitor
        if mon is not None:
            # publish this task's clock into the seam clock before it
            # parks: anything it did so far happens-before any wait that
            # succeeds after this point
            mon.on_yield(task)
        task.pred = pred
        task.wake_at = wake_at
        task.go.clear()
        task.state = _PARKED
        self._wake.set()
        task.go.wait()
        if task.killed:
            raise TaskKilled()
        if mon is not None and pred is not None and task.wait_ok:
            # a *successful* predicate wait is a synchronization point:
            # join the seam clock (timeouts and plain sleeps are not)
            mon.on_wait_ok(task)
        return task.wait_ok

    def sleep(self, seconds: float) -> None:
        self._yield(None, self.now + max(0.0, seconds))

    def poll_until(self, pred: Callable[[], bool],
                   timeout: Optional[float]) -> bool:
        """Park until ``pred()`` holds (True) or the virtual timeout
        expires (False).  The scheduler evaluates the predicate, so no
        context switches burn while it is false."""
        if pred():
            mon = self.monitor
            if mon is not None:
                task = self._current()
                if task is not None:
                    mon.on_wait_ok(task)
            return True
        wake_at = None if timeout is None else self.now + max(0.0, timeout)
        return self._yield(pred, wake_at)

    # ---- the scheduler loop ------------------------------------------
    def _runnable(self, t: _Task) -> bool:
        if t.state == _NEW:
            return True
        if t.state != _PARKED:
            return False
        if t.killed:
            return True
        if t.pred is not None and t.pred():
            return True
        return t.wake_at is not None and t.wake_at <= self.now

    def run(self, main_fn: Callable[[], None]) -> None:
        """Drive the simulation until ``main_fn``'s task completes; then
        kill and unwind every leftover task.  Raises the main task's
        exception, or SimDeadlock / SimHorizon / SimStuck."""
        self.spawn("main", main_fn, node="driver")
        with self._lock:
            main = self._tasks[-1]
        try:
            self._loop(lambda: main.state == _DONE)
        finally:
            self._finish_all()
        if main.error is not None:
            raise main.error

    def _loop(self, done: Callable[[], bool]) -> None:
        while not done():
            with self._lock:
                tasks = list(self._tasks)
            ready = [t for t in tasks if self._runnable(t)]
            if not ready:
                wakes = [t.wake_at for t in tasks
                         if t.state == _PARKED and t.wake_at is not None]
                if not wakes:
                    parked = [t.name for t in tasks if t.state == _PARKED]
                    raise SimDeadlock(
                        f"all tasks parked with no future wake at "
                        f"t={self.now:.3f}: {parked}")
                target = min(wakes)
                if target > self.horizon:
                    raise SimHorizon(
                        f"virtual time would pass the {self.horizon:.0f}s "
                        f"horizon (next wake {target:.1f}s)")
                self.now = max(self.now, target)
                continue
            ready.sort(key=lambda t: t.seq)
            if self.strategy == "pct":
                pick = self._pct_pick(ready)
            else:
                pick = self.rng.choice(ready)
            self._dispatch(pick)

    def _pct_pick(self, ready: list[_Task]) -> _Task:
        """PCT dispatch: highest priority wins; at each change point the
        current top priority drops below everything assigned so far."""
        self._step += 1
        while self._change_points and self._step >= self._change_points[0]:
            self._change_points.pop(0)
            top = max(ready,
                      key=lambda t: (self._prio.get(t.seq, 0.0), -t.seq))
            self._demotions += 1
            self._prio[top.seq] = -float(self._demotions)
        return max(ready, key=lambda t: (self._prio.get(t.seq, 0.0), -t.seq))

    def _dispatch(self, task: _Task) -> None:
        # wait_ok tells a pred-parked task whether its predicate held
        # (vs. a timeout / kill wake)
        task.wait_ok = bool(task.killed
                            or task.pred is None or task.pred())
        task.pred = None
        task.wake_at = None
        task.state = _RUNNING
        self._running = task
        self.event("run", task.name)
        self._wake.clear()
        task.go.set()
        while task.state == _RUNNING:
            if not self._wake.wait(self.watchdog_s):
                raise SimStuck(
                    f"task {task.name} did not yield within "
                    f"{self.watchdog_s:.0f}s real time — blocked outside "
                    f"the clock seam")
            self._wake.clear()

    def _finish_all(self) -> None:
        """Kill every unfinished task and run each to completion so no
        sim thread outlives the run."""
        self._finishing = True
        with self._lock:
            leftover = [t for t in self._tasks if t.state != _DONE]
        for t in leftover:
            t.killed = True
        for t in leftover:
            # NEW tasks unwind before their fn; PARKED ones raise
            # TaskKilled at their yield point; a task mid-unwind may
            # park again in a finally block — keep dispatching it
            while t.state != _DONE:
                t.state = _RUNNING
                self._wake.clear()
                t.go.set()
                while t.state == _RUNNING:
                    if not self._wake.wait(self.watchdog_s):
                        raise SimStuck(
                            f"task {t.name} stuck during unwind")
                    self._wake.clear()


class SimClock(clock_mod.Clock):
    """The virtual clock the sim installs at the ``utils/clock`` seam:
    every blocking primitive becomes a scheduler yield."""

    #: virtual runs report a fixed wall-clock epoch so timestamps in
    #: artifacts are reproducible
    EPOCH = 1_753_920_000.0

    def __init__(self, sched: SimScheduler):
        self.sched = sched

    def time(self) -> float:
        return self.EPOCH + self.sched.now

    def monotonic(self) -> float:
        return self.sched.now

    def sleep(self, seconds: float) -> None:
        self.sched.sleep(seconds)

    def wait_event(self, event: threading.Event,
                   timeout: Optional[float] = None) -> bool:
        return self.sched.poll_until(event.is_set, timeout)

    def cv_wait(self, cv: threading.Condition,
                timeout: Optional[float] = None) -> bool:
        # Condition carries no pollable predicate, so release the lock,
        # park one quantum, reacquire, and let the caller's loop recheck
        # — the documented spurious-wakeup contract of the seam
        wait = CV_QUANTUM if timeout is None else min(CV_QUANTUM, timeout)
        cv.release()
        try:
            self.sched.sleep(max(0.0, wait))
        finally:
            cv.acquire()
        return True

    def wait_future(self, future, timeout: Optional[float] = None):
        if not self.sched.poll_until(future.done, timeout):
            from concurrent.futures import TimeoutError as FutTimeout
            raise FutTimeout()
        return future.result(timeout=0)

    def start_thread(self, thread: threading.Thread) -> None:
        self.sched.adopt_thread(thread)

    def join_thread(self, thread: threading.Thread,
                    timeout: Optional[float] = None) -> None:
        self.sched.poll_until(lambda: not thread.is_alive(), timeout)

"""The election-record verifier: every spec check, batch-first.

Native replacement for the reference's [ext] ``Verifier(record, nthreads).verify()``
(call site: src/test/java/electionguard/workflow/RunRemoteWorkflowTest.java:179-182
— the reference's final ground truth for "did the workflow work", run with an
11-thread CPU pool; SURVEY.md §4).  Here the per-ballot checks (the 🔥 bulk:
selection range proofs, contest limit proofs, subgroup membership, tally
aggregation) run as batched limb-array computations on the TPU plane, while
structural checks and Fiat-Shamir hashing run host-side.

Verification steps (numbered in the result):
  V1  group parameters + quorum bounds
  V2  guardian public keys: Schnorr proofs
  V3  joint public key + base hashes
  V4  selection encryptions: subgroup membership + disjunctive CP proofs
  V5  contest vote limits: accumulation + constant CP proofs
  V6  ballot chaining codes
  V7  ballot aggregation == encrypted tally
  V8  direct partial-decryption CP proofs
  V9  compensated shares: recovery keys + CP proofs
  V10 Lagrange reconstruction of missing shares
  V11 share combination: B / Π Mᵢ == g^t
  V12 tally decode sanity (t vs cast count, placeholder exclusion)
  V13 spoiled ballot decryptions
  V14 manifest validation + tally/manifest coherence
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from electionguard_tpu.ballot.ciphertext import BallotState
from electionguard_tpu.ballot.manifest import validate_manifest
from electionguard_tpu.core.group import ElementModP, GroupContext
from electionguard_tpu.core.group_jax import (jax_exp_ops, jax_ops,
                                              limbs_to_bytes_be)
from electionguard_tpu.core import sha256_jax
from electionguard_tpu.core.hash import _encode, hash_digest, hash_elems
from electionguard_tpu.crypto.cp_batch import batch_cp_verify
from electionguard_tpu.decrypt.decryption import lagrange_coefficient
from electionguard_tpu.keyceremony.trustee import commitment_product
from electionguard_tpu.obs import REGISTRY, election_labels, span
from electionguard_tpu.obs import tenant as _tenant
from electionguard_tpu.publish.election_record import ElectionRecord
from electionguard_tpu.utils import devicetime, knobs
from electionguard_tpu.verify import rlc


@dataclass
class VerificationResult:
    checks: dict[str, bool] = field(default_factory=dict)
    errors: list[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return all(self.checks.values()) and not self.errors

    def record(self, check: str, ok: bool, msg: str = ""):
        self.checks[check] = self.checks.get(check, True) and ok
        if not ok:
            self.errors.append(f"{check}: {msg}")

    def summary(self) -> str:
        lines = [f"{'PASS' if v else 'FAIL'} {k}" for k, v in
                 sorted(self.checks.items())]
        return "\n".join(lines + self.errors)


@dataclass
class _BallotAggregates:
    """Cross-chunk state for streaming verification: V7 products, the code
    chain tail, cast/spoiled bookkeeping — everything later checks need,
    so host residency stays O(chunk) (SURVEY.md §7 hard part 4)."""

    prods: dict = field(default_factory=dict)   # (contest,sel) -> (pa, pb)
    cast_count: int = 0
    total_count: int = 0
    spoiled_ids: set = field(default_factory=set)
    prev_code: Optional[bytes] = None           # last ballot's code
    # fabric (record carries shard manifests): maximal contiguous chain
    # runs as [first_code_seed, count, last_code] — finalize maps them
    # onto the manifests — plus ballot-id overlap bookkeeping
    segments: list = field(default_factory=list)
    seen_ids: set = field(default_factory=set)
    dup_ids: set = field(default_factory=set)


class Verifier:
    """``chunk_size`` bounds how many ballots are resident/dispatched at
    once; ``record.encrypted_ballots`` may be ANY iterable — pass a lazy
    ``Consumer.iterate_encrypted_ballots()`` to verify a million-ballot
    record without materializing it (reference analogue: the 11-thread
    ``Verifier(record, nthreads)`` loads everything, RunRemoteWorkflowTest.java:180)."""

    def __init__(self, record: ElectionRecord,
                 group: Optional[GroupContext] = None,
                 chunk_size: int = 4096, mesh=None,
                 mix_input_fn=None):
        """``mesh``: an ``electionguard_tpu.parallel.mesh`` device mesh —
        when given (and the group supports the fused path), the V4/V5
        device programs shard the selection/contest batch axis over the
        mesh's dp axis, scaling verification across chips the way the
        reference scales it across 11 CPU threads
        (RunRemoteWorkflowTest.java:180).

        ``mix_input_fn``: zero-arg callable returning the mixnet's
        stage-0 input ``(pads, datas)`` rows when the record carries mix
        stages and the ballot stream is not re-iterable (run_verifier
        passes a fresh Consumer iteration); with an in-memory ballot
        list the rows are derived directly."""
        self.record = record
        self.group = group if group is not None else \
            record.election_init.joint_public_key.group
        self.ops = jax_ops(self.group)
        self.eops = jax_exp_ops(self.group)
        self.init = record.election_init
        self.chunk_size = chunk_size
        self.mesh = mesh
        self.mix_input_fn = mix_input_fn

    def _fused(self):
        """The fused on-device V4/V5 checker for this verifier's batch
        plane (verify/fused.py) — shared process-wide per (plane, mesh),
        so its jitted programs compile once per group."""
        from electionguard_tpu.verify.fused import get_fused
        return get_fused(self.ops, self.mesh)

    def _masked_prod(self, arrays, row_groups):
        """Π over row groups of (S, n) limb arrays in ONE device
        product-reduce: gather each group's rows (identity-padded to the
        widest group), stack every input array's groups, reduce the
        group axis.  The shared primitive behind V5's contest ciphertext
        accumulation and V7's tally products.  Returns one
        (len(row_groups), n) array per input."""
        nk = len(row_groups)
        maxm = max(len(ix) for ix in row_groups)
        gather = np.zeros((nk, maxm), dtype=np.int64)
        mask = np.zeros((nk, maxm), dtype=bool)
        for j, ix in enumerate(row_groups):
            gather[j, :len(ix)] = ix
            mask[j, :len(ix)] = True
        one_row = np.zeros((self.ops.n,), np.uint32)
        one_row[0] = 1
        gathered = [np.where(mask[..., None], np.asarray(a)[gather],
                             one_row) for a in arrays]
        stacked = np.concatenate(gathered).transpose(1, 0, 2)
        prod = np.asarray(self.ops.prod_reduce(stacked))
        return [prod[i * nk:(i + 1) * nk] for i in range(len(arrays))]

    # ==================================================================
    def verify(self) -> VerificationResult:
        res = VerificationResult()
        agg = _BallotAggregates()
        self.verify_ballots_partial(self.record.encrypted_ballots,
                                    res, agg)
        return self.finalize(res, agg)

    # -- the three phases of a (possibly multi-feeder) verification ----
    def verify_ballots_partial(self, ballots, res: VerificationResult,
                               agg: _BallotAggregates,
                               prev_code: Optional[bytes] = None) -> None:
        """Run the per-ballot checks (V4/V5/V6 + V7/V13 bookkeeping)
        over ``ballots`` — one contiguous slice of the record stream.
        ``prev_code`` seeds the V6 chain for a feeder starting mid-record
        (the preceding ballot's confirmation code); None means this slice
        starts the chain and must anchor to the manifest.  Feeders run
        this independently over disjoint slices; ``merge_partials`` then
        recombines their (res, agg) pairs."""
        if prev_code is not None:
            agg.prev_code = prev_code
        it = iter(ballots)
        while True:
            chunk = list(itertools.islice(it, self.chunk_size))
            if not chunk:
                break
            devicetime.charge(
                "verify_batch" if knobs.get_flag("EGTPU_VERIFY_BATCH")
                else "verify", len(chunk))
            self._verify_ballot_chunk(res, chunk, agg)

    @staticmethod
    def merge_partials(parts) -> tuple[VerificationResult,
                                       _BallotAggregates]:
        """Combine feeders' (res, agg) pairs: checks AND together, V7
        products multiply (the tally product tree is associative), counts
        and spoiled sets add.  Feeders must cover disjoint contiguous
        slices in record order, each seeded with its predecessor's
        boundary code."""
        res = VerificationResult()
        agg = _BallotAggregates()
        for r, a in parts:
            for k, v in r.checks.items():
                res.checks[k] = res.checks.get(k, True) and v
            res.errors.extend(r.errors)
            for k, (pa, pb) in a.prods.items():
                x, y = agg.prods.get(k, (1, 1))
                agg.prods[k] = (x * pa, y * pb)
            agg.cast_count += a.cast_count
            agg.total_count += a.total_count
            agg.spoiled_ids |= a.spoiled_ids
            # fabric: a chain run continuing across the feeder boundary
            # coalesces (its first seed IS the previous slice's tail code)
            for seg in a.segments:
                if agg.segments and seg[0] == agg.segments[-1][2]:
                    agg.segments[-1][1] += seg[1]
                    agg.segments[-1][2] = seg[2]
                else:
                    agg.segments.append(list(seg))
            agg.dup_ids |= a.dup_ids | (agg.seen_ids & a.seen_ids)
            agg.seen_ids |= a.seen_ids
            agg.prev_code = a.prev_code
        return res, agg

    def finalize(self, res: VerificationResult,
                 agg: _BallotAggregates) -> VerificationResult:
        """The record-level checks that need the WHOLE record's
        aggregates: group/key/guardian checks, V7 against the tally,
        decryption share checks, spoiled tallies, coherence."""
        # reduce merged products mod p (merge_partials multiplies raw)
        g = self.group
        agg.prods = {k: (pa % g.p, pb % g.p)
                     for k, (pa, pb) in agg.prods.items()}
        self._v1_parameters(res)
        self._v2_guardian_keys(res)
        self._v3_joint_key(res)
        if self.record.tally_result is not None:
            self._v7_aggregation(res, agg)
        if self.record.decryption_result is not None:
            self._v8_to_v12_decryption(res)
        self._v13_spoiled(res, agg)
        self._v14_coherence(res)
        if self.record.mix_stages:
            self._v15_mixnet(res)
        if self.record.shard_manifests:
            self._v_shard_manifests(res, agg)
        return res

    # ==================================================================
    def _v1_parameters(self, res):
        g = self.group
        res.record("V1.parameters",
                   g.spec.name != "production-4096"
                   or (g.p.bit_length() == 4096
                       and g.q == (1 << 256) - 189),
                   "production group has wrong p/q sizes")
        res.record("V1.parameters", (g.p - 1) % g.q == 0 and
                   pow(g.g, g.q, g.p) == 1 and g.g != 1,
                   "group structure invalid")
        cfg = self.init.config
        res.record("V1.parameters",
                   1 <= cfg.quorum <= cfg.n_guardians,
                   "quorum out of range")
        res.record("V1.parameters",
                   len(self.init.guardians) == cfg.n_guardians,
                   "guardian count mismatch")

    def _v2_guardian_keys(self, res):
        """Structure host-side; all Schnorr proofs + subgroup checks of
        the whole ceremony as ONE device batch (batch_schnorr_verify) —
        the reference verifies them one at a time inside each trustee
        [ext] (SURVEY.md §3.1)."""
        from electionguard_tpu.crypto.schnorr import batch_schnorr_verify
        quorum = self.init.config.quorum
        proofs, refs = [], []
        for gr in self.init.guardians:
            if (len(gr.coefficient_commitments) != quorum
                    or len(gr.coefficient_proofs) != quorum):
                res.record("V2.guardian_keys", False,
                           f"{gr.guardian_id} has "
                           f"{len(gr.coefficient_commitments)} commitments /"
                           f" {len(gr.coefficient_proofs)} proofs, expected "
                           f"quorum={quorum} of each")
            for j, (k, pr) in enumerate(zip(gr.coefficient_commitments,
                                            gr.coefficient_proofs)):
                if pr.public_key != k:
                    res.record("V2.guardian_keys", False,
                               f"{gr.guardian_id} proof {j} wrong key")
                    continue
                proofs.append(pr)
                refs.append((gr.guardian_id, j))
        if proofs:
            ok, sub = batch_schnorr_verify(self.group, proofs,
                                           check_subgroup=True)
            # one error per failing proof: a proof failing both masks
            # reports the Schnorr failure, not a second subgroup line
            for i in np.nonzero(~ok)[0]:
                gid, j = refs[int(i)]
                res.record("V2.guardian_keys", False,
                           f"{gid} Schnorr {j} invalid")
            for i in np.nonzero(ok & ~sub)[0]:
                gid, j = refs[int(i)]
                res.record("V2.guardian_keys", False,
                           f"{gid} commitment {j} not in subgroup")
        res.record("V2.guardian_keys", True)

    def _v3_joint_key(self, res):
        g = self.group
        joint = g.mult_p(*(gr.coefficient_commitments[0]
                           for gr in self.init.guardians))
        res.record("V3.joint_key", joint == self.init.joint_public_key,
                   "joint key != product of guardian keys")
        crypto_base = hash_elems(
            g, g.p, g.q, g.g, self.init.config.n_guardians,
            self.init.config.quorum, self.init.manifest_hash)
        res.record("V3.joint_key",
                   crypto_base == self.init.crypto_base_hash,
                   "crypto base hash mismatch")
        extended = hash_elems(g, crypto_base, self.init.joint_public_key)
        res.record("V3.joint_key",
                   extended == self.init.extended_base_hash,
                   "extended base hash mismatch")

    # ---- RLC batch screens (EGTPU_VERIFY_BATCH) ----------------------
    def _v4_rlc_batch(self, g, qbar, K, alphas, betas, c0s, v0s, c1s,
                      v1s, sel_hints, A_l, B_l, c0_l, c1_l, in_range):
        """Accept screen for a whole chunk of V4 proofs: hash-bind each
        hint row to its published challenge, then one membership RLC and
        one equation RLC (two MSMs) replace ~6 full ladders per proof.
        Returns True only when EVERY check is green; any failure bumps
        ``verify_rlc_fallbacks_total`` and the caller re-runs the naive
        path, which owns per-row error attribution (soundness budget:
        verify/rlc.py module docstring)."""
        S = len(alphas)
        eo = self.ops
        with span("verify.batch", {"family": "V4", "n": S,
                           "election": _tenant.current_election()}):
            REGISTRY.counter("verify_rlc_batches_total",
                 election_labels()).inc()
            if any(len(h) != 4 or not all(0 < x < g.p for x in h)
                   for h in sel_hints):
                REGISTRY.counter("verify_rlc_fallbacks_total",
                 election_labels()).inc()
                return False
            if sha256_jax.supports(g):
                h_l = [eo.to_limbs_p([h[j] for h in sel_hints])
                       for j in range(4)]
                hash_ok = self._fused().v4_hint_hash(
                    A_l, B_l, h_l[0], h_l[1], h_l[2], h_l[3],
                    c0_l, c1_l, _encode(qbar))
            else:
                hash_ok = np.zeros(S, dtype=bool)
                for i in range(S):
                    h = sel_hints[i]
                    c = hash_elems(
                        g, qbar,
                        ElementModP(alphas[i], g), ElementModP(betas[i], g),
                        ElementModP(h[0], g), ElementModP(h[1], g),
                        ElementModP(h[2], g), ElementModP(h[3], g))
                    hash_ok[i] = (c0s[i] + c1s[i]) % g.q == c.value
            ok = (bool(np.asarray(hash_ok).all())
                  and bool(in_range.all())
                  and rlc.membership_rlc(eo, list(alphas) + list(betas))
                  and rlc.rlc_check_v4(eo, K, alphas, betas,
                                       c0s, v0s, c1s, v1s, sel_hints))
        if not ok:
            REGISTRY.counter("verify_rlc_fallbacks_total",
                 election_labels()).inc()
        return ok

    def _v5_rlc_batch(self, g, qbar, K, CA_l, CB_l, consts, ccs, cvs,
                      con_hints, cc_l):
        """V5 twin of ``_v4_rlc_batch``.  CA/CB are device products of
        V4 elements that already passed the membership screen, so only
        the hash binding and the equation RLC run here."""
        C = len(ccs)
        eo = self.ops
        with span("verify.batch", {"family": "V5", "n": C,
                           "election": _tenant.current_election()}):
            REGISTRY.counter("verify_rlc_batches_total",
                 election_labels()).inc()
            if any(len(h) != 2 or not all(0 < x < g.p for x in h)
                   for h in con_hints):
                REGISTRY.counter("verify_rlc_fallbacks_total",
                 election_labels()).inc()
                return False
            CA_np, CB_np = np.asarray(CA_l), np.asarray(CB_l)
            CA_i = eo.from_limbs(CA_np)
            CB_i = eo.from_limbs(CB_np)
            if sha256_jax.supports(g):
                ha_l = np.asarray(eo.to_limbs_p([h[0] for h in con_hints]))
                hb_l = np.asarray(eo.to_limbs_p([h[1] for h in con_hints]))
                cc_np = np.asarray(cc_l)
                hash_ok = np.zeros(C, dtype=bool)
                fused = self._fused()
                by_const: dict[int, list[int]] = {}
                for i, const in enumerate(consts):
                    by_const.setdefault(const, []).append(i)
                for const, idxs in by_const.items():
                    ix = np.asarray(idxs)
                    hash_ok[ix] = fused.v5_hint_hash(
                        CA_np[ix], CB_np[ix], ha_l[ix], hb_l[ix],
                        cc_np[ix], _encode(qbar) + _encode(const))
            else:
                hash_ok = np.zeros(C, dtype=bool)
                for i in range(C):
                    h = con_hints[i]
                    c = hash_elems(
                        g, qbar, consts[i],
                        ElementModP(CA_i[i], g), ElementModP(CB_i[i], g),
                        ElementModP(h[0], g), ElementModP(h[1], g))
                    hash_ok[i] = ccs[i] == c.value
            ok = (bool(hash_ok.all())
                  and rlc.rlc_check_v5(eo, K, CA_i, CB_i,
                                       consts, ccs, cvs, con_hints))
        if not ok:
            REGISTRY.counter("verify_rlc_fallbacks_total",
                 election_labels()).inc()
        return ok

    # ==================================================================
    def _verify_ballot_chunk(self, res, ballots, agg: _BallotAggregates):
        """V4/V5/V6 on one chunk + V7/V13 bookkeeping into ``agg``."""
        g = self.group
        qbar = self.init.extended_base_hash

        # ---- flatten all selections --------------------------------------
        alphas, betas = [], []
        c0s, v0s, c1s, v1s = [], [], [], []
        sel_refs, sel_hints = [], []
        key_rows: dict[tuple, list[int]] = {}  # V7: cast rows per key
        manifest_sels = {(c.object_id, s.object_id)
                         for c in self.init.config.manifest.contests
                         for s in c.selections}
        manifest_contests = {c.object_id: c
                             for c in self.init.config.manifest.contests}
        for b in ballots:
            # structural soundness per ballot: no duplicate contests, and
            # within each contest the non-placeholder selections must match
            # the manifest contest's selection set EXACTLY (duplicates or
            # omissions would add/remove votes while every proof still
            # verifies), with exactly votes_allowed placeholders.
            contest_ids = [c.contest_id for c in b.contests]
            if len(set(contest_ids)) != len(contest_ids):
                res.record("V4.selection_proofs", False,
                           f"{b.ballot_id}: duplicate contest ids")
            try:
                style_contests = {
                    c.object_id for c in
                    self.init.config.manifest.contests_for_style(
                        b.ballot_style_id)}
                if set(contest_ids) != style_contests:
                    res.record("V4.selection_proofs", False,
                               f"{b.ballot_id}: contests do not match "
                               f"ballot style {b.ballot_style_id}")
            except StopIteration:
                res.record("V4.selection_proofs", False,
                           f"{b.ballot_id}: unknown ballot style "
                           f"{b.ballot_style_id}")
            for c in b.contests:
                desc = manifest_contests.get(c.contest_id)
                if desc is None:
                    res.record("V4.selection_proofs", False,
                               f"{b.ballot_id}: contest {c.contest_id} not "
                               f"in manifest")
                    continue
                real_ids = [s.selection_id for s in c.selections
                            if not s.is_placeholder]
                want_ids = {s.object_id for s in desc.selections}
                if len(set(real_ids)) != len(real_ids):
                    res.record("V4.selection_proofs", False,
                               f"{b.ballot_id}/{c.contest_id}: duplicate "
                               f"selection ids")
                if set(real_ids) != want_ids:
                    res.record("V4.selection_proofs", False,
                               f"{b.ballot_id}/{c.contest_id}: selections "
                               f"do not match the manifest exactly")
                ph_ids = [s.selection_id for s in c.selections
                          if s.is_placeholder]
                if (len(ph_ids) != desc.votes_allowed
                        or len(set(ph_ids)) != len(ph_ids)):
                    res.record("V4.selection_proofs", False,
                               f"{b.ballot_id}/{c.contest_id}: expected "
                               f"{desc.votes_allowed} distinct placeholders,"
                               f" got {len(ph_ids)}")
            for c in b.contests:
                for s in c.selections:
                    # the placeholder flag must be consistent with the id:
                    # real selections live in the manifest, placeholders use
                    # the reserved naming — prevents flipping the flag to
                    # add/remove votes from the tally
                    if s.is_placeholder:
                        if not s.selection_id.startswith(
                                f"{c.contest_id}-placeholder-"):
                            res.record(
                                "V4.selection_proofs", False,
                                f"{b.ballot_id}: placeholder flag on "
                                f"non-placeholder id {s.selection_id}")
                    elif (c.contest_id, s.selection_id) not in manifest_sels:
                        res.record(
                            "V4.selection_proofs", False,
                            f"{b.ballot_id}: selection {s.selection_id} "
                            f"not in manifest contest {c.contest_id}")
                    if not s.is_placeholder and b.state == BallotState.CAST:
                        # V7 gathers this row's limbs straight from the
                        # V4 arrays — no second int->limb conversion
                        key_rows.setdefault(
                            (c.contest_id, s.selection_id),
                            []).append(len(alphas))
                    alphas.append(s.ciphertext.pad.value)
                    betas.append(s.ciphertext.data.value)
                    p = s.proof
                    c0s.append(p.proof_zero_challenge.value)
                    v0s.append(p.proof_zero_response.value)
                    c1s.append(p.proof_one_challenge.value)
                    v1s.append(p.proof_one_response.value)
                    sel_hints.append(p.commitment_hints)
                    sel_refs.append((b.ballot_id, c.contest_id,
                                     s.selection_id))
        S = len(alphas)
        if S == 0:
            res.record("V4.selection_proofs", True)
            self._chunk_bookkeeping(res, ballots, agg, None, None, {})
            return
        eo, ee = self.ops, self.eops
        A_l = eo.to_limbs_p(alphas)
        B_l = eo.to_limbs_p(betas)
        c0_l = ee.to_limbs(c0s)
        v0_l = ee.to_limbs(v0s)
        c1_l = ee.to_limbs(c1s)
        v1_l = ee.to_limbs(v1s)

        # range check on host (the ints are already in hand); everything
        # element-sized stays on device
        in_range = np.fromiter(
            ((0 < a < g.p) and (0 < b < g.p)
             for a, b in zip(alphas, betas)), dtype=bool, count=S)
        K = self.init.joint_public_key.value
        q = g.q
        # RLC batch screen (EGTPU_VERIFY_BATCH): when every proof in the
        # chunk carries commitment hints, one hash-binding pass + two
        # MSMs replace the per-proof modexp ladders.  ANY failure —
        # missing/corrupt hints, membership, or the RLC equation — falls
        # through to the naive path below, which re-judges every row and
        # owns the per-row error attribution.
        v4_done = False
        if (knobs.get_flag("EGTPU_VERIFY_BATCH")
                and all(h is not None for h in sel_hints)):
            v4_done = self._v4_rlc_batch(
                g, qbar, K, alphas, betas, c0s, v0s, c1s, v1s,
                sel_hints, A_l, B_l, c0_l, c1_l, in_range)
        if v4_done:
            pass
        elif sha256_jax.supports(g):
            # fused device program (verify/fused.py): shared-base
            # multi-exp {q, c0, c1} per ciphertext element, commitment
            # recompute, device Fiat–Shamir, challenge compare — one
            # (S, 2) boolean array comes back, nothing element-sized.
            ok2 = self._fused().v4_selections(
                A_l, B_l, c0_l, v0_l, c1_l, v1_l,
                K, _encode(qbar))
            for i in np.nonzero(~(ok2[:, 0] & in_range))[0]:
                res.record("V4.selection_proofs", False,
                           f"ciphertext element {sel_refs[int(i)]} not in "
                           f"subgroup")
            for i in np.nonzero(~ok2[:, 1])[0]:
                res.record("V4.selection_proofs", False,
                           f"disjunctive proof fails for {sel_refs[int(i)]}")
        else:
            # unfused fallback (tiny group / host hash): shared-base
            # multi-exp still halves the ladder work, hash runs on host
            q_row = ee.to_limbs([g.q])[0]
            q_rep = np.broadcast_to(q_row, (S, q_row.shape[0]))
            pows_a = np.asarray(eo.multi_powmod(
                A_l, np.stack([q_rep, np.asarray(c0_l),
                               np.asarray(c1_l)], axis=1)))
            pows_b = np.asarray(eo.multi_powmod(
                B_l, np.stack([q_rep, np.asarray(c0_l),
                               np.asarray(c1_l)], axis=1)))
            one_l = np.zeros_like(pows_a[:, 0])
            one_l[:, 0] = 1
            in_subgroup = ((pows_a[:, 0] == one_l).all(axis=1)
                           & (pows_b[:, 0] == one_l).all(axis=1))
            for i in np.nonzero(~(in_subgroup & in_range))[0]:
                res.record("V4.selection_proofs", False,
                           f"ciphertext element {sel_refs[int(i)]} not in "
                           f"subgroup")

            ginv = g.GINV_MOD_P.value
            g_pows = np.asarray(eo.g_pow(np.concatenate([v0_l, v1_l])))
            k_pows = np.asarray(eo.base_pow(K, np.concatenate([v0_l, v1_l])))
            ginv_c1 = np.asarray(eo.base_pow(ginv, c1_l))
            a0 = np.asarray(eo.mulmod(g_pows[:S], pows_a[:, 1]))
            b0 = np.asarray(eo.mulmod(k_pows[:S], pows_b[:, 1]))
            a1 = np.asarray(eo.mulmod(g_pows[S:], pows_a[:, 2]))
            b1 = np.asarray(eo.mulmod(
                k_pows[S:], np.asarray(eo.mulmod(pows_b[:, 2], ginv_c1))))

            alpha_b = limbs_to_bytes_be(A_l)
            beta_b = limbs_to_bytes_be(B_l)
            a0b, b0b = limbs_to_bytes_be(a0), limbs_to_bytes_be(b0)
            a1b, b1b = limbs_to_bytes_be(a1), limbs_to_bytes_be(b1)
            for i in range(S):
                c = hash_elems(
                    g, qbar,
                    g.bytes_to_p(bytes(alpha_b[i])),
                    g.bytes_to_p(bytes(beta_b[i])),
                    g.bytes_to_p(bytes(a0b[i])), g.bytes_to_p(bytes(b0b[i])),
                    g.bytes_to_p(bytes(a1b[i])), g.bytes_to_p(bytes(b1b[i])))
                if (c0s[i] + c1s[i]) % q != c.value:
                    res.record("V4.selection_proofs", False,
                               f"disjunctive proof fails for {sel_refs[i]}")
        res.record("V4.selection_proofs", True)

        # ---- V5: contest limits ------------------------------------------
        contest_cs, contest_vs, contest_consts = [], [], []
        contest_refs, con_hints = [], []
        contest_spans = []   # (start, count) into the V4 selection rows
        contests_by_id = {c.object_id: c
                          for c in self.init.config.manifest.contests}
        off = 0
        for b in ballots:
            for c in b.contests:
                contest_spans.append((off, len(c.selections)))
                off += len(c.selections)
                contest_cs.append(c.proof.challenge.value)
                contest_vs.append(c.proof.response.value)
                contest_consts.append(c.proof.constant)
                con_hints.append(c.proof.commitment_hints)
                contest_refs.append((b.ballot_id, c.contest_id))
                desc = contests_by_id.get(c.contest_id)
                if desc is not None and c.proof.constant != desc.votes_allowed:
                    res.record("V5.contest_limits", False,
                               f"{b.ballot_id}/{c.contest_id} limit proof "
                               f"constant {c.proof.constant} != "
                               f"{desc.votes_allowed}")
        C = len(contest_refs)
        # contest ciphertext accumulation Π(α,β) on DEVICE: one masked
        # gather + product-reduce over the V4 limb arrays — no
        # per-selection host BigInteger math
        A_np, B_np = np.asarray(A_l), np.asarray(B_l)
        CA_l, CB_l = self._masked_prod(
            [A_np, B_np],
            [list(range(start, start + cnt))
             for start, cnt in contest_spans])
        cc_l = np.asarray(ee.to_limbs(contest_cs))
        cv_l = np.asarray(ee.to_limbs(contest_vs))
        v5_done = False
        if (knobs.get_flag("EGTPU_VERIFY_BATCH") and C > 0
                and all(h is not None for h in con_hints)):
            v5_done = self._v5_rlc_batch(
                g, qbar, K, CA_l, CB_l, contest_consts, contest_cs,
                contest_vs, con_hints, cc_l)
        if v5_done:
            pass
        elif sha256_jax.supports(g):
            # fused device program: (g^-1)^L fixed-base pass, commitment
            # recompute, device Fiat–Shamir, challenge compare — booleans
            # back.  Rows share a hash-message layout only within one
            # constant value; group by constant (in practice one group
            # per election).
            Lq_l = np.asarray(ee.to_limbs(contest_consts))
            by_const: dict[int, list[int]] = {}
            for i, const in enumerate(contest_consts):
                by_const.setdefault(const, []).append(i)
            fused = self._fused()
            for const, idxs in by_const.items():
                ix = np.asarray(idxs)
                ok5 = fused.v5_contests(
                    CA_l[ix], CB_l[ix], Lq_l[ix], cc_l[ix], cv_l[ix],
                    K, _encode(qbar) + _encode(const))
                for j in np.nonzero(~ok5)[0]:
                    res.record(
                        "V5.contest_limits", False,
                        f"constant proof fails for {contest_refs[idxs[int(j)]]}")
        else:
            # unfused fallback: device group math, host Fiat–Shamir
            ginv = g.GINV_MOD_P.value
            gL = [pow(ginv, L, g.p) for L in contest_consts]  # B / g^L
            gL_l = eo.to_limbs_p(gL)
            CBs_l = np.asarray(eo.mulmod(CB_l, gL_l))
            var2 = np.asarray(eo.powmod(
                np.concatenate([CA_l, CBs_l]), np.concatenate([cc_l, cc_l])))
            gp2 = np.asarray(eo.g_pow(cv_l))
            kp2 = np.asarray(eo.base_pow(K, cv_l))
            a_c = np.asarray(eo.mulmod(gp2, var2[:C]))
            b_c = np.asarray(eo.mulmod(kp2, var2[C:]))
            CAb = limbs_to_bytes_be(CA_l)
            CBb = limbs_to_bytes_be(CB_l)
            acb = limbs_to_bytes_be(a_c)
            bcb = limbs_to_bytes_be(b_c)
            for i in range(C):
                c = hash_elems(
                    g, qbar, contest_consts[i],
                    g.bytes_to_p(bytes(CAb[i])), g.bytes_to_p(bytes(CBb[i])),
                    g.bytes_to_p(bytes(acb[i])), g.bytes_to_p(bytes(bcb[i])))
                if contest_cs[i] != c.value:
                    res.record("V5.contest_limits", False,
                               f"constant proof fails for {contest_refs[i]}")
        res.record("V5.contest_limits", True)

        # ---- V6 chain + V7/V13 bookkeeping -------------------------------
        self._chunk_bookkeeping(res, ballots, agg, A_np, B_np, key_rows)

    def _chunk_bookkeeping(self, res, ballots, agg: _BallotAggregates,
                           A_np, B_np, key_rows):
        """V6 chaining (continuity carried across chunks via ``agg``) plus
        V7 product accumulation and cast/spoiled counting.  ``A_np``/
        ``B_np`` are the chunk's V4 selection limb arrays and
        ``key_rows`` maps (contest, selection) -> their cast
        non-placeholder row indices: V7 gathers straight from the arrays
        already on hand (one device product-reduce, no per-selection
        int->limb rebuild)."""
        g = self.group
        from electionguard_tpu.ballot.code_batch import batch_codes
        codes = batch_codes(ballots)   # recomputed hash tree, batched
        sharded = bool(self.record.shard_manifests)
        for i, b in enumerate(ballots):
            if b.code != codes[i].tobytes():
                res.record("V6.ballot_chaining", False,
                           f"{b.ballot_id} confirmation code invalid")
            if sharded:
                # a merged fleet record is N chains, not one: collect the
                # maximal contiguous runs here; finalize's
                # V.shard_manifest family maps every run onto a signed
                # manifest (so a chain break is a red check THERE, not an
                # inline V6 error)
                if (not agg.segments or agg.prev_code is None
                        or b.code_seed != agg.prev_code):
                    # also opens the run for a feeder seeded mid-chain:
                    # its first seed is the previous slice's tail code, so
                    # merge_partials coalesces the two runs back together
                    agg.segments.append([b.code_seed, 0, b.code])
                seg = agg.segments[-1]
                seg[1] += 1
                seg[2] = b.code
                if b.ballot_id in agg.seen_ids:
                    agg.dup_ids.add(b.ballot_id)
                agg.seen_ids.add(b.ballot_id)
            elif agg.prev_code is None:
                # chain start must anchor to the manifest (the encryptor's
                # start value, encrypt/encryptor.py): otherwise truncating
                # leading ballots is invisible to the chain check
                anchor = hash_digest("code-chain-start",
                                     self.init.manifest_hash)
                if b.code_seed != anchor:
                    res.record("V6.ballot_chaining", False,
                               f"{b.ballot_id} chain start is not anchored "
                               f"to the manifest (leading ballots removed?)")
            elif b.code_seed != agg.prev_code:
                # chain continuity: code_seed = previous ballot's code
                res.record("V6.ballot_chaining", False,
                           f"{b.ballot_id} breaks the code chain")
            agg.prev_code = b.code
        res.record("V6.ballot_chaining", True)

        agg.total_count += len(ballots)
        agg.spoiled_ids.update(b.ballot_id for b in ballots
                               if b.state == BallotState.SPOILED)
        agg.cast_count += sum(b.state == BallotState.CAST for b in ballots)
        if not key_rows:
            return
        keys = sorted(key_rows)
        pa_l, pb_l = self._masked_prod([A_np, B_np],
                                       [key_rows[k] for k in keys])
        pa_i = self.ops.from_limbs(pa_l)
        pb_i = self.ops.from_limbs(pb_l)
        for i, k in enumerate(keys):
            pa, pd = agg.prods.get(k, (1, 1))
            agg.prods[k] = (pa * pa_i[i] % g.p, pd * pb_i[i] % g.p)

    # ==================================================================
    def _v7_aggregation(self, res, agg: _BallotAggregates):
        tally = self.record.tally_result.encrypted_tally
        prods = agg.prods
        seen = set()
        for c in tally.contests:
            for s in c.selections:
                key = (c.contest_id, s.selection_id)
                seen.add(key)
                # a selection on no cast ballot accumulates the identity
                want = prods.get(key, (1, 1))
                got = (s.ciphertext.pad.value, s.ciphertext.data.value)
                if got != want:
                    res.record("V7.aggregation", False,
                               f"tally mismatch at {key}")
        if agg.total_count:
            for key in prods:
                if key not in seen:
                    res.record("V7.aggregation", False,
                               f"ballot selection {key} missing from tally")
            if tally.cast_ballot_count != agg.cast_count:
                res.record("V7.aggregation", False,
                           f"tally cast count {tally.cast_ballot_count} != "
                           f"{agg.cast_count} cast ballots in record")
        res.record("V7.aggregation", True)

    # ==================================================================
    def _v8_to_v12_decryption(self, res):
        g = self.group
        dr = self.record.decryption_result
        avail = {dg.guardian_id: dg for dg in dr.decrypting_guardians}
        xs = [dg.x_coordinate for dg in dr.decrypting_guardians]

        # Lagrange coefficients recorded == recomputed (V10 part 1)
        for dg in dr.decrypting_guardians:
            want = lagrange_coefficient(g, xs, dg.x_coordinate)
            if dg.lagrange_coefficient != want:
                res.record("V10.lagrange", False,
                           f"lagrange coefficient of {dg.guardian_id} wrong")
        res.record("V10.lagrange", True)

        # anchor against the independently verified record tally (V7
        # checked it against the ballots), NOT the copy embedded in the
        # attacker-publishable DecryptionResult — otherwise dropping a
        # selection from both halves of that one file passes
        anchor_tally = (self.record.tally_result.encrypted_tally
                        if self.record.tally_result is not None
                        else dr.tally_result.encrypted_tally)
        cast_count = anchor_tally.cast_ballot_count
        labels = {"direct": "V8.direct_proofs", "comp": "V9.compensated",
                  "lagrange": "V10.lagrange",
                  "combine": "V11.share_combination"}
        self._verify_tally_shares(res, dr.decrypted_tally, avail, labels)

        # V12: decode sanity — per-selection and per-contest bounds, and
        # the decrypted tally must cover the encrypted tally one-for-one
        # (dropping a selection from the published decryption would
        # otherwise go unnoticed)
        contests_by_id = {c.object_id: c
                          for c in self.init.config.manifest.contests}
        enc_keys = {(c.contest_id, s.selection_id)
                    for c in anchor_tally.contests
                    for s in c.selections}
        dec_keys = set()
        for c in dr.decrypted_tally.contests:
            contest_sum = 0
            for s in c.selections:
                dec_keys.add((c.contest_id, s.selection_id))
                contest_sum += s.tally
                if cast_count and s.tally > cast_count:
                    res.record("V12.tally_decode", False,
                               f"tally {s.tally} exceeds cast ballots")
            desc = contests_by_id.get(c.contest_id)
            if desc is None:
                res.record("V12.tally_decode", False,
                           f"decrypted contest {c.contest_id} not in "
                           f"manifest")
            elif cast_count and \
                    contest_sum > desc.votes_allowed * cast_count:
                res.record("V12.tally_decode", False,
                           f"contest {c.contest_id} decoded sum "
                           f"{contest_sum} exceeds votes_allowed "
                           f"({desc.votes_allowed}) x cast ({cast_count})")
        if dec_keys != enc_keys:
            res.record("V12.tally_decode", False,
                       f"decrypted tally selections do not match the "
                       f"encrypted tally (missing: "
                       f"{sorted(enc_keys - dec_keys)}, extra: "
                       f"{sorted(dec_keys - enc_keys)})")
        res.record("V8.direct_proofs", True)
        res.record("V9.compensated", True)
        res.record("V11.share_combination", True)
        res.record("V12.tally_decode", True)

    def _verify_tally_shares(self, res, tally, avail, labels):
        """Share/proof/combination checks for one decrypted tally — used for
        the main tally (V8-V11) and each spoiled ballot (V13).

        All modexp work is batched on the device plane: the per-share CP
        proofs go through ``batch_cp_verify`` (one dispatch for the whole
        tally), the Lagrange reconstruction powers through one ``powmod``
        dispatch, and the g^t decode checks through one fixed-base
        dispatch — no per-selection host ``pow`` (the reference's combine
        loop RunRemoteDecryptor.java:261-273 is the CPU analogue).
        """
        g = self.group
        qbar = self.init.extended_base_hash
        guardians = {gr.guardian_id: gr for gr in self.init.guardians}

        cp_x, cp_g2, cp_y, cp_c, cp_v = [], [], [], [], []
        cp_meta: list[tuple[str, str]] = []   # (label, failure message)
        recon_base, recon_exp = [], []        # Lagrange power rows
        recon_meta = []                       # (start, count, want, lbl, msg)
        sel_entries = []                      # (selection, m_total int)
        # recovery keys depend only on (missing guardian, trustee) — O(n²),
        # computed once, NOT per selection
        recovery_cache: dict[tuple[str, str], ElementModP] = {}

        all_ids = set(guardians)
        avail_ids = set(avail)
        for c in tally.contests:
            for s in c.selections:
                A = s.message.pad
                m_total = 1
                # share coverage: every available guardian must contribute
                # a proved direct share, every missing guardian a
                # reconstructed share — dropping or duplicating one would
                # silently shift M = Π Mᵢ
                direct_ids = [sh.guardian_id for sh in s.shares
                              if sh.proof is not None]
                recon_ids = [sh.guardian_id for sh in s.shares
                             if sh.proof is None]
                # sorted-list comparison also rejects duplicates (the
                # right-hand sides are duplicate-free)
                if sorted(direct_ids) != sorted(avail_ids):
                    res.record(labels["direct"], False,
                               f"{s.selection_id}: direct shares from "
                               f"{sorted(direct_ids)} != available "
                               f"guardians {sorted(avail_ids)}")
                want_missing = sorted(all_ids - avail_ids)
                if sorted(recon_ids) != want_missing:
                    res.record(labels["comp"], False,
                               f"{s.selection_id}: reconstructed shares "
                               f"from {sorted(recon_ids)} != missing "
                               f"guardians {want_missing}")
                for share in s.shares:
                    gr = guardians.get(share.guardian_id)
                    if gr is None:
                        res.record(labels["direct"], False,
                                   f"share from unknown guardian "
                                   f"{share.guardian_id}")
                        continue
                    if share.proof is not None:  # direct share
                        cp_x.append(gr.coefficient_commitments[0].value)
                        cp_g2.append(A.value)
                        cp_y.append(share.share.value)
                        cp_c.append(share.proof.challenge.value)
                        cp_v.append(share.proof.response.value)
                        cp_meta.append((labels["direct"],
                                        f"direct proof {share.guardian_id} "
                                        f"on {s.selection_id} invalid"))
                    else:  # reconstructed missing share
                        if share.recovered_parts is None:
                            res.record(labels["comp"], False,
                                       f"missing share {share.guardian_id} "
                                       f"has no parts")
                            continue
                        if set(share.recovered_parts) != avail_ids:
                            res.record(labels["comp"], False,
                                       f"{s.selection_id}: parts for "
                                       f"{share.guardian_id} from "
                                       f"{sorted(share.recovered_parts)} != "
                                       f"available {sorted(avail_ids)}")
                        start, count = len(recon_base), 0
                        for t_id, part in share.recovered_parts.items():
                            t_rec = avail.get(t_id)
                            if t_rec is None:
                                res.record(labels["comp"], False,
                                           f"part from non-participant {t_id}")
                                continue
                            key = (share.guardian_id, t_id)
                            if key not in recovery_cache:
                                recovery_cache[key] = commitment_product(
                                    g, gr.coefficient_commitments,
                                    t_rec.x_coordinate)
                            if part.recovered_public_key_share != \
                                    recovery_cache[key]:
                                res.record(labels["comp"], False,
                                           f"recovery key {t_id} for "
                                           f"{share.guardian_id} wrong")
                            cp_x.append(part.recovered_public_key_share.value)
                            cp_g2.append(A.value)
                            cp_y.append(part.partial_decryption.value)
                            cp_c.append(part.proof.challenge.value)
                            cp_v.append(part.proof.response.value)
                            cp_meta.append((labels["comp"],
                                            f"compensated proof {t_id} for "
                                            f"{share.guardian_id} invalid"))
                            recon_base.append(part.partial_decryption.value)
                            recon_exp.append(
                                t_rec.lagrange_coefficient.value)
                            count += 1
                        recon_meta.append(
                            (start, count, share.share.value,
                             labels["lagrange"],
                             f"reconstruction of {share.guardian_id} on "
                             f"{s.selection_id} mismatched"))
                    m_total = m_total * share.share.value % g.p
                sel_entries.append((s, m_total))

        ok = batch_cp_verify(g, cp_x, cp_g2, cp_y, cp_c, cp_v, qbar)
        for i in np.nonzero(~ok)[0]:
            label, msg = cp_meta[int(i)]
            res.record(label, False, msg)

        if recon_base:  # M_m = Π parts^{w_ℓ}: one powmod dispatch
            pows = self.ops.powmod_ints(recon_base, recon_exp)
            for start, count, want, label, msg in recon_meta:
                prod = 1
                for v in pows[start:start + count]:
                    prod = prod * v % g.p
                if prod != want:
                    res.record(label, False, msg)

        if sel_entries:  # value·ΠMᵢ == B (no inversion) and g^t == value
            gt = self.ops.g_pow_ints([s.tally for s, _ in sel_entries])
            for (s, m_total), gt_i in zip(sel_entries, gt):
                if s.value.value * m_total % g.p != s.message.data.value:
                    res.record(labels["combine"], False,
                               f"decrypted value mismatch {s.selection_id}")
                if gt_i != s.value.value:
                    res.record(labels["combine"], False,
                               f"g^t != value for {s.selection_id}")

    # ==================================================================
    def _v13_spoiled(self, res, agg: _BallotAggregates):
        """Spoiled ballots: excluded from the tally (V7 handles that) and
        any published spoiled-ballot decryption must verify with the same
        share logic as the main tally."""
        spoiled_ids = agg.spoiled_ids
        dr = self.record.decryption_result
        avail = ({dg.guardian_id: dg for dg in dr.decrypting_guardians}
                 if dr is not None else {})
        labels = {k: "V13.spoiled"
                  for k in ("direct", "comp", "lagrange", "combine")}
        manifest_contests = {c.object_id: c
                             for c in self.init.config.manifest.contests}
        seen_tally_ids = set()
        for t in self.record.spoiled_ballot_tallies:
            if t.tally_id not in spoiled_ids:
                res.record("V13.spoiled", False,
                           f"spoiled tally {t.tally_id} for non-spoiled "
                           f"ballot")
                continue
            if t.tally_id in seen_tally_ids:
                res.record("V13.spoiled", False,
                           f"duplicate spoiled tally {t.tally_id}")
                continue
            seen_tally_ids.add(t.tally_id)
            if dr is None:
                res.record("V13.spoiled", False,
                           f"spoiled tally {t.tally_id} without a "
                           f"decryption result")
                continue
            # structure vs manifest: contests must exist, selections must
            # be manifest selections or that contest's placeholders
            for c in t.contests:
                desc = manifest_contests.get(c.contest_id)
                if desc is None:
                    res.record("V13.spoiled", False,
                               f"{t.tally_id}: contest {c.contest_id} not "
                               f"in manifest")
                    continue
                known = {s.object_id for s in desc.selections}
                for s in c.selections:
                    if (s.selection_id not in known
                            and not s.selection_id.startswith(
                                f"{c.contest_id}-placeholder-")):
                        res.record("V13.spoiled", False,
                                   f"{t.tally_id}: selection "
                                   f"{s.selection_id} not in manifest "
                                   f"contest {c.contest_id}")
            self._verify_tally_shares(res, t, avail, labels)
        res.record("V13.spoiled", True)

    def _v15_mixnet(self, res):
        """Mix cascade verification (mixnet/verify_mix.py): stage 0 must
        re-encrypt exactly the record's cast ballots, every stage must
        chain, and every Terelius–Wikström transcript must verify."""
        from electionguard_tpu.mixnet import verify_mix
        fn = self.mix_input_fn
        if fn is None:
            ballots = self.record.encrypted_ballots
            if isinstance(ballots, (list, tuple)):
                fn = lambda: verify_mix.rows_from_ballots(ballots)  # noqa: E731
        if fn is None:
            res.record("V15.mix_structure", False,
                       "mix stages present but the ballot stream is not "
                       "re-iterable and no mix_input_fn was given")
            return
        verify_mix.verify_stages(self.group, self.init,
                                 self.record.mix_stages, res, fn)

    def _v14_coherence(self, res):
        msgs = validate_manifest(self.init.config.manifest)
        if msgs.has_errors():
            res.record("V14.coherence", False, str(msgs))
        if self.init.manifest_hash != \
                self.init.config.manifest.crypto_hash():
            res.record("V14.coherence", False, "manifest hash mismatch")
        manifest_sels = {
            (c.object_id, s.object_id)
            for c in self.init.config.manifest.contests
            for s in c.selections}
        if self.record.tally_result is not None:
            for c in self.record.tally_result.encrypted_tally.contests:
                for s in c.selections:
                    if (c.contest_id, s.selection_id) not in manifest_sels:
                        res.record("V14.coherence", False,
                                   f"tally selection ({c.contest_id}, "
                                   f"{s.selection_id}) not in manifest")
        res.record("V14.coherence", True)

    def _v_shard_manifests(self, res, agg: _BallotAggregates):
        """V.shard_manifest.*: a merged fleet record's shard chains are
        individually contiguous, mutually disjoint, and jointly complete.

        * ``signature`` — every published manifest's Schnorr signature
          verifies under its own key (tampering with a signed manifest
          without the worker's secret goes red; binding the KEYS to the
          legitimate fleet roster is the deployment's job — e.g. publish
          the router's registration log);
        * ``seed`` — every claimed chain seed is
          ``H("shard-chain-start", manifest_hash, shard_id)``, so a
          manifest can't smuggle in an arbitrary anchor;
        * ``chain`` — every contiguous chain run in the ballot stream
          starts at exactly one manifest's seed and carries exactly that
          manifest's admitted count up to its head hash (a gap splits a
          run in two: the orphan half matches no manifest);
        * ``overlap`` — no ballot id is published by two chains;
        * ``complete`` — shard ids are distinct and the manifests'
          admitted counts sum to the record's ballot count.
        """
        from electionguard_tpu.fabric import manifest as fab_manifest
        g = self.group
        manifests = self.record.shard_manifests
        seen_sids: set[int] = set()
        seed_of: dict[bytes, object] = {}
        for m in manifests:
            if m.shard_id in seen_sids:
                res.record("V.shard_manifest.complete", False,
                           f"duplicate shard id {m.shard_id} in the "
                           f"published manifests")
            seen_sids.add(m.shard_id)
            if not fab_manifest.verify_manifest_signature(g, m):
                res.record("V.shard_manifest.signature", False,
                           f"shard {m.shard_id}: manifest signature "
                           f"invalid (forged or tampered)")
            want = fab_manifest.shard_chain_seed(self.init.manifest_hash,
                                                 m.shard_id)
            if m.chain_seed != want:
                res.record("V.shard_manifest.seed", False,
                           f"shard {m.shard_id}: chain seed is not "
                           f"H('shard-chain-start', manifest_hash, "
                           f"{m.shard_id})")
            seed_of[m.chain_seed] = m
        # map every observed chain run onto exactly one manifest
        claimed: dict[int, list] = {}
        for first_seed, count, last_code in agg.segments:
            m = seed_of.get(first_seed)
            if m is None:
                res.record("V.shard_manifest.chain", False,
                           f"chain run of {count} ballot(s) starting at "
                           f"{first_seed.hex()[:16]} matches no shard "
                           f"manifest (gapped or truncated chain?)")
                continue
            if m.shard_id in claimed:
                res.record("V.shard_manifest.chain", False,
                           f"shard {m.shard_id}: chain restarts from its "
                           f"seed ({claimed[m.shard_id][1]} then {count} "
                           f"ballots)")
                continue
            claimed[m.shard_id] = [first_seed, count, last_code]
        for m in manifests:
            got = claimed.get(m.shard_id)
            if got is None:
                if m.admitted_count:
                    res.record("V.shard_manifest.chain", False,
                               f"shard {m.shard_id}: manifest claims "
                               f"{m.admitted_count} ballot(s), the record "
                               f"has none from its chain")
                continue
            _, count, last_code = got
            if count != m.admitted_count:
                res.record("V.shard_manifest.chain", False,
                           f"shard {m.shard_id}: manifest claims "
                           f"{m.admitted_count} ballot(s), its chain has "
                           f"{count}")
            if last_code != m.head_hash:
                res.record("V.shard_manifest.chain", False,
                           f"shard {m.shard_id}: chain head "
                           f"{last_code.hex()[:16]} != manifest head "
                           f"{m.head_hash.hex()[:16]}")
        if agg.dup_ids:
            some = ", ".join(sorted(agg.dup_ids)[:3])
            res.record("V.shard_manifest.overlap", False,
                       f"{len(agg.dup_ids)} ballot id(s) published by more "
                       f"than one shard chain: {some}")
        want_total = sum(m.admitted_count for m in manifests)
        if want_total != agg.total_count:
            res.record("V.shard_manifest.complete", False,
                       f"manifests claim {want_total} ballot(s), the "
                       f"record has {agg.total_count}")
        for name in ("signature", "seed", "chain", "overlap", "complete"):
            res.record(f"V.shard_manifest.{name}", True)


"""Fused on-device V4/V5 verification programs.

The measured bottleneck of the chunked verifier on real hardware is not
compute: one 2048-ballot chunk's group math is ~0.8 s of device time, but
the unfused pipeline round-trips every intermediate (six 4096-bit arrays
per chunk) through ``np.asarray``, and over the single-chip tunnel those
synchronous device->host pulls dominate wall-clock ~5:1.  These programs
keep the entire selection/contest proof check on device — shared-base
multi-exponentiation, fixed-base PowRadix passes, Montgomery products,
big-endian byte imaging, SHA-256 Fiat–Shamir, and the challenge
comparison — and return ONE boolean row per selection/contest.  Per
chunk the host now uploads ciphertexts + proof scalars and downloads
booleans; nothing element-sized comes back.

Everything stays in the Montgomery domain end-to-end (montmul(xR, yR) =
xyR): the only domain exits are the four commitment byte images fed to
the hash.  The reference's equivalent is the per-element JVM loop in
src/test/java/electionguard/workflow/RunRemoteWorkflowTest.java:179-182.

Applies to groups supported by the device SHA path
(``sha256_jax.supports``): the production 4096-bit/256-bit geometry.
The tiny-group/host-hash fallback keeps the unfused path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from electionguard_tpu.core import bignum_jax as bn
from electionguard_tpu.core import sha256_jax
from electionguard_tpu.core.group_jax import JaxGroupOps, run_tiled

_P_HDR = np.frombuffer(sha256_jax._TAG_P_HDR, np.uint8)  # tag 0x01 + len 512


def limbs_to_bytes_j(x: jax.Array) -> jax.Array:
    """(..., n) uint32 16-bit LE limbs -> (..., 2n) uint8 BE bytes,
    on device (twin of group_jax.limbs_to_bytes_be)."""
    xr = x[..., ::-1]
    hi = (xr >> 8).astype(jnp.uint8)
    lo = (xr & jnp.uint32(0xFF)).astype(jnp.uint8)
    return jnp.stack([hi, lo], axis=-1).reshape(*x.shape[:-1],
                                                2 * x.shape[-1])


def fixed_pow_mont(ops: JaxGroupOps, table, exp, hat=None) -> jax.Array:
    """PowRadix fixed-base power over 8-bit windows, Montgomery-domain
    output — the shared device walk for every fused program (verify AND
    encrypt; one definition so the window layout can never diverge).

    With ``hat`` (the NTT-evaluated table from ``fixed_table_hat``),
    every window after the first multiplies through ``montmul_hat`` —
    the table operand's forward NTT was done at build time, cutting 4 of
    16 MXU matmuls plus the operand's digit glue per ladder step."""
    use_hat = hat is not None and ops._mm_hat is not None
    acc = None
    for w in range(ops.nwin8):
        limb = exp[..., w // 2]
        digit = ((limb >> ((w % 2) * 8))
                 & jnp.uint32(0xFF)).astype(jnp.int32)
        if acc is None:
            acc = table[w][digit]
        elif use_hat:
            acc = ops._mm_hat(acc, hat[w][digit])
        else:
            acc = ops._mm(acc, table[w][digit])
    return acc


def challenge_rows(hdr, q_limbs, prefix_row, elem_bytes) -> jax.Array:
    """Device Fiat–Shamir challenge rows: prefix || (hdr || elem)* —
    the one definition of the hash framing shared by every fused
    program (byte-twin of sha256_jax.batch_challenge_p)."""
    nb = elem_bytes[0].shape[0]
    parts = [jnp.broadcast_to(prefix_row, (nb, prefix_row.shape[0]))]
    for e in elem_bytes:
        parts.append(jnp.broadcast_to(hdr, (nb, 5)))
        parts.append(e)
    msgs = jnp.concatenate(parts, axis=1)
    return sha256_jax._digest_mod_q(sha256_jax.sha256_rows(msgs), q_limbs)


def get_fused(ops: JaxGroupOps, mesh=None) -> "FusedVerifier":
    """One FusedVerifier per (batch plane, mesh), stored ON the plane so
    the jitted programs and g/g^-1 tables are reused across Verifier
    instances and the pairing can never dangle (an id()-keyed side table
    could alias a GC'd plane to a different group's tables).  The cached
    FusedVerifier holds its mesh, so a live cache entry's key can't be
    recycled either."""
    cache = getattr(ops, "_fused_verifiers", None)
    if cache is None:
        cache = ops._fused_verifiers = {}
    key = None if mesh is None else id(mesh)
    fv = cache.get(key)
    if fv is None:
        fv = FusedVerifier(ops, mesh)
        cache[key] = fv
    return fv


def shard_rows(fn, mesh, n_rows: int, n_reps: int, n_out: int = 1):
    """shard_map an elementwise-over-rows fused program over the mesh's
    dp axis: the first ``n_rows`` args shard their leading axis, the
    last ``n_reps`` (tables, prefix rows) replicate; all ``n_out``
    outputs are row-sharded.  The program bodies are per-row (no
    cross-row math), so dp sharding needs zero communication — this is
    the flag-flip multi-chip path."""
    from electionguard_tpu.parallel.mesh import DP_AXIS
    from electionguard_tpu.parallel.sharded import shard_map
    from jax.sharding import PartitionSpec as P
    return shard_map(
        fn, mesh=mesh,
        in_specs=tuple([P(DP_AXIS)] * n_rows + [P()] * n_reps),
        out_specs=(P(DP_AXIS) if n_out == 1
                   else tuple([P(DP_AXIS)] * n_out)))


def k_tables(ops: JaxGroupOps, K: int):
    """(plain, hat-or-dummy) fixed-base tables for a runtime base — ONE
    definition shared by every fused program so the jitted signatures
    (and the cios dummy trick) can never diverge between encrypt and
    verify.  The dummy is safe: fixed_pow_mont only consults the hat
    when the backend provides a hat multiplier."""
    k_table = ops.fixed_table(K)
    k_hat = (ops.fixed_table_hat(K) if ops._mm_hat is not None
             else jnp.zeros((1,), jnp.uint32))
    return k_table, k_hat


def pad_to_dp(arrays, ndp: int):
    """Pad row arrays so every dispatch bucket (a power of two ≥ 16) is
    divisible by the dp degree; requires power-of-two ndp."""
    if ndp & (ndp - 1):
        raise ValueError(f"dp degree must be a power of two, got {ndp}")
    n = arrays[0].shape[0]
    if n >= ndp:
        return arrays, n
    out = []
    for a in arrays:
        pad = np.zeros((ndp - n,) + a.shape[1:], dtype=np.asarray(a).dtype)
        out.append(np.concatenate([np.asarray(a), pad], axis=0))
    return out, n


class FusedVerifier:
    """Per-``JaxGroupOps`` jitted V4/V5 selection+contest checkers.

    Group-constant tables (g, g^-1) are closure constants — stable across
    elections, so compiled programs and the persistent cache survive
    election turnover; the election key table and hash prefix are runtime
    arguments.  With ``mesh``, both programs shard their row axis over
    the mesh's dp axis (bit-identical results; tested on the virtual
    CPU mesh)."""

    def __init__(self, ops: JaxGroupOps, mesh=None):
        self.ops = ops
        self.mesh = mesh
        g = ops.group
        self._q_limbs = jnp.asarray(bn.int_to_limbs(g.q, 16))
        self._hdr = jnp.asarray(_P_HDR)
        self._ginv_table = ops.fixed_table(g.GINV_MOD_P.value)
        # NTT-evaluated table twins (None on the cios backend)
        self._g_hat = ops.fixed_table_hat(g.g)
        self._ginv_hat = ops.fixed_table_hat(g.GINV_MOD_P.value)
        if mesh is None:
            self.ndp = 1
            self._v4_j = jax.jit(self._v4_impl)
            self._v5_j = jax.jit(self._v5_impl)
            self._v4h_j = jax.jit(self._v4h_impl)
            self._v5h_j = jax.jit(self._v5h_impl)
        else:
            from electionguard_tpu.parallel.mesh import DP_AXIS
            self.ndp = mesh.shape[DP_AXIS]
            self._v4_j = jax.jit(shard_rows(self._v4_impl, mesh, 6, 3))
            self._v5_j = jax.jit(shard_rows(self._v5_impl, mesh, 5, 3))
            self._v4h_j = jax.jit(shard_rows(self._v4h_impl, mesh, 8, 1))
            self._v5h_j = jax.jit(shard_rows(self._v5h_impl, mesh, 6, 1))


    # -- shared helpers (device) ---------------------------------------
    def _challenge(self, prefix_row, elem_bytes):
        return challenge_rows(self._hdr, self._q_limbs, prefix_row,
                              elem_bytes)

    # -- V4: disjunctive selection proofs ------------------------------
    def _v4_impl(self, A, B, c0, v0, c1, v1, k_table, k_hat, prefix_row):
        """-> (t, 2) bool: [subgroup membership, proof challenge ok].

        a0 = g^v0 α^c0, b0 = K^v0 β^c0, a1 = g^v1 α^c1,
        b1 = K^v1 β^c1 (g^-1)^c1;  c0 + c1 == H(Q̄, α, β, a0, b0, a1, b1).
        α and β each carry exponents {q, c0, c1} through one shared-base
        multi-exp (the x^q factor is the subgroup check).
        """
        ops = self.ops
        ctx, mm, ms = ops.ctx, ops._mm, ops._ms
        t = A.shape[0]
        r2 = jnp.broadcast_to(ctx.r2_mod_p, A.shape)
        exps = jnp.stack([jnp.broadcast_to(self._q_limbs, c0.shape),
                          c0, c1], axis=1)
        mm_sh = ops._mm_shared
        pa = bn.mont_multi_pow_shared(ctx, mm(A, r2), exps, ops.exp_bits,
                                      montmul_fn=mm, montsqr_fn=ms,
                                      montmul_shared_fn=mm_sh)
        pb = bn.mont_multi_pow_shared(ctx, mm(B, r2), exps, ops.exp_bits,
                                      montmul_fn=mm, montsqr_fn=ms,
                                      montmul_shared_fn=mm_sh)
        one_m = jnp.broadcast_to(ctx.r_mod_p, A.shape)
        ok_sub = (jnp.all(pa[:, 0] == one_m, axis=-1)
                  & jnp.all(pb[:, 0] == one_m, axis=-1))

        gp = fixed_pow_mont(ops, ops.g_table, jnp.concatenate([v0, v1]),
                            self._g_hat)
        kp = fixed_pow_mont(ops, k_table, jnp.concatenate([v0, v1]),
                            k_hat)
        gic = fixed_pow_mont(ops, self._ginv_table, c1, self._ginv_hat)
        a0 = mm(gp[:t], pa[:, 1])
        b0 = mm(kp[:t], pb[:, 1])
        a1 = mm(gp[t:], pa[:, 2])
        b1 = mm(kp[t:], mm(pb[:, 2], gic))
        com = bn.from_mont_via(mm, jnp.concatenate([a0, b0, a1, b1]))
        cb = limbs_to_bytes_j(com)
        chal = self._challenge(
            prefix_row,
            [limbs_to_bytes_j(A), limbs_to_bytes_j(B),
             cb[:t], cb[t:2 * t], cb[2 * t:3 * t], cb[3 * t:]])
        sum_c = bn.add_mod(c0, c1, self._q_limbs)
        ok_chal = jnp.all(sum_c == chal, axis=-1)
        return jnp.stack([ok_sub, ok_chal], axis=1)

    def v4_selections(self, A_l, B_l, c0, v0, c1, v1, K: int,
                      prefix: bytes) -> np.ndarray:
        """Host entry: (S, 2) bool via the shared tiling policy.  ``K``
        is the election public key; its fixed-base tables (plain + NTT
        hat) are resolved from the plane's caches."""
        k_table, k_hat = k_tables(self.ops, K)
        prefix_row = jnp.asarray(np.frombuffer(prefix, np.uint8))
        arrays, n = pad_to_dp([A_l, B_l, c0, v0, c1, v1], self.ndp)
        return np.asarray(run_tiled(
            lambda a, b, x0, y0, x1, y1: self._v4_j(
                a, b, x0, y0, x1, y1, k_table, k_hat, prefix_row),
            arrays,
            [True, True, False, False, False, False]))[:n]

    # -- RLC batch-path hash binding (no modexp) -----------------------
    def _v4h_impl(self, A, B, h0, h1, h2, h3, c0, c1, prefix_row):
        """Hint hash binding for the RLC batch path: recompute the V4
        Fiat–Shamir challenge from the PROVIDED commitment hints
        (h0..h3 = a0, b0, a1, b1) instead of recomputing the
        commitments — pure device SHA, zero modexps.  Returns (t,) bool
        of c0 + c1 == H(Q̄, α, β, a0, b0, a1, b1)."""
        chal = self._challenge(
            prefix_row, [limbs_to_bytes_j(x)
                         for x in (A, B, h0, h1, h2, h3)])
        sum_c = bn.add_mod(c0, c1, self._q_limbs)
        return jnp.all(sum_c == chal, axis=-1)

    def v4_hint_hash(self, A_l, B_l, h0, h1, h2, h3, c0, c1,
                     prefix: bytes) -> np.ndarray:
        prefix_row = jnp.asarray(np.frombuffer(prefix, np.uint8))
        arrays, n = pad_to_dp([A_l, B_l, h0, h1, h2, h3, c0, c1],
                              self.ndp)
        return np.asarray(run_tiled(
            lambda a, b, x0, x1, x2, x3, y0, y1: self._v4h_j(
                a, b, x0, x1, x2, x3, y0, y1, prefix_row),
            arrays, [True, True, True, True, True, True, False, False]
        ))[:n]

    def _v5h_impl(self, CA, CB, ha, hb, cc, prefix_row):
        """V5 twin of ``_v4h_impl``: cc == H(Q̄, L, CA, CB, a, b) with
        (a, b) taken from the hints; L rides in the prefix."""
        chal = self._challenge(
            prefix_row, [limbs_to_bytes_j(x) for x in (CA, CB, ha, hb)])
        return jnp.all(cc == chal, axis=-1)

    def v5_hint_hash(self, CA_l, CB_l, ha, hb, cc,
                     prefix: bytes) -> np.ndarray:
        prefix_row = jnp.asarray(np.frombuffer(prefix, np.uint8))
        arrays, n = pad_to_dp([CA_l, CB_l, ha, hb, cc], self.ndp)
        return np.asarray(run_tiled(
            lambda a, b, x0, x1, y: self._v5h_j(a, b, x0, x1, y,
                                                prefix_row),
            arrays, [True, True, True, True, False]))[:n]

    # -- V5: contest limit (constant CP) proofs ------------------------
    def _v5_impl(self, CA, CB, Lq, cc, cv, k_table, k_hat, prefix_row):
        """-> (t,) bool.  a = g^cv CA^cc, b = K^cv (CB·g^-L)^cc;
        cc == H(Q̄, L, CA, CB, a, b).  L arrives as exponent limbs Lq for
        the fixed-base (g^-1)^L factor."""
        ops = self.ops
        ctx, mm, ms = ops.ctx, ops._mm, ops._ms
        t = CA.shape[0]
        r2 = jnp.broadcast_to(ctx.r2_mod_p, CA.shape)
        giL = fixed_pow_mont(ops, self._ginv_table, Lq, self._ginv_hat)
        CBs_m = mm(mm(CB, r2), giL)
        var = bn.mont_pow(ctx, jnp.concatenate([mm(CA, r2), CBs_m]),
                          jnp.concatenate([cc, cc]), ops.exp_bits,
                          montmul_fn=mm, montsqr_fn=ms)
        gp = fixed_pow_mont(ops, ops.g_table, cv, self._g_hat)
        kp = fixed_pow_mont(ops, k_table, cv, k_hat)
        a_c = mm(gp, var[:t])
        b_c = mm(kp, var[t:])
        com = bn.from_mont_via(mm, jnp.concatenate([a_c, b_c]))
        cb = limbs_to_bytes_j(com)
        chal = self._challenge(
            prefix_row,
            [limbs_to_bytes_j(CA), limbs_to_bytes_j(CB), cb[:t], cb[t:]])
        return jnp.all(cc == chal, axis=-1)

    def v5_contests(self, CA_l, CB_l, Lq, cc, cv, K: int,
                    prefix: bytes) -> np.ndarray:
        k_table, k_hat = k_tables(self.ops, K)
        prefix_row = jnp.asarray(np.frombuffer(prefix, np.uint8))
        arrays, n = pad_to_dp([CA_l, CB_l, Lq, cc, cv], self.ndp)
        return np.asarray(run_tiled(
            lambda a, b, lq, x, y: self._v5_j(a, b, lq, x, y, k_table,
                                              k_hat, prefix_row),
            arrays,
            [True, True, False, False, False]))[:n]

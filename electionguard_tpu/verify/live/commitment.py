"""Chunk commitment ledger: the bulletin board's cryptographic spine.

The live verifier commits to every chunk it verifies, in stream order,
with two structures over the same leaves:

* a **hash chain** (``head``) — O(1) state, recomputed append-only, so
  a checkpoint needs only the previous head to extend it.  Observers
  polling ``getRoot`` can detect a rewritten past (any change to an
  already-committed chunk changes every later head).
* a **Merkle tree** (``root`` + ``prove``/``verify_proof``) — so an
  auditor holding one chunk's bytes can check membership against the
  published root with a log-sized proof, without the whole ledger.

Leaf preimages bind everything that makes a chunk *that* chunk: its
index, its frame span in the stream, the sha256 of its on-disk framed
bytes, and whether the verifier accepted it.  Domain-separation tags
(``live-leaf``/``live-node``/``live-head``) keep leaves, interior
nodes, and chain links from colliding.

Determinism is the whole point: the terminal batch pass rebuilds this
ledger from the finished record and must land on bit-identical ``root``
and ``head`` — that equality is the sim's convergence oracle.
"""

from __future__ import annotations

import hashlib
import struct
from dataclasses import dataclass


def _h(*parts: bytes) -> bytes:
    d = hashlib.sha256()
    for p in parts:
        d.update(p)
    return d.digest()


def chunk_leaf(index: int, start_frame: int, n_frames: int,
               chunk_digest: bytes, accepted: bool) -> bytes:
    """The 32-byte commitment to one verified chunk."""
    return _h(b"live-leaf", struct.pack(">QQQB", index, start_frame,
                                        n_frames, 1 if accepted else 0),
              chunk_digest)


def frames_digest(frames: list[bytes]) -> bytes:
    """sha256 over the chunk's framed on-disk bytes (header + payload
    per frame) — byte-identical to hashing the file span itself."""
    d = hashlib.sha256()
    for fr in frames:
        d.update(struct.pack(">I", len(fr)))
        d.update(fr)
    return d.digest()


@dataclass
class ChunkCommit:
    """One ledger row (what ``getInclusionProof`` serves back)."""
    index: int
    start_frame: int
    n_frames: int
    chunk_digest: bytes
    accepted: bool

    @property
    def leaf(self) -> bytes:
        return chunk_leaf(self.index, self.start_frame, self.n_frames,
                          self.chunk_digest, self.accepted)


class CommitmentLedger:
    """Append-only ledger of chunk commitments.

    The Merkle root is recomputed from the leaf list on demand (chunk
    counts are bounded by record size / chunk size — thousands, not
    millions — so the O(n) rebuild is noise next to one chunk's proof
    verification)."""

    EMPTY_ROOT = _h(b"live-empty")

    def __init__(self):
        self.chunks: list[ChunkCommit] = []
        self.head: bytes = _h(b"live-head")   # chain genesis

    def append(self, start_frame: int, n_frames: int,
               chunk_digest: bytes, accepted: bool) -> ChunkCommit:
        c = ChunkCommit(len(self.chunks), start_frame, n_frames,
                        chunk_digest, accepted)
        self.chunks.append(c)
        self.head = _h(b"live-head", self.head, c.leaf)
        return c

    # -- Merkle ---------------------------------------------------------
    def root(self) -> bytes:
        level = [c.leaf for c in self.chunks]
        if not level:
            return self.EMPTY_ROOT
        while len(level) > 1:
            nxt = []
            for i in range(0, len(level) - 1, 2):
                nxt.append(_h(b"live-node", level[i], level[i + 1]))
            if len(level) % 2:
                nxt.append(level[-1])   # odd node promotes unchanged
            level = nxt
        return level[0]

    def prove(self, index: int) -> tuple[list[bytes], list[bool]]:
        """Sibling path for leaf ``index``: ``(siblings, is_right)``
        where ``is_right[i]`` says the sibling sits to the RIGHT of the
        running hash at level ``i``."""
        if not 0 <= index < len(self.chunks):
            raise IndexError(f"no chunk {index} in ledger of "
                             f"{len(self.chunks)}")
        path: list[bytes] = []
        right: list[bool] = []
        level = [c.leaf for c in self.chunks]
        pos = index
        while len(level) > 1:
            sib = pos ^ 1
            if sib < len(level):
                path.append(level[sib])
                right.append(sib > pos)
            nxt = []
            for i in range(0, len(level) - 1, 2):
                nxt.append(_h(b"live-node", level[i], level[i + 1]))
            if len(level) % 2:
                nxt.append(level[-1])
            level = nxt
            pos //= 2
        return path, right

    @staticmethod
    def verify_proof(leaf: bytes, path: list[bytes], right: list[bool],
                     root: bytes) -> bool:
        h = leaf
        for sib, r in zip(path, right):
            h = _h(b"live-node", h, sib) if r else _h(b"live-node",
                                                      sib, h)
        return h == root

    # -- checkpoint (de)hydration --------------------------------------
    def to_state(self) -> dict:
        return {"head": self.head.hex(),
                "chunks": [{"start_frame": c.start_frame,
                            "n_frames": c.n_frames,
                            "digest": c.chunk_digest.hex(),
                            "accepted": c.accepted}
                           for c in self.chunks]}

    @classmethod
    def from_state(cls, state: dict) -> "CommitmentLedger":
        led = cls()
        for row in state.get("chunks", []):
            led.append(int(row["start_frame"]), int(row["n_frames"]),
                       bytes.fromhex(row["digest"]),
                       bool(row["accepted"]))
        want = state.get("head")
        if want is not None and led.head.hex() != want:
            raise ValueError("commitment checkpoint head does not match "
                             "its own chunk list (checkpoint tampered "
                             "or mixed)")
        return led

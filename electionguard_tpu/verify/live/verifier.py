"""LiveVerifier: verify the election record WHILE it is being written.

The terminal batch verifier (``verify/verifier.py``) is already a
streaming fold: ``verify_ballots_partial`` over chunks into
``_BallotAggregates``, then ``finalize`` for the record-level checks.
This module runs exactly that fold, but *against a stream that is still
growing* — a ``publish.framing.FramedTailer`` follows the framed
encrypted-ballot stream, and every time ``EGTPU_LIVE_CHUNK`` frames
have fully landed the chunk goes through the same V4/V5/V6 plane (RLC
screens with naive fallback, ``EGTPU_VERIFY_BATCH``) the batch pass
would use.  Each verified chunk is committed into a
``CommitmentLedger`` (hash chain + Merkle root) that the bulletin
board (``verify/live/board.py``) serves mid-election.

**Convergence is the contract** (the sim's ``live_convergence``
oracle): because chunk boundaries are a pure function of frame INDEX
(chunk *i* is frames ``[i*chunk, (i+1)*chunk)``) — never of poll
timing — and the fold itself is deterministic, the live pass's final
verdict, error list, chunk-accept set, and commitment root are
bit-identical to a terminal batch pass over the finished stream, no
matter how the polls interleaved with the writer or how often the live
verifier was SIGKILL'd and resumed.

**Crash safety**: after every committed chunk the verifier writes an
atomic checkpoint (tmp + fsync + rename) holding the stream cursor,
the serialized aggregates/result, and the ledger.  A SIGKILL between
"chunk verified" and "checkpoint written" just means the next
incarnation re-verifies that chunk from disk — same bytes, same fold,
same commitment.

A torn tail at finalize time (writer died mid-append) is DROPPED, the
same policy ``repair_frame_stream`` applies during crash recovery —
the torn frame's admission was never acknowledged.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from electionguard_tpu.crypto import validate
from electionguard_tpu.obs import REGISTRY, election_labels, span
from electionguard_tpu.publish import framing, pb, serialize
from electionguard_tpu.publish.election_record import ElectionRecord
from electionguard_tpu.publish.publisher import _BALLOTS, Consumer
from electionguard_tpu.serve.journal import JOURNAL_NAME
from electionguard_tpu.utils import knobs
from electionguard_tpu.verify.live.commitment import (CommitmentLedger,
                                                      frames_digest)
from electionguard_tpu.verify.verifier import (VerificationResult,
                                               Verifier,
                                               _BallotAggregates)

CHECKPOINT_NAME = "live_checkpoint.json"

#: audit_state() status values (mirrored into the bulletin-board proto)
TAILING, FINALIZING, DONE = "TAILING", "FINALIZING", "DONE"


def _agg_to_state(agg: _BallotAggregates) -> dict:
    return {
        "prods": {f"{c}\x1f{s}": [str(pa), str(pb)]
                  for (c, s), (pa, pb) in agg.prods.items()},
        "cast_count": agg.cast_count,
        "total_count": agg.total_count,
        "spoiled_ids": sorted(agg.spoiled_ids),
        "prev_code": agg.prev_code.hex() if agg.prev_code else None,
        "segments": [[seed.hex(), n, code.hex()]
                     for seed, n, code in agg.segments],
        "seen_ids": sorted(agg.seen_ids),
        "dup_ids": sorted(agg.dup_ids),
    }


def _agg_from_state(state: dict) -> _BallotAggregates:
    agg = _BallotAggregates()
    for k, (pa, pb) in state["prods"].items():
        c, s = k.split("\x1f", 1)
        agg.prods[(c, s)] = (int(pa), int(pb))
    agg.cast_count = int(state["cast_count"])
    agg.total_count = int(state["total_count"])
    agg.spoiled_ids = set(state["spoiled_ids"])
    pc = state.get("prev_code")
    agg.prev_code = bytes.fromhex(pc) if pc else None
    agg.segments = [[bytes.fromhex(a), int(n), bytes.fromhex(b)]
                    for a, n, b in state["segments"]]
    agg.seen_ids = set(state["seen_ids"])
    agg.dup_ids = set(state["dup_ids"])
    return agg


class LiveVerifier:
    """Incremental verifier over a growing record directory.

    Drive it with ``poll()`` while the election runs, then ``finalize()``
    once the producing workflow is done (tally/decryption artifacts
    landed, ballot stream closed).  ``audit_state()`` / the ledger are
    what the bulletin board serves between polls."""

    def __init__(self, record_dir: str, group,
                 chunk: Optional[int] = None,
                 checkpoint_path: Optional[str] = None,
                 max_frame: Optional[int] = None,
                 mesh=None):
        self.dir = record_dir
        self.group = group
        self.chunk = chunk if chunk is not None else \
            knobs.get_int("EGTPU_LIVE_CHUNK")
        self.checkpoint_path = checkpoint_path or \
            knobs.get_str("EGTPU_LIVE_CHECKPOINT") or \
            os.path.join(record_dir, CHECKPOINT_NAME)
        max_frame = max_frame if max_frame is not None else \
            knobs.get_int("EGTPU_LIVE_MAX_FRAME")

        self._consumer = Consumer(record_dir, group)
        record = ElectionRecord(self._consumer.read_election_initialized())
        # shard manifests flip V6 into segment mode — must be decided
        # before the first chunk, like the batch feeders do
        record.shard_manifests = self._consumer.read_shard_manifests()
        self._verifier = Verifier(record, group, chunk_size=self.chunk,
                                  mesh=mesh)

        self.res = VerificationResult()
        self.agg = _BallotAggregates()
        self.ledger = CommitmentLedger()
        self.status = TAILING
        self._pending: list[bytes] = []   # landed frames < one chunk
        self._tailer = framing.FramedTailer(
            os.path.join(record_dir, _BALLOTS), max_frame=max_frame)
        # cursor of the last COMMITTED chunk boundary (what resume uses;
        # the tailer may be further ahead, holding _pending)
        self.verified_offset = 0
        self.verified_frames = 0

        self._chunks_counter = REGISTRY.counter(
            "live_chunks_verified_total", election_labels())
        self._lag_gauge = REGISTRY.gauge("live_audit_lag_frames")
        self._restore_checkpoint()

    # -- checkpoint -----------------------------------------------------
    def _restore_checkpoint(self) -> None:
        path = self.checkpoint_path
        if not os.path.exists(path):
            return
        with open(path) as f:
            state = json.load(f)
        self.verified_offset = int(state["verified_offset"])
        self.verified_frames = int(state["verified_frames"])
        self.res = VerificationResult(
            checks=dict(state["res"]["checks"]),
            errors=list(state["res"]["errors"]))
        self.agg = _agg_from_state(state["agg"])
        self.ledger = CommitmentLedger.from_state(state["ledger"])
        self.status = state.get("status", TAILING)
        # resume the tail exactly at the committed boundary: frames the
        # dead incarnation had polled but not committed re-read from disk
        self._tailer.offset = self.verified_offset
        self._tailer.frames = self.verified_frames
        self._pending = []

    def _write_checkpoint(self) -> None:
        state = {
            "version": 1,
            "verified_offset": self.verified_offset,
            "verified_frames": self.verified_frames,
            "res": {"checks": self.res.checks, "errors": self.res.errors},
            "agg": _agg_to_state(self.agg),
            "ledger": self.ledger.to_state(),
            "status": self.status,
        }
        tmp = self.checkpoint_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.checkpoint_path)

    # -- the incremental fold -------------------------------------------
    def _verify_chunk(self, frames: list[bytes]) -> bool:
        """One chunk through the batch plane; returns accepted (no new
        errors) and commits it into the ledger + checkpoint."""
        start_frame = self.verified_frames
        with span("verify.live.chunk",
                  {"start_frame": start_frame, "n_frames": len(frames)}):
            errors_before = len(self.res.errors)
            ballots = []
            for fr in frames:
                m = pb.EncryptedBallot()
                m.ParseFromString(fr)
                # ingestion gate per ballot: a defective element makes
                # this chunk red with a named [validate.*] error and the
                # ballot never enters the fold — the rejection is part
                # of the deterministic fold state (checkpointed via
                # res.errors), so resume/replay converge bit-for-bit
                try:
                    validate.gate_wire_p(
                        self.group,
                        [(f"{m.ballot_id} {c.contest_id}/"
                          f"{s.selection_id}.{fld}",
                          bytes(getattr(s.ciphertext, fld).value))
                         for c in m.contests for s in c.selections
                         for fld in ("pad", "data")],
                        "live")
                except validate.GateError as e:
                    self.res.errors.append(str(e))
                    continue
                ballots.append(serialize.import_encrypted_ballot(
                    self.group, m))
            self._verifier.verify_ballots_partial(ballots, self.res,
                                                  self.agg)
            accepted = len(self.res.errors) == errors_before
        self.verified_frames += len(frames)
        self.verified_offset += sum(framing.HEADER_LEN + len(fr)
                                    for fr in frames)
        self.ledger.append(start_frame, len(frames),
                           frames_digest(frames), accepted)
        self._chunks_counter.inc()
        self._write_checkpoint()
        return accepted

    def poll(self) -> int:
        """Ingest newly landed frames; verify + commit every chunk that
        completed.  Returns the number of chunks committed this poll."""
        self._pending.extend(self._tailer.poll())
        done = 0
        while len(self._pending) >= self.chunk:
            chunk, self._pending = (self._pending[:self.chunk],
                                    self._pending[self.chunk:])
            self._verify_chunk(chunk)
            done += 1
        self._lag_gauge.set(self.audit_lag_frames())
        return done

    def finalize(self) -> VerificationResult:
        """Stream is complete: drain the tail (the final partial chunk
        is its own commitment; torn trailing bytes are dropped), load
        the terminal artifacts, and run the record-level checks."""
        self.status = FINALIZING
        self.poll()
        if self._pending:
            self._verify_chunk(self._pending)
            self._pending = []
        c = self._consumer
        record = self._verifier.record
        if c.has_tally_result():
            record.tally_result = c.read_tally_result()
        if c.has_decryption_result():
            record.decryption_result = c.read_decryption_result()
        record.spoiled_ballot_tallies = list(
            c.iterate_spoiled_ballot_tallies())
        record.shard_manifests = c.read_shard_manifests()
        if c.has_mix_stages():
            record.mix_stages = c.read_mix_stages()

        def mix_input_fn():
            from electionguard_tpu.mixnet.verify_mix import \
                rows_from_ballots
            return rows_from_ballots(c.iterate_encrypted_ballots())

        self._verifier.mix_input_fn = mix_input_fn
        with span("verify.live.finalize",
                  {"n_frames": self.verified_frames,
                   "n_chunks": len(self.ledger.chunks)}):
            res = self._verifier.finalize(self.res, self.agg)
        self.status = DONE
        self._lag_gauge.set(self.audit_lag_frames())
        self._write_checkpoint()
        return res

    # -- audit surface --------------------------------------------------
    def frames_published(self) -> int:
        """Complete frames on disk right now (committed + pending)."""
        return self._tailer.frames

    def audit_lag_frames(self) -> int:
        return self.frames_published() - self.verified_frames

    def ballots_admitted(self) -> int:
        """Admissions currently journaled (complete lines only, drops
        tombstoned out) — fsync'd-but-unpublished entries show up here
        as audit LAG, never as an error."""
        path = os.path.join(self.dir, JOURNAL_NAME)
        if not os.path.exists(path):
            return 0
        with open(path, "rb") as f:
            lines, _torn = framing.complete_lines(f.read())
        n = 0
        for raw in lines:
            try:
                rec = json.loads(raw)
            except ValueError:
                continue   # audit counter only; replay() owns rejection
            n += -1 if rec.get("drop") else 1
        return max(0, n)

    def audit_state(self) -> dict:
        chunks = self.ledger.chunks
        return {
            "status": self.status,
            "frames_published": self.frames_published(),
            "frames_verified": self.verified_frames,
            "ballots_admitted": self.ballots_admitted(),
            "chunks_accepted": sum(c.accepted for c in chunks),
            "chunks_rejected": sum(not c.accepted for c in chunks),
            "audit_lag_frames": self.audit_lag_frames(),
            "verdict_ok": self.res.ok,
            "errors": list(self.res.errors),
        }
